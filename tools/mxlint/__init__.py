"""mxlint — framework-aware static analysis for the mxtpu tree
(ISSUE 5 tentpole; the static half of mxtpu/guards.py).

Generic linters can't see the TPU-stack failure modes this codebase
actually has, so mxlint knows the framework:

* **retrace hazards** — impure calls (time/random/np.random/os.environ
  /print) inside jit bodies, Python branching on traced parameters,
  value-concretization (`float`/`np.asarray`/`.item()`) under trace,
  and inline ``jax.jit(f)(x)`` immediate invocations;
* **host-sync leaks** — ``.item()``/``float()``/``np.asarray`` style
  device→host syncs in files marked ``# mxlint: hot-path``, outside
  lines whitelisted with ``# mxlint: sync-point``;
* **lock discipline** — attributes annotated ``# guarded-by: <lock>``
  must only be touched inside ``with self.<lock>:`` (methods named
  ``*_locked`` and ``__init__`` are assumed to hold it);
* **knob registry** — every ``MXTPU_*`` env read must go through
  ``mxtpu.knobs.get`` (``knob-raw-env``), name a registered knob
  (``knob-unregistered``), and the README knob table must match the
  registry (``knob-readme-drift``).

Suppression: ``# mxlint: disable=<rule>[,<rule>...]`` on (or on the
comment line directly above) the offending line;
``# mxlint: disable-file=<rule>`` near the top of a file.

Findings are fingerprinted (rule, path, stripped source line) so the
committed baseline (``tools/mxlint/baseline.json``) survives
line-number drift; ``--check`` fails only on NEW findings.

mxlint never imports jax or the mxtpu package — ``mxtpu/knobs.py`` is
loaded standalone by file path, everything else is pure ``ast``.
"""
from .core import Finding, lint_repo, load_baseline  # noqa: F401
