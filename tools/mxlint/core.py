"""mxlint core: file model, suppression handling, baseline, runner.

Pure stdlib (``ast``/``re``/``json``); must never import jax or the
mxtpu package — linting the tree cannot pay a framework import, and a
broken mxtpu must still be lintable.
"""
from __future__ import annotations

import ast
import importlib.util
import json
import re
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, \
    Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_PATHS = ("mxtpu", "tools", "bench.py", "tests")
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

_SUPPRESS_RE = re.compile(r"#\s*mxlint:\s*disable=([\w\-, ]+)")
_FILE_SUPPRESS_RE = re.compile(r"#\s*mxlint:\s*disable-file=([\w\-, ]+)")
_SYNC_RE = re.compile(r"#\s*mxlint:\s*sync-point")
_HOT_RE = re.compile(r"#\s*mxlint:\s*hot-path")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")

# pragma lines must appear this early to mark a whole file
_HEADER_LINES = 5


class Finding:
    """One violation.  ``fingerprint`` identifies it across edits that
    only move lines: the exact line text (stripped) within a file for
    a given rule."""

    __slots__ = ("rule", "path", "line", "message", "snippet")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 snippet: str = ""):
        self.rule = rule
        self.path = path          # repo-relative posix path
        self.line = line
        self.message = message
        self.snippet = snippet

    @property
    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "message": self.message,
                "snippet": self.snippet}


class FileCtx:
    """Parsed file + its mxlint pragmas."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        head = self.lines[:_HEADER_LINES]
        self.hot_path = any(_HOT_RE.search(ln) for ln in head)
        self.file_suppressions: Set[str] = set()
        for ln in head:
            m = _FILE_SUPPRESS_RE.search(ln)
            if m:
                self.file_suppressions.update(
                    r.strip() for r in m.group(1).split(",") if r.strip())
        # line -> suppressed rule names; a comment-only pragma line
        # also covers the line after it (annotations above multi-line
        # statements)
        self.suppressions: Dict[int, Set[str]] = {}
        self.sync_points: Set[int] = set()
        for i, ln in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(ln)
            comment_only = ln.lstrip().startswith("#")
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.suppressions.setdefault(i, set()).update(rules)
                if comment_only:
                    self.suppressions.setdefault(i + 1, set()).update(
                        rules)
            if _SYNC_RE.search(ln):
                self.sync_points.add(i)
                if comment_only:
                    self.sync_points.add(i + 1)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        if rule in self.file_suppressions or \
                "*" in self.file_suppressions:
            return True
        rules = self.suppressions.get(lineno, ())
        return rule in rules or "*" in rules


class Rule:
    """A named check over one FileCtx (or, for ``repo_check``, the
    whole repo)."""

    name = ""

    def applies(self, ctx: FileCtx) -> bool:
        """Scope gate.  The source-hygiene rules audit the shipped
        tree, not the test suite (tests legitimately poke monkeys:
        raw env reads in conftest, deliberate traced branches in
        regression repros); test-suite-specific rules override this
        to target ``tests/`` instead."""
        return not ctx.rel.startswith("tests/")

    def check(self, ctx: FileCtx) -> List[Finding]:
        return []


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.random.split' for nested Attribute/Name chains, else
    None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ----------------------------------------------------------------------
# knobs.py standalone load (no mxtpu import: knobs.py catches the
# failing relative import of .base and degrades to RuntimeError)
# ----------------------------------------------------------------------
def load_knobs_module(root: Path = REPO_ROOT):
    path = root / "mxtpu" / "knobs.py"
    spec = importlib.util.spec_from_file_location("_mxlint_knobs", path)
    mod = importlib.util.module_from_spec(spec)
    assert spec.loader is not None
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------------------------
# file discovery
# ----------------------------------------------------------------------
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist",
              ".eggs"}


def iter_py_files(paths: Sequence[str],
                  root: Path = REPO_ROOT) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        full = (root / p) if not Path(p).is_absolute() else Path(p)
        if full.is_file() and full.suffix == ".py":
            out.append(full)
        elif full.is_dir():
            for f in sorted(full.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in f.parts):
                    out.append(f)
    return out


def parse_files(files: Iterable[Path],
                root: Path = REPO_ROOT) -> Tuple[List[FileCtx],
                                                 List[Finding]]:
    ctxs: List[FileCtx] = []
    errors: List[Finding] = []
    for f in files:
        rel = f.relative_to(root).as_posix() if f.is_relative_to(root) \
            else f.as_posix()
        try:
            src = f.read_text()
            ctxs.append(FileCtx(f, rel, src))
        except (SyntaxError, UnicodeDecodeError) as e:
            lineno = getattr(e, "lineno", 1) or 1
            errors.append(Finding("parse-error", rel, lineno, str(e)))
    return ctxs, errors


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------
def load_baseline(path: Path = DEFAULT_BASELINE) -> Set[Tuple[str, str,
                                                              str]]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {tuple(fp) for fp in data.get("fingerprints", [])}


def write_baseline(findings: Sequence[Finding],
                   path: Path = DEFAULT_BASELINE) -> None:
    fps = sorted({f.fingerprint for f in findings})
    path.write_text(json.dumps(
        {"comment": "mxlint accepted-findings baseline; regenerate "
                    "with `python -m tools.mxlint --write-baseline`",
         "fingerprints": [list(fp) for fp in fps]}, indent=1) + "\n")


def split_by_baseline(findings: Sequence[Finding],
                      baseline: Set[Tuple[str, str, str]]
                      ) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined)."""
    new, old = [], []
    for f in findings:
        (old if f.fingerprint in baseline else new).append(f)
    return new, old


# ----------------------------------------------------------------------
# runner
# ----------------------------------------------------------------------
def lint_repo(paths: Sequence[str] = DEFAULT_PATHS,
              root: Path = REPO_ROOT) -> List[Finding]:
    """Run every rule over ``paths``; returns unsuppressed findings
    (baseline NOT applied — callers split against it)."""
    from . import rules as R
    ctxs, findings = parse_files(iter_py_files(paths, root), root)
    per_file = R.file_rules()
    for ctx in ctxs:
        for rule in per_file:
            if not rule.applies(ctx):
                continue
            for f in rule.check(ctx):
                if not ctx.suppressed(f.rule, f.line):
                    if not f.snippet:
                        f.snippet = ctx.line_text(f.line)
                    findings.append(f)
    findings.extend(R.repo_checks(ctxs, root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
