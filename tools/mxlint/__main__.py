"""mxlint CLI.

Exit codes (the contract tests/test_lint.py pins):

* 0 — no findings outside the committed baseline;
* 1 — new findings (or README knob-table drift);
* 2 — usage / internal error (unreadable baseline, bad paths).

Default scan set: ``mxtpu/``, ``tools/``, ``bench.py``.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from .core import (DEFAULT_BASELINE, DEFAULT_PATHS, REPO_ROOT,
                   lint_repo, load_baseline, split_by_baseline,
                   write_baseline)
from .rules import fix_readme


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="Framework-aware static analysis for the mxtpu "
                    "tree (retrace hazards, host-sync leaks, lock "
                    "discipline, knob registry).")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help="accepted-findings baseline JSON")
    ap.add_argument("--check", action="store_true",
                    help="counts only; exit 1 on new findings "
                         "(CI mode)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the "
                         "baseline and exit 0")
    ap.add_argument("--fix-readme", action="store_true",
                    help="regenerate the README knob table from "
                         "mxtpu/knobs.py and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    args = ap.parse_args(argv)

    if args.fix_readme:
        changed = fix_readme(REPO_ROOT)
        print("README.md knob table "
              + ("rewritten" if changed else "already current"))
        return 0

    t0 = time.perf_counter()
    try:
        findings = lint_repo(tuple(args.paths) or DEFAULT_PATHS)
    except SyntaxError as e:  # a rule crashed on a parse artifact
        print(f"mxlint: internal error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(f"wrote {len({f.fingerprint for f in findings})} "
              f"fingerprints to {args.baseline}")
        return 0

    try:
        baseline = load_baseline(args.baseline)
    except (ValueError, OSError) as e:
        print(f"mxlint: cannot read baseline {args.baseline}: {e}",
              file=sys.stderr)
        return 2
    new, old = split_by_baseline(findings, baseline)
    dt = time.perf_counter() - t0

    if args.as_json:
        print(json.dumps({"new": [f.as_json() for f in new],
                          "baselined": [f.as_json() for f in old],
                          "seconds": round(dt, 3)}, indent=1))
    elif args.check:
        print(f"mxlint: {len(new)} new, {len(old)} baselined "
              f"({dt:.2f}s)")
        for f in new:
            print("  " + f.format())
    else:
        for f in new:
            print(f.format())
        if old:
            print(f"({len(old)} baselined finding(s) suppressed; "
                  f"see {args.baseline.name})")
        print(f"mxlint: {len(new)} new finding(s) in {dt:.2f}s")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
