"""mxlint rule families (ISSUE 5): retrace hazards, host-sync leaks,
lock discipline, knob registry.

Every rule is deliberately framework-aware and best-effort: it flags
the patterns that have actually bitten this codebase, with the
suppression comment as the escape hatch — NOT a general-purpose
soundness analysis.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set

from .core import (FileCtx, Finding, Rule, dotted_name,
                   load_knobs_module, _GUARDED_RE)

# ----------------------------------------------------------------------
# jit-body discovery (shared by the retrace rules)
# ----------------------------------------------------------------------
_JIT_NAMES = {"jit", "pjit"}


def _is_jit_callable(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``jax.experimental.pjit.pjit`` refs."""
    d = dotted_name(node)
    if d is None:
        return False
    last = d.rsplit(".", 1)[-1]
    return last in _JIT_NAMES


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` — including ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    if _is_jit_callable(node.func):
        return True
    d = dotted_name(node.func)
    if d is not None and d.rsplit(".", 1)[-1] == "partial":
        return any(_is_jit_callable(a) for a in node.args)
    return False


def find_jit_bodies(tree: ast.AST) -> List[ast.AST]:
    """Function defs (or lambdas) that become jit entries:

    * decorated with ``@jit`` / ``@jax.jit`` /
      ``@partial(jax.jit, ...)``;
    * a ``def f`` whose NAME is later passed to a ``jax.jit(...)``
      call anywhere in the module;
    * a lambda appearing directly inside a ``jax.jit(...)`` call.
    """
    jitted_names: Set[str] = set()
    bodies: List[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_call(node):
            for a in node.args:
                if isinstance(a, ast.Name):
                    jitted_names.add(a.id)
                elif isinstance(a, ast.Lambda):
                    bodies.append(a)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in jitted_names:
                bodies.append(node)
            elif any(_is_jit_call(d) or _is_jit_callable(d)
                     for d in node.decorator_list):
                bodies.append(node)
    return bodies


def _param_names(fn: ast.AST) -> Set[str]:
    args = fn.args
    names = [a.arg for a in
             args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return {n for n in names if n != "self"}


# ----------------------------------------------------------------------
# retrace rules
# ----------------------------------------------------------------------
_IMPURE_EXACT = {
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "os.getenv", "os.urandom", "uuid.uuid4", "input",
}
_IMPURE_PREFIX = ("random.", "np.random.", "numpy.random.",
                  "os.environ.", "secrets.")
# jax.random / self._rng etc. must NOT match: prefixes anchor at the
# full dotted chain, so "jax.random.split" is safe.


class RetraceImpureCall(Rule):
    """Host-impure calls in a jit body run ONCE at trace time and are
    baked into the compiled program — time stands still, randomness
    freezes, env reads go stale.

    Inside the deterministic scope (``mxtpu/quant/`` — INT8
    calibration promises byte-identical thresholds across runs, and
    quant_policy.json commits them) the scan widens from jit bodies to
    EVERY function body: an RNG or clock call anywhere in the
    calibration tier silently breaks the committed evidence.  ``print``
    stays allowed there — it is non-deterministic only in a trace."""

    name = "retrace-impure-call"
    _DETERMINISTIC_SCOPE = ("mxtpu/quant/",)

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        deterministic = ctx.rel.startswith(self._DETERMINISTIC_SCOPE)
        if deterministic:
            bodies = [n for n in ast.walk(ctx.tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.Lambda))]
        else:
            bodies = find_jit_bodies(ctx.tree)
        for body in bodies:
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d is None:
                    continue
                if d in _IMPURE_EXACT or \
                        any(d.startswith(p) for p in _IMPURE_PREFIX) \
                        or (d == "print" and not deterministic):
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"impure call `{d}` in the deterministic "
                        f"calibration scope breaks byte-reproducible "
                        f"thresholds (quant_policy.json evidence)"
                        if deterministic else
                        f"impure call `{d}` inside a jit body executes "
                        f"once at trace time and is constant-folded "
                        f"into the compiled program"))
        return out


_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}


class RetraceTracedBranch(Rule):
    """``if``/``while`` on a traced parameter's VALUE forces a
    concretization error or per-value retrace.  Branching on shape,
    dtype, or None-ness is static under tracing and allowed."""

    name = "retrace-traced-branch"

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for body in find_jit_bodies(ctx.tree):
            params = _param_names(body)
            if not params or isinstance(body, ast.Lambda):
                continue
            for node in ast.walk(body):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                bad = self._value_use(node.test, params)
                if bad:
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"branching on traced parameter `{bad}`'s "
                        f"value inside a jit body (use jnp.where/"
                        f"lax.cond, or make it a static arg)"))
        return out

    def _value_use(self, test: ast.AST, params: Set[str]
                   ) -> Optional[str]:
        """First param whose VALUE (not shape/dtype/None-ness) feeds
        the condition."""
        # `x is None` / `x is not None` guards are static
        if isinstance(test, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
            return None
        return self._scan(test, params)

    def _scan(self, node: ast.AST, params: Set[str]) -> Optional[str]:
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return None  # static metadata access
            return self._scan(node.value, params)
        if isinstance(node, ast.Name):
            return node.id if node.id in params else None
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("len", "isinstance", "hasattr", "getattr",
                     "callable", "type"):
                return None  # static under tracing
            for a in list(node.args) + [kw.value
                                        for kw in node.keywords]:
                hit = self._scan(a, params)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Compare):
            for sub in [node.left] + list(node.comparators):
                hit = self._scan(sub, params)
                if hit:
                    return hit
            return None
        for child in ast.iter_child_nodes(node):
            hit = self._scan(child, params)
            if hit:
                return hit
        return None


class RetraceInlineJit(Rule):
    """``jax.jit(f)(x)`` — a fresh jit wrapper invoked immediately.
    When ``f`` is a fresh closure/lambda the cache never hits and
    every call recompiles (the exact churn mxtpu.guards catches at
    runtime)."""

    name = "retrace-inline-jit"

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Call) and \
                    _is_jit_call(node.func):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "inline `jax.jit(...)(...)` immediate invocation "
                    "— bind the jitted callable once (or AOT "
                    "lower/compile) so the cache can hit"))
        return out


_CONCRETIZE_METHODS = {"item", "tolist", "asnumpy"}
_CONCRETIZE_FUNCS = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array", "float", "bool"}


class RetraceConcretize(Rule):
    """Concretizing a traced value (``float()``, ``np.asarray``,
    ``.item()``) inside a jit body either raises a
    ConcretizationTypeError or silently constant-folds."""

    name = "retrace-concretize"

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for body in find_jit_bodies(ctx.tree):
            params = _param_names(body)
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _CONCRETIZE_METHODS and \
                        not node.args:
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"`.{node.func.attr}()` inside a jit body "
                        f"concretizes a traced value"))
                    continue
                d = dotted_name(node.func)
                if d in _CONCRETIZE_FUNCS and node.args and \
                        self._touches_param(node.args[0], params):
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        f"`{d}(...)` on a traced parameter inside a "
                        f"jit body concretizes it (use jnp/lax ops)"))
        return out

    @staticmethod
    def _touches_param(node: ast.AST, params: Set[str]) -> bool:
        return any(isinstance(n, ast.Name) and n.id in params
                   for n in ast.walk(node))


# ----------------------------------------------------------------------
# host-sync leaks (files marked `# mxlint: hot-path`)
# ----------------------------------------------------------------------
_SYNC_METHODS = {"item", "tolist", "asnumpy", "block_until_ready"}
_SYNC_FUNCS = {"np.asarray", "np.array", "numpy.asarray",
               "numpy.array", "jax.device_get", "float", "bool"}


class HostSync(Rule):
    """In hot-path files, device→host syncs stall the dispatch
    pipeline (the asnumpy() trap).  Deliberate materialization points
    carry ``# mxlint: sync-point``."""

    name = "host-sync"

    def check(self, ctx: FileCtx) -> List[Finding]:
        if not ctx.hot_path:
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if node.lineno in ctx.sync_points:
                continue
            label = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_METHODS and not node.args:
                label = f".{node.func.attr}()"
            else:
                d = dotted_name(node.func)
                if d in _SYNC_FUNCS:
                    if d in ("float", "bool") and (
                            not node.args or isinstance(
                                node.args[0], ast.Constant)):
                        continue
                    label = f"{d}(...)"
            if label:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"{label} in a hot-path file forces a device→host "
                    f"sync; move it off the hot path or annotate the "
                    f"line `# mxlint: sync-point`"))
        return out


# ----------------------------------------------------------------------
# lock discipline (`# guarded-by: <lock>` annotations)
# ----------------------------------------------------------------------
class LockDiscipline(Rule):
    """``self.<attr>`` annotated ``# guarded-by: <lock>`` may only be
    touched inside ``with self.<lock>:``.  ``__init__`` (no concurrent
    access before construction completes) and methods named
    ``*_locked`` (documented called-with-lock-held convention) are
    exempt."""

    name = "lock-discipline"

    _ASSIGN_RE = re.compile(r"self\.(\w+)\s*(?::[^=]*)?=[^=]")

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(ctx, cls))
        return out

    def _annotations(self, ctx: FileCtx,
                     cls: ast.ClassDef) -> Dict[str, str]:
        """attr -> lock name, from guarded-by comments inside the
        class body's line range."""
        end = cls.end_lineno or len(ctx.lines)
        guarded: Dict[str, str] = {}
        for i in range(cls.lineno, end + 1):
            line = ctx.lines[i - 1] if i <= len(ctx.lines) else ""
            m = _GUARDED_RE.search(line)
            if not m:
                continue
            lock = m.group(1)
            # the guarded attribute: assignment on this line, else on
            # the next (annotation above a multi-line statement)
            am = self._ASSIGN_RE.search(line)
            if am is None and i < len(ctx.lines):
                am = self._ASSIGN_RE.search(ctx.lines[i])
            if am:
                guarded[am.group(1)] = lock
        return guarded

    def _check_class(self, ctx: FileCtx,
                     cls: ast.ClassDef) -> List[Finding]:
        guarded = self._annotations(ctx, cls)
        if not guarded:
            return []
        out: List[Finding] = []
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name == "__init__" or meth.name.endswith("_locked"):
                continue
            self._walk(ctx, meth, guarded, frozenset(), out)
        return out

    def _held_after(self, node: ast.With,
                    held: frozenset) -> frozenset:
        extra = set()
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                extra.add(expr.attr)
        return held | extra

    def _walk(self, ctx: FileCtx, node: ast.AST, guarded: Dict[str, str],
              held: frozenset, out: List[Finding]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            held = self._held_after(node, held)
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr in guarded:
            lock = guarded[node.attr]
            if lock not in held:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"`self.{node.attr}` is `# guarded-by: {lock}` but "
                    f"accessed outside `with self.{lock}:`"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)) and held:
            # a nested def/lambda does not inherit the enclosing
            # lock scope — it may run later, unlocked
            held = frozenset()
        for child in ast.iter_child_nodes(node):
            self._walk(ctx, child, guarded, held, out)


# ----------------------------------------------------------------------
# knob registry rules
# ----------------------------------------------------------------------
def _knob_registry_names() -> Set[str]:
    return set(load_knobs_module().registered())


class _KnobRuleBase(Rule):
    _registry: Optional[Set[str]] = None

    @property
    def registry(self) -> Set[str]:
        if _KnobRuleBase._registry is None:
            _KnobRuleBase._registry = _knob_registry_names()
        return _KnobRuleBase._registry


class KnobRawEnv(_KnobRuleBase):
    """``os.environ`` reads of ``MXTPU_*``/``MXNET_*`` names must go
    through ``mxtpu.knobs.get`` — the registry is the single source of
    typing, defaults, and the README table.  Writes (launch scripts,
    ablation probes) are allowed."""

    name = "knob-raw-env"
    _EXEMPT = ("mxtpu/knobs.py", "mxtpu/base.py")

    def check(self, ctx: FileCtx) -> List[Finding]:
        if ctx.rel in self._EXEMPT:
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            knob = self._env_read(node)
            if knob:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"raw environment read of `{knob}` — use "
                    f"`mxtpu.knobs.get(\"{knob}\")`"))
        return out

    @staticmethod
    def _literal_knob(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith(("MXTPU_", "MXNET_")):
            return node.value
        return None

    def _env_read(self, node: ast.AST) -> Optional[str]:
        # os.environ.get("X") / os.environ.setdefault("X", ...) /
        # os.getenv("X")
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("os.environ.get", "os.environ.setdefault",
                     "os.getenv") and node.args:
                return self._literal_knob(node.args[0])
            return None
        # os.environ["X"] reads (Load context only — assignment to
        # os.environ["X"] is a write)
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                dotted_name(node.value) == "os.environ":
            return self._literal_knob(node.slice)
        return None


class KnobUnregistered(_KnobRuleBase):
    """``knobs.get("NAME")`` must name a registered knob (knobs.get
    raises at runtime; the lint catches it before that)."""

    name = "knob-unregistered"

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr == "get" and
                    isinstance(node.func.value, ast.Name) and
                    node.func.value.id == "knobs" and node.args):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and \
                    arg.value not in self.registry:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"knobs.get({arg.value!r}): not registered in "
                    f"mxtpu/knobs.py"))
        return out


# ----------------------------------------------------------------------
# compiled-artifact discipline (tests/ only)
# ----------------------------------------------------------------------
class HloRawAssert(Rule):
    """Tests must not inspect compiled artifacts raw: ``.hlo_text(``
    / ``.as_text(`` grepping and manual ``.lower(x)`` chains in
    ``tests/`` fragment the HLO-parsing story ISSUE 6 consolidated
    into ``mxtpu.analysis`` (``program_summary`` /
    ``compiled_summary`` / ``compiled_evidence``).  Argument-less
    ``.lower()`` is string casing and stays exempt.  Suppress a
    deliberate exception with ``# mxlint: disable=hlo-raw-assert``."""

    name = "hlo-raw-assert"
    _TEXT_ATTRS = ("hlo_text", "as_text")

    def applies(self, ctx: FileCtx) -> bool:
        return ctx.rel.startswith("tests/")

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in self._TEXT_ATTRS:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"raw `.{attr}()` in a test — assert on "
                    f"`program_summary()` / "
                    f"`mxtpu.analysis.compiled_summary` instead"))
            elif attr == "lower" and (node.args or node.keywords):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "manual `.lower(...)` in a test — use "
                    "`mxtpu.analysis.compiled_artifact` (or the "
                    "TrainStep/ModelRunner summary APIs) so contract "
                    "checks stay on one parser"))
        return out


class MemHygiene(Rule):
    """Tests must not grep memory facts raw: ``.memory_analysis()``
    and ``.opt_state_bytes()`` calls in ``tests/`` fragment the
    byte-accounting story ISSUE 20 consolidated into
    ``mxtpu.analysis.memflow`` — assert on the sanctioned
    ``memory_summary()`` view (TrainStep / ModelRunner /
    GenerateRunner) or ``last_memory_analysis()`` instead, so the
    ``hbm_peak`` convention and the decomposition stay on one
    analyzer.  Suppress a deliberate exception with
    ``# mxlint: disable=mem-hygiene``."""

    name = "mem-hygiene"
    _MEM_ATTRS = ("memory_analysis", "opt_state_bytes")

    def applies(self, ctx: FileCtx) -> bool:
        return ctx.rel.startswith("tests/")

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in self._MEM_ATTRS:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"raw `.{attr}()` in a test — assert on "
                    f"`memory_summary()` (or "
                    f"`last_memory_analysis()`) so byte accounting "
                    f"stays on the one memflow analyzer"))
        return out


class ObsRegistry(Rule):
    """Metrics go through the ``mxtpu.obs`` registry, correctly named
    (ISSUE 8).  Three checks:

    * literal instrument names in ``obs.counter/gauge/histogram``
      calls must follow the convention — ``mxtpu_`` snake_case prefix,
      counters end ``_total``, histograms end ``_seconds`` / ``_us``
      / ``_bytes`` (the registry raises at runtime too; the lint
      catches it before the code path runs);
    * no ad-hoc module-level counters (``_N_CALLS = 0`` style) in the
      serving/parallel hot paths — those belong on the registry or on
      a locked instance attribute;
    * no ``profiler.Counter`` instances in serving/parallel — the
      chrome-trace counter is for trace dumps, not for metrics the
      registry should own.

    Suppress a deliberate exception with
    ``# mxlint: disable=obs-registry``."""

    name = "obs-registry"
    _FACTORIES = {"counter", "gauge", "histogram"}
    _NAME_RE = re.compile(r"^mxtpu_[a-z][a-z0-9_]*$")
    _HIST_SUFFIXES = ("_seconds", "_us", "_bytes")
    _COUNTERISH = re.compile(
        r"(?:^|_)(?:n|num|count|counts|counter|total|totals|hits|"
        r"misses|calls)(?:_|$)", re.IGNORECASE)
    _HOT_DIRS = ("mxtpu/serving/", "mxtpu/parallel/")

    def _name_findings(self, ctx: FileCtx, node: ast.Call,
                       kind: str) -> List[Finding]:
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            return []
        name = node.args[0].value
        bad: Optional[str] = None
        if not self._NAME_RE.match(name):
            bad = ("instrument name must match "
                   "`mxtpu_[a-z][a-z0-9_]*`")
        elif kind == "counter" and not name.endswith("_total"):
            bad = "counter names end `_total`"
        elif kind == "histogram" and \
                not name.endswith(self._HIST_SUFFIXES):
            bad = ("histogram names end `_seconds` / `_us` / "
                   "`_bytes` (name the unit)")
        if bad is None:
            return []
        return [Finding(self.name, ctx.rel, node.lineno,
                        f"obs.{kind}({name!r}): {bad}")]

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        in_hot = ctx.rel.startswith(self._HOT_DIRS)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None:
                continue
            head, _, last = d.rpartition(".")
            if last in self._FACTORIES and head.endswith("obs"):
                out.extend(self._name_findings(ctx, node, last))
            elif in_hot and d.endswith("profiler.Counter"):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "profiler.Counter in a serving/parallel hot path "
                    "— publish through the mxtpu.obs registry (the "
                    "chrome-trace counter is a trace artifact, not "
                    "the metrics surface)"))
        if in_hot:
            for stmt in ctx.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                if not (isinstance(stmt.value, ast.Constant) and
                        isinstance(stmt.value.value, int) and
                        not isinstance(stmt.value.value, bool)):
                    continue
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and \
                            self._COUNTERISH.search(tgt.id):
                        out.append(Finding(
                            self.name, ctx.rel, stmt.lineno,
                            f"module-level counter `{tgt.id}` in a "
                            f"serving/parallel hot path — use an "
                            f"obs registry counter (process-wide, "
                            f"locked, scrapeable) instead"))
        return out


class ThreadHygiene(Rule):
    """Threading discipline for the serving/obs stack (mxrace
    satellite): no bare ``time.sleep()`` polling loops — waiters must
    be interruptible (``Event.wait(timeout)`` / ``Condition.wait``) or
    clock-injected so shutdown and sync-mode tests don't block on wall
    time — and every ``threading.Thread`` is ``daemon=True`` (shutdown
    is join-with-timeout + daemon fallback; a non-daemon worker the
    close path misses wedges interpreter exit, which is exactly what
    the conftest thread-leak gate fails tests for)."""

    name = "thread-hygiene"
    _SCOPE = ("mxtpu/serving/", "mxtpu/obs/")

    def applies(self, ctx: FileCtx) -> bool:
        return ctx.rel.startswith(self._SCOPE)

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        sleeps: Dict[tuple, ast.Call] = {}
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            for sub in ast.walk(loop):
                if isinstance(sub, ast.Call) and \
                        dotted_name(sub.func) == "time.sleep":
                    sleeps[(sub.lineno, sub.col_offset)] = sub
        for key in sorted(sleeps):
            out.append(Finding(
                self.name, ctx.rel, sleeps[key].lineno,
                "bare time.sleep() in a loop — wait on an "
                "Event/Condition with a timeout (or the injected "
                "clock) so shutdown and sync-mode tests can "
                "interrupt it"))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None or \
                    not (d == "Thread" or d.endswith("threading.Thread")):
                continue
            daemon = next((kw.value for kw in node.keywords
                           if kw.arg == "daemon"), None)
            if not (isinstance(daemon, ast.Constant)
                    and daemon.value is True):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "threading.Thread without daemon=True in "
                    "serving/obs — a worker the close path misses "
                    "must not wedge interpreter exit; set "
                    "daemon=True and join with a timeout"))
        return out


class DtypeHygiene(Rule):
    """Precision discipline for library code (mxprec satellite): no
    ad-hoc f64.  ``np.float64``/``jnp.float64`` literals,
    ``.astype("float64")``, and ``jax.config.update("jax_enable_x64",
    ...)`` in ``mxtpu/`` silently double memory/compute and poison the
    bf16/f32 dtype story the precision ledgers pin — f64 is a
    per-callsite decision that needs the pragma as a visible waiver.
    Tests are exempt (seeding f64 to exercise the f64-creep rule is
    their job)."""

    name = "dtype-hygiene"
    _F64_ATTRS = {"np.float64", "numpy.float64", "jnp.float64",
                  "jax.numpy.float64"}

    def applies(self, ctx: FileCtx) -> bool:
        return ctx.rel.startswith("mxtpu/")

    def _is_f64_arg(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant) and node.value == "float64":
            return True
        return dotted_name(node) in self._F64_ATTRS

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        claimed: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is not None and d.endswith("config.update") and \
                    node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value == "jax_enable_x64":
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "jax_enable_x64 toggled in library code — x64 is "
                    "process-global and breaks the bf16/f32 policy "
                    "contracts/prec/ pins; scope it to the caller "
                    "(jax.experimental.enable_x64) or waive with a "
                    "pragma"))
                continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype":
                for a in node.args:
                    if self._is_f64_arg(a):
                        claimed.add(id(a))
                        out.append(Finding(
                            self.name, ctx.rel, node.lineno,
                            ".astype(float64) in library code — f64 "
                            "doubles memory/compute and trips "
                            "mxprec's f64-creep rule; accumulate in "
                            "f32 (or waive with a pragma where f64 "
                            "is the point)"))
        for node in ast.walk(ctx.tree):
            if id(node) in claimed or \
                    dotted_name(node) not in self._F64_ATTRS:
                continue
            out.append(Finding(
                self.name, ctx.rel, node.lineno,
                "float64 literal in library code — silent f32->f64 "
                "promotion (mxprec's f64-creep rule names the "
                "compiled sites); use f32 or waive with a pragma"))
        return sorted(out, key=lambda f: f.line)


class NoAdhocBf16(Rule):
    """The AMP pass is the ONE cast authority (r15): bf16 edges are
    decided by ``contracts/amp_policy.json`` at the op-dispatch choke
    point, so the six ``*_amp`` precision ledgers describe every
    program.  A hand-rolled bf16 cast in a model/layer hot path
    (``mxtpu/models/``, ``mxtpu/gluon/``) bypasses the policy veto and
    the f32-accumulation rule — it can reintroduce exactly the bf16
    accumulating reductions mxprec exists to catch, invisibly to the
    ledgers.  Waive a deliberate site (an I/O boundary, a test
    fixture block) with ``# mxlint: disable=no-adhoc-bf16`` and say
    why."""

    name = "no-adhoc-bf16"
    _BF16_ATTRS = {"np.bfloat16", "numpy.bfloat16", "jnp.bfloat16",
                   "jax.numpy.bfloat16", "ml_dtypes.bfloat16"}
    _BF16_STRINGS = {"bfloat16", "bf16"}
    _CASTERS = {"astype", "cast", "cast_all"}

    def applies(self, ctx: FileCtx) -> bool:
        return ctx.rel.startswith(("mxtpu/models/", "mxtpu/gluon/"))

    def _is_bf16_arg(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant) and \
                node.value in self._BF16_STRINGS:
            return True
        return dotted_name(node) in self._BF16_ATTRS

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        claimed: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func.attr \
                if isinstance(node.func, ast.Attribute) else (
                    node.func.id if isinstance(node.func, ast.Name)
                    else None)
            args = list(node.args) + [kw.value for kw in node.keywords
                                      if kw.arg == "dtype"]
            if callee not in self._CASTERS:
                # a dtype="bfloat16" kwarg on any call (array ctor,
                # layer ctor) plants ad-hoc bf16 state just the same
                args = [kw.value for kw in node.keywords
                        if kw.arg == "dtype"]
            for a in args:
                if self._is_bf16_arg(a):
                    claimed.add(id(a))
                    out.append(Finding(
                        self.name, ctx.rel, node.lineno,
                        "ad-hoc bf16 cast in a model/layer hot path — "
                        "bf16 edges belong to the policy-driven AMP "
                        "pass (amp=True consumes contracts/"
                        "amp_policy.json with f32 accumulation); a "
                        "hand cast bypasses the policy veto and the "
                        "*_amp ledgers, or waive with a pragma "
                        "stating why this site is exempt"))
        for node in ast.walk(ctx.tree):
            if id(node) in claimed or \
                    dotted_name(node) not in self._BF16_ATTRS:
                continue
            out.append(Finding(
                self.name, ctx.rel, node.lineno,
                "bfloat16 literal in a model/layer hot path — route "
                "mixed precision through mxtpu.amp (amp=True) so the "
                "precision ledgers stay true, or waive with a pragma"))
        return sorted(out, key=lambda f: f.line)


class RawDeserialize(Rule):
    """Disk artifacts reach the process through ONE verified door
    (ISSUE 13): ``mxtpu/cache.py``'s loader checksums and
    key-revalidates every entry before ``pickle.loads`` /
    ``deserialize_and_load`` touch the bytes.  Raw
    ``pickle.load(s)`` / ``marshal.load(s)`` /
    ``serialize_executable.deserialize_and_load`` anywhere else in the
    shipped tree is a silent wrong-executable / arbitrary-code hazard
    the cache module exists to fence.  Waive a deliberate site (an
    in-process round-trip of bytes this process just produced, a
    checkpoint format with its own framing) with
    ``# mxlint: disable=raw-deserialize`` and say why."""

    name = "raw-deserialize"
    _LOADERS = {"pickle.load", "pickle.loads", "cPickle.load",
                "cPickle.loads", "marshal.load", "marshal.loads"}

    def applies(self, ctx: FileCtx) -> bool:
        return super().applies(ctx) and ctx.rel != "mxtpu/cache.py"

    def check(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None:
                continue
            if d in self._LOADERS:
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    f"raw `{d}` on disk bytes outside mxtpu/cache.py "
                    f"— route persisted artifacts through the cache's "
                    f"checksum-verified loader, or waive with a "
                    f"pragma stating why this site is safe"))
            elif d.endswith("deserialize_and_load"):
                out.append(Finding(
                    self.name, ctx.rel, node.lineno,
                    "`deserialize_and_load` outside mxtpu/cache.py — "
                    "loading an unverified executable can silently "
                    "run the WRONG program; only the cache's "
                    "verified loader may revive compiled payloads"))
        return out


# ----------------------------------------------------------------------
# repo-level checks
# ----------------------------------------------------------------------
def readme_drift(root: Path) -> List[Finding]:
    """README knob table must match ``knobs.readme_table()``
    (regenerate with ``python -m tools.mxlint --fix-readme``)."""
    knobs = load_knobs_module()
    readme = root / "README.md"
    if not readme.exists():
        return [Finding("knob-readme-drift", "README.md", 1,
                        "README.md missing")]
    text = readme.read_text()
    begin, end = knobs.TABLE_BEGIN, knobs.TABLE_END
    if begin not in text or end not in text:
        return [Finding(
            "knob-readme-drift", "README.md", 1,
            "README.md lacks the mxlint:knob-table markers — run "
            "`python -m tools.mxlint --fix-readme`")]
    current = text.split(begin, 1)[1].split(end, 1)[0]
    want = knobs.readme_table().split(begin, 1)[1].split(end, 1)[0]
    if current.strip() != want.strip():
        line = text[:text.index(begin)].count("\n") + 1
        return [Finding(
            "knob-readme-drift", "README.md", line,
            "README knob table is stale vs mxtpu/knobs.py — run "
            "`python -m tools.mxlint --fix-readme`",
            snippet="knob-table")]
    return []


def fix_readme(root: Path) -> bool:
    """Rewrite the README table between the markers; returns True when
    the file changed."""
    knobs = load_knobs_module()
    readme = root / "README.md"
    text = readme.read_text()
    begin, end = knobs.TABLE_BEGIN, knobs.TABLE_END
    if begin not in text or end not in text:
        raise SystemExit(
            f"README.md lacks the markers {begin!r} … {end!r}; add "
            f"them where the table should live")
    head = text.split(begin, 1)[0]
    tail = text.split(end, 1)[1]
    new = head + knobs.readme_table() + tail
    if new != text:
        readme.write_text(new)
        return True
    return False


# ----------------------------------------------------------------------
# registry of rules
# ----------------------------------------------------------------------
def file_rules() -> List[Rule]:
    return [RetraceImpureCall(), RetraceTracedBranch(),
            RetraceInlineJit(), RetraceConcretize(), HostSync(),
            LockDiscipline(), KnobRawEnv(), KnobUnregistered(),
            HloRawAssert(), MemHygiene(), ObsRegistry(),
            ThreadHygiene(), DtypeHygiene(), NoAdhocBf16(),
            RawDeserialize()]


def repo_checks(ctxs: Sequence[FileCtx], root: Path) -> List[Finding]:
    return readme_drift(root)
