"""One CI gate for the static tier: source lint + compiled-program
contracts.

``python -m tools.ci_static`` (or ``python tools/ci_static.py``) runs

* ``python -m tools.mxlint --check``  (AST rules over the tree), then
* ``python -m tools.hlocheck --check`` (lowered programs vs the
  committed ``contracts/`` lockfiles), then
* ``python -m mxtpu.obs --self-check`` (the observability layer's
  zero-overhead-when-off + exposition round-trip contracts, plus the
  operator layers end-to-end on a fake clock: sampler windows, a
  driven SLO burn-rate alert, every debug-HTTP page rendering), then
* ``python -m mxtpu.cache --self-check`` (the persistent compile
  cache's round-trip, key-miss, poison-quarantine and read-only
  fallback probes on a throwaway root), then
* ``python -m tools.mxrace --check`` (lock-order graph vs the
  committed ``contracts/lockorder.json`` + guarded-by hygiene), then
* ``python -m tools.mxprec --check`` (pre-optimization dtype flow vs
  the committed ``contracts/prec/`` ledgers + the derived
  ``contracts/amp_policy.json``), then
* ``python -m tools.mxmem --check`` (per-device HBM decomposition and
  memory hazard rules vs the committed ``contracts/mem/`` ledgers +
  the declarative device-class budgets), then
* ``python -m mxtpu.amp --self-check`` (the AMP pass's three
  contracts: policy parse/classes, an autocast round-trip on the
  selftest program — bf16 edges, zero hazards, no leak outside the
  scope — and the loss-scaler grow/backoff/skip accounting), then
* ``python -m mxtpu.quant --self-check`` (the INT8 tier's contracts:
  quant-policy parse/classes/evidence, a calibrate→quantize round
  trip — deterministic scales, s8×s8→s32 accumulation, tagged and
  hazard-free, numerically close to f32 — and the no-leak-outside-
  the-scope kill-switch shape),

prints one PASS/FAIL line per stage, and exits non-zero if any
failed — the single entry point a CI job or pre-push hook needs.
Extra arguments are forwarded to the lint/contract tools (e.g.
``--json``); the obs self-check takes none.
"""
from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (name, argv, forward_extra_args)
STAGES = (
    ("mxlint", ("-m", "tools.mxlint", "--check"), True),
    ("hlocheck", ("-m", "tools.hlocheck", "--check"), True),
    ("obs-self-check", ("-m", "mxtpu.obs", "--self-check"), False),
    ("cache-self-check", ("-m", "mxtpu.cache", "--self-check"), False),
    ("mxrace", ("-m", "tools.mxrace", "--check"), True),
    ("mxprec", ("-m", "tools.mxprec", "--check"), True),
    ("mxmem", ("-m", "tools.mxmem", "--check"), True),
    ("amp-self-check", ("-m", "mxtpu.amp", "--self-check"), False),
    ("quant-self-check", ("-m", "mxtpu.quant", "--self-check"), False),
)


def main(argv=None) -> int:
    extra = list(sys.argv[1:] if argv is None else argv)
    failed = []
    for name, args, fwd in STAGES:
        cmd = [sys.executable, *args, *(extra if fwd else ())]
        print(f"ci_static: {name}: {' '.join(cmd[1:])}", flush=True)
        rc = subprocess.call(cmd, cwd=REPO_ROOT)
        print(f"ci_static: {name}: "
              f"{'PASS' if rc == 0 else f'FAIL (rc={rc})'}",
              flush=True)
        if rc != 0:
            failed.append(name)
    if failed:
        print(f"ci_static: FAILED: {', '.join(failed)}")
        return 1
    print("ci_static: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
