"""One CI gate for the static tier: source lint + compiled-program
contracts.

``python -m tools.ci_static`` (or ``python tools/ci_static.py``) runs

* ``python -m tools.mxlint --check``  (AST rules over the tree), then
* ``python -m tools.hlocheck --check`` (lowered programs vs the
  committed ``contracts/`` lockfiles),

prints one PASS/FAIL line per stage, and exits non-zero if either
failed — the single entry point a CI job or pre-push hook needs.
Extra arguments are forwarded to BOTH tools (e.g. ``--json``).
"""
from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STAGES = (
    ("mxlint", ("-m", "tools.mxlint", "--check")),
    ("hlocheck", ("-m", "tools.hlocheck", "--check")),
)


def main(argv=None) -> int:
    extra = list(sys.argv[1:] if argv is None else argv)
    failed = []
    for name, args in STAGES:
        cmd = [sys.executable, *args, *extra]
        print(f"ci_static: {name}: {' '.join(cmd[1:])}", flush=True)
        rc = subprocess.call(cmd, cwd=REPO_ROOT)
        print(f"ci_static: {name}: "
              f"{'PASS' if rc == 0 else f'FAIL (rc={rc})'}",
              flush=True)
        if rc != 0:
            failed.append(name)
    if failed:
        print(f"ci_static: FAILED: {', '.join(failed)}")
        return 1
    print("ci_static: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
