#!/usr/bin/env python
"""Pack an image folder or .lst file into RecordIO
(reference ``tools/im2rec.py``†; output loads in both this framework
and upstream MXNet — same wire format).

  python tools/im2rec.py prefix image_root          # folder mode
  python tools/im2rec.py prefix.lst image_root      # list mode
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxtpu import recordio


def list_images(root, exts=(".jpg", ".jpeg", ".png")):
    """Yield (index, relpath, label) walking class subfolders
    (reference ``list_image``†)."""
    idx = 0
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    for label, cls in enumerate(classes):
        for fname in sorted(os.listdir(os.path.join(root, cls))):
            if os.path.splitext(fname)[1].lower() in exts:
                yield idx, os.path.join(cls, fname), float(label)
                idx += 1


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), parts[-1], float(parts[1])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("prefix", help="output prefix or existing .lst file")
    p.add_argument("root", help="image root directory")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter edge")
    p.add_argument("--encoding", default=".jpg")
    args = p.parse_args()

    if args.prefix.endswith(".lst"):
        items = list(read_list(args.prefix))
        prefix = args.prefix[:-4]
    else:
        items = list(list_images(args.root))
        prefix = args.prefix
        with open(prefix + ".lst", "w") as f:
            for idx, rel, label in items:
                f.write(f"{idx}\t{label}\t{rel}\n")

    import cv2
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    for idx, rel, label in items:
        img = cv2.imread(os.path.join(args.root, rel))
        if img is None:
            print(f"skip unreadable {rel}", file=sys.stderr)
            continue
        if args.resize:
            h, w = img.shape[:2]
            scale = args.resize / min(h, w)
            img = cv2.resize(img, (int(w * scale), int(h * scale)))
        packed = recordio.pack_img(
            recordio.IRHeader(0, label, idx, 0), img,
            quality=args.quality, img_fmt=args.encoding)
        rec.write_idx(idx, packed)
    rec.close()
    print(f"wrote {len(items)} records to {prefix}.rec")


if __name__ == "__main__":
    main()
