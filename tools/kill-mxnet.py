#!/usr/bin/env python
"""Kill stray distributed workers (reference ``tools/kill-mxnet.py``†):
after a crashed multi-process run, orphaned workers can hold the
coordinator port.  Matches processes whose command line contains the
given pattern (default: dist_worker / launch.py children).

  python tools/kill-mxnet.py [pattern]
"""
import os
import signal
import sys


def main():
    pattern = sys.argv[1] if len(sys.argv) > 1 else "launch.py"
    me = os.getpid()
    killed = []
    for pid in filter(str.isdigit, os.listdir("/proc")):
        if int(pid) == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    errors="replace")
        except OSError:
            continue
        if pattern in cmd and "kill-mxnet" not in cmd:
            try:
                os.kill(int(pid), signal.SIGKILL)
                killed.append((pid, cmd[:80]))
            except OSError:
                pass
    for pid, cmd in killed:
        print(f"killed {pid}: {cmd}")
    if not killed:
        print(f"no processes matching {pattern!r}")


if __name__ == "__main__":
    main()
