"""mxrace CLI (static Pass 1).

Exit codes (same contract as tools/mxlint, pinned by
tests/test_race.py):

* 0 — no findings outside the committed baseline;
* 1 — new findings (lock-order cycle, growth drift vs
  ``contracts/lockorder.json``, unguarded shared attr, stale README
  table);
* 2 — usage / internal error.

The dynamic lockset sanitizer (Pass 2) is not run here — it rides
the test suite under ``MXTPU_RACE=1``; see mxtpu/analysis/lockset.py.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def _load_concurrency():
    """Load the analyzer by file path — importing it as
    ``mxtpu.analysis.concurrency`` would execute ``mxtpu/__init__``
    (and therefore jax); a lint tool must not pay a framework import
    and must survive a broken tree."""
    path = REPO_ROOT / "mxtpu" / "analysis" / "concurrency.py"
    spec = importlib.util.spec_from_file_location(
        "_mxrace_concurrency", path)
    mod = importlib.util.module_from_spec(spec)
    assert spec.loader is not None
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    conc = _load_concurrency()
    core = conc.lintcore
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxrace",
        description="Lock-order graph + shared-state hygiene for the "
                    "threaded serving/obs stack (static Pass 1 of "
                    "mxrace).")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to scan (default: "
                         f"{' '.join(conc.SCOPES)})")
    ap.add_argument("--check", action="store_true",
                    help="counts only; exit 1 on new findings "
                         "(CI mode)")
    ap.add_argument("--update", action="store_true",
                    help="rewrite contracts/lockorder.json from the "
                         "current tree and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings + graph as JSON")
    ap.add_argument("--fix-readme", action="store_true",
                    help="regenerate the README lock-order table and "
                         "exit")
    ap.add_argument("--lockfile", type=Path,
                    default=conc.DEFAULT_LOCKFILE,
                    help="lock-order contract JSON")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help="accepted-findings baseline JSON")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the "
                         "baseline and exit 0")
    args = ap.parse_args(argv)
    paths = tuple(args.paths) or conc.SCOPES

    t0 = time.perf_counter()
    try:
        if args.update:
            an = conc.scan(paths)
            if an.parse_errors:
                for f in an.parse_errors:
                    print("  " + f.format(), file=sys.stderr)
                return 2
            g = conc.build_graph(an)
            cyc = conc.cycle_findings(g)
            if cyc:  # never pin a cyclic graph as the contract
                for f in cyc:
                    print("  " + f.format())
                print(f"mxrace: refusing --update: "
                      f"{len(cyc)} lock-order cycle(s)")
                return 1
            conc.save_lockfile(conc.lockfile_dict(g), args.lockfile)
            print(f"mxrace: wrote {args.lockfile} "
                  f"({len(g.locks)} locks, {len(g.edges)} edges, "
                  f"{time.perf_counter() - t0:.2f}s)")
            return 0

        if args.fix_readme:
            an = conc.scan(paths)
            g = conc.build_graph(an)
            changed = conc.fix_readme(REPO_ROOT, g)
            print("README.md lock-order table "
                  + ("rewritten" if changed else "already current"))
            return 0

        findings, notices, g = conc.run_check(
            paths, lockfile=args.lockfile)
    except (SyntaxError, OSError, ValueError) as e:
        print(f"mxrace: internal error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        core.write_baseline(findings, args.baseline)
        print(f"wrote {len({f.fingerprint for f in findings})} "
              f"fingerprints to {args.baseline}")
        return 0

    try:
        baseline = core.load_baseline(args.baseline)
    except (ValueError, OSError) as e:
        print(f"mxrace: cannot read baseline {args.baseline}: {e}",
              file=sys.stderr)
        return 2
    new, old = core.split_by_baseline(findings, baseline)
    dt = time.perf_counter() - t0

    if args.as_json:
        print(json.dumps(
            {"new": [f.as_json() for f in new],
             "baselined": [f.as_json() for f in old],
             "notices": notices,
             "locks": {n: i["kind"]
                       for n, i in sorted(g.locks.items())},
             "edges": sorted(f"{a} -> {b}" for (a, b) in g.edges),
             "seconds": round(dt, 3)}, indent=1))
    elif args.check:
        print(f"mxrace: {len(new)} new, {len(old)} baselined, "
              f"{len(g.locks)} locks, {len(g.edges)} edges "
              f"({dt:.2f}s)")
        for f in new:
            print("  " + f.format())
    else:
        for f in new:
            print(f.format())
        for n in notices:
            print(f"note: {n}")
        if old:
            print(f"({len(old)} baselined finding(s) suppressed; "
                  f"see {args.baseline.name})")
        print(f"mxrace: {len(new)} new finding(s), {len(g.locks)} "
              f"locks, {len(g.edges)} edges in {dt:.2f}s")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
