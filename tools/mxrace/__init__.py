"""mxrace — lock-order graphs + lockset race detection (ISSUE 9).

The third static-analysis tier next to tools/mxlint (AST source
rules) and tools/hlocheck (compiled-program contracts):

* Pass 1 (static, this CLI): ``mxtpu/analysis/concurrency.py``
  extracts the lock-order DAG of the threaded serving/obs stack and
  pins it in ``contracts/lockorder.json``; cycles and unannotated
  shared mutable attrs are findings.
* Pass 2 (dynamic): ``mxtpu/analysis/lockset.py`` is an Eraser-style
  lockset sanitizer the test suite activates with ``MXTPU_RACE=1``.

CLI mirrors mxlint: ``python -m tools.mxrace [--check|--update|
--json|--fix-readme]``, exit 0/1/2.
"""
