"""Ablation profile of the BERT-Large training step on the real chip
(VERDICT r4 item 3 — the profile_resnet.py treatment for BERT).

Decomposes fwd+bwd time at b32 s128 (and b8 s512) by knocking out one
component at a time and re-measuring the sustained chained step
(tools/microbench.py methodology: a real data dependence threads the
iterations, so nothing is DCE'd).  Components are ablated by
monkeypatching the model module's class names before construction —
the blocks resolve them at call time.

r6 additions, covering the hot-path work this profile motivated:
- ``epilogue_lax``     — MXTPU_FUSED_LN_EPILOGUE=0: the fused
  bias+dropout+add+LN Pallas epilogue replaced by the lax composite
  (same numerics, unfused memory traffic).
- ``loop_floor``       — the chained loop on an identity-cost body:
  dispatch + loop overhead that no model change can remove; subtract
  from every other row before computing component shares.
- ``step_batched`` /
  ``step_perparam``    — the FULL TrainStep (fwd+bwd+optimizer) via
  build_train_step with MXTPU_BATCHED_OPT=1/0; their difference is
  the shape/dtype-bucketed optimizer saving, and step_batched minus
  ``full`` is the whole optimizer+writeback share.
- ``step_zero``        — the FULL TrainStep on a dp mesh over every
  local device (dp = min(8, devices)) with ZeRO-1 sharded optimizer
  states; vs step_batched this prices the reduce-scatter/all-gather
  exchange against the dp× opt-state HBM saving.  Skipped on a
  single-device host.
- ``--cost``           — also print TrainStep.cost_analysis() FLOPs /
  bytes for the step program (on TPU the Pallas custom calls hide
  their FLOPs; the CPU lowering counts everything — see
  bench.py _TRAIN_FLOPS provenance notes).

Usage: python tools/profile_bert.py [batch] [seqlen] [only,csv] [--cost]
(MXTPU_PROFILE_BERT_MODEL=tiny|base|large swaps the model so the
harness itself can be smoke-tested on a CPU box.)
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.microbench import sustained


def sustained_ms(fn, x0, n=10, repeats=3):
    return sustained(fn, x0, n=n, repeats=repeats) * 1e3


def _build_bert(seqlen, dropout=0.1):
    """bert_large unless MXTPU_PROFILE_BERT_MODEL overrides — the
    tiny/base tiers exist so the harness itself can be smoke-tested on
    a CPU box where a Large compile takes minutes."""
    import mxtpu.models.transformer as tr
    from mxtpu import knobs
    kind = knobs.get("MXTPU_PROFILE_BERT_MODEL")
    if kind == "tiny":
        return tr.BERTModel(30522, 128, 512, 2, 2, max_length=seqlen,
                            dropout=dropout)
    if kind == "base":
        return tr.bert_base(vocab_size=30522, max_length=seqlen,
                            dropout=dropout)
    return tr.bert_large(vocab_size=30522, max_length=seqlen,
                         dropout=dropout)


def build_loss_fn(batch, seqlen, variant, dropout=0.1):
    """Returns (loss_of(x_tokens_f32) -> scalar, token array)."""
    import mxtpu.models.transformer as tr
    from mxtpu import nd
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon import nn
    from mxtpu.gluon.block import HybridBlock, _traced_forward
    from mxtpu.ndarray.ndarray import NDArray
    from mxtpu.symbol import _is_aux_name

    saved = {}

    def patch(name, cls):
        saved[name] = getattr(tr, name)
        setattr(tr, name, cls)

    class AttnCoreOnlyV(tr.MultiHeadAttention):
        # flash-attention core replaced by the value passthrough:
        # QKV/proj GEMMs stay (isolates the attention-core cost)
        def hybrid_forward(self, F, x):
            u, h = self._units, self._heads
            qkv = self.qkv(x)
            v = F.slice_axis(qkv, axis=-1, begin=2 * u, end=3 * u)
            out = self.proj(v)
            if self.drop is not None:
                out = self.drop(out)
            return out

    class AttnIdentity(HybridBlock):
        def __init__(self, *a, **k):
            super().__init__()

        def hybrid_forward(self, F, x):
            return x

    class FFNIdentity(HybridBlock):
        def __init__(self, *a, **k):
            super().__init__()

        def hybrid_forward(self, F, x):
            return x

    class LNIdentity(HybridBlock):
        def __init__(self, *a, **k):
            super().__init__()

        def hybrid_forward(self, F, x):
            return x

    if variant == "attn_core_ablated":
        patch("MultiHeadAttention", AttnCoreOnlyV)
    elif variant == "attn_ablated":
        patch("MultiHeadAttention", AttnIdentity)
    elif variant == "ffn_ablated":
        patch("PositionwiseFFN", FFNIdentity)
    elif variant == "ln_ablated":
        saved["LayerNorm"] = nn.LayerNorm
        nn.LayerNorm = LNIdentity

    if variant == "no_dropout":
        dropout = 0.0

    try:
        net = _build_bert(seqlen, dropout)
        if variant == "mlm_ablated":
            net.mlm = nn.Dense(1024, flatten=False)
            net.register_child(net.mlm)
        net.initialize(init="xavier")
    finally:
        for k, v in saved.items():
            if k == "LayerNorm":
                nn.LayerNorm = v
            else:
                setattr(tr, k, v)

    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 30522, (batch, seqlen))
                       .astype(np.float32))

    # collect params once (eager)
    x_nd = NDArray(toks, None, _placed=True)
    from mxtpu import autograd
    with autograd.record():
        net(x_nd)
    params = net.collect_params()
    plist = list(params.values())
    pvals0 = [p.data().data for p in plist]
    cdt = jnp.bfloat16

    lfn = gloss.SoftmaxCrossEntropyLoss()
    V = net.mlm._units if hasattr(net.mlm, "_units") else 30522

    def loss_of(tv, xx):
        pvals = [v.astype(cdt)
                 if not _is_aux_name(plist[i].name)
                 and jnp.issubdtype(v.dtype, jnp.floating) else v
                 for i, v in enumerate(tv)]
        raw_outs, _, _, _ = _traced_forward(
            net, {p.name: p for p in plist}, pvals,
            [NDArray(xx, None, _placed=True)], True,
            jax.random.PRNGKey(0))
        pred = NDArray(raw_outs[0], None, _placed=True)
        l = lfn(pred.reshape((-1, pred.shape[-1])),
                NDArray(xx.reshape(-1), None, _placed=True))
        return jnp.mean(l.data.astype(jnp.float32))

    return loss_of, toks, tuple(pvals0), plist


class _env:
    """Set env overrides for the duration of one variant build+measure
    (the kill switches are read at trace time, and every measurement
    jits afresh)."""

    def __init__(self, **kv):
        self._kv = kv

    def __enter__(self):
        self._old = {k: os.environ.get(k) for k in self._kv}
        os.environ.update(self._kv)

    def __exit__(self, *a):
        for k, v in self._old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def measure_train_step(batch, seqlen, batched, zero=None):
    """Full compiled TrainStep (fwd+bwd+optimizer+writeback) per-step
    ms — the number bench.py's BERT row is made of.  ``zero=1`` runs
    it on a dp mesh over every local device with ZeRO-1 sharded
    optimizer states (bench.py's bert_zero row)."""
    from mxtpu import nd, parallel
    from mxtpu.gluon import loss as gloss

    mesh = None
    if zero:
        dp = min(8, jax.device_count())
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:dp]), ("dp",))
    with _env(MXTPU_BATCHED_OPT="1" if batched else "0"):
        net = _build_bert(seqlen)
        net.initialize(init="xavier")

        def mlm_loss(pred, y):
            return gloss.SoftmaxCrossEntropyLoss()(
                pred.reshape((-1, pred.shape[-1])), y.reshape((-1,)))

        step = parallel.build_train_step(
            net, mlm_loss, "adam", {"learning_rate": 1e-4},
            compute_dtype="bfloat16", cast_batch=False,
            mesh=mesh, zero=zero)
        rng = np.random.RandomState(0)
        toks = nd.array(rng.randint(0, 30522, (batch, seqlen))
                        .astype(np.float32))
        last = step.run_steps(toks, toks, 2, reuse_batch=True)
        float(last.asnumpy()[-1])  # compile + drain
        n, best = 8, float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            last = step.run_steps(toks, toks, n, reuse_batch=True)
            float(last.asnumpy()[-1])
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e3, step, toks


def measure_variant(batch, seqlen, variant):
    if variant == "step_zero":
        dp = min(8, jax.device_count())
        if dp <= 1 or batch % dp:
            return None  # needs a >1 dp mesh that divides the batch
        t, _, _ = measure_train_step(batch, seqlen, True, zero=1)
        return t
    if variant in ("step_batched", "step_perparam"):
        t, _, _ = measure_train_step(batch, seqlen,
                                     variant == "step_batched")
        return t
    if variant == "loop_floor":
        rng = np.random.RandomState(0)
        toks = jnp.asarray(rng.randint(0, 30522, (batch, seqlen))
                           .astype(np.float32))
        # identity-cost body: what remains is the chained-loop +
        # dispatch floor every other row also pays
        return sustained_ms(
            lambda xx: jnp.clip(xx + jnp.sum(xx) * 0.0 + 1e-12,
                                0, 30521),
            toks, n=8, repeats=3)

    env = {"epilogue_lax": {"MXTPU_FUSED_LN_EPILOGUE": "0"}} \
        .get(variant, {})
    with _env(**env):
        loss_of, toks, pvals, plist = build_loss_fn(
            batch, seqlen, variant)

        grad_fn = jax.grad(lambda tv, xx: loss_of(tv, xx))

        def chain(xx):
            g = grad_fn(pvals, xx)
            s = sum(jnp.sum(gi.astype(jnp.float32))
                    for gi in jax.tree_util.tree_leaves(g))
            # fold the grad signal back into the token ids (kept valid
            # by a tiny scale + floor) so iterations are data-dependent
            return jnp.clip(xx + s * 1e-12, 0, 30521)

        return sustained_ms(chain, toks, n=8, repeats=3)


VARIANTS = ["full", "attn_core_ablated", "attn_ablated", "ffn_ablated",
            "mlm_ablated", "ln_ablated", "no_dropout", "epilogue_lax",
            "loop_floor", "step_batched", "step_perparam", "step_zero"]


def main():
    argv = [a for a in sys.argv[1:] if not a.startswith("--")]
    want_cost = "--cost" in sys.argv[1:]
    batch = int(argv[0]) if len(argv) > 0 else 32
    seqlen = int(argv[1]) if len(argv) > 1 else 128
    only = argv[2].split(",") if len(argv) > 2 else None
    print(f"device={jax.devices()[0]} b{batch} s{seqlen} bf16 "
          f"(fwd+bwd, chained; step_* rows add the optimizer)")
    base = None
    for v in VARIANTS:
        if only and v not in only:
            continue
        t = measure_variant(batch, seqlen, v)
        if t is None:
            print(f"{v:>18}: skipped (needs a >1-device dp mesh that "
                  f"divides the batch)", flush=True)
            continue
        tok_s = batch * seqlen / t * 1e3
        delta = f"  (component ~{base - t:6.1f} ms)" \
            if base is not None and not v.startswith("step_") \
            and v != "loop_floor" else ""
        if v == "full":
            base = t
        print(f"{v:>18}: {t:7.1f} ms/step  {tok_s:9.0f} tok/s{delta}",
              flush=True)
    if want_cost:
        from mxtpu import nd, parallel
        from mxtpu.gluon import loss as gloss
        net = _build_bert(seqlen)
        net.initialize(init="xavier")

        def mlm_loss(pred, y):
            return gloss.SoftmaxCrossEntropyLoss()(
                pred.reshape((-1, pred.shape[-1])), y.reshape((-1,)))

        step = parallel.build_train_step(
            net, mlm_loss, "adam", {"learning_rate": 1e-4},
            compute_dtype="bfloat16", cast_batch=False)
        rng = np.random.RandomState(0)
        toks = nd.array(rng.randint(0, 30522, (batch, seqlen))
                        .astype(np.float32))
        ca = step.cost_analysis(toks, toks)
        flops = ca.get("flops")
        toks_n = batch * seqlen
        print(f"cost_analysis: flops={flops:.3e} "
              f"({flops / toks_n:.3e}/token)  "
              f"bytes={ca.get('bytes accessed', float('nan')):.3e}  "
              f"(Pallas custom calls hide their FLOPs on TPU; the CPU "
              f"lowering counts everything)", flush=True)


if __name__ == "__main__":
    main()
