"""Ablation profile of the BERT-Large training step on the real chip
(VERDICT r4 item 3 — the profile_resnet.py treatment for BERT).

Decomposes fwd+bwd time at b32 s128 (and b8 s512) by knocking out one
component at a time and re-measuring the sustained chained step
(tools/microbench.py methodology: a real data dependence threads the
iterations, so nothing is DCE'd).  Components are ablated by
monkeypatching the model module's class names before construction —
the blocks resolve them at call time.

Usage: PYTHONPATH=.:... python tools/profile_bert.py [batch] [seqlen]
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.microbench import sustained


def sustained_ms(fn, x0, n=10, repeats=3):
    return sustained(fn, x0, n=n, repeats=repeats) * 1e3


def build_loss_fn(batch, seqlen, variant, dropout=0.1):
    """Returns (loss_of(x_tokens_f32) -> scalar, token array)."""
    import mxtpu.models.transformer as tr
    from mxtpu import nd
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon import nn
    from mxtpu.gluon.block import HybridBlock, _traced_forward
    from mxtpu.ndarray.ndarray import NDArray
    from mxtpu.symbol import _is_aux_name

    saved = {}

    def patch(name, cls):
        saved[name] = getattr(tr, name)
        setattr(tr, name, cls)

    class AttnCoreOnlyV(tr.MultiHeadAttention):
        # flash-attention core replaced by the value passthrough:
        # QKV/proj GEMMs stay (isolates the attention-core cost)
        def hybrid_forward(self, F, x):
            u, h = self._units, self._heads
            qkv = self.qkv(x)
            v = F.slice_axis(qkv, axis=-1, begin=2 * u, end=3 * u)
            out = self.proj(v)
            if self.drop is not None:
                out = self.drop(out)
            return out

    class AttnIdentity(HybridBlock):
        def __init__(self, *a, **k):
            super().__init__()

        def hybrid_forward(self, F, x):
            return x

    class FFNIdentity(HybridBlock):
        def __init__(self, *a, **k):
            super().__init__()

        def hybrid_forward(self, F, x):
            return x

    class LNIdentity(HybridBlock):
        def __init__(self, *a, **k):
            super().__init__()

        def hybrid_forward(self, F, x):
            return x

    if variant == "attn_core_ablated":
        patch("MultiHeadAttention", AttnCoreOnlyV)
    elif variant == "attn_ablated":
        patch("MultiHeadAttention", AttnIdentity)
    elif variant == "ffn_ablated":
        patch("PositionwiseFFN", FFNIdentity)
    elif variant == "ln_ablated":
        saved["LayerNorm"] = nn.LayerNorm
        nn.LayerNorm = LNIdentity

    if variant == "no_dropout":
        dropout = 0.0

    try:
        net = tr.bert_large(vocab_size=30522, max_length=seqlen,
                            dropout=dropout)
        if variant == "mlm_ablated":
            net.mlm = nn.Dense(1024, flatten=False)
            net.register_child(net.mlm)
        net.initialize(init="xavier")
    finally:
        for k, v in saved.items():
            if k == "LayerNorm":
                nn.LayerNorm = v
            else:
                setattr(tr, k, v)

    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 30522, (batch, seqlen))
                       .astype(np.float32))

    # collect params once (eager)
    x_nd = NDArray(toks, None, _placed=True)
    from mxtpu import autograd
    with autograd.record():
        net(x_nd)
    params = net.collect_params()
    plist = list(params.values())
    pvals0 = [p.data().data for p in plist]
    cdt = jnp.bfloat16

    lfn = gloss.SoftmaxCrossEntropyLoss()
    V = net.mlm._units if hasattr(net.mlm, "_units") else 30522

    def loss_of(tv, xx):
        pvals = [v.astype(cdt)
                 if not _is_aux_name(plist[i].name)
                 and jnp.issubdtype(v.dtype, jnp.floating) else v
                 for i, v in enumerate(tv)]
        raw_outs, _, _, _ = _traced_forward(
            net, {p.name: p for p in plist}, pvals,
            [NDArray(xx, None, _placed=True)], True,
            jax.random.PRNGKey(0))
        pred = NDArray(raw_outs[0], None, _placed=True)
        l = lfn(pred.reshape((-1, pred.shape[-1])),
                NDArray(xx.reshape(-1), None, _placed=True))
        return jnp.mean(l.data.astype(jnp.float32))

    return loss_of, toks, tuple(pvals0), plist


def measure_variant(batch, seqlen, variant):
    loss_of, toks, pvals, plist = build_loss_fn(batch, seqlen, variant)

    grad_fn = jax.grad(lambda tv, xx: loss_of(tv, xx))

    def chain(xx):
        g = grad_fn(pvals, xx)
        s = sum(jnp.sum(gi.astype(jnp.float32))
                for gi in jax.tree_util.tree_leaves(g))
        # fold the grad signal back into the token ids (kept valid by
        # a tiny scale + floor) so iterations are data-dependent
        return jnp.clip(xx + s * 1e-12, 0, 30521)

    return sustained_ms(chain, toks, n=8, repeats=3)


VARIANTS = ["full", "attn_core_ablated", "attn_ablated", "ffn_ablated",
            "mlm_ablated", "ln_ablated", "no_dropout"]


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    seqlen = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    only = sys.argv[3].split(",") if len(sys.argv) > 3 else None
    print(f"device={jax.devices()[0]} b{batch} s{seqlen} bf16 "
          f"(fwd+bwd, chained)")
    base = None
    for v in VARIANTS:
        if only and v not in only:
            continue
        t = measure_variant(batch, seqlen, v)
        tok_s = batch * seqlen / t * 1e3
        delta = f"  (component ~{base - t:6.1f} ms)" \
            if base is not None and v != "full" else ""
        if v == "full":
            base = t
        print(f"{v:>18}: {t:7.1f} ms/step  {tok_s:9.0f} tok/s{delta}",
              flush=True)


if __name__ == "__main__":
    main()
