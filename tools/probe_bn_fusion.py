"""Measured evidence for the fused-BN Pallas dispatch (r5 item 1).

Chains K BN(+ReLU) layers back-to-back (output feeds input — nothing
can be DCE'd; see tools/microbench.py) and reports marginal per-layer
time for the XLA composite vs the channel-blocked Pallas kernel, at
each ResNet-50 stage shape (b256 bf16).  Also runs a conv+BN chain so
any relayout cost XLA inserts around the pallas_call shows up.

Usage: PYTHONPATH=.:... python tools/probe_bn_fusion.py [batch]
"""
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxtpu.kernels.batch_norm import (_pick_cb, bn_act_reference,
                                      fused_bn_act)
from tools.microbench import sustained


def bn_chain_time(shape, dtype, act, mode, K=8, grad=False):
    """Marginal ms per BN layer: time(K layers) via sustained chain."""
    N, C, H, W = shape
    rng = np.random.RandomState(0)
    x0 = jnp.array(rng.randn(*shape), dtype)
    g = jnp.array(rng.rand(C).astype(np.float32) + 0.5)
    b = jnp.array(rng.randn(C).astype(np.float32))

    if mode == "pallas":
        os.environ["MXTPU_FUSED_BN"] = "1"
        layer = lambda x: fused_bn_act(x, g, b, act=act)[0]
    elif mode == "xla":
        os.environ["MXTPU_FUSED_BN"] = "0"
        layer = lambda x: fused_bn_act(x, g, b, act=act)[0]
    else:  # oracle: plain jnp autodiff
        layer = lambda x: bn_act_reference(x, g, b, act=act)[0]

    if not grad:
        def step(x):
            for _ in range(K):
                x = layer(x)
            return x
        t = sustained(step, x0, n=8, repeats=2)
    else:
        def loss(x):
            for _ in range(K):
                x = layer(x)
            # quadratic loss -> the output cotangent is data-dependent
            # (a linear loss gives a CONSTANT dy and XLA folds most of
            # the BN backward away — the r3 DCE trap)
            return jnp.sum(jnp.square(x.astype(jnp.float32))) * 1e-6

        gf = jax.grad(loss)

        def step(x):
            dx = gf(x)
            return x + dx.astype(x.dtype) * 1e-12
        t = sustained(step, x0, n=8, repeats=2)
    os.environ.pop("MXTPU_FUSED_BN", None)
    return t * 1e3 / K


def conv_bn_chain_time(shape, dtype, mode, K=6, grad=True):
    """conv3x3(C->C) + BN + relu chain — the realistic fusion context."""
    N, C, H, W = shape
    rng = np.random.RandomState(0)
    x0 = jnp.array(rng.randn(*shape), dtype)
    w = jnp.array(rng.randn(C, C, 3, 3).astype(np.float32)
                  * (1.0 / np.sqrt(9 * C)), dtype)
    g = jnp.array(rng.rand(C).astype(np.float32) + 0.5)
    b = jnp.array(rng.randn(C).astype(np.float32))

    def conv(x):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    if mode == "pallas":
        os.environ["MXTPU_FUSED_BN"] = "1"
        layer = lambda x: fused_bn_act(conv(x), g, b, act="relu")[0]
    else:
        os.environ["MXTPU_FUSED_BN"] = "0"
        layer = lambda x: fused_bn_act(conv(x), g, b, act="relu")[0]

    def loss(x):
        y = x
        for _ in range(K):
            y = layer(y)
        return jnp.sum(jnp.square(y.astype(jnp.float32))) * 1e-6

    gf = jax.grad(loss)

    def step(x):
        dx = gf(x)
        return x + dx.astype(x.dtype) * 1e-12

    t = sustained(step, x0, n=8, repeats=2)
    os.environ.pop("MXTPU_FUSED_BN", None)
    return t * 1e3 / K


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    dtype = jnp.bfloat16
    print(f"device={jax.devices()[0]} batch={batch} dtype=bfloat16")
    stages = [  # (name, C, H)  — ResNet-50 stage shapes
        ("stem112", 64, 112),
        ("s1_56", 256, 56),
        ("s2_28", 512, 28),
        ("s3_14", 1024, 14),
        ("s4_7", 2048, 7),
    ]
    only = sys.argv[2].split(",") if len(sys.argv) > 2 else None
    if only:
        stages = [s for s in stages if s[0] in only]
    print(f"{'shape':>10} {'cb(f/b)':>9} {'xla f':>7} {'pal f':>7} "
          f"{'xla f+b':>8} {'pal f+b':>8}  ms/layer")
    for name, C, H in stages:
        shape = (batch, C, H, H)
        S = H * H
        cbf = _pick_cb(batch, C, S, 2, 14)
        xf = bn_chain_time(shape, dtype, "relu", "xla", grad=False)
        xb = bn_chain_time(shape, dtype, "relu", "xla", grad=True)
        try:
            pf = bn_chain_time(shape, dtype, "relu", "pallas",
                               grad=False)
            pb = bn_chain_time(shape, dtype, "relu", "pallas",
                               grad=True)
            pf, pb = f"{pf:7.3f}", f"{pb:8.3f}"
        except Exception as e:  # noqa: BLE001 — record Mosaic failures
            pf, pb = "  FAIL", "  FAIL"
            print(f"    [{name}] pallas error: {str(e)[:4000]}")
        print(f"{name:>10} {str(cbf):>9} {xf:7.3f} {pf} "
              f"{xb:8.3f} {pb}")

    from mxtpu import knobs
    if not knobs.get("MXTPU_PROBE_CONV"):
        return
    print("\nconv3x3+BN+relu chain (fwd+bwd, marginal ms/layer):")
    for name, C, H in stages[1:]:
        shape = (batch, C // 4, H, H)   # bottleneck inner width
        xc = conv_bn_chain_time(shape, dtype, "xla")
        try:
            pc = conv_bn_chain_time(shape, dtype, "pallas")
            pc = f"{pc:8.3f}"
        except Exception as e:  # noqa: BLE001
            pc = "    FAIL"
            print(f"    [{name}] pallas error: {str(e)[:120]}")
        print(f"{name:>10} C={C // 4:<5} xla {xc:8.3f}  pallas {pc}")


if __name__ == "__main__":
    main()
