"""mxmem CLI.

Exit codes (the contract tests/test_mem.py pins, mirroring mxlint /
hlocheck / mxprec / mxrace):

* 0 — every checked ledger matches; budgets + README table fresh;
* 1 — memory-ledger drift (or missing ledger in --check mode);
* 2 — usage / internal error (unknown target, unreadable ledger,
      orphaned ledger, empty baseline).

``--update`` re-compiles the named targets (default: all) on the CPU
backend and rewrites ``contracts/mem/<target>.json``; it also
bootstraps ``contracts/mem/budgets.json`` (the declarative per-
device-class HBM budgets) when — and only when — that file is
missing: budgets are hand-edited policy, never regenerated.  The
README HBM-decomposition table drift check rides only on a full
default check (no explicit targets), so a single-target round trip
stays cheap for tier-1 tests.  Compilation happens on the CPU backend
with the 8-virtual-device topology the test suite uses, so ledgers
are reproducible on any box.
"""
from __future__ import annotations

import os

# pin the environment BEFORE jax (imported via mxtpu) loads: memory
# ledgers are CPU-backend artifacts by definition
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import argparse   # noqa: E402
import json       # noqa: E402
import sys        # noqa: E402
import time       # noqa: E402
from pathlib import Path  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxmem",
        description="Static memory-footprint analysis over the "
                    "compiled hlocheck targets: peak HBM per device "
                    "decomposed into params / optimizer state / "
                    "activations / collectives scratch / KV table, "
                    "checked against committed memory ledgers "
                    "(contracts/mem/) and the declarative device-"
                    "class budgets (contracts/mem/budgets.json).")
    ap.add_argument("targets", nargs="*",
                    help="targets to process (default: every "
                         "committed ledger for --check, every "
                         "registered target for --update)")
    ap.add_argument("--check", action="store_true",
                    help="counts-only output; exit 1 on drift (CI "
                         "mode — this is also the default behaviour)")
    ap.add_argument("--update", action="store_true",
                    help="regenerate ledgers for the named targets "
                         "(bootstraps budgets.json if missing) and "
                         "exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit results as JSON")
    ap.add_argument("--list", action="store_true",
                    help="list registered targets and exit")
    ap.add_argument("--fix-readme", action="store_true",
                    help="regenerate the README memory table from "
                         "the COMMITTED ledgers (no compiling) and "
                         "exit")
    ap.add_argument("--contracts-dir", type=Path, default=None,
                    help="lockfile directory (default: contracts/)")
    args = ap.parse_args(argv)

    from mxtpu.analysis import contracts as C
    from mxtpu.analysis import memflow as M
    from tools.hlocheck import targets as T

    directory = args.contracts_dir or C.CONTRACTS_DIR

    if args.list:
        for name in sorted(T.MEM_TARGETS):
            state = "ledger" if M.ledger_path(
                name, directory).exists() else "NO LEDGER"
            print(f"{name:20s} [{state}]")
        return 0

    if args.fix_readme:
        ledgers = M.committed_ledgers(directory)
        if not ledgers:
            print(f"mxmem: no ledgers in {M.mem_dir(directory)}"
                  f" — run --update first", file=sys.stderr)
            return 2
        changed = M.fix_readme(M.REPO_ROOT, ledgers)
        print("mxmem: README memory table "
              + ("rewritten" if changed else "already fresh"))
        return 0

    if args.targets:
        unknown = [t for t in args.targets
                   if t not in T.MEM_TARGETS]
        if unknown:
            print(f"mxmem: unknown target(s): "
                  f"{', '.join(unknown)} (see --list)",
                  file=sys.stderr)
            return 2
        names = list(args.targets)
    elif args.update:
        names = sorted(T.MEM_TARGETS)
    else:
        # check everything that has a committed ledger AND is still a
        # registered target; a ledger whose target vanished is an
        # error, not silence
        names = sorted(p.stem for p in
                       M.mem_dir(directory).glob("*.json")
                       if p.stem != M.BUDGETS_NAME) \
            if M.mem_dir(directory).is_dir() else []
        orphans = [n for n in names if n not in T.MEM_TARGETS]
        if orphans:
            print(f"mxmem: ledger(s) without a registered target: "
                  f"{', '.join(orphans)}", file=sys.stderr)
            return 2
        if not names:
            print(f"mxmem: no ledgers in "
                  f"{M.mem_dir(directory)} — run --update first",
                  file=sys.stderr)
            return 2

    # budgets: hand-edited policy.  --update bootstraps a missing
    # file (so a tmp-dir round trip is self-contained); --check
    # treats an unreadable file as an internal error
    bpath = M.budgets_path(directory)
    if args.update and not bpath.exists():
        M.save_budgets(dict(M.DEFAULT_BUDGETS), directory)
        if not args.as_json:
            print(f"mxmem: bootstrapped {bpath}")
    try:
        budgets = M.load_budgets(directory)
    except (ValueError, OSError) as e:
        print(f"mxmem: cannot read {bpath}: {e}", file=sys.stderr)
        return 2

    # README drift rides only on a FULL sweep (it is a whole-tree
    # artifact); explicit-target runs stay cheap
    full = not args.targets

    t0 = time.perf_counter()
    all_violations: list = []
    results = {}
    for name in names:
        t1 = time.perf_counter()
        record = T.build_mem(name)
        ledger = M.build_ledger(record, budgets)
        dt = time.perf_counter() - t1
        if args.update:
            path = M.save_ledger(ledger, directory)
            results[name] = {"updated": str(path),
                             "programs": sorted(ledger["programs"]),
                             "hazards": len(ledger["hazards"]),
                             "seconds": round(dt, 1)}
            if not args.as_json:
                print(f"mxmem: wrote {path} "
                      f"({len(ledger['programs'])} program(s), "
                      f"{len(ledger['hazards'])} hazard(s), "
                      f"{dt:.1f}s)")
            continue
        try:
            committed = M.load_ledger(name, directory)
        except FileNotFoundError:
            all_violations.append(
                f"{name}: no ledger "
                f"{M.ledger_path(name, directory)} — run "
                f"--update {name}")
            continue
        except (ValueError, OSError) as e:
            print(f"mxmem: cannot read ledger for {name}: {e}",
                  file=sys.stderr)
            return 2
        drift = M.compare_ledgers(committed, ledger)
        all_violations += [f"{name}: {d}" for d in drift]
        results[name] = {"drift": drift, "seconds": round(dt, 1)}
        if not args.as_json and not args.check:
            print(f"mxmem: {name}: {len(drift)} drift(s) "
                  f"({dt:.1f}s)")

    if args.update:
        if args.as_json:
            print(json.dumps(results, indent=1))
        return 0

    if full:
        all_violations += M.readme_drift(
            M.REPO_ROOT, M.committed_ledgers(directory))

    dt = time.perf_counter() - t0
    if args.as_json:
        print(json.dumps({"results": results,
                          "violations": all_violations,
                          "seconds": round(dt, 1)}, indent=1))
    else:
        for v in all_violations:
            print("  " + v)
        print(f"mxmem: {len(names)} target(s), "
              f"{len(all_violations)} violation(s) ({dt:.1f}s)")
    return 1 if all_violations else 0


if __name__ == "__main__":
    sys.exit(main())
