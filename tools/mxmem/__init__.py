"""tools.mxmem — static memory-footprint analysis with committed HBM
ledgers (ISSUE 20).

The analyzer lives in :mod:`mxtpu.analysis.memflow`; this package is
the CLI shell (``python -m tools.mxmem``) that builds per-target
memory records from the shared hlocheck fixtures
(``tools.hlocheck.targets.MEM_TARGETS``) and round-trips them against
``contracts/mem/<target>.json``.
"""
