"""Operator-coverage manifest generator.

Diffs the registry (``mxtpu.ops.registry.list_ops``) against the
reference's operator inventory (``src/operator/``† families, SURVEY.md
§2.1-N8) and writes ``OPS_MANIFEST.md`` at the repo root with one row
per reference op name: implemented (and under which registered name) or
missing.  Run from the repo root:

    python tools/op_manifest.py

The inventory below is the 2018-era (v1.2-1.3) MXNet public op surface
stated from upstream knowledge — the reference mount has been empty in
every session (SURVEY.md provenance caveat), so it cannot be extracted
mechanically.  Names the registry serves through an alias or equivalent
canonical name are mapped via EQUIV.
"""
import os
import sys
from collections import OrderedDict

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# reference op inventory by family: {family: [op names]}
REFERENCE_OPS = OrderedDict([
    ("nn (src/operator/nn/*)", [
        "Convolution", "Deconvolution", "FullyConnected", "Pooling",
        "Activation", "BatchNorm", "Dropout", "SoftmaxActivation",
        "softmax", "log_softmax", "softmin", "LayerNorm", "LRN",
        "Embedding", "UpSampling", "im2col", "col2im",
    ]),
    ("legacy nn (v1 aliases)", [
        "Convolution_v1", "Pooling_v1", "BatchNorm_v1",
        "IdentityAttachKLSparseReg",
    ]),
    ("elemwise unary (tensor/elemwise_unary_op*)", [
        "abs", "sign", "round", "rint", "ceil", "floor", "trunc", "fix",
        "square", "sqrt", "cbrt", "rsqrt", "rcbrt", "exp", "log",
        "log10", "log2", "log1p", "expm1", "gamma", "gammaln", "erf",
        "erfinv", "digamma", "relu", "sigmoid", "hard_sigmoid",
        "softsign", "reciprocal", "negative", "logical_not",
        "sin", "cos", "tan", "arcsin", "arccos", "arctan", "degrees",
        "radians", "sinh", "cosh", "tanh", "arcsinh", "arccosh",
        "arctanh", "make_loss", "stop_gradient", "BlockGrad", "identity",
        "_copy", "cast", "Cast", "zeros_like", "ones_like",
        "shape_array", "size_array", "amp_cast", "amp_multicast",
    ]),
    ("elemwise binary + scalar (tensor/elemwise_binary*_op*)", [
        "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
        "_plus", "_minus", "_mul", "_div", "_mod", "_power", "_maximum",
        "_minimum", "_hypot", "_equal", "_not_equal", "_greater",
        "_greater_equal", "_lesser", "_lesser_equal", "_logical_and",
        "_logical_or", "_logical_xor", "_plus_scalar", "_minus_scalar",
        "_rminus_scalar", "_mul_scalar", "_div_scalar", "_rdiv_scalar",
        "_mod_scalar", "_rmod_scalar", "_power_scalar", "_rpower_scalar",
        "_maximum_scalar", "_minimum_scalar", "_hypot_scalar",
        "_equal_scalar", "_not_equal_scalar", "_greater_scalar",
        "_greater_equal_scalar", "_lesser_scalar",
        "_lesser_equal_scalar", "_logical_and_scalar",
        "_logical_or_scalar", "_logical_xor_scalar",
        "_scatter_elemwise_div", "_scatter_plus_scalar",
        "_scatter_minus_scalar", "smooth_l1", "add_n", "ElementWiseSum",
    ]),
    ("broadcast (tensor/broadcast_reduce_op*, elemwise_broadcast*)", [
        "broadcast_add", "broadcast_sub", "broadcast_mul",
        "broadcast_div", "broadcast_mod", "broadcast_power",
        "broadcast_maximum", "broadcast_minimum", "broadcast_hypot",
        "broadcast_equal", "broadcast_not_equal", "broadcast_greater",
        "broadcast_greater_equal", "broadcast_lesser",
        "broadcast_lesser_equal", "broadcast_logical_and",
        "broadcast_logical_or", "broadcast_logical_xor",
        "broadcast_to", "broadcast_axis", "broadcast_like",
        "broadcast_axes",
    ]),
    ("reduce (tensor/broadcast_reduce_op_value*)", [
        "sum", "sum_axis", "mean", "prod", "nansum", "nanprod", "max",
        "min", "max_axis", "min_axis", "argmax", "argmin",
        "argmax_channel", "norm", "moments", "pick",
        "choose_element_0index", "fill_element_0index",
    ]),
    ("matrix / shape (tensor/matrix_op*, dot)", [
        "dot", "batch_dot", "Reshape", "reshape", "Flatten", "flatten",
        "transpose", "SwapAxis", "swapaxes", "expand_dims", "slice",
        "slice_axis", "slice_like", "SliceChannel", "split", "_split_v2",
        "Concat", "concat", "stack", "clip", "repeat", "tile", "reverse",
        "flip", "Pad", "pad", "squeeze", "depth_to_space",
        "space_to_depth", "reshape_like", "diag", "_slice_assign",
        "_slice_assign_scalar", "_crop_assign", "_crop_assign_scalar",
        "Crop", "space_to_batch_nd? (absent in 1.x)",
    ]),
    ("indexing (tensor/indexing_op*)", [
        "take", "batch_take", "one_hot", "gather_nd", "scatter_nd",
        "_scatter_set_nd", "where", "ravel_multi_index",
        "unravel_index", "Embedding_grad(sparse row_sparse)",
    ]),
    ("ordering (tensor/ordering_op*)", [
        "sort", "argsort", "topk",
    ]),
    ("init (tensor/init_op*)", [
        "_zeros", "_ones", "_full", "_eye", "_arange", "_linspace",
        "zeros_like", "ones_like",
    ]),
    ("linalg (tensor/la_op*)", [
        "linalg_gemm", "linalg_gemm2", "linalg_potrf", "linalg_potri",
        "linalg_trmm", "linalg_trsm", "linalg_sumlogdiag",
        "linalg_syrk", "linalg_gelqf", "linalg_syevd", "linalg_det",
        "linalg_inverse", "linalg_extractdiag", "linalg_makediag",
        "linalg_extracttrian", "linalg_maketrian", "linalg_slogdet",
        "khatri_rao",
    ]),
    ("random (random/*)", [
        "_random_uniform", "_random_normal", "_random_gamma",
        "_random_exponential", "_random_poisson",
        "_random_negative_binomial",
        "_random_generalized_negative_binomial", "_random_randint",
        "_sample_uniform", "_sample_normal", "_sample_gamma",
        "_sample_exponential", "_sample_poisson",
        "_sample_negative_binomial",
        "_sample_generalized_negative_binomial", "_sample_multinomial",
        "_sample_unique_zipfian", "_shuffle",
    ]),
    ("optimizer (optimizer_op*)", [
        "sgd_update", "sgd_mom_update", "mp_sgd_update",
        "mp_sgd_mom_update", "multi_sgd_update", "multi_sgd_mom_update",
        "multi_mp_sgd_update", "multi_mp_sgd_mom_update",
        "nag_mom_update", "mp_nag_mom_update", "adam_update",
        "rmsprop_update", "rmspropalex_update", "ftrl_update",
        "signsgd_update", "signum_update", "adagrad_update",
        "adadelta_update",
    ]),
    ("loss / output (softmax_output, regression, ctc)", [
        "SoftmaxOutput", "LinearRegressionOutput",
        "LogisticRegressionOutput", "MAERegressionOutput", "SVMOutput",
        "MakeLoss", "softmax_cross_entropy", "CTCLoss", "ctc_loss",
    ]),
    ("sequence / rnn", [
        "RNN", "SequenceMask", "SequenceLast", "SequenceReverse",
        "_rnn_param_concat",
    ]),
    ("spatial (grid/sampler/correlation/roi)", [
        "GridGenerator", "BilinearSampler", "SpatialTransformer",
        "Correlation", "ROIPooling", "InstanceNorm", "L2Normalization",
    ]),
    ("contrib detection (contrib/*)", [
        "_contrib_MultiBoxPrior", "_contrib_MultiBoxTarget",
        "_contrib_MultiBoxDetection", "_contrib_Proposal",
        "_contrib_MultiProposal", "_contrib_ROIAlign",
        "_contrib_box_nms", "_contrib_box_iou",
        "_contrib_bipartite_matching", "_contrib_box_encode(1.5)",
        "_contrib_box_decode(1.5)",
        "_contrib_PSROIPooling", "_contrib_DeformableConvolution",
        "_contrib_DeformablePSROIPooling",
    ]),
    ("contrib misc (contrib/*)", [
        "_contrib_CountSketch", "_contrib_fft", "_contrib_ifft",
        "_contrib_quadratic", "_contrib_boolean_mask",
        "_contrib_getnnz", "_contrib_index_copy",
        "_contrib_SyncBatchNorm", "_contrib_AdaptiveAvgPooling2D",
        "_contrib_BilinearResize2D", "_contrib_foreach",
        "_contrib_while_loop", "_contrib_cond",
        "_contrib_flash_attention (new capability)",
    ]),
    ("quantization (quantization/*)", [
        "_contrib_quantize", "_contrib_quantize_v2",
        "_contrib_dequantize", "_contrib_requantize",
        "_contrib_quantized_conv", "_contrib_quantized_fully_connected",
        "_contrib_quantized_pooling", "_contrib_quantized_flatten",
        "_contrib_quantized_concat", "_contrib_quantized_act",
    ]),
    ("sparse-specific (tensor/*sparse*, cast_storage)", [
        "cast_storage", "sparse_retain", "_sparse_adagrad_update",
    ]),
    ("custom / control", [
        "Custom", "_CustomFunction", "_NoGradient",
    ]),
    ("image (src/operator/image/*)", [
        "_image_to_tensor", "_image_normalize",
        "_image_flip_left_right", "_image_flip_top_bottom",
        "_image_random_flip_left_right",
        "_image_random_flip_top_bottom",
    ]),
])

# registry-name equivalences: reference name -> our canonical name
EQUIV = {
    "_plus": "_plus", "Reshape": "Reshape",
    "_contrib_MultiBoxPrior": "MultiBoxPrior",
    "_contrib_MultiBoxTarget": "MultiBoxTarget",
    "_contrib_MultiBoxDetection": "MultiBoxDetection",
    "_contrib_CountSketch": "_contrib_count_sketch",
    "_contrib_fft": "_contrib_fft",
    "_contrib_ifft": "_contrib_ifft",
    "_contrib_quadratic": "_contrib_quadratic",
    "_contrib_boolean_mask": "_contrib_boolean_mask",
    "_contrib_getnnz": "_contrib_getnnz",
    "_contrib_box_nms": "_contrib_box_nms",
    "_contrib_box_iou": "_contrib_box_iou",
    "_contrib_flash_attention (new capability)":
        "contrib_flash_attention",
    "_contrib_quantize": "quantize",
    "_contrib_quantize_v2": "quantize_v2",
    "_contrib_dequantize": "dequantize",
    "_contrib_foreach": "python:mxtpu.ndarray.contrib.foreach",
    "_contrib_while_loop": "python:mxtpu.ndarray.contrib.while_loop",
    "_contrib_cond": "python:mxtpu.ndarray.contrib.cond",
    "Custom": "python:mxtpu.operator.CustomOp",
    "_CustomFunction": "python:mxtpu.autograd.Function",
    "_NoGradient": "stop_gradient",
    "choose_element_0index": "pick",
    "fill_element_0index": "fill_element_0index",
    "Embedding_grad(sparse row_sparse)": "python:row_sparse grads "
        "(mxtpu/ndarray/sparse.py, dense-backed)",
    "_rnn_param_concat": "concat",
    "max_axis": "max", "min_axis": "min",
    "broadcast_axes": "broadcast_axis",
    "_slice_assign": "_slice_assign",
    "_crop_assign": "_slice_assign",
    "_crop_assign_scalar": "_slice_assign_scalar",
    "_scatter_set_nd": "_scatter_set_nd",
}

SKIP_MARKERS = ("absent", "(1.5)", "?")


def build_manifest():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mxtpu.ops.registry import OP_REGISTRY, list_ops
    names = set(list_ops())
    rule_ids = set()
    for n in names:
        rule_ids.add(id(OP_REGISTRY.get(n).fn))

    lines = ["# OPS_MANIFEST — operator coverage vs the reference",
             "",
             "Generated by `python tools/op_manifest.py` — do not edit "
             "by hand.", "",
             f"Registry: **{len(names)} public names**, "
             f"**{len(rule_ids)} distinct lowering rules**.",
             "",
             "Reference inventory: 2018-era MXNet v1.x "
             "(`src/operator/`†, from SURVEY.md knowledge — mount "
             "empty).  `python:` entries are capabilities served by "
             "Python surface instead of a registered op.", ""]
    total = impl = 0
    missing_all = []
    for family, ops in REFERENCE_OPS.items():
        rows = []
        fam_impl = 0
        for ref in ops:
            if any(m in ref for m in SKIP_MARKERS) and ref not in EQUIV:
                rows.append((ref, "n/a", "not in the reference era / "
                             "explicitly descoped"))
                continue
            total += 1
            ours = None
            if ref in EQUIV:
                ours = EQUIV[ref]
                if not ours.startswith("python:") and ours not in names:
                    ours = None
            elif ref in names:
                ours = ref
            elif ref.startswith("_contrib_") and ref[9:] in names:
                ours = ref[9:]
            if ours:
                impl += 1
                fam_impl += 1
                rows.append((ref, "yes", ours))
            else:
                rows.append((ref, "MISSING", ""))
                missing_all.append(ref)
        lines.append(f"## {family} — {fam_impl}/"
                     f"{sum(1 for r in rows if r[1] != 'n/a')}")
        lines.append("")
        lines.append("| reference op | status | served by |")
        lines.append("|---|---|---|")
        for ref, st, by in rows:
            lines.append(f"| `{ref}` | {st} | {by} |")
        lines.append("")
    lines.insert(5, f"Coverage: **{impl}/{total}** reference ops "
                 f"({100 * impl // total}%); {len(missing_all)} missing.")
    lines.insert(6, "")
    return "\n".join(lines), impl, total, missing_all


if __name__ == "__main__":
    text, impl, total, missing = build_manifest()
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "OPS_MANIFEST.md")
    with open(out, "w") as f:
        f.write(text + "\n")
    print(f"wrote {out}: {impl}/{total} implemented")
    if missing:
        print("missing:", ", ".join(missing))
