"""Ablation profile of the ResNet-50 training step on the real chip.

Decomposes the 119 ms/step (b256 bf16) into fwd / bwd / optimizer and
locates the conv-MFU gap.  Honest methodology (see tools/microbench.py):
sustained timing chains iterations with a real data dependence inside
one jitted program — the loss is folded back into the input at 1e-12 so
nothing is DCE'd, hoisted, or strength-reduced.
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from mxtpu import nd
from mxtpu.gluon import loss as gloss
from mxtpu.models import resnet50
from mxtpu.parallel import build_train_step

try:
    from tools.microbench import sustained
except ImportError:  # run as `python tools/profile_resnet.py`
    from microbench import sustained


def sustained_ms(apply_fn, x0, n=20, repeats=3):
    return sustained(apply_fn, x0, n=n, repeats=repeats) * 1e3


def build_fns(batch=256, dtype="bfloat16", layout="NCHW"):
    if layout == "NHWC":
        from mxtpu.gluon.model_zoo.vision import resnet50_v1
        net = resnet50_v1(classes=1000, layout="NHWC")
    else:
        net = resnet50(classes=1000)
    net.initialize(init="xavier")
    step = build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype=dtype)
    rng = np.random.RandomState(0)
    shape = (batch, 3, 224, 224) if layout == "NCHW" \
        else (batch, 224, 224, 3)
    x = nd.array(rng.randn(*shape).astype(np.float32))
    y = nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32))
    step._collect(x)

    params = step._params
    train_idx = step._train_idx
    frozen_idx = [i for i in range(len(params))
                  if i not in set(train_idx)]
    train_vals = tuple(params[i]._data._data for i in train_idx)
    frozen_vals = tuple(params[i]._data._data for i in frozen_idx)
    cdt = jnp.dtype(dtype)

    from mxtpu.gluon.block import _traced_forward
    from mxtpu.ndarray.ndarray import NDArray
    from mxtpu.symbol import _is_aux_name

    def loss_of(tv, fv, xx):
        pvals = [None] * len(params)
        for i, v in zip(train_idx, tv):
            pvals[i] = v
        for i, v in zip(frozen_idx, fv):
            pvals[i] = v
        pvals = [v.astype(cdt)
                 if v is not None and not _is_aux_name(params[i].name)
                 and jnp.issubdtype(v.dtype, jnp.floating) else v
                 for i, v in enumerate(pvals)]
        raw_outs, _, _, _ = _traced_forward(
            net, params, pvals,
            [NDArray(xx.astype(cdt), None, _placed=True)], True,
            jax.random.PRNGKey(0))
        l = gloss.SoftmaxCrossEntropyLoss()(
            NDArray(raw_outs[0], None, _placed=True),
            NDArray(y.data if hasattr(y, "data") else y, None,
                    _placed=True))
        return jnp.mean(l.data.astype(jnp.float32))

    return step, x, y, loss_of, train_vals, frozen_vals


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    layout = sys.argv[2] if len(sys.argv) > 2 else "NCHW"
    print(f"device: {jax.devices()[0]}  batch={batch} layout={layout}")
    step, x, y, loss_of, tv, fv = build_fns(batch=batch, layout=layout)

    xj = x.data

    # 1. forward only
    def fwd_chain(xx):
        l = loss_of(tv, fv, xx)
        return xx + l.astype(xx.dtype) * 1e-12

    t_fwd = sustained_ms(fwd_chain, xj, n=10)
    print(f"fwd-only:  {t_fwd:.1f} ms/step")

    # 2. forward+backward (grads wrt train params)
    grad_fn = jax.grad(lambda tv_, xx: loss_of(tv_, fv, xx))

    def fwdbwd_chain(xx):
        g = grad_fn(tv, xx)
        s = sum(jnp.sum(gi.astype(jnp.float32)) for gi in
                jax.tree_util.tree_leaves(g))
        return xx + s.astype(xx.dtype) * 1e-12

    t_fb = sustained_ms(fwdbwd_chain, xj, n=10)
    print(f"fwd+bwd:   {t_fb:.1f} ms/step  (bwd = {t_fb - t_fwd:.1f})")

    # 3. full train step via run_steps (fwd+bwd+sgd+aux)
    last = step.run_steps(x, y, 3, reuse_batch=True)
    float(last.asnumpy()[-1])
    t0 = time.perf_counter()
    last = step.run_steps(x, y, 10, reuse_batch=True)
    float(last.asnumpy()[-1])
    t_full = (time.perf_counter() - t0) / 10 * 1e3
    print(f"full step: {t_full:.1f} ms/step "
          f"-> {batch / t_full * 1e3:.0f} samples/sec")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _TRAIN_FLOPS, _peak_flops
    fl = _TRAIN_FLOPS["resnet50"] * batch / 1e12  # TFLOP, fwd+bwd
    peak = _peak_flops() or 197e12
    tf = fl / (t_fb / 1e3)
    print(f"fwd+bwd sustained: {tf:.1f} TF/s "
          f"({tf * 1e12 / peak * 100:.1f}% MFU)")


if __name__ == "__main__":
    main()
