"""Honest sustained-throughput microbench for the axon tunnel.

Methodology (the hard-won part): the loop body must CONSUME the
previous iteration's full output, or XLA deletes the work —
``y * 0`` is constant-folded, ``y[0, 0]`` is strength-reduced to a
row-column dot, and a loop-invariant ``a @ b`` is hoisted.  Earlier
probes fell for all three and over-reported by ~17x.  Here each
iteration's output IS the next iteration's input (like a real
network), weights are scaled to keep unit variance, and we divide by
the number of chained applications.  Dispatch (~10 ms/RPC on this
tunnel) amortizes across the chain inside ONE jitted program.

Run: python tools/microbench.py [matmul|conv|all]
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp


def sustained(apply_fn, x0, n=50, repeats=3):
    """Time n chained applications of apply_fn inside one jit program.

    apply_fn: x -> y with y.shape == x.shape (shape-preserving so the
    chain is expressible as fori_loop).  Returns seconds per
    application, best of `repeats`.
    """
    @jax.jit
    def run(x):
        return jax.lax.fori_loop(0, n, lambda i, x: apply_fn(x), x)

    out = run(x0)
    float(jnp.sum(out))  # compile + drain (host read = real tunnel sync)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run(x0)
        float(jnp.sum(out))
        best = min(best, (time.perf_counter() - t0) / n)
    return best


def bench_matmul():
    print("== sustained matmul (chained y = y @ W) ==")
    rows = []
    for (M, K) in [(4096, 4096), (8192, 8192), (50176, 256),
                   (50176, 1024), (6272, 1024), (8192, 1024)]:
        x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.bfloat16)
        w = (jax.random.normal(jax.random.PRNGKey(1), (K, K), jnp.bfloat16)
             / (K ** 0.5))
        t = sustained(lambda x: x @ w, x)
        tf = 2 * M * K * K / t / 1e12
        rows.append((M, K, tf, t * 1e3))
        print(f"  ({M},{K})@({K},{K}): {tf:.1f} TF/s  ({t*1e3:.2f} ms/op)")
    return rows


def bench_conv():
    print("== sustained conv 3x3 s1 SAME NHWC (chained, C=O) ==")
    for (H, C, N) in [(14, 256, 256), (28, 128, 256), (7, 512, 256),
                      (56, 64, 256), (14, 512, 256)]:
        x = jax.random.normal(jax.random.PRNGKey(0), (N, H, H, C),
                              jnp.bfloat16)
        w = (jax.random.normal(jax.random.PRNGKey(1), (3, 3, C, C),
                               jnp.bfloat16) / (3 * (C ** 0.5)))

        def step(x):
            return jax.lax.conv_general_dilated(
                x, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        t = sustained(step, x)
        tf = 2 * N * H * H * C * C * 9 / t / 1e12
        print(f"  b{N} {H}x{H} C={C}: {tf:.1f} TF/s  ({t*1e3:.2f} ms/op)")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    print("device:", jax.devices()[0])
    if which in ("matmul", "all"):
        bench_matmul()
    if which in ("conv", "all"):
        bench_conv()
