#!/usr/bin/env python
"""Parse training logs into (epoch, train-acc, val-acc, samples/sec)
rows (reference ``tools/parse_log.py``†).

  python tools/parse_log.py train.log
"""
import argparse
import re
import sys

TRAIN = re.compile(r"Epoch\[(\d+)\] Train-([\w-]+)=([\d.]+)")
VAL = re.compile(r"Epoch\[(\d+)\] Validation-([\w-]+)=([\d.]+)")
SPEED = re.compile(r"Epoch\[(\d+)\].*Speed: ([\d.]+) samples/sec")


def parse(lines):
    rows = {}
    for line in lines:
        m = TRAIN.search(line)
        if m:
            rows.setdefault(int(m.group(1)), {})[
                "train-" + m.group(2)] = float(m.group(3))
        m = VAL.search(line)
        if m:
            rows.setdefault(int(m.group(1)), {})[
                "val-" + m.group(2)] = float(m.group(3))
        m = SPEED.search(line)
        if m:
            row = rows.setdefault(int(m.group(1)), {})
            row.setdefault("speeds", []).append(float(m.group(2)))
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("logfile")
    args = p.parse_args()
    with open(args.logfile) as f:
        rows = parse(f)
    cols = sorted({k for r in rows.values() for k in r if k != "speeds"})
    print("\t".join(["epoch"] + cols + ["samples/sec"]))
    for epoch in sorted(rows):
        row = rows[epoch]
        speeds = row.get("speeds", [])
        avg = sum(speeds) / len(speeds) if speeds else float("nan")
        print("\t".join([str(epoch)] +
                        [f"{row.get(c, float('nan')):.4f}"
                         for c in cols] + [f"{avg:.1f}"]))


if __name__ == "__main__":
    main()
