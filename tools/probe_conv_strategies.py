"""Conv strategy shootout on the real chip (VERDICT r4 item 1).

Compares, with the honest chained harness (tools/microbench.py):
  a) XLA conv_general_dilated          (the current path)
  b) shifted-GEMM: sum over (kh,kw) of strided-slice + matmul, pure XLA
  c) Pallas kernel: VMEM-staged tiles, MXU dot per (kh,kw) shift

All NHWC, stride 1, SAME, C=O (chainable), bf16, b256.
"""
import functools
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:
    from tools.microbench import sustained
except ImportError:
    from microbench import sustained


def xla_conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def shifted_gemm_conv(x, w):
    # x: (N,H,W,C), w: (KH,KW,C,O); pad then accumulate 9 matmuls
    N, H, W, C = x.shape
    KH, KW, _, O = w.shape
    ph, pw = KH // 2, KW // 2
    xp = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    acc = jnp.zeros((N, H, W, O), jnp.float32)
    for kh in range(KH):
        for kw in range(KW):
            xs = jax.lax.slice(
                xp, (0, kh, kw, 0), (N, kh + H, kw + W, C))
            acc = acc + jnp.einsum(
                "nhwc,co->nhwo", xs, w[kh, kw],
                preferred_element_type=jnp.float32)
    return acc.astype(x.dtype)


def _conv_kernel(x_ref, w_ref, o_ref, acc, *, OH, OW, C, O, KH, KW):
    bn = x_ref.shape[0]
    acc[:] = jnp.zeros_like(acc)
    for kh in range(KH):
        for kw in range(KW):
            xs = x_ref[:, kh:kh + OH, kw:kw + OW, :]
            xm = xs.reshape(bn * OH * OW, C)
            acc[:] += jax.lax.dot_general(
                xm, w_ref[kh, kw], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    o_ref[:] = acc[:].reshape(bn, OH, OW, O).astype(o_ref.dtype)


def pallas_conv(x, w, bn=8):
    N, H, W, C = x.shape
    KH, KW, _, O = w.shape
    ph, pw = KH // 2, KW // 2
    xp = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    HP, WP = H + 2 * ph, W + 2 * pw
    return pl.pallas_call(
        functools.partial(_conv_kernel, OH=H, OW=W, C=C, O=O,
                          KH=KH, KW=KW),
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, HP, WP, C), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((KH, KW, C, O), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, H, W, O), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, H, W, O), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn * H * W, O), jnp.float32)],
    )(xp, w)


def main():
    shapes = [(14, 256), (28, 128), (7, 512)]
    if len(sys.argv) > 1:
        shapes = [shapes[int(sys.argv[1])]]
    N = 256
    for (H, C) in shapes:
        x = jax.random.normal(jax.random.PRNGKey(0), (N, H, H, C),
                              jnp.bfloat16)
        w = (jax.random.normal(jax.random.PRNGKey(1), (3, 3, C, C),
                               jnp.bfloat16) / (3 * C ** 0.5))
        fl = 2 * N * H * H * C * C * 9
        # one-shot probe script: per-call compiles are the point
        ref = jax.jit(xla_conv)(x, w)  # mxlint: disable=retrace-inline-jit
        print(f"-- b{N} {H}x{H} C={C} ({fl/1e9:.0f} GFLOP) --")
        for name, fn in [("xla_conv", xla_conv),
                         ("shifted_gemm", shifted_gemm_conv),
                         ("pallas bn=8", functools.partial(pallas_conv,
                                                          bn=8)),
                         ("pallas bn=16", functools.partial(pallas_conv,
                                                           bn=16))]:
            try:
                got = jax.jit(  # mxlint: disable=retrace-inline-jit
                    lambda x: fn(x, w))(x)
                err = float(jnp.max(jnp.abs(
                    got.astype(jnp.float32) - ref.astype(jnp.float32))))
                t = sustained(lambda x: fn(x, w), x, n=20)
                print(f"  {name:14s}: {fl/t/1e12:6.1f} TF/s "
                      f"({t*1e3:.2f} ms)  err={err:.2e}")
            except Exception as e:
                msg = str(e).split(chr(10))[0][:120]
                print(f"  {name:14s}: FAILED {type(e).__name__}: {msg}")


if __name__ == "__main__":
    main()
