# Makes tools/ importable so `python -m tools.mxlint` (and
# `from tools.microbench import ...`) resolve from the repo root.
