#!/usr/bin/env python
"""Multi-host job launcher (reference ``tools/launch.py``† +
dmlc_tracker).

The reference spawns a ps-lite scheduler + servers + workers over
ssh/mpi and wires ``DMLC_*`` env.  The TPU-native job is SPMD: every
host runs the SAME program and ``jax.distributed.initialize`` forms
the mesh, so the launcher's job collapses to exporting the
coordination env and execing one process per host (SURVEY §5.8).

Local simulation of an N-process cluster (the reference's
``--launcher local`` trick, SURVEY §4.5):

  python tools/launch.py -n 4 --launcher local python train.py

Real multi-host: run on each host with --host-rank set (or under your
scheduler, e.g. one task per host):

  python tools/launch.py -n 16 --coordinator host0:1234 \
      --host-rank $RANK python train.py
"""
import argparse
import os
import subprocess
import sys


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-n", "--num-processes", type=int, required=True,
                   help="total hosts (processes) in the job")
    p.add_argument("--coordinator", default="127.0.0.1:49375",
                   help="coordinator address host:port")
    p.add_argument("--host-rank", type=int, default=None)
    p.add_argument("--launcher", choices=("local", "env", "ssh"),
                   default="env")
    p.add_argument("-H", "--hostfile", default=None,
                   help="one host per line (ssh launcher); rank = "
                        "line order, coordinator = first host")
    p.add_argument("--ssh-user", default=None)
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args()
    if not args.command:
        p.error("no command given")

    base_env = dict(os.environ)
    base_env["MXTPU_COORDINATOR"] = args.coordinator
    base_env["MXTPU_NUM_PROCESSES"] = str(args.num_processes)
    # jax.distributed.initialize() reads these directly
    base_env["JAX_COORDINATOR_ADDRESS"] = args.coordinator
    base_env["JAX_NUM_PROCESSES"] = str(args.num_processes)

    if args.launcher == "local":
        # N local processes, each pretending to be one host — the
        # distributed test harness (no real multi-chip needed)
        procs = []
        for rank in range(args.num_processes):
            env = dict(base_env)
            env["JAX_PROCESS_ID"] = str(rank)
            env["MXTPU_PROCESS_ID"] = str(rank)
            procs.append(subprocess.Popen(args.command, env=env))
        rc = 0
        for proc in procs:
            rc |= proc.wait()
        sys.exit(rc)

    if args.launcher == "ssh":
        # dmlc_tracker's ssh launcher†, SPMD-shaped: ssh to every host
        # in the hostfile, export the coordination env, run the SAME
        # command; rank = hostfile order, coordinator = host 0
        if not args.hostfile:
            p.error("--hostfile required with --launcher ssh")
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f
                     if h.strip() and not h.strip().startswith("#")]
        if len(hosts) < args.num_processes:
            p.error(f"hostfile has {len(hosts)} hosts, need "
                    f"{args.num_processes}")
        hosts = hosts[:args.num_processes]
        coord = args.coordinator
        if coord.startswith("127.0.0.1"):
            coord = hosts[0] + ":" + coord.split(":")[1]
        import shlex
        procs = []
        for rank, host in enumerate(hosts):
            exports = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in (
                    ("JAX_COORDINATOR_ADDRESS", coord),
                    ("JAX_NUM_PROCESSES", str(args.num_processes)),
                    ("JAX_PROCESS_ID", str(rank)),
                    ("MXTPU_COORDINATOR", coord),
                    ("MXTPU_NUM_PROCESSES", str(args.num_processes)),
                    ("MXTPU_PROCESS_ID", str(rank))))
            remote = f"cd {shlex.quote(os.getcwd())} && env " \
                f"{exports} " + " ".join(
                    shlex.quote(c) for c in args.command)
            target = host if args.ssh_user is None else \
                f"{args.ssh_user}@{host}"
            procs.append(subprocess.Popen(
                ["ssh", "-o", "StrictHostKeyChecking=no", target,
                 remote]))
        rc = 0
        for proc in procs:
            rc |= proc.wait()
        sys.exit(rc)

    rank = args.host_rank
    if rank is None:
        p.error("--host-rank required with --launcher env (or use "
                "--launcher local / ssh)")
    base_env["JAX_PROCESS_ID"] = str(rank)
    base_env["MXTPU_PROCESS_ID"] = str(rank)
    os.execvpe(args.command[0], args.command, base_env)


if __name__ == "__main__":
    main()
