#!/usr/bin/env python
"""Collective-bandwidth harness (reference ``tools/bandwidth/
measure.py``†, rebuilt for XLA collectives): times in-graph psum /
all_gather / reduce_scatter / ppermute over the device mesh and prints
GB/s per collective — the ICI/DCN story the kvstore path rides.

Single real chip: trivially fast (no transport).  Multi-device: run
under the virtual CPU mesh or on a slice:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  JAX_PLATFORMS=cpu python tools/bandwidth/measure.py --mb 64
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mb", type=float, default=64.0,
                   help="payload megabytes per device")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--dtype", default="float32")
    args = p.parse_args()

    import jax

    # the axon sitecustomize pins the TPU; honour JAX_PLATFORMS anyway
    # (env alone is ignored once the plugin registers)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms",
                          os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))
    elems = int(args.mb * 1e6 / jnp.dtype(args.dtype).itemsize)
    elems -= elems % max(n, 1)
    x = jnp.ones((elems,), args.dtype)
    x = jax.device_put(x, NamedSharding(mesh, P("x")))
    nbytes = elems * jnp.dtype(args.dtype).itemsize

    def timed(fn, x):
        f = jax.jit(fn)
        out = f(x)
        jax.block_until_ready(out)
        float(jnp.sum(out))  # force a host sync even on async runtimes
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = f(x)
        float(jnp.sum(out))
        return (time.perf_counter() - t0) / args.iters

    shard_map = jax.shard_map

    def _psum(v):
        return jax.lax.psum(v, "x")

    def _ag(v):
        return jax.lax.all_gather(v, "x", tiled=True)

    def _ppermute(v):
        return jax.lax.ppermute(
            v, "x", [(i, (i + 1) % n) for i in range(n)])

    print(f"devices: {n} x {devs[0].device_kind}; payload "
          f"{nbytes / 1e6:.0f} MB total")
    for name, coll, spec_out in (
            ("psum (all-reduce)", _psum, P("x")),
            ("all_gather", _ag, P()),
            ("ppermute (ring hop)", _ppermute, P("x"))):
        fn = shard_map(coll, mesh=mesh, in_specs=P("x"),
                       out_specs=spec_out, check_vma=False)
        dt = timed(fn, x)
        # algorithm bytes: all-reduce moves 2(n-1)/n of payload per
        # device; gather/permute move the payload once
        factor = 2 * (n - 1) / max(n, 1) if "psum" in name else 1.0
        gbps = nbytes * factor / dt / 1e9
        print(f"{name:22s}: {dt * 1e3:8.2f} ms  ->  "
              f"{gbps:7.2f} GB/s (bus)")


if __name__ == "__main__":
    main()
