"""hlocheck target registry: the named model x config programs whose
compiled HLO is pinned by a lockfile in ``contracts/``.

Every target is a zero-argument builder returning
``{program_name: (hlo_text, mem_stats_dict_or_None)}``.  Builders run
on the CPU backend with the 8-virtual-device mesh the CLI pins
(``__main__`` sets ``JAX_PLATFORMS``/``XLA_FLAGS`` before jax loads),
so a lockfile regenerated on any box matches CI.

The models are *tiny stand-ins* for the bench configurations — same
code paths (ZeRO shard_map step, batched optimizer, fused epilogues,
serving bucket ladder), scaled so the whole ``--check`` sweep lowers
in a couple of minutes on CPU.  Contract properties (which
collectives, dtype policy, zero host transfers) are scale-invariant;
budget properties (fusion counts, peak bytes) pin the tiny config's
numbers, which still move when the underlying compilation strategy
changes — that is the regression-tripwire the lockfile exists for.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

Artifact = Tuple[str, Optional[dict]]
Builder = Callable[[], Dict[str, Artifact]]

TARGETS: Dict[str, Builder] = {}

# mxprec rides the same six targets at the PRE-optimization level:
# each prec builder returns ``{"programs": {prog: pre_opt_hlo_text},
# "optimizer": optimizer_or_None, "param_sigs": sigs_or_None}``.
# Model/step construction is shared with the hlocheck builders above
# so the two registries can never drift apart.
PrecBuilder = Callable[[], Dict]

PREC_TARGETS: Dict[str, PrecBuilder] = {}

# mxmem (ISSUE 20) rides the same fixtures a third time: each mem
# builder returns a memflow *record* (programs + byte attributions +
# the zero/kv oracles) that ``tools.mxmem`` turns into the committed
# ``contracts/mem/<target>.json`` ledger.
MemBuilder = Callable[[], Dict]

MEM_TARGETS: Dict[str, MemBuilder] = {}


def register_target(name: str):
    def deco(fn: Builder) -> Builder:
        TARGETS[name] = fn
        return fn
    return deco


def register_prec(name: str):
    def deco(fn: PrecBuilder) -> PrecBuilder:
        PREC_TARGETS[name] = fn
        return fn
    return deco


def register_mem(name: str):
    def deco(fn: MemBuilder) -> MemBuilder:
        MEM_TARGETS[name] = fn
        return fn
    return deco


def build(name: str) -> Dict[str, dict]:
    """Summaries (contract-shaped) for every program of ``name``."""
    from mxtpu.analysis import summarize
    artifacts = TARGETS[name]()
    return {prog: summarize(text, mem)
            for prog, (text, mem) in sorted(artifacts.items())}


def build_prec(name: str) -> Dict:
    """Pre-optimization dtype-flow facts for ``name`` (mxprec's
    substrate) — lowering only, never a compile, so the sweep stays
    cheap on CPU."""
    return PREC_TARGETS[name]()


def build_mem(name: str) -> Dict:
    """Memory record for ``name`` (mxmem's substrate): compiled
    ``memory_analysis()`` stats plus the byte attributions and
    geometry oracles ``mxtpu.analysis.memflow`` decomposes into the
    committed ledger."""
    return MEM_TARGETS[name]()


# ----------------------------------------------------------------------
# shared pieces
# ----------------------------------------------------------------------
_VOCAB = 512


def _mlm_loss():
    from mxtpu.gluon import loss as gloss
    ce = gloss.SoftmaxCrossEntropyLoss()

    def loss(pred, y):
        return ce(pred.reshape((-1, _VOCAB)), y.reshape((-1,)))
    return loss


def _train_step_artifact(step, x, y) -> Artifact:
    return step.hlo_text(x, y), step.memory_analysis(x, y)


def _prec_train(step, x, y) -> Dict:
    return {"programs": {"train_step": step.lowered_hlo_text(x, y)},
            "optimizer": step.optimizer,
            "param_sigs": step.param_sigs(x, y)}


def _bert_parts(zero: int, amp: bool = False):
    import jax
    from mxtpu import nd, parallel
    from mxtpu.models.transformer import BERTModel
    net = BERTModel(_VOCAB, 64, 128, 2, 2, max_length=32,
                    dropout=0.1)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, _VOCAB, (8, 16)).astype(np.float32))
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("dp",))
    step = parallel.build_train_step(
        net, _mlm_loss(), "adam", {"learning_rate": 1e-3},
        mesh=mesh, cast_batch=False, zero=zero, amp=amp or None)
    return step, x, x


@register_target("bert_replicated")
def bert_replicated() -> Dict[str, Artifact]:
    """Tiny BERT, dp8 data-parallel with replicated optimizer states
    (the pre-ZeRO path: gradient all-reduce)."""
    return {"train_step": _train_step_artifact(*_bert_parts(zero=0))}


@register_target("bert_zero")
def bert_zero() -> Dict[str, Artifact]:
    """Tiny BERT, dp8 ZeRO-1: reduce-scatter + all-gather per bucket,
    no big all-reduce — the comm signature tests/test_zero.py pins."""
    return {"train_step": _train_step_artifact(*_bert_parts(zero=1))}


@register_target("bert_zero_amp")
def bert_zero_amp() -> Dict[str, Artifact]:
    """``bert_zero`` with ``amp=True`` — pins the AMP comm payoff:
    the same reduce-scatter count as the f32 contract but the
    exchanged buckets ride bf16 (collective bytes ~ half of
    ``bert_zero``'s), upcast to f32 immediately after the exchange.

    The payoff is pinned on the ``train_step_as_written`` program
    (the pre-optimization lowering): the CPU backend's
    float-normalization pass rewrites bf16 collectives back to f32
    in the compiled text, so only the as-written level carries the
    dtype the wire sees on a real accelerator."""
    step, x, y = _bert_parts(zero=1, amp=True)
    return {"train_step": _train_step_artifact(step, x, y),
            "train_step_as_written":
                (step.lowered_hlo_text(x, y), None)}


@register_prec("bert_replicated")
def bert_replicated_prec() -> Dict:
    return _prec_train(*_bert_parts(zero=0))


@register_prec("bert_zero")
def bert_zero_prec() -> Dict:
    return _prec_train(*_bert_parts(zero=1))


@register_prec("bert_replicated_amp")
def bert_replicated_amp_prec() -> Dict:
    return _prec_train(*_bert_parts(zero=0, amp=True))


@register_prec("bert_zero_amp")
def bert_zero_amp_prec() -> Dict:
    return _prec_train(*_bert_parts(zero=1, amp=True))


def _transformer_parts(amp: bool = False):
    from mxtpu import nd, parallel
    from mxtpu.gluon.block import HybridBlock
    from mxtpu.models.transformer import TransformerModel

    class MTWrap(HybridBlock):
        def __init__(self, split, **kw):
            super().__init__(**kw)
            self._split = split
            self.model = TransformerModel(
                _VOCAB, units=64, hidden_size=128, num_layers=2,
                num_heads=2, max_length=64, dropout=0.1)

        def hybrid_forward(self, F, x):
            src = F.slice_axis(x, axis=1, begin=0, end=self._split)
            tgt = F.slice_axis(x, axis=1, begin=self._split,
                               end=None)
            return self.model(src, tgt)

    net = MTWrap(16)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, _VOCAB, (4, 32)).astype(np.float32))
    y = nd.array(rng.randint(0, _VOCAB, (4, 16)).astype(np.float32))
    step = parallel.build_train_step(
        net, _mlm_loss(), "adam", {"learning_rate": 1e-4},
        cast_batch=False, amp=amp or None)
    return step, x, y


@register_target("transformer")
def transformer() -> Dict[str, Artifact]:
    """Tiny encoder-decoder transformer (the bench `transformer` row's
    shape: src|tgt concatenated on the time axis)."""
    return {"train_step": _train_step_artifact(*_transformer_parts())}


@register_prec("transformer")
def transformer_prec() -> Dict:
    return _prec_train(*_transformer_parts())


@register_prec("transformer_amp")
def transformer_amp_prec() -> Dict:
    return _prec_train(*_transformer_parts(amp=True))


def _resnet_parts(amp: bool = False):
    from mxtpu import nd, parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon.model_zoo import vision
    net = vision.get_resnet(1, 18, thumbnail=True, classes=10)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 3, 32, 32).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (8,)).astype(np.float32))
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, amp=amp or None)
    return step, x, y


@register_target("resnet18")
def resnet18() -> Dict[str, Artifact]:
    """resnet18 thumbnail (BN-heavy conv net — the fused-BN bracket
    watchpoint of ROADMAP item 3)."""
    return {"train_step": _train_step_artifact(*_resnet_parts())}


@register_prec("resnet18")
def resnet18_prec() -> Dict:
    return _prec_train(*_resnet_parts())


@register_prec("resnet18_amp")
def resnet18_amp_prec() -> Dict:
    return _prec_train(*_resnet_parts(amp=True))


def _quant_calib_batches(n: int = 4):
    """Seeded representative batches for INT8 calibration — fixed
    token ids, so the calibrated thresholds (and therefore the
    quantized fixture's HLO, which bakes them in as constants) are
    byte-reproducible on any box."""
    rng = np.random.RandomState(0)
    return [{"data": rng.randint(0, _VOCAB, (4, 32))
             .astype(np.float32)} for _ in range(n)]


def _serving_runner(amp: bool = False, quant: bool = False):
    import os
    import tempfile
    from mxtpu import nd
    from mxtpu.models.transformer import BERTModel
    from mxtpu.serving import ModelRunner
    if quant:
        # float serving programs are weight-independent (params are
        # runtime inputs), but the quantized trace bakes the
        # CALIBRATED activation thresholds in as constants — and those
        # depend on the weights, so the int8 fixture pins the global
        # init stream
        from mxtpu.ndarray import random as _mxrnd
        _mxrnd.seed(0)
    net = BERTModel(_VOCAB, 64, 128, 2, 2, max_length=32,
                    dropout=0.0)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    net(nd.array(rng.randint(0, _VOCAB, (1, 32))
                 .astype(np.float32)))
    d = tempfile.mkdtemp(prefix="hlocheck_bert_")
    sym_file, param_file = net.export(os.path.join(d, "bert"))
    runner = ModelRunner.from_export(
        sym_file, param_file, input_specs={"data": (None,)},
        seq_buckets=[16, 32], max_batch_size=4,
        amp=amp or None, quant=quant or None)
    if quant:
        # explicit mode (not the env knob): the committed contracts
        # pin the entropy-calibrated thresholds
        runner.calibrate(_quant_calib_batches(), mode="entropy")
    return runner


@register_target("serving_bert")
def serving_bert() -> Dict[str, Artifact]:
    """Serving bucket ladder: tiny exported BERT through
    ModelRunner's AOT (batch, seq) executables — every bucket gets
    its own contract entry."""
    runner = _serving_runner()
    runner.warmup()
    out: Dict[str, Artifact] = {}
    for bucket in runner.buckets():
        batch, seq = bucket
        text, mem = runner.program_artifact(bucket)
        out[f"bucket_b{batch}_s{seq}"] = (text, mem)
    return out


@register_prec("serving_bert")
def serving_bert_prec() -> Dict:
    # lowering only — no warmup/compile, so the prec sweep stays fast
    runner = _serving_runner()
    programs = {}
    for bucket in runner.buckets():
        batch, seq = bucket
        programs[f"bucket_b{batch}_s{seq}"] = \
            runner.lowered_program_text(bucket)
    return {"programs": programs, "optimizer": None,
            "param_sigs": None}


@register_prec("serving_bert_amp")
def serving_bert_amp_prec() -> Dict:
    runner = _serving_runner(amp=True)
    programs = {}
    for bucket in runner.buckets():
        batch, seq = bucket
        programs[f"bucket_b{batch}_s{seq}"] = \
            runner.lowered_program_text(bucket)
    return {"programs": programs, "optimizer": None,
            "param_sigs": None}


@register_target("serving_bert_int8")
def serving_bert_int8() -> Dict[str, Artifact]:
    """The serving ladder calibrated + quantized (mxtpu.quant): every
    bucket's compiled program carries the policy's contractions as
    s8xs8 GEMMs accumulating in i32, plus one ``_as_written``
    (pre-optimization) entry — the level the prec ledger and the
    dtypeflow int8 hazard rules read, immune to any CPU-backend
    normalization of the compiled text."""
    runner = _serving_runner(quant=True)
    runner.warmup()
    out: Dict[str, Artifact] = {}
    for bucket in runner.buckets():
        batch, seq = bucket
        text, mem = runner.program_artifact(bucket)
        out[f"bucket_b{batch}_s{seq}"] = (text, mem)
    top = max(runner.buckets())
    out[f"bucket_b{top[0]}_s{top[1]}_as_written"] = \
        (runner.lowered_program_text(top), None)
    return out


@register_prec("serving_bert_int8")
def serving_bert_int8_prec() -> Dict:
    runner = _serving_runner(quant=True)
    programs = {}
    for bucket in runner.buckets():
        batch, seq = bucket
        programs[f"bucket_b{batch}_s{seq}"] = \
            runner.lowered_program_text(bucket)
    return {"programs": programs, "optimizer": None,
            "param_sigs": None}


def _generate_runner(amp: bool = False):
    """Tiny causal BERT through the incremental-decode path (ISSUE
    19): hybrid-forward with (step, cache) extra inputs exported, then
    a GenerateRunner over a 2-lane bucket-paged KV cache.  The decode
    contract this pins: the per-lane ``dynamic-update-slice`` KV
    write + masked cached attention, single fused device program, no
    host round-trips inside the step."""
    import os
    import tempfile
    from mxtpu import nd
    from mxtpu.models.transformer import BERTModel
    from mxtpu.serving import GenerateRunner
    net = BERTModel(_VOCAB, 64, 128, 2, 2, max_length=32,
                    dropout=0.0, use_token_type=False, causal=True)
    net.initialize(init="xavier")
    net.hybridize()
    rng = np.random.RandomState(0)
    toks = nd.array(rng.randint(0, _VOCAB, (1, 8))
                    .astype(np.float32))
    step = nd.array(np.zeros((1,), np.float32))
    cache = nd.array(np.zeros(net.kv_cache_spec(1), np.float32))
    net(toks, step, cache)   # trace the incremental signature
    d = tempfile.mkdtemp(prefix="hlocheck_gen_")
    sym_file, param_file = net.export(os.path.join(d, "genbert"))
    return GenerateRunner.from_export(
        sym_file, param_file, net.kv_cache_spec(2, 32),
        prompt_buckets=(16, 32), cache=None, amp=amp or None)


@register_target("generate_decode")
def generate_decode() -> Dict[str, Artifact]:
    """Generation ladder: every (batch-rung x prompt-bucket) prefill
    executable plus THE decode-step executable.  The decode entry is
    the per-token serving contract — its compiled text must carry the
    slot-table ``dynamic-update-slice`` KV writes (one per layer per
    k/v) and no host transfer."""
    runner = _generate_runner()
    runner.warmup()
    out: Dict[str, Artifact] = {}
    for bucket in runner.buckets():
        kind, shp = bucket
        text, mem = runner.program_artifact(bucket)
        if kind == "decode":
            out["decode_step"] = (text, mem)
        else:
            out[f"prefill_b{shp[0]}_s{shp[1]}"] = (text, mem)
    # pre-optimization view of the decode step: the level the mxprec
    # ledger and dtypeflow hazard rules read (update-slice signature
    # survives backend normalization here)
    out["decode_step_as_written"] = \
        (runner.lowered_program_text(runner.default_bucket()), None)
    return out


@register_prec("generate_decode")
def generate_decode_prec() -> Dict:
    # lowering only — no compile, the sweep stays fast on CPU
    runner = _generate_runner()
    programs = {}
    for bucket in runner.buckets():
        kind, shp = bucket
        name = "decode_step" if kind == "decode" \
            else f"prefill_b{shp[0]}_s{shp[1]}"
        programs[name] = runner.lowered_program_text(bucket)
    return {"programs": programs, "optimizer": None,
            "param_sigs": None}


@register_prec("generate_decode_amp")
def generate_decode_amp_prec() -> Dict:
    """bf16 decode with f32 accumulation: the amp ledger must show
    zero hazards — attention scores and softmax stay f32 (ISSUE 16
    layout contracts) while the matmul operands ride bf16."""
    runner = _generate_runner(amp=True)
    programs = {}
    for bucket in runner.buckets():
        kind, shp = bucket
        name = "decode_step" if kind == "decode" \
            else f"prefill_b{shp[0]}_s{shp[1]}"
        programs[name] = runner.lowered_program_text(bucket)
    return {"programs": programs, "optimizer": None,
            "param_sigs": None}


class _QuantEvidenceCollector:
    """MinMax activation collector that ALSO records the per-channel
    |w| scales the quantized trace computes in-graph — the policy's
    machine evidence that every quantized weight has a usable
    per-output-channel scale (``observe_weight`` is the optional hook
    ``mxtpu.quant.wrap_op`` probes for)."""

    def __init__(self):
        from mxtpu import quant as Q
        self._inner = Q.MinMaxCollector()
        self.weights: Dict[str, list] = {}

    mode = "minmax"

    def observe(self, key, value):
        self._inner.observe(key, value)

    def observe_weight(self, key, value):
        from mxtpu import quant as Q
        arr = np.asarray(value, np.float32)
        red = tuple(range(1, arr.ndim))
        t = np.abs(arr).max(axis=red) if arr.ndim > 1 else np.abs(arr)
        self.weights.setdefault(
            key, [Q._round6(float(v))
                  for v in np.ravel(np.maximum(t, 1e-12))])

    def thresholds(self):
        return self._inner.thresholds()


def quant_calibration_evidence() -> Dict:
    """The ``calibration`` section of ``contracts/quant_policy.json``
    (written by ``python -m tools.mxprec --quant --update``):
    deterministic seeded evidence from the quantized serving fixture —
    both collectors' per-tensor activation thresholds, every quantized
    parameter's per-channel weight scales, and the s8xs8->s32
    contraction census of the quantized bucket ladder."""
    from mxtpu.analysis import dtypeflow
    batches = _quant_calib_batches()
    runner = _serving_runner(quant=True)  # entropy-calibrated
    evidence = _QuantEvidenceCollector()
    minmax = runner.calibrate(batches, collector=evidence)
    # re-arm with the entropy table LAST so the census below matches
    # the committed serving_bert_int8 contracts (also entropy)
    entropy = runner.calibrate(batches, mode="entropy")
    census = {}
    for bucket in runner.buckets():
        batch, seq = bucket
        census[f"bucket_b{batch}_s{seq}"] = \
            dtypeflow.int8_contraction_census(
                runner.lowered_program_text(bucket))
    return {
        "fixture": "serving_bert fixture, quant=True: mxtpu.random "
                   "seed 0 init, 4 seeded token batches "
                   "(RandomState(0), shape (4, 32))",
        "num_batches": len(batches),
        "activation_thresholds": {"entropy": entropy,
                                  "minmax": minmax},
        "weight_scales": evidence.weights,
        "int8_contractions": census,
    }


def _selftest_parts():
    import jax.numpy as jnp

    def f(a, b):
        w, v = jnp.linalg.eigh(a.T @ a)
        return ((v * w).sum() + (a @ b).sum())

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(8, 8).astype(np.float32))
    b = jnp.asarray(rng.randn(8, 4).astype(np.float32))
    return f, a, b


@register_target("selftest")
def selftest() -> Dict[str, Artifact]:
    """A deliberately small program that exercises every summary
    family in milliseconds: a lapack custom call (the CPU backend's
    genuine custom-call + layout-bracket specimen), fusions, and a
    clean f32 dtype story.  Keeps one end-to-end CLI round trip
    cheap enough for tier-1."""
    from mxtpu.analysis import compiled_artifact
    f, a, b = _selftest_parts()
    text, mem = compiled_artifact(f, a, b)
    return {"eigh_matmul": (text, mem)}


@register_prec("selftest")
def selftest_prec() -> Dict:
    from mxtpu.analysis import lowered_text
    f, a, b = _selftest_parts()
    return {"programs": {"eigh_matmul": lowered_text(f, a, b)},
            "optimizer": None, "param_sigs": None}


@register_prec("selftest_amp")
def selftest_amp_prec() -> Dict:
    """The selftest math with its contraction routed through the nd
    op registry under an autocast scope — the smallest ledgered
    specimen of the policy in action (bf16 dot operands, f32
    accumulation, eigh/transcendental chain untouched)."""
    import jax.numpy as jnp
    from mxtpu import amp, nd
    from mxtpu.analysis import lowered_text
    from mxtpu.ndarray import NDArray

    def f(a, b):
        w, v = jnp.linalg.eigh(a.T @ a)
        with amp.autocast():
            prod = nd.dot(NDArray(a, None, _placed=True),
                          NDArray(b, None, _placed=True))
        return (v * w).sum() + prod._data.sum()

    _, a, b = _selftest_parts()
    return {"programs": {"eigh_matmul": lowered_text(f, a, b)},
            "optimizer": None, "param_sigs": None}


# ----------------------------------------------------------------------
# mxmem records (ISSUE 20) — same fixtures, byte-attribution view
# ----------------------------------------------------------------------
@register_mem("bert_replicated")
def bert_replicated_mem() -> Dict:
    from mxtpu.analysis import memflow
    step, x, y = _bert_parts(zero=0)
    return memflow.train_step_record(step, x, y, "bert_replicated")


@register_mem("bert_zero")
def bert_zero_mem() -> Dict:
    """The ZeRO-1 ledger: measured per-device optimizer-state bytes
    against the ``plan_zero_buckets`` shard geometry — the committed
    proof of the dp8 opt-state saving (BASELINE.md r7's 2784.6 ->
    348.1 MiB/device at bench scale)."""
    from mxtpu.analysis import memflow
    step, x, y = _bert_parts(zero=1)
    return memflow.train_step_record(step, x, y, "bert_zero",
                                     zero_expected=True)


@register_mem("bert_zero_amp")
def bert_zero_amp_mem() -> Dict:
    from mxtpu.analysis import memflow
    step, x, y = _bert_parts(zero=1, amp=True)
    return memflow.train_step_record(step, x, y, "bert_zero_amp",
                                     zero_expected=True)


@register_mem("transformer")
def transformer_mem() -> Dict:
    from mxtpu.analysis import memflow
    step, x, y = _transformer_parts()
    return memflow.train_step_record(step, x, y, "transformer")


@register_mem("resnet18")
def resnet18_mem() -> Dict:
    from mxtpu.analysis import memflow
    step, x, y = _resnet_parts()
    return memflow.train_step_record(step, x, y, "resnet18")


@register_mem("serving_bert")
def serving_bert_mem() -> Dict:
    from mxtpu.analysis import memflow
    return memflow.runner_record(_serving_runner(), "serving_bert")


@register_mem("serving_bert_int8")
def serving_bert_int8_mem() -> Dict:
    from mxtpu.analysis import memflow
    return memflow.runner_record(_serving_runner(quant=True),
                                 "serving_bert_int8")


@register_mem("generate_decode")
def generate_decode_mem() -> Dict:
    """The KV-table ledger: per-program decomposition with the slot
    table attributed, plus the kv section whose ``table_bytes ==
    expected_bytes`` equality (declared ``kv_cache_spec`` geometry +
    1 scratch slot) is the committed anti-overcommit proof."""
    from mxtpu.analysis import memflow
    return memflow.generate_record(_generate_runner(),
                                   "generate_decode")


@register_mem("selftest")
def selftest_mem() -> Dict:
    """The cheap end-to-end CLI specimen (mirrors hlocheck/mxprec):
    one compiled program, no params/opt attribution — pure
    activations+temps decomposition."""
    from mxtpu.analysis import compiled_artifact, memflow
    f, a, b = _selftest_parts()
    text, mem = compiled_artifact(f, a, b)
    return {"target": "selftest",
            "programs": {"eigh_matmul": {
                "mem": mem or {},
                "collective_scratch":
                    memflow.collective_scratch_bytes(text)}}}
