"""hlocheck CLI.

Exit codes (the contract tests/test_analysis.py pins, mirroring
mxlint):

* 0 — every checked target matches its lockfile;
* 1 — contract violations (or missing lockfile in --check mode);
* 2 — usage / internal error (unknown target, unreadable contract).

``--update`` rebuilds the named targets (default: all) and rewrites
``contracts/<target>.json``; the default mode re-lowers and checks.
Programs are always lowered on the CPU backend with the same
8-virtual-device topology the test suite uses, so lockfiles are
reproducible on any box regardless of what accelerator the caller's
environment points at.
"""
from __future__ import annotations

import os

# pin the lowering environment BEFORE jax (imported via mxtpu) loads:
# contracts are CPU-backend artifacts by definition
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import argparse   # noqa: E402
import json       # noqa: E402
import sys        # noqa: E402
import time       # noqa: E402
from pathlib import Path  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.hlocheck",
        description="Static analysis over compiled XLA programs "
                    "against committed contract lockfiles "
                    "(collectives, custom-call brackets, dtype "
                    "policy, budgets, host transfers).")
    ap.add_argument("targets", nargs="*",
                    help="targets to process (default: every "
                         "committed contract for --check, every "
                         "registered target for --update)")
    ap.add_argument("--check", action="store_true",
                    help="counts-only output; exit 1 on violations "
                         "(CI mode — this is also the default "
                         "behaviour)")
    ap.add_argument("--update", action="store_true",
                    help="regenerate lockfiles for the named "
                         "targets and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit results as JSON")
    ap.add_argument("--list", action="store_true",
                    help="list registered targets and exit")
    ap.add_argument("--contracts-dir", type=Path, default=None,
                    help="lockfile directory (default: contracts/)")
    args = ap.parse_args(argv)

    from mxtpu.analysis import contracts as C
    from . import targets as T

    directory = args.contracts_dir or C.CONTRACTS_DIR

    if args.list:
        for name in sorted(T.TARGETS):
            state = "contract" if C.contract_path(
                name, directory).exists() else "NO CONTRACT"
            print(f"{name:20s} [{state}]  "
                  f"{(T.TARGETS[name].__doc__ or '').strip().splitlines()[0]}")
        return 0

    if args.targets:
        unknown = [t for t in args.targets if t not in T.TARGETS]
        if unknown:
            print(f"hlocheck: unknown target(s): "
                  f"{', '.join(unknown)} (see --list)",
                  file=sys.stderr)
            return 2
        names = list(args.targets)
    elif args.update:
        names = sorted(T.TARGETS)
    else:
        # check everything that has a committed lockfile AND is still
        # a registered target; a contract whose target vanished is an
        # error, not silence.  contracts/ is shared with mxrace
        # (lockorder.json, checked by `python -m tools.mxrace`),
        # mxprec (amp_policy.json + quant_policy.json + prec/, checked
        # by `python -m tools.mxprec`), and mxmem (mem/ — the memory
        # ledgers + budgets.json, checked by `python -m tools.mxmem`);
        # the glob below only sees top-level files, so the prec/ and
        # mem/ subdirectories are naturally out of scope here.
        foreign = {"lockorder", "amp_policy", "quant_policy"}
        names = sorted(p.stem for p in directory.glob("*.json")
                       if p.stem not in foreign)
        orphans = [n for n in names if n not in T.TARGETS]
        if orphans:
            print(f"hlocheck: contract(s) without a registered "
                  f"target: {', '.join(orphans)}", file=sys.stderr)
            return 2
        if not names:
            print(f"hlocheck: no contracts in {directory} — run "
                  f"--update first", file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    all_violations, all_notices = [], []
    results = {}
    for name in names:
        t1 = time.perf_counter()
        observed = T.build(name)
        dt = time.perf_counter() - t1
        if args.update:
            path = C.save_contract(
                C.make_contract(name, observed), directory)
            results[name] = {"updated": str(path),
                             "programs": sorted(observed),
                             "seconds": round(dt, 1)}
            if not args.as_json:
                print(f"hlocheck: wrote {path} "
                      f"({len(observed)} program(s), {dt:.1f}s)")
            continue
        try:
            contract = C.load_contract(name, directory)
        except FileNotFoundError:
            all_violations.append(C.Violation(
                "contract", name, "*",
                f"no lockfile {C.contract_path(name, directory)} — "
                f"run --update {name}"))
            continue
        except (ValueError, OSError) as e:
            print(f"hlocheck: cannot read contract for {name}: {e}",
                  file=sys.stderr)
            return 2
        violations, notices = C.check_contract(contract, observed)
        all_violations += violations
        all_notices += notices
        results[name] = {
            "violations": [v.as_json() for v in violations],
            "notices": notices, "seconds": round(dt, 1)}
        if not args.as_json and not args.check:
            print(f"hlocheck: {name}: {len(violations)} violation(s)"
                  f" ({dt:.1f}s)")

    dt = time.perf_counter() - t0
    if args.update:
        if args.as_json:
            print(json.dumps(results, indent=1))
        return 0
    if args.as_json:
        print(json.dumps({"results": results,
                          "seconds": round(dt, 1)}, indent=1))
    else:
        for n in all_notices:
            print(f"  note: {n}")
        for v in all_violations:
            print("  " + v.format())
        print(f"hlocheck: {len(names)} target(s), "
              f"{len(all_violations)} violation(s), "
              f"{len(all_notices)} notice(s) ({dt:.1f}s)")
    return 1 if all_violations else 0


if __name__ == "__main__":
    sys.exit(main())
