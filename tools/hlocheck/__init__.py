"""tools/hlocheck — compiled-program contracts (ISSUE 6).

``python -m tools.hlocheck [--check|--update|--json] [targets...]``
lowers the registered model x config targets on the CPU backend,
summarizes each compiled program with ``mxtpu.analysis``, and
compares (or rewrites) the committed lockfiles in ``contracts/``.
Same 0/1/2 exit contract as ``tools/mxlint``.
"""
