"""Flash-attention vs O(T^2) fallback on the real chip (VERDICT r4
item 4: the long-context story needs a recorded perf number).

Honest methodology (tools/microbench.py): fwd+bwd chained through a
real data dependence inside one program; j applications per iteration
amortize the per-iteration floor.

Run: python tools/bench_flash.py [T ...]   (default 512 2048 4096)
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp

try:
    from tools.microbench import sustained
except ImportError:
    from microbench import sustained

from mxtpu.kernels.flash_attention import (attention_reference,
                                           flash_attention)


def fwdbwd_chain(attn, q, k, v, j=4):
    """j fused attention fwd+bwd per iteration, dq folded back into q."""
    def step(q):
        for _ in range(j):
            def loss(q_):
                return jnp.sum(attn(q_, k, v).astype(jnp.float32) ** 2)
            g = jax.grad(loss)(q)
            q = q + g.astype(q.dtype) * 1e-6
        return q
    return step


def run(T, B=4, H=16, D=64, j=4):
    key = jax.random.PRNGKey(0)
    shape = (B, H, T, D)
    q = jax.random.normal(key, shape, jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), shape, jnp.bfloat16)
    # attention fwd+bwd flops ~= 3 * (4*T^2*D) per (b,h) pair.
    # CAUSAL convention: the flash kernel skips blocks strictly above
    # the diagonal (~T^2/2 executed) while the fallback computes the
    # full masked T^2 — each path is credited the FLOPs it actually
    # executes, so the TF/s columns are per-path utilization and NOT
    # directly comparable; compare times/speedup instead (r4 review)
    fl_full = 3 * 4 * T * T * D * B * H * j
    fl = {"flash": fl_full // 2, "fallback": fl_full}
    rows = {}
    for name, attn in [
            ("flash", functools.partial(flash_attention, causal=True)),
            ("fallback", functools.partial(attention_reference,
                                           causal=True))]:
        try:
            t = sustained(fwdbwd_chain(attn, q, k, v, j=j), q, n=8)
            rows[name] = t / j
            print(f"  T={T} {name:8s}: {t/j*1e3:7.2f} ms/fwd+bwd "
                  f"({fl[name]/j/(t/j)/1e12:5.1f} TF/s)")
        except Exception as e:
            print(f"  T={T} {name:8s}: FAILED {type(e).__name__}: "
                  f"{str(e)[:100]}")
    if len(rows) == 2:
        print(f"  T={T} speedup flash/fallback: "
              f"{rows['fallback'] / rows['flash']:.2f}x")


if __name__ == "__main__":
    Ts = [int(a) for a in sys.argv[1:]] or [512, 2048, 4096]
    print("device:", jax.devices()[0])
    for T in Ts:
        run(T)
