"""mxprec — interprocedural dtype-flow analysis with committed
precision ledgers (ISSUE 10: the AMP groundwork pass).

Rides hlocheck's six lowering targets at the PRE-optimization level
(``mxtpu.analysis.lowered_text``): every convert is tracked to its
producing op and source line, precision hazards are classified
(bf16 accumulating reductions, matmuls missing
``preferred_element_type``, f64 creep, fp32 master-weight violations),
and the results are pinned as lockfiles under ``contracts/prec/``
plus the machine-derived ``contracts/amp_policy.json`` op policy the
AMP PR consumes.

``python -m tools.mxprec --check`` is the CI entry point (stage 5 of
``tools/ci_static.py``); the analysis core lives in
``mxtpu.analysis.dtypeflow`` — the ONE dtype analyzer in the tree,
shared with hlocheck's dtype-policy contract family and the
``MXTPU_PREC_AUDIT`` runtime audit.
"""
