"""mxprec core: ledger build/compare, the derived AMP op policy, and
the README dtype table.

Ledger = ``contracts/prec/<target>.json``: per program the cast
provenance (``flows``), float-op census and hazard findings from
:mod:`mxtpu.analysis.dtypeflow`, plus (train targets) the optimizer's
multi-precision facts.  Serialization matches the repo's lockfile
idiom (``json.dumps(..., indent=1, sort_keys=True)``) so two
``--update`` runs are byte-identical.

``contracts/amp_policy.json`` is machine-derived, not hand-curated:
every float-carrying opcode OBSERVED across the six targets' pre-opt
programs is classified allow / deny / fp32_force / inherit with its
per-target evidence counts, and the Pallas kernels' declared
accumulation contracts ride along as ``custom_calls`` — the artifact
the AMP PR consumes.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]

PREC_SUBDIR = "prec"

PREC_BEGIN = "<!-- mxprec:dtypes:begin -->"
PREC_END = "<!-- mxprec:dtypes:end -->"

# ---------------------------------------------------------------------
# opcode policy classes (classification is fixed; the OBSERVED set and
# evidence counts are machine-derived from the lowered targets)
# ---------------------------------------------------------------------
_ALLOW_OPS = {"dot", "convolution"}
_ALLOW_REASON = ("MXU-bound contraction: bf16 inputs are the point of "
                 "AMP, but accumulation must stay f32 "
                 "(preferred_element_type=float32)")

_DENY_OPS = {"exponential", "exponential-minus-one", "log",
             "log-plus-one", "power", "sqrt", "rsqrt", "cbrt",
             "divide", "erf", "erf-inv", "logistic", "tanh", "sine",
             "cosine", "tan", "atan2"}
_DENY_REASON = ("transcendental/division: bf16's 8-bit mantissa "
                "compounds ULP error through these — compute in f32")

_FP32_FORCE_OPS = {"reduce", "reduce-window", "all-reduce",
                   "reduce-scatter"}
_FP32_FORCE_PREFIXES = ("batch-norm",)
_FP32_FORCE_REASON = ("accumulating reduction (incl. cross-replica): "
                      "sum in f32, downcast once at the edge")

_INHERIT_REASON = ("elementwise / data movement / structural: follows "
                   "its input dtype, no accumulation of its own")

# INT8 quantization tier (mxtpu.quant, ISSUE 18).  The allow class is
# the same machine-observed contraction set, one dtype down; the deny
# class carries the AMP transcendental list over VERBATIM — int8 has
# no mantissa for these, they stay bf16/f32.
_QALLOW_REASON = ("MXU-bound contraction: s8xs8 inputs with i32 "
                  "accumulation (preferred_element_type=int32); "
                  "per-channel weight scales, per-tensor activation "
                  "scales")
_QDENY_REASON = ("transcendental/division: stays bf16/f32 — carried "
                 "over verbatim from the AMP deny class (int8 has no "
                 "mantissa for these)")

# quant-policy evidence base: the serving fixture (dot) plus the conv
# net (convolution) — the two contraction families the int8 tier
# rewrites; `--quant` lowers exactly these so the focused mode stays
# much cheaper than a full sweep
QUANT_BASE_TARGETS = ("resnet18", "serving_bert")


def classify_opcode(opcode: str) -> Tuple[str, str]:
    """(section, reason) for one observed float-carrying opcode."""
    if opcode in _ALLOW_OPS:
        return "allow", _ALLOW_REASON
    if opcode in _DENY_OPS:
        return "deny", _DENY_REASON
    if opcode in _FP32_FORCE_OPS or \
            opcode.startswith(_FP32_FORCE_PREFIXES):
        return "fp32_force", _FP32_FORCE_REASON
    return "inherit", _INHERIT_REASON


# ---------------------------------------------------------------------
# paths + lockfile serialization (byte-deterministic)
# ---------------------------------------------------------------------
def prec_dir(directory: Path) -> Path:
    return directory / PREC_SUBDIR


def ledger_path(name: str, directory: Path) -> Path:
    return prec_dir(directory) / f"{name}.json"


def amp_policy_path(directory: Path) -> Path:
    return directory / "amp_policy.json"


def quant_policy_path(directory: Path) -> Path:
    return directory / "quant_policy.json"


def _dump(obj) -> str:
    return json.dumps(obj, indent=1, sort_keys=True) + "\n"


def save_ledger(ledger: Dict, directory: Path) -> Path:
    path = ledger_path(ledger["target"], directory)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(_dump(ledger))
    return path


def load_ledger(name: str, directory: Path) -> Dict:
    return json.loads(ledger_path(name, directory).read_text())


def committed_ledgers(directory: Path) -> Dict[str, Dict]:
    d = prec_dir(directory)
    if not d.is_dir():
        return {}
    return {p.stem: json.loads(p.read_text())
            for p in sorted(d.glob("*.json"))}


# ---------------------------------------------------------------------
# building
# ---------------------------------------------------------------------
def build_target(name: str) -> Tuple[Dict, Dict[str, str]]:
    """(ledger, {program: pre_opt_hlo_text}) for one registered
    target.  The texts ride back so a full sweep can derive the AMP
    policy without lowering anything twice."""
    from mxtpu.analysis import dtypeflow
    from tools.hlocheck import targets as T

    raw = T.build_prec(name)
    texts: Dict[str, str] = dict(raw["programs"])
    ledger: Dict = {
        "comment": "mxprec precision ledger -- regenerate with "
                   f"`python -m tools.mxprec --update {name}`",
        "target": name,
        "programs": {prog: dtypeflow.program_ledger(text)
                     for prog, text in sorted(texts.items())},
    }
    opt, sigs = raw.get("optimizer"), raw.get("param_sigs")
    if opt is not None and sigs is not None:
        dtypes: Dict[str, int] = {}
        for _, _, dt in sigs:
            dtypes[dt] = dtypes.get(dt, 0) + 1
        mp = opt.multi_precision
        ledger["optimizer"] = {
            "kind": type(opt).__name__.lower(),
            "multi_precision": "auto" if mp is None else bool(mp),
            "param_dtypes": {k: dtypes[k] for k in sorted(dtypes)},
            "hazards": dtypeflow.master_weight_findings(opt, sigs),
        }
    return ledger, texts


def build_amp_policy(texts_by_target: Dict[str, Dict[str, str]]
                     ) -> Dict:
    """Classify every float-carrying opcode observed across the
    targets' pre-opt programs; evidence = per-target instruction
    counts.  Pallas kernels' declared accumulation contracts ride
    along (custom calls are opaque to the HLO scan)."""
    from mxtpu import kernels
    from mxtpu.analysis import dtypeflow

    # ``*_amp`` / ``*_int8`` targets are CONSUMERS of the derived
    # policies (their lowerings already carry the casts / int8 GEMMs
    # those prescribe); feeding them back in as evidence would be
    # circular and would churn the committed policy every time a
    # rewritten lowering changes.  Derive from the f32 baselines only.
    texts_by_target = {t: v for t, v in texts_by_target.items()
                       if not t.endswith(("_amp", "_int8"))}

    counts: Dict[str, Dict[str, int]] = {}
    for target in sorted(texts_by_target):
        for prog in sorted(texts_by_target[target]):
            text = texts_by_target[target][prog]
            for op, n in dtypeflow.float_opcode_counts(text).items():
                slot = counts.setdefault(op, {})
                slot[target] = slot.get(target, 0) + n

    sections: Dict[str, Dict] = {"allow": {}, "deny": {},
                                 "fp32_force": {}, "inherit": {}}
    for op in sorted(counts):
        section, reason = classify_opcode(op)
        sections[section][op] = {"reason": reason,
                                 "evidence": counts[op]}
    return {
        "comment": "machine-derived AMP op policy -- every opcode "
                   "below was observed float-carrying in a lowered "
                   "target program; regenerate with "
                   "`python -m tools.mxprec --update`",
        "targets": sorted(texts_by_target),
        "allow": sections["allow"],
        "deny": sections["deny"],
        "fp32_force": sections["fp32_force"],
        "inherit": sections["inherit"],
        "custom_calls": kernels.precision_metadata(),
    }


def save_amp_policy(policy: Dict, directory: Path) -> Path:
    path = amp_policy_path(directory)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(_dump(policy))
    return path


def build_quant_policy(texts_by_target: Dict[str, Dict[str, str]]
                       ) -> Dict:
    """``contracts/quant_policy.json``: the allow class is every
    contraction opcode OBSERVED float-carrying across the f32
    baselines of :data:`QUANT_BASE_TARGETS`, the deny class carries
    the AMP transcendental list verbatim, and the calibration section
    is machine evidence from a deterministic seeded calibration of
    the quantized serving fixture — both collectors' per-tensor
    activation thresholds, the per-channel weight scales of every
    quantized parameter, and the s8xs8->s32 contraction census of the
    quantized bucket ladder.  Byte-deterministic: fixed batches,
    6-significant-figure rounding, sorted keys."""
    from mxtpu.analysis import dtypeflow
    from tools.hlocheck import targets as T

    base = {t: v for t, v in texts_by_target.items()
            if t in QUANT_BASE_TARGETS}
    counts: Dict[str, Dict[str, int]] = {}
    for target in sorted(base):
        for prog in sorted(base[target]):
            text = base[target][prog]
            for op, n in dtypeflow.float_opcode_counts(text).items():
                slot = counts.setdefault(op, {})
                slot[target] = slot.get(target, 0) + n

    allow = {op: {"reason": _QALLOW_REASON, "evidence": counts[op]}
             for op in sorted(counts) if op in _ALLOW_OPS}
    deny = {op: {"reason": _QDENY_REASON,
                 "evidence": counts.get(op, {})}
            for op in sorted(_DENY_OPS)}
    return {
        "comment": "machine-derived INT8 quantization policy -- "
                   "allow = contractions observed in the f32 "
                   "baselines, deny = the AMP transcendental class "
                   "verbatim, calibration = deterministic seeded "
                   "evidence from the quantized serving fixture; "
                   "regenerate with `python -m tools.mxprec --quant "
                   "--update`",
        "targets": sorted(base),
        "allow": allow,
        "deny": deny,
        "calibration": T.quant_calibration_evidence(),
    }


def save_quant_policy(policy: Dict, directory: Path) -> Path:
    path = quant_policy_path(directory)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(_dump(policy))
    return path


# ---------------------------------------------------------------------
# comparison (drift -> human-readable violation strings)
# ---------------------------------------------------------------------
def _diff(old, new, path: str, out: List[str],
          cap: int = 20) -> None:
    if len(out) >= cap:
        return
    if type(old) is not type(new):
        out.append(f"{path}: {_fmt(old)} -> {_fmt(new)}")
        return
    if isinstance(old, dict):
        for k in sorted(set(old) | set(new)):
            sub = f"{path}.{k}" if path else str(k)
            if k not in old:
                out.append(f"{sub}: missing in lockfile, now "
                           f"{_fmt(new[k])}")
            elif k not in new:
                out.append(f"{sub}: {_fmt(old[k])} vanished")
            else:
                _diff(old[k], new[k], sub, out, cap)
            if len(out) >= cap:
                return
    elif isinstance(old, list):
        if old != new:
            out.append(f"{path}: {_fmt(old)} -> {_fmt(new)}")
    elif old != new:
        out.append(f"{path}: {_fmt(old)} -> {_fmt(new)}")


def _fmt(v) -> str:
    s = json.dumps(v, sort_keys=True)
    return s if len(s) <= 120 else s[:117] + "..."


def compare_ledgers(committed: Dict, fresh: Dict) -> List[str]:
    """Drift between a committed ledger and a fresh build — empty when
    byte-identical under the lockfile serialization."""
    if _dump(committed) == _dump(fresh):
        return []
    out: List[str] = []
    _diff(committed, fresh, "", out)
    return out or ["ledger drifted (serialization-level difference)"]


def compare_policy(committed: Dict, fresh: Dict,
                   label: str = "amp_policy") -> List[str]:
    if _dump(committed) == _dump(fresh):
        return []
    out: List[str] = []
    _diff(committed, fresh, label, out)
    return out or [f"{label} drifted"]


# ---------------------------------------------------------------------
# README dtype table (committed ledgers -> markdown between markers)
# ---------------------------------------------------------------------
def _ledger_row(name: str, ledger: Dict) -> str:
    floats: Dict[str, int] = {}
    casts = 0
    hazards = 0
    for prog in ledger.get("programs", {}).values():
        for dt, n in prog.get("float_ops", {}).items():
            floats[dt] = floats.get(dt, 0) + n
        for flow in prog.get("flows", {}).values():
            casts += flow.get("count", 0)
        hazards += len(prog.get("hazards", []))
    opt = ledger.get("optimizer")
    hazards += len(opt.get("hazards", [])) if opt else 0
    fl = " ".join(f"{dt}:{floats[dt]}" for dt in sorted(floats)) \
        or "—"
    return (f"| {name} | {len(ledger.get('programs', {}))} | {fl} "
            f"| {casts} | {hazards} |")


def render_dtype_table(ledgers: Dict[str, Dict]) -> str:
    lines = [PREC_BEGIN,
             "| target | programs | float ops | casts | hazards |",
             "|---|---|---|---|---|"]
    for name in sorted(ledgers):
        lines.append(_ledger_row(name, ledgers[name]))
    lines.append("")
    lines.append(f"*Pre-optimization dtype flow over {len(ledgers)} "
                 f"target(s); pinned in `contracts/prec/`, regenerate "
                 f"with `python -m tools.mxprec --fix-readme`.*")
    lines.append(PREC_END)
    return "\n".join(lines)


def readme_drift(root: Path, ledgers: Dict[str, Dict]) -> List[str]:
    readme = root / "README.md"
    if not readme.exists():
        return ["README.md missing"]
    text = readme.read_text()
    if PREC_BEGIN not in text or PREC_END not in text:
        return ["README.md lacks the mxprec:dtypes markers — run "
                "`python -m tools.mxprec --fix-readme`"]
    current = text.split(PREC_BEGIN, 1)[1].split(PREC_END, 1)[0]
    want = render_dtype_table(ledgers) \
        .split(PREC_BEGIN, 1)[1].split(PREC_END, 1)[0]
    if current.strip() != want.strip():
        return ["README precision table is stale — run "
                "`python -m tools.mxprec --fix-readme`"]
    return []


def fix_readme(root: Path, ledgers: Dict[str, Dict]) -> bool:
    readme = root / "README.md"
    text = readme.read_text()
    if PREC_BEGIN not in text or PREC_END not in text:
        raise SystemExit(
            f"README.md lacks the markers {PREC_BEGIN!r} … "
            f"{PREC_END!r}; add them where the table should live")
    head = text.split(PREC_BEGIN, 1)[0]
    tail = text.split(PREC_END, 1)[1]
    new = head + render_dtype_table(ledgers) + tail
    if new != text:
        readme.write_text(new)
        return True
    return False
