"""mxprec CLI.

Exit codes (the contract tests/test_prec.py pins, mirroring mxlint /
hlocheck / mxrace):

* 0 — every checked ledger matches; AMP policy + README table fresh;
* 1 — precision-ledger drift (or missing ledger in --check mode);
* 2 — usage / internal error (unknown target, unreadable ledger,
      orphaned ledger, empty baseline).

``--update`` re-lowers the named targets (default: all) at the
PRE-optimization level and rewrites ``contracts/prec/<target>.json``;
a full ``--update`` additionally derives ``contracts/amp_policy.json``
and ``contracts/quant_policy.json`` from the same lowerings.
``--quant`` is the focused INT8 mode: it lowers only the quant
evidence base (``core.QUANT_BASE_TARGETS``) and writes/checks
``contracts/quant_policy.json`` alone — the cheap round trip the
quant tests pin.  The default mode re-lowers and checks; the
AMP-policy and README-table drift checks run only on a full default
check (no explicit targets), so a single-target round trip stays
cheap for tier-1 tests.  Lowering happens on the CPU backend with the
8-virtual-device topology the test suite uses, so ledgers are
reproducible on any box.
"""
from __future__ import annotations

import os

# pin the lowering environment BEFORE jax (imported via mxtpu) loads:
# precision ledgers are CPU-backend artifacts by definition
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import argparse   # noqa: E402
import json       # noqa: E402
import sys        # noqa: E402
import time       # noqa: E402
from pathlib import Path  # noqa: E402

from . import core  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxprec",
        description="Interprocedural dtype-flow analysis over the "
                    "pre-optimization lowerings of the hlocheck "
                    "targets, checked against committed precision "
                    "ledgers (contracts/prec/) and the derived AMP "
                    "op policy (contracts/amp_policy.json).")
    ap.add_argument("targets", nargs="*",
                    help="targets to process (default: every "
                         "committed ledger for --check, every "
                         "registered target for --update)")
    ap.add_argument("--check", action="store_true",
                    help="counts-only output; exit 1 on drift (CI "
                         "mode — this is also the default behaviour)")
    ap.add_argument("--update", action="store_true",
                    help="regenerate ledgers for the named targets "
                         "(full run also rewrites amp_policy.json) "
                         "and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit results as JSON")
    ap.add_argument("--list", action="store_true",
                    help="list registered targets and exit")
    ap.add_argument("--fix-readme", action="store_true",
                    help="regenerate the README precision table from "
                         "the COMMITTED ledgers (no lowering) and "
                         "exit")
    ap.add_argument("--quant", action="store_true",
                    help="focused INT8 mode: derive/check "
                         "contracts/quant_policy.json from the quant "
                         "evidence base only (with --update: rewrite "
                         "it)")
    ap.add_argument("--contracts-dir", type=Path, default=None,
                    help="lockfile directory (default: contracts/)")
    args = ap.parse_args(argv)

    from mxtpu.analysis import contracts as C
    from tools.hlocheck import targets as T

    directory = args.contracts_dir or C.CONTRACTS_DIR

    if args.list:
        for name in sorted(T.PREC_TARGETS):
            state = "ledger" if core.ledger_path(
                name, directory).exists() else "NO LEDGER"
            print(f"{name:20s} [{state}]")
        return 0

    if args.fix_readme:
        ledgers = core.committed_ledgers(directory)
        if not ledgers:
            print(f"mxprec: no ledgers in {core.prec_dir(directory)}"
                  f" — run --update first", file=sys.stderr)
            return 2
        changed = core.fix_readme(core.REPO_ROOT, ledgers)
        print("mxprec: README precision table "
              + ("rewritten" if changed else "already fresh"))
        return 0

    if args.quant:
        # focused INT8 round trip: lower only the evidence base,
        # write or check contracts/quant_policy.json, nothing else
        t0 = time.perf_counter()
        missing = [t for t in core.QUANT_BASE_TARGETS
                   if t not in T.PREC_TARGETS]
        if missing:
            print(f"mxprec: quant base target(s) unregistered: "
                  f"{', '.join(missing)}", file=sys.stderr)
            return 2
        violations = []
        committed = None
        ppath = core.quant_policy_path(directory)
        if not args.update:
            # probe the committed file BEFORE the expensive lowerings
            # — a missing/unreadable policy needs no fresh evidence
            if not ppath.exists():
                violations.append(
                    f"quant_policy: no {ppath} — run --quant --update")
            else:
                try:
                    committed = json.loads(ppath.read_text())
                except (ValueError, OSError) as e:
                    print(f"mxprec: cannot read {ppath}: {e}",
                          file=sys.stderr)
                    return 2
        if args.update or committed is not None:
            texts = {}
            for name in core.QUANT_BASE_TARGETS:
                _, texts[name] = core.build_target(name)
            policy = core.build_quant_policy(texts)
        dt = time.perf_counter() - t0
        if args.update:
            path = core.save_quant_policy(policy, directory)
            print(f"mxprec: wrote {path} ({dt:.1f}s)")
            return 0
        if committed is not None:
            violations += core.compare_policy(committed, policy,
                                              "quant_policy")
        if args.as_json:
            print(json.dumps({"violations": violations,
                              "seconds": round(dt, 1)}, indent=1))
        else:
            for v in violations:
                print("  " + v)
            print(f"mxprec: quant policy, {len(violations)} "
                  f"violation(s) ({dt:.1f}s)")
        return 1 if violations else 0

    if args.targets:
        unknown = [t for t in args.targets
                   if t not in T.PREC_TARGETS]
        if unknown:
            print(f"mxprec: unknown target(s): "
                  f"{', '.join(unknown)} (see --list)",
                  file=sys.stderr)
            return 2
        names = list(args.targets)
    elif args.update:
        names = sorted(T.PREC_TARGETS)
    else:
        # check everything that has a committed ledger AND is still a
        # registered target; a ledger whose target vanished is an
        # error, not silence
        names = sorted(p.stem for p in
                       core.prec_dir(directory).glob("*.json")) \
            if core.prec_dir(directory).is_dir() else []
        orphans = [n for n in names if n not in T.PREC_TARGETS]
        if orphans:
            print(f"mxprec: ledger(s) without a registered target: "
                  f"{', '.join(orphans)}", file=sys.stderr)
            return 2
        if not names:
            print(f"mxprec: no ledgers in "
                  f"{core.prec_dir(directory)} — run --update first",
                  file=sys.stderr)
            return 2

    # amp-policy + README drift ride only on a FULL sweep (they are
    # whole-tree artifacts); explicit-target runs stay cheap
    full = not args.targets

    t0 = time.perf_counter()
    all_violations: list = []
    results = {}
    texts_by_target = {}
    fresh_ledgers = {}
    for name in names:
        t1 = time.perf_counter()
        ledger, texts = core.build_target(name)
        dt = time.perf_counter() - t1
        texts_by_target[name] = texts
        fresh_ledgers[name] = ledger
        if args.update:
            path = core.save_ledger(ledger, directory)
            results[name] = {"updated": str(path),
                             "programs": sorted(ledger["programs"]),
                             "seconds": round(dt, 1)}
            if not args.as_json:
                print(f"mxprec: wrote {path} "
                      f"({len(ledger['programs'])} program(s), "
                      f"{dt:.1f}s)")
            continue
        try:
            committed = core.load_ledger(name, directory)
        except FileNotFoundError:
            all_violations.append(
                f"{name}: no ledger "
                f"{core.ledger_path(name, directory)} — run "
                f"--update {name}")
            continue
        except (ValueError, OSError) as e:
            print(f"mxprec: cannot read ledger for {name}: {e}",
                  file=sys.stderr)
            return 2
        drift = core.compare_ledgers(committed, ledger)
        all_violations += [f"{name}: {d}" for d in drift]
        results[name] = {"drift": drift, "seconds": round(dt, 1)}
        if not args.as_json and not args.check:
            print(f"mxprec: {name}: {len(drift)} drift(s) "
                  f"({dt:.1f}s)")

    if args.update:
        if full:
            path = core.save_amp_policy(
                core.build_amp_policy(texts_by_target), directory)
            if not args.as_json:
                print(f"mxprec: wrote {path}")
            qpath = core.save_quant_policy(
                core.build_quant_policy(texts_by_target), directory)
            if not args.as_json:
                print(f"mxprec: wrote {qpath}")
        if args.as_json:
            print(json.dumps(results, indent=1))
        return 0

    if full:
        policy = core.build_amp_policy(texts_by_target)
        ppath = core.amp_policy_path(directory)
        if not ppath.exists():
            all_violations.append(
                f"amp_policy: no {ppath} — run --update")
        else:
            try:
                committed_policy = json.loads(ppath.read_text())
            except (ValueError, OSError) as e:
                print(f"mxprec: cannot read {ppath}: {e}",
                      file=sys.stderr)
                return 2
            all_violations += core.compare_policy(committed_policy,
                                                  policy)
        qpolicy = core.build_quant_policy(texts_by_target)
        qpath = core.quant_policy_path(directory)
        if not qpath.exists():
            all_violations.append(
                f"quant_policy: no {qpath} — run --quant --update")
        else:
            try:
                committed_q = json.loads(qpath.read_text())
            except (ValueError, OSError) as e:
                print(f"mxprec: cannot read {qpath}: {e}",
                      file=sys.stderr)
                return 2
            all_violations += core.compare_policy(
                committed_q, qpolicy, "quant_policy")
        all_violations += core.readme_drift(
            core.REPO_ROOT, core.committed_ledgers(directory))

    dt = time.perf_counter() - t0
    if args.as_json:
        print(json.dumps({"results": results,
                          "violations": all_violations,
                          "seconds": round(dt, 1)}, indent=1))
    else:
        for v in all_violations:
            print("  " + v)
        print(f"mxprec: {len(names)} target(s), "
              f"{len(all_violations)} violation(s) ({dt:.1f}s)")
    return 1 if all_violations else 0


if __name__ == "__main__":
    sys.exit(main())
