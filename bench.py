"""Benchmark: hybridized LeNet-MNIST training throughput (north-star
workload 1, BASELINE.md).  Runs on whatever accelerator jax exposes
(the driver runs it on the real TPU chip) and prints ONE JSON line.

The measured unit is the full compiled training step — forward,
backward, fused optimizer — via ``mxtpu.parallel.build_train_step``,
i.e. the samples/sec a Speedometer would report (SURVEY.md §5.5).
``vs_baseline`` is null: the reference mount was empty both rounds, so
no published number exists to compare against (BASELINE.md).
"""
import json
import sys
import time

import numpy as np


def bench_lenet(batch_size=512, warmup=5, iters=30):
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import lenet

    net = lenet()
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 1, 28, 28).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (batch_size,)).astype(np.float32))
    for _ in range(warmup):
        step(x, y)
    nd.waitall()
    t0 = time.perf_counter()
    last = None
    for _ in range(iters):
        last = step(x, y)
    float(last.asscalar())  # sync
    dt = time.perf_counter() - t0
    return batch_size * iters / dt


def main():
    value = bench_lenet()
    print(json.dumps({
        "metric": "lenet_mnist_train_throughput",
        "value": round(value, 1),
        "unit": "samples/sec",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
