"""Benchmark: compiled training-step throughput on the real chip.

Prints ONE JSON line whose primary metric is the **ResNet-50 ImageNet
training throughput** (north-star #1, BASELINE.md); the BERT-Large
(north-star #2) and LeNet numbers ride along in ``extras`` so every
round's ``BENCH_r{N}.json`` captures the full picture.  Set
MXTPU_BENCH_MODEL=lenet|resnet50|resnet50_pipeline|bert|bert_s512|
transformer|moe_ffn|ssd|bert_zero|serving_bert|serving_fleet|
serving_autoscale|serving_coldstart|serving_bert_int8|
serving_generate to run a single
workload (moe_ffn, ssd, bert_zero and the serving_* rows are
on-demand only — not part of the default ``all`` sweep, which is
sized to the wall budget).  ``--amp`` (or MXTPU_BENCH_MODEL=resnet50_amp|bert_amp|
transformer_amp|bert_zero_amp) runs the ``mxtpu.amp`` pair rows: the
base workload measured AMP-off and AMP-on, rate + MFU + (for the
ZeRO pair) contract-pinned comm bytes side by side.  Every row's ``details``
carries ``hbm_peak`` — the per-device resident high-water
(temp + argument bytes) of the compiled program, from XLA's
memory_analysis.  ``bench.py --preflight`` prints the per-row wall
estimates for the selected sweep and exits non-zero if it would not
fit MXTPU_BENCH_WALL_BUDGET — check this BEFORE burning a TPU run.

The measured unit is the full compiled training step — forward,
backward, fused optimizer (+BN aux writeback) — via
``mxtpu.parallel.build_train_step``, i.e. the samples/sec a
Speedometer would report (SURVEY.md §5.5).

``mfu`` is model-FLOPs utilisation: training FLOPs/sample as counted
by XLA's cost_analysis of the compiled fwd+bwd program (see
_TRAIN_FLOPS) divided by the chip's peak bf16 FLOP/s.
``vs_baseline`` compares the run best against the PREVIOUS round's
self-measured best in BASELINE_SELF.json — the reference mount has
been empty every round (SURVEY.md provenance caveat), so the baseline
is our own trend line; regression < 1.0 is failure unless
``within_noise`` (the shared-chip tunnel shows 5-15% run-to-run
spread, recorded per metric in ``band``).

Wall budget (r5 post-mortem: one ~12-minute workload cost the round
its entire perf record, BENCH_r05.json rc=124): the run carries a
global deadline (``MXTPU_BENCH_WALL_BUDGET`` seconds, default 780).
When the selected sweep's TOTAL estimate already exceeds the budget,
the sweep is auto-trimmed UP FRONT: rows that don't fit the cumulative
estimate are recorded as ``{"skipped": "budget"}`` before anything
runs — the same arithmetic ``--preflight`` prints, applied instead of
merely warned about.  Before each remaining workload the leftover time
is re-checked against that row's conservative estimate as a backstop;
a row that does not fit is likewise recorded as
``{"skipped": "budget"}`` instead of running — the JSON always prints
and the process always exits 0 inside the window.  The pipeline row
additionally self-limits: repeats stop when its own slice of the
budget is spent.  Stale ``mxtpu_bench_rec_*`` temp dirs from killed
runs are swept at startup.

Two ISSUE 14 hardenings close the rc=124 class at the source:
``JAX_PLATFORMS`` is pinned to ``cpu`` when unset BEFORE jax loads
(r05's experimental axon plugin hung device discovery at import —
earlier than any deadline logic), and a ``SIGALRM`` at the wall
budget flushes the partial record (never-ran rows as
``{"skipped": "budget"}``) and exits 0 even if a single row hangs
straight through its estimate.
"""
import json
import os
import signal
import subprocess
import sys
import time

# r05 post-mortem (BENCH_r05.json rc=124, tail shows the experimental
# `axon` jax plugin initializing): with JAX_PLATFORMS unset, device
# discovery probes every registered plugin and a dead axon tunnel
# hangs the process at import — before any deadline logic can run.
# Pin the platform BEFORE anything imports jax; an explicit setting
# from the driver (e.g. a real TPU run) always wins.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from mxtpu import guards, knobs, obs

# MXTPU_GUARDS must never change bench semantics: self_check asserts
# the disabled scope is the shared no-op object (zero per-call
# overhead when guards are off) and, when enabled, that a jitted
# probe returns bit-identical results inside the guard scope.
guards.self_check()
# Same contract for MXTPU_OBS: disabled instruments are the shared
# no-op singletons, and the exposition formats round-trip losslessly.
obs.self_check()

# Peak dense bf16 FLOP/s per chip, by jax device_kind prefix.
# v5 lite (v5e) 197 TFLOP/s; v5p 459; v4 275; v3 123 (bf16).
_PEAK_BF16 = (("TPU v5 lite", 197e12), ("TPU v5p", 459e12),
              ("TPU v5", 459e12), ("TPU v4", 275e12), ("TPU v3", 123e12),
              ("TPU v2", 45e12))

# single source for each workload's metric name (success AND error
# paths report the same key)
_METRIC_NAMES = {
    "resnet50": "resnet50_imagenet_train_throughput",
    "resnet50_pipeline": "resnet50_pipeline_fed_train_throughput",
    "bert": "bert_large_pretrain_throughput",
    "bert_s512": "bert_large_s512_pretrain_throughput",
    "transformer": "transformer_big_wmt_train_throughput",
    "moe_ffn": "moe_ffn_microbench_throughput",
    "ssd": "ssd300_voc_train_throughput",
    "bert_zero": "bert_large_zero1_train_throughput",
    "serving_bert": "serving_bert_sustained_throughput",
    "serving_fleet": "serving_fleet_soak_throughput",
    "serving_autoscale": "serving_autoscale_burst_absorb_throughput",
    "serving_coldstart": "serving_coldstart_disk_warm_speedup",
    "serving_bert_int8": "serving_bert_int8_raw_throughput",
    "serving_generate": "serving_generate_decode_throughput",
    "lenet": "lenet_mnist_train_throughput",
    # --amp pairs: each row runs its base workload twice (AMP off /
    # AMP on via mxtpu.amp) and reports rate + MFU + comm side by side
    "resnet50_amp": "resnet50_imagenet_amp_train_throughput",
    "bert_amp": "bert_large_amp_pretrain_throughput",
    "transformer_amp": "transformer_big_wmt_amp_train_throughput",
    "bert_zero_amp": "bert_large_zero1_amp_train_throughput",
}

# Training FLOPs per unit (sample or token), from XLA's own
# cost_analysis() of the compiled fwd+bwd program (r4: the widely
# quoted "4.1 GFLOP" for ResNet-50 is multiply-ACCUMULATES; XLA counts
# 7.54 GFLOP fwd / 22.49 GFLOP fwd+bwd per sample at 224x224, so r1-r3
# under-reported ResNet MFU by 1.83x.  BERT's 6N estimate was within
# 3% of XLA's 2.063 GFLOP/token and is replaced by the measured value.)
_TRAIN_FLOPS = {
    "resnet50": 22.49e9,      # XLA cost_analysis, fwd+bwd, b256
    "resnet50_pipeline": 22.49e9,  # same model, pipeline-fed
    "bert": 2.063e9,          # XLA cost_analysis, fwd+bwd, b32 s128
    # s512: s128 measurement + analytic attention delta (4*T*d*L fwd,
    # x3 fwd+bwd; the flash-attention custom call hides its FLOPs from
    # cost_analysis, so the analytic form is the honest one here)
    "bert_s512": 2.18e9,
    # TrainStep.cost_analysis on the CPU lowering (r6), fwd+bwd+adam,
    # transformer_big b16 s64+s64, per src+tgt token.  The CPU count
    # INCLUDES the attention einsums the TPU Pallas custom call hides,
    # so it is the complete denominator (1.489e12 FLOPs / 2048 tokens).
    "transformer": 0.727e9,
    "moe_ffn": None,          # microbench reports its own details
    "bert_zero": None,        # ablation row — the throughput delta and
                              # opt-state bytes are the result, not MFU
    "ssd": None,              # anchor machinery dominates op count,
                              # MFU would flatter the conv backbone
    "serving_bert": None,     # latency/throughput row — the served/raw
                              # ratio is the result, not MFU
    "serving_fleet": None,    # robustness row — zero in-deadline drops
                              # through a kill/restart is the result
    "serving_autoscale": None,  # control-plane row — absorb time / SLO
                                # violations vs static-N are the result
    "serving_coldstart": None,  # robustness row — the cold vs
                                # disk-warmed warmup split is the result
    "serving_bert_int8": None,  # ablation row — the int8/f32 ratio,
                                # accuracy delta and s8xs8->s32 census
                                # are the result, not MFU
    "serving_generate": None,   # decode row — tokens/sec, TTFT and
                                # the kv-vs-naive-reprefill ratio are
                                # the result, not MFU
    "lenet": None,            # too small for MFU to mean anything
    # amp pairs reuse the base row's FLOP denominator: AMP changes
    # operand dtypes, not the model math being counted
    "resnet50_amp": 22.49e9,
    "bert_amp": 2.063e9,
    "transformer_amp": 0.727e9,
    "bert_zero_amp": None,
}


def _peak_flops():
    import jax
    kind = jax.devices()[0].device_kind
    for prefix, peak in _PEAK_BF16:
        if kind.startswith(prefix):
            return peak
    return None


def _measure(step, x, y, warmup, iters, batch_size, repeats=5):
    """Timing of BULKED execution: ``iters`` steps run as one compiled
    ``lax.scan`` program (``TrainStep.run_steps``), the TPU-native
    analogue of the reference's bulked graph execution.  Necessary for
    honesty here: the tunnel charges ~10 ms of host RPC per dispatch
    plus ~2-3 ms per loop iteration (BASELINE.md r4 platform
    analysis), which at single-step granularity would measure the
    tunnel, not the chip.  Returns {best, median, n, spread} over
    ``repeats`` runs — the shared chip shows 5-15% run-to-run spread,
    so a single point is not a result."""
    last = step.run_steps(x, y, max(warmup, 2), reuse_batch=True)
    float(last.asnumpy()[-1])  # drain warmup incl. compile
    vals = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        last = step.run_steps(x, y, iters, reuse_batch=True)
        float(last.asnumpy()[-1])  # sync
        dt = time.perf_counter() - t0
        vals.append(batch_size * iters / dt)
    vals.sort()
    median = vals[len(vals) // 2] if len(vals) % 2 else \
        0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
    # spread = (max-min)/median over the runs minus the single worst
    # (the shared tunnel occasionally stalls a run outright — a 20x
    # outlier would make every future delta "within noise").  Normal
    # run-to-run variance on this chip is +-5-15% (VERDICT r3 weak-2).
    core = vals[1:] if len(vals) >= 4 else vals
    # per-device resident high-water (temp + argument bytes) of the
    # compiled scan program — rides into every row's ``details``
    mem = step.last_memory_analysis()
    return {"best": max(vals), "median": median, "n": len(vals),
            "spread": round((max(core) - min(core)) / median, 4),
            "runs": [round(v, 1) for v in vals],
            "info": {"hbm_peak": mem["hbm_peak"] if mem else None}}


def bench_lenet(batch_size=512, warmup=5, iters=30):
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import lenet

    net = lenet()
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 1, 28, 28).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (batch_size,)).astype(np.float32))
    return _measure(step, x, y, warmup, iters, batch_size), \
        _METRIC_NAMES["lenet"], "samples/sec"


def bench_resnet50(batch_size=None, warmup=3, iters=20, amp=None):
    """ResNet-50 ImageNet-shaped training step (north-star #1).
    Defaults to the standard TPU recipe — bf16 compute over f32 master
    weights, batch 256 (MXTPU_BENCH_DTYPE= / MXTPU_BENCH_BATCH
    override; set MXTPU_BENCH_DTYPE="" for pure f32).  ``amp=True``
    switches to the policy-driven ``mxtpu.amp`` path (bf16 storage +
    f32 masters + loss scaling) instead of the blanket compute-dtype
    cast — the two are mutually exclusive."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import resnet50

    batch_size = batch_size or knobs.get("MXTPU_BENCH_BATCH")
    net = resnet50(classes=1000)
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype=(None if amp
                       else knobs.get("MXTPU_BENCH_DTYPE") or None),
        amp=amp)
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 3, 224, 224).astype(np.float32))
    y = nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32))
    return _measure(step, x, y, warmup, iters, batch_size), \
        _METRIC_NAMES["resnet50"], "samples/sec"


def bench_resnet50_pipeline(batch_size=None, warmup=4, iters=24,
                            repeats=3, row_budget=None):
    """Pipeline-fed ResNet-50 (VERDICT r5 item 2): trains from an
    ImageRecordIter over a synthetic raw-record dataset — per-step
    batches, NO reuse_batch.  The full L6 pipeline:

        disk → vectorized batch assembly (one read_batch_into +
        blockwise mirror, worker thread via PrefetchingIter)
             → double-buffered H2D (DeviceFeedIter: batch N+1's
               non-blocking device_put issued while step N runs)
             → compiled step (uint8 crosses the link; cast + mean/std
               fuse into the first conv's XLA program).

    The raw-record tier is the honest rate-proof on THIS host: the
    box has ONE CPU core (nproc=1), which caps cv2 JPEG decode at
    ~380 img/s no matter the implementation; raw records take decode
    out and measure the framework's own assembly + feed architecture
    (BASELINE.md "Input pipeline").  Reference:
    iter_image_recordio_2.cc† + iter_prefetcher.h†.

    Self-limiting (r5 post-mortem): measurement repeats stop when
    ``row_budget`` seconds have elapsed in this row — a slow pipeline
    produces a worse number, never a dead round."""
    import shutil
    import tempfile

    from mxtpu import parallel
    from mxtpu import recordio as rio
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon import nn
    from mxtpu.io import (DeviceFeedIter, ImageRecordIter,
                          PrefetchingIter)
    from mxtpu.models import resnet50

    batch_size = batch_size or knobs.get("MXTPU_BENCH_BATCH")
    row_budget = row_budget or knobs.get("MXTPU_BENCH_ROW_BUDGET")
    t_row = time.perf_counter()
    d = tempfile.mkdtemp(prefix="mxtpu_bench_rec_")
    try:
        prefix = os.path.join(d, "synth")
        rng = np.random.RandomState(0)
        n_img = 4 * batch_size
        rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                    "w")
        base = (rng.rand(3, 224, 224) * 255).astype(np.uint8)
        for i in range(n_img):
            # distinct images without n_img full RNG draws: roll+refresh
            if i % 61 == 0:
                base = (rng.rand(3, 224, 224) * 255).astype(np.uint8)
            rec.write_idx(i, rio.pack(
                rio.IRHeader(0, float(i % 1000), i, 0),
                np.roll(base, i % 224, axis=2).tobytes()))
        rec.close()

        compute_dtype = knobs.get("MXTPU_BENCH_DTYPE") or "float32"

        class _DeviceNormalize(nn.HybridBlock):
            """uint8 -> (x - mean)/std on device; XLA fuses it into the
            step (channel-mean simplification: ImageNet grand mean /
            std — the arithmetic cost is identical to per-channel).
            The 1/std lives in a frozen parameter so the layer inherits
            the compute dtype from the AMP cast machinery: eager
            shape-inference sees f32, the compiled step sees bf16 — no
            hand-managed casts."""

            def __init__(self, **kw):
                super().__init__(**kw)
                from mxtpu import initializer
                self.inv_std = self.params.get(
                    "inv_std", shape=(1,),
                    init=initializer.Constant(1.0 / 57.7),
                    grad_req="null")

            def hybrid_forward(self, F, x, inv_std):
                return (x.astype(str(inv_std.dtype)) - 114.8) * inv_std

        net = nn.HybridSequential(prefix="pipe_")
        net.add(_DeviceNormalize(), resnet50(classes=1000))
        net.initialize(init="xavier")
        step = parallel.build_train_step(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
            compute_dtype=(compute_dtype if compute_dtype != "float32"
                           else None),
            cast_batch=False)

        # host_batches=True: the worker thread hands raw numpy across
        # the queue; the single device_put per array happens one batch
        # ahead in DeviceFeedIter, overlapping the running step
        it = ImageRecordIter(prefix + ".rec", (3, 224, 224), batch_size,
                             path_imgidx=prefix + ".idx", shuffle=True,
                             rand_mirror=True, raw_records=True,
                             dtype="uint8", preprocess_threads=2,
                             host_batches=True)
        feed = DeviceFeedIter(PrefetchingIter(it))

        def batches():
            while True:
                try:
                    yield feed.next()
                except StopIteration:
                    feed.reset()

        stream = batches()
        loss = None
        for _ in range(warmup):  # includes the compile
            b = next(stream)
            loss = step(b.data[0], b.label[0])
        float(loss.asnumpy().mean())
        vals = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(iters):
                b = next(stream)
                loss = step(b.data[0], b.label[0])  # async dispatch
            float(loss.asnumpy().mean())  # sync
            vals.append(batch_size * iters /
                        (time.perf_counter() - t0))
            # stop, don't die: the next repeat must fit what is left
            # of this row's budget (r5's rc=124 lesson)
            spent = time.perf_counter() - t_row
            if spent + (time.perf_counter() - t0) > row_budget:
                break
        vals.sort()
        median = vals[len(vals) // 2] if len(vals) % 2 else \
            0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
        mem = step.last_memory_analysis()
        stats = {"best": max(vals), "median": median, "n": len(vals),
                 "spread": round((max(vals) - min(vals)) / median, 4),
                 "runs": [round(v, 1) for v in vals],
                 "info": {"hbm_peak": mem["hbm_peak"] if mem
                          else None}}
        return stats, _METRIC_NAMES["resnet50_pipeline"], "samples/sec"
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_bert(batch_size=32, seq_len=128, warmup=3, iters=20,
               metric_key="bert", amp=None):
    """BERT-Large MLM-style training step, tokens/sec (north-star #2).
    bf16 compute by default (set MXTPU_BENCH_DTYPE= to override);
    ``amp=True`` takes the ``mxtpu.amp`` path instead."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models.transformer import bert_large

    net = bert_large(vocab_size=30522, max_length=seq_len, dropout=0.1)
    net.initialize(init="xavier")
    dtype = None if amp else knobs.get("MXTPU_BENCH_DTYPE") or None

    def mlm_loss(pred, y):
        V = 30522
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, V)), y.reshape((-1,)))

    # cast_batch=False: token ids must not be rounded through bf16
    step = parallel.build_train_step(
        net, mlm_loss, "adam", {"learning_rate": 1e-4},
        compute_dtype=dtype, cast_batch=False, amp=amp)
    rng = np.random.RandomState(0)
    toks = nd.array(rng.randint(0, 30522, (batch_size, seq_len))
                    .astype(np.float32))
    tokens_per_batch = batch_size * seq_len
    value = _measure(step, toks, toks, warmup, iters, tokens_per_batch)
    return value, _METRIC_NAMES[metric_key], "tokens/sec"


def bench_transformer(batch_size=16, src_len=64, tgt_len=64, warmup=3,
                      iters=16, amp=None):
    """Transformer-big WMT-shaped seq2seq training step, tokens/sec
    over src+tgt tokens (north-star #4 / M6 bench presence).  Sized to
    fit the wall budget: b16 s64/s64 keeps the compile + 5 measurement
    repeats inside the row estimate while every GEMM is already
    MXU-shaped (the per-token cost is sequence-length-flat until
    attention dominates)."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon.block import HybridBlock
    from mxtpu.models.transformer import transformer_big

    V = 32768

    class _MTWrap(HybridBlock):
        """TrainStep feeds ONE batch array: src|tgt ride concatenated
        on the time axis and split here."""

        def __init__(self, split, **kw):
            super().__init__(**kw)
            self._split = split
            self.model = transformer_big(vocab_size=V, max_length=256,
                                         dropout=0.1)

        def hybrid_forward(self, F, x):
            src = F.slice_axis(x, axis=1, begin=0, end=self._split)
            tgt = F.slice_axis(x, axis=1, begin=self._split, end=None)
            return self.model(src, tgt)

    net = _MTWrap(src_len)
    net.initialize(init="xavier")
    dtype = None if amp else knobs.get("MXTPU_BENCH_DTYPE") or None

    def mt_loss(pred, y):
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, V)), y.reshape((-1,)))

    # cast_batch=False: token ids must not be rounded through bf16
    step = parallel.build_train_step(
        net, mt_loss, "adam", {"learning_rate": 1e-4},
        compute_dtype=dtype, cast_batch=False, amp=amp)
    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, V, (batch_size, src_len + tgt_len))
                 .astype(np.float32))
    y = nd.array(rng.randint(0, V, (batch_size, tgt_len))
                 .astype(np.float32))
    tokens_per_batch = batch_size * (src_len + tgt_len)
    value = _measure(step, x, y, warmup, iters, tokens_per_batch)
    return value, _METRIC_NAMES["transformer"], "tokens/sec"


def bench_ssd(batch_size=8, size=300, num_classes=20, warmup=3,
              iters=10):
    """SSD-300 VOC-shaped training throughput (VERDICT r5 item 5): the
    full detection step — backbone, multi-scale heads, MultiBoxTarget
    assignment, SSDLoss — compiled via build_train_step on synthetic
    VOC-shaped batches (3x300x300, up to 3 boxes/image)."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.models.ssd import SSDLoss, ssd_300

    net = ssd_300(num_classes=num_classes)
    net.initialize(init="xavier")
    loss_fn = SSDLoss()

    def det_loss(pred, labels):
        anchors, cls_preds, box_preds = pred
        bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds)
        return nd.mean(loss_fn(cls_preds, box_preds, ct, bt, bm))

    # cast_batch only touches x (the image) — labels reach
    # MultiBoxTarget in f32, so class ids and box coords never round
    # through bf16
    step = parallel.build_train_step(
        net, det_loss, "sgd",
        {"learning_rate": 5e-3, "momentum": 0.9, "wd": 5e-4},
        compute_dtype=knobs.get("MXTPU_BENCH_DTYPE") or None)
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 3, size, size)
                 .astype(np.float32))
    # VOC-shaped labels: (B, 3, 5) [cls, x0, y0, x1, y1], -1 pads
    labels = np.full((batch_size, 3, 5), -1.0, np.float32)
    for b in range(batch_size):
        for o in range(1 + b % 3):
            x0, y0 = rng.uniform(0, 0.6, 2)
            labels[b, o] = [rng.randint(num_classes), x0, y0,
                            x0 + rng.uniform(0.2, 0.4),
                            y0 + rng.uniform(0.2, 0.4)]
    y = nd.array(labels)
    value = _measure(step, x, y, warmup, iters, batch_size)
    return value, _METRIC_NAMES["ssd"], "samples/sec"


def bench_moe_ffn(T=8192, E=8, D=1024, H=4096, warmup=2, iters=8,
                  repeats=3):
    """Switch-MoE FFN microbench at the honesty point VERDICT r5
    item 6 names: T=8192 tokens, E=8 experts, D=1024, bf16, fwd+bwd.
    Reports tokens/sec plus ``details``: the dense-FFN equivalent
    (same D→H→D at the same token count), HBM high-water from XLA's
    memory_analysis, and the router+dispatch share (time not spent in
    the expert GEMMs themselves, measured by running the expert FFN on
    pre-dispatched (E, C, D) activations)."""
    import jax
    import jax.numpy as jnp

    from mxtpu.parallel.moe import MoEFFN

    layer = MoEFFN(D, H, E, capacity_factor=1.25)
    params = layer.params()
    C = int(np.ceil(T / E * 1.25))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(T, D).astype(np.float32),
                    dtype=jnp.bfloat16)

    def _chain(fn, x0, n, label):
        """Sustained fwd+bwd: each iteration's grad signal feeds the
        next input, so nothing is DCE'd or hoisted
        (tools/microbench.py methodology)."""
        grad_fn = jax.grad(
            lambda xx: fn(xx).astype(jnp.float32).sum() * 1e-3)

        @jax.jit
        def run(xx):
            return jax.lax.fori_loop(
                0, n, lambda i, v: (v + 1e-3 * grad_fn(v)
                                    .astype(v.dtype)), xx)

        out = run(x0)
        float(jnp.sum(out.astype(jnp.float32)))  # compile + drain
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = run(x0)
            float(jnp.sum(out.astype(jnp.float32)))
            best = min(best, (time.perf_counter() - t0) / n)
        # HBM high-water of the compiled loop program through the ONE
        # memflow analyzer (``hbm`` keeps the historical
        # temp+arg+output accounting; ``peak`` is the sweep-wide
        # hbm_peak convention, temp+arg only)
        from mxtpu import analysis
        try:
            mem = analysis.mem_stats(run.lower(x0).compile())
        except Exception:
            mem = None
        if mem is None:
            hbm = peak = None
        else:
            hbm = mem["hbm_peak"] + mem.get("output_size_in_bytes", 0)
            peak = mem["hbm_peak"]
        return best, hbm, peak

    def moe_out(xx):
        # aux (load-balance loss) is dropped: the router itself stays
        # live through the dispatch/combine einsums y depends on
        return layer.apply(params, xx)[0]

    t_moe, hbm_moe, peak_moe = _chain(moe_out, x, iters, "moe")

    # dense-FFN equivalent: one D→H→D over the same tokens
    k = jax.random.PRNGKey(1)
    w1 = (jax.random.normal(k, (D, H)) / np.sqrt(D)).astype(jnp.bfloat16)
    w2 = (jax.random.normal(k, (H, D)) / np.sqrt(H)).astype(jnp.bfloat16)
    t_dense, hbm_dense, _ = _chain(
        lambda xx: jax.nn.relu(xx @ w1) @ w2, x, iters, "dense")

    # experts-only: the same per-expert GEMMs on pre-dispatched
    # activations — the difference to t_moe is the router + the two
    # dispatch/combine einsums
    _, w1e, b1e, w2e, b2e = params
    w1c = w1e.astype(jnp.bfloat16)
    w2c = w2e.astype(jnp.bfloat16)
    xe = jnp.asarray(rng.randn(E, C, D).astype(np.float32),
                     dtype=jnp.bfloat16)

    def experts_only(v):
        h = jnp.einsum("ecd,edh->ech", v, w1c) \
            + b1e.astype(jnp.bfloat16)[:, None, :]
        return jnp.einsum("ech,ehd->ecd", jax.nn.relu(h), w2c) \
            + b2e.astype(jnp.bfloat16)[:, None, :]

    t_exp, _, _ = _chain(experts_only, xe, iters, "experts")

    vals = [T / t_moe]
    stats = {"best": max(vals), "median": vals[0], "n": 1,
             "spread": 0.0, "runs": [round(v, 1) for v in vals],
             "info": {
                 "hbm_peak": peak_moe,
                 "shape": {"T": T, "E": E, "D": D, "H": H,
                           "capacity": C, "dtype": "bfloat16"},
                 "dense_ffn_tokens_per_sec": round(T / t_dense, 1),
                 "vs_dense_ffn": round(t_dense / t_moe, 3),
                 "hbm_highwater_bytes": hbm_moe,
                 "dense_hbm_highwater_bytes": hbm_dense,
                 "router_dispatch_share": round(
                     max(0.0, (t_moe - t_exp)) / t_moe, 3),
             }}
    return stats, _METRIC_NAMES["moe_ffn"], "tokens/sec"


def bench_bert_zero(batch_size=32, seq_len=128, warmup=2, iters=8,
                    amp=None):
    """ZeRO-1 ablation (on-demand, MXTPU_BENCH_MODEL=bert_zero): the
    BERT-Large adam step replicated vs ZeRO-1 sharded optimizer states
    (``mxtpu.parallel`` TrainStep docs) on a dp mesh over every local
    device, dp = min(8, devices).  The primary value is the ZeRO
    variant's tokens/sec when a dp mesh exists (else the replicated
    number); ``details`` carries both variants' step rates and
    per-device optimizer-state bytes.  When fewer than 8 devices are
    attached the dp=8 footprint is additionally PLANNED from
    ``plan_zero_buckets`` geometry — pure arithmetic, the same
    provenance as BASELINE.md's optimizer-memory table."""
    import jax

    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models.transformer import bert_large

    V = 30522
    dtype = None if amp else knobs.get("MXTPU_BENCH_DTYPE") or None
    rng = np.random.RandomState(0)
    toks = nd.array(rng.randint(0, V, (batch_size, seq_len))
                    .astype(np.float32))
    tokens_per_batch = batch_size * seq_len

    def mlm_loss(pred, y):
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, V)), y.reshape((-1,)))

    def _variant(mesh, zero):
        net = bert_large(vocab_size=V, max_length=seq_len, dropout=0.1)
        net.initialize(init="xavier")
        step = parallel.build_train_step(
            net, mlm_loss, "adam", {"learning_rate": 1e-4}, mesh=mesh,
            compute_dtype=dtype, cast_batch=False, zero=zero, amp=amp)
        stats = _measure(step, toks, toks, warmup, iters,
                         tokens_per_batch, repeats=3)
        return stats, step

    dp = min(8, jax.device_count())
    repl, rstep = _variant(None, None)
    info = {
        "dp": dp,
        "hbm_peak": (repl.get("info") or {}).get("hbm_peak"),
        "replicated_hbm_peak": (repl.get("info") or {}).get("hbm_peak"),
        "replicated_tokens_per_sec": round(repl["best"], 1),
        "replicated_opt_state_bytes": rstep.opt_state_bytes(),
    }
    stats = repl
    if dp > 1:
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:dp]), ("dp",))
        zstats, zstep = _variant(mesh, 1)
        info.update({
            # hbm_peak reports the primary (ZeRO) program
            "hbm_peak": (zstats.get("info") or {}).get("hbm_peak"),
            "zero_tokens_per_sec": round(zstats["best"], 1),
            "zero_opt_state_bytes_per_device": zstep.opt_state_bytes(),
            "zero_vs_replicated": round(zstats["best"] / repl["best"],
                                        3),
        })
        stats = zstats
    if dp < 8:
        from mxtpu.analysis import memflow
        sigs = [(tuple(rstep._params[i]._data._data.shape),
                 str(rstep._params[i]._data._data.dtype))
                for i in rstep._train_idx]
        # adam: two f32 state leaves (m, v) per bucket, dp-sharded —
        # the same plan_zero_buckets oracle the mem ledgers commit
        info["zero_dp8_planned_opt_state_bytes_per_device"] = \
            memflow.planned_shard_bytes(sigs, 8)
    stats = dict(stats)
    stats["info"] = info
    return stats, _METRIC_NAMES["bert_zero"], "tokens/sec"


def _contract_comm_bytes():
    """Reduce-scatter/all-gather byte counts from the committed
    bert_zero contracts — the f32 program's compiled collectives vs
    the AMP program's AS-WRITTEN collectives (the CPU backend's
    float-normalization pass rewrites bf16 collectives back to f32 in
    compiled text, so the as-written level is where the wire dtype
    lives; see tools/hlocheck/targets.py::bert_zero_amp).  These are
    the tiny pinned stand-in programs, not the bench model — the
    RATIO is the scale-invariant contract property being reported."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, "contracts",
                               "bert_zero.json")) as f:
            f32 = json.load(f)["programs"]["train_step"]["collectives"]
        with open(os.path.join(here, "contracts",
                               "bert_zero_amp.json")) as f:
            amp = json.load(f)["programs"]["train_step_as_written"][
                "collectives"]
    except (OSError, KeyError, ValueError):
        return None
    rs_f, rs_a = f32["reduce-scatter"], amp["reduce-scatter"]
    return {"f32_reduce_scatter_bytes": rs_f["bytes"],
            "amp_reduce_scatter_bytes": rs_a["bytes"],
            "reduce_scatter_bytes_ratio": round(
                rs_a["bytes"] / rs_f["bytes"], 3),
            "f32_all_gather_bytes": f32["all-gather"]["bytes"],
            "amp_all_gather_bytes": amp["all-gather"]["bytes"],
            "counts_equal": rs_f["count"] == rs_a["count"]}


def bench_amp_pair(key, base_fn, **kw):
    """One --amp row: the base workload measured twice — AMP off
    (the row's existing recipe) and AMP on (``mxtpu.amp``: bf16
    storage + autocast + f32 masters + loss scaling) — reported side
    by side.  The primary value is the AMP-on rate; ``details``
    carries both variants' rate/MFU/HBM and, for the ZeRO pair, the
    contract-pinned comm-byte split."""
    off, _, unit = base_fn(amp=None, **kw)
    on, _, _ = base_fn(amp=True, **kw)
    peak = _peak_flops()
    base_key = key[: -len("_amp")]

    def _side(stats):
        return {"best": round(stats["best"], 1),
                "median": round(stats["median"], 1),
                "mfu": _mfu(base_key, stats["best"], peak),
                "hbm_peak": (stats.get("info") or {}).get("hbm_peak")}

    info = dict(on.get("info") or {})
    info.update({
        "amp_off": _side(off), "amp_on": _side(on),
        "amp_speedup": round(on["best"] / off["best"], 3),
    })
    if base_key == "bert_zero":
        comm = _contract_comm_bytes()
        if comm:
            info["comm_contract"] = comm
    stats = dict(on)
    stats["info"] = info
    return stats, _METRIC_NAMES[key], unit


def bench_serving_bert(seq_len=64, max_batch=8, repeats=3):
    """mxtpu.serving end-to-end row (on-demand,
    MXTPU_BENCH_MODEL=serving_bert): a small exported BERT behind
    ``InferenceServer`` under OPEN-LOOP arrival (requests submitted on
    a fixed schedule regardless of completions — the serving-honest
    load model; a closed loop self-throttles and hides queueing).

    The primary value is sustained served req/sec at saturation
    (offered 1.5x the raw AOT back-to-back capacity of the largest
    bucket, single-length traffic), best of ``repeats`` — the number
    the within-15%-of-raw acceptance check in BASELINE.md reads.
    ``details`` carries the raw back-to-back rate, served/raw ratio,
    and a mixed-length latency sweep at two sub-saturation arrival
    rates with p50/p95/p99, batch fill-rate and peak queue depth."""
    import tempfile
    import threading  # noqa: F401 — server worker threads

    from mxtpu import nd
    from mxtpu.models.transformer import BERTModel
    from mxtpu.serving import InferenceServer, ModelRunner, ServerBusy

    V = 8192
    net = BERTModel(V, 256, 1024, 4, 4, max_length=seq_len,
                    dropout=0.0)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    net(nd.array(rng.randint(0, V, (1, seq_len))
                 .astype(np.float32)))          # materialize params
    d = tempfile.mkdtemp(prefix="mxtpu_bench_rec_serving_")
    sym_file, param_file = net.export(os.path.join(d, "bert"))
    runner = ModelRunner.from_export(
        sym_file, param_file, input_specs={"data": (None,)},
        seq_buckets=[seq_len // 2, seq_len], max_batch_size=max_batch)
    t0 = time.perf_counter()
    runner.warmup()
    compile_s = time.perf_counter() - t0

    # raw AOT back-to-back capacity of the saturation bucket — the
    # denominator of the batcher-overhead acceptance check
    bucket = (max_batch, seq_len)
    full = [{"data": rng.randint(0, V, (seq_len,)).astype(np.float32)}
            for _ in range(max_batch)]
    vals = runner._pad_stack(full, bucket)
    np.asarray(runner.run_raw(vals, bucket)[0])       # settle
    raw_iters = 30
    t0 = time.perf_counter()
    for _ in range(raw_iters):
        outs = runner.run_raw(vals, bucket)
    np.asarray(outs[0])                               # sync
    raw_rps = max_batch * raw_iters / (time.perf_counter() - t0)

    def open_loop(offered_rps, lens, n_req, timeout_s=None):
        """One fresh endpoint, ``n_req`` arrivals at 1/offered_rps
        spacing; returns (served_rps, stats snapshot, rejected)."""
        payloads = [rng.randint(0, V, (lens[i % len(lens)],))
                    .astype(np.float32) for i in range(n_req)]
        interval = 1.0 / offered_rps
        with InferenceServer() as server:
            server.register("bert", runner, max_queue_delay_us=2000)
            reqs, rejected = [], 0
            t_start = time.perf_counter()
            for i, row in enumerate(payloads):
                lag = t_start + i * interval - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                try:
                    reqs.append(server.submit(
                        "bert", {"data": row}, timeout_s=timeout_s))
                except ServerBusy:
                    rejected += 1   # load shed at the edge, open loop
            done = 0
            for r in reqs:
                try:
                    r.result(timeout=60.0)
                    done += 1
                except Exception:   # noqa: BLE001 — timeouts counted
                    pass            # via the endpoint snapshot
            served = done / (time.perf_counter() - t_start)
            for _ in range(200):    # let worker counters settle
                snap = server.stats("bert")
                if snap["completed"] >= done:
                    break
                time.sleep(0.01)
        return served, snap, rejected

    # -- mixed-length latency sweep at two sub-saturation rates --------
    sweep_lens = [int(v) for v in
                  rng.randint(seq_len // 4, seq_len + 1, 64)]
    sweep = {}
    for frac in (0.25, 0.5):
        offered = max(frac * raw_rps, 10.0)
        n_req = int(min(600, max(60, offered * 2.0)))
        served, snap, rejected = open_loop(offered, sweep_lens, n_req)
        sweep[f"offered_{frac:.2f}x_raw"] = {
            "offered_rps": round(offered, 1),
            "served_rps": round(served, 1),
            "p50_ms": snap["latency_ms"]["p50"],
            "p95_ms": snap["latency_ms"]["p95"],
            "p99_ms": snap["latency_ms"]["p99"],
            "batch_fill_rate": snap["batch_fill_rate"],
            "mean_batch_size": snap["mean_batch_size"],
            "peak_queue_depth": snap["peak_queue_depth"],
            "rejected": rejected,
            "timed_out": snap["timed_out"],
        }

    # -- saturation: sustained server throughput vs raw AOT ------------
    sat_vals, sat_snap = [], None
    for _ in range(repeats):
        offered = 1.5 * raw_rps
        n_req = int(min(2000, max(120, raw_rps * 1.5)))
        served, sat_snap, _ = open_loop(offered, [seq_len], n_req)
        sat_vals.append(served)
    sat_vals.sort()
    median = sat_vals[len(sat_vals) // 2] if len(sat_vals) % 2 else \
        0.5 * (sat_vals[len(sat_vals) // 2 - 1]
               + sat_vals[len(sat_vals) // 2])
    stats = {
        "best": max(sat_vals), "median": median, "n": len(sat_vals),
        "spread": round((max(sat_vals) - min(sat_vals)) / median, 4),
        "runs": [round(v, 1) for v in sat_vals],
        "info": {
            "hbm_peak": None,   # inference path; no scan program
            "raw_back_to_back_rps": round(raw_rps, 1),
            "served_vs_raw": round(max(sat_vals) / raw_rps, 4),
            "saturated_fill_rate": sat_snap["batch_fill_rate"],
            "saturated_peak_queue_depth": sat_snap["peak_queue_depth"],
            "compile_seconds_total": round(compile_s, 2),
            "compiled_buckets": runner.num_compiled(),
            "max_batch_size": max_batch,
            "seq_buckets": list(runner.seq_buckets),
            "weight_mb": round(runner.weight_bytes() / 2 ** 20, 1),
            "arrival_sweep": sweep,
        },
    }
    return stats, _METRIC_NAMES["serving_bert"], "req/sec"


def bench_serving_fleet(n_workers=3, n_req=600, repeats=3):
    """Fault-tolerant fleet soak row (on-demand,
    MXTPU_BENCH_MODEL=serving_fleet): open-loop traffic against a
    :class:`FleetRouter` over ``n_workers`` workers while one worker
    is KILLED mid-run (preemption) and a warm replacement is attached
    from the victim's compiled-ladder handoff.

    The acceptance contract (ISSUE 7): ZERO in-deadline requests
    dropped or hanging across the kill/restart — every submitted
    request either completes with a correct result or fails its own
    deadline, none blocks forever.  The primary value is sustained
    served req/sec THROUGH the failure; ``details`` carries
    p50/p95/p99 end-to-end latency and the recovery counters
    (retries, requeues, deaths, drains) the router aggregates."""
    from mxtpu import obs
    from mxtpu import symbol as sym
    from mxtpu.serving import (FleetRouter, FleetWorker, ModelRunner,
                               RequestTimeout)

    dim, max_batch = 64, 8
    w = np.arange(1, dim + 1, dtype=np.float32)
    rng = np.random.RandomState(0)

    def make_runner():
        return ModelRunner(sym.var("data") * sym.var("w"), {"w": w},
                           {"data": (dim,)}, max_batch_size=max_batch)

    # raw capacity of one worker's saturation bucket: sets the offered
    # rate so the fleet runs loaded but not in permanent shed
    probe = make_runner()
    bucket = (max_batch, None)
    rows = [{"data": rng.rand(dim).astype(np.float32)}
            for _ in range(max_batch)]
    vals = probe._pad_stack(rows, bucket)
    np.asarray(probe.run_raw(vals, bucket)[0])        # compile+settle
    t0 = time.perf_counter()
    raw_iters = 50
    for _ in range(raw_iters):
        outs = probe.run_raw(vals, bucket)
    np.asarray(outs[0])
    raw_rps = max_batch * raw_iters / (time.perf_counter() - t0)

    def soak():
        canary = {"data": np.ones(dim, np.float32)}
        router = FleetRouter(threaded=True, tick_s=0.002,
                             canary=canary, canary_expect=[w.copy()],
                             canary_interval_s=0.25,
                             canary_timeout_s=2.0)
        offered = min(0.5 * n_workers * raw_rps, 4000.0)
        interval = 1.0 / offered
        kill_at, replace_at = n_req // 3, n_req // 2
        # sampler-overhead row (ISSUE 14): when obs is on the soak
        # runs with the full operator stack live — 100 Hz sampler +
        # availability SLO ticking inside the router loop.  Under
        # MXTPU_OBS=0 both factories hand back the shared no-ops and
        # attach_slo refuses them, so that run is the control.
        eng = obs.slo_engine(
            [obs.AvailabilitySLO("fleet_avail", objective=0.999)],
            obs.sampler(period_us=10_000.0))
        router.attach_slo(eng)
        with router:
            for i in range(n_workers):
                router.add_worker(FleetWorker(
                    make_runner(), f"w{i}", max_queue_delay_us=2000.0))
            handoff = router._workers["w0"].handoff()
            reqs, vecs = [], []
            t_start = time.perf_counter()
            for i in range(n_req):
                lag = t_start + i * interval - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                if i == kill_at:
                    router.kill("w0")                 # preemption
                if i == replace_at:
                    router.add_worker(FleetWorker(
                        make_runner(), "wR",
                        max_queue_delay_us=2000.0), warm_from=handoff)
                vec = rng.rand(dim).astype(np.float32)
                vecs.append(vec)
                reqs.append(router.submit({"data": vec},
                                          timeout_s=30.0))
            done, dropped, hung, wrong = 0, 0, 0, 0
            for vec, r in zip(vecs, reqs):
                try:
                    out = r.result(timeout=30.0)[0]
                    done += 1
                    if not np.allclose(out, vec * w, rtol=1e-5):
                        wrong += 1
                except RequestTimeout:
                    hung += 1      # result() wait expired = a hang
                except Exception:  # noqa: BLE001 — anything terminal
                    dropped += 1   # inside the 30s deadline = a drop
            served = done / (time.perf_counter() - t_start)
            snap = router.fleet_stats()
        return served, snap, dropped, hung, wrong

    vals_run, last = [], None
    dropped = hung = wrong = 0
    for _ in range(repeats):
        served, last, d, h, wr = soak()
        vals_run.append(served)
        dropped += d
        hung += h
        wrong += wr
    vals_run.sort()
    median = vals_run[len(vals_run) // 2] if len(vals_run) % 2 else \
        0.5 * (vals_run[len(vals_run) // 2 - 1]
               + vals_run[len(vals_run) // 2])
    ex = last["extras"]
    stats = {
        "best": max(vals_run), "median": median, "n": len(vals_run),
        "spread": round((max(vals_run) - min(vals_run)) / median, 4),
        "runs": [round(v, 1) for v in vals_run],
        "info": {
            "hbm_peak": None,      # inference path; no scan program
            "in_deadline_dropped": dropped,   # the contract: all zero
            "in_deadline_hung": hung,
            "wrong_results": wrong,
            "p50_ms": last["latency_ms"]["p50"],
            "p95_ms": last["latency_ms"]["p95"],
            "p99_ms": last["latency_ms"]["p99"],
            "retries": ex.get("retries", 0),
            "requeues": ex.get("requeues", 0),
            "deaths": ex.get("deaths", 0),
            "hedges_won": ex.get("hedges_won", 0),
            "timed_out": last["timed_out"],
            "workers": {n: s["state"]
                        for n, s in last["workers"].items()},
            "raw_back_to_back_rps": round(raw_rps, 1),
            "n_workers": n_workers,
            "n_req_per_run": n_req,
            "obs_live": bool(obs.enabled()),   # sampler+SLO attached?
        },
    }
    return stats, _METRIC_NAMES["serving_fleet"], "req/sec"


def bench_serving_autoscale(n_burst=480, repeats=3):
    """Fleet control-plane row (on-demand,
    MXTPU_BENCH_MODEL=serving_autoscale): a traffic burst against
    (a) a STATIC single-worker fleet and (b) the same fleet with an
    :class:`Autoscaler` (min=1, max=3) driven by the router tick, both
    with predictive admission control on.  The contract (ISSUE 11):
    the autoscaled fleet absorbs the burst inside the SLO that the
    static fleet provably cannot meet, sheds nothing, and every
    replica comes up warm from the donor's compiled-ladder handoff.

    Vehicle: per-batch service time is scripted through the fault
    harness (``SlowExec(service_s, time.sleep)`` — the same injector
    tier-1 recovery tests use) because worker replicas only buy wall
    time when service parallelizes, and on a 1-core CPU box real
    compute cannot.  Sleeps do.  Everything else is real: the
    measured absorb time includes genuine scale-up reaction latency,
    replica ladder compiles, dispatch, retry and admission decisions.
    The primary value is the autoscaled fleet's burst absorb rate
    (served req/sec over the time for ALL submitted requests to reach
    a terminal state); ``details`` carries the static-N comparison —
    absorb seconds, SLO violation rate (timeouts), admission-shed
    counts — plus the scale-up count and warm-compile evidence."""
    from mxtpu import symbol as sym
    from mxtpu.serving import (Autoscaler, FaultPlan, FleetRouter,
                               FleetWorker, ModelRunner, ServerBusy,
                               SlowExec)

    dim, max_batch = 64, 8
    service_s = 0.02           # scripted per-batch service time
    w = np.arange(1, dim + 1, dtype=np.float32)
    rng = np.random.RandomState(0)

    # the burst floor: a single worker needs at least this long
    static_floor = (n_burst + max_batch - 1) // max_batch * service_s
    slo_s = 0.6 * static_floor      # feasible only by scaling out
    submit_window = 0.25 * static_floor   # paced, not instantaneous —
    # later submissions see a live ETA, so admission has signal

    def make_worker(name):
        runner = ModelRunner(sym.var("data") * sym.var("w"), {"w": w},
                             {"data": (dim,)},
                             max_batch_size=max_batch)
        return FleetWorker(runner, name, max_queue_delay_us=2000.0,
                           faults=FaultPlan(
                               SlowExec(service_s, time.sleep)))

    def run(autoscale):
        router = FleetRouter(threaded=True, tick_s=0.002, canary=None,
                             admission=True, admission_margin=1.0)
        shed = 0
        with router:
            w0 = make_worker("w0")
            router.add_worker(w0)
            w0.runner.warmup()
            scaler = None
            if autoscale:
                scaler = Autoscaler(
                    router, make_worker, min_workers=1, max_workers=3,
                    up_depth=2.0 * max_batch, down_depth=0.5,
                    breach_ticks=2, cooldown_s=0.05)
                router.add_controller(scaler.tick)
            interval = submit_window / n_burst
            reqs = []
            t0 = time.perf_counter()
            for i in range(n_burst):
                lag = t0 + i * interval - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                try:
                    reqs.append(router.submit(
                        {"data": rng.rand(dim).astype(np.float32)},
                        timeout_s=slo_s))
                except ServerBusy:
                    shed += 1
            served, violated = 0, 0
            for r in reqs:
                try:
                    r.result(timeout=slo_s + 10.0)
                    served += 1
                except Exception:  # noqa: BLE001 — timeout = SLO miss
                    violated += 1
            absorb = time.perf_counter() - t0
            members = router.members()
            cold = sum(1 for m in members
                       if m.runner.num_compiled()
                       < w0.runner.num_compiled())
            snap = router.fleet_stats()
        ex = snap["extras"]
        return {
            "absorb_s": round(absorb, 3),
            "served": served,
            "slo_violations": violated,
            "shed_admission": shed + ex.get("shed_admission", 0),
            "shed_backlog": ex.get("shed_backlog", 0),
            "n_workers_final": len(members),
            "cold_replicas": cold,
            "scale_ups": scaler.snapshot()["scale_ups"]
            if scaler else 0,
        }

    vals_run, statics, autos = [], [], []
    for _ in range(repeats):
        statics.append(run(autoscale=False))
        a = run(autoscale=True)
        autos.append(a)
        vals_run.append(a["served"] / a["absorb_s"])
    vals_run.sort()
    median = vals_run[len(vals_run) // 2] if len(vals_run) % 2 else \
        0.5 * (vals_run[len(vals_run) // 2 - 1]
               + vals_run[len(vals_run) // 2])
    mid_s = sorted(statics, key=lambda d: d["absorb_s"])[len(statics)
                                                        // 2]
    mid_a = sorted(autos, key=lambda d: d["absorb_s"])[len(autos) // 2]
    stats = {
        "best": max(vals_run), "median": median, "n": len(vals_run),
        "spread": round((max(vals_run) - min(vals_run)) / median, 4),
        "runs": [round(v, 1) for v in vals_run],
        "info": {
            "hbm_peak": None,       # inference path; no scan program
            "n_burst": n_burst,
            "service_s_per_batch": service_s,
            "slo_s": round(slo_s, 3),
            "static_floor_s": round(static_floor, 3),
            "static": mid_s,        # median-absorb static run
            "autoscaled": mid_a,    # median-absorb autoscaled run
            "absorb_speedup": round(
                mid_s["absorb_s"] / mid_a["absorb_s"], 2),
            "static_slo_violation_rate": round(
                (mid_s["slo_violations"] + mid_s["shed_admission"])
                / n_burst, 4),
            "auto_slo_violation_rate": round(
                (mid_a["slo_violations"] + mid_a["shed_admission"])
                / n_burst, 4),
        },
    }
    return stats, _METRIC_NAMES["serving_autoscale"], "req/sec"


def bench_serving_coldstart(seq_len=64, max_batch=8, repeats=2):
    """Persistent compile-cache row (on-demand,
    MXTPU_BENCH_MODEL=serving_coldstart): the cold vs disk-warmed
    cold-start split (ISSUE 13).  A small exported BERT's full bucket
    ladder is warmed twice — once against an empty cache root (every
    bucket is an XLA compile + a store) and once as a fresh runner
    against the now-populated root (every bucket is a verified disk
    load, ``num_compiled`` asserted zero-compile) — plus the
    operator-facing number: time-to-first-served-request for a fresh
    process in each mode.

    The primary value is the full-ladder warmup speedup (cold seconds
    / disk-warmed seconds, best of ``repeats``); ``details`` carries
    the four raw timings BASELINE.md splits out."""
    import shutil
    import tempfile

    from mxtpu import nd
    from mxtpu.cache import ExecutableCache
    from mxtpu.models.transformer import BERTModel
    from mxtpu.serving import ModelRunner

    V = 8192
    net = BERTModel(V, 128, 512, 2, 2, max_length=seq_len,
                    dropout=0.0)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    net(nd.array(rng.randint(0, V, (1, seq_len))
                 .astype(np.float32)))          # materialize params
    d = tempfile.mkdtemp(prefix="mxtpu_bench_rec_coldstart_")
    sym_file, param_file = net.export(os.path.join(d, "bert"))

    def make_runner(root):
        return ModelRunner.from_export(
            sym_file, param_file, input_specs={"data": (None,)},
            seq_buckets=[seq_len], max_batch_size=max_batch,
            cache=ExecutableCache(root))

    req = [{"data": rng.randint(0, V, (seq_len,))
            .astype(np.float32)}]

    def first_request_s(runner):
        bucket = runner.bucket_for(1, seq_len)
        vals = runner._pad_stack(req, bucket)
        t0 = time.perf_counter()
        np.asarray(runner.run_raw(vals, bucket)[0])
        return time.perf_counter() - t0

    runs = []
    for _ in range(repeats):
        root = os.path.join(d, f"cache{len(runs)}")
        cold = make_runner(root)
        t0 = time.perf_counter()
        cold.warmup()
        cold_warmup_s = time.perf_counter() - t0
        nbuckets = len(cold.buckets())
        assert cold.num_compiled() == nbuckets

        # a second fresh "process" against the populated root: the
        # whole ladder must come off disk with zero XLA compiles
        warm = make_runner(root)
        t0 = time.perf_counter()
        warm.warmup()
        warm_warmup_s = time.perf_counter() - t0
        assert warm.num_compiled() == nbuckets
        assert warm._cache.stats()["hit"] == nbuckets, \
            warm._cache.stats()

        # operator number: first served request, fresh runner each
        cold_first = make_runner(os.path.join(d, f"cachef{len(runs)}"))
        cold_first_s = first_request_s(cold_first)
        warm_first = make_runner(root)
        warm_first_s = first_request_s(warm_first)
        runs.append({"cold_warmup_s": round(cold_warmup_s, 3),
                     "warm_warmup_s": round(warm_warmup_s, 3),
                     "cold_first_req_s": round(cold_first_s, 3),
                     "warm_first_req_s": round(warm_first_s, 3),
                     "buckets": nbuckets})
    shutil.rmtree(d, ignore_errors=True)
    vals = sorted(r["cold_warmup_s"] / r["warm_warmup_s"]
                  for r in runs)
    median = vals[len(vals) // 2] if len(vals) % 2 else \
        0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
    best_run = max(runs, key=lambda r: r["cold_warmup_s"]
                   / r["warm_warmup_s"])
    stats = {
        "best": max(vals), "median": median, "n": len(vals),
        "spread": round((max(vals) - min(vals)) / median, 4),
        "runs": [round(v, 2) for v in vals],
        "info": {"hbm_peak": None,  # inference path; no scan program
                 "best_run": best_run, "all_runs": runs},
    }
    return stats, _METRIC_NAMES["serving_coldstart"], "x"


def bench_serving_bert_int8(seq_len=64, max_batch=8, repeats=3,
                            iters=30):
    """INT8 serving ablation row (on-demand,
    MXTPU_BENCH_MODEL=serving_bert_int8): the serving_bert model
    exported once and served three ways over the same saturation
    bucket — f32, bf16 (mxtpu.amp) and int8 (mxtpu.quant,
    entropy-calibrated on seeded batches) — raw AOT back-to-back
    throughput and per-request p50/p95 per arm.

    The primary value is the int8 arm's raw req/sec (best of
    ``repeats``); ``details`` carries the int8-vs-f32 and
    int8-vs-bf16 speedups, each reduced-precision arm's max-|Δlogit|
    vs f32 on a fixed eval batch, and the s8×s8→s32 contraction
    census of the int8 bucket's lowering — the proof the arm actually
    quantized (on the CPU backend int8 GEMMs may not run faster, so
    the census, not the ratio, is the floor evidence; the hard
    accuracy gate on this shape lives in tests/test_quant.py)."""
    import tempfile

    from mxtpu import nd
    from mxtpu.analysis import dtypeflow
    from mxtpu.models.transformer import BERTModel
    from mxtpu.serving import ModelRunner

    V = 8192
    net = BERTModel(V, 256, 1024, 4, 4, max_length=seq_len,
                    dropout=0.0)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    net(nd.array(rng.randint(0, V, (1, seq_len))
                 .astype(np.float32)))          # materialize params
    d = tempfile.mkdtemp(prefix="mxtpu_bench_rec_serving_int8_")
    sym_file, param_file = net.export(os.path.join(d, "bert"))

    bucket = (max_batch, seq_len)
    calib = [{"data": rng.randint(0, V, (max_batch, seq_len))
              .astype(np.float32)} for _ in range(4)]
    eval_rows = [{"data": rng.randint(0, V, (seq_len,))
                  .astype(np.float32)} for _ in range(max_batch)]

    def make_runner(arm):
        runner = ModelRunner.from_export(
            sym_file, param_file, input_specs={"data": (None,)},
            seq_buckets=[seq_len], max_batch_size=max_batch,
            amp=(arm == "bf16") or None,
            quant=(arm == "int8") or None)
        if arm == "int8":
            runner.calibrate(calib, mode="entropy")
        return runner

    arms = {}
    f32_logits = None
    int8_census = None
    for arm in ("f32", "bf16", "int8"):
        runner = make_runner(arm)
        if arm == "int8":
            int8_census = dtypeflow.int8_contraction_census(
                runner.lowered_program_text(bucket))
        t0 = time.perf_counter()
        runner.warmup([bucket])     # one bucket per arm — cheap row
        compile_s = time.perf_counter() - t0
        vals = runner._pad_stack(eval_rows, bucket)
        logits = np.asarray(runner.run_raw(vals, bucket)[0],
                            np.float32)         # settle + eval batch
        if arm == "f32":
            f32_logits = logits
        best = 0.0
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(iters):
                outs = runner.run_raw(vals, bucket)
            np.asarray(outs[0])                 # sync
            best = max(best,
                       max_batch * iters / (time.perf_counter() - t0))
        lats = []
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(runner.run_raw(vals, bucket)[0])
            lats.append((time.perf_counter() - t0) * 1e3)
        lats.sort()
        arms[arm] = {
            "raw_rps": round(best, 1),
            "p50_ms": round(lats[len(lats) // 2], 3),
            "p95_ms": round(
                lats[min(len(lats) - 1,
                         int(round(0.95 * (len(lats) - 1))))], 3),
            "compile_seconds": round(compile_s, 2),
            "max_abs_logit_delta_vs_f32": None if arm == "f32" else
                round(float(np.abs(logits - f32_logits).max()), 5),
            "weight_mb": round(runner.weight_bytes() / 2 ** 20, 1),
        }
    stats = {
        "best": arms["int8"]["raw_rps"],
        "median": arms["int8"]["raw_rps"], "n": repeats,
        "spread": 0.0, "runs": [arms["int8"]["raw_rps"]],
        "info": {
            "hbm_peak": None,   # inference path; no scan program
            "arms": arms,
            "int8_vs_f32": round(
                arms["int8"]["raw_rps"] / arms["f32"]["raw_rps"], 4),
            "int8_vs_bf16": round(
                arms["int8"]["raw_rps"] / arms["bf16"]["raw_rps"], 4),
            "int8_contraction_census": int8_census,
            "f32_logit_scale": round(
                float(np.abs(f32_logits).max()), 4),
        },
    }
    return stats, _METRIC_NAMES["serving_bert_int8"], "req/sec"


def bench_serving_generate(n_req=8, max_tokens=24, repeats=3):
    """Generation serving row (on-demand,
    MXTPU_BENCH_MODEL=serving_generate): KV-cache incremental decode
    (ISSUE 19) at saturation — ``n_req`` greedy requests continuously
    batched onto the lane table of a small exported causal BERT,
    stepped until drained.

    The primary value is decode tokens/sec at saturation (best of
    ``repeats``; warm ladder — compile time is the coldstart row's
    job).  ``details`` carries p50/p95 TTFT and per-token latency
    measured at the stream callback (the timestamps an SSE client
    would see, BASELINE.md token-latency methodology), and the
    honesty denominator: a naive re-prefill-every-token baseline that
    generates the same greedy continuation by running a full prefill
    over the growing sequence for each token — the speedup over that
    is what the KV cache actually buys."""
    import tempfile

    from mxtpu import nd
    from mxtpu.models.transformer import BERTModel
    from mxtpu.serving import GenerateBatcher, GenerateRunner

    V, LANES, L = 8192, 4, 64
    prompt_len = 8
    net = BERTModel(V, 128, 512, 2, 2, max_length=L, dropout=0.0,
                    use_token_type=False, causal=True)
    net.initialize(init="xavier")
    net.hybridize()
    rng = np.random.RandomState(0)
    tokens = nd.array(rng.randint(0, V, (2, 3)).astype(np.float32))
    stepv = nd.array(np.zeros(2, np.float32))
    kv0 = nd.array(np.zeros(net.kv_cache_spec(2), np.float32))
    net(tokens, stepv, kv0)                     # incremental trace
    d = tempfile.mkdtemp(prefix="mxtpu_bench_rec_generate_")
    sym_file, param_file = net.export(os.path.join(d, "genbert"))
    runner = GenerateRunner.from_export(
        sym_file, param_file, net.kv_cache_spec(LANES, L),
        prompt_buckets=(16, 32), cache=None)
    t0 = time.perf_counter()
    runner.warmup()
    warmup_s = time.perf_counter() - t0

    prompts = [list(rng.randint(1, V, prompt_len).astype(int))
               for _ in range(n_req)]

    def saturation_run():
        """All n_req requests through one batcher; the stream
        callback records TTFT and inter-token gaps per request."""
        batcher = GenerateBatcher(runner)
        marks = [[] for _ in prompts]           # perf_counter stamps
        reqs = []
        t_submit = time.perf_counter()
        for i, p in enumerate(prompts):
            reqs.append(batcher.submit(
                p, max_tokens=max_tokens,
                on_token=lambda t, idx, m=marks[i]:
                    m.append(time.perf_counter())))
        while not batcher.drain():
            batcher.step()
        elapsed = time.perf_counter() - t_submit
        batcher.close()
        total = sum(len(r.result(0)) for r in reqs)
        ttfts = [(m[0] - t_submit) * 1e3 for m in marks if m]
        gaps = [(b - a) * 1e3 for m in marks
                for a, b in zip(m, m[1:])]
        return total / elapsed, ttfts, gaps

    def pct(vals, q):
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1,
                              int(round(q * (len(vals) - 1))))], 3)

    best, ttfts, gaps = 0.0, [], []
    runs = []
    for _ in range(repeats):
        rate, t, g = saturation_run()
        runs.append(round(rate, 1))
        ttfts += t
        gaps += g
        best = max(best, rate)

    # naive denominator: the SAME greedy continuation produced by
    # re-running a full prefill over the growing sequence per token
    # (what serving without a KV cache degenerates to); single
    # request — the naive path has no lane table to batch onto.
    b = runner.batch_rung_for(1)
    kv = runner.new_cache()
    seq = list(prompts[0])
    t0 = time.perf_counter()
    while len(seq) - prompt_len < max_tokens:
        s = runner.prompt_bucket_for(len(seq))
        tok = np.zeros((b, s), np.float32)
        tok[0, :len(seq)] = seq
        logits, kv = runner.prefill(
            tok, np.zeros(b, np.float32),
            np.full(b, LANES, np.float32), kv)  # scratch slot
        seq.append(int(np.argmax(logits[0, len(seq) - 1])))
    naive_rate = max_tokens / (time.perf_counter() - t0)

    stats = {
        "best": round(best, 1), "median": sorted(runs)[len(runs) // 2],
        "n": repeats, "spread": round((max(runs) - min(runs))
                                      / max(runs), 4),
        "runs": runs,
        "info": {
            "hbm_peak": None,   # inference path; no scan program
            "ttft_ms": {"p50": pct(ttfts, 0.5),
                        "p95": pct(ttfts, 0.95)},
            "per_token_ms": {"p50": pct(gaps, 0.5),
                             "p95": pct(gaps, 0.95)},
            "naive_reprefill_tok_per_sec": round(naive_rate, 1),
            "kv_vs_naive": round(best / naive_rate, 2),
            "lanes": LANES, "n_req": n_req,
            "max_tokens": max_tokens, "prompt_len": prompt_len,
            "warmup_seconds": round(warmup_s, 2),
            "ladder": [list(map(str, bkt))
                       for bkt in runner.buckets()],
        },
    }
    import shutil
    shutil.rmtree(d, ignore_errors=True)
    return stats, _METRIC_NAMES["serving_generate"], "tok/sec"


def _mfu(model, value, peak, per_unit=None):
    per_unit = per_unit or _TRAIN_FLOPS.get(model)
    if per_unit is None or peak is None:
        return None
    return round(per_unit * value / peak, 4)


# Conservative per-row wall estimates (seconds, incl. compile on the
# tunnel) used by the pre-flight gate: a row only STARTS if this much
# time is left before the global deadline.  Overestimates drop rows
# early (recorded, recoverable one-at-a-time via MXTPU_BENCH_MODEL=…);
# underestimates risk rc=124 — err high.
_ROW_EST = {"resnet50": 150, "resnet50_pipeline": 120, "bert": 150,
            "bert_s512": 130, "lenet": 60, "transformer": 120,
            "moe_ffn": 60, "ssd": 90, "bert_zero": 150,
            # 8 bucket compiles (4-rung ladder x 2 seq buckets) of a
            # 4-layer BERT + two latency sweeps + 3 saturation runs
            "serving_bert": 180,
            # tiny model, but 3 soak runs x (n_workers + replacement)
            # ladder compiles + open-loop pacing
            "serving_fleet": 120,
            # 6 short burst runs (static + autoscaled x 3 repeats),
            # each ~2 s of scripted service + replica ladder compiles
            "serving_autoscale": 90,
            # 2 repeats x (cold ladder compile + disk-warmed reload +
            # two first-request probes) of a 2-layer BERT
            "serving_coldstart": 120,
            # 3 arms (f32/bf16/int8) x one bucket compile + timing
            # loops + one calibration pass of a 4-layer BERT
            "serving_bert_int8": 150,
            # full generate ladder compile (prefill rungs + decode
            # step) of a 2-layer causal BERT + 3 saturation drains +
            # the naive re-prefill baseline loop
            "serving_generate": 150,
            # pairs run the base workload twice (off + on)
            "resnet50_amp": 300, "bert_amp": 300,
            "transformer_amp": 240, "bert_zero_amp": 300}


def _sweep_stale_tmpdirs():
    """Remove mxtpu_bench_rec_* dirs left by killed/old runs — each
    holds a ~150 MB record set (VERDICT r5 weak #6: ~1.8 GB had
    accumulated)."""
    import glob
    import shutil
    import tempfile
    for d in glob.glob(os.path.join(tempfile.gettempdir(),
                                    "mxtpu_bench_rec_*")):
        shutil.rmtree(d, ignore_errors=True)


def _emit(results, order, budget, deadline):
    """The one exit path for bench JSON: primary row + extras + wall
    block, printed as a single line (success, trim, and the SIGALRM
    wall backstop all come through here)."""
    primary = next((results[m] for m in order
                    if results[m].get("value") is not None),
                   results[order[0]])
    out = dict(primary)
    if len(results) > 1:
        out["extras"] = {m: results[m] for m in order
                         if results[m] is not primary}
    out["wall"] = {"budget_seconds": round(budget, 1),
                   "elapsed_seconds": round(
                       budget - (deadline - time.monotonic()), 1),
                   "skipped": [m for m in order
                               if results[m].get("skipped")]}
    print(json.dumps(out))
    sys.stdout.flush()


def main():
    which = knobs.get("MXTPU_BENCH_MODEL")
    table = {"lenet": bench_lenet, "resnet50": bench_resnet50,
             "resnet50_pipeline": bench_resnet50_pipeline,
             "bert": bench_bert,
             # long-context north-star row (VERDICT r3 item 4): at
             # s512 attention is a real fraction of the FLOPs, so the
             # flash-attention kernel shows up in a recorded number
             "bert_s512": lambda: bench_bert(
                 batch_size=8, seq_len=512,
                 metric_key="bert_s512"),
             "transformer": bench_transformer,
             # on-demand rows (MXTPU_BENCH_MODEL=moe_ffn / ssd /
             # bert_zero / serving_bert / serving_fleet /
             # serving_autoscale): each fits the budget on its own but
             # the default sweep is already near the wall, so they are
             # not in "all"
             "moe_ffn": bench_moe_ffn,
             "ssd": bench_ssd,
             "bert_zero": bench_bert_zero,
             "serving_bert": bench_serving_bert,
             "serving_fleet": bench_serving_fleet,
             "serving_autoscale": bench_serving_autoscale,
             "serving_coldstart": bench_serving_coldstart,
             "serving_bert_int8": bench_serving_bert_int8,
             "serving_generate": bench_serving_generate,
             # --amp pairs (on-demand): AMP off vs on side by side
             "resnet50_amp": lambda: bench_amp_pair(
                 "resnet50_amp", bench_resnet50),
             "bert_amp": lambda: bench_amp_pair(
                 "bert_amp", bench_bert),
             "transformer_amp": lambda: bench_amp_pair(
                 "transformer_amp", bench_transformer),
             "bert_zero_amp": lambda: bench_amp_pair(
                 "bert_zero_amp", bench_bert_zero)}
    if "--amp" in sys.argv[1:]:
        # `bench.py --amp` swaps every selected row that has an AMP
        # pair for it (MXTPU_BENCH_MODEL=resnet50 --amp runs the
        # resnet50_amp pair; rows without a pair run unchanged)
        if which != "all" and f"{which}_amp" in table:
            which = f"{which}_amp"
    if which != "all" and which not in table:
        sys.exit(f"unknown MXTPU_BENCH_MODEL={which!r}; "
                 f"choices: {sorted(table) + ['all']}")
    budget = knobs.get("MXTPU_BENCH_WALL_BUDGET")
    order = [which] if which != "all" else \
        ["resnet50", "resnet50_pipeline", "bert", "bert_s512",
         "transformer", "lenet"]
    if "--amp" in sys.argv[1:] and which == "all":
        order = [f"{m}_amp" if f"{m}_amp" in table else m
                 for m in order]
    est_total = sum(_ROW_EST[m] for m in order)
    if "--contracts" in sys.argv[1:]:
        # fail FAST if any program drifted from its committed
        # lockfile — a whole bench round against a silently changed
        # program (a vanished reduce-scatter, a new layout bracket)
        # records numbers nobody should trust.  Runs as a subprocess:
        # hlocheck pins its own CPU-backend lowering environment and
        # must not inherit this process's accelerator state.
        rc = subprocess.call(
            [sys.executable, "-m", "tools.hlocheck", "--check"],
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if rc != 0:
            sys.exit(f"bench: --contracts gate failed (hlocheck "
                     f"rc={rc}) — a compiled program drifted from "
                     f"its lockfile; inspect `python -m "
                     f"tools.hlocheck` and either fix the drift or "
                     f"regenerate with --update before benching")
        # same refusal for the lock-order contract: the serving-fleet
        # rows drive the threaded stack, and a lock-graph that drifted
        # from contracts/lockorder.json means the concurrency shape
        # being benched is not the one that was reviewed.
        rc = subprocess.call(
            [sys.executable, "-m", "tools.mxrace", "--check"],
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if rc != 0:
            sys.exit(f"bench: --contracts gate failed (mxrace "
                     f"rc={rc}) — the lock-order graph drifted from "
                     f"contracts/lockorder.json; inspect `python -m "
                     f"tools.mxrace` and either fix the drift or "
                     f"regenerate with --update before benching")
        # and for the precision ledgers: AMP-relevant numerics that
        # drifted from contracts/prec/ mean the dtype story being
        # benched (accumulation widths, cast placement) is not the
        # one that was reviewed.
        rc = subprocess.call(
            [sys.executable, "-m", "tools.mxprec", "--check"],
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if rc != 0:
            sys.exit(f"bench: --contracts gate failed (mxprec "
                     f"rc={rc}) — the dtype flow drifted from "
                     f"contracts/prec/; inspect `python -m "
                     f"tools.mxprec` and either fix the drift or "
                     f"regenerate with --update before benching")
        # and for the memory ledgers: an HBM decomposition that
        # drifted from contracts/mem/ means the footprint being
        # benched (opt-state sharding, KV geometry, donation) is not
        # the one that was reviewed.
        rc = subprocess.call(
            [sys.executable, "-m", "tools.mxmem", "--check"],
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if rc != 0:
            sys.exit(f"bench: --contracts gate failed (mxmem "
                     f"rc={rc}) — the memory footprint drifted from "
                     f"contracts/mem/; inspect `python -m "
                     f"tools.mxmem` and either fix the drift or "
                     f"regenerate with --update before benching")
        print("bench: --contracts gate passed (programs match "
              "contracts/, lock graph matches lockorder.json, "
              "dtype flow matches contracts/prec/, memory ledgers "
              "match contracts/mem/)")
    if "--preflight" in sys.argv[1:]:
        # Answer "will the selected sweep fit the wall budget?" without
        # touching the TPU.  Non-zero exit = the sweep as configured
        # would drop rows — fix the budget or the row list BEFORE
        # burning a run.
        for m in order:
            print(f"  {m:<20} est {_ROW_EST[m]:>4}s")
        verdict = "FITS" if est_total <= budget else "EXCEEDS"
        print(f"preflight: {len(order)} rows, estimated {est_total}s "
              f"{verdict} MXTPU_BENCH_WALL_BUDGET={budget:.0f}s")
        sys.exit(0 if est_total <= budget else 1)
    _sweep_stale_tmpdirs()
    deadline = time.monotonic() + budget
    peak = _peak_flops()
    baseline = {}
    self_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_SELF.json")
    if os.path.exists(self_path):
        with open(self_path) as f:
            baseline = json.load(f).get("metrics", {})

    results = {}
    if hasattr(signal, "SIGALRM"):
        # last line of the rc=124 defence: even if a single row blows
        # straight through its estimate (a hung tunnel inside one
        # compile), the alarm fires at the wall, the rows that never
        # ran land as {"skipped": "budget"}, the JSON still prints,
        # and the exit code is 0 — a driver timeout can never again
        # produce `parsed: null`.
        def _wall_trip(signum, frame):
            for m in order:
                results.setdefault(
                    m, {"metric": _METRIC_NAMES[m], "value": None,
                        "unit": None, "mfu": None,
                        "vs_baseline": None, "skipped": "budget"})
            print(f"bench: wall budget {budget:.0f}s tripped "
                  f"mid-row; flushing partial record", file=sys.stderr)
            _emit(results, order, budget, deadline)
            os._exit(0)
        signal.signal(signal.SIGALRM, _wall_trip)
        signal.alarm(max(1, int(budget)))
    if est_total > budget:
        # r5's rc=124 must never recur: when the sweep as configured
        # cannot fit, trim it UP FRONT by the same arithmetic
        # --preflight prints — each row whose estimate does not fit
        # the cumulative total is dropped on record before anything
        # runs.  The per-row runtime check below stays as the
        # backstop for rows that overrun their estimate.
        cum = 0.0
        for m in order:
            if cum + _ROW_EST[m] <= budget:
                cum += _ROW_EST[m]
                continue
            results[m] = {"metric": _METRIC_NAMES[m], "value": None,
                          "unit": None, "mfu": None,
                          "vs_baseline": None, "skipped": "budget",
                          "est_seconds": _ROW_EST[m],
                          "remaining_seconds": round(budget - cum, 1)}
        print(f"bench pre-flight: estimated {est_total}s for "
              f"{order} exceeds MXTPU_BENCH_WALL_BUDGET={budget:.0f}s; "
              f"auto-trimmed {sorted(results)} onto the record",
              file=sys.stderr)
    for model in order:
        if model in results:
            continue
        remaining = deadline - time.monotonic()
        if remaining < _ROW_EST[model]:
            # r5 lesson: a row that cannot finish must be DROPPED ON
            # RECORD, never allowed to run the process into rc=124
            results[model] = {"metric": _METRIC_NAMES[model],
                              "value": None, "unit": None, "mfu": None,
                              "vs_baseline": None,
                              "skipped": "budget",
                              "est_seconds": _ROW_EST[model],
                              "remaining_seconds": round(remaining, 1)}
            continue
        # one workload failing (e.g. a transient tunnel error) must not
        # cost the round its benchmark line — record the error and move on
        try:
            stats, metric, unit = table[model]()
        except Exception as e:
            results[model] = {"metric": _METRIC_NAMES[model],
                              "value": None, "unit": None, "mfu": None,
                              "vs_baseline": None,
                              "error": str(e)[:300]}
            continue
        prev = baseline.get(metric)
        # value/vs_baseline stay best-vs-best: BASELINE_SELF.json's
        # r2/r3 numbers were recorded as best-of-N, so switching the
        # numerator to median would manufacture a ~spread/2 "regression"
        # on unchanged performance.  The band carries the honesty.
        value = stats["best"]
        ratio = (value / prev) if prev else None
        results[model] = {
            "metric": metric, "value": round(value, 1), "unit": unit,
            "mfu": _mfu(model, value, peak),
            "vs_baseline": (round(ratio, 3) if ratio else None),
            # a regression/gain smaller than the half-width of the
            # run-to-run band is tunnel noise, not a result
            # (VERDICT r3 weak-2)
            "within_noise": (abs(1.0 - ratio) <= stats["spread"] / 2
                             if ratio else None),
            "band": {"median": round(stats["median"], 1),
                     "n": stats["n"], "spread": stats["spread"]},
        }
        if stats.get("info"):
            # row-specific context (e.g. moe_ffn's dense-FFN envelope)
            results[model]["details"] = stats["info"]
        # ISSUE 8: every row carries the obs registry state as of its
        # run — compile counts, step-time histograms, serving counters
        results[model].setdefault("details", {})["obs"] = obs.summary()
    if hasattr(signal, "SIGALRM"):
        signal.alarm(0)
    _emit(results, order, budget, deadline)


if __name__ == "__main__":
    main()
