"""Benchmark: compiled training-step throughput on the real chip.

Prints ONE JSON line whose primary metric is the **ResNet-50 ImageNet
training throughput** (north-star #1, BASELINE.md); the BERT-Large
(north-star #2) and LeNet numbers ride along in ``extras`` so every
round's ``BENCH_r{N}.json`` captures the full picture.  Set
MXTPU_BENCH_MODEL=lenet|resnet50|bert to run a single workload.

The measured unit is the full compiled training step — forward,
backward, fused optimizer (+BN aux writeback) — via
``mxtpu.parallel.build_train_step``, i.e. the samples/sec a
Speedometer would report (SURVEY.md §5.5).

``mfu`` is model-FLOPs utilisation: training FLOPs/sample as counted
by XLA's cost_analysis of the compiled fwd+bwd program (see
_TRAIN_FLOPS) divided by the chip's peak bf16 FLOP/s.
``vs_baseline`` compares the run best against the PREVIOUS round's
self-measured best in BASELINE_SELF.json — the reference mount has
been empty every round (SURVEY.md provenance caveat), so the baseline
is our own trend line; regression < 1.0 is failure unless
``within_noise`` (the shared-chip tunnel shows 5-15% run-to-run
spread, recorded per metric in ``band``).
"""
import json
import os
import sys
import time

import numpy as np

# Peak dense bf16 FLOP/s per chip, by jax device_kind prefix.
# v5 lite (v5e) 197 TFLOP/s; v5p 459; v4 275; v3 123 (bf16).
_PEAK_BF16 = (("TPU v5 lite", 197e12), ("TPU v5p", 459e12),
              ("TPU v5", 459e12), ("TPU v4", 275e12), ("TPU v3", 123e12),
              ("TPU v2", 45e12))

# single source for each workload's metric name (success AND error
# paths report the same key)
_METRIC_NAMES = {
    "resnet50": "resnet50_imagenet_train_throughput",
    "resnet50_pipeline": "resnet50_pipeline_fed_train_throughput",
    "bert": "bert_large_pretrain_throughput",
    "bert_s512": "bert_large_s512_pretrain_throughput",
    "lenet": "lenet_mnist_train_throughput",
}

# Training FLOPs per unit (sample or token), from XLA's own
# cost_analysis() of the compiled fwd+bwd program (r4: the widely
# quoted "4.1 GFLOP" for ResNet-50 is multiply-ACCUMULATES; XLA counts
# 7.54 GFLOP fwd / 22.49 GFLOP fwd+bwd per sample at 224x224, so r1-r3
# under-reported ResNet MFU by 1.83x.  BERT's 6N estimate was within
# 3% of XLA's 2.063 GFLOP/token and is replaced by the measured value.)
_TRAIN_FLOPS = {
    "resnet50": 22.49e9,      # XLA cost_analysis, fwd+bwd, b256
    "resnet50_pipeline": 22.49e9,  # same model, pipeline-fed
    "bert": 2.063e9,          # XLA cost_analysis, fwd+bwd, b32 s128
    # s512: s128 measurement + analytic attention delta (4*T*d*L fwd,
    # x3 fwd+bwd; the flash-attention custom call hides its FLOPs from
    # cost_analysis, so the analytic form is the honest one here)
    "bert_s512": 2.18e9,
    "lenet": None,            # too small for MFU to mean anything
}


def _peak_flops():
    import jax
    kind = jax.devices()[0].device_kind
    for prefix, peak in _PEAK_BF16:
        if kind.startswith(prefix):
            return peak
    return None


def _measure(step, x, y, warmup, iters, batch_size, repeats=5):
    """Timing of BULKED execution: ``iters`` steps run as one compiled
    ``lax.scan`` program (``TrainStep.run_steps``), the TPU-native
    analogue of the reference's bulked graph execution.  Necessary for
    honesty here: the tunnel charges ~10 ms of host RPC per dispatch
    plus ~2-3 ms per loop iteration (BASELINE.md r4 platform
    analysis), which at single-step granularity would measure the
    tunnel, not the chip.  Returns {best, median, n, spread} over
    ``repeats`` runs — the shared chip shows 5-15% run-to-run spread,
    so a single point is not a result."""
    last = step.run_steps(x, y, max(warmup, 2), reuse_batch=True)
    float(last.asnumpy()[-1])  # drain warmup incl. compile
    vals = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        last = step.run_steps(x, y, iters, reuse_batch=True)
        float(last.asnumpy()[-1])  # sync
        dt = time.perf_counter() - t0
        vals.append(batch_size * iters / dt)
    vals.sort()
    median = vals[len(vals) // 2] if len(vals) % 2 else \
        0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
    # spread = (max-min)/median over the runs minus the single worst
    # (the shared tunnel occasionally stalls a run outright — a 20x
    # outlier would make every future delta "within noise").  Normal
    # run-to-run variance on this chip is +-5-15% (VERDICT r3 weak-2).
    core = vals[1:] if len(vals) >= 4 else vals
    return {"best": max(vals), "median": median, "n": len(vals),
            "spread": round((max(core) - min(core)) / median, 4),
            "runs": [round(v, 1) for v in vals]}


def bench_lenet(batch_size=512, warmup=5, iters=30):
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import lenet

    net = lenet()
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 1, 28, 28).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (batch_size,)).astype(np.float32))
    return _measure(step, x, y, warmup, iters, batch_size), \
        _METRIC_NAMES["lenet"], "samples/sec"


def bench_resnet50(batch_size=None, warmup=3, iters=20):
    """ResNet-50 ImageNet-shaped training step (north-star #1).
    Defaults to the standard TPU recipe — bf16 compute over f32 master
    weights, batch 256 (MXTPU_BENCH_DTYPE= / MXTPU_BENCH_BATCH
    override; set MXTPU_BENCH_DTYPE="" for pure f32)."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import resnet50

    batch_size = batch_size or int(
        os.environ.get("MXTPU_BENCH_BATCH", "256"))
    net = resnet50(classes=1000)
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype=os.environ.get("MXTPU_BENCH_DTYPE",
                                     "bfloat16") or None)
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 3, 224, 224).astype(np.float32))
    y = nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32))
    return _measure(step, x, y, warmup, iters, batch_size), \
        _METRIC_NAMES["resnet50"], "samples/sec"


def bench_resnet50_pipeline(batch_size=None, warmup=4, iters=24,
                            repeats=3):
    """Pipeline-fed ResNet-50 (VERDICT r4 item 2): trains from an
    ImageRecordIter over a synthetic raw-record dataset — per-step
    batches, NO reuse_batch — with background prefetch
    (PrefetchingIter) and device-side normalization: uint8 crosses
    the host->device link (~38 MB/batch at ~2 GB/s measured) and the
    cast + mean/std fuse into the compiled train step.  This is the
    rate a user's fit() loop achieves with the input pipeline in the
    loop.

    The raw-record tier is the honest rate-proof on THIS host: the
    box has ONE CPU core (nproc=1), which caps cv2 JPEG decode at
    ~380 img/s no matter the implementation — six times below the
    chip's compute rate; a standard multi-core TPU host VM runs the
    same threaded decode pool past the training rate (BASELINE.md
    "Input pipeline").  Reference: iter_image_recordio_2.cc† +
    iter_prefetcher.h†."""
    import tempfile

    from mxtpu import parallel
    from mxtpu import recordio as rio
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon import nn
    from mxtpu.io import ImageRecordIter, PrefetchingIter
    from mxtpu.models import resnet50

    batch_size = batch_size or int(
        os.environ.get("MXTPU_BENCH_BATCH", "256"))
    d = tempfile.mkdtemp(prefix="mxtpu_bench_rec_")
    prefix = os.path.join(d, "synth")
    rng = np.random.RandomState(0)
    n_img = 8 * batch_size
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    base = (rng.rand(3, 224, 224) * 255).astype(np.uint8)
    for i in range(n_img):
        # distinct images without n_img full RNG draws: roll + refresh
        if i % 61 == 0:
            base = (rng.rand(3, 224, 224) * 255).astype(np.uint8)
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i % 1000), i, 0),
            np.roll(base, i % 224, axis=2).tobytes()))
    rec.close()

    compute_dtype = os.environ.get("MXTPU_BENCH_DTYPE",
                                   "bfloat16") or "float32"

    class _DeviceNormalize(nn.HybridBlock):
        """uint8 -> (x - mean)/std on device; XLA fuses it into the
        step (channel-mean simplification: ImageNet grand mean / std —
        the arithmetic cost is identical to per-channel).  The 1/std
        lives in a frozen parameter so the layer inherits the compute
        dtype from the AMP cast machinery: eager shape-inference sees
        f32, the compiled step sees bf16 — no hand-managed casts."""

        def __init__(self, **kw):
            super().__init__(**kw)
            from mxtpu import initializer
            self.inv_std = self.params.get(
                "inv_std", shape=(1,),
                init=initializer.Constant(1.0 / 57.7), grad_req="null")

        def hybrid_forward(self, F, x, inv_std):
            return (x.astype(str(inv_std.dtype)) - 114.8) * inv_std

    net = nn.HybridSequential(prefix="pipe_")
    net.add(_DeviceNormalize(), resnet50(classes=1000))
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype=(compute_dtype if compute_dtype != "float32"
                       else None),
        cast_batch=False)

    it = ImageRecordIter(prefix + ".rec", (3, 224, 224), batch_size,
                         path_imgidx=prefix + ".idx", shuffle=True,
                         rand_mirror=True, raw_records=True,
                         dtype="uint8", preprocess_threads=2)
    pit = PrefetchingIter(it)

    def batches():
        while True:
            try:
                yield pit.next()
            except StopIteration:
                pit.reset()

    stream = batches()
    loss = None
    for _ in range(warmup):  # includes the compile
        b = next(stream)
        loss = step(b.data[0], b.label[0])
    float(loss.asnumpy().mean())
    vals = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            b = next(stream)
            loss = step(b.data[0], b.label[0])  # async dispatch
        float(loss.asnumpy().mean())  # sync
        vals.append(batch_size * iters / (time.perf_counter() - t0))
    vals.sort()
    median = vals[len(vals) // 2] if len(vals) % 2 else \
        0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
    stats = {"best": max(vals), "median": median, "n": len(vals),
             "spread": round((max(vals) - min(vals)) / median, 4),
             "runs": [round(v, 1) for v in vals]}
    return stats, _METRIC_NAMES["resnet50_pipeline"], "samples/sec"


def bench_bert(batch_size=32, seq_len=128, warmup=3, iters=20,
               metric_key="bert"):
    """BERT-Large MLM-style training step, tokens/sec (north-star #2).
    bf16 compute by default (set MXTPU_BENCH_DTYPE= to override)."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models.transformer import bert_large

    net = bert_large(vocab_size=30522, max_length=seq_len, dropout=0.1)
    net.initialize(init="xavier")
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16") or None

    def mlm_loss(pred, y):
        V = 30522
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, V)), y.reshape((-1,)))

    # cast_batch=False: token ids must not be rounded through bf16
    step = parallel.build_train_step(
        net, mlm_loss, "adam", {"learning_rate": 1e-4},
        compute_dtype=dtype, cast_batch=False)
    rng = np.random.RandomState(0)
    toks = nd.array(rng.randint(0, 30522, (batch_size, seq_len))
                    .astype(np.float32))
    tokens_per_batch = batch_size * seq_len
    value = _measure(step, toks, toks, warmup, iters, tokens_per_batch)
    return value, _METRIC_NAMES[metric_key], "tokens/sec"


def _mfu(model, value, peak):
    per_unit = _TRAIN_FLOPS.get(model)
    if per_unit is None or peak is None:
        return None
    return round(per_unit * value / peak, 4)


def main():
    which = os.environ.get("MXTPU_BENCH_MODEL", "all")
    table = {"lenet": bench_lenet, "resnet50": bench_resnet50,
             "resnet50_pipeline": bench_resnet50_pipeline,
             "bert": bench_bert,
             # long-context north-star row (VERDICT r3 item 4): at
             # s512 attention is a real fraction of the FLOPs, so the
             # flash-attention kernel shows up in a recorded number
             "bert_s512": lambda: bench_bert(
                 batch_size=8, seq_len=512,
                 metric_key="bert_s512")}
    if which != "all" and which not in table:
        sys.exit(f"unknown MXTPU_BENCH_MODEL={which!r}; "
                 f"choices: {sorted(table) + ['all']}")
    peak = _peak_flops()
    baseline = {}
    self_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_SELF.json")
    if os.path.exists(self_path):
        with open(self_path) as f:
            baseline = json.load(f).get("metrics", {})

    order = [which] if which != "all" else \
        ["resnet50", "resnet50_pipeline", "bert", "bert_s512", "lenet"]
    results = {}
    for model in order:
        # one workload failing (e.g. a transient tunnel error) must not
        # cost the round its benchmark line — record the error and move on
        try:
            stats, metric, unit = table[model]()
        except Exception as e:
            results[model] = {"metric": _METRIC_NAMES[model],
                              "value": None, "unit": None, "mfu": None,
                              "vs_baseline": None,
                              "error": str(e)[:300]}
            continue
        prev = baseline.get(metric)
        # value/vs_baseline stay best-vs-best: BASELINE_SELF.json's
        # r2/r3 numbers were recorded as best-of-N, so switching the
        # numerator to median would manufacture a ~spread/2 "regression"
        # on unchanged performance.  The band carries the honesty.
        value = stats["best"]
        ratio = (value / prev) if prev else None
        results[model] = {
            "metric": metric, "value": round(value, 1), "unit": unit,
            "mfu": _mfu(model, value, peak),
            "vs_baseline": (round(ratio, 3) if ratio else None),
            # a regression/gain smaller than the half-width of the
            # run-to-run band is tunnel noise, not a result
            # (VERDICT r3 weak-2)
            "within_noise": (abs(1.0 - ratio) <= stats["spread"] / 2
                             if ratio else None),
            "band": {"median": round(stats["median"], 1),
                     "n": stats["n"], "spread": stats["spread"]},
        }
    primary = next((results[m] for m in order
                    if results[m]["value"] is not None),
                   results[order[0]])
    out = dict(primary)
    if len(results) > 1:
        out["extras"] = {m: results[m] for m in order
                         if results[m] is not primary}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
