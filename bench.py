"""Benchmark: compiled training-step throughput on the real chip.

Prints ONE JSON line.  Default workload: hybridized LeNet-MNIST
(north-star workload 1, BASELINE.md); set MXTPU_BENCH_MODEL=resnet50
for the ImageNet-shaped north-star config.  The measured unit is the
full compiled training
step — forward, backward, fused optimizer (+BN aux writeback) — via
``mxtpu.parallel.build_train_step``, i.e. the samples/sec a
Speedometer would report (SURVEY.md §5.5).  ``vs_baseline`` is null:
the reference mount was empty in every round so far, so no published
number exists to compare against (BASELINE.md).
"""
import json
import os
import sys
import time

import numpy as np


def _measure(step, x, y, warmup, iters, batch_size, repeats=3):
    """Best-of-N timing passes.  The axon tunnel to the chip has
    ~100ms sync round-trips and multi-second wake-from-idle stalls;
    repeated async passes (one sync each) isolate steady-state device
    throughput from transport noise."""
    last = None
    for _ in range(warmup):
        last = step(x, y)
    float(last.asscalar())  # drain warmup incl. compile
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            last = step(x, y)
        float(last.asscalar())  # sync
        dt = time.perf_counter() - t0
        best = max(best, batch_size * iters / dt)
    return best


def bench_lenet(batch_size=512, warmup=5, iters=30):
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import lenet

    net = lenet()
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 1, 28, 28).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (batch_size,)).astype(np.float32))
    return _measure(step, x, y, warmup, iters, batch_size), \
        "lenet_mnist_train_throughput", "samples/sec"


def bench_resnet50(batch_size=None, warmup=3, iters=20):
    """ResNet-50 ImageNet-shaped training step (north-star #1).
    Defaults to the standard TPU recipe — bf16 compute over f32 master
    weights, batch 128 (MXTPU_BENCH_DTYPE= / MXTPU_BENCH_BATCH
    override; set MXTPU_BENCH_DTYPE="" for pure f32)."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import resnet50

    batch_size = batch_size or int(
        os.environ.get("MXTPU_BENCH_BATCH", "128"))
    net = resnet50(classes=1000)
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype=os.environ.get("MXTPU_BENCH_DTYPE",
                                     "bfloat16") or None)
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 3, 224, 224).astype(np.float32))
    y = nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32))
    return _measure(step, x, y, warmup, iters, batch_size), \
        "resnet50_imagenet_train_throughput", "samples/sec"


def bench_bert(batch_size=32, seq_len=128, warmup=3, iters=20):
    """BERT-Large MLM-style training step, tokens/sec (north-star #2).
    bf16 compute by default (set MXTPU_BENCH_DTYPE= to override)."""
    from mxtpu import nd
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models.transformer import bert_large

    net = bert_large(vocab_size=30522, max_length=seq_len, dropout=0.1)
    net.initialize(init="xavier")
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16") or None

    def mlm_loss(pred, y):
        V = 30522
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, V)), y.reshape((-1,)))

    # cast_batch=False: token ids must not be rounded through bf16
    step = parallel.build_train_step(
        net, mlm_loss, "adam", {"learning_rate": 1e-4},
        compute_dtype=dtype, cast_batch=False)
    rng = np.random.RandomState(0)
    toks = nd.array(rng.randint(0, 30522, (batch_size, seq_len))
                    .astype(np.float32))
    tokens_per_batch = batch_size * seq_len
    value = _measure(step, toks, toks, warmup, iters, tokens_per_batch)
    return value, "bert_large_pretrain_throughput", "tokens/sec"


def main():
    model = os.environ.get("MXTPU_BENCH_MODEL", "lenet")
    table = {"lenet": bench_lenet, "resnet50": bench_resnet50,
             "bert": bench_bert}
    fn = table.get(model)
    if fn is None:
        sys.exit(f"unknown MXTPU_BENCH_MODEL={model!r}; "
                 f"choices: {sorted(table)}")
    value, metric, unit = fn()
    print(json.dumps({
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
