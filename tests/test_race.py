"""mxrace — lock-order graphs + deterministic lockset race detection
(ISSUE 9).

Three layers under test:

* the static extractor (``mxtpu/analysis/concurrency.py``): synthetic
  sources in, lock-order edges / cycles / unguarded-attr findings out;
* the committed contract (``contracts/lockorder.json``): byte
  determinism, growth-only drift, and the repo-level empty-findings
  gate;
* the dynamic lockset sanitizer (``mxtpu/analysis/lockset.py``):
  seeded races — a torn counter, a guarded-by violation, a lock-order
  inversion — must each trip EXACTLY their own rule, and the real
  sync-mode fleet scenarios must run clean under full instrumentation.
"""
import json
import subprocess
import sys
import textwrap
import threading
from pathlib import Path

import pytest

from mxtpu.analysis import concurrency as cc
from mxtpu.analysis import lockset

REPO = Path(__file__).resolve().parents[1]

try:
    import test_fleet as tf
except ImportError:  # collected from repo root without tests/ on path
    from tests import test_fleet as tf


def _scan_src(tmp_path, src, rel="mxtpu/fake.py"):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return cc.scan([rel], root=tmp_path)


def _edges(g):
    return sorted(f"{a} -> {b}" for (a, b) in g.edges)


# ---------------------------------------------------------- extractor

def test_nested_with_yields_edge(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def m(self):
                with self._a:
                    with self._b:
                        pass
    """)
    g = cc.build_graph(an)
    assert _edges(g) == ["C._a -> C._b"]
    assert g.locks["C._a"]["kind"] == "Lock"


def test_interprocedural_self_call(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._inner = threading.Lock()
            def outer(self):
                with self._lock:
                    self.helper()
            def helper(self):
                with self._inner:
                    pass
    """)
    g = cc.build_graph(an)
    assert "C._lock -> C._inner" in _edges(g)


def test_locked_suffix_seeds_primary_lock(tmp_path):
    # `*_locked` methods are callee-side contracts: the caller holds
    # the class's primary lock, so nesting inside them is an edge even
    # with no visible call site.
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._aux = threading.Lock()
            def drain_locked(self):
                with self._aux:
                    pass
    """)
    g = cc.build_graph(an)
    assert "C._lock -> C._aux" in _edges(g)


def test_typed_attr_call_resolves_across_classes(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class Child:
            def __init__(self):
                self._lock = threading.Lock()
            def poke(self):
                with self._lock:
                    pass

        class Parent:
            def __init__(self):
                self._lock = threading.Lock()
                self.kid = Child()
            def run(self):
                with self._lock:
                    self.kid.poke()
    """)
    g = cc.build_graph(an)
    assert "Parent._lock -> Child._lock" in _edges(g)


def test_module_lock_and_condition_kind(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        _LOCK = threading.Lock()

        class C:
            def __init__(self):
                self._cond = threading.Condition()
            def m(self):
                with self._cond:
                    with _LOCK:
                        pass
    """)
    g = cc.build_graph(an)
    assert "C._cond -> fake._LOCK" in _edges(g)
    assert g.locks["C._cond"]["kind"] == "Condition"


def test_cycle_reported(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def ab(self):
                with self._a:
                    with self._b:
                        pass
            def ba(self):
                with self._b:
                    with self._a:
                        pass
    """)
    g = cc.build_graph(an)
    fs = cc.cycle_findings(g)
    assert [f.rule for f in fs] == ["lock-cycle"]
    assert "C._a" in fs[0].message and "C._b" in fs[0].message


def test_unguarded_attr_flagged_and_suppressed(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.bad = 0
                self.good = 0   # guarded-by: _lock
                # mxrace: disable=unguarded-attr (test waiver)
                self.waived = 0
            def w1(self):
                self.bad += 1
                with self._lock:
                    self.good += 1
                self.waived += 1
            def w2(self):
                self.bad = 2
                with self._lock:
                    self.good = 2
                self.waived = 2
    """)
    fs = cc.unguarded_findings(an)
    assert [f.rule for f in fs] == ["unguarded-attr"]
    assert "bad" in fs[0].message
    assert "good" not in fs[0].message \
        and "waived" not in fs[0].message


def test_sync_primitive_attrs_exempt(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = threading.Event()
            def a(self):
                self._stop.set()
            def b(self):
                self._stop.clear()
    """)
    assert cc.unguarded_findings(an) == []


# ----------------------------------------------------- lockfile contract

def test_lockfile_roundtrip_no_drift(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def m(self):
                with self._a:
                    with self._b:
                        pass
    """)
    g = cc.build_graph(an)
    lf = tmp_path / "lockorder.json"
    cc.save_lockfile(cc.lockfile_dict(g), lf)
    findings, notices = cc.diff_lockfile(cc.load_lockfile(lf), g, lf)
    assert findings == [] and notices == []


def test_lockfile_new_edge_is_drift_finding(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def m(self):
                with self._a:
                    with self._b:
                        pass
    """)
    g = cc.build_graph(an)
    d = cc.lockfile_dict(g)
    d["edges"] = []                       # stored DAG predates the edge
    lf = tmp_path / "lockorder.json"
    cc.save_lockfile(d, lf)
    findings, _ = cc.diff_lockfile(cc.load_lockfile(lf), g, lf)
    assert [f.rule for f in findings] == ["lock-order-drift"]
    assert "C._a -> C._b" in findings[0].message


def test_lockfile_vanished_edge_is_notice_only(tmp_path):
    an = _scan_src(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
            def m(self):
                with self._a:
                    pass
    """)
    g = cc.build_graph(an)
    d = cc.lockfile_dict(g)
    d["edges"] = ["C._a -> C._gone"]
    lf = tmp_path / "lockorder.json"
    cc.save_lockfile(d, lf)
    findings, notices = cc.diff_lockfile(cc.load_lockfile(lf), g, lf)
    assert findings == []
    assert any("vanished" in n for n in notices)


def test_lockfile_missing_is_finding(tmp_path):
    an = _scan_src(tmp_path, "x = 1\n")
    g = cc.build_graph(an)
    findings, _ = cc.diff_lockfile(None, g, tmp_path / "none.json")
    assert [f.rule for f in findings] == ["lock-order-drift"]
    assert "missing" in findings[0].message


def test_lockfile_bytes_deterministic(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    for p in (a, b):
        g = cc.build_graph(cc.scan())
        cc.save_lockfile(cc.lockfile_dict(g), p)
    assert a.read_bytes() == b.read_bytes()
    # ... and matches the committed contract (update → check fixpoint)
    assert a.read_bytes() == (REPO / "contracts" /
                              "lockorder.json").read_bytes()


# --------------------------------------------------------- repo gate

def test_repo_static_race_check_is_clean():
    """The committed tree carries zero mxrace findings: annotations
    complete, DAG cycle-free and pinned, README table fresh."""
    findings, _notices, g = cc.run_check()
    assert findings == [], [f"{f.rule} {f.path}:{f.line} {f.message}"
                            for f in findings]
    assert cc.find_cycles(g) == []
    assert len(g.locks) >= 15 and len(g.edges) >= 10


def test_cli_check_exit_zero_and_json():
    out = subprocess.run(
        [sys.executable, "-m", "tools.mxrace", "--check", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["new"] == []
    assert len(payload["locks"]) >= 15
    assert len(payload["edges"]) >= 10


# ----------------------------------------------- dynamic: seeded races

class _Torn:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux = threading.Lock()
        self.count = 0

    def bump_main(self):
        with self._lock:
            self.count += 1

    def bump_aux(self):                   # seeded race: wrong lock
        with self._aux:
            self.count += 1


class _Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def add(self, x):
        with self._lock:
            self.items.append(x)

    def bare_read(self):                  # seeded race: no lock
        return len(self.items)


class _Inverted:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def ab(self):
        with self.a:
            with self.b:
                pass

    def ba(self):                         # seeded race: inversion
        with self.b:
            with self.a:
                pass


@pytest.mark.mxrace_off
def test_torn_counter_trips_only_lockset_empty():
    c = lockset.LocksetChecker()
    c.instrument(_Torn, attrs=("count",))
    with c.activate():
        t = _Torn()
        t.bump_main()
        t.bump_aux()
    assert [r.rule for r in c.reports] == ["lockset-empty"]
    r = c.reports[0]
    assert r.subject == "_Torn.count"
    assert len(r.sites) == 2            # BOTH access sites named
    assert all("test_race.py" in s for s in r.sites)
    assert r.sites[0] != r.sites[1]


@pytest.mark.mxrace_off
def test_guarded_by_violation_trips_only_its_rule():
    c = lockset.LocksetChecker()
    c.instrument(_Guarded, guarded={"items": "_lock"})
    with c.activate():
        g = _Guarded()
        g.add(1)
        g.bare_read()
    assert [r.rule for r in c.reports] == ["guarded-by-violation"]
    assert c.reports[0].subject == "_Guarded.items"
    assert "_lock" in c.reports[0].message


@pytest.mark.mxrace_off
def test_lock_order_inversion_trips_only_lock_order():
    c = lockset.LocksetChecker()
    c.instrument(_Inverted)               # naming only
    with c.activate():
        i = _Inverted()
        i.ab()
        i.ba()
    assert [r.rule for r in c.reports] == ["lock-order"]
    r = c.reports[0]
    assert "_Inverted.a" in r.subject and "_Inverted.b" in r.subject
    assert len(r.sites) == 2            # inversion site + prior order


@pytest.mark.mxrace_off
def test_clean_class_reports_nothing():
    class Clean:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

    c = lockset.LocksetChecker()
    c.instrument(Clean, attrs=("n",), guarded={"n": "_lock"})
    with c.activate():
        obj = Clean()
        for _ in range(5):
            obj.bump()
        # Condition/Event/Thread built on patched locks keep exact
        # semantics (wait drops the lock, notify wakes)
        ev = threading.Event()
        th = threading.Thread(target=ev.set)
        th.start()
        th.join()
        assert ev.wait(1.0)
        cond = threading.Condition()
        with cond:
            cond.wait(timeout=0.01)
            cond.notify_all()
    assert c.reports == []
    # restore is complete: factories and class dicts untouched
    assert threading.Lock is lockset._REAL_LOCK
    assert threading.RLock is lockset._REAL_RLOCK
    assert "__getattribute__" not in Clean.__dict__


@pytest.mark.mxrace_off
def test_torn_counter_detected_across_real_threads():
    c = lockset.LocksetChecker()
    c.instrument(_Torn, attrs=("count",))
    with c.activate():
        t = _Torn()
        ths = [threading.Thread(target=t.bump_main),
               threading.Thread(target=t.bump_aux)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
    assert any(r.rule == "lockset-empty" for r in c.reports)


# ------------------------------------- acceptance: removed-lock regression

@pytest.mark.mxrace_off
def test_removed_lock_detected_with_both_sites():
    """Revert the PR 5 torn-read fix in spirit: read ``dispatched``
    bare (as ``stats()`` did before ``dispatch_counts()``) and the
    sanitizer must name BOTH access sites — the locked increment in
    server.py and the bare read here."""
    from mxtpu.serving.server import _Endpoint

    class _StubRunner:
        max_batch_size = 4
        seq_buckets = None

    c = lockset.LocksetChecker()
    c.instrument(_Endpoint, attrs=("dispatched",))
    with c.activate():
        ep = _Endpoint("m", 1, [_StubRunner(), _StubRunner()],
                       max_queue_delay_us=1000.0, max_queue=None,
                       log_every_s=10.0)
        ep._next_runner()                 # locked write (server.py)
        dict(ep.dispatched)               # the reverted bare read
        ep.batcher.close()
    assert [r.rule for r in c.reports] == ["lockset-empty"]
    r = c.reports[0]
    assert r.subject == "_Endpoint.dispatched"
    assert any("mxtpu/serving/server.py" in s for s in r.sites)
    assert any("test_race.py" in s for s in r.sites)


# ------------------------------ acceptance: fleet scenarios run clean

@pytest.mark.mxrace_off
def test_fleet_recovery_scenarios_clean_under_sanitizer():
    """Kill / steal / drain / wedge sync-mode scenarios rerun under
    full default instrumentation with zero reports — the MXTPU_RACE=1
    acceptance bar, in-process."""
    c = lockset.LocksetChecker()
    names = lockset.install_default(c)
    assert {"FleetRouter", "FleetWorker", "DynamicBatcher",
            "InferenceServer", "_Endpoint",
            "MetricsRegistry"} <= set(names)
    with c.activate():
        tf.test_fleet_happy_path_round_robin()
        tf.test_fleet_crash_requeues_never_drops()
        tf.test_fleet_queue_wedge_detected_by_liveness()
        tf.test_fleet_slow_start_recovers_via_canary()
    assert c.reports == [], [r.format() for r in c.reports]


# ------------------------------------------------------- thread hygiene

def test_thread_leak_gate_tolerates_joined_threads():
    done = threading.Event()
    th = threading.Thread(target=done.set)  # non-daemon, but joined
    th.start()
    th.join()
    assert done.is_set()


@pytest.mark.thread_leak_ok
def test_thread_leak_marker_registered(request):
    assert request.node.get_closest_marker("thread_leak_ok")
