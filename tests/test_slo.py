"""mxtpu.obs operator layers (ISSUE 14): time-series sampler,
declarative SLOs with multi-window burn-rate alerting, and the debug
HTTP endpoints.

Everything deterministic runs on a hand-stepped fake clock: sampler
windows, burn-rate edges and the committed CrashAt acceptance scenario
are bit-reproducible with no sleeps.  Only the HTTP round-trips touch
a real socket (loopback, ephemeral port) and they assert payloads,
not latencies.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxtpu import obs, profiler
from mxtpu.base import MXNetError
from mxtpu.obs import (NULL_SAMPLER, NULL_SERVER, NULL_SLO_ENGINE,
                       AvailabilitySLO, BurnRateRule, LatencySLO,
                       Sampler, SLOEngine, parse_slo_classes)
from mxtpu.obs.metrics import (MetricsRegistry, parse_prometheus_text,
                               samples_from_snapshot)
from mxtpu.obs.recorder import FlightRecorder
from mxtpu.serving import Autoscaler, CrashAt, FaultPlan
from mxtpu.serving import stats as serving_stats

from tests.test_fleet import (FakeClock, _crank, _payload, _router,
                              _worker)


@pytest.fixture(autouse=True)
def _clean_obs():
    """Each test starts from an empty registry / sampler / recorders."""
    obs.reset()
    profiler.set_state("stop")
    profiler.dumps(reset=True)
    yield
    profiler.set_state("stop")
    profiler.dumps(reset=True)
    obs.reset()


def _fetch(url):
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.read().decode("utf-8")


# ------------------------------------------------- unified quantile code

def test_percentile_is_the_one_implementation():
    """serving/stats delegates to obs.metrics.percentile — one
    nearest-rank implementation for the whole tree, pinned here."""
    assert serving_stats._percentile is obs.percentile
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    assert obs.percentile(vals, 0) == 1.0
    assert obs.percentile(vals, 50) == 3.0
    assert obs.percentile(vals, 75) == 4.0
    assert obs.percentile(vals, 95) == 5.0
    assert obs.percentile(vals, 100) == 5.0
    assert obs.percentile([], 50) == 0.0
    assert obs.percentile([7.5], 99) == 7.5


def test_bucket_quantile_pinned():
    bounds = (1.0, 2.0, 4.0)
    cum = (5.0, 5.0, 10.0)
    # rank 5 lands exactly on the first bucket's cumulative count
    assert obs.bucket_quantile(bounds, cum, 50) == pytest.approx(1.0)
    # rank 9 interpolates inside (2, 4]: 2 + 2 * (9-5)/(10-5)
    assert obs.bucket_quantile(bounds, cum, 90) == pytest.approx(3.6)
    assert obs.bucket_quantile((), (), 50) is None
    assert obs.bucket_quantile(bounds, (0.0, 0.0, 0.0), 50) is None


# ------------------------------------------------------------- sampler

def test_sampler_counter_gauge_windows():
    t = [0.0]
    reg = MetricsRegistry()
    c = reg.counter("mxtpu_flow_total", "f")
    g = reg.gauge("mxtpu_depth", "d")
    smp = Sampler(reg, capacity=16, period_us=1_000_000,
                  clock=lambda: t[0])
    smp.sample(0.0)
    c.inc(5)
    g.set(2)
    smp.sample(10.0)
    assert smp.level("mxtpu_depth") == 2.0
    assert smp.level("mxtpu_flow_total") == 5.0
    assert smp.delta("mxtpu_flow_total") == 5.0
    assert smp.rate("mxtpu_flow_total") == pytest.approx(0.5)
    c.inc(20)
    smp.sample(20.0)
    # a 10 s window anchors at the newest sample: only [10, 20]
    assert smp.delta("mxtpu_flow_total", window_s=10.0) == 20.0
    assert smp.rate("mxtpu_flow_total", window_s=10.0) == \
        pytest.approx(2.0)
    # whole-ring read still spans everything
    assert smp.delta("mxtpu_flow_total") == 25.0
    # unknown series / one-sample windows answer None
    assert smp.level("mxtpu_nope_total") is None
    assert smp.delta("mxtpu_flow_total", window_s=0.5) is None
    assert "mxtpu_flow_total" in smp.series_names()


def test_sampler_bounded_ring_and_period_gate():
    t = [0.0]
    reg = MetricsRegistry()
    c = reg.counter("mxtpu_flow_total", "f")
    smp = Sampler(reg, capacity=4, period_us=1_000_000,
                  clock=lambda: t[0])
    for k in range(8):
        t[0] = float(k)
        c.inc(1)
        smp.sample()
    # ring keeps the last 4 samples: delta is vs the oldest retained
    assert smp.delta("mxtpu_flow_total") == 3.0
    assert smp.summary()["samples"] == 8
    # period gating: 0.5 s after the last sample is too soon
    t[0] = 7.5
    assert smp.maybe_sample() is False
    t[0] = 8.0
    assert smp.maybe_sample() is True


def test_sampler_histogram_windowed_quantile():
    t = [0.0]
    reg = MetricsRegistry()
    h = reg.histogram("mxtpu_lat_seconds", "l",
                      buckets=(0.1, 1.0, 10.0))
    smp = Sampler(reg, clock=lambda: t[0])
    smp.sample(0.0)
    for _ in range(10):
        h.observe(0.05)
    smp.sample(10.0)
    for _ in range(10):
        h.observe(5.0)
    smp.sample(20.0)
    # the 10 s window sees ONLY the slow burst: p50 interpolates in
    # (1, 10] at rank 5 of 10 -> 1 + 9 * 0.5
    assert smp.quantile("mxtpu_lat_seconds", q=50, window_s=10.0) == \
        pytest.approx(5.5)
    # whole-ring p50 sits in the fast bucket
    q = smp.quantile("mxtpu_lat_seconds", q=50)
    assert 0.0 < q <= 0.1
    d = smp.hist_delta("mxtpu_lat_seconds", window_s=10.0)
    assert d[0] == (0.1, 1.0, 10.0)
    assert d[1] == (0.0, 0.0, 10.0, 10.0)


def test_null_sampler_answers_none():
    assert NULL_SAMPLER.maybe_sample(1.0) is False
    NULL_SAMPLER.sample(1.0)
    assert NULL_SAMPLER.rate("mxtpu_x_total") is None
    assert NULL_SAMPLER.quantile("mxtpu_x_seconds") is None
    assert NULL_SAMPLER.summary()["series"] == 0
    assert NULL_SAMPLER.series_names() == []


def test_factories_return_null_singletons_when_off(monkeypatch):
    monkeypatch.setenv("MXTPU_OBS", "0")
    obs.reset()
    assert obs.sampler() is NULL_SAMPLER
    assert obs.slo_engine([]) is NULL_SLO_ENGINE
    assert obs.debug_server(port=0) is NULL_SERVER
    # self_check proves the same contract from the inside
    assert obs.self_check()["enabled"] is False
    assert obs.registry().names() == []


def test_sampler_factory_is_a_singleton():
    smp = obs.sampler(period_us=0)
    assert obs.sampler() is smp
    obs.reset()
    assert obs.sampler(period_us=0) is not smp


# ----------------------------------------------------------- SLO math

def _avail_rig(buckets=(0.01, 0.1, 1.0)):
    """Private registry with the serving series an SLO reads."""
    t = [0.0]
    reg = MetricsRegistry()
    ep = {"endpoint": "fleet"}
    c = reg.counter("mxtpu_serving_completed_total", "c",
                    labels=("endpoint",)).labels(**ep)
    to = reg.counter("mxtpu_serving_timeout_total", "t",
                     labels=("endpoint",)).labels(**ep)
    sh = reg.counter("mxtpu_serving_rejected_total", "r",
                     labels=("endpoint",)).labels(**ep)
    wr = reg.counter("mxtpu_fleet_events_total", "e",
                     labels=("endpoint", "kind")).labels(
                         endpoint="fleet", kind="wrong_results")
    h = reg.histogram("mxtpu_serving_latency_seconds", "l",
                      labels=("endpoint",),
                      buckets=buckets).labels(**ep)
    smp = Sampler(reg, period_us=0, clock=lambda: t[0])
    return t, reg, smp, c, to, sh, wr, h


def test_availability_slo_formula():
    t, _, smp, c, to, sh, wr, _ = _avail_rig()
    slo = AvailabilitySLO("avail", objective=0.9)
    assert slo.error_ratio(smp, None) is None      # nothing sampled
    smp.sample(0.0)
    c.inc(90)
    to.inc(5)
    sh.inc(3)
    wr.inc(2)
    smp.sample(10.0)
    # 1 - availability = (timeouts + sheds + wrong) / admitted
    assert slo.error_ratio(smp, None) == pytest.approx(10.0 / 100.0)
    # a quiet window (single in-window sample) gives no verdict
    smp.sample(20.0)
    assert slo.error_ratio(smp, 5.0) is None
    with pytest.raises(MXNetError):
        AvailabilitySLO("bad", objective=1.5)


def test_latency_slo_formula_is_conservative():
    t, _, smp, *_rest, h = _avail_rig()
    smp.sample(0.0)
    for _ in range(8):
        h.observe(0.05)
    for _ in range(2):
        h.observe(0.5)
    smp.sample(10.0)
    # target on a bucket boundary: the 8 fast requests are good
    slo = LatencySLO("lat", target_s=0.1, objective=0.95)
    assert slo.error_ratio(smp, None) == pytest.approx(0.2)
    # target INSIDE a bucket: everything straddling counts bad
    strict = LatencySLO("strict", target_s=0.05, objective=0.95)
    assert strict.error_ratio(smp, None) == pytest.approx(1.0)
    # display percentile interpolates the bucket deltas
    assert slo.observed(smp, None) == pytest.approx(0.775)
    with pytest.raises(MXNetError):
        LatencySLO("neg", target_s=-1.0)


def test_parse_slo_classes():
    got = parse_slo_classes("gold:fleet:50:0.95,bulk:fleet:500:0.9:99")
    assert [(s.name, s.endpoint, s.target_s, s.objective,
             s.percentile) for s in got] == \
        [("gold", "fleet", 0.05, 0.95, 95.0),
         ("bulk", "fleet", 0.5, 0.9, 99.0)]
    assert parse_slo_classes("") == []
    with pytest.raises(MXNetError):
        parse_slo_classes("gold:fleet:50")          # too few fields
    with pytest.raises(MXNetError):
        parse_slo_classes("gold:fleet:xx:0.95")     # non-numeric


# ----------------------------------------------- burn-rate alert edges

def _engine(smp, t, rules):
    reg = MetricsRegistry()
    return SLOEngine(
        [AvailabilitySLO("avail", objective=0.9)], smp, rules=rules,
        clock=lambda: t[0],
        alerts=reg.counter("mxtpu_slo_alerts_total", "a",
                           labels=("slo", "window")),
        recorder=FlightRecorder("test/slo", clock=lambda: t[0]))


def test_burn_rate_needs_both_windows():
    """The Google-SRE shape: a fast-only spike never fires — the slow
    window must ALSO breach."""
    t, _, smp, c, to, *_ = _avail_rig()
    eng = _engine(smp, t, (BurnRateRule(fast_s=10.0, slow_s=60.0,
                                        factor=2.0),))
    # one minute of clean traffic
    for now in range(0, 60, 10):
        c.inc(100)
        t[0] = float(now)
        assert eng.tick(t[0]) == []
    # a fast-only spike: fast burn 30/130/0.1 = 2.3x but the slow
    # window is diluted by the clean history (0.48x) — no alert
    c.inc(100)
    to.inc(30)
    t[0] = 60.0
    assert eng.tick(60.0) == []
    assert eng.firing() == []
    # the burn SUSTAINS: the slow window eventually breaches too and
    # the alert fires exactly once (edge-triggered)
    fired = []
    for now in range(70, 130, 10):
        to.inc(30)
        t[0] = float(now)
        fired += eng.tick(t[0])
    assert fired == [("avail", "10s/60s")]
    assert eng.firing() == [("avail", "10s/60s")]
    assert eng._alerts.labels(slo="avail",
                              window="10s/60s").value() == 1.0
    kinds = [e["kind"] for e in eng.recorder.events()]
    assert kinds.count("slo_alert") == 1
    snap = eng.snapshot()
    assert snap["firing"] == [["avail", "10s/60s"]] or \
        snap["firing"] == [("avail", "10s/60s")]
    win = snap["slos"]["avail"]["windows"]["10s/60s"]
    assert win["firing"] is True
    assert win["fast_burn"] >= 2.0 and win["slow_burn"] >= 2.0
    assert snap["alerts"][-1]["slo"] == "avail"


def test_burn_rate_clears_and_refires():
    t, _, smp, c, to, *_ = _avail_rig()
    eng = _engine(smp, t, (BurnRateRule(fast_s=10.0, slow_s=60.0,
                                        factor=2.0),))
    smp.sample(0.0)
    now = 0.0
    # drive a sustained burn until it fires
    fired = []
    while not fired and now < 300.0:
        now += 10.0
        to.inc(50)
        t[0] = now
        fired = eng.tick(now)
    assert fired and eng.firing()
    # recovery: clean traffic clears the fast window immediately
    now += 10.0
    c.inc(1000)
    t[0] = now
    assert eng.tick(now) == []
    assert eng.firing() == []
    assert [e["kind"] for e in eng.recorder.events()].count(
        "slo_clear") == 1
    # a second sustained burn re-fires: the counter totals the edges
    fired = []
    while not fired and now < 600.0:
        now += 10.0
        to.inc(5000)
        t[0] = now
        fired = eng.tick(now)
    assert fired
    assert eng._alerts.labels(slo="avail",
                              window="10s/60s").value() == 2.0


def test_engine_rejects_duplicate_names():
    with pytest.raises(MXNetError):
        SLOEngine([AvailabilitySLO("a"), AvailabilitySLO("a")],
                  NULL_SAMPLER)


def test_null_engine_is_inert():
    assert NULL_SLO_ENGINE.tick(1.0) == []
    assert NULL_SLO_ENGINE.firing() == []
    assert NULL_SLO_ENGINE.snapshot()["slos"] == {}


# --------------------------------------------------- debug HTTP server

def test_debug_server_round_trips():
    c = obs.counter("mxtpu_demo_total", "demo")
    c.inc(3)
    srv = obs.debug_server(port=0)
    try:
        assert srv.enabled and srv.port > 0
        base = srv.url
        # /metrics parses back to exactly the registry snapshot
        text = _fetch(base + "/metrics")
        assert parse_prometheus_text(text) == \
            samples_from_snapshot(obs.registry().snapshot())
        varz = json.loads(_fetch(base + "/varz"))
        assert varz["mxtpu_demo_total"]["series"][0]["value"] == 3.0
        health = json.loads(_fetch(base + "/healthz"))
        assert health["status"] == "ok"
        statusz = json.loads(_fetch(base + "/statusz"))
        assert statusz["workers"] == {} and statusz["slo"] is None
        # /tracez round-trips (unknown id is an empty timeline)
        assert json.loads(_fetch(base + "/tracez?id=r-nope")) == []
        with pytest.raises(urllib.error.HTTPError) as e400:
            _fetch(base + "/tracez")
        assert e400.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e404:
            _fetch(base + "/nope")
        assert e404.value.code == 404
    finally:
        srv.close()
    srv.close()                      # idempotent


def test_debug_server_disabled_by_default_port():
    # the knob defaults to -1: no server unless asked for
    assert obs.debug_server() is NULL_SERVER


# --------------------------- acceptance: mid-burst kill trips the SLO

def test_crash_mid_burst_trips_availability_slo_everywhere():
    """The committed ISSUE 14 scenario: a scripted CrashAt kill during
    a burst drives the availability SLO's fast AND slow burn windows
    over threshold; the alert lands in the alerts counter, the
    fleet/slo flight ring, postmortem()/fleet_stats(), and a live
    /statusz fetch that also shows the DEAD worker."""
    clk = FakeClock()
    with _router(clk, canary=False) as router:
        smp = obs.sampler(period_us=0, clock=clk)
        eng = obs.slo_engine(
            [obs.AvailabilitySLO("avail", objective=0.9)],
            sampler=smp,
            rules=(obs.BurnRateRule(fast_s=1.0, slow_s=8.0,
                                    factor=2.0),),
            clock=clk)
        router.attach_slo(eng)
        srv = obs.debug_server(port=0, router=router, slo=eng,
                               sampler=smp)
        try:
            router.add_worker(_worker(
                clk, "w0", faults=FaultPlan(CrashAt(at_batch=2))))
            # warm traffic: two clean batches land in the slow window
            for i in range(2):
                r = router.submit(_payload(i), timeout_s=5.0)
                _crank(router, clk, n=4, dt=0.1)
                np.testing.assert_allclose(
                    r.result(timeout=0)[0], [i, 2.0 * i, 3.0 * i])
            # the burst: batch 2 crashes the only worker mid-flight
            burst = [router.submit(_payload(9), timeout_s=0.5)
                     for _ in range(4)]
            _crank(router, clk, n=10, dt=0.1)
            assert router.workers()["w0"] == "dead"
            assert all(r.done() for r in burst)
            # the alert fired and is still firing
            assert eng.firing() == [("avail", "1s/8s")]
            key = 'mxtpu_slo_alerts_total{slo="avail",window="1s/8s"}'
            assert obs.summary()[key] == 1.0
            # ... in the flight ring
            kinds = [e["kind"]
                     for e in obs.flight("fleet/slo").events()]
            assert "slo_alert" in kinds
            # ... in fleet_stats() and the worker's postmortem
            assert router.fleet_stats()["slo"]["firing"] == \
                [("avail", "1s/8s")]
            pm = router.postmortem("w0")
            assert pm["health"]["state"] == "dead"
            assert pm["slo"]["firing"] == [("avail", "1s/8s")]
            # ... and on the LIVE operator page
            statusz = json.loads(_fetch(srv.url + "/statusz"))
            assert statusz["workers"]["w0"] == "dead"
            assert statusz["slo"]["firing"] == [["avail", "1s/8s"]]
            tail = statusz["flight"]["fleet/slo"]
            assert any(e["kind"] == "slo_alert" for e in tail)
            # /healthz rolls up to degraded: nobody admits
            health = json.loads(_fetch(srv.url + "/healthz"))
            assert health["status"] == "degraded"
            metrics = parse_prometheus_text(
                _fetch(srv.url + "/metrics"))
            assert metrics[("mxtpu_slo_alerts_total",
                            (("slo", "avail"),
                             ("window", "1s/8s")))] == 1.0
        finally:
            srv.close()


def test_crash_scenario_bit_identical_with_obs_off(monkeypatch):
    """Zero-overhead contract on the ISSUE 14 scenario: MXTPU_OBS=0
    swaps every operator-layer object for its null singleton and the
    serving results are bit-identical."""
    def run_once():
        clk = FakeClock()
        with _router(clk, canary=False) as router:
            smp = obs.sampler(period_us=0, clock=clk)
            eng = obs.slo_engine(
                [obs.AvailabilitySLO("avail", objective=0.9)],
                sampler=smp,
                rules=(obs.BurnRateRule(fast_s=1.0, slow_s=8.0,
                                        factor=2.0),),
                clock=clk)
            router.attach_slo(eng)
            router.add_worker(_worker(
                clk, "w0", faults=FaultPlan(CrashAt(at_batch=2))))
            outs = []
            for i in range(2):
                r = router.submit(_payload(i), timeout_s=5.0)
                _crank(router, clk, n=4, dt=0.1)
                outs.append(np.asarray(r.result(timeout=0)[0]))
            burst = [router.submit(_payload(9), timeout_s=0.5)
                     for _ in range(4)]
            _crank(router, clk, n=10, dt=0.1)
            snap = router.fleet_stats()
            verdicts = []
            for r in burst:
                try:
                    verdicts.append(
                        ("ok", np.asarray(
                            r.result(timeout=0)[0]).tobytes()))
                except Exception as e:   # noqa: BLE001 — the verdict
                    verdicts.append(("err", type(e).__name__))
            return outs, verdicts, snap["extras"], snap["timed_out"]

    on = run_once()
    obs.reset()
    monkeypatch.setenv("MXTPU_OBS", "0")
    off = run_once()
    assert obs.sampler(period_us=0) is NULL_SAMPLER
    for a, b in zip(on[0], off[0]):
        assert a.tobytes() == b.tobytes()
    assert on[1:] == off[1:]
    assert obs.registry().names() == []   # off: registry untouched


# ------------------------------------------- autoscaler burn-rate gate

class _FiringSLO:
    enabled = True

    def firing(self):
        return [("avail", "1s/8s")]

    def tick(self, now=None):
        return []

    def snapshot(self):
        return {"slos": {}, "firing": self.firing(), "alerts": [],
                "ticks": 0}


def test_autoscaler_burn_gate_off_by_default():
    clk = FakeClock()
    with _router(clk, canary=False) as r:
        r.add_worker(_worker(clk, "w0"))
        made = []
        scaler = Autoscaler(r, lambda n: made.append(n),
                            min_workers=1, max_workers=3,
                            up_depth=100.0, down_depth=0.0,
                            breach_ticks=2, cooldown_s=0.0,
                            slo=_FiringSLO())
        assert scaler.burn_scale is False       # knob default
        for _ in range(5):
            clk.advance(0.1)
            scaler.tick(clk())
        assert made == []
        assert scaler.snapshot()["scale_ups"] == 0


def test_autoscaler_burn_gate_scales_up_when_enabled():
    clk = FakeClock()
    with _router(clk, canary=False) as r:
        r.add_worker(_worker(clk, "w0"))
        made = []

        def mk(name):
            w = _worker(clk, name)
            made.append(w)
            return w

        scaler = Autoscaler(r, mk, min_workers=1, max_workers=3,
                            up_depth=100.0, down_depth=0.0,
                            breach_ticks=2, cooldown_s=10.0,
                            slo=_FiringSLO(), burn_scale=True)
        assert scaler.snapshot()["burn_scale"] is True
        for _ in range(4):
            clk.advance(0.1)
            scaler.tick(clk())
        # queue depth never breached (up_depth=100) — the firing SLO
        # alone drove the scale-up
        assert len(made) == 1
        ups = [e for e in scaler.recorder.events()
               if e["kind"] == "scale_up"]
        assert ups and ups[-1]["burn_slos"] == [("avail", "1s/8s")]
