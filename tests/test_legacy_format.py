"""Reference-binary .params serialization: byte-layout pinning,
round-trips, auto-detection, V3 read support, error paths.

Reference: ``src/ndarray/ndarray.cc``† Save/Load + ``MXNDArraySave``†
framing.  The golden-bytes test pins the exact dmlc::Stream layout so
a refactor can't silently break interchange.
"""
import struct

import numpy as np
import pytest

from mxtpu import nd
from mxtpu.base import MXNetError
from mxtpu.ndarray import legacy_format as lf


def test_golden_bytes_layout():
    """Byte-for-byte: one named f32 (2,3) array."""
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    blob = lf.dumps({"w": a})
    expect = b"".join([
        struct.pack("<QQ", 0x112, 0),          # list magic + reserved
        struct.pack("<Q", 1),                  # one array
        struct.pack("<I", 0xF993FAC9),         # V2 magic
        struct.pack("<i", 0),                  # dense stype
        struct.pack("<I", 2),                  # ndim
        struct.pack("<qq", 2, 3),              # dims (int64 dim_t)
        struct.pack("<ii", 1, 0),              # cpu(0) context
        struct.pack("<i", 0),                  # type_flag f32
        a.tobytes(),                           # raw LE payload
        struct.pack("<Q", 1),                  # one name
        struct.pack("<Q", 1), b"w",            # name record
    ])
    assert blob == expect


def test_roundtrip_dict_and_list(tmp_path):
    rng = np.random.RandomState(0)
    # (f64/i64 through NDArray downcast to f32/i32 — jax x64 is off;
    # the format itself round-trips all flags, see
    # test_all_type_flags_roundtrip)
    d = {"arg:fc1_weight": rng.randn(4, 5).astype(np.float32),
         "aux:bn_mean": rng.randn(5).astype(np.float32),
         "idx": np.arange(7, dtype=np.int32)}
    f = str(tmp_path / "net.params")
    nd.save(f, {k: nd.array(v) for k, v in d.items()})
    # .params extension → legacy binary on disk
    with open(f, "rb") as fh:
        assert lf.is_legacy(fh.read(8))
    back = nd.load(f)
    assert set(back) == set(d)
    for k in d:
        np.testing.assert_array_equal(back[k].asnumpy(), d[k])
        assert back[k].dtype == d[k].dtype
    # anonymous list save
    f2 = str(tmp_path / "list.params")
    nd.save(f2, [nd.array(d["idx"]), nd.ones((2, 2))])
    back2 = nd.load(f2)
    assert isinstance(back2, list) and len(back2) == 2
    np.testing.assert_array_equal(back2[0].asnumpy(), d["idx"])


def test_all_type_flags_roundtrip():
    rng = np.random.RandomState(1)
    for dt in (np.float32, np.float64, np.float16, np.uint8, np.int32,
               np.int8, np.int64):
        a = (rng.randn(3, 4) * 10).astype(dt)
        arrays, names = lf.loads(lf.dumps({"x": a}))
        assert names == ["x"]
        np.testing.assert_array_equal(arrays[0], a)
        assert arrays[0].dtype == dt


def test_scalar_and_empty_shapes():
    for shape in ((), (0,), (3, 0, 2)):
        a = np.ones(shape, np.float32)
        arrays, _ = lf.loads(lf.dumps([a]))
        assert arrays[0].shape == shape


def test_v3_int64_dims_read():
    """Streams written by later 1.x (V3 magic, int64 dims) load too."""
    a = np.arange(12, dtype=np.int32).reshape(3, 4)
    blob = b"".join([
        struct.pack("<QQ", 0x112, 0),
        struct.pack("<Q", 1),
        struct.pack("<I", 0xF993FACA),         # V3 magic
        struct.pack("<i", 0),
        struct.pack("<I", 2),
        struct.pack("<qq", 3, 4),              # int64 dims
        struct.pack("<ii", 1, 0),
        struct.pack("<i", 4),                  # type_flag i32
        a.tobytes(),
        struct.pack("<Q", 0),                  # anonymous
    ])
    arrays, names = lf.loads(blob)
    assert names == []
    np.testing.assert_array_equal(arrays[0], a)


def test_prefix_uint32_v2_fallback():
    """Pre-2026-07-30 mxtpu builds wrote V2 dims as uint32 (a bug vs
    the reference's int64 dim_t); those self-written files must still
    load, with a warning."""
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    old = b"".join([
        struct.pack("<QQ", 0x112, 0),
        struct.pack("<Q", 1),
        struct.pack("<I", 0xF993FAC9),         # V2 magic
        struct.pack("<i", 0),
        struct.pack("<I", 2),
        struct.pack("<II", 2, 3),              # old uint32 dims
        struct.pack("<ii", 1, 0),
        struct.pack("<i", 0),
        a.tobytes(),
        struct.pack("<Q", 1),
        struct.pack("<Q", 1), b"w",
    ])
    with pytest.warns(UserWarning, match="uint32 V2 dims"):
        arrays, names = lf.loads(old)
    assert names == ["w"]
    np.testing.assert_array_equal(arrays[0], a)


def test_mxtpu_format_still_default_for_other_extensions(tmp_path):
    f = str(tmp_path / "x.ndarray")
    nd.save(f, {"a": nd.ones((2,))})
    with open(f, "rb") as fh:
        assert fh.read(8) == b"MXTPU01\n"
    back = nd.load(f)
    np.testing.assert_array_equal(back["a"].asnumpy(), np.ones(2))


def test_format_override(tmp_path):
    f = str(tmp_path / "x.whatever")
    nd.save(f, {"a": nd.ones((2,))}, format="legacy")
    with open(f, "rb") as fh:
        assert lf.is_legacy(fh.read(8))
    assert nd.load(f)["a"].shape == (2,)
    with pytest.raises(MXNetError):
        nd.save(f, {"a": nd.ones((2,))}, format="msgpack")


def test_error_paths():
    with pytest.raises(MXNetError):  # truncated
        lf.loads(lf.dumps({"x": np.ones((2, 2), np.float32)})[:-3])
    with pytest.raises(MXNetError):  # wrong list magic
        lf.loads(struct.pack("<QQQ", 0x113, 0, 0))
    blob = bytearray(lf.dumps([np.ones((2,), np.float32)]))
    # NDArray magic sits at byte 24 (8 list magic + 8 reserved + 8 n)
    blob[24:28] = struct.pack("<I", 0xDEAD)
    with pytest.raises(MXNetError):
        lf.loads(bytes(blob))
    # V3 negative dim must raise, not silently mis-shape + rewind
    bad = b"".join([
        struct.pack("<QQQ", 0x112, 0, 1),
        struct.pack("<I", 0xF993FACA), struct.pack("<i", 0),
        struct.pack("<I", 2), struct.pack("<qq", 2, -1),
        struct.pack("<ii", 1, 0), struct.pack("<i", 0),
        struct.pack("<Q", 0),
    ])
    with pytest.raises(MXNetError):
        lf.loads(bad)


def test_gluon_save_parameters_interchange(tmp_path):
    """save_parameters → .params now writes the reference binary and
    round-trips through load_parameters."""
    from mxtpu.gluon import nn
    net = nn.Dense(3)
    net.initialize(init="xavier")
    net(nd.ones((2, 4)))
    f = str(tmp_path / "dense.params")
    net.save_parameters(f)
    with open(f, "rb") as fh:
        assert lf.is_legacy(fh.read(8))
    net2 = nn.Dense(3)
    net2.load_parameters(f)
    np.testing.assert_array_equal(
        net2(nd.ones((2, 4))).asnumpy(),
        net(nd.ones((2, 4))).asnumpy())
