"""Autograd tests — modeled on tests/python/unittest/test_autograd.py†."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, nd

import jax as _jax

# backend-aware tolerance: MXU bf16-pass matmuls / TPU transcendentals
# don't match exact-f32 numpy refs to 1e-5 (SURVEY §7 hard-part 9);
# matmul bound comes from the shared test_utils tables
from mxtpu.test_utils import get_tolerance as _get_tol
_RTOL = _get_tol(__import__("numpy").float32)[0]
_RTOL6 = 1e-4 if _jax.default_backend() != "cpu" else 1e-6


def test_simple_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain():
    x = nd.array([0.5, 1.0])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * 2.0
        z = y.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * np.exp(x.asnumpy()),
                               rtol=_RTOL6)


def test_two_inputs():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), b.asnumpy() + 1)
    np.testing.assert_allclose(b.grad.asnumpy(), a.asnumpy())


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3.0 * x
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_dot_grad():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 2).astype(np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = nd.dot(a, b).sum()
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(),
                               np.ones((3, 2)) @ b.asnumpy().T, rtol=_RTOL)
    np.testing.assert_allclose(b.grad.asnumpy(),
                               a.asnumpy().T @ np.ones((3, 2)), rtol=_RTOL)


def test_grad_add_req():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2.0 * x
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_pause_and_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            z = y * 5.0  # not recorded
        w = y + 1.0
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])
    assert z._tape is None

    with autograd.record():
        y = (x * x).detach() * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])  # d(cx)/dx = c = 4


def test_is_recording_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.pause():
        assert not autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_autograd_grad_api():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    g = autograd.grad(y, x)
    np.testing.assert_allclose(g.asnumpy(), [27.0])


def test_nondiff_path():
    x = nd.array([1.0, 5.0, 3.0])
    x.attach_grad()
    with autograd.record():
        i = nd.argmax(x)  # non-differentiable: no tape node
        y = x * 2.0
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 2.0, 2.0])
    assert i._tape is None


def test_getitem_grad():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = x[0] * 3.0
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[3.0, 3.0], [0.0, 0.0]])


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = nd.array([0.0, 1.0])
    x.attach_grad()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=_RTOL)


def test_multi_output_split_grad():
    x = nd.array(np.arange(4, dtype=np.float32).reshape(2, 2))
    x.attach_grad()
    with autograd.record():
        a, b = nd.split(x, num_outputs=2, axis=0)
        y = (a * 2.0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[2, 2], [0, 0]])


def test_mark_variables():
    x = nd.array([1.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 7.0
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [7.0])


def test_retain_graph_fresh_grads():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])  # not doubled


def test_grad_api_preserves_dot_grad():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2.0
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0])
    with autograd.record():
        z = x * x
    g = autograd.grad(z, x)
    np.testing.assert_allclose(g.asnumpy(), [6.0])
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0])  # untouched


def test_grad_does_not_touch_bystander_grads():
    """Regression (ADVICE r1): autograd.grad must not overwrite .grad of
    leaves that were not requested."""
    a = mx.nd.array([1., 2., 3.])
    a.attach_grad()
    b = mx.nd.array([4., 5., 6.])
    b.attach_grad()
    with mx.autograd.record():
        z = (a * b).sum()
    z.backward()
    b_before = b.grad.asnumpy().copy()
    a_before = a.grad.asnumpy().copy()
    with mx.autograd.record():
        z2 = (a * b * 2).sum()
    ga = mx.autograd.grad(z2, [a])
    ga = ga if isinstance(ga, list) else [ga]
    np.testing.assert_allclose(b.grad.asnumpy(), b_before)
    np.testing.assert_allclose(a.grad.asnumpy(), a_before)
    np.testing.assert_allclose(ga[0].asnumpy(), 2 * np.array([4., 5., 6.]))


def test_grad_of_intermediate_variable():
    a = mx.nd.array([1., 2., 3.])
    a.attach_grad()
    with mx.autograd.record():
        m = a * 2
        z = (m * m).sum()
    gm = mx.autograd.grad(z, [m])
    gm = gm if isinstance(gm, list) else [gm]
    np.testing.assert_allclose(gm[0].asnumpy(), 2 * (2 * np.array([1., 2., 3.])))


def test_scalar_promotion_comparison():
    """Regression (ADVICE r1): int array vs fractional python scalar."""
    ia = mx.nd.array(np.array([1, 2, 3], dtype="int32"))
    np.testing.assert_array_equal((ia >= 1.5).asnumpy(),
                                  [False, True, True])
    r = (ia * 0.5).asnumpy()
    np.testing.assert_allclose(r, [0.5, 1.0, 1.5])


# ----------------------------------------------------------------------
# higher-order gradients (create_graph=True)
# ----------------------------------------------------------------------
def test_grad_create_graph_second_order():
    x = nd.array(np.array([2.0, 3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        gx = autograd.grad(y, x, create_graph=True)   # 3x²
        z = nd.sum(gx)
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy(),
                               rtol=1e-6)


def test_grad_create_graph_third_order_nested():
    x = nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x * x                              # x⁴
        g1 = autograd.grad(y, x, create_graph=True)    # 4x³
        g2 = autograd.grad(g1, x, create_graph=True)   # 12x²
        g3 = autograd.grad(g2, x)                      # 24x
    np.testing.assert_allclose(g3.asnumpy(), [48.0], rtol=1e-6)


def test_grad_create_graph_transcendental_and_hvp():
    x = nd.array(np.array([0.5], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x)
        g1 = autograd.grad(y, x, create_graph=True)
        g2 = autograd.grad(g1, x)
    np.testing.assert_allclose(g2.asnumpy(), [-np.sin(0.5)],
                               rtol=1e-5)
    # hessian-vector product through a 2-layer computation
    w = nd.array(np.array([1.0, 2.0], np.float32))
    w.attach_grad()
    v = nd.array(np.array([1.0, -1.0], np.float32))
    with autograd.record():
        loss = nd.sum(w * w * w)      # H = diag(6w)
        g = autograd.grad(loss, w, create_graph=True)
        gv = nd.sum(g * v)
    gv.backward()
    np.testing.assert_allclose(w.grad.asnumpy(),
                               6 * w.asnumpy() * v.asnumpy(),
                               rtol=1e-5)


def test_grad_create_graph_function_node_raises():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    x = nd.array(np.array([3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = Square()(x)
        from mxtpu.base import MXNetError
        with pytest.raises(MXNetError, match="create_graph"):
            autograd.grad(y, x, create_graph=True)


def test_grad_create_graph_intermediate_variable():
    """Higher-order grads w.r.t. a non-leaf variable (the _watch
    analogue of the first-order path)."""
    x = nd.array(np.array([2.0, 3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = nd.sum(y * x)
    g = autograd.grad(z, [y], create_graph=True)  # single var → array
    np.testing.assert_allclose(g.asnumpy(), x.asnumpy(), rtol=1e-6)


def test_grad_create_graph_outside_record_block():
    """create_graph implies recording the backward even when called
    after the record() block closed (reference semantics)."""
    x = nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.sum(x * x * x)
    g1 = autograd.grad(y, x, create_graph=True)
    g2 = autograd.grad(g1, x)
    np.testing.assert_allclose(g2.asnumpy(), 6 * x.asnumpy(),
                               rtol=1e-6)
