"""Perl frontend (reference ``perl-package/``† AI::MXNet, minimal):
XS bindings over the training-tier C ABI train a linear model from
Perl end-to-end.
"""
import os
import shutil
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PERL = os.path.join(_ROOT, "perl_package")


def test_perl_trains_linear_model():
    if shutil.which("perl") is None or \
            shutil.which("xsubpp") is None or \
            shutil.which("gcc") is None:
        pytest.skip("perl/xsubpp/gcc not available")
    r = subprocess.run(["sh", os.path.join(_PERL, "build.sh"),
                        sys.executable],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-1500:]
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        ["perl", os.path.join(_PERL, "examples", "train_linear.pl")],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, \
        f"stdout:{r.stdout[-800:]}\nstderr:{r.stderr[-800:]}"
    assert "perl frontend OK" in r.stdout, r.stdout[-800:]
    assert r.stdout.count("step ") == 10
