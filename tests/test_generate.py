"""mxtpu.serving.generate — KV-cache incremental decode, continuous
batching, token streaming, and replay-on-steal (ISSUE 19).

Layered like the subsystem: incremental-model parity first (the
hybrid-forward (step, cache) signature IS the substrate), then the
seeded sampler, the GenerateRunner executable ladder + persistent
cache, fake-clock GenerateBatcher units (join at step boundaries,
lane reuse after EOS, deadline eviction mid-decode, partial state on
close), and finally the fleet: a scripted kill mid-generation must
yield ZERO wrong or duplicated tokens and an exactly resumed stream,
reconstructable from the request's one trace id.
"""
import json

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import obs, profiler
from mxtpu.base import MXNetError
from mxtpu.cache import ExecutableCache
from mxtpu.models.transformer import BERTModel
from mxtpu.serving import (FleetGenerateRequest, FleetRouter,
                           FleetWorker, GenerateBatcher,
                           GenerateRunner, InferenceServer,
                           RequestTimeout, ServerBusy, WorkerLost,
                           sample_token)

V, U, HID, NL, NH, L = 32, 16, 32, 2, 2, 16
LANES = 2


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def net():
    n = BERTModel(V, U, HID, NL, NH, max_length=L, dropout=0.0,
                  use_token_type=False, causal=True)
    n.initialize()
    n.hybridize()
    # trace the incremental signature once so export carries the
    # (tokens, step, cache) triple
    n(mx.nd.array(np.ones((1, 3))), mx.nd.array(np.zeros(1)),
      mx.nd.array(np.zeros(n.kv_cache_spec(1), np.float32)))
    return n


@pytest.fixture(scope="module")
def export(net, tmp_path_factory):
    d = tmp_path_factory.mktemp("genbert")
    # re-trace the incremental signature: an earlier test may have run
    # the plain forward last, and export serializes the latest trace
    net(mx.nd.array(np.ones((1, 3))), mx.nd.array(np.zeros(1)),
        mx.nd.array(np.zeros(net.kv_cache_spec(1), np.float32)))
    sym_file, param_file = net.export(str(d / "genbert"))
    return sym_file, param_file


def _runner(export, **kw):
    sym_file, param_file = export
    net_spec = BERTModel(V, U, HID, NL, NH, max_length=L, dropout=0.0,
                         use_token_type=False,
                         causal=True).kv_cache_spec(LANES, L)
    kw.setdefault("prompt_buckets", (4, 8))
    kw.setdefault("cache", None)
    return GenerateRunner.from_export(sym_file, param_file, net_spec,
                                      **kw)


@pytest.fixture(scope="module")
def runner(export):
    return _runner(export)


def _ref_greedy(net, prompt, n):
    """Reference decode: full forward re-run per token (the naive
    baseline the KV path must match token-for-token)."""
    toks = list(prompt)
    for _ in range(n):
        x = mx.nd.array(np.array(toks, np.float32)[None, :])
        logits = net(x).asnumpy()[0]
        toks.append(int(np.argmax(logits[len(toks) - 1])))
    return toks[len(prompt):]


def _batcher(runner, clk, **kw):
    kw.setdefault("clock", clk)
    return GenerateBatcher(runner, **kw)


def _drive(b, clk, *reqs, n=30, dt=0.01):
    for _ in range(n):
        clk.advance(dt)
        b.step()
        if all(r.done() for r in reqs):
            return
    raise AssertionError(f"requests not done after {n} steps")


# ----------------------------------- incremental forward parity (sat 1)

def test_incremental_forward_matches_full(net):
    """The hybrid-forward (step, cache) path must pin the full
    forward's logits bit-close at every position: prefill a prompt,
    then extend one token at a time through the cache and compare
    each step's last-position logits against a from-scratch run."""
    prompt = [3, 7, 1, 4]
    cache = mx.nd.array(np.zeros(net.kv_cache_spec(1), np.float32))
    x = mx.nd.array(np.array(prompt, np.float32)[None, :])
    inc, cache = net(x, mx.nd.array(np.zeros(1)), cache)
    full = net(x)
    np.testing.assert_allclose(inc.asnumpy(), full.asnumpy(),
                               rtol=1e-5, atol=1e-5)
    toks = list(prompt)
    for step in range(4):
        nxt = int(np.argmax(inc.asnumpy()[0, len(toks) - 1 if step == 0
                                          else 0]))
        toks.append(nxt)
        inc, cache = net(
            mx.nd.array(np.array([[nxt]], np.float32)),
            mx.nd.array(np.array([len(toks) - 1], np.float32)), cache)
        ref = net(mx.nd.array(np.array(toks, np.float32)[None, :]))
        np.testing.assert_allclose(
            inc.asnumpy()[0, 0], ref.asnumpy()[0, len(toks) - 1],
            rtol=1e-5, atol=1e-5)


def test_kv_cache_spec_shape(net):
    assert net.kv_cache_spec(LANES, L) == (NL, 2, LANES, NH, L, U // NH)


# ------------------------------------------------------- sample_token

def test_sample_token_greedy_is_argmax():
    logits = np.array([0.1, 2.0, -1.0, 0.5], np.float32)
    assert sample_token(logits, position=5) == 1


def test_sample_token_seeded_by_absolute_position():
    """The draw is keyed by (seed, absolute position) ONLY — the same
    position yields the same token no matter which attempt or process
    samples it.  This is what makes a replayed stream identical."""
    rng = np.random.RandomState(0)
    logits = rng.randn(64).astype(np.float32)
    a = [sample_token(logits, position=p, seed=9, top_k=8)
         for p in range(12)]
    b = [sample_token(logits, position=p, seed=9, top_k=8)
         for p in range(12)]
    assert a == b
    assert len(set(a)) > 1          # top-k actually varies by position
    c = [sample_token(logits, position=p, seed=10, top_k=8)
         for p in range(12)]
    assert a != c                   # seed matters


# --------------------------------------------------- runner executables

def test_runner_bucket_ladder(runner):
    bk = runner.buckets()
    assert ("decode", (LANES + 1,)) in bk
    assert ("prefill", (1, 4)) in bk and ("prefill", (2, 8)) in bk
    assert runner.prompt_bucket_for(3) == 4
    assert runner.prompt_bucket_for(9) == 8   # capped: chunked prefill


def test_runner_rejects_bad_kv_spec(export):
    sym_file, param_file = export
    with pytest.raises(MXNetError):
        GenerateRunner.from_export(sym_file, param_file,
                                   (NL, 2, LANES, NH, L),
                                   prompt_buckets=(4,), cache=None)
    with pytest.raises(MXNetError):
        _runner(export, prompt_buckets=(64,))  # bucket > KV capacity


def test_decode_program_contains_kv_update_write(runner):
    """The decode-step program writes the KV cache IN PLACE at each
    lane's own step index — per-lane ``lax.dynamic_update_slice``
    vmapped over lanes, which lowers to scatter in the as-written HLO
    (hlocheck pins the compiled artifact).  The cache must thread
    through as an updated operand, never be rebuilt from scratch."""
    text = runner.lowered_program_text(("decode", (LANES + 1,)))
    assert "scatter" in text or "dynamic-update-slice" in text or \
        "dynamic_update_slice" in text


def test_greedy_decode_matches_full_forward(net, runner):
    clk = FakeClock()
    b = _batcher(runner, clk)
    r = b.submit([1, 2, 3], max_tokens=5)
    _drive(b, clk, r)
    assert r.result(0) == _ref_greedy(net, [1, 2, 3], 5)
    assert r.finish_reason == "length"


def test_chunked_prefill_beyond_largest_bucket(net, runner):
    clk = FakeClock()
    b = _batcher(runner, clk)
    r = b.submit([1] * 9, max_tokens=3)       # 9 > largest bucket 8
    _drive(b, clk, r)
    assert r.result(0) == _ref_greedy(net, [1] * 9, 3)


# ------------------------------------ persistent cache: warm decode path

def test_warmed_worker_has_zero_cold_compiles(export, tmp_path):
    """THE first-token-is-never-a-compile acceptance: warm the ladder
    through one runner, then a fresh runner (new process stand-in)
    over the same disk cache must build every entry from disk —
    zero cold compiles — and still decode correctly."""
    # one prompt bucket keeps the ladder at 3 programs — the cache
    # contract is per-entry, a taller ladder proves nothing more
    donor = _runner(export, cache=ExecutableCache(tmp_path),
                    prompt_buckets=(4,))
    donor.warmup()
    assert donor.cold_compiles() == len(donor.buckets())

    fresh = _runner(export, cache=ExecutableCache(tmp_path),
                    prompt_buckets=(4,))
    warmed = fresh.warm_from_disk()
    assert set(warmed) == set(fresh.buckets())
    assert fresh.cold_compiles() == 0
    assert set(fresh.compile_sources().values()) == {"disk"}

    clk = FakeClock()
    b = _batcher(fresh, clk)
    r = b.submit([1, 2, 3], max_tokens=3)
    _drive(b, clk, r)
    assert len(r.result(0)) == 3
    assert fresh.cold_compiles() == 0          # still nothing cold


def test_int8_decode_keys_separately(export, tmp_path):
    """int8-armed executables must key APART from the float path in
    the persistent cache — a float warmup can never satisfy (or be
    poisoned by) an int8 decode entry."""
    cache = ExecutableCache(tmp_path)
    f32 = _runner(export, cache=cache)
    i8 = _runner(export, cache=cache, quant=True,
                 quant_scales={"t": 1.0})
    bucket = ("decode", (LANES + 1,))
    assert f32._cache_key(bucket) != i8._cache_key(bucket)
    f32.warmup([bucket])
    assert f32.cached_buckets() == [bucket]
    assert i8.cached_buckets() == []           # float entry invisible


# ------------------------------------------ batcher: continuous batching

def test_join_at_step_boundary_with_lane_accounting(net, runner):
    """A request submitted mid-decode joins at the NEXT step boundary
    by claiming a freed-or-free lane; both streams stay exact."""
    clk = FakeClock()
    b = _batcher(runner, clk)
    r1 = b.submit([1, 2, 3], max_tokens=5)
    out = b.step()
    assert out["admitted"] == 1 and b.free_lanes() == LANES - 1
    r2 = b.submit([4, 5], max_tokens=4)        # late joiner
    assert b.depth == 1                        # queued, not in a lane
    clk.advance(0.01)
    out = b.step()                             # the join boundary
    assert out["admitted"] == 1 and b.free_lanes() == LANES - 2
    _drive(b, clk, r1, r2)
    assert r1.result(0) == _ref_greedy(net, [1, 2, 3], 5)
    assert r2.result(0) == _ref_greedy(net, [4, 5], 4)
    assert b.joins == 2
    assert b.free_lanes() == LANES             # both lanes reclaimed


def test_lane_reuse_after_eos(net, runner):
    """An EOS-finished lane frees at the step boundary and the next
    queued request claims it — lane recycling must not leak the dead
    stream's KV state into the new one (the attention mask caps at
    the new lane's own frontier)."""
    ref = _ref_greedy(net, [1, 2, 3], 5)
    eos = ref[2]
    clk = FakeClock()
    b = _batcher(runner, clk)
    # saturate both lanes (one step = prefill + first decode)
    ra = b.submit([1, 2, 3], max_tokens=10, eos_id=eos)
    rb = b.submit([1] * 4, max_tokens=6)
    b.step()
    assert b.free_lanes() == 0
    rc = b.submit([4, 5], max_tokens=3)        # waits for a lane
    _drive(b, clk, ra)
    assert ra.finish_reason == "eos" and ra.result(0) == ref[:3]
    _drive(b, clk, rb, rc)
    assert rc.result(0) == _ref_greedy(net, [4, 5], 3)
    assert rb.result(0) == _ref_greedy(net, [1] * 4, 6)
    assert b.free_lanes() == LANES


def test_deadline_eviction_mid_decode(runner):
    clk = FakeClock()
    b = _batcher(runner, clk, on_timeout=None)
    r = b.submit([1, 2, 3], max_tokens=50, timeout_s=0.05)
    b.step()                                   # prefill, 1 token out
    clk.advance(1.0)
    b.step()                                   # evicted at the boundary
    with pytest.raises(RequestTimeout):
        r.result(0)
    assert b.free_lanes() == LANES


def test_queue_full_raises_server_busy(runner):
    clk = FakeClock()
    b = _batcher(runner, clk, max_queue=1)
    b.submit([1, 2], max_tokens=2)
    with pytest.raises(ServerBusy):
        for _ in range(3):
            b.submit([1, 2], max_tokens=2)


def test_max_lanes_knob_caps_batching_width(net, runner):
    # MXTPU_GEN_MAX_LANES narrows continuous batching below the
    # exported KV table width without re-exporting: with a 1-lane cap
    # on a 2-lane runner the second request waits for the first to
    # finish, and the result is still the greedy reference.
    clk = FakeClock()
    b = _batcher(runner, clk, max_lanes=1)
    ra = b.submit([1, 2, 3], max_tokens=3)
    rb = b.submit([4, 5], max_tokens=3)
    clk.advance(0.01)
    b.step()          # ra holds the only lane (prefill + 1st decode)
    assert len(b.active()) == 1 and b.depth == 1
    _drive(b, clk, ra, rb)
    assert ra.result(0) == _ref_greedy(net, [1, 2, 3], 3)
    assert rb.result(0) == _ref_greedy(net, [4, 5], 3)
    assert b.joins == 2


def test_stream_callbacks_carry_indices(net, runner):
    clk = FakeClock()
    b = _batcher(runner, clk)
    got = []
    r = b.submit([1, 2, 3], max_tokens=4,
                 on_token=lambda t, i: got.append((i, t)))
    _drive(b, clk, r)
    exp = _ref_greedy(net, [1, 2, 3], 4)
    assert [t for _, t in got] == exp
    assert [i for i, _ in got] == [0, 1, 2, 3]


# ------------------------------- partial state + replay economics (sat 2)

def test_close_carries_partial_generation_state(runner):
    """WorkerLost from a dying batcher carries prompt + emitted tokens
    + the ORIGINAL t_submit/deadline, so a replay resumes without
    double-billing the clock."""
    clk = FakeClock(200.0)
    b = _batcher(runner, clk)
    r = b.submit([1, 2, 3], max_tokens=50, timeout_s=9.0)
    clk.advance(0.5)
    b.step()                            # prefill + first decode step
    clk.advance(0.5)
    b.step()                            # one more decode step
    b.close()
    with pytest.raises(WorkerLost) as ei:
        r.result(0)
    p = ei.value.partial
    assert p["prompt"] == [1, 2, 3]
    assert p["tokens"] == r.prefix + r.tokens and len(p["tokens"]) == 3
    assert p["t_submit"] == 200.0              # original admission time
    assert p["deadline"] == pytest.approx(209.0)


def test_replay_prefix_resumes_exact_stream(net, runner):
    """Resuming from a prefix (prompt + already-streamed tokens) must
    produce the IDENTICAL remaining stream, with indices continuing
    where the dead attempt stopped — seeded sampling is keyed by
    absolute position, so the steal is invisible in the tokens."""
    exp = _ref_greedy(net, [1, 2, 3], 5)
    clk = FakeClock()
    b = _batcher(runner, clk)
    got = []
    r = b.submit([1, 2, 3], max_tokens=5, prefix=exp[:2],
                 on_token=lambda t, i: got.append((i, t)))
    _drive(b, clk, r)
    assert r.result(0) == exp                  # full stream, replayed
    assert [i for i, _ in got] == [2, 3, 4]    # only NEW indices fired
    assert [t for _, t in got] == exp[2:]


def test_replay_never_double_bills_deadline(runner):
    """A replay submitted with the original deadline already expired
    fails fast as queued-deadline-expiry — it must NOT restart the
    clock from the new submit."""
    clk = FakeClock(300.0)
    b = _batcher(runner, clk)
    r = b.submit([1, 2, 3], max_tokens=5, prefix=[0],
                 timeout_s=0.05)               # original budget spent
    clk.advance(1.0)
    b.step()
    with pytest.raises(RequestTimeout):
        r.result(0)


# -------------------------------------------------- sampling determinism

def test_topk_sampling_identical_across_runs_and_steal(net, runner):
    """Seeded top-k: two full runs produce the same stream, and a
    steal (replay from any prefix point) continues it exactly."""
    def run(prefix=()):
        clk = FakeClock()
        b = _batcher(runner, clk)
        r = b.submit([5, 6, 7], max_tokens=6, top_k=4, seed=13,
                     prefix=list(prefix))
        _drive(b, clk, r)
        return r.result(0)

    full_a, full_b = run(), run()
    assert full_a == full_b                    # across runs
    for cut in (1, 3, 5):
        assert run(prefix=full_a[:cut]) == full_a   # across a steal


# -------------------------------------------------------- fleet: replay

def _gen_worker(export, clk, name):
    # one prompt bucket (8 covers every fleet prompt + replay prefix)
    # keeps each worker's ladder at 3 programs — fleet behavior, not
    # ladder breadth, is under test here
    return FleetWorker(None, name, clock=clk,
                       gen_runner=_runner(export, prompt_buckets=(8,)))


def _gen_router(clk, **kw):
    kw.setdefault("backoff_base_us", 10_000)
    kw.setdefault("backoff_cap_us", 50_000)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("hedge_after_us", 0)
    return FleetRouter(clock=clk, threaded=False, canary=None, **kw)


def _crank(router, clk, n=40, dt=0.05, until=None):
    for _ in range(n):
        clk.advance(dt)
        router.tick()
        if until is not None and until():
            return


def test_fleet_kill_mid_generation_exact_resume(net, export):
    """THE acceptance scenario: kill the hosting worker mid-stream.
    The request replays from prompt + already-streamed tokens on the
    survivor, the caller sees every stream index exactly once, zero
    wrong and zero duplicated tokens, and the final stream equals the
    uninterrupted reference."""
    clk = FakeClock(100.0)
    profiler.set_state("run")
    try:
        router = _gen_router(clk)
        router.add_worker(_gen_worker(export, clk, "w0"))
        router.add_worker(_gen_worker(export, clk, "w1"))
        exp = _ref_greedy(net, [1, 2, 3], 6)

        streamed = []
        freq = router.submit_generate(
            [1, 2, 3], max_tokens=6, timeout_s=60.0,
            on_token=lambda t, i: streamed.append((i, t)))
        assert isinstance(freq, FleetGenerateRequest)
        assert freq.trace_id is not None
        _crank(router, clk, dt=0.01, until=lambda: len(streamed) >= 2)
        assert len(streamed) >= 2 and not freq.done()

        host = freq.tried[-1]
        router.kill(host)
        _crank(router, clk, until=freq.done)

        assert freq.result(0) == exp
        assert freq.requeues == 1
        assert freq.anomalies() == {"duplicate_tokens": 0,
                                    "wrong_tokens": 0}
        assert [t for _, t in streamed] == exp     # exactly once, in
        assert [i for i, _ in streamed] == list(range(6))  # order
        surv = [w for w in ("w0", "w1") if w != host][0]
        assert freq.tried == [host, surv]

        # the whole story reconstructs from the one trace id: prefill
        # on the first host, tokens, the replay marker on the survivor
        events = json.loads(profiler.dumps())["traceEvents"]
        timeline = obs.trace_of(freq.trace_id, events=events)
        names = [e["name"] for e in timeline]
        for span in (obs.SPAN_SUBMIT, obs.SPAN_PREFILL, obs.SPAN_TOKEN,
                     obs.SPAN_STEAL, obs.SPAN_REPLAY):
            assert span in names, f"missing {span} in {names}"
        replay = next(e for e in timeline
                      if e["name"] == obs.SPAN_REPLAY)
        assert replay["args"]["worker"] == surv
        assert 1 <= replay["args"]["resumed"] < 6  # mid-stream resume
        token_idx = sorted(e["args"]["index"] for e in timeline
                           if e["name"] == obs.SPAN_TOKEN)
        assert token_idx[-1] == 5 and token_idx[0] == 0
        router.close()
    finally:
        profiler.set_state("stop")
        profiler.dumps(reset=True)


def test_fleet_generate_continuous_batching_late_join(net, export):
    """Two streams on ONE worker: the second submits mid-decode of the
    first and joins at a step boundary (lane accounting asserted)."""
    clk = FakeClock(100.0)
    router = _gen_router(clk)
    w = _gen_worker(export, clk, "w0")
    router.add_worker(w)
    f1 = router.submit_generate([1, 2, 3], max_tokens=5,
                                timeout_s=60.0)
    clk.advance(0.01)
    router.tick()                              # f1 prefilled: 1 lane
    assert w.generator.free_lanes() == LANES - 1
    f2 = router.submit_generate([4, 5], max_tokens=4, timeout_s=60.0)
    _crank(router, clk, until=lambda: f1.done() and f2.done())
    assert f1.result(0) == _ref_greedy(net, [1, 2, 3], 5)
    assert f2.result(0) == _ref_greedy(net, [4, 5], 4)
    assert w.generator.joins == 2
    assert w.generator.free_lanes() == LANES
    router.close()


def test_fleet_generate_never_hedges(export):
    """Hedging a stream would double-emit tokens — generation requests
    are excluded from the hedging loop by contract."""
    clk = FakeClock(100.0)
    router = _gen_router(clk, hedge_after_us=1)  # hedge ASAP
    router.add_worker(_gen_worker(export, clk, "w0"))
    router.add_worker(_gen_worker(export, clk, "w1"))
    freq = router.submit_generate([1, 2, 3], max_tokens=4,
                                  timeout_s=60.0)
    _crank(router, clk, until=freq.done)
    assert freq.hedges == 0 and len(freq.tried) == 1
    assert freq.anomalies() == {"duplicate_tokens": 0,
                                "wrong_tokens": 0}
    router.close()


def test_fleet_generate_deadline_never_stale_stream(export):
    clk = FakeClock(100.0)
    router = _gen_router(clk)
    router.add_worker(_gen_worker(export, clk, "w0"))
    freq = router.submit_generate([1, 2, 3], max_tokens=500,
                                  timeout_s=0.2)
    clk.advance(0.01)
    router.tick()                              # starts decoding
    clk.advance(5.0)
    router.tick()
    with pytest.raises(RequestTimeout):
        freq.result(0)
    router.close()


# ----------------------------------------------------- server endpoint

def test_server_generate_roundtrip(net, export):
    """Streamed generation through InferenceServer's continuous
    endpoint (threaded, real clock): result + per-token callbacks."""
    srv = InferenceServer()
    srv.register_generator("bert", _runner(export))
    got = []
    out = srv.generate("bert", [1, 2, 3], max_tokens=5, timeout_s=60.0,
                       on_token=lambda t, i: got.append((i, t)))
    assert out == _ref_greedy(net, [1, 2, 3], 5)
    assert [t for _, t in sorted(got)] == out
    snap = srv.stats("bert")
    assert snap["lanes"] == LANES
    # first emission lands in the TTFT histogram, the rest per-token
    assert snap["generate"]["tokens_emitted"] >= 4
    assert "bert:v1:gen" in srv.stats()
    srv.close()


def test_server_generator_registry_guards(export):
    srv = InferenceServer()
    srv.register_generator("g", _runner(export))
    with pytest.raises(MXNetError):
        srv.register_generator("g", _runner(export))  # dup version
    with pytest.raises(MXNetError):
        srv.generate("nope", [1], max_tokens=1)
    srv.unregister("g")
    with pytest.raises(MXNetError):
        srv.generate("g", [1], max_tokens=1)
    srv.close()
