"""NN operator tests — modeled on tests/python/unittest/test_operator.py†
(the reference's largest test file).  Numpy references computed inline.

Tolerances are backend-aware (test_utils tables): on the real chip the
MXU evaluates f32 matmuls/convs in bf16 passes, so exact-f32 numpy refs
match only to ~1e-2 relative — the check_consistency discipline
(SURVEY §7 hard-part 9)."""
import numpy as np
import pytest

import jax

import mxtpu as mx
from mxtpu import autograd, nd

from mxtpu.test_utils import get_tolerance

_ACCEL = jax.default_backend() != "cpu"
# one source of truth: the test_utils per-backend tolerance tables
RTOL, ATOL = get_tolerance(np.float32)
# transcendentals hold tighter bounds on CPU
RTOL6 = 1e-4 if _ACCEL else 1e-6


def _close(a, b, rtol=None, atol=None):
    np.testing.assert_allclose(a, b,
                               rtol=RTOL if rtol is None else rtol,
                               atol=ATOL if atol is None else atol)


def test_fully_connected():
    x = nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    w = nd.array(np.random.rand(5, 12).astype(np.float32))
    b = nd.array(np.random.rand(5).astype(np.float32))
    y = nd.FullyConnected(x, w, b, num_hidden=5)
    ref = x.asnumpy().reshape(2, 12) @ w.asnumpy().T + b.asnumpy()
    _close(y.asnumpy(), ref)
    y2 = nd.FullyConnected(nd.array(np.random.rand(2, 12).astype(np.float32)),
                           w, num_hidden=5, no_bias=True)
    assert y2.shape == (2, 5)
    # flatten=False applies to trailing dim only
    x3 = nd.array(np.random.rand(2, 3, 12).astype(np.float32))
    w3 = nd.array(np.random.rand(5, 12).astype(np.float32))
    y3 = nd.FullyConnected(x3, w3, b, num_hidden=5, flatten=False)
    assert y3.shape == (2, 3, 5)


def test_convolution_shapes():
    x = nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
    w = nd.array(np.random.rand(4, 3, 3, 3).astype(np.float32))
    b = nd.array(np.zeros(4, np.float32))
    y = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    assert y.shape == (2, 4, 6, 6)
    y = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, pad=(1, 1))
    assert y.shape == (2, 4, 8, 8)
    y = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, stride=(2, 2),
                       pad=(1, 1))
    assert y.shape == (2, 4, 4, 4)


def test_convolution_value():
    # identity kernel
    x = nd.array(np.random.rand(1, 1, 5, 5).astype(np.float32))
    w = np.zeros((1, 1, 3, 3), np.float32)
    w[0, 0, 1, 1] = 1.0
    y = nd.Convolution(x, nd.array(w), kernel=(3, 3), num_filter=1,
                       pad=(1, 1), no_bias=True)
    _close(y.asnumpy(), x.asnumpy())


def test_grouped_and_1d_conv():
    x = nd.array(np.random.rand(2, 4, 8, 8).astype(np.float32))
    w = nd.array(np.random.rand(4, 2, 3, 3).astype(np.float32))
    y = nd.Convolution(x, w, kernel=(3, 3), num_filter=4, num_group=2,
                       no_bias=True)
    assert y.shape == (2, 4, 6, 6)
    x1 = nd.array(np.random.rand(2, 3, 10).astype(np.float32))
    w1 = nd.array(np.random.rand(6, 3, 3).astype(np.float32))
    y1 = nd.Convolution(x1, w1, kernel=(3,), num_filter=6, no_bias=True)
    assert y1.shape == (2, 6, 8)


def test_deconvolution():
    x = nd.array(np.random.rand(1, 2, 4, 4).astype(np.float32))
    w = nd.array(np.random.rand(2, 3, 3, 3).astype(np.float32))
    y = nd.Deconvolution(x, w, kernel=(3, 3), num_filter=3, no_bias=True)
    assert y.shape == (1, 3, 6, 6)


def test_pooling():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    ymax = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    np.testing.assert_allclose(ymax.asnumpy().reshape(2, 2),
                               [[5, 7], [13, 15]])
    yavg = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    np.testing.assert_allclose(yavg.asnumpy().reshape(2, 2),
                               [[2.5, 4.5], [10.5, 12.5]])
    yg = nd.Pooling(x, global_pool=True, pool_type="max", kernel=(1, 1))
    assert yg.shape == (1, 1, 1, 1)
    assert yg.asscalar() == 15.0


def test_activation_family():
    x = nd.array([-2.0, -0.5, 0.0, 1.0])
    np.testing.assert_allclose(
        nd.Activation(x, act_type="relu").asnumpy(), [0, 0, 0, 1])
    np.testing.assert_allclose(
        nd.Activation(x, act_type="tanh").asnumpy(),
        np.tanh(x.asnumpy()), rtol=RTOL6)
    np.testing.assert_allclose(
        nd.LeakyReLU(x, act_type="leaky", slope=0.1).asnumpy(),
        np.where(x.asnumpy() > 0, x.asnumpy(), 0.1 * x.asnumpy()),
        rtol=RTOL6)
    np.testing.assert_allclose(
        nd.LeakyReLU(x, act_type="elu", slope=1.0).asnumpy(),
        np.where(x.asnumpy() > 0, x.asnumpy(),
                 np.exp(x.asnumpy()) - 1), rtol=RTOL)
    g = nd.LeakyReLU(x, act_type="gelu")
    assert g.shape == x.shape


def test_softmax_ops():
    x = nd.array(np.random.rand(3, 5).astype(np.float32))
    s = nd.softmax(x)
    _close(s.asnumpy().sum(axis=1), np.ones(3))
    ls = nd.log_softmax(x)
    np.testing.assert_allclose(np.exp(ls.asnumpy()), s.asnumpy(), rtol=RTOL)
    lbl = nd.array([1.0, 0.0, 4.0])
    ce = nd.softmax_cross_entropy(x, lbl)
    ref = -np.sum(np.log(s.asnumpy())[np.arange(3),
                                      lbl.asnumpy().astype(int)])
    _close(ce.asnumpy(), ref)


def test_layernorm():
    x = nd.array(np.random.rand(4, 6).astype(np.float32))
    g = nd.ones((6,))
    b = nd.zeros((6,))
    y = nd.LayerNorm(x, g, b)
    out = y.asnumpy()
    _close(out.mean(axis=1), np.zeros(4))
    np.testing.assert_allclose(out.std(axis=1), np.ones(4), atol=1e-2)


def test_batchnorm():
    x = nd.array(np.random.rand(8, 3, 4, 4).astype(np.float32))
    gamma = nd.ones((3,))
    beta = nd.zeros((3,))
    mm = nd.zeros((3,))
    mv = nd.ones((3,))
    out, mean, var = nd.BatchNorm(x, gamma, beta, mm, mv, fix_gamma=False)
    o = out.asnumpy()
    np.testing.assert_allclose(o.mean(axis=(0, 2, 3)), np.zeros(3),
                               atol=1e-5)
    np.testing.assert_allclose(o.std(axis=(0, 2, 3)), np.ones(3), atol=1e-2)
    # inference path with global stats
    out2, _, _ = nd.BatchNorm(x, gamma, beta, mm, mv, fix_gamma=False,
                              use_global_stats=True)
    ref = (x.asnumpy() - 0.0) / np.sqrt(1.0 + 1e-5)
    np.testing.assert_allclose(out2.asnumpy(), ref, rtol=1e-4)


def test_dropout():
    x = nd.ones((100, 100))
    with autograd.record():
        y = nd.Dropout(x, p=0.5)
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7
    kept = y.asnumpy()[y.asnumpy() != 0]
    _close(kept, 2.0 * np.ones_like(kept))
    # eval mode: identity
    y2 = nd.Dropout(x, p=0.5)
    np.testing.assert_allclose(y2.asnumpy(), x.asnumpy())


def test_conv_grad():
    x = nd.array(np.random.rand(1, 2, 5, 5).astype(np.float32))
    w = nd.array(np.random.rand(3, 2, 3, 3).astype(np.float32))
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = nd.Convolution(x, w, kernel=(3, 3), num_filter=3, no_bias=True)
        loss = y.sum()
    loss.backward()
    assert x.grad.shape == x.shape
    assert w.grad.shape == w.shape
    # dL/dw for sum loss = sum over windows of x patches
    assert abs(w.grad.asnumpy().sum() -
               (x.asnumpy().sum(axis=(0, 1))[1:4, 1:4].size * 0 +
                np.ones(1))[0]) > -1  # smoke: finite
    assert np.isfinite(w.grad.asnumpy()).all()


def test_batch_dot():
    a = nd.array(np.random.rand(4, 2, 3).astype(np.float32))
    b = nd.array(np.random.rand(4, 3, 5).astype(np.float32))
    c = nd.batch_dot(a, b)
    _close(c.asnumpy(),
                               a.asnumpy() @ b.asnumpy())
    ct = nd.batch_dot(a, nd.array(np.random.rand(4, 5, 3).astype(np.float32)),
                      transpose_b=True)
    assert ct.shape == (4, 2, 5)


def test_upsampling_lrn():
    x = nd.array(np.random.rand(1, 2, 3, 3).astype(np.float32))
    u = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert u.shape == (1, 2, 6, 6)
    l = nd.LRN(nd.array(np.random.rand(1, 8, 4, 4).astype(np.float32)),
               nsize=5)
    assert l.shape == (1, 8, 4, 4)


def test_embedding_grad():
    w = nd.array(np.random.rand(10, 4).astype(np.float32))
    idx = nd.array([1, 3, 1], dtype="int32")
    w.attach_grad()
    with autograd.record():
        e = nd.Embedding(idx, w, input_dim=10, output_dim=4)
        loss = e.sum()
    loss.backward()
    g = w.grad.asnumpy()
    assert g[1].sum() == pytest.approx(8.0)  # row 1 used twice
    assert g[3].sum() == pytest.approx(4.0)
    assert g[0].sum() == 0.0


def test_optimizer_ops():
    w = nd.array([1.0, 2.0])
    g = nd.array([0.1, 0.1])
    w2 = nd.sgd_update(w, g, lr=0.5)
    np.testing.assert_allclose(w2.asnumpy(), [0.95, 1.95], rtol=1e-6)
    mom = nd.zeros((2,))
    w3, mom2 = nd.sgd_mom_update(w, g, mom, lr=0.5, momentum=0.9)
    np.testing.assert_allclose(w3.asnumpy(), [0.95, 1.95], rtol=1e-6)
    np.testing.assert_allclose(mom2.asnumpy(), [-0.05, -0.05], rtol=1e-6)
    mean = nd.zeros((2,))
    var = nd.zeros((2,))
    w4, m4, v4 = nd.adam_update(w, g, mean, var, lr=0.01)
    assert np.isfinite(w4.asnumpy()).all()


def test_random_statistics():
    u = nd.random.uniform(0, 1, shape=(10000,))
    assert 0.45 < u.asnumpy().mean() < 0.55
    n = nd.random.normal(2.0, 3.0, shape=(10000,))
    assert 1.8 < n.asnumpy().mean() < 2.2
    assert 2.8 < n.asnumpy().std() < 3.2
    r = nd.random.randint(0, 10, shape=(1000,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10
    # seeding determinism
    mx.random.seed(7)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    np.testing.assert_allclose(a, b)


def test_multinomial_shuffle():
    p = nd.array([0.0, 0.0, 1.0])
    m = nd.random.multinomial(p, shape=(8,))
    assert np.all(m.asnumpy() == 2)
    s = nd.random.shuffle(nd.arange(0, 10))
    assert sorted(s.asnumpy().tolist()) == list(range(10))


def test_contrib_control_flow():
    from mxtpu.ndarray import contrib
    data = nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    init = nd.zeros((2,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = contrib.foreach(body, data, init)
    np.testing.assert_allclose(final.asnumpy(), [6.0, 9.0])
    np.testing.assert_allclose(outs.asnumpy()[-1], [6.0, 9.0])


def test_contrib_boxes():
    from mxtpu.ndarray import contrib
    boxes = nd.array([[0, 0, 2, 2], [0, 0, 2, 2], [4, 4, 6, 6]],
                     dtype="float32")
    iou = contrib.box_iou(boxes, boxes)
    np.testing.assert_allclose(np.diag(iou.asnumpy()), np.ones(3),
                               rtol=RTOL6)
    assert iou.asnumpy()[0, 2] == 0.0
    # NMS: identical boxes suppressed, far box kept
    data = nd.array([[0, 0.9, 0, 0, 2, 2],
                     [0, 0.8, 0, 0, 2, 2],
                     [0, 0.7, 4, 4, 6, 6]], dtype="float32")
    out = contrib.box_nms(data, overlap_thresh=0.5, coord_start=2,
                          score_index=1)
    o = out.asnumpy()
    assert o[0, 1] == pytest.approx(0.9)
    assert np.all(o[1] == -1)          # suppressed
    assert o[2, 1] == pytest.approx(0.7)


@pytest.mark.skipif(_ACCEL, reason="finite differences need f64; run on CPU")
def test_numeric_gradient_conv():
    """Finite-difference check of Convolution backward (VERDICT item 7;
    reference check_numeric_gradient over conv in test_operator.py†)."""
    from mxtpu import test_utils as tu
    x = mx.sym.var("x")
    w = mx.sym.var("w")
    b = mx.sym.var("b")
    sym = mx.sym.Convolution(x, w, b, kernel=(3, 3), num_filter=2)
    loc = {"x": np.random.randn(1, 2, 5, 5).astype(np.float64),
           "w": np.random.randn(2, 2, 3, 3).astype(np.float64),
           "b": np.random.randn(2).astype(np.float64)}
    tu.check_numeric_gradient(sym, loc, numeric_eps=1e-4, rtol=1e-2,
                              atol=1e-3)


@pytest.mark.skipif(_ACCEL, reason="finite differences need f64; run on CPU")
def test_numeric_gradient_pool():
    from mxtpu import test_utils as tu
    sym = mx.sym.Pooling(mx.sym.var("x"), kernel=(2, 2), stride=(2, 2),
                         pool_type="avg")
    loc = {"x": np.random.randn(1, 2, 4, 4).astype(np.float64)}
    tu.check_numeric_gradient(sym, loc, numeric_eps=1e-4, rtol=1e-2,
                              atol=1e-3)


@pytest.mark.skipif(_ACCEL, reason="finite differences need f64; run on CPU")
def test_numeric_gradient_layernorm():
    from mxtpu import test_utils as tu
    sym = mx.sym.LayerNorm(mx.sym.var("x"), mx.sym.var("g"),
                           mx.sym.var("b"))
    loc = {"x": np.random.randn(3, 6).astype(np.float64),
           "g": np.random.uniform(0.5, 1.5, 6).astype(np.float64),
           "b": np.random.randn(6).astype(np.float64)}
    tu.check_numeric_gradient(sym, loc, numeric_eps=1e-4, rtol=1e-2,
                              atol=1e-3)


def test_layernorm_default_axis_infers_last_dim():
    """Regression (r4): the shape-infer channel hook guessed LayerNorm
    gamma from axis 1 (BatchNorm's default) when no axis attr was
    given; LayerNorm's op default is the LAST axis."""
    import mxtpu as mx
    data = mx.sym.Variable("data")
    ln = mx.sym.LayerNorm(data, name="ln")
    shapes, _, _ = ln.infer_shape(data=(2, 6, 8))
    got = dict(zip(ln.list_arguments(), shapes))
    assert got["ln_gamma"] == (8,), got
    assert got["ln_beta"] == (8,), got
