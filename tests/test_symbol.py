"""Symbol graph-lite + Executor tests.

Mirrors the reference's ``tests/python/unittest/test_symbol.py``† and the
executor pieces of ``test_executor.py``†: composition, JSON round-trip,
infer_shape, bind/forward/backward, export→imports.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, sym
from mxtpu.gluon import nn, SymbolBlock


def test_arith_eval_and_scalar_ops():
    a, b = sym.var("a"), sym.var("b")
    c = (2.0 * a + b ** 2 - 1.0) / 2.0
    av = nd.array(np.full((2, 3), 3.0, np.float32))
    bv = nd.array(np.full((2, 3), 2.0, np.float32))
    out = c.eval(a=av, b=bv)[0].asnumpy()
    assert np.allclose(out, (2 * 3.0 + 4.0 - 1) / 2)
    d = (1.0 - a) * (a >= 3.0)
    assert np.allclose(d.eval(a=av)[0].asnumpy(), -2.0)


def test_list_arguments_and_auto_vars():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, num_hidden=10, name="fc2", no_bias=True)
    assert fc2.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight"]
    assert fc2.list_outputs() == ["fc2_output"]


def test_json_roundtrip_file(tmp_path):
    data = sym.var("data")
    net = sym.Activation(
        sym.FullyConnected(data, num_hidden=8, name="fc"),
        act_type="tanh")
    fname = str(tmp_path / "net-symbol.json")
    net.save(fname)
    net2 = sym.load(fname)
    assert net2.list_arguments() == net.list_arguments()
    x = nd.array(np.random.randn(2, 5).astype(np.float32))
    w = nd.array(np.random.randn(8, 5).astype(np.float32))
    b = nd.array(np.zeros(8, np.float32))
    o1 = net.eval(data=x, fc_weight=w, fc_bias=b)[0].asnumpy()
    o2 = net2.eval(data=x, fc_weight=w, fc_bias=b)[0].asnumpy()
    assert np.allclose(o1, o2)


def test_infer_shape_conv_net():
    data = sym.var("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    p1 = sym.Pooling(c1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f1 = sym.flatten(p1)
    fc = sym.FullyConnected(f1, num_hidden=10, name="fc")
    arg_shapes, out_shapes, _ = fc.infer_shape(data=(4, 3, 28, 28))
    args = fc.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d["c1_weight"] == (8, 3, 3, 3)
    assert d["c1_bias"] == (8,)
    assert d["fc_weight"] == (10, 8 * 13 * 13)
    assert out_shapes == [(4, 10)]


def test_infer_shape_partial_and_error():
    a = sym.var("a")
    w = sym.var("w")
    out = sym.FullyConnected(a, w, no_bias=True, num_hidden=4)
    shapes, outs, _ = out.infer_shape_partial()
    assert outs == [None]
    with pytest.raises(mx.MXNetError):
        sym.broadcast_add(a, w).infer_shape(a=(2, 2))


def test_multi_output_indexing_and_group():
    data = sym.var("data")
    parts = sym.split(data, num_outputs=3, axis=1)
    assert len(parts) == 3
    g = sym.Group([parts[0], parts[2]])
    outs = g.eval(data=nd.array(np.arange(12, dtype=np.float32)
                                .reshape(2, 6)))
    assert outs[0].shape == (2, 2) and outs[1].shape == (2, 2)
    assert np.allclose(outs[1].asnumpy(), [[4, 5], [10, 11]])


def test_get_internals_and_lookup():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=4, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=2, name="fc2")
    internals = fc2.get_internals()
    assert "relu1_output" in internals.list_outputs()
    feat = fc2["relu1_output"]
    assert feat.name == "relu1"


def test_composition():
    base = sym.FullyConnected(sym.var("data"), num_hidden=4, name="fc1")
    head = sym.Activation(sym.var("in2"), act_type="relu")
    composed = head(in2=base)
    assert "data" in composed.list_arguments()
    assert "in2" not in composed.list_arguments()


def test_executor_forward_backward_matches_autograd():
    rng = np.random.RandomState(7)
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc")
    loss = sym.sum(sym.square(fc))
    ex = loss.simple_bind(grad_req="write", data=(4, 5))
    xv = rng.randn(4, 5).astype(np.float32)
    wv = rng.randn(3, 5).astype(np.float32)
    bv = rng.randn(3).astype(np.float32)
    ex.arg_dict["fc_weight"] = nd.array(wv)
    ex.arg_dict["fc_bias"] = nd.array(bv)
    ex.forward(is_train=True, data=nd.array(xv))
    ex.backward()
    # reference: d(sum((xW'+b)^2))/dW = 2 (xW'+b)' x
    y = xv.dot(wv.T) + bv
    expected = 2 * y.T.dot(xv)
    assert np.allclose(ex.grad_dict["fc_weight"].asnumpy(), expected,
                       rtol=1e-4, atol=1e-4)
    # grad_req add accumulates
    ex2 = loss.simple_bind(grad_req="add", data=(4, 5))
    ex2.arg_dict["fc_weight"] = nd.array(wv)
    ex2.arg_dict["fc_bias"] = nd.array(bv)
    for _ in range(2):
        ex2.forward(is_train=True, data=nd.array(xv))
        ex2.backward()
    assert np.allclose(ex2.grad_dict["fc_weight"].asnumpy(), 2 * expected,
                       rtol=1e-4, atol=1e-4)


def test_symbolic_trace_of_gluon_block():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = nd.array(np.random.randn(2, 6).astype(np.float32))
    y_eager = net(x).asnumpy()
    s = net(sym.var("data"))
    assert isinstance(s, sym.Symbol)
    bindings = {"data": x}
    for name, p in net.collect_params().items():
        bindings[name] = p.data()
    y_sym = s.eval(**bindings)[0].asnumpy()
    assert np.allclose(y_eager, y_sym, rtol=1e-5, atol=1e-5)


def test_export_imports_roundtrip(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, activation="relu"),
            nn.BatchNorm(),
            nn.Flatten(),
            nn.Dense(5))
    net.initialize(init="xavier")
    x = nd.array(np.random.randn(2, 3, 8, 8).astype(np.float32))
    y0 = net(x).asnumpy()
    prefix = str(tmp_path / "model")
    sym_file, param_file = net.export(prefix, epoch=3)
    assert sym_file.endswith("-symbol.json")
    assert param_file.endswith("-0003.params")
    blk = SymbolBlock.imports(sym_file, ["data"], param_file)
    y1 = blk(x)
    y1 = (y1[0] if isinstance(y1, (list, tuple)) else y1).asnumpy()
    assert np.allclose(y0, y1, rtol=1e-5, atol=1e-6)
