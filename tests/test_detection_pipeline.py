"""Detection data path end-to-end (VERDICT r2 item 4): det-record
packing, ImageDetIter with box-aware augmentation, VOC07 mAP, and SSD
training from a .rec reaching a mAP threshold.

References: src/io/iter_image_det_recordio.cc†,
python/mxnet/image/detection.py†, example/ssd/evaluate/eval_metric.py†.
"""
import numpy as np
import pytest

from mxtpu import nd
from mxtpu import recordio as rio
from mxtpu.image import (DetHorizontalFlipAug, DetRandomCropAug,
                         ImageDetIter, pack_det_label)
from mxtpu.metric import MApMetric, VOC07MApMetric


def _write_rec(prefix, n=16, size=32, seed=0):
    rng = np.random.RandomState(seed)
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    truths = []
    for i in range(n):
        img = (rng.rand(size, size, 3) * 40).astype(np.uint8)
        cls = int(rng.randint(2))
        w = int(rng.randint(size // 4, size // 2))
        x0 = int(rng.randint(0, size - w))
        y0 = int(rng.randint(0, size - w))
        # class-coded color so the class head has signal to learn
        img[y0:y0 + w, x0:x0 + w] = (220, 40, 60) if cls == 0 \
            else (40, 220, 60)
        box = [cls, x0 / size, y0 / size, (x0 + w) / size,
               (y0 + w) / size]
        truths.append(box)
        rec.write_idx(i, rio.pack_img(
            rio.IRHeader(0, pack_det_label([box]), i, 0), img,
            quality=95))
    rec.close()
    return prefix + ".rec", prefix + ".idx", truths


def test_pack_det_label_layout():
    lab = pack_det_label([[1, 0.1, 0.2, 0.3, 0.4],
                          [0, 0.5, 0.5, 0.9, 0.9]])
    assert lab[0] == 2 and lab[1] == 5 and lab.size == 12
    hdr, rest = int(lab[0]), lab[2:]
    objs = lab[hdr:].reshape(-1, 5)
    np.testing.assert_allclose(objs[0], [1, 0.1, 0.2, 0.3, 0.4])


def test_imagedetiter_reads_and_pads(tmp_path):
    rec, idx, truths = _write_rec(str(tmp_path / "det"), n=10)
    it = ImageDetIter(rec, (3, 32, 32), batch_size=4, path_imgidx=idx,
                      scale=1.0 / 255)
    assert it.max_objs == 1
    batch = next(it)
    data = batch.data[0].asnumpy()
    label = batch.label[0].asnumpy()
    assert data.shape == (4, 3, 32, 32) and label.shape == (4, 1, 5)
    # labels round-trip through the wire format
    np.testing.assert_allclose(label[0, 0], truths[0], atol=1e-6)
    assert 0.0 <= data.min() and data.max() <= 1.0
    # padding on the tail batch
    batches = [batch] + list(it)
    assert batches[-1].pad == 2  # 10 % 4


def test_det_flip_aug_moves_boxes():
    rng = np.random.RandomState(0)
    img = rng.rand(16, 16, 3)
    label = np.asarray([[0, 0.1, 0.2, 0.4, 0.6]], np.float32)

    class AlwaysFlip(DetHorizontalFlipAug):
        def __init__(self):
            super().__init__(p=1.1)

    img2, lab2 = AlwaysFlip()(img.copy(), label.copy())
    np.testing.assert_allclose(lab2[0], [0, 0.6, 0.2, 0.9, 0.6],
                               atol=1e-6)
    np.testing.assert_allclose(img2, img[:, ::-1])
    # flip twice = identity
    _, lab3 = AlwaysFlip()(img2, lab2.copy())
    np.testing.assert_allclose(lab3, label, atol=1e-6)


def test_det_random_crop_keeps_covered_boxes():
    rng = np.random.RandomState(3)
    aug = DetRandomCropAug(min_object_covered=0.5,
                           area_range=(0.5, 0.9),
                           rng=rng)
    img = rng.rand(64, 64, 3)
    label = np.asarray([[0, 0.3, 0.3, 0.7, 0.7]], np.float32)
    kept = 0
    for _ in range(10):
        _, lab2 = aug(img, label.copy())
        if lab2[0, 0] >= 0:
            kept += 1
            assert 0 <= lab2[0, 1] <= lab2[0, 3] <= 1
            assert 0 <= lab2[0, 2] <= lab2[0, 4] <= 1
    assert kept >= 5  # central box survives most crops


def test_voc07_map_known_values():
    m = VOC07MApMetric()
    label = np.array([[[0, .1, .1, .5, .5], [1, .6, .6, .9, .9],
                       [-1] * 5]])
    pred = np.array([[[0, .95, .1, .1, .5, .5],
                      [1, .9, .6, .6, .9, .9], [-1] * 6]])
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6
    # duplicate detection of a matched gt counts as false positive
    m.reset()
    pred_dup = np.array([[[0, .95, .1, .1, .5, .5],
                          [0, .90, .1, .1, .5, .5], [-1] * 6]])
    label_one = np.array([[[0, .1, .1, .5, .5]]])
    m.update([label_one], [pred_dup])
    # full recall happens at the top-scored det, so the 11-point AP
    # stays 1.0 — the fp only lowers later precision
    assert abs(m.get()[1] - 1.0) < 1e-6
    m2 = MApMetric()
    m2.update([label_one], [pred_dup])
    assert abs(m2.get()[1] - 1.0) < 1e-6


def test_ssd_trains_from_rec_and_reaches_map(tmp_path):
    """The reference's SSD recipe end-to-end on a tiny synthetic set:
    pack rec → ImageDetIter → MultiBoxTarget training → detect →
    VOC07 mAP above threshold."""
    import mxtpu as mx
    from mxtpu import autograd, gluon
    from mxtpu.models.ssd import SSDLoss, toy_ssd

    mx.random.seed(0)
    rec, idx, _ = _write_rec(str(tmp_path / "train"), n=24, size=32,
                             seed=1)
    it = ImageDetIter(rec, (3, 32, 32), batch_size=8, path_imgidx=idx,
                      shuffle=True, rand_mirror=True, scale=1.0 / 255)
    net = toy_ssd(num_classes=2)
    net.initialize(init="xavier")
    loss_fn = SSDLoss()
    trainer = None
    losses = []
    # 25 epochs: the loss bottoms out near ep 10 but detection quality
    # keeps climbing as the box head sharpens (mAP ~0.18 at ep 10,
    # ~0.5 at ep 15, >0.9 by ep 25) — stopping at 10 made the floor
    # a coin flip on the RNG stream
    for _ in range(25):
        it.reset()
        for batch in it:
            x, labels = batch.data[0], batch.label[0]
            if trainer is None:
                net(x)
                trainer = gluon.Trainer(net.collect_params(), "adam",
                                        {"learning_rate": 5e-3})
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                bt, bm, ct = nd.MultiBoxTarget(anchors, labels,
                                               cls_preds)
                loss = nd.mean(loss_fn(cls_preds, box_preds, ct, bt,
                                       bm))
            loss.backward()
            trainer.step(batch_size=x.shape[0])
            losses.append(float(loss.asscalar()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    metric = VOC07MApMetric(iou_thresh=0.3)
    it.reset()
    for batch in it:
        out = net.detect(batch.data[0])
        metric.update([batch.label[0]], [out])
    name, value = metric.get()
    # tiny net + tiny data: the bar proves the pipeline learns real
    # detection signal (converged runs sit >0.9; half that is the
    # flake margin), not detection SOTA
    assert value > 0.45, value


def test_voc07_map_difficult_neutral():
    """VOC protocol: difficult gts excluded from npos; matches to them
    are neutral (neither tp nor fp)."""
    m = VOC07MApMetric()
    # one easy gt (matched) + one difficult gt (matched by a 2nd det)
    label = np.array([[[0, .1, .1, .5, .5, 0],
                       [0, .6, .6, .9, .9, 1]]])
    pred = np.array([[[0, .95, .1, .1, .5, .5],
                      [0, .90, .6, .6, .9, .9]]])
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6  # difficult det is neutral


def test_imagedetiter_seeded_reproducible_any_pool_size(tmp_path):
    """Per-sample augmentation seeds are drawn serially, so identical
    iterator seeds give identical epochs at any preprocess_threads."""
    rec, idx, _ = _write_rec(str(tmp_path / "rp"), n=12, size=48,
                             seed=5)

    def epoch(threads):
        it = ImageDetIter(rec, (3, 32, 32), batch_size=4,
                          path_imgidx=idx, shuffle=True,
                          rand_crop=0.5, rand_mirror=True, seed=9,
                          preprocess_threads=threads)
        out = [(b.data[0].asnumpy(), b.label[0].asnumpy())
               for b in it]
        it.close()
        return out

    a, b, c = epoch(4), epoch(4), epoch(1)
    for (da, la), (db, lb_), (dc, lc) in zip(a, b, c):
        np.testing.assert_array_equal(da, db)
        np.testing.assert_array_equal(da, dc)
        np.testing.assert_array_equal(la, lb_)
        np.testing.assert_array_equal(la, lc)
