"""Mixture-of-Experts + expert parallelism (the ep mesh axis).

New-capability subsystem (north star: dp/tp/pp/sp/ep); Switch
capacity routing, dense-einsum dispatch, GSPMD all-to-all sharding.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxtpu import parallel
from mxtpu.parallel.moe import MoEFFN, moe_ffn, switch_router


def test_router_capacity_and_slots():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    gw = jnp.asarray(rng.randn(8, 4).astype(np.float32))
    C = 3
    dispatch, combine, aux = switch_router(x, gw, C)
    d = np.asarray(dispatch)
    # each token occupies at most one (expert, slot)
    assert d.sum(axis=(1, 2)).max() <= 1.0 + 1e-6
    # each expert slot holds at most one token
    assert d.sum(axis=0).max() <= 1.0 + 1e-6
    # combine weights are the router prob of the kept tokens
    c = np.asarray(combine)
    kept = d.sum(axis=(1, 2)) > 0
    assert (c.sum(axis=(1, 2))[kept] > 0).all()
    assert float(aux) > 0


def test_moe_single_expert_matches_dense_ffn():
    """E=1 with ample capacity IS the dense FFN — exact parity."""
    rng = np.random.RandomState(1)
    D, H, T = 8, 16, 12
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    gw = jnp.zeros((D, 1), jnp.float32)
    w1 = jnp.asarray(rng.randn(1, D, H).astype(np.float32)) * 0.3
    b1 = jnp.asarray(rng.randn(1, H).astype(np.float32)) * 0.1
    w2 = jnp.asarray(rng.randn(1, H, D).astype(np.float32)) * 0.3
    b2 = jnp.asarray(rng.randn(1, D).astype(np.float32)) * 0.1
    y, _ = moe_ffn(x, gw, w1, b1, w2, b2, capacity_factor=1.0)
    want = jax.nn.relu(x @ w1[0] + b1[0]) @ w2[0] + b2[0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_moe_dropped_tokens_get_zero_output():
    rng = np.random.RandomState(2)
    D, H, T, E = 4, 8, 32, 2
    # positive features so a negative gate column repels ALL tokens
    # (there is no gate bias; the logit is x @ w)
    x = jnp.asarray(np.abs(rng.randn(T, D)).astype(np.float32) + 0.1)
    gw = jnp.zeros((D, E), jnp.float32).at[:, 1].set(-10.0)
    m = MoEFFN(D, H, E, capacity_factor=0.125)
    _, w1, b1, w2, b2 = m.params()
    y, _ = moe_ffn(x, gw, w1, b1, w2, b2, capacity_factor=0.125)
    # capacity = ceil(32/2 * 0.125) = 2 slots; the rest overflow to 0
    nz = (np.abs(np.asarray(y)).sum(axis=-1) > 1e-7).sum()
    assert nz <= 2, nz


def test_moe_expert_parallel_parity_8dev():
    """ep-sharded MoE over the 8-device mesh == unsharded result, and
    the expert activations really shard over ep."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device mesh")
    rng = np.random.RandomState(3)
    D, H, T, E = 16, 32, 64, 8
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    m = MoEFFN(D, H, E, seed=7)
    params = m.params()
    mesh = parallel.make_mesh({"ep": 8})

    y_ref, aux_ref = jax.jit(
        lambda p, x: m.apply(p, x))(params, x)

    @jax.jit
    def sharded(p, x):
        return m.apply(p, x, mesh=mesh)

    y_ep, aux_ep = sharded(params, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux_ep), float(aux_ref),
                               rtol=1e-5)


def test_moe_grads_flow_and_balance_loss_trains():
    """One SGD step on (task loss + aux) moves gate and expert params;
    the router remains trainable through the dispatch einsums."""
    rng = np.random.RandomState(4)
    D, H, T, E = 8, 16, 32, 4
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    target = jnp.asarray(rng.randn(T, D).astype(np.float32))
    m = MoEFFN(D, H, E, seed=1)
    params = m.params()

    def loss(p):
        y, aux = m.apply(p, x)
        return jnp.mean((y - target) ** 2) + 0.01 * aux

    l0 = float(loss(params))
    grads = jax.grad(loss)(params)
    assert all(float(jnp.abs(g).sum()) > 0 for g in grads)
    params2 = tuple(p - 0.1 * g for p, g in zip(params, grads))
    l1 = float(loss(params2))
    assert l1 < l0, (l0, l1)


def test_gluon_moe_dense_trains():
    """The gluon MoEDense layer trains end-to-end through autograd
    (task loss + aux), incl. deferred shape inference."""
    import mxtpu as mx
    from mxtpu import autograd, nd
    from mxtpu.gluon import Trainer
    from mxtpu.gluon.contrib.nn import MoEDense

    rng = np.random.RandomState(5)
    layer = MoEDense(units=6, hidden=12, num_experts=4)
    layer.initialize(init="xavier")
    X = nd.array(rng.randn(32, 6).astype(np.float32))
    Yt = nd.array(rng.randn(32, 6).astype(np.float32))
    tr = Trainer(layer.collect_params(), "adam",
                 {"learning_rate": 0.01})
    losses = []
    for _ in range(30):
        with autograd.record():
            y, aux = layer(X)
            l = nd.mean(nd.square(y - Yt)) + 0.01 * aux
        l.backward()
        tr.step(32)
        losses.append(float(l.asscalar()))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])
    # gate gets gradients too (the router is trainable)
    with autograd.record():
        y, aux = layer(X)
        l = nd.mean(nd.square(y - Yt)) + 0.01 * aux
    l.backward()
    g = layer.gate_weight.grad()
    assert float(nd.sum(nd.abs(g)).asscalar()) > 0
