"""mxtpu.amp — policy-driven bf16 autocast with fp32 master weights,
dynamic loss scaling, and the bf16 ZeRO gradient exchange.

Parity tests run the SAME initial parameters through an AMP train
step and an f32 train step and require the loss trajectories to agree
to bf16 rounding; the contract tests pin the mechanics the ledgers
rely on (masters stay f32, params ride bf16, ``MXTPU_AMP=0`` produces
a byte-identical program, scaler state rides checkpoints)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxtpu import amp, nd, parallel
from mxtpu.gluon import nn
from mxtpu.parallel import restore_params, snapshot_params
from mxtpu.symbol import _is_aux_name


def _mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, have {len(devs)}")
    return jax.sharding.Mesh(np.array(devs[:n]), ("dp",))


def _dense_net(x, batchnorm=False):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, flatten=False))
    if batchnorm:
        net.add(nn.BatchNorm(axis=-1))
    net.add(nn.Dense(4, flatten=False))
    net.initialize(init="xavier")
    net(x)
    return net


def _mse(p, t):
    return ((p - t) ** 2).mean()


# ----------------------------------------------------------------------
# loss scaler units
# ----------------------------------------------------------------------
def test_scaler_grow_backoff_skip():
    st = amp.scaler_init(1024.0)
    assert float(st[0]) == 1024.0
    # finite steps below the window: scale holds, good_steps counts up
    st = amp.scaler_update(st, True, window=3)
    st = amp.scaler_update(st, True, window=3)
    assert float(st[0]) == 1024.0 and int(st[1]) == 2
    # window reached: grow x2, counter resets
    st = amp.scaler_update(st, True, window=3)
    assert float(st[0]) == 2048.0 and int(st[1]) == 0
    # non-finite: halve, count a skip, reset the streak
    st = amp.scaler_update(st, True, window=3)
    st = amp.scaler_update(st, False, window=3)
    assert float(st[0]) == 1024.0
    assert int(st[1]) == 0 and int(st[2]) == 1


def test_scaler_cap_and_floor():
    st = amp.scaler_init(2.0 ** 24)
    st = amp.scaler_update(st, True, window=1)
    assert float(st[0]) == 2.0 ** 24  # capped
    st = amp.scaler_init(1.0)
    st = amp.scaler_update(st, False, window=1)
    assert float(st[0]) == 1.0  # floored


def test_all_finite():
    good = (jnp.ones(3), jnp.zeros((2, 2), jnp.bfloat16))
    bad = (jnp.ones(3), jnp.asarray([1.0, np.inf]))
    assert bool(amp.all_finite(good))
    assert not bool(amp.all_finite(bad))
    # integer leaves never poison the verdict
    assert bool(amp.all_finite((jnp.arange(3),)))


def test_resolve_kill_switch_precedence(monkeypatch):
    monkeypatch.setenv("MXTPU_AMP", "0")
    assert amp.resolve(True) is False  # env kill beats the argument
    monkeypatch.setenv("MXTPU_AMP", "1")
    assert amp.resolve(None) is True
    monkeypatch.delenv("MXTPU_AMP")
    assert amp.resolve(None) is False
    assert amp.resolve(True) is True


# ----------------------------------------------------------------------
# master weights / parameter storage
# ----------------------------------------------------------------------
def test_masters_f32_params_bf16():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))
    net = _dense_net(x, batchnorm=True)
    step = parallel.build_train_step(net, _mse, "adam",
                                     {"learning_rate": 1e-3}, amp=True)
    step(x, y)
    for p in net.collect_params().values():
        want = jnp.float32 if _is_aux_name(p.name) else jnp.bfloat16
        assert p.data().dtype == want, p.name
    # every float optimizer-state leaf (momenta + the f32 master the
    # multi-precision rule seeds) stays full precision
    for leaf in jax.tree_util.tree_leaves(step._opt_state):
        dt = jnp.asarray(leaf).dtype
        if jnp.issubdtype(dt, jnp.floating):
            assert dt == jnp.float32
    stats = step.amp_stats()
    assert stats["skipped_steps"] == 0 and stats["loss_scale"] >= 1.0


def test_nonfinite_batch_skips_update(monkeypatch):
    monkeypatch.setenv("MXTPU_AMP_LOSS_SCALE", "1024")
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))
    net = _dense_net(x)
    step = parallel.build_train_step(net, _mse, "sgd",
                                     {"learning_rate": 0.1}, amp=True)
    step(x, y)
    before = snapshot_params(net)
    bad_y = nd.array(np.full((4, 4), np.inf, np.float32))
    step(x, bad_y)
    after = snapshot_params(net)
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b, a)
    stats = step.amp_stats()
    assert stats["skipped_steps"] == 1
    assert stats["loss_scale"] == 512.0  # halved on the bad step


# ----------------------------------------------------------------------
# AMP vs f32 parity (the tentpole's correctness claim)
# ----------------------------------------------------------------------
def _parity_run(net_fn, x, y, loss, opt, oparams, steps=3, **kw):
    losses = {}
    for mode in ("f32", "amp"):
        net = net_fn()
        net(x)  # materialize deferred shapes before snapshot/restore
        if "snap" not in losses:
            losses["snap"] = snapshot_params(net)
        restore_params(net, losses["snap"])
        step = parallel.build_train_step(
            net, loss, opt, dict(oparams),
            amp=(mode == "amp") or None, **kw)
        losses[mode] = [float(step(x, y).asscalar())
                        for _ in range(steps)]
    np.testing.assert_allclose(losses["amp"], losses["f32"],
                               rtol=3e-2, atol=1e-2)
    return losses


def test_amp_parity_bert():
    from mxtpu.models.transformer import BERTModel
    from mxtpu.gluon import loss as gloss
    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 128, (4, 8)).astype(np.float32))
    ce = gloss.SoftmaxCrossEntropyLoss()

    def loss(pred, t):
        return ce(pred.reshape((-1, 128)), t.reshape((-1,)))

    def net_fn():
        net = BERTModel(128, 32, 64, 1, 1, max_length=16, dropout=0.0)
        net.initialize(init="xavier")
        return net

    _parity_run(net_fn, x, x, loss, "adam", {"learning_rate": 1e-3},
                cast_batch=False)


def test_amp_parity_resnet():
    # a compact conv-BN-dense stack stands in for resnet18 here: it
    # exercises the same AMP paths (amp.conv_general's custom VJP,
    # BatchNorm aux exemption, FullyConnected) at a fraction of the
    # double compile — the full resnet18 AMP lowering is pinned by the
    # resnet18_amp ledger / hlocheck target instead
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon import nn
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(2, 3, 16, 16).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (2,)).astype(np.float32))

    def net_fn():
        net = nn.HybridSequential()
        net.add(nn.Conv2D(16, 3, padding=1),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.Conv2D(32, 3, strides=2, padding=1),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.GlobalAvgPool2D(), nn.Dense(10))
        net.initialize(init="xavier")
        return net

    _parity_run(net_fn, x, y, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                {"learning_rate": 0.05, "momentum": 0.9})


def test_amp_parity_transformer():
    from mxtpu.gluon.block import HybridBlock
    from mxtpu.gluon import loss as gloss
    from mxtpu.models.transformer import TransformerModel
    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 128, (2, 16)).astype(np.float32))
    y = nd.array(rng.randint(0, 128, (2, 8)).astype(np.float32))
    ce = gloss.SoftmaxCrossEntropyLoss()

    def loss(pred, t):
        return ce(pred.reshape((-1, 128)), t.reshape((-1,)))

    class MTWrap(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.model = TransformerModel(
                128, units=32, hidden_size=64, num_layers=1,
                num_heads=2, max_length=32, dropout=0.0)

        def hybrid_forward(self, F, xx):
            src = F.slice_axis(xx, axis=1, begin=0, end=8)
            tgt = F.slice_axis(xx, axis=1, begin=8, end=None)
            return self.model(src, tgt)

    def net_fn():
        net = MTWrap()
        net.initialize(init="xavier")
        return net

    _parity_run(net_fn, x, y, loss, "adam", {"learning_rate": 1e-4},
                cast_batch=False)


# ----------------------------------------------------------------------
# kill switch / program identity
# ----------------------------------------------------------------------
def test_kill_switch_bit_identical_program(monkeypatch):
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))

    def lowered(amp_flag):
        net = _dense_net(x)
        step = parallel.build_train_step(
            net, _mse, "adam", {"learning_rate": 1e-3}, amp=amp_flag)
        return step.lowered_hlo_text(x, y)

    monkeypatch.setenv("MXTPU_AMP", "0")
    killed = lowered(True)   # amp requested, env kills it
    monkeypatch.delenv("MXTPU_AMP")
    off = lowered(None)
    assert killed == off     # byte-identical pre-opt program
    on = lowered(True)
    assert on != off and "bf16" in on and "bf16" not in off


def test_zero_reduce_scatter_rides_bf16():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 8).astype(np.float32))
    y = nd.array(rng.randn(8, 4).astype(np.float32))

    def rs_lines(amp_flag):
        net = _dense_net(x)
        step = parallel.build_train_step(
            net, _mse, "adam", {"learning_rate": 1e-3},
            mesh=_mesh(), zero=1, amp=amp_flag)
        assert step.zero
        text = step.lowered_hlo_text(x, y)
        return [ln for ln in text.splitlines()
                if "reduce-scatter(" in ln]

    amp_rs = rs_lines(True)
    f32_rs = rs_lines(None)
    assert amp_rs and f32_rs
    # every AMP gradient exchange rides bf16; the f32 path none
    assert all("bf16[" in ln for ln in amp_rs)
    assert all("bf16[" not in ln for ln in f32_rs)


def test_zero_amp_parity():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 8).astype(np.float32))
    y = nd.array(rng.randn(8, 4).astype(np.float32))

    def run(amp_flag, snap):
        net = _dense_net(x)
        restore_params(net, snap)
        step = parallel.build_train_step(
            net, _mse, "adam", {"learning_rate": 1e-3},
            mesh=_mesh(), zero=1, amp=amp_flag)
        return [float(step(x, y).asscalar()) for _ in range(3)]

    snap = snapshot_params(_dense_net(x))
    np.testing.assert_allclose(run(True, snap), run(None, snap),
                               rtol=3e-2, atol=1e-2)


# ----------------------------------------------------------------------
# checkpointing
# ----------------------------------------------------------------------
def test_scaler_state_rides_checkpoint(tmp_path, monkeypatch):
    # window=1 so the scale moves every step — a fresh scaler would be
    # observably different after restore
    monkeypatch.setenv("MXTPU_AMP_SCALE_WINDOW", "1")
    monkeypatch.setenv("MXTPU_AMP_LOSS_SCALE", "256")
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))

    def make():
        net = _dense_net(x)
        return net, parallel.build_train_step(
            net, _mse, "adam", {"learning_rate": 1e-3}, amp=True)

    net, step = make()
    snap = snapshot_params(net)
    for _ in range(2):
        step(x, y)
    assert step.amp_stats()["loss_scale"] == 1024.0  # 256 -> 512 -> 1024
    fname = str(tmp_path / "amp.states")
    step.save_states(fname)

    net2, step2 = make()
    restore_params(net2, snap)
    step2.load_states(fname, x_example=x)
    assert step2.amp_stats() == step.amp_stats()
    # the restored run continues the schedule, not a fresh scaler
    step2(x, y)
    assert step2.amp_stats()["loss_scale"] == 2048.0
