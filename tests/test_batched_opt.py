"""Batched (shape/dtype-bucketed, stacked) optimizer update in the
compiled train step vs the per-parameter loop (ISSUE 2 tentpole 2):
sgd/adam are elementwise, so the stacked apply must be BIT-identical;
LAMB's per-slice trust-ratio norms may differ by reduction order only.
Also covers the new LAMB optimizer end to end."""
import numpy as np
import pytest

from mxtpu import autograd, gluon, nd, parallel
from mxtpu.gluon import nn
from mxtpu.parallel import snapshot_params, restore_params


def _make_net(x):
    net = nn.HybridSequential()
    # three Dense(16) → a 3-param bucket for weights and one for
    # biases, plus singleton buckets from the in/out layers
    net.add(nn.Dense(16, flatten=False), nn.Dense(16, flatten=False),
            nn.Dense(16, flatten=False), nn.Dense(4, flatten=False))
    net.initialize(init="xavier")
    net(x)
    return net


def _run(optname, oparams, batched, x, y, snap, steps=5,
         compute_dtype=None, monkeypatch=None):
    monkeypatch.setenv("MXTPU_BATCHED_OPT", "1" if batched else "0")
    net = _make_net(x)
    restore_params(net, snap)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), optname, dict(oparams),
        compute_dtype=compute_dtype)
    losses = [float(step(x, y).asscalar()) for _ in range(steps)]
    return losses, snapshot_params(net)


@pytest.fixture()
def _data():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 16).astype(np.float32))
    y = nd.array(rng.randn(8, 4).astype(np.float32))
    snap = snapshot_params(_make_net(x))
    return x, y, snap


@pytest.mark.parametrize("optname,oparams", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.05}),
    ("adam", {"learning_rate": 1e-3, "wd": 1e-4}),
])
def test_batched_bit_identical_elementwise_rules(optname, oparams,
                                                 _data, monkeypatch):
    x, y, snap = _data
    la, pa = _run(optname, oparams, True, x, y, snap,
                  monkeypatch=monkeypatch)
    lb, pb = _run(optname, oparams, False, x, y, snap,
                  monkeypatch=monkeypatch)
    assert la == lb
    for a, b in zip(pa, pb):
        np.testing.assert_array_equal(a, b)


def test_batched_lamb_matches_per_param(_data, monkeypatch):
    x, y, snap = _data
    la, pa = _run("lamb", {"learning_rate": 1e-2, "wd": 1e-2}, True,
                  x, y, snap, monkeypatch=monkeypatch)
    lb, pb = _run("lamb", {"learning_rate": 1e-2, "wd": 1e-2}, False,
                  x, y, snap, monkeypatch=monkeypatch)
    # trust-ratio norms reduce in a different order when stacked:
    # per-dtype tolerance, not bitwise
    np.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-7)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("optname,oparams", [
    ("adam", {"learning_rate": 1e-3, "wd": 1e-4}),
    ("lamb", {"learning_rate": 1e-2, "wd": 1e-2}),
])
def test_batched_multi_precision_bf16(optname, oparams, _data,
                                      monkeypatch):
    """compute_dtype='bfloat16' (the multi_precision recipe: bf16
    fwd/bwd, f32 master weights + optimizer state) batched vs
    per-param."""
    x, y, snap = _data
    la, pa = _run(optname, oparams, True, x, y, snap,
                  compute_dtype="bfloat16", monkeypatch=monkeypatch)
    lb, pb = _run(optname, oparams, False, x, y, snap,
                  compute_dtype="bfloat16", monkeypatch=monkeypatch)
    np.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-7)
    for a, b in zip(pa, pb):
        assert a.dtype == np.float32  # master weights stay f32
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_batched_run_steps_scan_path(_data, monkeypatch):
    """The scanned multi-step path threads the bucketed update through
    lax.scan and still converges."""
    monkeypatch.setenv("MXTPU_BATCHED_OPT", "1")
    x, y, snap = _data
    net = _make_net(x)
    restore_params(net, snap)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "adam",
        {"learning_rate": 3e-3})
    losses = step.run_steps(x, y, steps=12, reuse_batch=True)
    ls = np.asarray(losses.asnumpy())
    assert ls.shape == (12,)
    assert ls[-1] < ls[0], ls


def test_batched_save_load_states_roundtrip(tmp_path, _data,
                                            monkeypatch):
    monkeypatch.setenv("MXTPU_BATCHED_OPT", "1")
    x, y, snap = _data
    net = _make_net(x)
    restore_params(net, snap)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "lamb",
        {"learning_rate": 1e-2})
    for _ in range(3):
        step(x, y)
    fname = str(tmp_path / "opt.states")
    step.save_states(fname)
    step.load_states(fname)
    l4 = float(step(x, y).asscalar())
    assert np.isfinite(l4)


def test_lamb_eager_trainer_converges(_data):
    """The eager gluon.Trainer path of the new LAMB optimizer."""
    x, y, snap = _data
    net = _make_net(x)
    restore_params(net, snap)
    tr = gluon.Trainer(net.collect_params(), "lamb",
                       {"learning_rate": 5e-3})
    losses = []
    for _ in range(20):
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        tr.step(8)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_lamb_trust_ratio_scale_invariance():
    """LAMB's defining property: scaling the gradient does not change
    the step (trust ratio renormalizes) — exact up to the epsilon term
    in m̂/(√v̂+ε), hence the loose tolerance."""
    rng = np.random.RandomState(3)
    w = rng.randn(32, 32).astype(np.float32)
    g = rng.randn(32, 32).astype(np.float32)
    outs = []
    for scale in (1.0, 100.0):
        wn, m, v = nd.lamb_update(
            nd.array(w), nd.array(g * scale), nd.array(np.zeros_like(w)),
            nd.array(np.zeros_like(w)), nd.array(np.asarray(1, np.int32)),
            lr=0.1, wd=0.0)
        outs.append(np.asarray(wn.asnumpy()))
    np.testing.assert_allclose(outs[0], outs[1], rtol=5e-3, atol=1e-3)
    # and the update actually moved the weights
    assert np.abs(outs[0] - w).max() > 1e-3
