"""Fused residual epilogue — LN(res + dropout(h + bias)) — parity
tests (ISSUE 2 tentpole 1).  The Pallas kernel and the lax composite
share one threefry mask helper, so parity is exact seeded-mask
equality, not a statistical check.  On CPU the kernel runs in
interpreter mode via MXTPU_PALLAS=interpret."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxtpu import autograd, nd
from mxtpu.gluon import nn
from mxtpu.kernels.layer_norm import (
    _keep_thresh, _mask_bits, _threefry2x32,
    fused_residual_layer_norm, fused_residual_ln_reference,
    layer_norm_reference)


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("MXTPU_PALLAS", "interpret")


def _inputs(seed=0, shape=(2, 16, 256), dtype=np.float32):
    rng = np.random.RandomState(seed)
    C = shape[-1]
    h = jnp.asarray(rng.randn(*shape).astype(dtype))
    res = jnp.asarray(rng.randn(*shape).astype(dtype))
    bias = jnp.asarray(rng.randn(C).astype(dtype))
    g = jnp.asarray(rng.uniform(0.5, 1.5, C).astype(dtype))
    b = jnp.asarray(rng.randn(C).astype(dtype))
    kd = jnp.asarray([123, 456], jnp.uint32)
    return h, bias, res, g, b, kd


def test_threefry_known_answer_vectors():
    # official Random123 KAT: key=(0,0), ctr=(0,0) and the pi-digit
    # vector — guards the hand-rolled implementation against drift
    y0, y1 = _threefry2x32(jnp.uint32(0), jnp.uint32(0),
                           jnp.uint32(0), jnp.uint32(0))
    assert (int(y0), int(y1)) == (0x6B200159, 0x99BA4EFE)
    y0, y1 = _threefry2x32(jnp.uint32(0x13198A2E), jnp.uint32(0x03707344),
                           jnp.uint32(0x243F6A88), jnp.uint32(0x85A308D3))
    assert (int(y0), int(y1)) == (0xC4923A9C, 0x483DF7A0)


def test_forward_parity_seeded_mask():
    h, bias, res, g, b, kd = _inputs()
    y_p = fused_residual_layer_norm(h, bias, res, g, b, kd, p=0.1)
    y_r = fused_residual_ln_reference(h, bias, res, g, b, kd, p=0.1)
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_r),
                               rtol=1e-5, atol=1e-5)
    # deterministic in the key, sensitive to it
    y_p2 = fused_residual_layer_norm(h, bias, res, g, b, kd, p=0.1)
    assert np.array_equal(np.asarray(y_p), np.asarray(y_p2))
    kd2 = jnp.asarray([124, 456], jnp.uint32)
    y_k2 = fused_residual_layer_norm(h, bias, res, g, b, kd2, p=0.1)
    assert not np.array_equal(np.asarray(y_p), np.asarray(y_k2))


def test_mask_fraction_matches_p():
    bits = _mask_bits(jnp.uint32(7), jnp.uint32(11), jnp.uint32(0),
                      512, 1024)
    dropped = float((bits >= jnp.uint32(_keep_thresh(0.9))).mean())
    assert abs(dropped - 0.1) < 0.01


def test_grad_parity_all_operands():
    h, bias, res, g, b, kd = _inputs(seed=1)

    def loss(fn):
        return lambda h, bias, res, g, b: jnp.sum(
            jnp.sin(fn(h, bias, res, g, b, kd, p=0.1)))

    gp = jax.grad(loss(fused_residual_layer_norm),
                  argnums=(0, 1, 2, 3, 4))(h, bias, res, g, b)
    gr = jax.grad(loss(fused_residual_ln_reference),
                  argnums=(0, 1, 2, 3, 4))(h, bias, res, g, b)
    for name, a, c in zip(("dh", "dbias", "dres", "dgamma", "dbeta"),
                          gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_eval_mode_is_plain_ln_of_sum():
    h, bias, res, g, b, kd = _inputs(seed=2)
    y = fused_residual_layer_norm(h, bias, res, g, b, kd, p=0.1,
                                  training=False)
    ref = layer_norm_reference(res + h + bias, g, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_bf16_path():
    h, bias, res, g, b, kd = _inputs(seed=3)
    h16, res16 = h.astype(jnp.bfloat16), res.astype(jnp.bfloat16)
    y_p = fused_residual_layer_norm(h16, bias, res16, g, b, kd, p=0.1)
    y_r = fused_residual_ln_reference(h16, bias, res16, g, b, kd, p=0.1)
    assert y_p.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y_p, np.float32), np.asarray(y_r, np.float32),
        rtol=2e-2, atol=2e-2)


def test_kill_switch_uses_composite(monkeypatch):
    h, bias, res, g, b, kd = _inputs(seed=4)
    y_on = fused_residual_layer_norm(h, bias, res, g, b, kd, p=0.1)
    monkeypatch.setenv("MXTPU_FUSED_LN_EPILOGUE", "0")
    y_off = fused_residual_layer_norm(h, bias, res, g, b, kd, p=0.1)
    # identical numerics either way (shared mask helper)
    np.testing.assert_allclose(np.asarray(y_on), np.asarray(y_off),
                               rtol=1e-5, atol=1e-5)


def test_odd_rows_fall_back():
    # 3 rows: no Pallas row block — must still work via the composite
    h, bias, res, g, b, kd = _inputs(seed=5, shape=(3, 128))
    y = fused_residual_layer_norm(h, bias, res, g, b, kd, p=0.1)
    ref = fused_residual_ln_reference(h, bias, res, g, b, kd, p=0.1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ----------------------------------------------------------------------
# op / layer wiring
# ----------------------------------------------------------------------

def test_nd_op_training_vs_predict():
    rng = np.random.RandomState(6)
    C = 64
    h = nd.array(rng.randn(4, 8, C).astype(np.float32))
    res = nd.array(rng.randn(4, 8, C).astype(np.float32))
    bias = nd.array(rng.randn(C).astype(np.float32))
    g = nd.array(np.ones(C, np.float32))
    b = nd.array(np.zeros(C, np.float32))
    # outside autograd.record: eval mode, deterministic LN(res+h+bias)
    y = nd.FusedResidualLayerNorm(h, bias, res, g, b)
    ref = layer_norm_reference(res._data + h._data + bias._data,
                               g._data, b._data)
    np.testing.assert_allclose(np.asarray(y._data), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # under training: elements actually drop
    with autograd.record(train_mode=True):
        yt = nd.FusedResidualLayerNorm(h, bias, res, g, b, p=0.5)
    assert not np.allclose(np.asarray(yt._data), np.asarray(ref))


def test_gluon_layer_deferred_init_and_eval_parity():
    rng = np.random.RandomState(7)
    layer = nn.FusedResidualLayerNorm(dropout=0.1)
    layer.initialize()
    x = nd.array(rng.randn(2, 8, 32).astype(np.float32))
    r = nd.array(rng.randn(2, 8, 32).astype(np.float32))
    y = layer(x, r)
    assert y.shape == (2, 8, 32)
    assert layer.gamma.data().shape == (32,)
    # eval mode == LN(res + x + bias) with the layer's params
    ref = layer_norm_reference(
        r._data + x._data + layer.bias.data()._data,
        layer.gamma.data()._data, layer.beta.data()._data)
    np.testing.assert_allclose(np.asarray(y._data), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_encoder_cell_eval_matches_unfused_composition():
    """The rewired cell (bias folded into the epilogue) must compute
    the same function as the textbook post-LN composition."""
    from mxtpu.models.transformer import TransformerEncoderCell
    rng = np.random.RandomState(8)
    cell = TransformerEncoderCell(32, 64, 4, dropout=0.1)
    cell.initialize()
    x = nd.array(rng.randn(2, 8, 32).astype(np.float32))
    y = cell(x)  # eval mode: dropout off

    # manual unfused recomputation from the cell's own params
    def dense(t, w, b=None):
        out = jnp.dot(t, w._data.T)
        return out + b._data if b is not None else out

    xj = x._data
    qkv = dense(xj, cell.attn.qkv.weight.data(),
                cell.attn.qkv.bias.data())
    u = 32
    q, k, v = qkv[..., :u], qkv[..., u:2 * u], qkv[..., 2 * u:]

    def split(t):
        return jnp.transpose(t.reshape(2, 8, 4, 8), (0, 2, 1, 3))

    q, k, v = split(q), split(k), split(v)
    s = jnp.einsum("nhtd,nhsd->nhts", q, k) / np.sqrt(8.0)
    a = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("nhts,nhsd->nhtd", a, v)
    o = jnp.transpose(o, (0, 2, 1, 3)).reshape(2, 8, 32)
    attn = dense(o, cell.attn.proj.weight.data())
    h1 = layer_norm_reference(
        xj + attn + cell.ln1.bias.data()._data,
        cell.ln1.gamma.data()._data, cell.ln1.beta.data()._data)
    ff = dense(jax.nn.gelu(dense(h1, cell.ffn.ffn1.weight.data(),
                                 cell.ffn.ffn1.bias.data()),
                           approximate=False),
               cell.ffn.ffn2.weight.data())
    h2 = layer_norm_reference(
        h1 + ff + cell.ln2.bias.data()._data,
        cell.ln2.gamma.data()._data, cell.ln2.beta.data()._data)
    np.testing.assert_allclose(np.asarray(y._data), np.asarray(h2),
                               rtol=1e-4, atol=1e-4)


def test_traced_train_step_with_epilogue():
    """Mini encoder trains through the fused epilogue in the compiled
    train step (the traced path feeds fold_in keys to the kernel)."""
    from mxtpu.models.transformer import TransformerEncoder
    from mxtpu import parallel
    net = TransformerEncoder(2, 32, 64, 4, dropout=0.1)
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, lambda pred, y: ((pred - y) ** 2).mean(),
        "sgd", {"learning_rate": 0.05})
    rng = np.random.RandomState(9)
    x = nd.array(rng.randn(2, 8, 32).astype(np.float32))
    y = nd.array(rng.randn(2, 8, 32).astype(np.float32))
    losses = [float(step(x, y).asscalar()) for _ in range(6)]
    assert losses[-1] < losses[0], losses
