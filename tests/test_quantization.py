"""INT8 quantization depth (VERDICT r2 item 6): entropy/KL
calibration, the quantize_model graph rewrite, and int8 conv/fc
execution vs float within tolerance.

Reference: python/mxnet/contrib/quantization.py†,
src/operator/quantization/*†.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import io as mio
from mxtpu import nd
from mxtpu.contrib import quantization as q
from mxtpu.executor import Executor


def _convnet():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                            pad=(1, 1), name="conv1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
    f1 = mx.sym.Flatten(p1)
    fc = mx.sym.FullyConnected(f1, num_hidden=10, name="fc1")
    return mx.sym.softmax(fc, axis=-1)


def _setup(seed=0):
    rng = np.random.RandomState(seed)
    sym = _convnet()
    arg_shapes, _, _ = sym.infer_shape(data=(4, 3, 16, 16))
    args = {n: nd.array(rng.randn(*s).astype(np.float32) * 0.2)
            for n, s in zip(sym.list_arguments(), arg_shapes)
            if n != "data"}
    X = rng.randn(32, 3, 16, 16).astype(np.float32)
    return sym, args, X


def test_optimal_threshold_clips_outliers():
    rng = np.random.RandomState(0)
    # bulk mass in [-1, 1] plus a few extreme outliers: the KL
    # threshold should land well below the abs-max
    a = np.concatenate([rng.randn(100000),
                        np.asarray([40.0, -35.0, 30.0])])
    t = q.optimal_threshold(a)
    assert t < 15.0, t
    assert t > 1.0, t
    # pure gaussian: threshold within the support
    t2 = q.optimal_threshold(rng.randn(50000))
    assert 1.0 < t2 < 6.0


def test_calib_entropy_symmetric_ranges():
    rng = np.random.RandomState(1)
    out = q.calib_entropy({"x": [rng.randn(1000).astype(np.float32)]})
    lo, hi = out["x"]
    assert lo == -hi and hi > 0


def test_collect_layer_outputs():
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    names = ["conv1_output"]
    got = q.collect_layer_outputs(sym, args, {}, it, names,
                                  num_batches=3)
    assert len(got["conv1_output"]) == 3
    assert got["conv1_output"][0].shape == (4, 8, 16, 16)


@pytest.mark.parametrize("mode", ["naive", "entropy"])
def test_quantize_model_matches_float(mode):
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    qsym, qargs, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                      calib_mode=mode,
                                      num_calib_batches=4)
    # the rewrite actually int8-ized the compute ops
    ops = [n.op for n in qsym._topo() if n.op]
    assert "_contrib_quantized_conv" in ops
    assert "_contrib_quantized_fully_connected" in ops
    assert "Convolution" not in ops and "FullyConnected" not in ops

    fa = dict(args)
    fa["data"] = nd.array(X[:4])
    fout = Executor(sym, args=fa,
                    grad_req="null").forward()[0].asnumpy()
    qa = {k: v for k, v in dict(qargs, data=nd.array(X[:4])).items()
          if k in qsym.list_arguments()}
    qout = Executor(qsym, args=qa,
                    grad_req="null").forward()[0].asnumpy()
    assert np.abs(qout - fout).max() < 0.05
    # int8 model still ranks classes like the float one (argmax parity
    # on most samples)
    agree = (qout.argmax(1) == fout.argmax(1)).mean()
    assert agree >= 0.75, agree


def test_quantize_model_excluded_names_stay_float():
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    qsym, _, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                  calib_mode="naive",
                                  excluded_sym_names=("conv1",))
    ops = [n.op for n in qsym._topo() if n.op]
    assert "Convolution" in ops
    assert "_contrib_quantized_fully_connected" in ops


def test_quantize_model_roundtrips_json():
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    qsym, qargs, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                      calib_mode="naive")
    back = mx.sym.fromjson(qsym.tojson())
    qa = {k: v for k, v in dict(qargs, data=nd.array(X[:4])).items()
          if k in back.list_arguments()}
    out = Executor(back, args=qa, grad_req="null").forward()[0]
    assert out.shape == (4, 10)
