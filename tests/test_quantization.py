"""INT8 quantization depth (VERDICT r2 item 6): entropy/KL
calibration, the quantize_model graph rewrite, and int8 conv/fc
execution vs float within tolerance.

Reference: python/mxnet/contrib/quantization.py†,
src/operator/quantization/*†.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import io as mio
from mxtpu import nd
from mxtpu.contrib import quantization as q
from mxtpu.executor import Executor


def _convnet():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                            pad=(1, 1), name="conv1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
    f1 = mx.sym.Flatten(p1)
    fc = mx.sym.FullyConnected(f1, num_hidden=10, name="fc1")
    return mx.sym.softmax(fc, axis=-1)


def _setup(seed=0):
    rng = np.random.RandomState(seed)
    sym = _convnet()
    arg_shapes, _, _ = sym.infer_shape(data=(4, 3, 16, 16))
    args = {n: nd.array(rng.randn(*s).astype(np.float32) * 0.2)
            for n, s in zip(sym.list_arguments(), arg_shapes)
            if n != "data"}
    X = rng.randn(32, 3, 16, 16).astype(np.float32)
    return sym, args, X


def test_optimal_threshold_clips_outliers():
    rng = np.random.RandomState(0)
    # bulk mass in [-1, 1] plus a few extreme outliers: the KL
    # threshold should land well below the abs-max
    a = np.concatenate([rng.randn(100000),
                        np.asarray([40.0, -35.0, 30.0])])
    t = q.optimal_threshold(a)
    assert t < 15.0, t
    assert t > 1.0, t
    # pure gaussian: threshold within the support
    t2 = q.optimal_threshold(rng.randn(50000))
    assert 1.0 < t2 < 6.0


def test_calib_entropy_symmetric_ranges():
    rng = np.random.RandomState(1)
    out = q.calib_entropy({"x": [rng.randn(1000).astype(np.float32)]})
    lo, hi = out["x"]
    assert lo == -hi and hi > 0


def test_collect_layer_outputs():
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    names = ["conv1_output"]
    got = q.collect_layer_outputs(sym, args, {}, it, names,
                                  num_batches=3)
    assert len(got["conv1_output"]) == 3
    assert got["conv1_output"][0].shape == (4, 8, 16, 16)


@pytest.mark.parametrize("mode", ["naive", "entropy"])
def test_quantize_model_matches_float(mode):
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    qsym, qargs, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                      calib_mode=mode,
                                      num_calib_batches=4)
    # the rewrite actually int8-ized the compute ops
    ops = [n.op for n in qsym._topo() if n.op]
    assert "_contrib_quantized_conv" in ops
    assert "_contrib_quantized_fully_connected" in ops
    assert "Convolution" not in ops and "FullyConnected" not in ops

    fa = dict(args)
    fa["data"] = nd.array(X[:4])
    fout = Executor(sym, args=fa,
                    grad_req="null").forward()[0].asnumpy()
    qa = {k: v for k, v in dict(qargs, data=nd.array(X[:4])).items()
          if k in qsym.list_arguments()}
    qout = Executor(qsym, args=qa,
                    grad_req="null").forward()[0].asnumpy()
    assert np.abs(qout - fout).max() < 0.05
    # int8 model still ranks classes like the float one (argmax parity
    # on most samples)
    agree = (qout.argmax(1) == fout.argmax(1)).mean()
    assert agree >= 0.75, agree


def test_quantize_model_excluded_names_stay_float():
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    qsym, _, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                  calib_mode="naive",
                                  excluded_sym_names=("conv1",))
    ops = [n.op for n in qsym._topo() if n.op]
    assert "Convolution" in ops
    assert "_contrib_quantized_fully_connected" in ops


def test_quantize_model_roundtrips_json():
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)
    qsym, qargs, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                      calib_mode="naive")
    back = mx.sym.fromjson(qsym.tojson())
    qa = {k: v for k, v in dict(qargs, data=nd.array(X[:4])).items()
          if k in back.list_arguments()}
    out = Executor(back, args=qa, grad_req="null").forward()[0]
    assert out.shape == (4, 10)


def test_uint8_quantize_roundtrip():
    # shifted-range uint8: [0, hi] with zero point 0
    rng = np.random.RandomState(2)
    f = np.abs(rng.randn(64).astype(np.float32)) * 3
    a = nd.array(f)
    qv, lo, hi = nd.quantize_v2(a, out_type="uint8")
    assert qv.asnumpy().dtype == np.uint8
    back = nd.dequantize(qv, lo, hi).asnumpy()
    assert np.abs(back - f).max() < float(hi.asnumpy()) / 255 + 1e-6


def test_requantize_uint8():
    # int32 accumulators -> uint8 with calibrated shifted range
    acc = nd.array(np.arange(0, 1000, 10, dtype=np.int32))
    lo32, hi32 = nd.array(np.float32([-100.0])), \
        nd.array(np.float32([100.0]))
    qu, lo, hi = nd.requantize(acc, lo32, hi32, min_calib_range=-1.0,
                               max_calib_range=50.0, out_type="uint8")
    assert qu.asnumpy().dtype == np.uint8
    assert float(lo.asnumpy()) == 0.0  # negative calib lo clamps to 0


def test_quantized_conv_uint8_not_int8_wrapped():
    """uint8 activations 128..255 must NOT wrap negative through an
    int8 cast (r3 advisor medium finding)."""
    from mxtpu.ops.registry import get_op
    import jax.numpy as jnp
    rng = np.random.RandomState(3)
    # float data in [0, 4] quantized to uint8 over [0, 4]
    fd = rng.rand(2, 3, 8, 8).astype(np.float32) * 4
    fw = (rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3)
    hi_d, amax_w = 4.0, float(np.abs(fw).max())
    qd = np.clip(np.round(fd * 255 / hi_d), 0, 255).astype(np.uint8)
    qw = np.clip(np.round(fw * 127 / amax_w), -127,
                 127).astype(np.int8)
    out32, lo, hi = get_op("_contrib_quantized_conv")(
        jnp.asarray(qd), jnp.asarray(qw),
        jnp.float32(0.0), jnp.float32(hi_d),
        jnp.float32(-amax_w), jnp.float32(amax_w),
        kernel=(3, 3), stride=(1, 1), pad=(1, 1), num_filter=4)
    # dequantize accumulator and compare against float conv
    import jax
    ref = np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(fd), jnp.asarray(fw), (1, 1), [(1, 1), (1, 1)]))
    unit = (hi_d / 255) * (amax_w / 127)
    got = np.asarray(out32, np.float32) * unit
    assert np.abs(got - ref).max() / max(np.abs(ref).max(), 1e-6) \
        < 0.02
    # fc path too: flattened uint8 x int8
    fwf = rng.randn(4, 192).astype(np.float32) * 0.1
    amax_f = float(np.abs(fwf).max())
    qwf = np.clip(np.round(fwf * 127 / amax_f), -127,
                  127).astype(np.int8)
    qfc, _, _ = get_op("_contrib_quantized_fully_connected")(
        jnp.asarray(qd), jnp.asarray(qwf),
        jnp.float32(0.0), jnp.float32(hi_d),
        jnp.float32(-amax_f), jnp.float32(amax_f), num_hidden=4)
    reffc = fd.reshape(2, -1) @ fwf.T
    gotfc = np.asarray(qfc, np.float32) * (hi_d / 255) * (amax_f / 127)
    assert np.abs(gotfc - reffc).max() / np.abs(reffc).max() < 0.02


@pytest.mark.parametrize("dtype", ["uint8", "auto"])
def test_quantize_model_uint8_matches_float(dtype):
    """calib -> rewrite -> run parity for the uint8 tier (VERDICT r3
    item 7).  With 'auto', the post-ReLU fc input goes uint8 while the
    signed data input stays int8."""
    sym, args, X = _setup()
    X = np.abs(X)  # non-negative input so 'uint8' is honest end-to-end
    it = mio.NDArrayIter(X, None, batch_size=4)
    qsym, qargs, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                      calib_mode="naive",
                                      quantized_dtype=dtype,
                                      num_calib_batches=4)
    ops = [n.op for n in qsym._topo() if n.op]
    assert "_contrib_quantized_conv" in ops
    fa = dict(args)
    fa["data"] = nd.array(X[:4])
    fout = Executor(sym, args=fa,
                    grad_req="null").forward()[0].asnumpy()
    qa = {k: v for k, v in dict(qargs, data=nd.array(X[:4])).items()
          if k in qsym.list_arguments()}
    qout = Executor(qsym, args=qa,
                    grad_req="null").forward()[0].asnumpy()
    assert np.abs(qout - fout).max() < 0.05
    agree = (qout.argmax(1) == fout.argmax(1)).mean()
    assert agree >= 0.75, agree


def test_quantize_model_auto_picks_uint8_post_relu():
    sym, args, X = _setup()
    it = mio.NDArrayIter(X, None, batch_size=4)  # signed data input
    qsym, qargs, _ = q.quantize_model(sym, args, {}, data_iter=it,
                                      calib_mode="naive",
                                      quantized_dtype="auto",
                                      num_calib_batches=4)
    quants = [n for n in qsym._topo() if n.op == "quantize_v2"]
    outs = {n.attrs.get("out_type") for n in quants}
    # signed data -> int8 quantize; post-relu-pool fc input -> uint8
    assert outs == {"int8", "uint8"}, outs


def test_quantize_model_uint8_rejects_negative_input():
    from mxtpu.base import MXNetError
    sym, args, X = _setup()  # X is signed (randn)
    it = mio.NDArrayIter(X, None, batch_size=4)
    with pytest.raises(MXNetError):
        q.quantize_model(sym, args, {}, data_iter=it,
                         calib_mode="naive", quantized_dtype="uint8")
