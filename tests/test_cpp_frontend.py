"""C++ frontend (VERDICT r2 item 10): compile the cpp_package example
against libmxtpu_predict and run inference from an exported
checkpoint, including the MXPredReshape path.

Reference: cpp-package† (generated C++ surface over the C API).
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.gluon import nn

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CORE = os.path.join(_ROOT, "core")
_CPP = os.path.join(_ROOT, "cpp_package")
_LIB = os.path.join(_CORE, "libmxtpu_predict.so")


def _ensure_lib(target="predict", lib=_LIB):
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("g++/make not available")
    if not os.path.exists(lib):
        r = subprocess.run(
            ["make", target, f"PYTHON={sys.executable}"],
            cwd=_CORE, capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-1000:]


@pytest.fixture(scope="module")
def exported_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("cppfront")
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init="xavier")
    x = nd.array(np.random.RandomState(0).randn(2, 8)
                 .astype(np.float32))
    net(x)
    sym_file, param_file = net.export(str(d / "model"))
    return sym_file, param_file


def test_cpp_example_compiles_and_runs(exported_model, tmp_path):
    _ensure_lib()
    sym_file, param_file = exported_model
    exe = str(tmp_path / "predict")
    r = subprocess.run(
        ["g++", "-std=c++17",
         os.path.join(_CPP, "example", "predict.cc"),
         "-I" + os.path.join(_CPP, "include"),
         "-L" + _CORE, "-lmxtpu_predict",
         "-Wl,-rpath," + _CORE, "-o", exe],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-1500:]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the embedded interpreter must find the mxtpu package
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    run = subprocess.run([exe, sym_file, param_file, "2", "8"],
                         capture_output=True, text=True, env=env,
                         timeout=240)
    assert run.returncode == 0, (run.stdout, run.stderr[-1500:])
    assert "output shape: 2 4" in run.stdout
    assert "row 0 -> class" in run.stdout
    assert "reshaped batch 4 ok" in run.stdout


def test_python_reshape_matches_original(exported_model):
    """MXPredReshape semantics at the python layer: same weights, new
    batch shape, identical outputs on identical rows."""
    from mxtpu.c_predict import Predictor
    sym_file, param_file = exported_model
    with open(sym_file) as f:
        sym_json = f.read()
    with open(param_file, "rb") as f:
        params = f.read()
    rng = np.random.RandomState(1)
    x = rng.randn(2, 8).astype(np.float32)
    p = Predictor(sym_json, params, 1, 0, {"data": (2, 8)})
    p.set_input("data", x.tobytes())
    p.forward()
    out2 = np.frombuffer(p.get_output(0), np.float32).reshape(2, 4)
    p4 = p.reshape({"data": (4, 8)})
    x4 = np.concatenate([x, x])
    p4.set_input("data", x4.tobytes())
    p4.forward()
    out4 = np.frombuffer(p4.get_output(0), np.float32).reshape(4, 4)
    np.testing.assert_allclose(out4[:2], out2, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out4[2:], out2, rtol=1e-5, atol=1e-6)


def test_cpp_training_frontend(tmp_path):
    """C++ RAII training frontend (mxtpu-cpp/ndarray.hpp over the
    training C ABI) trains a linear model end-to-end — the reference
    cpp-package's training capability."""
    _ensure_lib("ndarray", os.path.join(_CORE, "libmxtpu_ndarray.so"))
    exe = str(tmp_path / "cpp_train")
    r = subprocess.run(
        ["g++", "-std=c++17",
         os.path.join(_ROOT, "cpp_package", "example", "train.cc"),
         f"-L{_CORE}", "-lmxtpu_ndarray", f"-Wl,-rpath,{_CORE}",
         "-o", exe],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-1500:]
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([exe], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, \
        f"stdout:{r.stdout[-800:]}\nstderr:{r.stderr[-800:]}"
    assert "C++ training frontend OK" in r.stdout, r.stdout[-800:]
