"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's trick of testing
distributed paths with local multiprocess, SURVEY.md §4.5, maps to XLA's
host-platform device-count flag).  Set MXTPU_TEST_PLATFORM=tpu to run the
suite against the real chip instead (the check_consistency harness then
compares cpu↔tpu).
"""
import os
import sys

# Must happen before the first real jax backend use.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if os.environ.get("MXTPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: strict/heavy variants excluded from the tier-1 "
        "`-m 'not slow'` run")
    config.addinivalue_line(
        "markers",
        "thread_leak_ok: opt out of the leaked-thread gate (tests "
        "that intentionally strand a worker, e.g. hang-fault soaks)")
    config.addinivalue_line(
        "markers",
        "mxrace_off: opt out of the MXTPU_RACE=1 sanitizer (tests "
        "that drive their own LocksetChecker, e.g. seeded races)")


@pytest.fixture(autouse=True)
def _seed_everything():
    """Per-test deterministic seeding — the reference's @with_seed()
    (tests/python/unittest/common.py†). MXTPU_TEST_SEED overrides."""
    import mxtpu
    seed = int(os.environ.get("MXTPU_TEST_SEED",
                              os.environ.get("MXNET_TEST_SEED", "42")))
    np.random.seed(seed)
    mxtpu.random.seed(seed)
    yield


# thread pools park non-daemon workers for reuse; those are pool
# lifecycle, not a test leaking its own worker
_LEAK_ALLOW = ("ThreadPoolExecutor-", "asyncio_", "pydevd.")
_LEAK_GRACE_S = 2.0


@pytest.fixture(autouse=True)
def _no_thread_leaks(request):
    """Fail any test that exits with live non-daemon threads it
    started (mxrace satellite: a leaked fleet/serving worker keeps the
    whole pytest process from exiting and poisons later tests'
    lockset state).  Opt out with ``@pytest.mark.thread_leak_ok``."""
    import threading
    before = set(threading.enumerate())
    yield
    if request.node.get_closest_marker("thread_leak_ok"):
        return
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive() and not t.daemon
              and not t.name.startswith(_LEAK_ALLOW)]
    for t in leaked:                      # shutdown race grace
        t.join(timeout=_LEAK_GRACE_S)
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        names = ", ".join(sorted(t.name for t in leaked))
        pytest.fail(
            f"leaked non-daemon thread(s): {names} — join or close "
            f"them in the test, or mark it @pytest.mark.thread_leak_ok",
            pytrace=False)


_RACE_PLAN = None   # (cls, guarded) pairs, built once per session


@pytest.fixture(autouse=True)
def _race_sanitizer(request):
    """Opt-in deterministic race detection: ``MXTPU_RACE=1 pytest``
    reruns every test under the mxrace lockset sanitizer
    (mxtpu/analysis/lockset.py) with the serving/obs classes
    instrumented per their ``# guarded-by:`` annotations."""
    if os.environ.get("MXTPU_RACE", "0") not in ("1", "true", "on") \
            or request.node.get_closest_marker("mxrace_off"):
        yield
        return
    from mxtpu.analysis import lockset
    global _RACE_PLAN
    if _RACE_PLAN is None:
        probe = lockset.LocksetChecker()
        lockset.install_default(probe)
        _RACE_PLAN = list(probe._instrumented)
    checker = lockset.LocksetChecker()
    for cls, attrs, guarded in _RACE_PLAN:
        checker.instrument(cls, attrs=attrs, guarded=guarded)
    with checker.activate():
        yield
    if checker.reports:
        msgs = "\n  ".join(r.format() for r in checker.reports)
        pytest.fail(f"mxrace lockset sanitizer:\n  {msgs}",
                    pytrace=False)
