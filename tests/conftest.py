"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's trick of testing
distributed paths with local multiprocess, SURVEY.md §4.5, maps to XLA's
host-platform device-count flag).  Set MXTPU_TEST_PLATFORM=tpu to run the
suite against the real chip instead (the check_consistency harness then
compares cpu↔tpu).
"""
import os
import sys

# Must happen before the first real jax backend use.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if os.environ.get("MXTPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: strict/heavy variants excluded from the tier-1 "
        "`-m 'not slow'` run")


@pytest.fixture(autouse=True)
def _seed_everything():
    """Per-test deterministic seeding — the reference's @with_seed()
    (tests/python/unittest/common.py†). MXTPU_TEST_SEED overrides."""
    import mxtpu
    seed = int(os.environ.get("MXTPU_TEST_SEED",
                              os.environ.get("MXNET_TEST_SEED", "42")))
    np.random.seed(seed)
    mxtpu.random.seed(seed)
    yield
