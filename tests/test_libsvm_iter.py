"""LibSVMIter (reference ``src/io/iter_libsvm.cc``†): libsvm text
parsing, qid skipping, 0/1-based index auto-detection, densified
batches (documented sparse divergence)."""
import numpy as np
import pytest

from mxtpu.base import MXNetError
from mxtpu.io import LibSVMIter


def test_libsvm_zero_based(tmp_path):
    p = tmp_path / "train.libsvm"
    p.write_text("1 0:1.5 3:2.0\n0 1:0.5\n1 qid:7 2:3.0 3:1.0\n")
    it = LibSVMIter(str(p), data_shape=(4,), batch_size=2)
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(),
                               [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(b.label[0].asnumpy().ravel(), [1, 0])
    b2 = next(it)
    np.testing.assert_allclose(b2.data[0].asnumpy()[0],
                               [0, 0, 3.0, 1.0])
    assert b2.pad == 1


def test_libsvm_one_based_explicit(tmp_path):
    p = tmp_path / "one.libsvm"
    p.write_text("1 1:9.0 4:2.0\n0 2:1.0\n")
    it = LibSVMIter(str(p), data_shape=(4,), batch_size=2,
                    indexing="one")
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(),
                               [[9, 0, 0, 2], [0, 1, 0, 0]])
    # 1-based indices under zero-based parsing go out of range: loud
    with pytest.raises(MXNetError):
        LibSVMIter(str(p), data_shape=(4,), batch_size=2)


def test_libsvm_label_file_and_len(tmp_path):
    p = tmp_path / "d.libsvm"
    p.write_text("9 0:1.0\n9 1:2.0\n")
    lp = tmp_path / "l.libsvm"
    lp.write_text("0 0:0.1 1:0.2 2:0.3\n0 0:0.4 1:0.5 2:0.6\n")
    it = LibSVMIter(str(p), data_shape=(2,), label_shape=(3,),
                    label_libsvm=str(lp), batch_size=2)
    b = next(it)
    np.testing.assert_allclose(b.label[0].asnumpy(),
                               [[0.1, 0.2, 0.3], [0.4, 0.5, 0.6]])
    with pytest.raises(MXNetError):
        LibSVMIter(str(p), data_shape=(2,), label_shape=(3,),
                   batch_size=2)


def test_libsvm_out_of_range_raises(tmp_path):
    p = tmp_path / "bad.libsvm"
    p.write_text("1 7:1.0\n")
    with pytest.raises(MXNetError):
        LibSVMIter(str(p), data_shape=(4,), batch_size=1)


def test_libsvm_epoch_reset(tmp_path):
    p = tmp_path / "r.libsvm"
    p.write_text("\n".join(f"{i % 2} 0:{i}.0" for i in range(6)) + "\n")
    it = LibSVMIter(str(p), data_shape=(2,), batch_size=3,
                    round_batch=False)
    assert sum(1 for _ in it) == 2
    it.reset()
    assert sum(1 for _ in it) == 2
