"""Symbolic-tier C ABI (VERDICT r4 item 6): a compiled C++ program
loads a -symbol.json + .params checkpoint, simple-binds it, and trains
10 SGD steps end-to-end through MXSymbol* / MXExecutor* /
MXImperativeInvoke — the workflow every reference frontend drives
through src/c_api/c_api_symbolic.cc† + c_api_executor.cc†
(SURVEY §2.1-N13).
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CORE = os.path.join(_ROOT, "core")
_EXAMPLE = os.path.join(_ROOT, "cpp_package", "example",
                        "train_symbolic.cc")


def _build_lib():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("g++/make not available")
    r = subprocess.run(["make", "libmxtpu_c.so",
                        f"PYTHON={sys.executable}"],
                       cwd=_CORE, capture_output=True, text=True)
    assert r.returncode == 0, \
        f"libmxtpu_c build failed: {r.stderr[-1000:]}"


def _make_artifacts(tmp_path):
    """Author the model in Python (as the reference workflow does),
    save symbol JSON + initial params for the C++ program to consume."""
    from mxtpu import nd, sym
    data = sym.var("data")
    label = sym.var("label")
    fc = sym.FullyConnected(data, num_hidden=1, name="fc")
    out = sym.LinearRegressionOutput(fc, label, name="linreg")
    json_path = str(tmp_path / "linreg-symbol.json")
    out.save(json_path)

    rng = np.random.RandomState(7)
    params = {
        "arg:fc_weight": nd.array(
            rng.randn(1, 4).astype(np.float32) * 0.1),
        "arg:fc_bias": nd.zeros((1,)),
    }
    params_path = str(tmp_path / "linreg-0000.params")
    nd.save(params_path, params)
    return json_path, params_path


def test_cpp_program_trains_through_symbolic_abi(tmp_path):
    _build_lib()
    json_path, params_path = _make_artifacts(tmp_path)
    exe = str(tmp_path / "train_symbolic")
    r = subprocess.run(
        ["g++", "-std=c++17", _EXAMPLE, f"-I{_CORE}", f"-L{_CORE}",
         "-lmxtpu_c", f"-Wl,-rpath,{_CORE}", "-o", exe],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-1000:]

    out_params = str(tmp_path / "trained.params")
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"  # ABI tier test, not a chip test
    r = subprocess.run([exe, json_path, params_path, out_params],
                       capture_output=True, text=True, timeout=600,
                       env=env)
    assert r.returncode == 0, \
        f"stdout:{r.stdout[-1200:]}\nstderr:{r.stderr[-1200:]}"
    assert "C-ABI symbolic training OK" in r.stdout, r.stdout[-800:]
    assert r.stdout.count("step ") == 10, r.stdout

    # the saved checkpoint is loadable from Python and near w*
    from mxtpu import nd
    trained = nd.load(out_params)
    w = trained["arg:fc_weight"].asnumpy().reshape(-1)
    np.testing.assert_allclose(w, [1.0, 2.0, -1.0, 0.5], atol=0.35)
