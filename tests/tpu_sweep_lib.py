"""Registry-wide cpu<->tpu consistency sweep — case synthesis.

VERDICT r3 item 2: the reference reran its whole operator suite on the
accelerator (``tests/python/gpu/test_operator_gpu.py``†); with 400
registered names the repo's 7-symbol tier was the biggest correctness
hole.  This module synthesizes a one-op test case for every registry
rule it can (generic shapes, including non-multiple-of-8 to hit
padding/tiling edges), plus curated cases for families whose
signatures defeat generic synthesis (conv/pool/rnn/detection/linalg/
ordering/quantized).

Design notes (why not 400 Executor binds): each remote TPU compile
costs 5-30 s on this tunnel, so the sweep jits GROUPS of ~25 op
applications into one program per backend (tests/tpu_sweep_runner.py)
— the same lowering rules the symbol/NDArray layers dispatch to,
16 compiles instead of 800.  The symbol-layer glue itself is covered
by tests/test_tpu_consistency.py.

Every op lands in exactly one bucket: CASES (swept), or LEDGER
(skipped, with a reason) — test_tpu_sweep.py asserts the union is the
whole registry, so a new op cannot silently dodge the sweep.
"""
from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# explicit skip/xfail ledger: op -> reason.  Grouped by cause.
# ---------------------------------------------------------------------------

_AXON_POISON = ("axon UNIMPLEMENTED (complex/FFT poisons the client — "
                "BASELINE.md platform notes)")
_HOST_SIDE = "host-side/python op (no device lowering to compare)"
_STATEFUL = "stateful RNG op — draws differ by backend design"
_NEEDS_DATA = "needs structured real data (covered by its own test)"
_NOT_GENERIC = "no generic one-op case (covered by family test file)"

LEDGER = {
    # complex / FFT: the first UNIMPLEMENTED op permanently poisons the
    # axon client, so these never go to the chip
    "_contrib_fft": _AXON_POISON,
    "_contrib_ifft": _AXON_POISON,
    # pure-python / host-side
    "_npi_load": _HOST_SIDE, "_npi_save": _HOST_SIDE,
    "Custom": _HOST_SIDE, "_CustomFunction": _HOST_SIDE,
    "_cvimread": _HOST_SIDE, "_cvimresize": _HOST_SIDE,
    "_cvcopyMakeBorder": _HOST_SIDE,
}

# RNG ops: cross-backend value equality is not the contract (threefry
# streams are seeded identically, but op-level draws route through
# different key-split orders per backend batch layout); their
# statistical behavior is tested in test_random.py.
_RNG_PREFIXES = ("_random_", "_sample_", "random_")


def ledger_reason(name, op):
    if name in LEDGER:
        return LEDGER[name]
    if name.startswith(_RNG_PREFIXES) or name in (
            "shuffle", "_shuffle", "BernoulliDropout", "Dropout"):
        return _STATEFUL
    return None


# ---------------------------------------------------------------------------
# curated cases: op -> list of (args_arrays, kwargs).  Shapes
# deliberately include non-multiples of 8.
# ---------------------------------------------------------------------------

def _r(*shape, seed=0, scale=0.5, pos=False):
    rng = np.random.RandomState(hash(shape) % 2 ** 31 + seed)
    a = rng.randn(*shape).astype(np.float32) * scale
    return np.abs(a) + 0.2 if pos else a


def _ri(lo, hi, *shape, seed=0):
    rng = np.random.RandomState(hash(shape) % 2 ** 31 + seed + 7)
    return rng.randint(lo, hi, shape).astype(np.int32)


def curated_cases():
    """Hand-built cases for ops whose Param defaults can't make a
    valid call (kernel sizes, paired index inputs, ...)."""
    c = {}
    x4 = _r(2, 3, 9, 7)          # NCHW, non-multiple-of-8 H/W
    x4c8 = _r(2, 8, 9, 7)
    w = _r(4, 3, 3, 3)
    c["Convolution"] = [((x4, w, _r(4)),
                         dict(kernel=(3, 3), num_filter=4, pad=(1, 1),
                              no_bias=False))]
    c["Deconvolution"] = [((x4, _r(3, 4, 3, 3), _r(4)),
                           dict(kernel=(3, 3), num_filter=4,
                                pad=(1, 1), no_bias=False))]
    c["Pooling"] = [((x4,), dict(kernel=(2, 2), stride=(2, 2),
                                 pool_type="max")),
                    ((x4,), dict(kernel=(3, 3), pad=(1, 1),
                                 pool_type="avg"))]
    c["FullyConnected"] = [((_r(5, 11), _r(6, 11), _r(6)),
                            dict(num_hidden=6, no_bias=False))]
    c["BatchNorm"] = [((x4c8, _r(8, pos=True), _r(8),
                        np.zeros(8, np.float32),
                        np.ones(8, np.float32)),
                       dict(fix_gamma=False))]
    c["LayerNorm"] = [((_r(5, 11), _r(11, pos=True), _r(11)), {})]
    c["InstanceNorm"] = [((x4, _r(3, pos=True), _r(3)), {})]
    c["L2Normalization"] = [((_r(5, 11),), {})]
    c["LRN"] = [((x4c8,), dict(nsize=3))]
    c["Activation"] = [((_r(5, 11),), dict(act_type=t))
                       for t in ("relu", "sigmoid", "tanh", "softrelu")]
    c["LeakyReLU"] = [((_r(5, 11),), dict(act_type="leaky")),
                      ((_r(5, 11), _r(1, pos=True)),
                       dict(act_type="prelu"))]
    c["softmax"] = [((_r(5, 11),), dict(axis=-1))]
    c["log_softmax"] = [((_r(5, 11),), dict(axis=-1))]
    c["SoftmaxActivation"] = [((_r(5, 11),), {})]
    c["SoftmaxOutput"] = [((_r(5, 11), _ri(0, 11, 5).astype(
        np.float32)), {})]
    c["Embedding"] = [((_ri(0, 19, 4, 5).astype(np.float32),
                        _r(19, 7)),
                       dict(input_dim=19, output_dim=7))]
    c["take"] = [((_r(9, 7), _ri(0, 9, 4).astype(np.float32)), {})]
    c["gather_nd"] = [((_r(6, 7), _ri(0, 6, 1, 5)), {})]
    c["one_hot"] = [((_ri(0, 9, 7).astype(np.float32),),
                     dict(depth=9))]
    c["Concat"] = [((_r(3, 5), _r(3, 6)), dict(dim=1))]
    c["stack"] = [((_r(3, 5), _r(3, 5)), dict(axis=0))]
    c["add_n"] = [((_r(3, 5), _r(3, 5), _r(3, 5)), {})]
    c["Reshape"] = [((_r(3, 10),), dict(shape=(5, 6)))]
    c["reshape_like"] = [((_r(3, 10), _r(5, 6)), {})]
    c["transpose"] = [((_r(3, 5, 7),), dict(axes=(2, 0, 1)))]
    c["expand_dims"] = [((_r(3, 5),), dict(axis=1))]
    c["slice"] = [((_r(5, 11),), dict(begin=(1, 2), end=(4, 9)))]
    c["slice_axis"] = [((_r(5, 11),),
                        dict(axis=1, begin=1, end=9))]
    c["slice_like"] = [((_r(5, 11), _r(3, 7)), {})]
    c["tile"] = [((_r(3, 5),), dict(reps=(2, 3)))]
    c["repeat"] = [((_r(3, 5),), dict(repeats=2, axis=1))]
    c["pad"] = [((x4,), dict(mode="constant",
                             pad_width=(0, 0, 0, 0, 1, 2, 1, 1)))]
    c["flip"] = [((_r(3, 5),), dict(axis=1))]
    c["clip"] = [((_r(5, 11),), dict(a_min=-0.3, a_max=0.4))]
    # ordering family (VERDICT named)
    c["topk"] = [((_r(5, 11),),
                  dict(k=3, axis=-1, ret_typ="value"))]
    c["sort"] = [((_r(5, 11),), dict(axis=-1))]
    c["argsort"] = [((_r(5, 11),), dict(axis=-1))]
    c["argmax"] = [((_r(5, 11),), dict(axis=1))]
    c["argmin"] = [((_r(5, 11),), dict(axis=1))]
    # reductions with axes
    for rop in ("sum", "mean", "prod", "max", "min", "nansum",
                "nanprod"):
        c[rop] = [((_r(3, 5, 7),), dict(axis=(0, 2))),
                  ((_r(3, 5, 7),), dict(axis=1, keepdims=True))]
    c["norm"] = [((_r(3, 5, 7),), dict(ord=2, axis=1))]
    # broadcasting binaries at broadcast shapes
    for bop in ("broadcast_add", "broadcast_sub", "broadcast_mul",
                "broadcast_div", "broadcast_maximum",
                "broadcast_minimum", "broadcast_power",
                "broadcast_hypot"):
        c[bop] = [((_r(3, 1, 7, pos=True), _r(1, 5, 7, pos=True)), {})]
    c["broadcast_to"] = [((_r(3, 1, 7),), dict(shape=(3, 5, 7)))]
    c["broadcast_like"] = [((_r(3, 1, 7), _r(3, 5, 7)), {})]
    c["where"] = [(((_r(3, 5) > 0).astype(np.float32), _r(3, 5),
                    _r(3, 5)), {})]
    c["dot"] = [((_r(5, 11), _r(11, 6)), {})]
    c["batch_dot"] = [((_r(3, 5, 11), _r(3, 11, 6)), {})]
    c["linalg_gemm2"] = [((_r(5, 11), _r(11, 6)), {})]
    # linalg family (VERDICT named): SPD inputs for potrf
    spd = (lambda a: (a @ a.T + 3 * np.eye(6)).astype(np.float32))(
        _r(6, 6))
    c["linalg_potrf"] = [((spd,), {})]
    c["linalg_syrk"] = [((_r(4, 6),), dict(transpose=False))]
    c["linalg_trmm"] = [((np.tril(_r(5, 5)) + np.eye(
        5, dtype=np.float32), _r(5, 7)), {})]
    c["linalg_trsm"] = [((np.tril(_r(5, 5)) + 2 * np.eye(
        5, dtype=np.float32), _r(5, 7)), {})]
    c["linalg_sumlogdiag"] = [((spd,), {})]
    c["linalg_extractdiag"] = [((_r(6, 6),), {})]
    c["linalg_makediag"] = [((_r(6),), {})]
    c["linalg_det"] = [((spd,), {})]
    c["linalg_inverse"] = [((spd,), {})]
    # sequence family
    c["SequenceMask"] = [((_r(7, 3, 5),
                           np.asarray([3, 5, 7], np.float32)),
                          dict(use_sequence_length=True))]
    c["SequenceLast"] = [((_r(7, 3, 5),
                           np.asarray([3, 5, 7], np.float32)),
                          dict(use_sequence_length=True))]
    c["SequenceReverse"] = [((_r(7, 3, 5),
                              np.asarray([3, 5, 7], np.float32)),
                             dict(use_sequence_length=True))]
    c["RNN"] = [((_r(7, 3, 5), _r(4 * 6 * (5 + 6 + 2)),
                  _r(1, 3, 6), _r(1, 3, 6)),
                 dict(state_size=6, num_layers=1, mode="lstm"))]
    # detection family (VERDICT named)
    c["_contrib_box_iou"] = [((np.asarray(
        [[0, 0, 2, 2], [1, 1, 3, 3]], np.float32),
        np.asarray([[0, 0, 2, 2]], np.float32)), {})]
    c["_contrib_box_nms"] = [((np.asarray(
        [[[0.9, 0, 0, 2, 2], [0.8, 1, 1, 3, 3],
          [0.7, 0, 0, 2.1, 2.1]]], np.float32),),
        dict(overlap_thresh=0.5))]
    c["_contrib_ROIAlign"] = [((_r(1, 4, 9, 9), np.asarray(
        [[0, 0, 0, 6, 6]], np.float32)),
        dict(pooled_size=(2, 2), spatial_scale=1.0))]
    c["ROIPooling"] = [((_r(1, 4, 9, 9), np.asarray(
        [[0, 0, 0, 6, 6]], np.float32)),
        dict(pooled_size=(2, 2), spatial_scale=1.0))]
    c["SliceChannel"] = [((_r(4, 6),),
                          dict(num_outputs=2, axis=1))]
    c["UpSampling"] = [((x4,), dict(scale=2,
                                    sample_type="nearest"))]
    c["BilinearSampler"] = [((_r(1, 2, 5, 5),
                              np.clip(_r(1, 2, 5, 5), -0.9, 0.9)), {})]
    c["GridGenerator"] = [((_r(1, 6),),
                           dict(transform_type="affine",
                                target_shape=(5, 5)))]
    c["Crop"] = [((_r(1, 3, 9, 9), _r(1, 3, 5, 5)),
                  dict(num_args=2))]
    c["Cast"] = [((_r(5, 11),), dict(dtype="float32"))]
    c["amp_cast"] = [((_r(5, 11),), dict(dtype="float32"))]
    # quantized family (VERDICT named): int8/uint8 data paths
    qd = _ri(0, 255, 2, 3, 9, 7).astype(np.uint8)
    qw = (_ri(0, 254, 4, 3, 3, 3) - 127).astype(np.int8)
    f0 = np.float32(0.0)
    f4 = np.float32(4.0)
    fw = np.float32(0.9)
    c["_contrib_quantized_conv"] = [((qd, qw, f0, f4, -fw, fw),
                                     dict(kernel=(3, 3), num_filter=4,
                                          pad=(1, 1)))]
    c["_contrib_quantized_fully_connected"] = [
        (((_ri(0, 254, 5, 6) - 127).astype(np.int8),
          (_ri(0, 254, 4, 6) - 127).astype(np.int8),
          -f4, f4, -fw, fw), dict(num_hidden=4))]
    c["_contrib_quantized_pooling"] = [((qd, f0, f4),
                                        dict(kernel=(2, 2),
                                             stride=(2, 2),
                                             pool_type="max"))]
    c["_contrib_quantized_act"] = [(((_ri(0, 254, 5, 7) - 127)
                                     .astype(np.int8), -f4, f4),
                                    dict(act_type="relu"))]
    c["_contrib_requantize"] = [((_ri(-9999, 9999, 5, 7), -f4, f4),
                                 dict(min_calib_range=-1.0,
                                      max_calib_range=1.0))]
    c["quantize"] = [((_r(5, 7), np.float32(-2.0), np.float32(2.0)),
                      dict(out_type="int8"))]
    c["quantize_v2"] = [((_r(5, 7),),
                         dict(min_calib_range=-2.0,
                              max_calib_range=2.0,
                              out_type="int8"))]
    c["dequantize"] = [(((_ri(0, 254, 5, 7) - 127).astype(np.int8),
                         np.float32(-2.0), np.float32(2.0)), {})]

    # ---- wave 2: optimizer updates + remaining families -------------
    w_, g_, m_, v_ = (_r(5, 11, seed=s) for s in range(4))
    okw = dict(lr=0.1, wd=0.01)
    c["sgd_update"] = [((w_, g_), dict(okw))]
    c["sgd_mom_update"] = [((w_, g_, m_), dict(okw, momentum=0.9))]
    c["nag_mom_update"] = [((w_, g_, m_), dict(okw, momentum=0.9))]
    c["signsgd_update"] = [((w_, g_), dict(okw))]
    c["signum_update"] = [((w_, g_, m_), dict(okw, momentum=0.9))]
    c["adam_update"] = [((w_, g_, m_, np.abs(v_)), dict(lr=0.01))]
    c["ftrl_update"] = [((w_, g_, m_, np.abs(v_) + 0.1),
                         dict(lr=0.1))]
    c["rmsprop_update"] = [((w_, g_, np.abs(v_) + 0.1),
                            dict(lr=0.01))]
    c["rmspropalex_update"] = [((w_, g_, m_ * 0.1, np.abs(v_) + 0.1,
                                 m_ * 0.0), dict(lr=0.01))]
    c["mp_sgd_update"] = [((w_.astype(np.float32), g_, w_),
                           dict(okw))]
    c["mp_sgd_mom_update"] = [((w_, g_, m_, w_),
                               dict(okw, momentum=0.9))]
    c["mp_nag_mom_update"] = [((w_, g_, m_, w_),
                               dict(okw, momentum=0.9))]
    c["multi_sgd_update"] = [((w_, g_, v_, m_),
                              dict(lrs=(0.1, 0.1), wds=(0.0, 0.0),
                                   num_weights=2))]
    c["multi_sgd_mom_update"] = [((w_, g_, m_, v_, g_, w_),
                                  dict(lrs=(0.1, 0.1),
                                       wds=(0.0, 0.0), momentum=0.9,
                                       num_weights=2))]
    c["multi_mp_sgd_update"] = [((w_, g_, w_, v_, g_, v_),
                                 dict(lrs=(0.1, 0.1), wds=(0.0, 0.0),
                                      num_weights=2))]
    c["multi_mp_sgd_mom_update"] = [((w_, g_, m_, w_, v_, g_, m_, v_),
                                     dict(lrs=(0.1, 0.1),
                                          wds=(0.0, 0.0),
                                          momentum=0.9,
                                          num_weights=2))]
    c["_sparse_adagrad_update"] = [((w_, g_, np.abs(v_) + 0.1),
                                    dict(lr=0.1))]
    # misc families
    c["matmul"] = [((_r(5, 11), _r(11, 6)), {})]
    c["pick"] = [((_r(5, 11), _ri(0, 11, 5).astype(np.float32)),
                  dict(axis=1))]
    c["batch_take"] = [((_r(5, 11), _ri(0, 11, 5)), {})]
    c["softmax_cross_entropy"] = [((_r(5, 11),
                                    _ri(0, 11, 5).astype(np.float32)),
                                   {})]
    c["GroupNorm"] = [((_r(2, 6, 9, 7), _r(6, pos=True), _r(6)),
                       dict(num_groups=2))]
    c["space_to_depth"] = [((_r(2, 3, 6, 8),), dict(block_size=2))]
    c["depth_to_space"] = [((_r(2, 12, 3, 4),), dict(block_size=2))]
    c["im2col"] = [((_r(2, 3, 9, 7),),
                    dict(kernel=(3, 3), pad=(1, 1)))]
    c["col2im"] = [((_r(2, 27, 63),),
                    dict(output_size=(9, 7), kernel=(3, 3),
                         pad=(1, 1)))]
    c["ElementWiseSum"] = [((_r(3, 10), _r(3, 10), _r(3, 10)), {})]
    c["amp_multicast"] = [((_r(3, 10), _r(3, 10).astype(np.float32)),
                           dict(num_outputs=2))]
    c["multi_all_finite"] = [((_r(3, 10), _r(3, 10)),
                              dict(num_arrays=2))]
    c["khatri_rao"] = [((_r(4, 5), _r(3, 5)), {})]
    c["linalg_gemm"] = [((_r(5, 11), _r(11, 6), _r(5, 6)), {})]
    spd2 = (lambda a: (a @ a.T + 3 * np.eye(6)).astype(np.float32))(
        _r(6, 6, seed=9))
    c["linalg_potri"] = [((np.linalg.cholesky(spd2),), {})]
    c["linalg_slogdet"] = [((spd2,), {})]
    c["linalg_syevd"] = [(((spd2 + spd2.T) / 2,), {})]
    c["arccosh"] = [((np.abs(_r(5, 11)) + 1.2,), {})]
    c["_mod_scalar"] = [((_r(5, 11, pos=True),), dict(scalar=0.7))]
    c["_DivScalar"] = [((_r(5, 11),), dict(scalar=0.7))]
    c["_arange"] = [((), dict(start=0.0, stop=12.0, step=0.5))]
    c["_eye"] = [((), dict(N=7, M=9, k=1))]
    c["_linspace"] = [((), dict(start=0.0, stop=3.0, num=13))]
    c["fill_element_0index"] = [((_r(5, 11), _r(5),
                                  _ri(0, 11, 5).astype(np.float32)),
                                 {})]
    c["_contrib_index_copy"] = [((_r(9, 4), _ri(0, 9, 3),
                                  _r(3, 4)), {})]
    c["_contrib_boolean_mask"] = [((_r(6, 4), np.asarray(
        [1, 0, 1, 1, 0, 1], np.float32)), {})]
    c["_scatter_set_nd"] = [((_r(6, 7), _r(5, 7), _ri(0, 6, 1, 5)),
                             dict(shape=(6, 7)))]
    c["scatter_nd"] = [((_r(5), _ri(0, 6, 1, 5)),
                        dict(shape=(6,)))]
    c["_ravel_multi_index"] = [((_ri(0, 5, 2, 4).astype(np.float32),),
                                dict(shape=(5, 5)))]
    c["_unravel_index"] = [((_ri(0, 24, 6).astype(np.float32),),
                            dict(shape=(4, 6)))]
    c["BilinearResize2D"] = [((_r(1, 3, 6, 5),),
                              dict(height=9, width=11))]
    c["_contrib_AdaptiveAvgPooling2D"] = [((_r(1, 3, 9, 7),),
                                           dict(output_size=(3, 3)))]
    c["_contrib_quantized_flatten"] = [
        (((_ri(0, 254, 2, 3, 4) - 127).astype(np.int8),
          np.float32(-2.0), np.float32(2.0)), {})]
    c["_contrib_MoEFFN"] = [((_r(24, 8), _r(8, 4) * 2,
                              _r(4, 8, 16, scale=0.3),
                              _r(4, 16, scale=0.1),
                              _r(4, 16, 8, scale=0.3),
                              _r(4, 8, scale=0.1)),
                             dict(capacity_factor=1.5))]
    c["_contrib_quantized_concat"] = [
        (((_ri(0, 254, 2, 3) - 127).astype(np.int8),
          (_ri(0, 254, 2, 4) - 127).astype(np.int8),
          np.float32(-2.0), np.float32(2.0),
          np.float32(-1.0), np.float32(1.0)),
         dict(num_args=2, dim=1))]
    return c


# ---------------------------------------------------------------------------
# generic synthesis for everything else
# ---------------------------------------------------------------------------

def _candidates(n_in):
    """Ordered generic input sets; first that works on CPU wins.
    (3, 10) and (2, 3, 9, 7) are deliberately non-multiples of 8."""
    outs = []
    base = [_r(3, 10, seed=i) for i in range(max(n_in, 1))]
    outs.append(tuple(base))
    outs.append(tuple(np.abs(b) + 0.2 for b in base))      # pos-only
    outs.append(tuple(_r(2, 3, 9, 7, seed=i)
                      for i in range(max(n_in, 1))))
    outs.append(tuple(np.abs(_r(2, 3, 9, 7, seed=i)) + 0.2
                      for i in range(max(n_in, 1))))
    outs.append(tuple(_ri(0, 5, 3, 10, seed=i).astype(np.float32)
                      for i in range(max(n_in, 1))))       # small ints
    return outs


def bf16_cases():
    """bf16 variants of the heavy families (case idx >= 100 marks the
    looser bf16 tolerance tier in test_tpu_sweep).  The north-star
    benches run bf16, so the consistency tier must cover it too.
    FORWARD-only: numpy's bfloat16 is not np.floating, so the runner's
    float_argnums sees no differentiable inputs — bwd coverage lives
    in the f32 tier."""
    import numpy as np
    base = curated_cases()
    picks = ["Convolution", "FullyConnected", "BatchNorm", "LayerNorm",
             "softmax", "dot", "batch_dot", "Pooling", "Activation",
             "_contrib_MoEFFN"]
    out = []
    for name in picks:
        for i, (args, kw) in enumerate(base.get(name, [])[:1]):
            # all float inputs go bf16 (conv/dot require matching
            # operand dtypes; params cast alongside data like the
            # compute_dtype train path)
            cast = tuple(
                a.astype("bfloat16")
                if isinstance(a, np.ndarray)
                and a.dtype == np.float32 else a
                for a in args)
            out.append((name, 100 + i, cast, kw))
    return out


def build_cases():
    """-> (cases: list[(op_name, case_idx, args, kwargs)],
           skipped: dict[op_name, reason]).

    Discovery runs each candidate eagerly on CPU; an op joins the
    sweep with its first working candidate (plus every curated case).
    """
    import jax
    import jax.numpy as jnp

    from mxtpu.ops.registry import get_op, list_ops

    curated = curated_cases()
    cases = []
    skipped = {}
    seen_fns = {}
    # pre-seed the rule->name map with the curated names so an alias
    # that sorts earlier (e.g. "MoEFFN" < "_contrib_MoEFFN", "_div" <
    # "broadcast_div") can neither claim the rule (stranding the
    # curated case) nor get auto-swept as a duplicate (r4 review: 14
    # rules were swept twice with a lying ledger)
    for cname in curated:
        try:
            seen_fns.setdefault(id(get_op(cname).fn), cname)
        except Exception:
            pass
    for name in sorted(list_ops()):
        op = get_op(name)
        if name in curated:
            for i, (args, kw) in enumerate(curated[name]):
                cases.append((name, i, args, kw))
            continue
        # aliases share the rule fn; sweep each rule once
        if id(op.fn) in seen_fns:
            skipped[name] = f"alias of {seen_fns[id(op.fn)]}"
            continue
        seen_fns[id(op.fn)] = name
        reason = ledger_reason(name, op)
        if reason is not None:
            skipped[name] = reason
            continue
        n_in = op.num_inputs if op.num_inputs >= 0 else 3
        if n_in == 0:
            # nullary init ops: compare with explicit shape
            try:
                out = op(shape=(3, 10))
                cases.append((name, 0, (), {"shape": (3, 10)}))
            except Exception:
                skipped[name] = _NOT_GENERIC
            continue
        placed = False
        for args in _candidates(n_in):
            for kw in ([{"num_args": len(args)}, {}]
                       if op.num_inputs == -1 else [{}]):
                try:
                    out = op(*[jnp.asarray(a) for a in args], **kw)
                    break
                except Exception:
                    out = None
            try:
                if out is None:
                    raise ValueError("no candidate call succeeded")
                leaves = jax.tree_util.tree_leaves(out)
                if not leaves:
                    raise ValueError("no outputs")
                ok = all(bool(jnp.all(jnp.isfinite(
                    l.astype(jnp.float32)))) for l in leaves
                    if hasattr(l, "astype")
                    and jnp.issubdtype(l.dtype, jnp.floating))
                if not ok:
                    continue
                cases.append((name, 0, args, kw))
                placed = True
                break
            except Exception:
                continue
        if not placed:
            skipped[name] = _NOT_GENERIC
    cases.extend(bf16_cases())
    return cases, skipped
