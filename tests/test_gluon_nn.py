"""Layer/loss/trainer tests (modelled on the reference's
``tests/python/unittest/test_gluon.py``† and ``test_loss.py``†)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, nd
from mxtpu.gluon import nn, loss as gloss, Trainer


def test_dense_shapes_values():
    layer = nn.Dense(4, in_units=3, use_bias=True,
                     bias_initializer="ones")
    layer.initialize(init="ones")
    x = nd.array(np.ones((2, 3), np.float32))
    out = layer(x)
    assert out.shape == (2, 4)
    # W=1, b=1: out = 3*1 + 1 = 4
    assert np.allclose(out.asnumpy(), 4.0)


def test_dense_deferred_init():
    layer = nn.Dense(7)
    layer.initialize()
    out = layer(nd.array(np.random.randn(5, 3).astype(np.float32)))
    assert out.shape == (5, 7)
    assert layer.weight.shape == (7, 3)


def test_dense_flatten_false():
    layer = nn.Dense(6, flatten=False)
    layer.initialize()
    out = layer(nd.array(np.random.randn(2, 5, 4).astype(np.float32)))
    assert out.shape == (2, 5, 6)


def test_conv2d_against_numpy():
    layer = nn.Conv2D(2, kernel_size=3, padding=1, in_channels=1)
    layer.initialize(init="ones")
    x = nd.array(np.ones((1, 1, 4, 4), np.float32))
    out = layer(x)
    assert out.shape == (1, 2, 4, 4)
    # center pixels see the full 3x3 window of ones
    assert np.allclose(out.asnumpy()[0, 0, 1:3, 1:3], 9.0)


def test_conv_deferred_and_pool():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3), nn.MaxPool2D(2),
            nn.GlobalAvgPool2D(), nn.Flatten())
    net.initialize()
    out = net(nd.array(np.random.randn(2, 3, 12, 12).astype(np.float32)))
    assert out.shape == (2, 4)


def test_conv1d_conv3d():
    c1 = nn.Conv1D(3, kernel_size=3)
    c1.initialize()
    assert c1(nd.array(np.random.randn(2, 2, 8).astype(
        np.float32))).shape == (2, 3, 6)
    c3 = nn.Conv3D(2, kernel_size=2)
    c3.initialize()
    assert c3(nd.array(np.random.randn(1, 1, 4, 4, 4).astype(
        np.float32))).shape == (1, 2, 3, 3, 3)


def test_conv2d_transpose_shape():
    layer = nn.Conv2DTranspose(3, kernel_size=4, strides=2, padding=1)
    layer.initialize()
    x = nd.array(np.random.randn(1, 2, 8, 8).astype(np.float32))
    assert layer(x).shape == (1, 3, 16, 16)


def test_batchnorm_train_and_running_stats():
    layer = nn.BatchNorm(in_channels=3, momentum=0.5)
    layer.initialize()
    x = nd.array((np.random.randn(4, 3, 5, 5) * 3 + 1).astype(np.float32))
    with autograd.record():
        out = layer(x)
    # normalized output: near zero mean / unit var per channel
    o = out.asnumpy()
    assert abs(o.mean()) < 1e-2
    rm = layer.running_mean.data().asnumpy()
    assert not np.allclose(rm, 0.0)  # stats updated
    # inference uses running stats
    out2 = layer(x)
    assert not np.allclose(out2.asnumpy(), o)


def test_batchnorm_hybrid_matches_imperative():
    np.random.seed(0)
    layer = nn.BatchNorm(in_channels=2)
    layer.initialize()
    x = nd.array(np.random.randn(3, 2, 4, 4).astype(np.float32))
    with autograd.record():
        ref = layer(x).asnumpy()
    rm_imp = layer.running_mean.data().asnumpy().copy()
    layer2 = nn.BatchNorm(in_channels=2)
    layer2.initialize()
    layer2.hybridize()
    with autograd.record():
        out = layer2(x).asnumpy()
    assert np.allclose(ref, out, atol=1e-5)
    assert np.allclose(rm_imp, layer2.running_mean.data().asnumpy(),
                       atol=1e-6)


def test_layernorm_embedding():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = nd.array(np.random.randn(2, 6).astype(np.float32))
    o = ln(x).asnumpy()
    assert np.allclose(o.mean(axis=-1), 0, atol=1e-5)
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = nd.array(np.array([[1, 2], [3, 4]], np.float32))
    assert emb(idx).shape == (2, 2, 4)


def test_activation_layers():
    for layer, fn in [(nn.Activation("relu"), lambda v: np.maximum(v, 0)),
                      (nn.LeakyReLU(0.1),
                       lambda v: np.where(v > 0, v, 0.1 * v)),
                      (nn.ELU(1.0),
                       lambda v: np.where(v > 0, v, np.exp(v) - 1))]:
        layer.initialize()
        x = np.random.randn(3, 4).astype(np.float32)
        assert np.allclose(layer(nd.array(x)).asnumpy(), fn(x),
                           atol=1e-5), type(layer).__name__


def test_prelu_swish_gelu_selu():
    x = nd.array(np.random.randn(2, 3).astype(np.float32))
    for layer in [nn.PReLU(), nn.Swish(), nn.GELU(), nn.SELU()]:
        layer.initialize()
        assert layer(x).shape == (2, 3)


def test_dropout_layer():
    layer = nn.Dropout(0.5)
    layer.initialize()
    x = nd.array(np.ones((50, 50), np.float32))
    # inference: identity
    assert np.allclose(layer(x).asnumpy(), 1.0)
    with autograd.record():
        y = layer(x).asnumpy()
    assert (y == 0).any() and not (y == 0).all()


def test_sequential_getitem_len():
    net = nn.Sequential()
    net.add(nn.Dense(3), nn.Dense(4), nn.Dense(5))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    net.initialize()
    assert net(nd.array(np.ones((2, 2), np.float32))).shape == (2, 5)


def test_lambda_blocks():
    net = nn.HybridSequential()
    net.add(nn.HybridLambda(lambda F, x: F.relu(x)),
            nn.HybridLambda("exp"))
    net.initialize()
    x = nd.array(np.array([[-1.0, 2.0]], np.float32))
    out = net(nd.array(np.array([[-1.0, 2.0]], np.float32)))
    assert np.allclose(out.asnumpy(), np.exp(np.maximum([[-1, 2]], 0)),
                       atol=1e-6)
    lam = nn.Lambda("sigmoid")
    assert np.allclose(lam(x).asnumpy(),
                       1 / (1 + np.exp(np.array([[1.0, -2.0]]))),
                       atol=1e-6)


# ---------------------------------------------------------------------
# losses (numpy references, reference test_loss.py† style)
# ---------------------------------------------------------------------
def test_l2_l1_loss():
    pred = np.random.randn(4, 5).astype(np.float32)
    label = np.random.randn(4, 5).astype(np.float32)
    l2 = gloss.L2Loss()(nd.array(pred), nd.array(label)).asnumpy()
    assert np.allclose(l2, 0.5 * ((pred - label) ** 2).mean(axis=1),
                       atol=1e-6)
    l1 = gloss.L1Loss()(nd.array(pred), nd.array(label)).asnumpy()
    assert np.allclose(l1, np.abs(pred - label).mean(axis=1), atol=1e-6)


def test_softmax_ce_loss():
    pred = np.random.randn(6, 10).astype(np.float32)
    label = np.random.randint(0, 10, (6,)).astype(np.float32)
    l = gloss.SoftmaxCrossEntropyLoss()(nd.array(pred), nd.array(label))
    logp = pred - pred.max(1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(1, keepdims=True))
    ref = -logp[np.arange(6), label.astype(int)]
    assert np.allclose(l.asnumpy(), ref, atol=1e-5)
    # dense labels
    onehot = np.eye(10, dtype=np.float32)[label.astype(int)]
    l2 = gloss.SoftmaxCrossEntropyLoss(sparse_label=False)(
        nd.array(pred), nd.array(onehot))
    assert np.allclose(l2.asnumpy(), ref, atol=1e-5)


def test_sigmoid_bce_loss():
    pred = np.random.randn(4, 3).astype(np.float32)
    label = np.random.randint(0, 2, (4, 3)).astype(np.float32)
    l = gloss.SigmoidBinaryCrossEntropyLoss()(
        nd.array(pred), nd.array(label)).asnumpy()
    ref = (np.maximum(pred, 0) - pred * label +
           np.log1p(np.exp(-np.abs(pred)))).mean(axis=1)
    assert np.allclose(l, ref, atol=1e-5)


def test_huber_hinge_kl():
    pred = np.random.randn(4, 3).astype(np.float32)
    label = np.random.randn(4, 3).astype(np.float32)
    h = gloss.HuberLoss(rho=1.0)(nd.array(pred), nd.array(label)).asnumpy()
    err = np.abs(pred - label)
    ref = np.where(err > 1, err - 0.5, 0.5 * err ** 2).mean(axis=1)
    assert np.allclose(h, ref, atol=1e-5)

    sign = np.sign(np.random.randn(4, 3)).astype(np.float32)
    hi = gloss.HingeLoss()(nd.array(pred), nd.array(sign)).asnumpy()
    assert np.allclose(hi, np.maximum(0, 1 - pred * sign).mean(axis=1),
                       atol=1e-5)

    prob = np.abs(np.random.randn(3, 5)).astype(np.float32)
    prob /= prob.sum(1, keepdims=True)
    logits = np.random.randn(3, 5).astype(np.float32)
    kl = gloss.KLDivLoss(from_logits=False)(
        nd.array(logits), nd.array(prob)).asnumpy()
    logq = logits - logits.max(1, keepdims=True)
    logq = logq - np.log(np.exp(logq).sum(1, keepdims=True))
    ref = (prob * (np.log(prob + 1e-12) - logq)).mean(axis=1)
    assert np.allclose(kl, ref, atol=1e-5)


def test_triplet_cosine_losses():
    a = nd.array(np.random.randn(4, 8).astype(np.float32))
    p = nd.array(np.random.randn(4, 8).astype(np.float32))
    n = nd.array(np.random.randn(4, 8).astype(np.float32))
    t = gloss.TripletLoss()(a, p, n)
    assert t.shape == (4,) and (t.asnumpy() >= 0).all()
    lbl = nd.array(np.array([1, -1, 1, -1], np.float32))
    c = gloss.CosineEmbeddingLoss()(a, p, lbl)
    assert c.shape == (4,)


# ---------------------------------------------------------------------
# trainer + end-to-end training
# ---------------------------------------------------------------------
def _toy_problem(n=256, d=10, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes).astype(np.float32)
    x = rng.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.randn(n, classes), axis=1)
    return x, y.astype(np.float32)


@pytest.mark.parametrize("opt,opt_args", [
    ("sgd", {"learning_rate": 0.5, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.05}),
])
def test_train_mlp_converges(opt, opt_args):
    x, y = _toy_problem()
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(3))
    net.initialize(init="xavier")
    net.hybridize()
    L = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), opt, opt_args)
    xb, yb = nd.array(x), nd.array(y)
    for _ in range(60):
        with autograd.record():
            l = L(net(xb), yb)
            l.backward()
        trainer.step(x.shape[0])
    pred = np.argmax(net(xb).asnumpy(), axis=1)
    acc = (pred == y).mean()
    assert acc > 0.9, f"{opt} acc={acc}"


def test_trainer_lr_and_states(tmp_path):
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    assert trainer.learning_rate == 0.1
    trainer.set_learning_rate(0.01)
    assert trainer.learning_rate == 0.01
    x = nd.array(np.random.randn(4, 3).astype(np.float32))
    with autograd.record():
        l = gloss.L2Loss()(net(x), nd.zeros((4, 2)))
        l.backward()
    trainer.step(4)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    t2 = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5,
                                               "momentum": 0.9})
    t2.load_states(fname)
    st = t2._updaters[0].states
    assert len(st) == len(trainer._updaters[0].states)


def test_trainer_step_uninitialized_raises():
    net = nn.Dense(2, in_units=3)
    trainer = Trainer(net.collect_params(), "sgd")
    with pytest.raises(mx.MXNetError):
        trainer.step(1)


def test_lenet_hybrid_training_decreases_loss():
    np.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(6, kernel_size=5, padding=2, activation="relu"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(16, kernel_size=5, activation="relu"),
            nn.MaxPool2D(2, 2), nn.Flatten(),
            nn.Dense(120, activation="relu"),
            nn.Dense(84, activation="relu"), nn.Dense(10))
    net.initialize(init="xavier")
    net.hybridize()
    L = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 0.005})
    x = nd.array(np.random.randn(16, 1, 28, 28).astype(np.float32))
    y = nd.array(np.random.randint(0, 10, (16,)).astype(np.float32))
    losses = []
    for _ in range(30):
        with autograd.record():
            l = L(net(x), y)
            l.backward()
        trainer.step(16)
        losses.append(float(nd.mean(l).asscalar()))
    assert losses[-1] < losses[0] * 0.5, losses
    assert len(net._cached_entries) == 1  # one compile, reused
