"""mxtpu.serving fleet — router, health checks, retry/backoff,
draining, fault injection (ISSUE 7).

Every recovery-path scenario here is fully deterministic: the router
is tick-driven with a hand-stepped clock (``threaded=False`` — nothing
runs in the background) and the faults are scripted per-batch-index
plans from :mod:`mxtpu.serving.faults`.  Each scenario test exercises
exactly ONE recovery path.  Only the threaded smoke test and the
slow-marked soak touch real time, and they assert outcomes, not
latencies.
"""
import numpy as np
import pytest

from mxtpu import symbol as sym
from mxtpu.base import MXNetError
from mxtpu.serving import (Corrupt, CrashAt, FaultPlan, FleetRouter,
                           FleetWorker, Hang, ModelRunner, QueueWedge,
                           RequestTimeout, RetriableError, ServerBusy,
                           SlowStart, WorkerHealth, WorkerLost,
                           WorkerState)


class FakeClock:
    """Hand-stepped monotonic clock (same pattern as test_serving)."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _mul_runner(**kwargs):
    """out = data * w, per-row independent (padding detectable)."""
    data = sym.var("data")
    w = sym.var("w")
    return ModelRunner(data * w, {"w": np.array([1.0, 2.0, 3.0],
                                                np.float32)},
                       {"data": (3,)}, max_batch_size=4, **kwargs)


CANARY_IN = {"data": np.ones(3, np.float32)}
CANARY_OUT = [np.array([1.0, 2.0, 3.0], np.float32)]


def _router(clk, canary=True, **kw):
    kw.setdefault("canary_interval_s", 1.0)
    kw.setdefault("canary_timeout_s", 0.5)
    return FleetRouter(clock=clk, threaded=False,
                       canary=CANARY_IN if canary else None,
                       canary_expect=CANARY_OUT if canary else None,
                       **kw)


def _worker(clk, name, **kw):
    kw.setdefault("max_queue_delay_us", 0.0)
    return FleetWorker(_mul_runner(), name, clock=clk, **kw)


def _payload(v):
    return {"data": np.full(3, float(v), np.float32)}


def _crank(router, clk, n=8, dt=0.05):
    for _ in range(n):
        clk.advance(dt)
        router.tick(clk())


# ---------------------------------------------------------- health machine

def test_health_canary_cycle():
    h = WorkerHealth("w", dead_after=3)
    assert h.state == WorkerState.HEALTHY and h.admits()
    h.canary_fail(1.0)
    assert h.state == WorkerState.SUSPECT
    assert not h.admits() and h.admits_canary()
    h.canary_ok(2.0)
    assert h.state == WorkerState.HEALTHY and h.failures == 0


def test_health_dead_after_consecutive_failures():
    h = WorkerHealth("w", dead_after=3)
    for t in (1.0, 2.0, 3.0):
        h.canary_fail(t)
    assert h.state == WorkerState.DEAD
    # dead is terminal: a late canary success cannot resurrect it
    h.canary_ok(4.0)
    assert h.state == WorkerState.DEAD
    # ... only an explicit recover() can, and it demands a canary pass
    h.recover(5.0)
    assert h.state == WorkerState.RECOVERING and not h.admits()
    h.canary_ok(6.0)
    assert h.state == WorkerState.HEALTHY


def test_health_recovering_absorbs_canary_failures():
    h = WorkerHealth("w", dead_after=2, start_recovering=True)
    assert h.state == WorkerState.RECOVERING
    for t in range(10):           # slow starter: failures don't kill it
        h.canary_fail(float(t))
    assert h.state == WorkerState.RECOVERING
    h.canary_ok(11.0)
    assert h.state == WorkerState.HEALTHY


def test_health_exec_signals_respect_canary_authority():
    h = WorkerHealth("w")
    h.exec_fail(1.0)
    assert h.state == WorkerState.SUSPECT
    h.exec_ok(2.0)                # canaries on: exec can't self-clear
    assert h.state == WorkerState.SUSPECT
    h2 = WorkerHealth("w2", exec_recovers=True, dead_after=2)
    h2.exec_fail(1.0)
    h2.exec_ok(2.0)               # canaries off: exec IS the probe
    assert h2.state == WorkerState.HEALTHY


def test_health_liveness_hang_and_wedge():
    h = WorkerHealth("w", liveness_s=2.0)
    h.liveness(1.0, 1.0, None)
    assert h.state == WorkerState.HEALTHY
    h.liveness(2.0, 2.5, None)
    assert h.state == WorkerState.SUSPECT and "hang" in h.reason
    h.liveness(3.0, 4.5, None)
    assert h.state == WorkerState.DEAD
    h2 = WorkerHealth("w2", liveness_s=2.0)
    h2.liveness(1.0, None, 5.0)
    assert h2.state == WorkerState.DEAD and "wedge" in h2.reason


def test_health_drain_is_retirement_not_death():
    h = WorkerHealth("w")
    h.drain(1.0)
    assert h.state == WorkerState.DRAINING and not h.admits()
    h.drained(2.0)
    assert h.state == WorkerState.DEAD and h.retired
    snap = h.snapshot()
    assert snap["retired"] and snap["state"] == "dead"


# ---------------------------------------------------------- fault scripts

def test_fault_plan_scripting():
    plan = FaultPlan(CrashAt(at_batch=2), Corrupt(from_batch=5))
    plan.before_batch(0)
    from mxtpu.serving import WorkerCrashed
    with pytest.raises(WorkerCrashed):
        plan.before_batch(2)
    assert any("crashat@2" in f for f in plan.fired)
    early = plan.mutator(3)          # before from_batch: pass-through
    assert early is None or np.allclose(
        early([np.array([1.0, 2.0], np.float32)])[0], [1.0, 2.0])
    mut = plan.mutator(6)
    out = mut([np.array([1.0, 2.0], np.float32)])
    assert not np.allclose(out[0], [1.0, 2.0])   # silently wrong
    assert not plan.wedged(0)
    assert FaultPlan(QueueWedge(after_batches=1)).wedged(1)


# ---------------------------------------------------------- error taxonomy

def test_error_taxonomy():
    assert issubclass(ServerBusy, RetriableError)
    assert issubclass(WorkerLost, RetriableError)
    assert issubclass(RequestTimeout, RetriableError)
    assert issubclass(RetriableError, MXNetError)
    assert ServerBusy("x").retriable and WorkerLost("x").retriable
    # a missed deadline is terminal: retrying cannot un-miss it
    assert not RequestTimeout("x").retriable


# ------------------------------------------------- scenario: happy path

def test_fleet_happy_path_round_robin():
    clk = FakeClock()
    with _router(clk) as router:
        router.add_worker(_worker(clk, "w0"))
        router.add_worker(_worker(clk, "w1"))
        reqs = [router.submit(_payload(i), timeout_s=5.0)
                for i in range(4)]
        _crank(router, clk, n=3)
        for i, r in enumerate(reqs):
            np.testing.assert_allclose(
                r.result(timeout=0)[0], [i, 2.0 * i, 3.0 * i])
            assert r.retries == 0 and not r.won_by_hedge
        assert {r.tried[0] for r in reqs} == {"w0", "w1"}
        snap = router.fleet_stats()
        assert snap["healthy_workers"] == 2
        assert snap["workers"]["w0"]["state"] == "healthy"


# --------------------------------------------- scenario: crash at step k

def test_fleet_crash_requeues_never_drops():
    clk = FakeClock()
    with _router(clk) as router:
        router.add_worker(_worker(clk, "w0"))
        router.add_worker(_worker(
            clk, "w1", faults=FaultPlan(CrashAt(at_batch=0))))
        reqs = [router.submit(_payload(i), timeout_s=10.0)
                for i in range(4)]
        _crank(router, clk)
        assert router.workers()["w1"] == "dead"
        stolen = 0
        for i, r in enumerate(reqs):
            np.testing.assert_allclose(       # in-deadline: all complete
                r.result(timeout=0)[0], [i, 2.0 * i, 3.0 * i])
            if r.requeues:
                stolen += 1
                assert r.retries == 1 and r.tried[-1] == "w0"
        assert stolen == 2                    # w1's share was stolen
        snap = router.fleet_stats()
        assert snap["extras"]["deaths"] == 1
        assert snap["extras"]["requeues"] == 2


# ----------------------------------------------------- scenario: hang

def test_fleet_hang_detected_by_liveness():
    clk = FakeClock()
    with _router(clk, canary=False) as router:
        router.add_worker(_worker(clk, "w0",
                                  faults=FaultPlan(Hang(at_batch=0)),
                                  liveness_s=0.1))
        router.add_worker(_worker(clk, "w1", liveness_s=0.1))
        reqs = [router.submit(_payload(i), timeout_s=10.0)
                for i in range(2)]
        router.tick(clk())                 # dispatch: w0 hangs mid-batch
        hung = [r for r in reqs if r.tried[0] == "w0"]
        assert len(hung) == 1 and not hung[0].done()
        _crank(router, clk, n=8, dt=0.05)  # > 2x liveness passes
        w0 = router.workers()["w0"]
        assert w0 == "dead"
        for i, r in enumerate(reqs):       # the hung request was stolen
            np.testing.assert_allclose(
                r.result(timeout=0)[0], [i, 2.0 * i, 3.0 * i])
        assert hung[0].requeues == 1
        assert "hang" in router.fleet_stats()["workers"]["w0"]["reason"]


# ----------------------------------------------- scenario: queue wedge

def test_fleet_queue_wedge_detected_by_liveness():
    clk = FakeClock()
    with _router(clk, canary=False) as router:
        router.add_worker(_worker(
            clk, "w0", faults=FaultPlan(QueueWedge(after_batches=0)),
            liveness_s=0.1))
        router.add_worker(_worker(clk, "w1", liveness_s=0.1))
        reqs = [router.submit(_payload(i), timeout_s=10.0)
                for i in range(2)]
        wedged = [r for r in reqs if r.tried[0] == "w0"]
        assert len(wedged) == 1
        _crank(router, clk, n=8, dt=0.05)
        assert router.workers()["w0"] == "dead"
        assert "wedge" in \
            router.fleet_stats()["workers"]["w0"]["reason"]
        for i, r in enumerate(reqs):
            np.testing.assert_allclose(
                r.result(timeout=0)[0], [i, 2.0 * i, 3.0 * i])


# ------------------------------------------ scenario: silent corruption

def test_fleet_corruption_caught_by_canary():
    clk = FakeClock()
    with _router(clk) as router:
        router.add_worker(_worker(
            clk, "w0", dead_after=3,
            faults=FaultPlan(Corrupt(from_batch=0))))
        assert router.workers()["w0"] == "healthy"
        # canaries run, results mismatch the expected output, and the
        # worker dies after dead_after consecutive verdicts — no
        # exception is ever raised; only the compare catches it
        _crank(router, clk, n=10, dt=1.1)
        assert router.workers()["w0"] == "dead"
        reason = router.fleet_stats()["workers"]["w0"]["reason"]
        assert "canary" in reason.lower()


# ----------------------------------------- scenario: slow-start warmup

def test_fleet_slow_start_recovers_via_canary():
    clk = FakeClock()
    with _router(clk) as router:
        router.add_worker(_worker(clk, "w0"))
        router.add_worker(_worker(
            clk, "w1", start_recovering=True,
            faults=FaultPlan(SlowStart(first_n=2))))
        assert router.workers()["w1"] == "recovering"
        req = router.submit(_payload(5), timeout_s=10.0)
        router.tick(clk())
        assert req.tried == ["w0"]         # no client traffic while
        req.result(timeout=0)              # still recovering
        _crank(router, clk, n=6, dt=1.1)   # canaries warm it up
        assert router.workers()["w1"] == "healthy"
        # now it takes traffic again
        reqs = [router.submit(_payload(i), timeout_s=10.0)
                for i in range(4)]
        _crank(router, clk, n=2)
        assert {r.tried[0] for r in reqs} == {"w0", "w1"}
        for i, r in enumerate(reqs):
            np.testing.assert_allclose(
                r.result(timeout=0)[0], [i, 2.0 * i, 3.0 * i])


# --------------------------------------------- scenario: drain + handoff

def test_fleet_drain_handoff_warm_replacement():
    clk = FakeClock()
    with _router(clk) as router:
        w0 = _worker(clk, "w0")
        router.add_worker(w0)
        reqs = [router.submit(_payload(i), timeout_s=10.0)
                for i in range(3)]
        _crank(router, clk, n=2)
        for r in reqs:
            r.result(timeout=0)
        meta = router.drain("w0")          # preemption notice arrives
        assert router.workers()["w0"] == "draining"
        assert meta["max_batch_size"] == 4
        assert meta["compiled_buckets"]    # donor working set
        _crank(router, clk, n=2)
        assert router.workers()["w0"] == "dead"
        assert router.fleet_stats()["workers"]["w0"]["retired"]
        assert "deaths" not in router.fleet_stats()["extras"]
        # replacement warms the donor's compiled ladder before traffic
        w2 = _worker(clk, "w2")
        router.add_worker(w2, warm_from=meta)
        assert w2.runner.num_compiled() >= len(meta["compiled_buckets"])
        req = router.submit(_payload(7), timeout_s=10.0)
        _crank(router, clk, n=2)
        np.testing.assert_allclose(req.result(timeout=0)[0],
                                   [7.0, 14.0, 21.0])
        assert req.tried == ["w2"]


# ------------------------------------------------- scenario: hard kill

def test_fleet_kill_steals_outstanding():
    clk = FakeClock()
    with _router(clk, canary=False) as router:
        router.add_worker(_worker(clk, "w0"))
        router.add_worker(_worker(clk, "w1"))
        reqs = [router.submit(_payload(i), timeout_s=10.0)
                for i in range(4)]
        router.kill("w0")                  # preemption, no flush
        _crank(router, clk, n=4)
        for i, r in enumerate(reqs):       # zero in-deadline drops
            np.testing.assert_allclose(
                r.result(timeout=0)[0], [i, 2.0 * i, 3.0 * i])
        assert all(r.tried[-1] == "w1" for r in reqs)


# ------------------------------------------- retry/backoff determinism

def test_backoff_deterministic_and_capped():
    clk = FakeClock()
    r1 = _router(clk, canary=False, seed=7, backoff_base_us=1000,
                 backoff_cap_us=64000, jitter=0.2)
    r2 = _router(clk, canary=False, seed=7, backoff_base_us=1000,
                 backoff_cap_us=64000, jitter=0.2)
    seq1 = [r1._backoff_s(n) for n in range(1, 10)]
    seq2 = [r2._backoff_s(n) for n in range(1, 10)]
    assert seq1 == seq2                    # seeded: reproducible
    assert all(b <= 64000 * 1.2 / 1e6 for b in seq1)
    assert seq1[0] >= 1000 / 1e6           # base + non-negative jitter
    # exponential growth until the cap
    bare = [b / (1 + 0.2) for b in seq1]   # strip max jitter bound
    assert bare[3] > bare[0]
    r1.close()
    r2.close()


def test_fleet_retry_exhaustion_fails_terminally():
    clk = FakeClock()
    with _router(clk, canary=False, retry_max=1,
                 backoff_base_us=100) as router:
        # every worker crashes on every batch: retries must exhaust
        router.add_worker(_worker(
            clk, "w0", faults=FaultPlan(*[CrashAt(at_batch=k)
                                          for k in range(8)])))
        router.add_worker(_worker(
            clk, "w1", faults=FaultPlan(*[CrashAt(at_batch=k)
                                          for k in range(8)])))
        req = router.submit(_payload(1), timeout_s=50.0)
        _crank(router, clk, n=6)
        assert req.done() and req.retries == 1
        with pytest.raises(WorkerLost):
            req.result(timeout=0)


def test_fleet_deadline_expiry_is_timeout_not_loop():
    clk = FakeClock()
    with _router(clk, canary=False) as router:
        router.add_worker(_worker(
            clk, "w0", faults=FaultPlan(QueueWedge(after_batches=0)),
            liveness_s=50.0))              # wedge never detected: the
        req = router.submit(_payload(1), timeout_s=0.2)   # deadline
        _crank(router, clk, n=8, dt=0.1)   # machinery must still fire
        assert req.done()
        with pytest.raises(RequestTimeout):
            req.result(timeout=0)


def test_fleet_pending_buffer_sheds_server_busy():
    clk = FakeClock()
    with _router(clk, canary=False, max_pending=2) as router:
        router.add_worker(_worker(clk, "w0", start_recovering=True))
        router.submit(_payload(0), timeout_s=5.0)   # parked: no
        router.submit(_payload(1), timeout_s=5.0)   # healthy worker
        with pytest.raises(ServerBusy):
            router.submit(_payload(2), timeout_s=5.0)


# ------------------------------------------------------ scenario: hedge

def test_fleet_hedged_request_wins_elsewhere():
    clk = FakeClock()
    with _router(clk, canary=False, hedge_after_us=100) as router:
        router.add_worker(_worker(
            clk, "w0", faults=FaultPlan(QueueWedge(after_batches=0)),
            liveness_s=100.0))             # slow, not (yet) dead
        router.add_worker(_worker(clk, "w1", liveness_s=100.0))
        req = router.submit(_payload(2), timeout_s=10.0)
        while req.tried[:1] != ["w0"]:     # force the slow worker first
            req = router.submit(_payload(2), timeout_s=10.0)
        router.tick(clk())
        assert not req.done()              # stuck behind the wedge
        _crank(router, clk, n=3, dt=0.01)  # > hedge_after_us passes
        np.testing.assert_allclose(req.result(timeout=0)[0],
                                   [2.0, 4.0, 6.0])
        assert req.won_by_hedge and req.hedges == 1
        assert req.tried[-1] == "w1"
        assert router.fleet_stats()["extras"]["hedges_won"] == 1


# --------------------------------------------------- threaded smoke

def test_fleet_threaded_smoke_with_kill():
    router = FleetRouter(threaded=True, tick_s=0.002,
                         canary=CANARY_IN, canary_expect=CANARY_OUT,
                         canary_interval_s=0.05,
                         canary_timeout_s=1.0)
    with router:
        router.add_worker(FleetWorker(_mul_runner(), "w0",
                                      max_queue_delay_us=500.0))
        router.add_worker(FleetWorker(_mul_runner(), "w1",
                                      max_queue_delay_us=500.0))
        reqs = [router.submit(_payload(i % 5), timeout_s=10.0)
                for i in range(8)]
        router.kill("w0")
        reqs += [router.submit(_payload(i % 5), timeout_s=10.0)
                 for i in range(8, 16)]
        for i, r in enumerate(reqs):       # nobody hangs, nobody drops
            v = i % 5
            np.testing.assert_allclose(r.result(timeout=10.0)[0],
                                       [v, 2.0 * v, 3.0 * v])
        snap = router.fleet_stats()
        assert snap["workers"]["w0"]["state"] == "dead"
        assert snap["workers"]["w1"]["state"] == "healthy"
        assert snap["completed"] == 16


@pytest.mark.slow
def test_fleet_kill_restart_soak():
    """Kill/restart soak: sustained traffic, a worker killed mid-run,
    a warm replacement attached from the drain handoff — zero
    in-deadline requests dropped or hanging."""
    router = FleetRouter(threaded=True, tick_s=0.002,
                         canary=CANARY_IN, canary_expect=CANARY_OUT,
                         canary_interval_s=0.05, canary_timeout_s=1.0)
    with router:
        w0 = FleetWorker(_mul_runner(), "w0", max_queue_delay_us=500.0)
        router.add_worker(w0)
        router.add_worker(FleetWorker(_mul_runner(), "w1",
                                      max_queue_delay_us=500.0))
        meta = w0.handoff()
        reqs = []
        for i in range(120):
            reqs.append(router.submit(_payload(i % 7), timeout_s=30.0))
            if i == 40:
                router.kill("w0")
            if i == 60:
                router.add_worker(FleetWorker(
                    _mul_runner(), "w2", max_queue_delay_us=500.0),
                    warm_from=meta)
        for i, r in enumerate(reqs):
            v = i % 7
            np.testing.assert_allclose(r.result(timeout=30.0)[0],
                                       [v, 2.0 * v, 3.0 * v])
        snap = router.fleet_stats()
        assert snap["completed"] == 120
        assert snap["workers"]["w2"]["state"] == "healthy"
