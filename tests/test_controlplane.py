"""Fleet control plane (ISSUE 11): autoscaling, predictive admission
control, priority/fairness scheduling.

Every scenario is fully deterministic: tick-driven router
(``threaded=False``) on a hand-stepped clock, scripted faults
(:class:`SlowExec` advances the SAME fake clock, so service-time
histograms are exact), and the autoscaler driven by the router's own
tick via ``add_controller``.  Three committed scenarios:

* burst-absorb — scale-up via warm handoff, ZERO cold compiles on the
  data path (asserted via ``num_compiled`` before the replica serves);
* scale-down-then-burst — drain-based scale-down (victim retires, no
  request dropped), then floor-repair scale-up warmed from the LAST
  retiree's handoff after the survivor dies;
* brownout-shed — admission control sheds strictly low-priority
  first; late high-priority submits still admitted.
"""
import numpy as np
import pytest

from mxtpu import obs
from mxtpu import symbol as sym
from mxtpu.base import MXNetError
from mxtpu.serving import (Autoscaler, FleetRouter, FleetWorker,
                           ModelRunner, PriorityClass, ServerBusy,
                           ServingStats, SlowExec, WorkerLost,
                           WorkerState, parse_classes)
from mxtpu.serving.faults import FaultPlan
from mxtpu.serving.router import FleetRequest


class FakeClock:
    """Hand-stepped monotonic clock (same pattern as test_fleet)."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _mul_runner(**kwargs):
    data = sym.var("data")
    w = sym.var("w")
    return ModelRunner(data * w, {"w": np.array([1.0, 2.0, 3.0],
                                                np.float32)},
                       {"data": (3,)}, max_batch_size=4, **kwargs)


def _router(clk, **kw):
    # control-plane tests run canary-free: compile accounting and
    # class accounting stay exactly what the test scripted
    return FleetRouter(clock=clk, threaded=False, canary=None, **kw)


def _worker(clk, name, **kw):
    kw.setdefault("max_queue_delay_us", 0.0)
    return FleetWorker(_mul_runner(), name, clock=clk, **kw)


def _payload(v):
    return {"data": np.full(3, float(v), np.float32)}


def _crank(router, clk, n=8, dt=0.05):
    for _ in range(n):
        clk.advance(dt)
        router.tick(clk())


# ----------------------------------------------------- priority classes

def test_parse_classes():
    got = parse_classes("gold:8,bulk:1:64")
    assert [(c.name, c.weight, c.quota) for c in got] == \
        [("gold", 8.0, None), ("bulk", 1.0, 64)]
    assert parse_classes("") == []
    assert parse_classes("solo")[0].weight == 1.0
    with pytest.raises(MXNetError):
        parse_classes("bad:notanumber")
    with pytest.raises(MXNetError):
        parse_classes("bad:2:1.5")


def test_priority_class_validation():
    with pytest.raises(MXNetError):
        PriorityClass("")
    with pytest.raises(MXNetError):
        PriorityClass("x", weight=0.0)
    with pytest.raises(MXNetError):
        PriorityClass("x", quota=0)


def test_router_rejects_unknown_and_duplicate_classes():
    clk = FakeClock()
    with pytest.raises(MXNetError):
        _router(clk, classes=[PriorityClass("a"), PriorityClass("a")])
    r = _router(clk, classes=[PriorityClass("gold", 8.0),
                              PriorityClass("bulk", 1.0)])
    r.add_worker(_worker(clk, "w0"))
    with pytest.raises(MXNetError):
        r.submit(_payload(1), priority="platinum")
    # no "default" class configured: highest weight is the default
    req = r.submit(_payload(1))
    assert req.priority == "gold"
    r.close()


# ------------------------------------------------ queue ETA estimator

def test_queue_eta_none_until_first_batch():
    st = ServingStats(clock=FakeClock())
    assert st.queue_eta_us() is None
    st.record_completion(500.0, 100.0)    # completion but no batch yet
    assert st.queue_eta_us() is None


def test_queue_eta_formula():
    st = ServingStats(clock=FakeClock())
    st.record_batch(4, 4)
    st.record_batch(4, 4)                 # fill = 8 real / 2 batches = 4
    for _ in range(4):
        st.record_completion(1000.0, 200.0)   # service = 800us each
    st.record_queue_depth(8)
    # p95 service x (1 + depth/fill): 800 * (1 + 8/4)
    assert st.queue_eta_us() == pytest.approx(2400.0)
    # the depth override prices a hypothetical queue position
    assert st.queue_eta_us(depth=0) == pytest.approx(800.0)
    assert st.queue_eta_us(depth=4) == pytest.approx(1600.0)


def test_queue_eta_service_time_never_negative():
    st = ServingStats(clock=FakeClock())
    st.record_batch(1, 1)
    st.record_completion(100.0, 500.0)    # clock skew: queue > latency
    assert st.queue_eta_us(depth=0) == pytest.approx(0.0)


def test_worker_refusal_carries_eta_hint():
    clk = FakeClock()
    w = _worker(clk, "w0", max_queue=2)
    w.stats.record_batch(4, 4)
    for _ in range(4):
        w.stats.record_completion(1000.0, 200.0)   # service 800us
    for i in range(2):
        w.submit_attempt(_payload(i), (1, None), None, None, clk())
    with pytest.raises(ServerBusy) as ei:
        w.submit_attempt(_payload(9), (1, None), None, None, clk())
    # depth 2, fill 4: 800 * (1 + 2/4)
    assert ei.value.retry_after_us == pytest.approx(1200.0)
    w.shutdown()


# ------------------------------------------- retry uses the ETA hint

def test_retry_after_hint_replaces_exponential_backoff():
    clk = FakeClock()
    r = _router(clk, backoff_base_us=1000, backoff_cap_us=64000,
                jitter=0.2)
    r.add_worker(_worker(clk, "w0"))
    now = clk()

    def freq():
        return FleetRequest(_payload(1), (1, None), None, now,
                            now + 5.0)

    def last_due():
        with r._lock:
            return r._pending[-1].due

    # park path: the refusal's hint prices the wait exactly
    f1 = freq()
    f1.last_error = ServerBusy("full", retry_after_us=5000.0)
    with r._lock:
        r._park_locked(f1, now, now)
    assert last_due() == pytest.approx(now + 0.005)
    # a hint above the backoff ceiling clamps to the ceiling
    f2 = freq()
    f2.last_error = ServerBusy("full", retry_after_us=1e9)
    with r._lock:
        r._park_locked(f2, now, now)
    assert last_due() == pytest.approx(now + 0.064)
    # attempt-failed path: hint wins over exponential backoff
    f3 = freq()
    with r._lock:
        r._handle_attempt_failed_locked(
            f3, "w0", ServerBusy("x", retry_after_us=2000.0), now)
    assert f3.retries == 1
    assert last_due() == pytest.approx(now + 0.002)
    # no hint: exponential backoff (base 1000us, jitter <= 20%)
    f4 = freq()
    with r._lock:
        r._handle_attempt_failed_locked(f4, "w0", ServerBusy("x"), now)
    assert now + 0.001 <= last_due() <= now + 0.00121
    r.close()


# --------------------------------------------------- per-class quotas

def test_quota_sheds_and_frees_on_completion():
    clk = FakeClock()
    r = _router(clk, classes=[PriorityClass("gold", 8.0),
                              PriorityClass("bulk", 1.0, quota=2)])
    r.add_worker(_worker(clk, "w0"))
    b1 = r.submit(_payload(1), priority="bulk")
    b2 = r.submit(_payload(2), priority="bulk")
    with pytest.raises(ServerBusy):
        r.submit(_payload(3), priority="bulk")   # quota exhausted
    g = r.submit(_payload(4), priority="gold")   # gold unaffected
    snap = r.fleet_stats()
    assert snap["extras"]["shed_quota"] == 1
    assert snap["classes"]["bulk"]["in_system"] == 2
    _crank(r, clk, n=2)
    for req in (b1, b2, g):
        assert req.done() and req.result(timeout=0) is not None
    # completions freed the quota (in-system decremented)
    assert r.fleet_stats()["classes"]["bulk"]["in_system"] == 0
    b3 = r.submit(_payload(5), priority="bulk")
    _crank(r, clk, n=2)
    assert b3.done()
    r.close()


# ------------------------------------------------ scenario: burst-absorb

def test_burst_absorb_scales_up_with_zero_cold_compiles():
    obs.reset()
    clk = FakeClock()
    r = _router(clk)
    w0 = FleetWorker(_mul_runner(), "w0", clock=clk,
                     max_queue_delay_us=0.0, max_queue=4)
    r.add_worker(w0)
    w0.runner.warmup()                    # donor holds the full ladder
    nbuckets = w0.runner.num_compiled()
    made = []

    def make_worker(name):
        w = FleetWorker(_mul_runner(), name, clock=clk,
                        max_queue_delay_us=0.0, max_queue=4)
        made.append(w)
        return w

    scaler = Autoscaler(r, make_worker, min_workers=1, max_workers=3,
                        up_depth=3.0, down_depth=0.5, breach_ticks=2,
                        cooldown_s=0.2)
    r.add_controller(scaler.tick)
    reqs = [r.submit(_payload(i), timeout_s=30.0) for i in range(24)]
    # crank until the first scale-up fires; the controller runs at the
    # END of the tick, so the replica has not served a single request
    for _ in range(20):
        clk.advance(0.05)
        r.tick(clk())
        if made:
            break
    assert made, "burst never triggered a scale-up"
    # warm handoff: the full donor ladder compiled BEFORE any traffic
    assert made[0].runner.num_compiled() == nbuckets
    _crank(r, clk, n=20)
    for i, req in enumerate(reqs):
        got = req.result(timeout=0)
        np.testing.assert_allclose(
            got[0], np.full(3, float(i)) * np.array([1.0, 2.0, 3.0]),
            rtol=1e-5)
    # zero cold compiles on the data path: no worker compiled anything
    # beyond the warmed ladder while absorbing the burst
    for w in [w0] + made:
        assert w.runner.num_compiled() == nbuckets
    snap = scaler.snapshot()
    assert snap["scale_ups"] == len(made) >= 1
    ups = [e for e in scaler.recorder.events()
           if e["kind"] == "scale_up"]
    assert len(ups) == len(made) and ups[0]["donor"] == "w0"
    assert r.fleet_stats()["extras"]["scale_ups"] == len(made)
    r.close()


# ------------------------------------- scenario: scale-down-then-burst

def test_scale_down_drains_then_burst_rewarms_from_last_handoff():
    obs.reset()
    clk = FakeClock()
    r = _router(clk)
    w0 = _worker(clk, "w0")
    w1 = _worker(clk, "w1")
    for w in (w0, w1):
        r.add_worker(w)
        w.runner.warmup()
    nbuckets = w0.runner.num_compiled()
    made = []

    def make_worker(name):
        w = _worker(clk, name)
        made.append(w)
        return w

    scaler = Autoscaler(r, make_worker, min_workers=1, max_workers=2,
                        up_depth=3.0, down_depth=0.5, breach_ticks=2,
                        cooldown_s=0.1)
    r.add_controller(scaler.tick)
    # phase 1: some traffic completes, then the fleet idles and the
    # autoscaler retires one worker by DRAINING it (never killing)
    reqs = [r.submit(_payload(i), timeout_s=10.0) for i in range(4)]
    for _ in range(20):
        clk.advance(0.05)
        r.tick(clk())
        if scaler.snapshot()["scale_downs"] == 1:
            break
    assert scaler.snapshot()["scale_downs"] == 1
    _crank(r, clk, n=2)                   # drain completes
    retired = [w for w in (w0, w1) if w.health.retired]
    assert len(retired) == 1
    assert retired[0].health.state == WorkerState.DEAD
    assert retired[0].outstanding() == 0
    snap = r.fleet_stats()
    assert snap["extras"]["drains_completed"] == 1
    # zero dropped in-flight: everything completed, nothing was stolen
    assert all(q.done() for q in reqs)
    assert snap["extras"].get("requeues", 0) == 0
    assert snap["timed_out"] == 0
    # phase 2: the survivor dies; floor repair scales up warmed from
    # the LAST retiree's handoff (no live donor exists)
    survivor = w0 if retired[0] is w1 else w1
    r.kill(survivor.name)
    for _ in range(20):
        clk.advance(0.05)
        r.tick(clk())
        if made:
            break
    assert made and made[0].runner.num_compiled() == nbuckets
    ups = [e for e in scaler.recorder.events()
           if e["kind"] == "scale_up"]
    assert ups and ups[0]["donor"] == "last_handoff"
    reqs2 = [r.submit(_payload(10 + i), timeout_s=10.0)
             for i in range(6)]
    _crank(r, clk, n=6)
    for i, req in enumerate(reqs2):
        got = req.result(timeout=0)
        np.testing.assert_allclose(
            got[0],
            np.full(3, float(10 + i)) * np.array([1.0, 2.0, 3.0]),
            rtol=1e-5)
    assert made[0].runner.num_compiled() == nbuckets
    r.close()


# ----------------------------------------------- scenario: brownout-shed

def test_brownout_sheds_strictly_low_priority_first():
    obs.reset()
    clk = FakeClock()
    r = _router(clk,
                classes=[PriorityClass("gold", 8.0),
                         PriorityClass("bulk", 1.0)],
                admission=True, admission_margin=3.0)
    w = FleetWorker(_mul_runner(), "w0", clock=clk,
                    max_queue_delay_us=0.0,
                    faults=FaultPlan(SlowExec(0.1, clk.advance)))
    r.add_worker(w)
    # prime the service-time histogram: one full batch at 0.1s/batch
    prime = [r.submit(_payload(i)) for i in range(4)]
    _crank(r, clk, n=1, dt=0.01)
    assert all(p.done() for p in prime)
    assert w.stats.queue_eta_us(depth=0) == pytest.approx(1e5)
    # the brownout: interleaved gold/bulk burst against a 1.2s budget.
    # Admission predicts eta = 100000us * (1 + ahead/4) counting only
    # same-or-higher-priority in-system traffic, sheds when
    # margin(3) * eta > 1.2s — i.e. when ahead > 12.
    golds, bulks, shed = [], [], []
    for i in range(16):
        cls = "gold" if i % 2 == 0 else "bulk"
        try:
            req = r.submit(_payload(i), timeout_s=1.2, priority=cls)
            (golds if cls == "gold" else bulks).append((i, req))
        except ServerBusy as e:
            shed.append((i, cls, e))
    # strict priority order: every shed is bulk, and late golds were
    # still admitted AFTER bulk started shedding
    assert [(i, c) for i, c, _ in shed] == [(13, "bulk"), (15, "bulk")]
    assert len(golds) == 8 and golds[-1][0] == 14 > shed[0][0]
    for _, _, e in shed:
        assert e.retry_after_us is not None and e.retry_after_us > 0
    # every admitted request completes correctly within its deadline
    _crank(r, clk, n=4, dt=0.01)
    for i, req in golds + bulks:
        got = req.result(timeout=0)
        np.testing.assert_allclose(
            got[0], np.full(3, float(i)) * np.array([1.0, 2.0, 3.0]),
            rtol=1e-5)
        assert req.t_done <= req.deadline
    snap = r.fleet_stats()
    assert snap["extras"]["shed_admission"] == 2
    assert snap["timed_out"] == 0
    sheds = [e for e in r.recorder.events() if e["kind"] == "shed"]
    assert len(sheds) == 2
    assert all(e["reason"] == "admission" and e["cls"] == "bulk"
               and e["eta_us"] > 0 for e in sheds)
    r.close()


# --------------------------------------------- starvation regression

def test_wrr_prevents_starvation_of_low_rate_tenant():
    clk = FakeClock()
    r = _router(clk, classes=[PriorityClass("hot", 1.0),
                              PriorityClass("lo", 1.0)])
    r.add_worker(FleetWorker(_mul_runner(), "w0", clock=clk,
                             max_queue_delay_us=0.0, max_queue=4))
    # a hot tenant floods 20 requests BEFORE the low-rate tenant's 2
    # arrive: FIFO would serve all 20 first; equal-weight WRR
    # interleaves the classes 1:1 out of the router backlog
    hot = [r.submit(_payload(i), timeout_s=30.0, priority="hot")
           for i in range(20)]
    lo = [r.submit(_payload(100 + i), timeout_s=30.0, priority="lo")
          for i in range(2)]
    _crank(r, clk, n=10)
    assert all(q.done() for q in hot + lo)
    lo_done = max(q.t_done for q in lo)
    # both low-rate requests finished ahead of most of the flood
    assert sum(1 for q in hot if q.t_done > lo_done) >= 12
    assert r.fleet_stats()["timed_out"] == 0
    r.close()


# ------------------------------------------------- autoscaler plumbing

def test_autoscaler_validates_bounds():
    clk = FakeClock()
    r = _router(clk)
    r.add_worker(_worker(clk, "w0"))
    with pytest.raises(MXNetError):
        Autoscaler(r, lambda n: None, min_workers=0, max_workers=2)
    with pytest.raises(MXNetError):
        Autoscaler(r, lambda n: None, min_workers=3, max_workers=2)
    r.close()


def test_autoscaler_respects_cooldown_and_max():
    clk = FakeClock()
    r = _router(clk)
    r.add_worker(FleetWorker(_mul_runner(), "w0", clock=clk,
                             max_queue_delay_us=0.0, max_queue=4))
    made = []

    def make_worker(name):
        w = FleetWorker(_mul_runner(), name, clock=clk,
                        max_queue_delay_us=0.0, max_queue=4)
        made.append(w)
        return w

    scaler = Autoscaler(r, make_worker, min_workers=1, max_workers=2,
                        up_depth=1.0, breach_ticks=1, cooldown_s=10.0)
    # sustained overload, but cooldown + max_workers cap the response
    reqs = [r.submit(_payload(i), timeout_s=60.0) for i in range(30)]
    for _ in range(6):
        clk.advance(0.05)
        r.tick(clk())
        scaler.tick(clk())               # driven directly, no hook
    assert len(made) == 1                # cooldown blocked the rest
    assert scaler.snapshot()["scale_ups"] == 1
    clk.advance(11.0)
    r.tick(clk())
    scaler.tick(clk())
    assert len(made) <= 2 <= 1 + scaler.max_workers
    _crank(r, clk, n=12)
    assert all(q.done() for q in reqs)
    r.close()
