"""tools/mxlint — framework-aware static analysis (ISSUE 5).

Tier-1 gate: the repo itself must lint clean against the committed
baseline (currently empty), plus unit coverage for every rule family,
the suppression machinery, the baseline fingerprinting, and the CLI
exit-code contract.
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.mxlint.core import (DEFAULT_BASELINE, DEFAULT_PATHS,
                               REPO_ROOT, FileCtx, lint_repo,
                               load_baseline, load_knobs_module,
                               split_by_baseline, write_baseline)
from tools.mxlint import rules as R


def _ctx(src: str, rel: str = "mxtpu/fake.py") -> FileCtx:
    return FileCtx(Path("/nonexistent/fake.py"), rel,
                   textwrap.dedent(src))


def _names(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------- the gate

def test_repo_lints_clean_against_baseline():
    """THE acceptance check: mxtpu/, tools/ and bench.py produce no
    findings outside tools/mxlint/baseline.json."""
    findings = lint_repo(DEFAULT_PATHS)
    new, _ = split_by_baseline(findings, load_baseline())
    assert not new, "new lint findings:\n" + "\n".join(
        f.format() for f in new)


def test_cli_exit_code_contract(tmp_path):
    """`python -m tools.mxlint --check` exits 0 on a clean tree and 1
    when a new violation appears."""
    env_ok = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--check"],
        cwd=REPO_ROOT, capture_output=True, text=True)
    assert env_ok.returncode == 0, env_ok.stdout + env_ok.stderr

    bad = tmp_path / "violating.py"
    bad.write_text('import os\n'
                   'V = os.environ.get("MXTPU_BOGUS", "1")\n')
    env_bad = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--check", str(bad)],
        cwd=REPO_ROOT, capture_output=True, text=True)
    assert env_bad.returncode == 1, env_bad.stdout + env_bad.stderr
    assert "knob-raw-env" in env_bad.stdout


# ------------------------------------------------------- retrace rules

def test_impure_call_in_jit_body():
    ctx = _ctx("""
        import jax, time

        @jax.jit
        def step(x):
            t0 = time.time()
            return x + t0
    """)
    found = R.RetraceImpureCall().check(ctx)
    assert _names(found) == ["retrace-impure-call"]
    assert "time.time" in found[0].message


def test_jax_random_is_not_impure():
    ctx = _ctx("""
        import jax

        @jax.jit
        def step(key, x):
            k1, k2 = jax.random.split(key)
            return x + jax.random.normal(k1, x.shape)
    """)
    assert R.RetraceImpureCall().check(ctx) == []


def test_np_random_in_jitted_name():
    ctx = _ctx("""
        import jax
        import numpy as np

        def fn(x):
            return x + np.random.randn(4)

        step = jax.jit(fn)
    """)
    assert _names(R.RetraceImpureCall().check(ctx)) == \
        ["retrace-impure-call"]


def test_traced_branch_flagged_but_static_branches_allowed():
    ctx = _ctx("""
        import jax

        @jax.jit
        def step(x, y=None):
            if y is None:          # None-ness: static, fine
                y = x
            if x.shape[0] > 2:     # shape: static, fine
                y = y * 2
            if x > 0:              # VALUE: retrace hazard
                y = y + 1
            return y
    """)
    found = R.RetraceTracedBranch().check(ctx)
    assert _names(found) == ["retrace-traced-branch"]
    assert "`x`" in found[0].message


def test_inline_jit_flagged():
    ctx = _ctx("""
        import jax

        def f(x):
            return jax.jit(lambda a: a * 2)(x)
    """)
    assert _names(R.RetraceInlineJit().check(ctx)) == \
        ["retrace-inline-jit"]


def test_concretize_in_jit_body():
    ctx = _ctx("""
        import jax

        @jax.jit
        def step(x):
            return float(x) + x.item()
    """)
    names = _names(R.RetraceConcretize().check(ctx))
    assert names == ["retrace-concretize", "retrace-concretize"]


# ----------------------------------------------------------- host-sync

_HOT_SRC = """
    # mxlint: hot-path
    import numpy as np

    def dispatch(out):
        return np.asarray(out)
"""


def test_host_sync_needs_hot_path_pragma():
    cold = _ctx(_HOT_SRC.replace("# mxlint: hot-path", "# plain"))
    assert R.HostSync().check(cold) == []
    hot = _ctx(_HOT_SRC)
    assert _names(R.HostSync().check(hot)) == ["host-sync"]


def test_host_sync_sync_point_whitelists():
    ctx = _ctx("""
        # mxlint: hot-path
        import numpy as np

        def dispatch(out):
            # mxlint: sync-point — deliberate materialization
            return np.asarray(out)
    """)
    assert R.HostSync().check(ctx) == []


def test_suppression_comment_filters_finding():
    src = """
        # mxlint: hot-path
        import numpy as np

        def dispatch(out):
            return np.asarray(out)  # mxlint: disable=host-sync
    """
    ctx = _ctx(src)
    findings = [f for f in R.HostSync().check(ctx)
                if not ctx.suppressed(f.rule, f.line)]
    assert findings == []


# ------------------------------------------------------ lock discipline

_LOCK_SRC = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.total = 0  # guarded-by: _lock

        def bump(self):
            with self._lock:
                self.total += 1

        def peek(self):
            return self.total          # VIOLATION: no lock

        def _sum_locked(self):
            return self.total          # convention: lock held
"""


def test_lock_discipline_flags_unlocked_access():
    found = R.LockDiscipline().check(_ctx(_LOCK_SRC))
    assert _names(found) == ["lock-discipline"]
    assert "self.total" in found[0].message and \
        "_lock" in found[0].message


def test_lock_discipline_nested_function_does_not_inherit():
    ctx = _ctx("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # guarded-by: _lock

            def go(self):
                with self._lock:
                    def cb():
                        return self.n   # runs later, unlocked
                    return cb
    """)
    assert _names(R.LockDiscipline().check(ctx)) == ["lock-discipline"]


# -------------------------------------------------------- knob registry

def test_knob_raw_env_read_flagged_but_write_allowed():
    ctx = _ctx("""
        import os
        A = os.environ.get("MXTPU_FOO", "1")
        os.environ["MXTPU_FOO"] = "0"     # write: launch/probe pattern
        B = os.environ["MXNET_BAR"]
        C = os.environ.get(dynamic_name)  # non-literal: out of scope
    """)
    found = R.KnobRawEnv().check(ctx)
    assert _names(found) == ["knob-raw-env", "knob-raw-env"]


def test_knob_raw_env_exempts_knobs_py():
    ctx = _ctx('import os\nA = os.environ.get("MXTPU_FOO")\n',
               rel="mxtpu/knobs.py")
    assert R.KnobRawEnv().check(ctx) == []


def test_knob_unregistered():
    ctx = _ctx("""
        from mxtpu import knobs
        a = knobs.get("MXTPU_ZERO")            # registered
        b = knobs.get("MXTPU_NOT_A_KNOB")      # not
    """)
    found = R.KnobUnregistered().check(ctx)
    assert _names(found) == ["knob-unregistered"]
    assert "MXTPU_NOT_A_KNOB" in found[0].message


def test_knobs_module_standalone_load_and_types():
    mod = load_knobs_module()
    reg = mod.registered()
    assert "MXTPU_GUARDS" in reg and "MXTPU_BENCH_MODEL" in reg
    # typed defaults straight from the registry
    assert mod.get("MXTPU_SERVING_MAX_BATCH") == 32
    assert mod.get("MXTPU_BATCHED_OPT") is True
    with pytest.raises(Exception, match="unregistered"):
        mod.get("MXTPU_NOT_A_KNOB")


def test_knobs_env_and_mxnet_fallback(monkeypatch):
    from mxtpu import knobs
    monkeypatch.setenv("MXTPU_SERVING_MAX_BATCH", "8")
    assert knobs.get("MXTPU_SERVING_MAX_BATCH") == 8
    monkeypatch.delenv("MXTPU_SERVING_MAX_BATCH")
    monkeypatch.setenv("MXNET_SERVING_MAX_BATCH", "16")
    assert knobs.get("MXTPU_SERVING_MAX_BATCH") == 16


def test_readme_drift_detection_and_fix(tmp_path):
    root = tmp_path
    (root / "mxtpu").mkdir()
    (root / "mxtpu" / "knobs.py").write_text(
        (REPO_ROOT / "mxtpu" / "knobs.py").read_text())
    knobs = load_knobs_module(root)
    (root / "README.md").write_text(
        f"# fake\n\n{knobs.TABLE_BEGIN}\nstale\n{knobs.TABLE_END}\n")
    assert _names(R.readme_drift(root)) == ["knob-readme-drift"]
    assert R.fix_readme(root) is True
    assert R.readme_drift(root) == []
    assert R.fix_readme(root) is False  # idempotent


def test_real_readme_table_is_current():
    assert R.readme_drift(REPO_ROOT) == []


# --------------------------------------------------------- obs registry

def test_obs_registry_naming_convention():
    src = """
        from mxtpu import obs
        ok1 = obs.counter("mxtpu_req_total", "fine")
        ok2 = obs.histogram("mxtpu_wait_seconds", "fine")
        ok3 = obs.gauge("mxtpu_depth", "fine")
        bad1 = obs.counter("requests_total", "no prefix")
        bad2 = obs.counter("mxtpu_requests", "no _total")
        bad3 = obs.histogram("mxtpu_wait", "no unit suffix")
        bad4 = obs.gauge("mxtpu_BadName", "not snake_case")
    """
    found = R.ObsRegistry().check(_ctx(src))
    assert _names(found) == ["obs-registry"] * 4
    assert {f.line for f in found} == {6, 7, 8, 9}


def test_obs_registry_hot_path_counters():
    src = """
        from mxtpu import profiler
        _N_CALLS = 0
        _RETRY_COUNT = 0
        PAD = 1
        c = profiler.Counter("batches", 0)
    """
    # flagged inside the serving/parallel hot paths...
    found = R.ObsRegistry().check(
        _ctx(src, rel="mxtpu/serving/fake.py"))
    msgs = " ".join(f.message for f in found)
    assert len(found) == 3
    assert "_N_CALLS" in msgs and "_RETRY_COUNT" in msgs
    assert "profiler.Counter" in msgs
    # ... but not elsewhere (profiler.py itself, examples, ...)
    assert R.ObsRegistry().check(
        _ctx(src, rel="mxtpu/other.py")) == []


def test_obs_registry_suppression():
    src = """
        from mxtpu import profiler
        _N_CALLS = 0  # mxlint: disable=obs-registry
    """
    ctx = _ctx(src, rel="mxtpu/parallel/fake.py")
    found = [f for f in R.ObsRegistry().check(ctx)
             if not ctx.suppressed(f.rule, f.line)]
    assert found == []


# -------------------------------------------------------- thread-hygiene

def test_thread_hygiene_flags_sleep_polling_loop():
    src = """
        import time
        import threading

        def worker(stop):
            while not stop.is_set():
                time.sleep(0.1)

        def fine(stop):
            while not stop.is_set():
                stop.wait(0.1)
        time.sleep(1.0)   # outside a loop: startup delay, allowed
    """
    found = R.ThreadHygiene().check(
        _ctx(src, rel="mxtpu/serving/fake.py"))
    assert _names(found) == ["thread-hygiene"]
    assert found[0].line == 7 and "time.sleep" in found[0].message


def test_thread_hygiene_flags_non_daemon_thread():
    src = """
        import threading
        t_bad = threading.Thread(target=print)
        t_also_bad = threading.Thread(target=print, daemon=False)
        t_ok = threading.Thread(target=print, daemon=True)
    """
    found = R.ThreadHygiene().check(
        _ctx(src, rel="mxtpu/obs/fake.py"))
    assert _names(found) == ["thread-hygiene"] * 2
    assert {f.line for f in found} == {3, 4}


def test_thread_hygiene_scoped_to_serving_and_obs():
    src = """
        import time
        import threading
        t = threading.Thread(target=print)
        def spin():
            while True:
                time.sleep(1)
    """
    # outside serving/obs the rule does not apply at all
    assert R.ThreadHygiene().applies(
        _ctx(src, rel="mxtpu/parallel/fake.py")) is False
    assert R.ThreadHygiene().applies(
        _ctx(src, rel="mxtpu/serving/fake.py")) is True


# ------------------------------------------------------- dtype hygiene

def test_dtype_hygiene_flags_f64_forms():
    ctx = _ctx("""
        import numpy as np
        import jax

        def widen(x):
            jax.config.update("jax_enable_x64", True)
            y = x.astype(np.float64)
            return np.float64(y.sum())
    """)
    found = R.DtypeHygiene().check(ctx)
    assert _names(found) == ["dtype-hygiene"] * 3
    msgs = " ".join(f.message for f in found)
    assert "jax_enable_x64" in msgs
    assert ".astype(float64)" in msgs
    assert "float64 literal" in msgs


def test_dtype_hygiene_astype_string_and_pragma():
    ctx = _ctx("""
        def narrow(x):
            a = x.astype("float64")
            b = x.astype("float64")  # mxlint: disable=dtype-hygiene
            return a + b
    """)
    found = [f for f in R.DtypeHygiene().check(ctx)
             if not ctx.suppressed(f.rule, f.line)]
    assert len(found) == 1
    assert found[0].line == 3


def test_dtype_hygiene_scoped_to_library_code():
    src = """
        import numpy as np
        SEED = np.float64(0.5)
    """
    # tests/ and tools/ seed f64 on purpose (the f64-creep rule's
    # fixtures live there) — only mxtpu/ is held to the policy
    assert R.DtypeHygiene().applies(
        _ctx(src, rel="tests/test_fake.py")) is False
    assert R.DtypeHygiene().applies(
        _ctx(src, rel="tools/fake.py")) is False
    assert R.DtypeHygiene().applies(
        _ctx(src, rel="mxtpu/fake.py")) is True


# ------------------------------------------------------- no-adhoc-bf16

def test_no_adhoc_bf16_flags_cast_forms():
    ctx = _ctx("""
        import jax.numpy as jnp

        def forward(self, F, x, net):
            a = x.astype("bfloat16")
            b = x.astype(jnp.bfloat16)
            c = F.cast(x, dtype="bf16")
            net.cast("bfloat16")
            w = F.zeros((4, 4), dtype="bfloat16")
            return a, b, c, w
    """, rel="mxtpu/models/fake.py")
    found = R.NoAdhocBf16().check(ctx)
    assert _names(found) == ["no-adhoc-bf16"] * 5
    assert {f.line for f in found} == {5, 6, 7, 8, 9}
    msgs = " ".join(f.message for f in found)
    assert "amp_policy.json" in msgs


def test_no_adhoc_bf16_pragma_waives():
    ctx = _ctx("""
        def forward(x):
            a = x.astype("bfloat16")
            b = x.astype("bfloat16")  # mxlint: disable=no-adhoc-bf16
            return a + b
    """, rel="mxtpu/gluon/fake.py")
    found = [f for f in R.NoAdhocBf16().check(ctx)
             if not ctx.suppressed(f.rule, f.line)]
    assert len(found) == 1
    assert found[0].line == 3


def test_no_adhoc_bf16_scoped_to_hot_paths():
    src = """
        def forward(x):
            return x.astype("bfloat16")
    """
    # the amp module, parallel's entry upcasts and tests cast bf16 on
    # purpose — only the model/layer hot paths are held to the policy
    rule = R.NoAdhocBf16()
    assert rule.applies(_ctx(src, rel="mxtpu/models/fake.py")) is True
    assert rule.applies(_ctx(src, rel="mxtpu/gluon/fake.py")) is True
    assert rule.applies(_ctx(src, rel="mxtpu/amp/fake.py")) is False
    assert rule.applies(_ctx(src, rel="mxtpu/parallel/fake.py")) \
        is False
    assert rule.applies(_ctx(src, rel="tests/test_fake.py")) is False


# ----------------------------------------------------- raw-deserialize

def test_raw_deserialize_flags_pickle_and_executable_load():
    ctx = _ctx("""
        import pickle, marshal
        from jax.experimental import serialize_executable

        def load(path):
            with open(path, "rb") as f:
                a = pickle.load(f)
            b = pickle.loads(open(path, "rb").read())
            c = marshal.loads(open(path, "rb").read())
            d = serialize_executable.deserialize_and_load(a, b, c)
            return d
    """)
    found = R.RawDeserialize().check(ctx)
    assert _names(found) == ["raw-deserialize"] * 4
    msgs = " ".join(f.message for f in found)
    assert "pickle.load" in msgs
    assert "deserialize_and_load" in msgs
    assert "WRONG program" in msgs


def test_raw_deserialize_pragma_waives():
    ctx = _ctx("""
        import pickle

        def load(blob):
            return pickle.loads(blob)  # mxlint: disable=raw-deserialize (in-process bytes)
    """)
    found = [f for f in R.RawDeserialize().check(ctx)
             if not ctx.suppressed(f.rule, f.line)]
    assert found == []


def test_raw_deserialize_cache_module_is_the_sanctioned_door():
    src = """
        import pickle
        def load(blob):
            return pickle.loads(blob)
    """
    # the checksum-verified loader in mxtpu/cache.py is THE one place
    # allowed to revive disk bytes; tests stay exempt like the other
    # source-hygiene rules
    assert R.RawDeserialize().applies(
        _ctx(src, rel="mxtpu/cache.py")) is False
    assert R.RawDeserialize().applies(
        _ctx(src, rel="tests/test_fake.py")) is False
    assert R.RawDeserialize().applies(
        _ctx(src, rel="mxtpu/serving/runner.py")) is True
    assert R.RawDeserialize().applies(
        _ctx(src, rel="tools/fake.py")) is True


# ------------------------------------------------------------- baseline

def test_baseline_fingerprint_survives_line_moves(tmp_path):
    src = """
        import os
        PAD = 1
        A = os.environ.get("MXTPU_FOO", "1")
    """
    f1 = R.KnobRawEnv().check(_ctx(src))[0]
    # same line text, shifted three lines down
    f2 = R.KnobRawEnv().check(_ctx("\n\n\n" + textwrap.dedent(src)))[0]
    for f in (f1, f2):
        f.snippet = 'A = os.environ.get("MXTPU_FOO", "1")'
    assert f1.fingerprint == f2.fingerprint

    path = tmp_path / "baseline.json"
    write_baseline([f1], path)
    new, old = split_by_baseline([f2], load_baseline(path))
    assert new == [] and old == [f2]


def test_committed_baseline_is_empty():
    """ISSUE 5 acceptance: the tree lints clean — every real finding
    was fixed or judged and annotated in place, none baselined."""
    data = json.loads(DEFAULT_BASELINE.read_text())
    assert data["fingerprints"] == []
