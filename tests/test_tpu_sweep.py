"""Registry-wide cpu<->tpu consistency sweep (VERDICT r3 item 2).

~300 auto-synthesized + curated one-op cases (incl. a bf16 tier)
over ~280 distinct registry rules run fwd+bwd on BOTH backends and cross-compare — the reference's
``tests/python/gpu/test_operator_gpu.py``† pattern at registry scale.
Groups of ~25 cases compile as ONE program per backend in an isolated
subprocess (see tests/tpu_sweep_runner.py for why).

``test_sweep_covers_registry`` runs everywhere and pins the contract:
every registered op is either swept or ledgered with a reason — a new
op cannot silently dodge the sweep.  The hardware groups run only
under MXTPU_TEST_PLATFORM=tpu, like test_tpu_consistency.py.
"""
import json
import os
import subprocess
import sys

import jax
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
GROUP_SIZE = 25
N_GROUPS = 13  # must satisfy N_GROUPS*GROUP_SIZE >= len(cases)

# documented per-op tolerance overrides (relative to max(|ref|, 1)):
# populated from the r4 real-hardware runs (300 cases, ONE
# divergence).  Every entry is a DIVERGENCE ACKNOWLEDGEMENT with a
# cause, not a silent skip; tol=None means value comparison is
# skipped entirely for that op.
# keys are (op_name, tier) with tier 0 = f32 cases, 100 = bf16 tier —
# an acknowledgement for one tier must NOT silently loosen the other
# (r4 review)
XFAIL_TOL = {
    # eigenvectors are defined only up to per-column sign (and
    # ordering within degenerate eigenspaces) — cpu and tpu LAPACK/
    # Eigh lowering legitimately pick different conventions (measured
    # fwd dev 1.6 on the real chip).  Eigenvalue correctness is
    # covered by test_ops_breadth's linalg tests.
    ("linalg_syevd", 0): ("eigenvector sign/order convention differs "
                          "per backend", None),
}

DEFAULT_FWD_TOL = 2e-4
DEFAULT_GRAD_TOL = 2e-3
# case idx >= 100 marks the bf16 tier (tpu_sweep_lib.bf16_cases):
# an 8-bit mantissa needs correspondingly loose bounds
BF16_FWD_TOL = 3e-2
BF16_GRAD_TOL = 6e-2


def test_sweep_covers_registry():
    from mxtpu.ops.registry import list_ops
    from tests.tpu_sweep_lib import build_cases
    cases, skipped = build_cases()
    covered = {c[0] for c in cases} | set(skipped)
    missing = sorted(set(list_ops()) - covered)
    assert not missing, f"ops neither swept nor ledgered: {missing}"
    # the hardware groups must actually span every case — otherwise a
    # newly-curated op past the last group silently never executes
    assert N_GROUPS * GROUP_SIZE >= len(cases), \
        (N_GROUPS, GROUP_SIZE, len(cases))
    # the sweep must stay registry-scale, not shrink back to a handful
    assert len({c[0] for c in cases}) >= 250, len(cases)
    # ledger reasons must be real text, not empty placeholders
    assert all(len(r) > 10 for r in skipped.values())


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="needs a real accelerator backend (MXTPU_TEST_PLATFORM=tpu)")
@pytest.mark.parametrize("group", range(N_GROUPS))
def test_registry_sweep_group(group):
    env = dict(os.environ)
    env.pop("MXTPU_TEST_PLATFORM", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_HERE, "tpu_sweep_runner.py"),
         str(group), str(GROUP_SIZE)],
        capture_output=True, text=True, timeout=1200, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    results = json.loads(line)["results"]
    bad = []
    for r in results:
        if r["status"] != "ok":
            bad.append(r)
            continue
        tier = 100 if r["case"] >= 100 else 0
        if (r["name"], tier) in XFAIL_TOL:
            tol = XFAIL_TOL[(r["name"], tier)][1]
            if tol is None:
                continue  # documented convention divergence
            fwd_tol, grad_tol = tol, DEFAULT_GRAD_TOL
        elif tier == 100:  # bf16 tier
            fwd_tol, grad_tol = BF16_FWD_TOL, BF16_GRAD_TOL
        else:
            fwd_tol, grad_tol = DEFAULT_FWD_TOL, DEFAULT_GRAD_TOL
        if r["max_fwd_err"] is not None and \
                r["max_fwd_err"] > fwd_tol:
            bad.append(r)
        elif r["max_grad_err"] is not None and \
                r["max_grad_err"] > grad_tol:
            bad.append(r)
    assert not bad, json.dumps(bad, indent=2)[:3000]
