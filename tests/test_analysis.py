"""mxtpu.analysis + tools/hlocheck (ISSUE 6).

Covers: the HLO parser on synthetic text; every one of the five
contract rule families tripped by a perturbation that touches ONLY
that family; the lockfile round-trip (``--update`` then ``--check``
is a fixed point, a corrupted lockfile fails with the right rule,
an unknown target is a usage error); two-lowering stability of
summaries; the ``MXTPU_HLO_AUDIT`` runtime knob; and the
``program_summary`` wiring on serving's ``ModelRunner``
(``TrainStep``'s is pinned by tests/test_zero.py).

Compiled programs are reached through ``analysis.compiled_summary``
/ ``compiled_artifact`` only — mxlint's ``hlo-raw-assert`` rule keeps
raw ``.lower()``/``hlo_text()`` grepping out of tests/.
"""
import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mxtpu import analysis
from mxtpu.analysis import contracts as C
from mxtpu.base import MXNetError

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------
# synthetic module: one fusion hiding bracket ops, one custom call,
# two collectives, one f64 parameter + downcast, a dead convert whose
# line each perturbation below swaps for its own poison
# ---------------------------------------------------------------------
_CV_LINE = "  %cv = f32[4]{0} convert(f64[4]{0} %p1)"
_CT_LINE = ("  %ct = f32[16,8]{1,0} transpose(f32[8,16]{1,0} %cc), "
            "dimensions={1,0}")

SYNTH = f"""HloModule synth

%add (x: f32[], y: f32[]) -> f32[] {{
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %z = f32[] add(f32[] %x, f32[] %y)
}}

%wrapped_fusion (param_0: f32[8,16]) -> f32[8,16] {{
  %param_0 = f32[8,16]{{1,0}} parameter(0)
  %t = f32[16,8]{{1,0}} transpose(f32[8,16]{{1,0}} %param_0), dimensions={{1,0}}
  ROOT %c = f32[8,16]{{1,0}} copy(f32[16,8]{{1,0}} %t)
}}

ENTRY %main (p0: f32[8,16], p1: f64[4]) -> (f32[8,16], f32[2,16]) {{
  %p0 = f32[8,16]{{1,0}} parameter(0)
  %p1 = f64[4]{{0}} parameter(1)
{_CV_LINE}
  %fu = f32[8,16]{{1,0}} fusion(f32[8,16]{{1,0}} %p0), kind=kLoop, calls=%wrapped_fusion
  %ar = f32[8,16]{{1,0}} all-reduce(f32[8,16]{{1,0}} %fu), replica_groups={{}}, to_apply=%add
  %rs = f32[2,16]{{1,0}} reduce-scatter(f32[8,16]{{1,0}} %ar), replica_groups={{{{0,1,2,3}}}}, dimensions={{0}}, to_apply=%add
  %cc = f32[8,16]{{1,0}} custom-call(f32[8,16]{{1,0}} %fu), custom_call_target="my_kernel"
{_CT_LINE}
  ROOT %tup = (f32[8,16]{{1,0}}, f32[2,16]{{1,0}}) tuple(f32[16,8]{{1,0}} %ct, f32[2,16]{{1,0}} %rs)
}}
"""

CLEAN = """HloModule clean

ENTRY %main (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  ROOT %r = f32[4]{0} add(f32[4]{0} %p0, f32[4]{0} %p0)
}
"""


def _summ(text):
    return analysis.summarize(text, {"hbm_peak": 4096})


def _rules(violations):
    return {v.rule for v in violations}


# ------------------------------------------------------------- parser

def test_parser_structure():
    prog = analysis.parse_hlo(SYNTH)
    assert set(prog.computations) == {"add", "wrapped_fusion", "main"}
    assert prog.entry_name == "main"
    assert prog.instruction_count() == 15
    assert prog.count_opcode("transpose") == 2
    main = prog.entry
    cc = main.by_name["cc"]
    assert cc.opcode == "custom-call" and cc.target == "my_kernel"
    fu = main.by_name["fu"]
    assert "wrapped_fusion" in fu.calls
    ar = main.by_name["ar"]
    assert ar.result_bytes() == 8 * 16 * 4
    assert ar.result_elems() == 128
    tup = main.by_name["tup"]
    assert tup.root
    assert tup.shapes == [("f32", (8, 16)), ("f32", (2, 16))]
    assert tup.result_bytes() == 512 + 128
    # consumers see through operand lists
    assert {i.name for i in main.consumers("cc")} == {"ct"}


def test_summary_families():
    s = _summ(SYNTH)
    assert s["collectives"] == {
        "all-reduce": {"count": 1, "bytes": 512, "max_elems": 128},
        "reduce-scatter": {"count": 1, "bytes": 128, "max_elems": 32},
    }
    # feeds: transpose+copy hidden in the fusion; consumes: %ct
    assert s["custom_calls"] == {
        "my_kernel": {"count": 1, "bracketed": 3}}
    assert s["dtype"]["f64_ops"] == 1          # the %p1 parameter
    assert s["dtype"]["converts"] == {"f64->f32": 1}
    assert s["dtype"]["upcasts"] == {}         # downcast is not creep
    assert s["budgets"] == {"instruction_count": 15, "fusion_count": 1,
                            "peak_bytes": 4096}
    assert s["host_transfers"] == {"count": 0, "ops": {}}


def test_bracket_evidence_rows():
    rows = analysis.bracket_evidence(analysis.parse_hlo(SYNTH))
    assert len(rows) == 3
    feeds = [r for r in rows if r["side"] == "feeds"]
    assert {r["op"] for r in feeds} == {"transpose", "copy"}
    assert all(r["via"] == "fu" for r in feeds)
    (consume,) = [r for r in rows if r["side"] == "consumes"]
    assert consume["op"] == "transpose" and consume["via"] == ""
    table = analysis.format_evidence_table(rows)
    assert "my_kernel" in table and "feeds" in table


# -------------------------------------------------- contract families

def test_contract_fixed_point_on_identical_summary():
    s = _summ(SYNTH)
    v, n = C.check_contract(C.make_contract("synth", {"p": s}),
                            {"p": copy.deepcopy(s)})
    assert v == [] and n == []


_AG_LINE = ("  %ag = f32[8,16]{1,0} all-gather(f32[8,16]{1,0} %fu), "
            "replica_groups={{0,1,2,3}}, dimensions={0}\n  %cc =")
_PERTURBATIONS = [
    # each mutation must trip its family and ONLY its family
    ("collective-new",
     lambda t: t.replace("  %cc =", _AG_LINE), "collectives"),
    ("collective-vanished",
     lambda t: t.replace("reduce-scatter(", "add("), "collectives"),
    ("custom-call-vanished",
     lambda t: t.replace("custom-call(", "negate("),
     "custom-call-bracket"),
    ("bracket-growth",       # a new copy consuming the custom call
     lambda t: t.replace(
         _CV_LINE, "  %cv = f32[8,16]{1,0} copy(f32[8,16]{1,0} %cc)"),
     "custom-call-bracket"),
    ("dtype-upcast",         # f64 result + f32->f64 convert appear
     lambda t: t.replace(
         _CV_LINE,
         "  %cv = f64[8,16]{1,0} convert(f32[8,16]{1,0} %p0)"),
     "dtype-policy"),
    ("host-transfer",
     lambda t: t.replace(
         _CV_LINE, "  %cv = token[] outfeed(f32[8,16]{1,0} %p0)"),
     "host-transfer"),
    ("budget-blowout",       # +4/15 instructions > the 10% tolerance
     lambda t: t.replace("  ROOT %tup", "".join(
         f"  %d{i} = f32[8,16]{{1,0}} add(f32[8,16]{{1,0}} %p0, "
         f"f32[8,16]{{1,0}} %p0)\n" for i in range(4)) + "  ROOT %tup"),
     "budget"),
]


@pytest.mark.parametrize(
    "mut,rule", [(m, r) for _, m, r in _PERTURBATIONS],
    ids=[name for name, _, _ in _PERTURBATIONS])
def test_synth_perturbation_trips_exactly_one_family(mut, rule):
    contract = C.make_contract("synth", {"p": _summ(SYNTH)})
    v, _ = C.check_contract(contract, {"p": _summ(mut(SYNTH))})
    assert v, f"perturbation did not trip {rule}"
    assert _rules(v) == {rule}


def test_budget_improvement_is_a_notice_not_a_violation():
    _, bloat, _ = _PERTURBATIONS[-1]
    contract = C.make_contract("synth", {"p": _summ(bloat(SYNTH))})
    v, n = C.check_contract(contract, {"p": _summ(SYNTH)})
    assert v == []
    assert any("improved" in x for x in n)


def test_missing_and_extra_programs_are_contract_violations():
    s = _summ(SYNTH)
    contract = C.make_contract("synth", {"p": s})
    v, _ = C.check_contract(contract, {"p": s, "extra": s})
    assert _rules(v) == {"contract"}
    v, _ = C.check_contract(contract, {})
    assert _rules(v) == {"contract"}


# ------------------------------------------- compiled perturbations

_LOOSE = {"instruction_count": 10.0, "fusion_count": 10.0,
          "peak_bytes": 10.0}


def _eigh_base(a):
    import jax.numpy as jnp
    w, _ = jnp.linalg.eigh(a + a.T)
    return w.sum()


def _eigh_pert(a):
    # same eigh custom call, but a transposed operand and an
    # eigenvector consumer force extra layout ops at the boundary
    import jax.numpy as jnp
    w, v = jnp.linalg.eigh(jnp.transpose(a @ a))
    return (v * w).sum()


def _sym_input():
    return np.arange(64.0, dtype=np.float32).reshape(8, 8) / 64.0


def test_compiled_bracket_perturbation_trips():
    a = _sym_input()
    base = analysis.compiled_summary(_eigh_base, a)
    pert = analysis.compiled_summary(_eigh_pert, a)
    assert any("syevd" in t for t in base["custom_calls"])
    contract = C.make_contract("eigh", {"p": base}, tolerances=_LOOSE)
    v, _ = C.check_contract(contract, {"p": pert})
    assert _rules(v) == {"custom-call-bracket"}
    assert any("brackets" in x.message for x in v)


def test_compiled_dtype_perturbation_trips():
    from jax.experimental import enable_x64

    def f32_step(x):
        return (x * 2.0).sum()

    def f64_step(x):
        import jax.numpy as jnp
        return (x.astype(jnp.float64) * 2.0).sum()

    x = np.ones((8, 8), np.float32)
    base = analysis.compiled_summary(f32_step, x)
    assert base["dtype"]["f64_ops"] == 0
    with enable_x64(True):
        pert = analysis.compiled_summary(f64_step, x)
    assert pert["dtype"]["f64_ops"] > 0
    assert pert["dtype"]["upcasts"].get("f32->f64", 0) >= 1
    contract = C.make_contract("dt", {"p": base}, tolerances=_LOOSE)
    v, _ = C.check_contract(contract, {"p": pert})
    assert "dtype-policy" in _rules(v)
    assert not _rules(v) & {"collectives", "custom-call-bracket",
                            "host-transfer"}


def test_compiled_host_transfer_trips():
    import jax

    def host_step(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2.0,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    obs = analysis.compiled_summary(host_step, np.ones(4, np.float32))
    assert obs["host_transfers"]["count"] >= 1
    assert any("callback" in op for op in obs["host_transfers"]["ops"])
    # zero out ONLY the stored transfer count: exactly that rule trips
    contract = C.make_contract("cb", {"p": copy.deepcopy(obs)})
    contract["programs"]["p"]["host_transfers"] = {"count": 0,
                                                   "ops": {}}
    v, _ = C.check_contract(contract, {"p": obs})
    assert _rules(v) == {"host-transfer"}


def test_two_lowering_stability():
    a = _sym_input()
    s1 = analysis.compiled_summary(_eigh_pert, a)
    s2 = analysis.compiled_summary(_eigh_pert, a)
    assert json.dumps(s1, sort_keys=True) == json.dumps(s2,
                                                        sort_keys=True)
    v, n = C.check_contract(C.make_contract("eigh", {"p": s1}),
                            {"p": s2})
    assert v == [] and n == []


# ---------------------------------------------------- runtime audit

class _FakeCompiled:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        return self._text


def test_maybe_audit_knob(monkeypatch):
    monkeypatch.delenv("MXTPU_HLO_AUDIT", raising=False)
    monkeypatch.delenv("MXNET_HLO_AUDIT", raising=False)
    dirty = _FakeCompiled(SYNTH)   # f64 param + bracketed custom call
    assert analysis.maybe_audit(dirty, label="t", mem={}) is None
    monkeypatch.setenv("MXTPU_HLO_AUDIT", "1")
    with pytest.warns(RuntimeWarning, match="HLO audit"):
        summ = analysis.maybe_audit(dirty, label="t", mem={})
    assert summ["custom_calls"]["my_kernel"]["bracketed"] == 3
    monkeypatch.setenv("MXTPU_HLO_AUDIT", "2")
    with pytest.raises(MXNetError, match="MXTPU_HLO_AUDIT=2"):
        analysis.maybe_audit(dirty, label="t", mem={})
    # a clean program passes silently even in raise mode
    assert analysis.maybe_audit(_FakeCompiled(CLEAN), label="t",
                                mem={}) is not None


def test_runner_program_summary_wiring(tmp_path):
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.gluon import nn
    from mxtpu.serving import ModelRunner
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize(init="xavier")
    net(nd.array(np.zeros((1, 5), np.float32)))
    sym_file, param_file = net.export(str(tmp_path / "m"))
    r = ModelRunner.from_export(sym_file, param_file,
                                input_specs={"data": (5,)},
                                max_batch_size=4)
    s = r.program_summary(r.bucket_for(1))
    assert s["budgets"]["instruction_count"] > 0
    assert s["host_transfers"]["count"] == 0
    text, _mem = r.program_artifact(r.bucket_for(1))
    assert isinstance(text, str) and "ENTRY" in text


# ------------------------------------------------------------- CLI

def _hlocheck(args):
    return subprocess.run(
        [sys.executable, "-m", "tools.hlocheck", *args],
        capture_output=True, text=True, cwd=_ROOT, timeout=240)


def test_cli_update_check_fixed_point(tmp_path):
    r = _hlocheck(["--update", "selftest",
                   "--contracts-dir", str(tmp_path)])
    assert r.returncode == 0, r.stdout + r.stderr
    path = tmp_path / "selftest.json"
    assert path.exists()
    r = _hlocheck(["--check", "selftest",
                   "--contracts-dir", str(tmp_path)])
    assert r.returncode == 0, r.stdout + r.stderr
    # corrupt exactly one pinned fact: the right family must be named
    data = json.loads(path.read_text())
    prog = next(iter(data["programs"]))
    cc = data["programs"][prog]["custom_calls"]
    cc[next(iter(cc))]["bracketed"] = 0
    path.write_text(json.dumps(data))
    r = _hlocheck(["--check", "selftest",
                   "--contracts-dir", str(tmp_path)])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "custom-call-bracket" in r.stdout


def test_cli_unknown_target_is_usage_error(tmp_path):
    r = _hlocheck(["--check", "no-such-target",
                   "--contracts-dir", str(tmp_path)])
    assert r.returncode == 2


@pytest.mark.slow
def test_committed_contracts_check_clean():
    """The committed contracts/ lockfiles hold for this tree — the
    same gate ci_static and `bench.py --contracts` run."""
    r = _hlocheck(["--check"])
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-2000:])
