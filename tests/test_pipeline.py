"""Pipeline parallelism (GPipe over a 'pp' mesh axis) — parity with
sequential execution, gradient flow, and the full pipelined train step.

Runs on the virtual 8-device CPU mesh (conftest).  Reference analogue:
the 2018 codebase's only model parallelism is manual ctx_group
placement (example/model-parallel-lstm†); the GPipe schedule is the
modern capability SURVEY §2.4 requires.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxtpu import nd
from mxtpu import parallel
from mxtpu.parallel import P
from mxtpu.parallel.pipeline import spmd_pipeline, stack_stage_params


def _toy_stage_fn(params_loc, h):
    # params_loc: [W (L/S, C, C), b (L/S, C)] — residual dense layers
    def layer(carry, lp):
        w, b = lp
        return carry + jnp.tanh(carry @ w + b), None
    h, _ = jax.lax.scan(layer, h, tuple(params_loc))
    return h


def _toy_params(L, C, seed=0):
    rng = np.random.RandomState(seed)
    ws = [rng.randn(C, C).astype(np.float32) * 0.3 for _ in range(L)]
    bs = [rng.randn(C).astype(np.float32) * 0.1 for _ in range(L)]
    return ws, bs


def _seq_apply(ws, bs, x):
    h = x
    for w, b in zip(ws, bs):
        h = h + jnp.tanh(h @ w + b)
    return h


def test_spmd_pipeline_forward_parity():
    L, C, B, S = 8, 16, 8, 4
    mesh = parallel.make_mesh({"pp": S})
    ws, bs = _toy_params(L, C)
    stacked = stack_stage_params([[w, b] for w, b in zip(ws, bs)])
    x = np.random.RandomState(1).randn(B, C).astype(np.float32)
    got = spmd_pipeline(_toy_stage_fn, stacked, jnp.asarray(x),
                        mesh=mesh, axis="pp", n_microbatches=4)
    want = _seq_apply(ws, bs, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_microbatch_counts():
    """Any M dividing B gives identical results (schedule-invariant)."""
    L, C, B, S = 4, 8, 12, 4
    mesh = parallel.make_mesh({"pp": S})
    ws, bs = _toy_params(L, C, seed=3)
    stacked = stack_stage_params([[w, b] for w, b in zip(ws, bs)])
    x = np.random.RandomState(2).randn(B, C).astype(np.float32)
    want = _seq_apply(ws, bs, jnp.asarray(x))
    for m in (2, 3, 6, 12):
        got = spmd_pipeline(_toy_stage_fn, stacked, jnp.asarray(x),
                            mesh=mesh, axis="pp", n_microbatches=m)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_grad_parity():
    """Reverse-mode AD through the scheduled scan == sequential grads."""
    L, C, B, S = 4, 8, 8, 4
    mesh = parallel.make_mesh({"pp": S})
    ws, bs = _toy_params(L, C, seed=5)
    stacked = stack_stage_params([[w, b] for w, b in zip(ws, bs)])
    x = jnp.asarray(np.random.RandomState(4).randn(B, C)
                    .astype(np.float32))

    def loss_pipe(sp):
        return jnp.sum(spmd_pipeline(_toy_stage_fn, sp, x, mesh=mesh,
                                     axis="pp", n_microbatches=4) ** 2)

    def loss_seq(sp):
        sw, sb = sp
        h = x
        for i in range(L):
            h = h + jnp.tanh(h @ sw[i] + sb[i])
        return jnp.sum(h ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for gp, gs in zip(g_pipe, g_seq):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                                   rtol=1e-4, atol=1e-4)


def test_spmd_pipeline_with_dp_axis():
    """pp×dp composition: batch stays dp-sharded through the pipeline."""
    L, C, B = 4, 8, 8
    mesh = parallel.make_mesh({"pp": 4, "dp": 2})
    ws, bs = _toy_params(L, C, seed=7)
    stacked = stack_stage_params([[w, b] for w, b in zip(ws, bs)])
    x = np.random.RandomState(6).randn(B, C).astype(np.float32)
    got = spmd_pipeline(_toy_stage_fn, stacked, jnp.asarray(x),
                        mesh=mesh, axis="pp", n_microbatches=2,
                        batch_spec=P("dp"))
    want = _seq_apply(ws, bs, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# ----------------------------------------------------------------------
# full pipelined train step over gluon blocks
# ----------------------------------------------------------------------
def _build_model(units=32, hidden=64, heads=4, L=4, classes=10,
                 dropout=0.0, seed=11):
    import mxtpu
    from mxtpu.gluon import nn
    from mxtpu.models.transformer import TransformerEncoderCell
    mxtpu.random.seed(seed)
    embed = nn.Dense(units, flatten=False)
    cells = [TransformerEncoderCell(units, hidden, heads, dropout)
             for _ in range(L)]
    head = nn.Dense(classes, flatten=False)
    for blk in [embed, *cells, head]:
        blk.initialize(init="xavier")
    return embed, cells, head


def _eager_loss(embed, cells, head, loss_fn, x, y):
    h = embed(x)
    for c in cells:
        h = c(h)
    out = head(h)
    return float(nd.mean(loss_fn(out, y)).asscalar())


def test_pipeline_train_step_loss_decreases_and_matches_eager():
    from mxtpu.gluon import loss as gloss
    mesh = parallel.make_mesh({"pp": 4})
    embed, cells, head = _build_model()
    loss_fn = gloss.SoftmaxCrossEntropyLoss()

    step = parallel.build_pipeline_train_step(
        embed, cells, head, loss_fn, "sgd",
        {"learning_rate": 0.1}, mesh=mesh, n_microbatches=4)

    rng = np.random.RandomState(0)
    B, T, Cin = 8, 6, 12
    x = nd.array(rng.randn(B, T, Cin).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (B, T)).astype(np.float32))

    # step 1 loss must equal the eager loss on the same params
    eager0 = _eager_loss(embed, cells, head, loss_fn, x, y)
    losses = [float(step(x, y).asscalar())]
    assert abs(losses[0] - eager0) < 5e-3, (losses[0], eager0)
    for _ in range(14):
        losses.append(float(step(x, y).asscalar()))
    assert losses[-1] < losses[0] * 0.7, losses

    # writeback: eager forward with synced params equals the loss the
    # NEXT step reports (same parameter values at that point)
    step.sync_params()
    eager_now = _eager_loss(embed, cells, head, loss_fn, x, y)
    loss_next = float(step(x, y).asscalar())
    assert abs(eager_now - loss_next) < 5e-3, (eager_now, loss_next)


def test_pipeline_train_step_pp_dp():
    from mxtpu.gluon import loss as gloss
    mesh = parallel.make_mesh({"pp": 2, "dp": 4})
    embed, cells, head = _build_model(L=4, seed=13)
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    step = parallel.build_pipeline_train_step(
        embed, cells, head, loss_fn, "adam",
        {"learning_rate": 3e-3}, mesh=mesh, n_microbatches=2,
        dp_axis="dp")
    rng = np.random.RandomState(1)
    B, T, Cin = 8, 5, 12
    x = nd.array(rng.randn(B, T, Cin).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (B, T)).astype(np.float32))
    losses = [float(step(x, y).asscalar()) for _ in range(10)]
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    # stacked cell params really live sharded over pp
    assert len(step._sv[0].sharding.device_set) == 8


def test_pipeline_eval_mode_and_frozen_params():
    from mxtpu.gluon import loss as gloss
    mesh = parallel.make_mesh({"pp": 4})
    embed, cells, head = _build_model(L=4, seed=17)
    for p in embed.collect_params().values():
        p.grad_req = "null"  # freeze the embed
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    step = parallel.build_pipeline_train_step(
        embed, cells, head, loss_fn, "sgd",
        {"learning_rate": 0.1}, mesh=mesh, n_microbatches=4)
    rng = np.random.RandomState(2)
    x = nd.array(rng.randn(8, 6, 12).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (8, 6)).astype(np.float32))
    step(x, y)  # triggers deferred init + setup
    step.sync_params()
    embed_before = [np.asarray(p.data().data)
                    for p in embed.collect_params().values()]
    for _ in range(4):
        step(x, y)
    # eval call: loss computed, nothing mutates
    t_before = step._t
    ev_id = [id(v) for v in step._ev]
    l_eval = float(step(x, y, training=False).asscalar())
    assert np.isfinite(l_eval)
    assert step._t == t_before and [id(v) for v in step._ev] == ev_id
    # frozen embed params unchanged by training
    step.sync_params()
    for before, p in zip(embed_before,
                         embed.collect_params().values()):
        np.testing.assert_array_equal(before, np.asarray(p.data().data))


def test_pipeline_save_load_states(tmp_path):
    from mxtpu.gluon import loss as gloss
    mesh = parallel.make_mesh({"pp": 4})
    embed, cells, head = _build_model(L=4, seed=19)
    step = parallel.build_pipeline_train_step(
        embed, cells, head, gloss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-3}, mesh=mesh, n_microbatches=2)
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(8, 4, 12).astype(np.float32))
    y = nd.array(rng.randint(0, 10, (8, 4)).astype(np.float32))
    for _ in range(3):
        step(x, y)
    f = str(tmp_path / "pipe.states")
    step.save_states(f)
    t_saved = step._t
    m_saved = np.asarray(step._opt_state_s[0][0])
    step(x, y)
    step.load_states(f)
    assert step._t == t_saved
    np.testing.assert_array_equal(np.asarray(step._opt_state_s[0][0]),
                                  m_saved)


def test_pipeline_rejects_bad_shapes():
    from mxtpu.base import MXNetError
    from mxtpu.gluon import loss as gloss
    mesh = parallel.make_mesh({"pp": 4})
    embed, cells, head = _build_model(L=3)
    with pytest.raises(MXNetError):
        parallel.build_pipeline_train_step(
            embed, cells, head, gloss.SoftmaxCrossEntropyLoss(),
            mesh=mesh)  # 3 layers not divisible by 4 stages
