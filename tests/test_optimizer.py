"""Optimizer tests vs numpy reference implementations (the reference's
``tests/python/unittest/test_optimizer.py``† approach)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu import optimizer as opt


def _run_updates(optimizer, w0, grads):
    w = nd.array(w0.copy())
    state = optimizer.create_state(0, w)
    for g in grads:
        optimizer.update(0, w, nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w0 = np.random.randn(5, 4).astype(np.float32)
    grads = [np.random.randn(5, 4).astype(np.float32) for _ in range(5)]
    got = _run_updates(opt.SGD(learning_rate=0.1, wd=0.01), w0, grads)
    w = w0.copy()
    for g in grads:
        w = w - 0.1 * (g + 0.01 * w)
    assert np.allclose(got, w, atol=1e-5)


def test_sgd_momentum_matches_numpy():
    w0 = np.random.randn(6).astype(np.float32)
    grads = [np.random.randn(6).astype(np.float32) for _ in range(4)]
    got = _run_updates(opt.SGD(learning_rate=0.2, momentum=0.9), w0, grads)
    w, m = w0.copy(), np.zeros_like(w0)
    for g in grads:
        m = 0.9 * m - 0.2 * g
        w = w + m
    assert np.allclose(got, w, atol=1e-5)


def test_adam_matches_numpy():
    w0 = np.random.randn(4, 3).astype(np.float32)
    grads = [np.random.randn(4, 3).astype(np.float32) for _ in range(6)]
    got = _run_updates(opt.Adam(learning_rate=0.01), w0, grads)
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t, g in enumerate(grads, 1):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        w = w - lr * m / (np.sqrt(v) + eps)
    assert np.allclose(got, w, atol=1e-5)


def test_rmsprop_matches_numpy():
    w0 = np.random.randn(8).astype(np.float32)
    grads = [np.random.randn(8).astype(np.float32) for _ in range(5)]
    got = _run_updates(opt.RMSProp(learning_rate=0.01, gamma1=0.9), w0,
                       grads)
    w, n = w0.copy().astype(np.float64), np.zeros(8)
    for g in grads:
        n = 0.9 * n + 0.1 * g * g
        w = w - 0.01 * g / np.sqrt(n + 1e-8)
    assert np.allclose(got, w, atol=1e-4)


def test_adagrad_matches_numpy():
    w0 = np.random.randn(5).astype(np.float32)
    grads = [np.random.randn(5).astype(np.float32) for _ in range(5)]
    got = _run_updates(opt.AdaGrad(learning_rate=0.1), w0, grads)
    w, h = w0.copy().astype(np.float64), np.zeros(5)
    for g in grads:
        h += g * g
        w = w - 0.1 * g / np.sqrt(h + 1e-7)
    assert np.allclose(got, w, atol=1e-5)


def test_ftrl_signum_adadelta_adamax_nadam_run():
    w0 = np.random.randn(6).astype(np.float32)
    grads = [np.random.randn(6).astype(np.float32) for _ in range(3)]
    for o in [opt.Ftrl(), opt.Signum(), opt.AdaDelta(), opt.Adamax(),
              opt.Nadam(), opt.NAG(momentum=0.9), opt.SGLD()]:
        got = _run_updates(o, w0, grads)
        assert got.shape == w0.shape
        assert np.isfinite(got).all(), type(o).__name__


def test_create_registry():
    assert isinstance(opt.create("sgd"), opt.SGD)
    assert isinstance(opt.create("adam", learning_rate=0.1), opt.Adam)
    assert isinstance(opt.create("ccSGD"), opt.SGD)
    with pytest.raises(mx.MXNetError):
        opt.create("definitely_not_an_optimizer")


def test_lr_scheduler_factor():
    sched = opt.lr_scheduler.FactorScheduler(step=10, factor=0.5,
                                             base_lr=1.0)
    assert sched(5) == 1.0
    assert sched(11) == 0.5
    assert sched(21) == 0.25


def test_lr_scheduler_multifactor():
    sched = opt.lr_scheduler.MultiFactorScheduler(step=[5, 10], factor=0.1,
                                                  base_lr=1.0)
    assert sched(3) == 1.0
    assert abs(sched(7) - 0.1) < 1e-12
    assert abs(sched(12) - 0.01) < 1e-12


def test_lr_scheduler_poly_cosine_warmup():
    poly = opt.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0,
                                          pwr=1)
    assert abs(poly(50) - 0.5) < 1e-6
    cos = opt.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0)
    assert abs(cos(50) - 0.5) < 1e-6
    warm = opt.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0,
                                          pwr=1, warmup_steps=10,
                                          warmup_begin_lr=0.0)
    assert warm(5) == 0.5  # halfway through warmup


def test_optimizer_with_scheduler():
    sched = opt.lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                             base_lr=1.0)
    o = opt.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = nd.array(np.ones(3, np.float32))
    for _ in range(5):
        o.update(0, w, nd.array(np.zeros(3, np.float32)), None)
    assert o.learning_rate < 1.0


def test_updater_states_roundtrip():
    o = opt.Adam()
    u = opt.get_updater(o)
    w = nd.array(np.random.randn(4).astype(np.float32))
    u(0, nd.array(np.random.randn(4).astype(np.float32)), w)
    blob = u.get_states()
    u2 = opt.get_updater(opt.Adam())
    u2.set_states(blob)
    assert 0 in u2.states
    # states usable after restore
    u2(0, nd.array(np.random.randn(4).astype(np.float32)), w)


def test_lr_wd_mult():
    o = opt.SGD(learning_rate=1.0, param_idx2name={0: "w0", 1: "w1"})
    o.set_lr_mult({"w0": 0.1})
    assert abs(o._get_lr(0) - 0.1) < 1e-12
    assert abs(o._get_lr(1) - 1.0) < 1e-12
    o2 = opt.SGD(learning_rate=1.0, wd=0.1)
    o2.set_wd_mult({0: 0.0})
    assert o2._get_wd(0) == 0.0
