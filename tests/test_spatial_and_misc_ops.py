"""Spatial transform ops + misc op-tranche tests.

Reference: ``src/operator/bilinear_sampler.cc``†,
``grid_generator.cc``†, ``spatial_transformer.cc``†, ``crop.cc``†,
``correlation.cc``†, ``regression_output-inl.h``†, ``make_loss.cc``†,
``optimizer_op.cc``† multi_sgd family.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import nd, autograd
from mxtpu.base import MXNetError


# ----------------------------------------------------------------------
# spatial
# ----------------------------------------------------------------------
def test_grid_generator_identity_affine():
    theta = nd.array(np.array([[1, 0, 0, 0, 1, 0]], np.float32))
    grid = nd.GridGenerator(theta, transform_type="affine",
                            target_shape=(4, 6))
    g = grid.asnumpy()
    assert g.shape == (1, 2, 4, 6)
    np.testing.assert_allclose(g[0, 0, 0], np.linspace(-1, 1, 6),
                               atol=1e-6)
    np.testing.assert_allclose(g[0, 1, :, 0], np.linspace(-1, 1, 4),
                               atol=1e-6)


def test_bilinear_sampler_identity_and_shift():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    theta = nd.array(np.array([[1, 0, 0, 0, 1, 0]], np.float32))
    grid = nd.GridGenerator(theta, transform_type="affine",
                            target_shape=(5, 5))
    out = nd.BilinearSampler(nd.array(x), grid)
    np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-5, atol=1e-5)
    # x-shift by one pixel: out[..., j] = x[..., j+1], last col zero pad
    theta2 = nd.array(np.array([[1, 0, 2.0 / 4, 0, 1, 0]], np.float32))
    grid2 = nd.GridGenerator(theta2, transform_type="affine",
                             target_shape=(5, 5))
    out2 = nd.BilinearSampler(nd.array(x), grid2).asnumpy()
    np.testing.assert_allclose(out2[..., :4], x[..., 1:], rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(out2[..., 4], 0.0, atol=1e-6)


def test_bilinear_sampler_grads_flow():
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(1, 1, 4, 4).astype(np.float32))
    theta = nd.array(np.array([[1, 0, 0.1, 0, 1, -0.1]], np.float32))
    x.attach_grad()
    theta.attach_grad()
    with autograd.record():
        grid = nd.GridGenerator(theta, transform_type="affine",
                                target_shape=(4, 4))
        out = nd.BilinearSampler(x, grid)
        loss = nd.sum(out * out)
    loss.backward()
    assert np.isfinite(x.grad.asnumpy()).all()
    assert np.abs(theta.grad.asnumpy()).sum() > 0


def test_spatial_transformer_matches_composed():
    rng = np.random.RandomState(2)
    x = nd.array(rng.randn(2, 3, 6, 6).astype(np.float32))
    theta = nd.array(np.array([[0.8, 0.1, 0, -0.1, 0.9, 0.2]] * 2,
                              np.float32))
    st = nd.SpatialTransformer(x, theta, target_shape=(6, 6))
    grid = nd.GridGenerator(theta, transform_type="affine",
                            target_shape=(6, 6))
    ref = nd.BilinearSampler(x, grid)
    np.testing.assert_allclose(st.asnumpy(), ref.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_crop():
    x = nd.array(np.arange(2 * 1 * 6 * 6, dtype=np.float32)
                 .reshape(2, 1, 6, 6))
    out = nd.Crop(x, offset=(1, 2), h_w=(3, 3))
    np.testing.assert_array_equal(out.asnumpy(),
                                  x.asnumpy()[:, :, 1:4, 2:5])
    ref = nd.zeros((2, 1, 4, 4))
    out2 = nd.Crop(x, ref, center_crop=True, num_args=2)
    np.testing.assert_array_equal(out2.asnumpy(),
                                  x.asnumpy()[:, :, 1:5, 1:5])


def test_correlation_self_is_mean_square():
    rng = np.random.RandomState(3)
    x = rng.randn(1, 4, 5, 5).astype(np.float32)
    out = nd.Correlation(nd.array(x), nd.array(x),
                         max_displacement=1).asnumpy()
    assert out.shape == (1, 9, 5, 5)
    # center displacement (dy=dx=0) = mean over channels of x*x
    np.testing.assert_allclose(out[0, 4], (x[0] ** 2).mean(0),
                               rtol=1e-5, atol=1e-5)


# ----------------------------------------------------------------------
# legacy output ops — gradient semantics
# ----------------------------------------------------------------------
def test_linear_regression_output_grad():
    d = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    l = nd.array(np.array([[0.0, 1.0], [5.0, 2.0]], np.float32))
    d.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(d, l)
    out.backward()
    # reference scale: grad_scale / outputs-per-sample (here 2)
    np.testing.assert_allclose(d.grad.asnumpy(),
                               (d.asnumpy() - l.asnumpy()) / 2,
                               rtol=1e-6)
    np.testing.assert_array_equal(out.asnumpy(), d.asnumpy())
    # 1-D data: one output per sample → raw difference
    d1 = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    l1 = nd.zeros((3,))
    d1.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(d1, l1)
    out.backward()
    np.testing.assert_allclose(d1.grad.asnumpy(), [1.0, 2.0, 3.0],
                               rtol=1e-6)


def test_mae_and_logistic_regression_outputs():
    d = nd.array(np.array([[1.0, -2.0]], np.float32))
    l = nd.array(np.array([[0.0, 1.0]], np.float32))
    d.attach_grad()
    with autograd.record():
        out = nd.MAERegressionOutput(d, l)
    out.backward()
    # (1, 2) data → 2 outputs per sample → sign/2
    np.testing.assert_allclose(d.grad.asnumpy(),
                               np.sign(d.asnumpy() - l.asnumpy()) / 2,
                               rtol=1e-6)
    with autograd.record():
        out = nd.LogisticRegressionOutput(d, l)
    sig = 1 / (1 + np.exp(-d.asnumpy()))
    np.testing.assert_allclose(out.asnumpy(), sig, rtol=1e-5)
    d2 = nd.array(np.array([[1.0, -2.0]], np.float32))
    d2.attach_grad()
    with autograd.record():
        out = nd.LogisticRegressionOutput(d2, l)
    out.backward()
    np.testing.assert_allclose(d2.grad.asnumpy(),
                               (sig - l.asnumpy()) / 2, rtol=1e-5)


def test_make_loss_gradient_is_scale():
    d = nd.array(np.ones((2, 3), np.float32) * 5)
    d.attach_grad()
    with autograd.record():
        out = nd.MakeLoss(d, grad_scale=2.0)
    out.backward()
    np.testing.assert_allclose(d.grad.asnumpy(),
                               np.full((2, 3), 2.0), rtol=1e-6)
    d.grad[:] = 0
    with autograd.record():
        out = nd.MakeLoss(d, normalization="batch")
    out.backward()
    np.testing.assert_allclose(d.grad.asnumpy(),
                               np.full((2, 3), 0.5), rtol=1e-6)
    # valid normalization: divide by the count above valid_thresh
    dv = nd.array(np.array([1.0, 1.0, 0.0, 0.0], np.float32))
    dv.attach_grad()
    with autograd.record():
        out = nd.MakeLoss(dv, normalization="valid",
                          valid_thresh=0.5)
    out.backward()
    np.testing.assert_allclose(dv.grad.asnumpy(),
                               np.full(4, 0.5), rtol=1e-6)


# ----------------------------------------------------------------------
# norm/statistics/misc
# ----------------------------------------------------------------------
def test_group_norm():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 6, 4).astype(np.float32)
    g = np.ones(6, np.float32)
    b = np.zeros(6, np.float32)
    out = nd.GroupNorm(nd.array(x), nd.array(g), nd.array(b),
                       num_groups=2).asnumpy()
    grp = out.reshape(2, 2, -1)
    np.testing.assert_allclose(grp.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(grp.std(-1), 1.0, atol=1e-3)
    with pytest.raises(MXNetError):
        nd.GroupNorm(nd.array(x), nd.array(g), nd.array(b),
                     num_groups=4)


def test_moments_histogram_eye_linspace():
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    mean, var = nd.moments(nd.array(x), axes=(0, 1))
    assert abs(float(mean.asscalar()) - 2.5) < 1e-6
    assert abs(float(var.asscalar()) - 1.25) < 1e-6
    counts, edges = nd.histogram(nd.array(np.arange(10, dtype=np.float32)),
                                 bin_cnt=5, range=(0, 10))
    np.testing.assert_array_equal(counts.asnumpy(), [2, 2, 2, 2, 2])
    # the PYTHON creation API keeps its positional signature; the
    # registry op is internal (_eye/_linspace)
    np.testing.assert_array_equal(nd.eye(3).asnumpy(), np.eye(3))
    np.testing.assert_allclose(nd.linspace(0, 1, 5).asnumpy(),
                               np.linspace(0, 1, 5), rtol=1e-6)
    np.testing.assert_array_equal(
        nd._eye(N=3, dtype="float32").asnumpy(), np.eye(3))


def test_misc_elementwise():
    x = np.array([-2.0, 0.0, 2.0], np.float32)
    np.testing.assert_allclose(
        nd.hard_sigmoid(nd.array(x)).asnumpy(),
        np.clip(0.2 * x + 0.5, 0, 1), rtol=1e-6)
    np.testing.assert_allclose(
        nd.mish(nd.array(x)).asnumpy(),
        x * np.tanh(np.log1p(np.exp(x))), rtol=1e-5)
    a = nd.array(np.array([1.0, 0.0, 1.0], np.float32))
    b = nd.array(np.array([1.0, 1.0, 0.0], np.float32))
    np.testing.assert_array_equal(nd.logical_xor(a, b).asnumpy(),
                                  [0, 1, 1])
    np.testing.assert_allclose(
        nd.digamma(nd.array(np.array([1.0], np.float32))).asnumpy(),
        [-0.5772157], rtol=1e-4)


def test_batch_take_unravel_shuffle_split():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    idx = nd.array(np.array([1, 0, 1], np.float32))
    np.testing.assert_array_equal(nd.batch_take(a, idx).asnumpy(),
                                  [1, 2, 5])
    u = nd.unravel_index(nd.array(np.array([5, 2], np.float32)),
                         shape=(2, 3))
    np.testing.assert_array_equal(u.asnumpy(), [[1, 0], [2, 2]])
    r = nd.ravel_multi_index(nd.array(np.array([[1, 0], [2, 2]],
                                               np.float32)),
                             shape=(2, 3))
    np.testing.assert_array_equal(r.asnumpy(), [5, 2])
    mx.random.seed(7)
    s = nd.shuffle(nd.array(np.arange(8, dtype=np.float32)))
    assert sorted(s.asnumpy().tolist()) == list(range(8))
    parts = nd.split_v2(nd.array(np.arange(10, dtype=np.float32)),
                        indices=(3, 7))
    assert [p.shape[0] for p in parts] == [3, 4, 3]


def test_multi_sgd_updates():
    w1, g1 = np.ones(3, np.float32), np.full(3, 0.5, np.float32)
    w2, g2 = np.full(2, 2.0, np.float32), np.ones(2, np.float32)
    o1, o2 = nd.multi_sgd_update(
        nd.array(w1), nd.array(g1), nd.array(w2), nd.array(g2),
        lrs=(0.1, 0.2), wds=(0.0, 0.0), num_weights=2)
    np.testing.assert_allclose(o1.asnumpy(), w1 - 0.1 * g1, rtol=1e-6)
    np.testing.assert_allclose(o2.asnumpy(), w2 - 0.2 * g2, rtol=1e-6)
    m1 = np.zeros(3, np.float32)
    nw, nm = nd.multi_sgd_mom_update(
        nd.array(w1), nd.array(g1), nd.array(m1),
        lrs=(0.1,), wds=(0.0,), momentum=0.9, num_weights=1)
    np.testing.assert_allclose(nm.asnumpy(), -0.1 * g1, rtol=1e-6)
    np.testing.assert_allclose(nw.asnumpy(), w1 - 0.1 * g1, rtol=1e-6)
