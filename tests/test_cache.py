"""mxtpu.cache — persistent AOT executable cache (ISSUE 13).

Three layers of coverage:

* the cache core — key composition (flip ANY component and the entry
  misses; identical keys hit across processes), crash-safe concurrent
  writes, and the verify-or-quarantine loader against every scripted
  poisoning (corrupt byte, truncation, stale key, read-only root) —
  a wrong executable is NEVER returned;
* the serving integration — a fresh ``ModelRunner`` warms its full
  ladder from disk with zero XLA compiles, and the fleet's
  replacement path (``add_worker`` with no donor handoff) serves its
  first request with ``num_compiled`` == the warmed ladder in both
  the deterministic and the threaded router modes, recompiling (not
  executing!) poisoned entries;
* the training integration — a second ``TrainStep`` build loads from
  disk and steps bit-identically to the cold build.

Everything is deterministic: scripted cache faults keyed on the
cache's own store counter, hand-stepped clocks for the fleet, no
sleeps.
"""
import os
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from mxtpu import obs
from mxtpu import symbol as sym
from mxtpu.cache import (CacheKey, ExecutableCache, default_cache,
                         poison_corrupt, poison_stale, poison_truncate,
                         self_check)
from mxtpu.serving import (Autoscaler, CorruptEntry, FaultPlan,
                           FleetRouter, FleetWorker, ModelRunner,
                           ReadOnlyDir, StaleKey, TruncateEntry)


class FakeClock:
    """Hand-stepped monotonic clock (same pattern as test_fleet)."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _mul_runner(**kwargs):
    data = sym.var("data")
    w = sym.var("w")
    return ModelRunner(data * w, {"w": np.array([1.0, 2.0, 3.0],
                                                np.float32)},
                       {"data": (3,)}, max_batch_size=4, **kwargs)


def _router(clk, **kw):
    return FleetRouter(clock=clk, threaded=False, canary=None, **kw)


def _payload(v):
    return {"data": np.full(3, float(v), np.float32)}


def _crank(router, clk, n=8, dt=0.05):
    for _ in range(n):
        clk.advance(dt)
        router.tick(clk())


def _tiny_compiled():
    import jax
    import jax.numpy as jnp
    x = jnp.arange(8, dtype=jnp.float32)
    return jax.jit(lambda v: v * 2 + 1).lower(x).compile(), x  # mxlint: disable=hlo-raw-assert (building a Compiled to cache, not inspecting HLO)


# ----------------------------------------------------- key composition

def test_cache_key_digest_is_order_independent_and_flip_sensitive():
    a = CacheKey({"model": "m", "shape": "(4,)", "mesh": "1dev"})
    b = CacheKey({"mesh": "1dev", "shape": "(4,)", "model": "m"})
    assert a.digest == b.digest and a.filename() == b.filename()
    for comp, val in (("model", "m2"), ("shape", "(8,)"),
                      ("mesh", "2dev")):
        assert a.replace(**{comp: val}).digest != a.digest


def test_flipping_any_key_component_misses_on_disk(tmp_path):
    cache = ExecutableCache(tmp_path)
    compiled, x = _tiny_compiled()
    key = cache.key(model="fp0", shape="(8,)f32", mesh="1dev")
    assert cache.store(key, compiled)
    assert cache.load(key) is not None
    # contract hash, mesh shape, jax version, bucket shape, model —
    # each flip must miss (and must NOT quarantine the good entry)
    for comp, val in (("contract", "feedfeedfeedfeed"),
                      ("mesh", "4dev"), ("jax", "0.0.0"),
                      ("shape", "(16,)f32"), ("model", "fp1"),
                      ("salt", "rolled")):
        assert cache.load(key.replace(**{comp: val})) is None
    st = cache.stats()
    assert st["quarantined"] == 0 and st["miss"] == 6
    assert cache.load(key) is not None       # original still intact


def test_round_trip_executes_identically(tmp_path):
    cache = ExecutableCache(tmp_path)
    compiled, x = _tiny_compiled()
    want = np.asarray(compiled(x))
    key = cache.key(model="rt", shape="(8,)f32")
    exe, source = cache.load_or_compile(key, lambda: compiled)
    assert source == "cold" and cache.entries() == 1
    exe2, source2 = cache.load_or_compile(
        key, lambda: pytest.fail("hit path must not compile"))
    assert source2 == "disk"
    np.testing.assert_array_equal(np.asarray(exe2(x)), want)


def test_store_meta_round_trips(tmp_path):
    """The entry-header meta sidecar (writer audit stamp): stored at
    store(), returned by load(with_meta=True), NOT part of the key —
    and a miss hands back an empty dict, never None."""
    cache = ExecutableCache(tmp_path)
    compiled, x = _tiny_compiled()
    key = cache.key(model="meta", shape="(8,)f32")
    assert cache.store(key, compiled,
                       meta={"hlo_audit": 2, "prec_audit": 0})
    loaded, meta = cache.load(key, with_meta=True)
    assert loaded is not None
    assert meta == {"hlo_audit": 2, "prec_audit": 0}
    missed, meta2 = cache.load(key.replace(model="nope"),
                               with_meta=True)
    assert missed is None and meta2 == {}
    # meta is a sidecar, not a key component: rewriting the entry
    # under different meta still hits the same key
    assert cache.store(key, compiled, meta={"hlo_audit": 0})
    _, meta3 = cache.load(key, with_meta=True)
    assert meta3 == {"hlo_audit": 0}


def test_identical_keys_across_two_processes_hit(tmp_path):
    """A second process composes the same key (same model fp, shape,
    mesh, jax, backend, contracts) and its entry hits here — the
    rollout/restart story in one assertion."""
    child = f"""
import sys
sys.path.insert(0, {str(Path(__file__).resolve().parents[1])!r})
from mxtpu.cache import ExecutableCache
import jax, jax.numpy as jnp
cache = ExecutableCache({str(tmp_path)!r})
x = jnp.arange(8, dtype=jnp.float32)
compiled = jax.jit(lambda v: v * 2 + 1).lower(x).compile()
key = cache.key(model="xproc", shape="(8,)f32", mesh="1dev")
assert cache.store(key, compiled), "child store failed"
print(key.digest)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", child], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    child_digest = out.stdout.strip().splitlines()[-1]
    cache = ExecutableCache(tmp_path)
    key = cache.key(model="xproc", shape="(8,)f32", mesh="1dev")
    assert key.digest == child_digest        # same key composition
    loaded = cache.load(key)
    assert loaded is not None                # verified cross-process hit
    _, x = _tiny_compiled()
    np.testing.assert_array_equal(np.asarray(loaded(x)),
                                  np.arange(8, dtype=np.float32) * 2 + 1)


def test_concurrent_writers_race_cleanly(tmp_path):
    """N writers hammer the SAME key (separate cache instances — the
    multi-process shape, minus the fork) while a reader polls: the
    reader only ever sees nothing or a valid entry, never a torn one,
    and the survivor loads clean."""
    compiled, x = _tiny_compiled()
    want = np.asarray(compiled(x))
    caches = [ExecutableCache(tmp_path) for _ in range(4)]
    key = caches[0].key(model="race", shape="(8,)f32")
    start = threading.Barrier(5)
    failures = []

    def writer(c):
        start.wait()
        for _ in range(5):
            if not c.store(key, compiled):
                failures.append("store refused")

    def reader():
        rc = ExecutableCache(tmp_path)
        start.wait()
        for _ in range(20):
            got = rc.load(key)
            if got is not None:
                if not np.array_equal(np.asarray(got(x)), want):
                    failures.append("torn/wrong entry served")
        if rc.stats()["quarantined"]:
            failures.append("reader quarantined a mid-write entry")

    threads = [threading.Thread(target=writer, args=(c,), daemon=True)
               for c in caches] + [threading.Thread(target=reader,
                                                    daemon=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not failures, failures
    assert not any(t.is_alive() for t in threads)
    final = ExecutableCache(tmp_path).load(key)
    assert final is not None
    np.testing.assert_array_equal(np.asarray(final(x)), want)
    # temp files all consumed by the atomic renames
    assert not list(Path(tmp_path).glob("*.tmp"))


# ----------------------------------------------- scripted cache faults

@pytest.mark.parametrize("fault_cls,reason", [
    (CorruptEntry, "checksum"),
    (TruncateEntry, "truncated"),
    (StaleKey, "stale_key"),
])
def test_poisoned_entry_quarantines_never_executes(tmp_path, fault_cls,
                                                   reason):
    plan = FaultPlan(fault_cls(at_store=0))
    cache = ExecutableCache(tmp_path, faults=plan)
    compiled, x = _tiny_compiled()
    want = np.asarray(compiled(x))
    key = cache.key(model="poison", shape="(8,)f32")
    assert cache.store(key, compiled)        # fault poisons post-commit
    assert plan.fired == [f"{fault_cls.__name__.lower()}@0"]
    assert cache.load(key) is None           # never a wrong executable
    st = cache.stats()
    assert st["quarantined"] == 1 and st["hit"] == 0
    qfiles = list((Path(tmp_path) / "quarantine").iterdir())
    assert len(qfiles) == 1 and f".{reason}." in qfiles[0].name
    # the recovery: load_or_compile recompiles and the NEXT load hits
    exe, source = cache.load_or_compile(key, lambda: compiled)
    assert source == "cold"
    exe2, source2 = cache.load_or_compile(
        key, lambda: pytest.fail("recovered entry must hit"))
    assert source2 == "disk"
    np.testing.assert_array_equal(np.asarray(exe2(x)), want)


def test_read_only_dir_falls_back_without_error(tmp_path):
    plan = FaultPlan(ReadOnlyDir(from_store=0))
    cache = ExecutableCache(tmp_path, faults=plan)
    compiled, x = _tiny_compiled()
    key = cache.key(model="ro", shape="(8,)f32")
    exe, source = cache.load_or_compile(key, lambda: compiled)
    assert source == "cold" and exe is compiled   # plain compile, no raise
    assert plan.fired == ["readonlydir@0"]
    assert not cache.writable()              # latched off, no respam
    st = cache.stats()
    assert st["fallback"] == 1 and cache.entries() == 0
    if obs.enabled():
        kinds = [e["kind"] for e in cache.recorder.events()]
        assert "fallback" in kinds           # flight-recorder evidence
    # latched: the next store is refused silently (no second fire)
    assert not cache.store(key, compiled)
    assert plan.fired == ["readonlydir@0"]


def test_cache_self_check_passes(tmp_path):
    info = self_check(root=str(tmp_path / "sc"))
    assert info["serialize_supported"] and info["round_trip"]
    assert info["poisons"] == 3 and info["read_only_fallback"]


def test_default_cache_is_knob_driven(tmp_path, monkeypatch):
    monkeypatch.delenv("MXTPU_CACHE_DIR", raising=False)
    assert default_cache() is None           # no root, no persistence
    monkeypatch.setenv("MXTPU_CACHE_DIR", str(tmp_path))
    c1 = default_cache()
    assert c1 is not None and c1.root == Path(tmp_path)
    assert default_cache() is c1             # per-root singleton
    monkeypatch.setenv("MXTPU_CACHE_SALT", "v2")
    c2 = default_cache()
    assert c2 is not c1 and c2.salt == "v2"  # salt roll = new cache
    monkeypatch.setenv("MXTPU_CACHE", "0")
    assert default_cache() is None           # master kill switch


# ------------------------------------------------- serving integration

def test_runner_warms_full_ladder_from_disk_zero_compiles(tmp_path):
    cache = ExecutableCache(tmp_path)
    donor = _mul_runner(cache=cache)
    donor.warmup()
    nbuckets = donor.num_compiled()
    assert nbuckets == len(donor.buckets()) >= 2
    assert cache.stats()["store"] == nbuckets
    x = _payload(3)
    bucket = donor.bucket_for(1)
    want = np.asarray(donor.run_raw(donor._pad_stack([x], bucket),
                                    bucket)[0])

    fresh = ExecutableCache(tmp_path)        # "new process" instance
    runner = _mul_runner(cache=fresh)
    assert sorted(runner.cached_buckets()) == sorted(runner.buckets())
    runner.warm_from_disk()
    st = fresh.stats()
    assert st["hit"] == nbuckets and st["store"] == 0  # zero compiles
    assert runner.num_compiled() == nbuckets
    got = np.asarray(runner.run_raw(runner._pad_stack([x], bucket),
                                    bucket)[0])
    np.testing.assert_array_equal(got, want)
    assert runner.num_compiled() == nbuckets  # serving added nothing


def _fc_quant_runner(cache, quant=False):
    """One FullyConnected — the smallest graph the INT8 calibration
    pass accepts (test_quant.py owns the numerics; here it only has
    to key the cache)."""
    data = sym.var("data")
    out = sym.FullyConnected(data, sym.var("w"), sym.var("b"),
                             num_hidden=4)
    rng = np.random.RandomState(7)
    r = ModelRunner(out, {"w": rng.randn(4, 6).astype(np.float32),
                          "b": np.zeros(4, np.float32)},
                    {"data": (6,)}, max_batch_size=2, cache=cache,
                    quant=quant or None)
    if quant:
        r.calibrate([{"data": np.linspace(-1.0, 1.0, 12,
                                          dtype=np.float32)
                      .reshape(2, 6)}], mode="minmax")
    return r


def test_quantized_entries_isolated_from_float_twin(tmp_path):
    """INT8 serving (ISSUE 18) never cross-loads: the calibrated
    fingerprint plus the explicit `quant` key component keep a
    quantized runner's disk entries disjoint from its float twin's,
    while a second identically-calibrated quantized process warms
    fully from disk — and a recalibration on different data misses."""
    seed = ExecutableCache(tmp_path)
    fl = _fc_quant_runner(seed)
    fl.warmup()
    n = fl.num_compiled()
    assert n == len(fl.buckets()) >= 2
    assert seed.stats()["store"] == n

    q1 = _fc_quant_runner(ExecutableCache(tmp_path), quant=True)
    bucket = fl.buckets()[0]
    # key level: same model/bucket, the quant component alone splits
    assert q1._cache_key(bucket).digest != fl._cache_key(bucket).digest
    # the float ladder on disk is invisible to the quantized runner
    assert q1.cached_buckets() == []
    q1.warmup()
    st = q1._cache.stats()
    assert st["hit"] == 0 and st["store"] == n
    # ... and vice versa: a fresh float twin still sees only its own
    fresh = _fc_quant_runner(ExecutableCache(tmp_path))
    assert sorted(fresh.cached_buckets()) == sorted(fresh.buckets())

    # same calibration in a "new process" -> full disk warm
    q2 = _fc_quant_runner(ExecutableCache(tmp_path), quant=True)
    assert sorted(q2.cached_buckets()) == sorted(q2.buckets())
    q2.warm_from_disk()
    st2 = q2._cache.stats()
    assert st2["hit"] == n and st2["store"] == 0

    # different calibration data -> different thresholds baked into
    # the trace -> the fingerprint must miss every entry
    q3 = ModelRunner(fl._symbol, {"w": fl._param_vals[0],
                                  "b": fl._param_vals[1]},
                     {"data": (6,)}, max_batch_size=2,
                     cache=ExecutableCache(tmp_path), quant=True)
    q3.calibrate([{"data": 5.0 * np.linspace(-1.0, 1.0, 12,
                                             dtype=np.float32)
                   .reshape(2, 6)}], mode="minmax")
    assert q3.cached_buckets() == []


def test_quantized_poisoned_entry_quarantines_and_recompiles(tmp_path):
    """Quarantine-on-mismatch holds on the int8 tier too: a corrupted
    quantized entry is caught by the verify-or-quarantine loader and
    recompiled off the data path, never executed."""
    plan = FaultPlan(CorruptEntry(at_store=0))
    q1 = _fc_quant_runner(ExecutableCache(tmp_path, faults=plan),
                          quant=True)
    q1.warmup()
    n = q1.num_compiled()
    assert n >= 2 and plan.fired == ["corruptentry@0"]

    fresh = ExecutableCache(tmp_path)
    q2 = _fc_quant_runner(fresh, quant=True)
    # the existence probe still lists the poisoned bucket ...
    assert sorted(q2.cached_buckets()) == sorted(q2.buckets())
    q2.warm_from_disk()
    st = fresh.stats()
    # ... but the verified load quarantines it and recompiles
    assert st["quarantined"] == 1 and st["hit"] == n - 1
    assert st["store"] == 1
    assert q2.num_compiled() == n


def test_fleet_kill_then_disk_warmed_replacement(tmp_path):
    """The acceptance scenario: a worker dies (preemption), no donor
    handoff exists, yet the replacement serves its FIRST request with
    zero data-path compiles — its whole ladder came off disk via
    ``add_worker``'s donor-less warm path."""
    clk = FakeClock()
    seed = ExecutableCache(tmp_path)
    with _router(clk) as router:
        w0 = FleetWorker(_mul_runner(cache=seed), "w0", clock=clk,
                         max_queue_delay_us=0.0)
        router.add_worker(w0)
        w0.runner.warmup()                   # populates the disk cache
        nbuckets = w0.runner.num_compiled()
        router.kill("w0")                    # hard preemption, no drain

        fresh = ExecutableCache(tmp_path)
        w1 = FleetWorker(_mul_runner(cache=fresh), "w1", clock=clk,
                         max_queue_delay_us=0.0)
        # NO warm_from metadata — add_worker reports the disk path
        assert router.add_worker(w1) == "disk_cache"
        # the ladder is compiled BEFORE the first request, all off disk
        assert w1.runner.num_compiled() == nbuckets
        assert fresh.stats()["hit"] == nbuckets
        assert fresh.stats()["store"] == 0   # zero data-path compiles
        reqs = [router.submit(_payload(i), timeout_s=10.0)
                for i in range(6)]
        _crank(router, clk, n=4)
        for i, r in enumerate(reqs):
            np.testing.assert_allclose(r.result(timeout=0)[0],
                                       [i, 2.0 * i, 3.0 * i])
        assert w1.runner.num_compiled() == nbuckets  # still zero


def test_fleet_replacement_with_poisoned_cache_recompiles(tmp_path):
    """Kill → replace where every disk entry was corrupted in the
    meantime: the replacement quarantines each entry and recompiles —
    the poisoned executables are NEVER executed, results stay exact."""
    clk = FakeClock()
    seed = ExecutableCache(tmp_path)
    with _router(clk) as router:
        w0 = FleetWorker(_mul_runner(cache=seed), "w0", clock=clk,
                         max_queue_delay_us=0.0)
        router.add_worker(w0)
        w0.runner.warmup()
        nbuckets = w0.runner.num_compiled()
        router.kill("w0")
        for entry in Path(tmp_path).glob("*.mxc"):
            poison_corrupt(entry)            # bit-rot while it was down

        fresh = ExecutableCache(tmp_path)
        w1 = FleetWorker(_mul_runner(cache=fresh), "w1", clock=clk,
                         max_queue_delay_us=0.0)
        router.add_worker(w1)
        st = fresh.stats()
        assert st["quarantined"] == nbuckets  # every entry caught
        assert st["hit"] == 0                 # nothing poisoned served
        assert st["store"] == nbuckets        # recompiled + re-stored
        assert w1.runner.num_compiled() == nbuckets
        qdir = Path(tmp_path) / "quarantine"
        assert len(list(qdir.iterdir())) == nbuckets
        req = router.submit(_payload(5), timeout_s=10.0)
        _crank(router, clk, n=2)
        np.testing.assert_allclose(req.result(timeout=0)[0],
                                   [5.0, 10.0, 15.0])


def test_fleet_threaded_disk_warmed_replacement(tmp_path):
    """Same replacement story through the threaded router (real
    threads, real clock): outcome-asserted, not latency-asserted."""
    seed = ExecutableCache(tmp_path)
    donor = _mul_runner(cache=seed)
    donor.warmup()
    nbuckets = donor.num_compiled()
    router = FleetRouter(threaded=True, tick_s=0.002, canary=None)
    with router:
        fresh = ExecutableCache(tmp_path)
        w = FleetWorker(_mul_runner(cache=fresh), "w0",
                        max_queue_delay_us=500.0)
        router.add_worker(w)
        assert w.runner.num_compiled() == nbuckets
        assert fresh.stats() == {"hit": nbuckets, "miss": 0,
                                 "store": 0, "fallback": 0,
                                 "quarantined": 0}
        reqs = [router.submit(_payload(i % 5), timeout_s=10.0)
                for i in range(8)]
        for i, r in enumerate(reqs):
            v = i % 5
            np.testing.assert_allclose(r.result(timeout=10.0)[0],
                                       [v, 2.0 * v, 3.0 * v])
        assert w.runner.num_compiled() == nbuckets


def test_disk_hit_reaudits_when_writer_audited_less(tmp_path,
                                                    monkeypatch):
    """Regression (review): ``MXTPU_HLO_AUDIT`` is a per-process
    knob.  Entries written by a process with auditing OFF carry that
    fact in their header stamp, and a process with auditing ON that
    warms from disk re-audits each reloaded program; a reader whose
    modes are no stricter than the writer's trusts the cold-birth
    audit and skips the pass."""
    from mxtpu import analysis

    calls = []
    real = analysis.maybe_audit

    def spy(compiled, label="", mem=None):
        calls.append(label)
        return real(compiled, label=label, mem=mem)

    monkeypatch.setattr(analysis, "maybe_audit", spy)

    monkeypatch.delenv("MXTPU_HLO_AUDIT", raising=False)
    monkeypatch.delenv("MXTPU_PREC_AUDIT", raising=False)
    writer = _mul_runner(cache=ExecutableCache(tmp_path))
    writer.warmup()                          # stamped hlo_audit=0
    calls.clear()

    monkeypatch.setenv("MXTPU_HLO_AUDIT", "1")
    reader = _mul_runner(cache=ExecutableCache(tmp_path))
    warmed = reader.warm_from_disk()
    assert len(warmed) == len(reader.buckets())
    # every disk hit was re-audited (writer never audited them)
    assert len(calls) == len(reader.buckets())

    # a writer that audits at the reader's level satisfies the stamp:
    # its entries are trusted, no re-audit fires on the hit
    for f in Path(tmp_path).glob("*.mxc"):
        f.unlink()
    w2 = _mul_runner(cache=ExecutableCache(tmp_path))
    w2.warmup()                              # stamped hlo_audit=1
    calls.clear()
    r2 = _mul_runner(cache=ExecutableCache(tmp_path))
    assert len(r2.warm_from_disk()) == len(r2.buckets())
    assert calls == []                       # cold-birth audit trusted


def test_autoscaler_scale_up_warms_from_disk_cache(tmp_path):
    """No live donor, no cached handoff — the scale-up replica warms
    from the persistent cache and the ``scale_up`` flight event says
    so (``donor="disk_cache"``)."""
    obs.reset()
    clk = FakeClock()
    seed = ExecutableCache(tmp_path)
    r = _router(clk)
    w0 = FleetWorker(_mul_runner(cache=seed), "w0", clock=clk,
                     max_queue_delay_us=0.0)
    r.add_worker(w0)
    w0.runner.warmup()                       # disk holds the ladder
    nbuckets = w0.runner.num_compiled()
    made = []

    def make_worker(name):
        w = FleetWorker(_mul_runner(cache=ExecutableCache(tmp_path)),
                        name, clock=clk, max_queue_delay_us=0.0)
        made.append(w)
        return w

    scaler = Autoscaler(r, make_worker, min_workers=1, max_workers=2,
                        up_depth=3.0, down_depth=0.5, breach_ticks=2,
                        cooldown_s=0.1)
    r.add_controller(scaler.tick)
    r.kill("w0")                             # preempted; NO handoff
    reqs = [r.submit(_payload(i), timeout_s=30.0) for i in range(6)]
    for _ in range(20):
        clk.advance(0.05)
        r.tick(clk())
        if made:
            break
    assert made, "floor repair never fired"
    assert made[0].runner.num_compiled() == nbuckets  # warm, off disk
    ups = [e for e in scaler.recorder.events()
           if e["kind"] == "scale_up"]
    assert ups and ups[0]["donor"] == "disk_cache"
    _crank(r, clk, n=6)
    for i, req in enumerate(reqs):
        np.testing.assert_allclose(req.result(timeout=0)[0],
                                   [i, 2.0 * i, 3.0 * i])
    assert made[0].runner.num_compiled() == nbuckets
    r.close()


# ------------------------------------------------ training integration

def test_train_step_same_signature_different_program_misses(tmp_path):
    """Regression (review): two nets with the same container class
    and IDENTICAL param shapes/dtypes but different computations
    (relu vs tanh activations) must never share a TrainStep cache
    entry — the key fingerprints the lowered program itself, so the
    second build is a clean miss (own store), never a silent
    wrong-gradient hit; rebuilding the same program still hits."""
    import mxtpu as mx
    from mxtpu import nd, parallel
    from mxtpu.gluon import loss as gloss, nn

    cache = ExecutableCache(tmp_path)

    def build(act):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation=act), nn.Dense(2))
        net.initialize(init="xavier")
        return parallel.build_train_step(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.2}, cache=cache)

    rng = np.random.RandomState(11)
    X = nd.array(rng.randn(8, 2).astype("float32"))
    y = nd.array((rng.rand(8) > 0.5).astype("int64"))
    build("relu")(X, y)
    assert cache.stats() == {"hit": 0, "miss": 1, "store": 1,
                             "fallback": 0, "quarantined": 0}
    build("tanh")(X, y)                      # same shapes, same classes
    st = cache.stats()
    assert st["store"] == 2 and st["hit"] == 0   # program differs: miss
    build("tanh")(X, y)                      # identical program: hit
    st = cache.stats()
    assert st["hit"] == 1 and st["store"] == 2


def test_train_step_second_build_hits_disk_bit_identical(tmp_path):
    import mxtpu as mx
    from mxtpu import nd, parallel
    from mxtpu.gluon import loss as gloss, nn

    cache = ExecutableCache(tmp_path)

    def build():
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
        net.initialize(init="xavier")
        return parallel.build_train_step(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.2, "momentum": 0.9}, cache=cache)

    rng = np.random.RandomState(7)
    X = rng.randn(32, 2).astype("float32")
    y = (rng.rand(32) > 0.5).astype("int64")
    losses_cold = build().run_steps(nd.array(X), nd.array(y),
                                    steps=4).asnumpy()
    assert cache.stats()["store"] == 1
    losses_warm = build().run_steps(nd.array(X), nd.array(y),
                                    steps=4).asnumpy()
    st = cache.stats()
    assert st["hit"] == 1 and st["store"] == 1  # second build off disk
    np.testing.assert_array_equal(losses_cold, losses_warm)
