"""C predict ABI: ctypes driver + a real compiled C program, both
running an exported model through libmxtpu_predict.so.

Reference: ``include/mxnet/c_predict_api.h``† /
``src/c_api/c_predict_api.cc``† and the predict-cpp example†.
"""
import ctypes
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.gluon import nn

_CORE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "core")
_LIB = os.path.join(_CORE, "libmxtpu_predict.so")


def _build_lib():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("g++/make not available")
    # link against THIS interpreter, not whatever python3 is on PATH
    r = subprocess.run(["make", "predict", f"PYTHON={sys.executable}"],
                       cwd=_CORE, capture_output=True, text=True)
    # toolchain present → a failing build is a real regression, not a
    # skip condition
    assert r.returncode == 0, \
        f"libmxtpu_predict build failed: {r.stderr[-1000:]}"


@pytest.fixture(scope="module")
def model(tmp_path_factory):
    d = tmp_path_factory.mktemp("cpredict")
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init="xavier")
    x = nd.array(np.random.RandomState(0).randn(2, 8)
                 .astype(np.float32))
    y0 = net(x).asnumpy()
    sym_file, param_file = net.export(str(d / "model"))
    return sym_file, param_file, np.asarray(x.asnumpy()), y0


def _load():
    if not os.path.exists(_LIB):
        _build_lib()
    lib = ctypes.CDLL(_LIB)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def test_ctypes_full_flow(model):
    sym_file, param_file, x, y0 = model
    lib = _load()
    with open(sym_file) as f:
        sym_json = f.read().encode()
    with open(param_file, "rb") as f:
        params = f.read()

    handle = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint * 2)(0, 2)
    shape = (ctypes.c_uint * 2)(*x.shape)
    rc = lib.MXPredCreate(sym_json, params, len(params), 1, 0, 1,
                          keys, indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXGetLastError().decode()

    data = x.astype(np.float32).ravel()
    rc = lib.MXPredSetInput(
        handle, b"data",
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        data.size)
    assert rc == 0, lib.MXGetLastError().decode()
    assert lib.MXPredForward(handle) == 0, \
        lib.MXGetLastError().decode()

    sdata = ctypes.POINTER(ctypes.c_uint)()
    ndim = ctypes.c_uint()
    rc = lib.MXPredGetOutputShape(handle, 0, ctypes.byref(sdata),
                                  ctypes.byref(ndim))
    assert rc == 0, lib.MXGetLastError().decode()
    oshape = tuple(sdata[i] for i in range(ndim.value))
    assert oshape == y0.shape

    out = np.zeros(int(np.prod(oshape)), np.float32)
    rc = lib.MXPredGetOutput(
        handle, 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size)
    assert rc == 0, lib.MXGetLastError().decode()
    np.testing.assert_allclose(out.reshape(oshape), y0, rtol=1e-5,
                               atol=1e-5)
    assert lib.MXPredFree(handle) == 0

    # error paths surface through MXGetLastError
    h2 = ctypes.c_void_p()
    rc = lib.MXPredCreate(b"not json", params, len(params), 1, 0, 1,
                          keys, indptr, shape, ctypes.byref(h2))
    assert rc == -1
    assert lib.MXGetLastError()


def test_predictor_semantics(model):
    """ABI-level contracts, tested at the Python half: output shapes
    available BEFORE forward (reference create→shape→alloc pattern),
    and only declared inputs are writable."""
    from mxtpu.base import MXNetError
    from mxtpu.c_predict import Predictor
    sym_file, param_file, x, y0 = model
    with open(sym_file) as f:
        sym_json = f.read()
    with open(param_file, "rb") as f:
        params = f.read()
    p = Predictor(sym_json, params, 1, 0, {"data": x.shape})
    assert p.get_output_shape(0) == y0.shape  # pre-forward
    assert p.num_outputs() == 1
    with pytest.raises(MXNetError, match="not a declared input"):
        p.set_input("dense36_weight",
                    np.zeros(4, np.float32).tobytes())
    with pytest.raises(MXNetError, match="forward"):
        p.get_output(0)
    p.set_input("data", x.astype(np.float32).tobytes())
    p.forward()
    got = np.frombuffer(p.get_output(0), np.float32) \
        .reshape(p.get_output_shape(0))
    np.testing.assert_allclose(got, y0, rtol=1e-5, atol=1e-5)


def test_reshape_shares_device_buffers(model):
    """MXPredReshape zero-copy contract: a reshape clone binds the SAME
    weight NDArrays (same underlying device buffers — no second
    upload), and its outputs match a fresh bind at the new shape."""
    from mxtpu.c_predict import Predictor
    sym_file, param_file, x, y0 = model
    with open(sym_file) as f:
        sym_json = f.read()
    with open(param_file, "rb") as f:
        params = f.read()
    p = Predictor(sym_json, params, 1, 0, {"data": x.shape})
    clone = p.reshape({"data": (5, x.shape[1])})

    weight_names = [k for k in p._executor.arg_dict
                    if k not in p._input_names]
    assert weight_names
    for k in weight_names:
        a, b = p._executor.arg_dict[k], clone._executor.arg_dict[k]
        assert a is b                 # same NDArray object...
        assert a.data is b.data       # ...wrapping the same jax buffer

    x5 = np.random.RandomState(1).randn(5, x.shape[1]) \
        .astype(np.float32)
    clone.set_input("data", x5.tobytes())
    clone.forward()
    got = np.frombuffer(clone.get_output(0), np.float32) \
        .reshape(clone.get_output_shape(0))
    fresh = Predictor(sym_json, params, 1, 0, {"data": x5.shape})
    fresh.set_input("data", x5.tobytes())
    fresh.forward()
    want = np.frombuffer(fresh.get_output(0), np.float32) \
        .reshape(fresh.get_output_shape(0))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    # the original's binding is untouched by the clone
    p.set_input("data", x.astype(np.float32).tobytes())
    p.forward()
    np.testing.assert_allclose(
        np.frombuffer(p.get_output(0), np.float32).reshape(y0.shape),
        y0, rtol=1e-5, atol=1e-5)


def test_int32_inputs_cross_wire_exactly(tmp_path):
    """Integer bindings are honoured on the wire: an int32 token-id
    input reads its bytes as int32 (ids above 2^24 must survive —
    float32 wire silently corrupted them), and integer outputs declare
    their dtype via get_output_dtype."""
    from mxtpu import symbol as sym
    from mxtpu.c_predict import Predictor
    big = 2 ** 24 + 3   # not representable in float32
    data = sym.var("data", dtype="int32")
    graph = data + data   # stays int32; 2*big still needs > 24 bits
    ids = np.array([[1, 7, big]], np.int32)
    pfile = str(tmp_path / "int32.params")
    nd.save(pfile, {"arg:unused": nd.zeros((1,))})
    with open(pfile, "rb") as f:
        blob = f.read()

    p = Predictor(graph.tojson(), blob, 1, 0, {"data": ids.shape})
    # bound dtype resolved from the var's __dtype__ attr
    assert p._executor.arg_dict["data"].dtype == np.int32
    p.set_input("data", ids.tobytes())   # int32 bytes, verbatim
    p.forward()
    assert p.get_output_dtype(0) == "int32"
    got = np.frombuffer(p.get_output(0), np.int32) \
        .reshape(p.get_output_shape(0))
    np.testing.assert_array_equal(got, ids * 2)   # exact, no 2^24 loss
    # explicit input_dtypes wins too, and survives reshape clones
    p2 = Predictor(graph.tojson(), blob, 1, 0, {"data": ids.shape},
                   input_dtypes={"data": "int32"})
    clone = p2.reshape({"data": (1, 2)})
    assert clone._executor.arg_dict["data"].dtype == np.int32
    clone.set_input("data", ids[:, :2].tobytes())
    clone.forward()
    np.testing.assert_array_equal(
        np.frombuffer(clone.get_output(0), np.int32),
        ids.ravel()[:2] * 2)


def test_float_outputs_keep_float32_wire(model):
    """ABI back-compat: floating bindings still cross as float32."""
    from mxtpu.c_predict import Predictor
    sym_file, param_file, x, y0 = model
    with open(sym_file) as f:
        sym_json = f.read()
    with open(param_file, "rb") as f:
        params = f.read()
    p = Predictor(sym_json, params, 1, 0, {"data": x.shape})
    p.set_input("data", x.astype(np.float32).tobytes())
    p.forward()
    assert p.get_output_dtype(0) == "float32"
    assert len(p.get_output(0)) == int(np.prod(y0.shape)) * 4


def test_compiled_c_program(model, tmp_path):
    """Compile predict_example.c with gcc/g++ and run it as a true
    external C consumer (embedded interpreter boot path)."""
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    sym_file, param_file, x, y0 = model
    if not os.path.exists(_LIB):
        _build_lib()
    exe = str(tmp_path / "predict")
    r = subprocess.run(
        ["g++", os.path.join(_CORE, "predict_example.c"),
         f"-L{_CORE}", "-lmxtpu_predict", f"-Wl,-rpath,{_CORE}",
         f"-I{_CORE}", "-o", exe],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    inp = str(tmp_path / "input.f32")
    x.astype(np.float32).tofile(inp)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(_CORE) + os.pathsep + \
        env.get("PYTHONPATH", "")
    r = subprocess.run(
        [exe, sym_file, param_file, f"{x.shape[0]},{x.shape[1]}", inp],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr + r.stdout
    assert f"output shape: {y0.shape[0]} {y0.shape[1]}" in r.stdout
    got = [float(v) for v in
           r.stdout.split("output:")[1].split()]
    # the embedded interpreter may land on a different backend than
    # this process (the axon sitecustomize pins TPU regardless of
    # JAX_PLATFORMS) — compare at cross-backend tolerance
    np.testing.assert_allclose(got, y0.ravel()[:len(got)], rtol=2e-2,
                               atol=5e-3)
