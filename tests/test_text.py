"""contrib.text: vocabulary, token indexing, pretrained-embedding
composition (VERDICT r3 item 6; reference
``python/mxnet/contrib/text/``†).  Embedding files are offline
fixtures in the published GloVe/fastText text formats.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.base import MXNetError
from mxtpu.contrib import text


CORPUS = "the quick brown fox jumps over the lazy dog\n" \
         "the dog barks at the fox\n"


def test_count_tokens_from_str():
    c = text.count_tokens_from_str(CORPUS)
    assert c["the"] == 4 and c["fox"] == 2 and c["dog"] == 2
    c2 = text.count_tokens_from_str("A a B", to_lower=True)
    assert c2["a"] == 2 and c2["b"] == 1
    base = text.count_tokens_from_str("x y")
    text.count_tokens_from_str("y z", counter_to_update=base)
    assert base["y"] == 2 and base["x"] == 1 and base["z"] == 1


def test_vocabulary_ordering_and_indexing():
    counter = text.count_tokens_from_str(CORPUS)
    v = text.Vocabulary(counter, most_freq_count=None, min_freq=1,
                        unknown_token="<unk>",
                        reserved_tokens=["<pad>"])
    # index 0 unknown, 1 reserved, then freq-desc alpha-tie order
    assert v.idx_to_token[0] == "<unk>"
    assert v.idx_to_token[1] == "<pad>"
    assert v.idx_to_token[2] == "the"          # freq 4
    assert set(v.idx_to_token[3:5]) == {"dog", "fox"}  # freq 2, alpha
    assert v.idx_to_token[3] == "dog"
    assert v.to_indices("the") == 2
    assert v.to_indices(["the", "never-seen"]) == [2, 0]
    assert v.to_tokens([2, 0]) == ["the", "<unk>"]
    with pytest.raises(MXNetError):
        v.to_tokens(len(v))
    # pruning
    v2 = text.Vocabulary(counter, most_freq_count=2)
    assert len(v2) == 3  # unk + 2 kept
    v3 = text.Vocabulary(counter, min_freq=2)
    assert set(v3.idx_to_token[1:]) == {"the", "dog", "fox"}


def _write_glove(path, tokens, dim=4, seed=0):
    rng = np.random.RandomState(seed)
    vecs = {}
    with open(path, "w") as f:
        for t in tokens:
            v = rng.randn(dim).astype(np.float32)
            vecs[t] = v
            f.write(t + " " + " ".join(f"{x:.6f}" for x in v) + "\n")
    return vecs


def test_custom_embedding_loads_glove_format(tmp_path):
    p = tmp_path / "tiny.txt"
    vecs = _write_glove(str(p), ["the", "fox", "dog"])
    emb = text.embedding.CustomEmbedding(str(p))
    assert emb.vec_len == 4 and len(emb) == 4  # + <unk>
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("fox").asnumpy(), vecs["fox"],
        rtol=1e-5)
    # unknown -> zeros (init_unknown_vec default)
    assert np.all(emb.get_vecs_by_tokens("absent").asnumpy() == 0)
    got = emb.get_vecs_by_tokens(["the", "absent", "dog"]).asnumpy()
    assert got.shape == (3, 4)
    np.testing.assert_allclose(got[2], vecs["dog"], rtol=1e-5)
    # update_token_vectors
    emb.update_token_vectors("the", nd.array(np.ones(4, np.float32)))
    assert np.all(emb.get_vecs_by_tokens("the").asnumpy() == 1)
    with pytest.raises(MXNetError):
        emb.update_token_vectors("absent", nd.zeros((4,)))


def test_glove_fasttext_roots(tmp_path):
    root = tmp_path / "emb"
    (root / "glove").mkdir(parents=True)
    (root / "fasttext").mkdir()
    _write_glove(str(root / "glove" / "glove.6B.50d.txt"),
                 ["alpha", "beta"], dim=3)
    # fastText format: header line then rows
    with open(root / "fasttext" / "wiki.simple.vec", "w") as f:
        f.write("2 3\n")
        f.write("alpha 1 2 3\n")
        f.write("gamma 4 5 6\n")
    g = text.embedding.GloVe(embedding_root=str(root))
    assert g.vec_len == 3 and "beta" in g.token_to_idx
    ft = text.embedding.FastText(embedding_root=str(root))
    assert ft.vec_len == 3
    np.testing.assert_allclose(
        ft.get_vecs_by_tokens("gamma").asnumpy(), [4, 5, 6])
    with pytest.raises(MXNetError):
        text.embedding.CustomEmbedding(str(root / "missing.txt"))
    assert "glove.6B.300d.txt" in \
        text.embedding.get_pretrained_file_names("glove")


def test_composite_embedding_with_nn_embedding(tmp_path):
    """The VERDICT r3 'done' bar: vocab from a corpus + fixture
    embedding composed into gluon nn.Embedding."""
    p1, p2 = tmp_path / "a.txt", tmp_path / "b.txt"
    v1 = _write_glove(str(p1), ["the", "fox", "dog"], dim=4, seed=1)
    v2 = _write_glove(str(p2), ["the", "lazy"], dim=2, seed=2)
    vocab = text.Vocabulary(text.count_tokens_from_str(CORPUS))
    comp = text.CompositeEmbedding(
        vocab, [text.embedding.CustomEmbedding(str(p1)),
                text.embedding.CustomEmbedding(str(p2))])
    assert comp.vec_len == 6 and len(comp) == len(vocab)
    i_fox = vocab.to_indices("fox")
    np.testing.assert_allclose(
        comp.idx_to_vec.asnumpy()[i_fox, :4], v1["fox"], rtol=1e-5)
    np.testing.assert_allclose(
        comp.idx_to_vec.asnumpy()[i_fox, 4:], 0.0)  # absent in b.txt

    from mxtpu.gluon import nn
    layer = nn.Embedding(len(vocab), comp.vec_len)
    layer.initialize()
    layer(nd.array(np.asarray([0], np.float32)))  # deferred init
    layer.weight.set_data(comp.idx_to_vec)
    idx = nd.array(np.asarray(
        vocab.to_indices(["the", "fox", "nope"]), np.float32))
    out = layer(idx).asnumpy()
    np.testing.assert_allclose(out[1], comp.idx_to_vec.asnumpy()[i_fox],
                               rtol=1e-5)
    assert np.all(out[2] == 0)  # unknown row
