"""Small real trainings asserting accuracy thresholds — the reference's
``tests/python/train/``† tier (test_mlp†, test_dtype† fp16 ≙ bf16
here), SURVEY §4.3.
"""
import numpy as np

import mxtpu as mx
from mxtpu import nd
from mxtpu.io import NDArrayIter


def _two_moons(n=1024, seed=0):
    """Separable-but-nonlinear 2-class data (no sklearn here)."""
    rng = np.random.RandomState(seed)
    t = rng.rand(n // 2) * np.pi
    x0 = np.stack([np.cos(t), np.sin(t)], 1)
    x1 = np.stack([1 - np.cos(t), 0.5 - np.sin(t)], 1)
    X = np.concatenate([x0, x1]).astype(np.float32)
    X += rng.randn(*X.shape).astype(np.float32) * 0.08
    y = np.concatenate([np.zeros(n // 2), np.ones(n // 2)]) \
        .astype(np.float32)
    order = rng.permutation(n)
    return X[order], y[order]


def test_mlp_convergence():
    """Module.fit on an MLP reaches >0.95 train accuracy (reference
    tests/python/train/test_mlp.py† shape)."""
    mx.random.seed(0)
    X, y = _two_moons()
    it = NDArrayIter(X, y, batch_size=64, shuffle=True)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc3")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(it, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5,
                              "rescale_grad": 1.0 / 64,
                              "momentum": 0.9},
            initializer=mx.init.Xavier())
    it.reset()
    score = dict(mod.score(it, mx.metric.Accuracy()))
    assert score["accuracy"] > 0.95, score


def test_gluon_lenet_thumbnail_convergence():
    """Gluon + compiled TrainStep on MNIST-shaped synthetic digits
    (the reference's conv convergence tier)."""
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import lenet

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    n, classes = 512, 4
    X = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.1
    y = rng.randint(0, classes, n).astype(np.float32)
    for i in range(n):  # class-coded bright patch position
        c = int(y[i])
        X[i, 0, 4 + 5 * c:9 + 5 * c, 6:22] = 1.0
    net = lenet(classes=classes)
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})
    for ep in range(6):
        order = rng.permutation(n)
        for i in range(0, n, 64):
            idx = order[i:i + 64]
            step(nd.array(X[idx]), nd.array(y[idx]))
    pred = net(nd.array(X)).asnumpy().argmax(1)
    acc = float((pred == y).mean())
    assert acc > 0.95, acc


def test_bf16_training_converges():
    """Mixed-precision training (reference test_dtype† fp16 tier →
    bf16 on TPU): compute in bf16 over f32 master weights and still
    converge."""
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss, nn

    mx.random.seed(0)
    X, y = _two_moons(seed=3)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.2, "momentum": 0.9},
        compute_dtype="bfloat16")
    losses = step.run_steps(nd.array(X[:960]), nd.array(y[:960]),
                            steps=15).asnumpy()
    for _ in range(7):
        losses = step.run_steps(nd.array(X[:960]), nd.array(y[:960]),
                                steps=15).asnumpy()
    pred = net(nd.array(X)).asnumpy().argmax(1)
    acc = float((pred == y).mean())
    # pure-f32 training of this exact config lands at 0.902 — the
    # bar checks bf16 matches f32 convergence, not the data ceiling
    assert acc > 0.88, (acc, losses[-3:])
