"""NDArray core tests — modeled on the reference's
tests/python/unittest/test_ndarray.py†."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd

import jax as _jax

# backend-aware tolerance: MXU bf16-pass matmuls / TPU transcendentals
# don't match exact-f32 numpy refs to 1e-5 (SURVEY §7 hard-part 9);
# matmul bound comes from the shared test_utils tables
from mxtpu.test_utils import get_tolerance as _get_tol
_RTOL = _get_tol(__import__("numpy").float32)[0]
_RTOL6 = 1e-4 if _jax.default_backend() != "cpu" else 1e-6


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32 or str(a.dtype) == "int32"
    z = nd.zeros((3, 4))
    assert z.shape == (3, 4)
    assert np.all(z.asnumpy() == 0)
    o = nd.ones((2,), dtype="float32")
    assert np.all(o.asnumpy() == 1)
    f = nd.full((2, 2), 7.0)
    assert np.all(f.asnumpy() == 7)
    r = nd.arange(0, 10, 2)
    assert np.array_equal(r.asnumpy(), np.arange(0, 10, 2, dtype=np.float32))


def test_arith():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    np.testing.assert_allclose((a * b).asnumpy(), [[10, 40], [90, 160]])
    np.testing.assert_allclose((b / a).asnumpy(), [[10, 10], [10, 10]])
    np.testing.assert_allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((1 + a).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((2 ** a).asnumpy(), [[2, 4], [8, 16]])
    np.testing.assert_allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])
    np.testing.assert_allclose(abs(-a).asnumpy(), a.asnumpy())


def test_inplace_arith():
    a = nd.ones((2, 2))
    a += 1
    np.testing.assert_allclose(a.asnumpy(), 2 * np.ones((2, 2)))
    a *= 3
    np.testing.assert_allclose(a.asnumpy(), 6 * np.ones((2, 2)))


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([3.0, 2.0, 1.0])
    np.testing.assert_allclose((a == b).asnumpy(), [0, 1, 0])
    np.testing.assert_allclose((a < b).asnumpy(), [1, 0, 0])
    np.testing.assert_allclose((a >= b).asnumpy(), [0, 1, 1])


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a[0].shape == (3, 4)
    assert a[0, 1].shape == (4,)
    assert a[0, 1, 2].asscalar() == 6
    assert a[:, 1].shape == (2, 4)
    assert a[0, 1:3].shape == (2, 4)
    idx = nd.array(np.array([0, 1]), dtype="int32")
    took = a[idx]
    assert took.shape == (2, 3, 4)


def test_setitem():
    a = nd.zeros((3, 3))
    a[1, 1] = 5.0
    assert a.asnumpy()[1, 1] == 5.0
    a[0] = 2.0
    assert np.all(a.asnumpy()[0] == 2.0)
    a[:] = 1.0
    assert np.all(a.asnumpy() == 1.0)


def test_reshape_transpose():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert a.reshape(4, 3).shape == (4, 3)
    assert a.reshape((2, 6)).shape == (2, 6)
    assert a.reshape(-1).shape == (12,)
    assert a.reshape(0, 2, 2).shape == (3, 2, 2)  # 0 = keep dim
    assert a.T.shape == (4, 3)
    assert a.transpose(1, 0).shape == (4, 3)
    assert nd.expand_dims(a, axis=1).shape == (3, 1, 4)
    assert nd.squeeze(nd.expand_dims(a, axis=0)).shape == (3, 4)
    assert a.flatten().shape == (3, 4)
    assert nd.ones((2, 3, 4)).flatten().shape == (2, 12)


def test_reductions():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert a.sum().asscalar() == 66.0
    np.testing.assert_allclose(a.sum(axis=0).asnumpy(), [12, 15, 18, 21])
    np.testing.assert_allclose(a.mean(axis=1, keepdims=True).shape, (3, 1))
    assert a.max().asscalar() == 11.0
    assert a.min().asscalar() == 0.0
    assert nd.sum(a, axis=1, exclude=True).shape == (4,)
    assert a.argmax().asscalar() == 11
    np.testing.assert_allclose(a.argmax(axis=1).asnumpy(), [3, 3, 3])


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    c = nd.dot(a, b)
    np.testing.assert_allclose(c.asnumpy(),
                               a.asnumpy() @ b.asnumpy(), rtol=_RTOL)
    d = nd.dot(a, b, transpose_a=False, transpose_b=False)
    assert d.shape == (3, 5)
    bt = nd.array(np.random.rand(5, 4).astype(np.float32))
    np.testing.assert_allclose(
        nd.dot(a, bt, transpose_b=True).asnumpy(),
        a.asnumpy() @ bt.asnumpy().T, rtol=_RTOL)


def test_concat_stack_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert parts[0].shape == (2, 3)
    np.testing.assert_allclose(parts[0].asnumpy(), a.asnumpy())


def test_broadcast_ops():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert nd.broadcast_add(a, b).shape == (2, 4, 3)
    assert nd.broadcast_to(nd.ones((1, 3)), shape=(5, 3)).shape == (5, 3)
    assert nd.broadcast_maximum(a, b).shape == (2, 4, 3)


def test_unary_math():
    x = nd.array([0.5, 1.0, 2.0])
    np.testing.assert_allclose(nd.exp(x).asnumpy(),
                               np.exp(x.asnumpy()), rtol=_RTOL6)
    np.testing.assert_allclose(nd.log(x).asnumpy(),
                               np.log(x.asnumpy()), rtol=_RTOL6)
    np.testing.assert_allclose(nd.sqrt(x).asnumpy(),
                               np.sqrt(x.asnumpy()), rtol=_RTOL6)
    np.testing.assert_allclose(nd.sigmoid(x).asnumpy(),
                               1 / (1 + np.exp(-x.asnumpy())), rtol=_RTOL6)
    np.testing.assert_allclose(nd.relu(nd.array([-1.0, 1.0])).asnumpy(),
                               [0, 1])


def test_take_embedding_onehot():
    w = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array([0, 2], dtype="int32")
    t = nd.take(w, idx)
    np.testing.assert_allclose(t.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    e = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    np.testing.assert_allclose(e.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    oh = nd.one_hot(idx, depth=4)
    np.testing.assert_allclose(oh.asnumpy(),
                               [[1, 0, 0, 0], [0, 0, 1, 0]])


def test_topk_sort():
    x = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    v = nd.topk(x, k=2, ret_typ="value")
    np.testing.assert_allclose(v.asnumpy(), [[3, 2], [5, 4]])
    i = nd.topk(x, k=1)
    np.testing.assert_allclose(i.asnumpy(), [[0], [1]])
    s = nd.sort(x)
    np.testing.assert_allclose(s.asnumpy(), [[1, 2, 3], [0, 4, 5]])
    a = nd.argsort(x)
    np.testing.assert_allclose(a.asnumpy(), [[1, 2, 0], [0, 2, 1]])


def test_where_clip_cast():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([10.0, 20.0, 30.0])
    np.testing.assert_allclose(nd.where(cond, x, y).asnumpy(), [1, 20, 3])
    np.testing.assert_allclose(
        nd.clip(nd.array([-2.0, 0.5, 9.0]), a_min=0.0, a_max=1.0).asnumpy(),
        [0, 0.5, 1])
    assert str(nd.cast(x, dtype="float16").data.dtype) == "float16"
    assert str(x.astype("int32").data.dtype) == "int32"


def test_save_load(tmp_path):
    fname = str(tmp_path / "test.params")
    a = nd.array([[1.0, 2.0]])
    b = nd.array([3.0])
    nd.save(fname, {"a": a, "b": b})
    loaded = nd.load(fname)
    np.testing.assert_allclose(loaded["a"].asnumpy(), a.asnumpy())
    np.testing.assert_allclose(loaded["b"].asnumpy(), b.asnumpy())
    nd.save(fname, [a, b])
    lst = nd.load(fname)
    assert isinstance(lst, list) and len(lst) == 2


def test_wait_sync():
    a = nd.ones((100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()  # must not raise
    nd.waitall()
    assert b.asnumpy()[0, 0] == 100.0


def test_context():
    a = nd.ones((2, 2), ctx=mx.cpu())
    assert a.context.device_type in ("cpu", "tpu")
    b = a.as_in_context(mx.cpu(0))
    assert b.context == mx.cpu(0) or b.context.device_type == "cpu"
    c = a.copy()
    c[0, 0] = 5
    assert a.asnumpy()[0, 0] == 1.0


def test_dtype_propagation():
    a = nd.zeros((2,), dtype="float16")
    assert str((a + a).data.dtype) == "float16"
    b = nd.zeros((2,), dtype="bfloat16")
    assert "bfloat16" in str(b.data.dtype)


def test_norm_pad_tile():
    x = nd.array([[3.0, 4.0]])
    np.testing.assert_allclose(nd.norm(x).asnumpy(), [5.0], rtol=_RTOL6)
    p = nd.pad(nd.ones((1, 1, 2, 2)), mode="constant",
               pad_width=(0, 0, 0, 0, 1, 1, 1, 1), constant_value=0.0)
    assert p.shape == (1, 1, 4, 4)
    t = nd.tile(nd.array([1.0, 2.0]), reps=(2, 2))
    assert t.shape == (2, 4)


def test_slice_ops():
    x = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    s = nd.slice(x, begin=(0, 1), end=(2, 3))
    assert s.shape == (2, 2, 4)
    sa = nd.slice_axis(x, axis=2, begin=1, end=3)
    assert sa.shape == (2, 3, 2)
    sl = nd.slice_like(nd.ones((4, 4)), nd.ones((2, 3)))
    assert sl.shape == (2, 3)


def test_gather_scatter():
    data = nd.array(np.arange(9, dtype=np.float32).reshape(3, 3))
    indices = nd.array([[0, 2], [1, 0]], dtype="int32")
    g = nd.gather_nd(data, indices)
    np.testing.assert_allclose(g.asnumpy(), [1.0, 6.0])
    s = nd.scatter_nd(nd.array([9.0, 8.0]), indices, shape=(3, 3))
    assert s.asnumpy()[0, 1] == 9.0 and s.asnumpy()[2, 0] == 8.0


def test_sequence_ops():
    data = nd.array(np.ones((4, 2, 3), dtype=np.float32))
    seq_len = nd.array([2.0, 3.0])
    masked = nd.SequenceMask(data, seq_len, use_sequence_length=True,
                             value=0.0)
    out = masked.asnumpy()
    assert np.all(out[:2, 0] == 1) and np.all(out[2:, 0] == 0)
    assert np.all(out[:3, 1] == 1) and np.all(out[3:, 1] == 0)
    last = nd.SequenceLast(data, seq_len, use_sequence_length=True)
    assert last.shape == (2, 3)


def test_sequence_defaults_and_axis():
    data = nd.array(np.arange(12, dtype=np.float32).reshape(3, 2, 2))
    # use_sequence_length=False => identity mask / plain flip
    np.testing.assert_allclose(nd.SequenceMask(data).asnumpy(),
                               data.asnumpy())
    rev = nd.SequenceReverse(data)
    np.testing.assert_allclose(rev.asnumpy(), data.asnumpy()[::-1])
    # axis=1 sequence reverse with lengths
    d2 = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    sl = nd.array([2.0, 3.0])
    r2 = nd.SequenceReverse(d2, sl, use_sequence_length=True, axis=1)
    np.testing.assert_allclose(r2.asnumpy(), [[1, 0, 2], [5, 4, 3]])


def test_sort_descending_uint8():
    x = nd.array(np.array([0, 5, 3], np.uint8))
    s = nd.sort(x, is_ascend=False)
    np.testing.assert_allclose(s.asnumpy(), [5, 3, 0])


def test_load_single_is_list(tmp_path):
    f = str(tmp_path / "one.params")
    nd.save(f, [nd.array([1.0, 2.0])])
    out = nd.load(f)
    assert isinstance(out, list) and len(out) == 1


def test_optimizer_lr_required():
    import pytest as _pytest
    with _pytest.raises(mx.MXNetError):
        nd.sgd_update(nd.ones((2,)), nd.ones((2,)))
