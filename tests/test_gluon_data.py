"""gluon.data + io + recordio (reference ``test_gluon_data.py``†,
``test_io.py``†, ``test_recordio.py``†)."""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.gluon import data as gdata


# ----------------------------------------------------------------------
# recordio
# ----------------------------------------------------------------------

def test_recordio_roundtrip(tmp_path):
    from mxtpu import recordio
    path = str(tmp_path / "test.rec")
    rec = recordio.MXRecordIO(path, "w")
    payloads = [bytes([i]) * (i * 7 + 1) for i in range(20)]
    for p in payloads:
        rec.write(p)
    rec.close()
    rec = recordio.MXRecordIO(path, "r")
    for expected in payloads:
        assert rec.read() == expected
    assert rec.read() is None
    rec.reset()
    assert rec.read() == payloads[0]
    rec.close()


def test_indexed_recordio(tmp_path):
    from mxtpu import recordio
    rec_path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(10):
        w.write_idx(i, f"record-{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"record-7"
    assert r.read_idx(2) == b"record-2"
    r.close()


def test_irheader_pack_unpack():
    from mxtpu import recordio
    h = recordio.IRHeader(0, 3.0, 42, 0)
    packed = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(packed)
    assert payload == b"payload"
    assert h2.label == 3.0 and h2.id == 42
    # multi-label
    h = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], np.float32), 7, 0)
    h2, payload = recordio.unpack(recordio.pack(h, b"x"))
    np.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])
    assert h2.flag == 3 and payload == b"x"


def test_pack_img_roundtrip(tmp_path):
    from mxtpu import recordio
    img = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".png")
    h, img2 = recordio.unpack_img(s)
    assert h.label == 1.0
    np.testing.assert_array_equal(img, img2)  # png is lossless


# ----------------------------------------------------------------------
# io iterators
# ----------------------------------------------------------------------

def test_ndarray_iter_pad_discard():
    from mxtpu import io
    X = np.arange(50, dtype=np.float32).reshape(10, 5)
    y = np.arange(10, dtype=np.float32)
    it = io.NDArrayIter(X, y, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    assert batches[0].data[0].shape == (4, 5)
    it = io.NDArrayIter(X, y, batch_size=4, last_batch_handle="discard")
    assert len(list(it)) == 2
    # iterate twice after reset
    it.reset()
    assert len(list(it)) == 2


def test_ndarray_iter_shuffle_dict():
    from mxtpu import io
    X = np.arange(20, dtype=np.float32).reshape(10, 2)
    it = io.NDArrayIter({"data": X}, {"label": np.zeros(10)},
                        batch_size=5, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy()[:, 0] for b in it])
    assert sorted(seen.tolist()) == sorted(X[:, 0].tolist())
    assert [d.name for d in it.provide_data] == ["data"]


def test_resize_and_prefetch_iter():
    from mxtpu import io
    X = np.random.randn(8, 3).astype(np.float32)
    base = io.NDArrayIter(X, np.zeros(8), batch_size=4)
    r = io.ResizeIter(base, 5)
    assert len(list(r)) == 5
    base.reset()
    p = io.PrefetchingIter(
        io.NDArrayIter(X, np.zeros(8), batch_size=4))
    batches = list(p)
    assert len(batches) == 2
    p.reset()
    assert len(list(p)) == 2


def test_csv_iter(tmp_path):
    from mxtpu import io
    data = np.random.randn(7, 3).astype(np.float32)
    np.savetxt(tmp_path / "d.csv", data, delimiter=",")
    np.savetxt(tmp_path / "l.csv", np.arange(7), delimiter=",")
    it = io.CSVIter(data_csv=str(tmp_path / "d.csv"), data_shape=(3,),
                    label_csv=str(tmp_path / "l.csv"), batch_size=3)
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:3], rtol=1e-5)


def test_image_record_iter(tmp_path):
    from mxtpu import io, recordio
    rec_path = str(tmp_path / "img.rec")
    idx_path = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(6):
        img = (rng.rand(10, 12, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img,
            img_fmt=".png"))
    w.close()
    it = io.ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                            data_shape=(3, 8, 8), batch_size=4,
                            shuffle=True, seed=1)
    b = next(it)
    assert b.data[0].shape == (4, 3, 8, 8)
    assert b.label[0].shape == (4,)
    labels = b.label[0].asnumpy()
    assert set(labels.astype(int)) <= {0, 1, 2}


# ----------------------------------------------------------------------
# gluon.data
# ----------------------------------------------------------------------

def test_array_dataset_and_samplers():
    X = np.random.randn(10, 4).astype(np.float32)
    y = np.arange(10)
    ds = gdata.ArrayDataset(X, y)
    assert len(ds) == 10
    xi, yi = ds[3]
    np.testing.assert_allclose(xi, X[3])
    assert yi == 3

    s = list(gdata.SequentialSampler(5))
    assert s == [0, 1, 2, 3, 4]
    r = list(gdata.RandomSampler(5))
    assert sorted(r) == [0, 1, 2, 3, 4]
    bs = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 1]
    bs = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "discard")
    assert [len(b) for b in bs] == [3, 3]


def test_dataset_transform():
    ds = gdata.SimpleDataset(list(range(5)))
    t = ds.transform(lambda x: x * 2)
    assert t[2] == 4 and len(t) == 5
    ds2 = gdata.ArrayDataset(np.arange(4, dtype=np.float32),
                             np.arange(4))
    tf = ds2.transform_first(lambda x: x + 100)
    x, y = tf[1]
    assert float(x) == 101.0 and y == 1


def test_dataloader_basic():
    X = np.random.randn(11, 3).astype(np.float32)
    y = np.arange(11, dtype=np.float32)
    loader = gdata.DataLoader(gdata.ArrayDataset(X, y), batch_size=4,
                              last_batch="keep")
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 3)
    assert batches[-1][0].shape == (3, 3)
    assert len(loader) == 3
    # discard
    loader = gdata.DataLoader(gdata.ArrayDataset(X, y), batch_size=4,
                              last_batch="discard", shuffle=True)
    batches = list(loader)
    assert len(batches) == 2


def test_dataloader_workers():
    X = np.random.randn(32, 3).astype(np.float32)
    loader = gdata.DataLoader(gdata.ArrayDataset(X, np.zeros(32)),
                              batch_size=8, num_workers=2)
    seen = [b[0].asnumpy() for b in loader]
    assert len(seen) == 4
    np.testing.assert_allclose(np.concatenate(seen), X, rtol=1e-6)
    # second epoch works
    assert len(list(loader)) == 4


def test_record_file_dataset(tmp_path):
    from mxtpu import recordio
    rec_path = str(tmp_path / "ds.rec")
    idx_path = str(tmp_path / "ds.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(4):
        w.write_idx(i, f"item{i}".encode())
    w.close()
    ds = gdata.RecordFileDataset(rec_path)
    assert len(ds) == 4
    assert ds[2] == b"item2"


def test_image_record_dataset_and_transforms(tmp_path):
    from mxtpu import recordio
    from mxtpu.gluon.data.vision import transforms
    rec_path = str(tmp_path / "imgs.rec")
    idx_path = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    raws = []
    for i in range(3):
        img = (rng.rand(12, 12, 3) * 255).astype(np.uint8)
        raws.append(img)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    ds = gdata.vision.ImageRecordDataset(rec_path)
    img, label = ds[1]
    assert img.shape == (12, 12, 3)
    assert label == 1.0
    # pack_img takes BGR (cv2 convention); the dataset yields RGB
    np.testing.assert_array_equal(img.asnumpy(), raws[1][:, :, ::-1])

    tf = transforms.Compose([transforms.ToTensor(),
                             transforms.Normalize(mean=0.5, std=0.5)])
    out = tf(img)
    assert out.shape == (3, 12, 12)
    assert float(out.asnumpy().max()) <= 1.0 + 1e-6

    resized = transforms.Resize(6)(img)
    assert resized.shape == (6, 6, 3)
    cropped = transforms.CenterCrop(8)(img)
    assert cropped.shape == (8, 8, 3)
    rrc = transforms.RandomResizedCrop(5)(img)
    assert rrc.shape == (5, 5, 3)
    flipped = transforms.RandomFlipLeftRight()(img)
    assert flipped.shape == (12, 12, 3)


def test_dataloader_feeds_training():
    """DataLoader → Trainer loop end-to-end (M3's loop shape)."""
    from mxtpu import autograd, gluon
    from mxtpu.gluon import nn, loss as gloss
    X = np.random.RandomState(0).randn(64, 6).astype(np.float32)
    yv = (X.sum(1) > 0).astype(np.float32)
    loader = gdata.DataLoader(gdata.ArrayDataset(X, yv), batch_size=16,
                              shuffle=True, num_workers=1)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
    net.initialize(init="xavier")
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    L = gloss.SigmoidBinaryCrossEntropyLoss()
    losses = []
    for _ in range(8):
        tot = 0.0
        for xb, yb in loader:
            with autograd.record():
                out = net(xb)
                l = L(out, yb.reshape((-1, 1)))
            l.backward()
            trainer.step(xb.shape[0])
            tot += float(l.mean().asnumpy())
        losses.append(tot)
    assert losses[-1] < losses[0] * 0.7, losses


def test_mnist_iter_and_dataset(tmp_path):
    """Synthetic MNIST idx files through both MNISTIter and
    gluon.data.vision.MNIST."""
    import struct
    from mxtpu import io
    rng = np.random.RandomState(0)
    imgs = (rng.rand(20, 28, 28) * 255).astype(np.uint8)
    labels = rng.randint(0, 10, 20).astype(np.uint8)
    root = tmp_path

    def write_idx(path, arr):
        with open(path, "wb") as f:
            code = 0x08
            f.write(struct.pack(">I", (code << 8) | arr.ndim))
            for d in arr.shape:
                f.write(struct.pack(">I", d))
            f.write(arr.tobytes())

    write_idx(root / "train-images-idx3-ubyte", imgs)
    write_idx(root / "train-labels-idx1-ubyte", labels)

    it = io.MNISTIter(image=str(root / "train-images-idx3-ubyte"),
                      label=str(root / "train-labels-idx1-ubyte"),
                      batch_size=5, shuffle=False)
    b = next(it)
    assert b.data[0].shape == (5, 1, 28, 28)
    np.testing.assert_allclose(b.data[0].asnumpy()[0, 0],
                               imgs[0] / 255.0, rtol=1e-6)

    from mxtpu.gluon.data import vision
    ds = vision.MNIST(root=str(root), train=True)
    assert len(ds) == 20
    img, label = ds[3]
    assert img.shape == (28, 28, 1)
    assert label == labels[3]


def test_ndarray_iter_batch_larger_than_data():
    """pad mode wraps repeatedly; batches are never ragged (review
    regression)."""
    from mxtpu import io
    X = np.arange(6, dtype=np.float32).reshape(3, 2)
    it = io.NDArrayIter(X, np.zeros(3), batch_size=8,
                        last_batch_handle="pad")
    b = next(it)
    assert b.data[0].shape == (8, 2)
    assert b.pad == 5
    np.testing.assert_allclose(b.data[0].asnumpy()[:, 0],
                               [0, 2, 4, 0, 2, 4, 0, 2])


def test_record_dataset_threaded_reads(tmp_path):
    """Concurrent read_idx through the DataLoader thread pool stays
    consistent (review regression: seek+read must be atomic)."""
    from mxtpu import recordio
    rec_path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(64):
        w.write_idx(i, (f"payload-{i:03d}-" + "x" * (i % 17)).encode())
    w.close()
    ds = gdata.RecordFileDataset(rec_path)

    def check(idx):
        raw = ds[idx]
        assert raw.startswith(f"payload-{idx:03d}-".encode()), raw[:16]
        return idx

    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(8) as pool:
        results = list(pool.map(check, list(range(64)) * 8))
    assert len(results) == 512


def test_recordio_scan_and_read_batch(tmp_path):
    """Native + python codecs agree on scan/read_batch; indexed reader
    works without a .idx sidecar."""
    from mxtpu import recordio
    path = str(tmp_path / "scan.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [bytes([i % 251]) * (10 + i * 7) for i in range(50)]
    for p in payloads:
        w.write(p)
    w.close()

    offs, lens = recordio.scan(path)
    assert len(offs) == 50
    got = recordio.read_batch(path, offs, lens)
    assert got == payloads

    # python fallback parity (force-native off)
    import mxtpu.recordio as rio
    nat = rio._NATIVE
    try:
        rio._NATIVE = False
        offs_py, lens_py = recordio.scan(path)
        got_py = recordio.read_batch(path, offs_py, lens_py)
    finally:
        rio._NATIVE = nat
    assert offs_py == list(offs) and lens_py == list(lens)
    assert got_py == payloads

    # MXIndexedRecordIO with no .idx file: auto-index via scan
    r = recordio.MXIndexedRecordIO(str(tmp_path / "missing.idx"),
                                   path, "r")
    assert len(r.keys) == 50
    assert r.read_idx(7) == payloads[7]
    r.close()


def test_recordio_read_batch_into(tmp_path):
    """Batched scatter-read into a caller buffer: native and python
    fallback agree on both the pixel rows and the header prefix."""
    from mxtpu import recordio
    import mxtpu.recordio as rio
    path = str(tmp_path / "into.rec")
    hdr_bytes, row = 24, 48
    w = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(3)
    payloads = [rng.randint(0, 256, hdr_bytes + row)
                .astype(np.uint8).tobytes() for _ in range(20)]
    for p in payloads:
        w.write(p)
    w.close()
    offs, lens = recordio.scan(path)

    def run():
        out = np.zeros((20, row), np.uint8)
        hdrs = recordio.read_batch_into(path, offs, lens, out,
                                        hdr_bytes)
        return out, hdrs

    out_n, hdrs_n = run()
    nat = rio._NATIVE
    try:
        rio._NATIVE = False
        out_p, hdrs_p = run()
    finally:
        rio._NATIVE = nat
    want = np.frombuffer(b"".join(payloads),
                         np.uint8).reshape(20, hdr_bytes + row)
    for out, hdrs in ((out_n, hdrs_n), (out_p, hdrs_p)):
        np.testing.assert_array_equal(out, want[:, hdr_bytes:])
        assert hdrs == want[:, :hdr_bytes].tobytes()


def test_device_feed_iter():
    """DeviceFeedIter yields the base iterator's batches unchanged
    (values and order), supports reset, and hands back device-placed
    NDArrays."""
    from mxtpu import io
    from mxtpu.ndarray import NDArray
    X = np.arange(24, dtype=np.float32).reshape(8, 3)
    y = np.arange(8, dtype=np.float32)
    base = io.NDArrayIter(X, y, batch_size=4)
    want = [(b.data[0].asnumpy(), b.label[0].asnumpy())
            for b in base]
    base.reset()
    feed = io.DeviceFeedIter(base)
    for _ in range(2):  # two epochs: reset must restage
        got = []
        while True:
            try:
                b = feed.next()
            except StopIteration:
                break
            assert isinstance(b.data[0], NDArray)
            got.append((b.data[0].asnumpy(), b.label[0].asnumpy()))
        assert len(got) == len(want)
        for (gx, gy), (wx, wy) in zip(got, want):
            np.testing.assert_array_equal(gx, wx)
            np.testing.assert_array_equal(gy, wy)
        feed.reset()
