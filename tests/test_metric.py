"""Metric tests vs numpy (reference ``test_metric.py``†)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, metric


def test_accuracy():
    pred = nd.array(np.array([[0.3, 0.7], [0.6, 0.4], [0.2, 0.8]],
                             np.float32))
    label = nd.array(np.array([1, 0, 0], np.float32))
    m = metric.Accuracy()
    m.update([label], [pred])
    name, val = m.get()
    assert name == "accuracy"
    assert abs(val - 2.0 / 3) < 1e-6


def test_topk_accuracy():
    np.random.seed(0)
    pred = np.random.randn(20, 6).astype(np.float32)
    label = np.random.randint(0, 6, 20).astype(np.float32)
    m = metric.TopKAccuracy(top_k=3)
    m.update([nd.array(label)], [nd.array(pred)])
    top3 = np.argsort(-pred, axis=1)[:, :3]
    ref = np.mean([l in t for l, t in zip(label.astype(int), top3)])
    assert abs(m.get()[1] - ref) < 1e-6


def test_f1():
    pred = nd.array(np.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7],
                              [0.6, 0.4]], np.float32))
    label = nd.array(np.array([0, 1, 0, 1], np.float32))
    m = metric.F1()
    m.update([label], [pred])
    # tp=1 (idx1), fp=1 (idx2), fn=1 (idx3)
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_mae_mse_rmse():
    pred = np.array([[1.0], [2.0]], np.float32)
    label = np.array([[0.0], [4.0]], np.float32)
    for name, ref in [("mae", 1.5), ("mse", 2.5),
                      ("rmse", np.sqrt(2.5))]:
        m = metric.create(name)
        m.update([nd.array(label)], [nd.array(pred)])
        assert abs(m.get()[1] - ref) < 1e-6, name


def test_cross_entropy_perplexity():
    prob = np.array([[0.2, 0.8], [0.6, 0.4]], np.float32)
    label = np.array([1, 0], np.float32)
    ce = metric.CrossEntropy()
    ce.update([nd.array(label)], [nd.array(prob)])
    ref = -(np.log(0.8) + np.log(0.6)) / 2
    assert abs(ce.get()[1] - ref) < 1e-6
    p = metric.Perplexity(ignore_label=None)
    p.update([nd.array(label)], [nd.array(prob)])
    assert abs(p.get()[1] - np.exp(ref)) < 1e-5


def test_composite_and_custom():
    comp = metric.CompositeEvalMetric()
    comp.add(metric.Accuracy())
    comp.add(metric.MAE())
    pred = nd.array(np.array([[0.3, 0.7]], np.float32))
    label = nd.array(np.array([1], np.float32))
    comp.update([label], [pred])
    names, values = comp.get()
    assert "accuracy" in names and "mae" in names

    custom = metric.np(lambda l, p: float((l == p.argmax(1)).mean()),
                       name="mycustom")
    custom.update([label], [pred])
    assert custom.get()[1] == 1.0


def test_create_and_reset():
    m = metric.create("acc")
    assert isinstance(m, metric.Accuracy)
    m = metric.create(["acc", "mse"])
    assert isinstance(m, metric.CompositeEvalMetric)
    a = metric.Accuracy()
    assert np.isnan(a.get()[1])
    with pytest.raises(mx.MXNetError):
        metric.create("not_a_metric")


def test_loss_metric():
    m = metric.Loss()
    m.update(None, [nd.array(np.full((2, 2), 3.0, np.float32))])
    assert abs(m.get()[1] - 3.0) < 1e-6
