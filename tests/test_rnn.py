"""RNN op + gluon.rnn (reference ``tests/python/unittest/test_gluon_rnn
.py``† and ``test_operator.py::test_rnn*``†)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, nd
from mxtpu.gluon import rnn
from mxtpu.ndarray.rnn_impl import rnn_param_size


def _np_lstm_ref(x, params, h0, c0, H):
    """Single-layer unidirectional LSTM, numpy, onto which the fused op's
    layout contract is pinned (gate order [i,f,g,o])."""
    T, N, I = x.shape
    G = 4
    off = 0
    w_i2h = params[off:off + G * H * I].reshape(G * H, I); off += G * H * I
    w_h2h = params[off:off + G * H * H].reshape(G * H, H); off += G * H * H
    b_i2h = params[off:off + G * H]; off += G * H
    b_h2h = params[off:off + G * H]; off += G * H

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    h, c = h0.copy(), c0.copy()
    outs = []
    for t in range(T):
        gates = x[t] @ w_i2h.T + b_i2h + h @ w_h2h.T + b_h2h
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs), h, c


def test_fused_lstm_matches_numpy():
    T, N, I, H = 5, 3, 4, 6
    rng = np.random.RandomState(0)
    x = rng.randn(T, N, I).astype(np.float32)
    nparam = rnn_param_size(1, I, H, False, "lstm")
    params = rng.randn(nparam).astype(np.float32) * 0.2
    h0 = rng.randn(1, N, H).astype(np.float32)
    c0 = rng.randn(1, N, H).astype(np.float32)

    out, hn, cn = nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                         nd.array(c0), state_size=H, num_layers=1,
                         mode="lstm", state_outputs=True)
    ref_out, ref_h, ref_c = _np_lstm_ref(x, params, h0[0], c0[0], H)
    np.testing.assert_allclose(out.asnumpy(), ref_out, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(hn.asnumpy()[0], ref_h, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(cn.asnumpy()[0], ref_c, rtol=1e-5,
                               atol=1e-5)


def test_fused_rnn_modes_shapes():
    T, N, I, H, L = 4, 2, 3, 5, 2
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(T, N, I).astype(np.float32))
    for mode, nstates in [("rnn_relu", 1), ("rnn_tanh", 1), ("gru", 1),
                          ("lstm", 2)]:
        for bi in (False, True):
            D = 2 if bi else 1
            nparam = rnn_param_size(L, I, H, bi, mode)
            params = nd.array(rng.randn(nparam).astype(np.float32) * 0.1)
            states = [nd.zeros((L * D, N, H)) for _ in range(nstates)]
            outs = nd.RNN(x, params, *states, state_size=H, num_layers=L,
                          mode=mode, bidirectional=bi,
                          state_outputs=True)
            out = outs[0]
            assert out.shape == (T, N, D * H), (mode, bi, out.shape)
            assert outs[1].shape == (L * D, N, H)
            if mode == "lstm":
                assert outs[2].shape == (L * D, N, H)


def test_lstm_layer_matches_cell_unroll():
    """Fused LSTM layer ≡ LSTMCell unrolled, same parameters."""
    T, N, I, H = 6, 2, 3, 4
    rng = np.random.RandomState(2)
    layer = rnn.LSTM(H, input_size=I)
    layer.initialize()
    x = nd.array(rng.randn(T, N, I).astype(np.float32))
    out = layer(x)
    assert out.shape == (T, N, H)

    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    # copy fused layer params into the cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    outs, states = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(out.asnumpy(), outs.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_gru_layer_matches_cell_unroll():
    T, N, I, H = 5, 3, 4, 4
    rng = np.random.RandomState(3)
    layer = rnn.GRU(H, input_size=I)
    layer.initialize()
    x = nd.array(rng.randn(T, N, I).astype(np.float32))
    out = layer(x)
    cell = rnn.GRUCell(H, input_size=I)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    outs, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(out.asnumpy(), outs.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_rnn_layer_states_and_ntc():
    N, T, I, H = 2, 5, 3, 4
    layer = rnn.LSTM(H, num_layers=2, layout="NTC", input_size=I)
    layer.initialize()
    x = nd.array(np.random.randn(N, T, I).astype(np.float32))
    states = layer.begin_state(batch_size=N)
    out, new_states = layer(x, states)
    assert out.shape == (N, T, H)
    assert new_states[0].shape == (2, N, H)
    assert new_states[1].shape == (2, N, H)


def test_bidirectional_layer_reverse_semantics():
    """Backward direction must process time reversed: compare with
    manually reversed forward pass of a unidirectional twin."""
    T, N, I, H = 4, 2, 3, 5
    rng = np.random.RandomState(4)
    bi = rnn.GRU(H, bidirectional=True, input_size=I)
    bi.initialize()
    x = rng.randn(T, N, I).astype(np.float32)
    out = bi(nd.array(x)).asnumpy()
    assert out.shape == (T, N, 2 * H)

    uni = rnn.GRU(H, input_size=I)
    uni.initialize()
    uni.l0_i2h_weight.set_data(bi.r0_i2h_weight.data())
    uni.l0_h2h_weight.set_data(bi.r0_h2h_weight.data())
    uni.l0_i2h_bias.set_data(bi.r0_i2h_bias.data())
    uni.l0_h2h_bias.set_data(bi.r0_h2h_bias.data())
    rev = uni(nd.array(x[::-1].copy())).asnumpy()[::-1]
    np.testing.assert_allclose(out[:, :, H:], rev, rtol=1e-5, atol=1e-5)


def test_rnn_gradient_flows():
    T, N, I, H = 4, 3, 5, 6
    layer = rnn.LSTM(H, num_layers=2, input_size=I)
    layer.initialize()
    x = nd.array(np.random.randn(T, N, I).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    assert float((x.grad.asnumpy() ** 2).sum()) > 0
    for name, p in layer.collect_params().items():
        g = p.grad().asnumpy()
        assert np.isfinite(g).all(), name
        assert float(np.abs(g).sum()) > 0, name


def test_rnn_hybridize_consistency():
    T, N, I, H = 4, 2, 3, 4
    rng = np.random.RandomState(5)
    x = nd.array(rng.randn(T, N, I).astype(np.float32))
    layer = rnn.GRU(H, num_layers=2, input_size=I)
    layer.initialize()
    eager = layer(x).asnumpy()
    layer.hybridize()
    hybrid = layer(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_rnn_cells_api():
    cell = rnn.RNNCell(4, input_size=3)
    cell.initialize()
    x = nd.array(np.random.randn(2, 3).astype(np.float32))
    states = cell.begin_state(batch_size=2)
    out, states2 = cell(x, states)
    assert out.shape == (2, 4) and states2[0].shape == (2, 4)

    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(4, input_size=3))
    stack.add(rnn.ResidualCell(rnn.GRUCell(4, input_size=4)))
    stack.add(rnn.DropoutCell(0.0))
    for c in [stack[0], stack[1].base_cell]:
        c.initialize()
    states = stack.begin_state(batch_size=2)
    out, states2 = stack(x, states)
    assert out.shape == (2, 4)
    assert len(states2) == len(states) == 3

    outs, _ = stack.unroll(5, nd.array(
        np.random.randn(2, 5, 3).astype(np.float32)), layout="NTC",
        merge_outputs=True)
    assert outs.shape == (2, 5, 4)


def test_bidirectional_cell():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(3, input_size=2),
                               rnn.LSTMCell(3, input_size=2))
    for c in (bi._l_cell, bi._r_cell):
        c.initialize()
    x = nd.array(np.random.randn(2, 4, 2).astype(np.float32))
    outs, states = bi.unroll(4, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 4, 6)
    assert len(states) == 4


def test_lstm_lm_convergence():
    """Tiny LSTM language-model-style training converges (reference
    ``tests/python/train/test_bucketing``†-style smoke)."""
    from mxtpu import gluon
    from mxtpu.gluon import nn, loss as gloss
    V, E, H, T, N = 12, 8, 16, 6, 8
    rng = np.random.RandomState(0)
    # learnable pattern: next token = (token + 1) % V
    seqs = np.stack([np.arange(i, i + T + 1) % V for i in range(N * 4)])

    class LM(nn.HybridSequential):
        pass

    net = LM()
    net.add(nn.Embedding(V, E))
    lstm = rnn.LSTM(H, layout="NTC", input_size=E)
    net.add(lstm)
    net.add(nn.Dense(V, flatten=False))
    net.initialize(init="xavier")
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.02})
    L = gloss.SoftmaxCrossEntropyLoss()
    losses = []
    for epoch in range(30):
        tot = 0.0
        for b in range(4):
            batch = seqs[b * N:(b + 1) * N]
            x = nd.array(batch[:, :-1].astype(np.float32))
            y = nd.array(batch[:, 1:].astype(np.float32))
            with autograd.record():
                out = net(x)
                l = L(out.reshape((-1, V)), y.reshape((-1,)))
            l.backward()
            trainer.step(N)
            tot += float(l.mean().asnumpy())
        losses.append(tot / 4)
    assert losses[-1] < 0.15, losses[-10:]


def test_rnn_symbolic_num_outputs():
    """Symbol composition must see the right output count per
    mode/state_outputs (review regression)."""
    import mxtpu as mx
    H, I, T, N = 4, 3, 5, 2
    nparam = rnn_param_size(1, I, H, False, "gru")
    data = mx.sym.var("data")
    par = mx.sym.var("p")
    st = mx.sym.var("s")
    out = mx.sym.RNN(data, par, st, state_size=H, num_layers=1,
                     mode="gru", state_outputs=True)
    assert len(out) == 2
    out1 = mx.sym.RNN(data, par, st, state_size=H, num_layers=1,
                      mode="gru", state_outputs=False)
    assert len(out1) == 1
    rng = np.random.RandomState(0)
    vals = out.eval(data=nd.array(rng.randn(T, N, I).astype(np.float32)),
                    p=nd.array(rng.randn(nparam).astype(np.float32) * .1),
                    s=nd.zeros((1, N, H)))
    assert vals[0].shape == (T, N, H)
    assert vals[1].shape == (1, N, H)


def test_unroll_valid_length_states():
    """States returned from unroll(valid_length=...) are taken at each
    sample's length, not after the padding (review regression)."""
    T, N, I, H = 6, 3, 2, 4
    rng = np.random.RandomState(7)
    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    x = rng.randn(N, T, I).astype(np.float32)
    vl = np.array([2, 6, 4], np.float32)
    outs, states = cell.unroll(T, nd.array(x), layout="NTC",
                               merge_outputs=True,
                               valid_length=nd.array(vl))
    # reference: sample 0's state == state after unrolling only 2 steps
    outs2, states2 = cell.unroll(2, nd.array(x[:1, :2]), layout="NTC",
                                 merge_outputs=True)
    np.testing.assert_allclose(states[0].asnumpy()[0],
                               states2[0].asnumpy()[0], rtol=1e-5,
                               atol=1e-6)
    # masked outputs beyond valid_length are zero
    o = outs.asnumpy()
    assert np.abs(o[0, 2:]).sum() == 0.0
    assert np.abs(o[2, 4:]).sum() == 0.0


def test_bidirectional_cell_valid_length():
    T, N, I, H = 5, 2, 2, 3
    rng = np.random.RandomState(8)
    bi = rnn.BidirectionalCell(rnn.GRUCell(H, input_size=I),
                               rnn.GRUCell(H, input_size=I))
    for c in (bi._l_cell, bi._r_cell):
        c.initialize()
    x = rng.randn(N, T, I).astype(np.float32)
    vl = np.array([3, 5], np.float32)
    outs, states = bi.unroll(T, nd.array(x), layout="NTC",
                             merge_outputs=True,
                             valid_length=nd.array(vl))
    o = outs.asnumpy()
    # outputs past each sample's valid length are masked to zero
    assert np.abs(o[0, 3:]).sum() == 0.0
    # sample 0's reverse outputs equal running the r_cell on just the
    # valid prefix reversed
    prefix = x[0:1, :3][:, ::-1].copy()
    r_outs, _ = bi._r_cell.unroll(3, nd.array(prefix), layout="NTC",
                                  merge_outputs=True)
    np.testing.assert_allclose(o[0, :3, H:], r_outs.asnumpy()[0][::-1],
                               rtol=1e-5, atol=1e-6)


def test_rnn_layer_symbolic_compose():
    """Symbol composition + export of a fused RNN layer (review
    regression: used to crash on Symbol.shape)."""
    import mxtpu as mx
    lstm = rnn.LSTM(4, input_size=3)
    lstm.initialize()
    out = lstm(mx.sym.var("data"))
    args = out.list_arguments()
    assert "data" in args
    assert any("begin_state" in a for a in args)
    # bind and compare with the eager path
    rng = np.random.RandomState(9)
    x = rng.randn(5, 2, 3).astype(np.float32)
    bindings = {"data": nd.array(x)}
    for a in args:
        if "begin_state" in a:
            bindings[a] = nd.zeros((1, 2, 4))
        elif a != "data":
            pname = a
            bindings[a] = dict(lstm.collect_params())[pname].data()
    got = out.eval(**bindings)
    ref = lstm(nd.array(x))
    np.testing.assert_allclose(got[0].asnumpy(), ref.asnumpy(),
                               rtol=1e-5, atol=1e-6)
