"""Recipe parity (VERDICT r2 item 9): the canonical common_fit loop,
train_cifar10 and benchmark_score run end-to-end on synthetic data.

Reference: example/image-classification/common/fit.py†,
train_cifar10.py†, benchmark_score.py†.
"""
import os
import runpy
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EX = os.path.join(_ROOT, "examples")


def _run(script, argv):
    old = sys.argv
    sys.path.insert(0, _EX)
    sys.argv = [script] + argv
    try:
        runpy.run_path(os.path.join(_EX, script), run_name="__main__")
    finally:
        sys.argv = old
        sys.path.remove(_EX)


def test_train_cifar10_recipe(tmp_path, caplog):
    import logging
    caplog.set_level(logging.INFO)
    _run("train_cifar10.py",
         ["--num-epochs", "2", "--batch-size", "64",
          "--num-classes", "2", "--lr", "0.01",
          "--lr-step-epochs", "1",
          "--model-prefix", str(tmp_path / "ck")])
    # the fit loop logged epochs + validation and wrote checkpoints
    msgs = [r.message for r in caplog.records]
    assert any("Validation-accuracy" in m for m in msgs)
    accs = [float(m.split("=")[1]) for m in msgs
            if m.startswith("Epoch[1] Validation-accuracy")]
    assert accs and accs[-1] > 0.9, msgs[-5:]
    assert (tmp_path / "ck-symbol.json").exists()
    assert (tmp_path / "ck-0002.params").exists()


def test_train_cifar10_resume(tmp_path, caplog):
    import logging
    caplog.set_level(logging.INFO)
    _run("train_cifar10.py",
         ["--num-epochs", "1", "--batch-size", "64",
          "--num-classes", "2", "--lr", "0.01",
          "--model-prefix", str(tmp_path / "ck")])
    _run("train_cifar10.py",
         ["--num-epochs", "2", "--batch-size", "64",
          "--num-classes", "2", "--lr", "0.01",
          "--model-prefix", str(tmp_path / "ck"),
          "--load-epoch", "1"])
    msgs = [r.message for r in caplog.records]
    assert any("resumed from" in m for m in msgs)
    assert (tmp_path / "ck-0002.params").exists()


def test_benchmark_score_runs(caplog):
    import logging
    caplog.set_level(logging.INFO)
    _run("benchmark_score.py",
         ["--networks", "squeezenet1_0", "--batch-sizes", "2",
          "--image-size", "64"])
    assert any("images/sec" in r.message for r in caplog.records)


def test_train_bert_tp_recipe(caplog):
    """TP recipe (VERDICT r3 item 9): megatron param_spec sharding over
    a dp2 x mp4 mesh with 1-device numerical parity."""
    import logging
    caplog.set_level(logging.INFO)
    _run("train_bert_tp.py",
         ["--model", "tiny", "--dp", "2", "--mp", "4",
          "--steps", "4", "--batch-size", "8", "--seq-len", "32",
          "--vocab", "2000", "--parity"])
    msgs = [r.message for r in caplog.records]
    assert any("TP sharding verified" in m for m in msgs)
    assert any("parity vs 1-device OK" in m for m in msgs)


def test_train_imagenet_recipe(caplog):
    """train_imagenet analog (VERDICT r3 missing-6): model_zoo network
    through the canonical fit recipe on synthetic ImageNet-shaped
    data."""
    import logging
    caplog.set_level(logging.INFO)
    _run("train_imagenet.py",
         ["--network", "resnet18_v1", "--image-shape", "3,32,32",
          "--num-classes", "4", "--num-examples", "512",
          "--num-epochs", "2", "--batch-size", "64",
          "--lr", "0.02"])
    msgs = [r.message for r in caplog.records]
    # epoch 1 reaches 1.0 train accuracy on this synthetic set (epoch 0
    # is ~0.75); two epochs keep the convergence signal at half the cost
    accs = [float(m.split("=")[1]) for m in msgs
            if m.startswith("Epoch[1] Train-accuracy")]
    assert accs and accs[-1] > 0.5, msgs[-6:]


def test_train_moe_recipe(caplog):
    """Expert-parallel MoE recipe: dp2 x ep4 mesh, expert weights
    sharded over ep, loss parity vs the unsharded run."""
    import logging
    caplog.set_level(logging.INFO)
    _run("train_moe.py",
         ["--dp", "2", "--ep", "4", "--steps", "12", "--parity"])
    msgs = [r.message for r in caplog.records]
    assert any("EP sharding verified" in m for m in msgs)
    assert any("parity vs unsharded OK" in m for m in msgs)


def test_serve_bert_recipe(capsys):
    """Serving recipe (ISSUE 4): export → ModelRunner.from_export →
    InferenceServer → concurrent mixed-length clients → stats."""
    with pytest.raises(SystemExit) as e:
        _run("serve_bert.py",
             ["--clients", "2", "--requests", "5", "--layers", "1",
              "--units", "64", "--heads", "2", "--seq-len", "32",
              "--max-batch", "4"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "req/sec end-to-end" in out
    assert '"completed": 10' in out
    assert "weights uploaded once" in out
