"""mxtpu.quant — INT8 post-training quantization (ISSUE 18).

Covers: the MXTPU_QUANT kill-switch precedence ladder and the
bit-identical off-path program; calibration determinism (byte-equal
threshold tables across runs, both collectors); the serving BERT
accuracy gate against its f32 twin with the s8xs8->s32 contraction
census and zero dtype-flow hazards pinned; the `python -m tools.mxprec
--quant` update->check fixed point at the byte level; and the
calibrate() error contract.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mxtpu import quant
from mxtpu import symbol as sym
from mxtpu.analysis import dtypeflow
from mxtpu.base import MXNetError
from mxtpu.serving import ModelRunner

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fc_runner(**kwargs):
    """Two tiny FullyConnected layers behind a relu — enough graph for
    calibration to observe two candidate contractions."""
    data = sym.var("data")
    h = sym.FullyConnected(data, sym.var("w1"), sym.var("b1"),
                           num_hidden=8)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, sym.var("w2"), sym.var("b2"),
                             num_hidden=4)
    rng = np.random.RandomState(3)
    params = {"w1": (rng.randn(8, 6) / np.sqrt(6)).astype(np.float32),
              "b1": np.zeros(8, np.float32),
              "w2": (rng.randn(4, 8) / np.sqrt(8)).astype(np.float32),
              "b2": np.zeros(4, np.float32)}
    return ModelRunner(out, params, {"data": (6,)}, max_batch_size=2,
                       cache=None, **kwargs)


def _calib_batches(scale=1.0, n=3):
    rng = np.random.RandomState(11)
    return [{"data": (scale * rng.randn(2, 6)).astype(np.float32)}
            for _ in range(n)]


# ----------------------------------------------------- switch + knobs

def test_resolve_kill_switch_precedence(monkeypatch):
    monkeypatch.setenv("MXTPU_QUANT", "0")
    assert quant.resolve(True) is False  # env kill beats the argument
    monkeypatch.setenv("MXTPU_QUANT", "1")
    assert quant.resolve(None) is True
    monkeypatch.delenv("MXTPU_QUANT")
    assert quant.resolve(None) is False
    assert quant.resolve(True) is True


def test_calib_config_rejects_unknown_mode(monkeypatch):
    monkeypatch.setenv("MXTPU_QUANT_CALIB", "percentile")
    with pytest.raises(MXNetError, match="MXTPU_QUANT_CALIB"):
        quant.calib_config()


def test_kill_switch_bit_identical_program(monkeypatch):
    """MXTPU_QUANT=0 with quant=True requested produces the same
    pre-opt program, byte for byte, as a plain float runner — the
    off path really is OFF."""
    monkeypatch.setenv("MXTPU_QUANT", "0")
    killed = _fc_runner(quant=True)       # requested, env kills it
    with pytest.raises(MXNetError, match="non-quantized"):
        killed.calibrate(_calib_batches())
    monkeypatch.delenv("MXTPU_QUANT")
    plain = _fc_runner()
    bucket = plain.buckets()[0]
    killed_text = killed.lowered_program_text(bucket)
    assert killed_text == plain.lowered_program_text(bucket)
    assert "s8[" not in killed_text
    # ... while the armed path rewrites the contractions to int8
    armed = _fc_runner(quant=True)
    armed.calibrate(_calib_batches())
    armed_text = armed.lowered_program_text(bucket)
    assert armed_text != killed_text
    assert dtypeflow.int8_contraction_census(armed_text) == \
        {"s8xs8->s32": 2}


# ------------------------------------------ calibration determinism

@pytest.mark.parametrize("mode", ["minmax", "entropy"])
def test_calibration_is_deterministic(mode):
    """Identical batches -> byte-equal threshold tables, run to run
    and runner to runner; every value carries the committed 6-sig-fig
    decimal form (quant_policy.json evidence stays byte-stable)."""
    runs = []
    for _ in range(2):
        r = _fc_runner(quant=True)
        runs.append(r.calibrate(_calib_batches(), mode=mode))
    assert runs[0] == runs[1]
    assert sorted(runs[0]) == ["FullyConnected_0", "FullyConnected_1"]
    for key, v in runs[0].items():
        assert v > 0, key
        assert v == float(f"{v:.6g}"), key  # round6'd
    # repeated calibration on ONE runner (pre-compile) re-derives the
    # same table rather than accumulating state
    r = _fc_runner(quant=True)
    first = r.calibrate(_calib_batches(), mode=mode)
    again = r.calibrate(_calib_batches(), mode=mode)
    assert first == again == r.quant_scales()


def test_collectors_disagree_on_outliers():
    """The two estimators are genuinely different algorithms: on a
    heavy-tailed activation the KL threshold clips inside the raw
    |x| max, minmax never does."""
    rng = np.random.RandomState(5)
    x = rng.randn(4096).astype(np.float32)
    x[0] = 40.0                             # one outlier
    mm = quant.MinMaxCollector()
    en = quant.EntropyCollector()
    for c in (mm, en):
        c.observe("k", x)
    t_mm = mm.thresholds()["k"]
    t_en = en.thresholds()["k"]
    assert t_mm == pytest.approx(40.0, rel=1e-5)
    assert 0 < t_en < 0.5 * t_mm


def test_calibrate_guardrails():
    r = _fc_runner(quant=True)
    r.calibrate(_calib_batches())
    bucket = r.buckets()[0]
    r.warmup([bucket])
    with pytest.raises(MXNetError, match="after buckets compiled"):
        r.calibrate(_calib_batches())
    # a graph with no quantizable contraction refuses to calibrate
    data = sym.var("data")
    mul = ModelRunner(data * sym.var("w"),
                      {"w": np.ones(3, np.float32)}, {"data": (3,)},
                      max_batch_size=2, cache=None, quant=True)
    with pytest.raises(MXNetError, match="no quantizable"):
        mul.calibrate([{"data": np.ones((2, 3), np.float32)}])


# ------------------------------------- serving BERT accuracy + census

def test_bert_int8_accuracy_census_and_hazards():
    """The acceptance gate: the quantized serving BERT fixture stays
    within 10% of its f32 twin's logit scale (measured 4.7% at seed
    0), every per-layer GEMM lowered as s8xs8 accumulating in s32
    (census == the committed quant_policy.json evidence), zero
    dtype-flow hazards, and the float twin carries no int8 at all."""
    from tools.hlocheck import targets as T
    from mxtpu.ndarray import random as mxrnd

    mxrnd.seed(0)                 # same init stream as the quant twin
    f32 = T._serving_runner()
    q8 = T._serving_runner(quant=True)   # reseeds 0 internally

    bucket = (4, 32)
    rng = np.random.RandomState(123)
    reqs = [{"data": rng.randint(0, T._VOCAB, (32,))
             .astype(np.float32)} for _ in range(4)]

    def logits(r):
        return np.asarray(
            r.run_raw(r._pad_stack(reqs, bucket), bucket)[0])

    lf, lq = logits(f32), logits(q8)
    scale = float(np.abs(lf).max())
    delta = float(np.abs(lq - lf).max())
    assert 0 < delta <= 0.10 * max(1.0, scale), (delta, scale)

    q_text = q8.lowered_program_text(bucket)
    census = dtypeflow.int8_contraction_census(q_text)
    assert census == {"s8xs8->s32": 9}
    assert dtypeflow.program_ledger(q_text)["hazards"] == []
    assert "s8[" not in f32.lowered_program_text(bucket)
    # the census in the committed policy evidence is THIS census
    with open(os.path.join(_ROOT, "contracts",
                           "quant_policy.json")) as f:
        policy = json.load(f)
    assert policy["calibration"]["int8_contractions"][
        f"bucket_b{bucket[0]}_s{bucket[1]}"] == census


# ---------------------------------------------------------------- CLI

def _mxprec(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxprec", *args],
        capture_output=True, text=True, cwd=_ROOT, timeout=240)


@pytest.mark.slow
def test_cli_quant_update_check_fixed_point(tmp_path):
    """`--quant --update` into a scratch dir reproduces the committed
    policy byte for byte (derivation is deterministic AND the
    committed file is its own fixed point), and `--quant` catches a
    corrupted threshold with exit 1."""
    d = str(tmp_path)
    up = _mxprec("--quant", "--update", "--contracts-dir", d)
    assert up.returncode == 0, up.stdout + up.stderr
    fresh = (tmp_path / "quant_policy.json").read_bytes()
    with open(os.path.join(_ROOT, "contracts",
                           "quant_policy.json"), "rb") as f:
        assert fresh == f.read()

    policy = json.loads(fresh)
    key = next(iter(policy["calibration"]["activation_thresholds"]
                    ["entropy"]))
    policy["calibration"]["activation_thresholds"]["entropy"][key] \
        += 1.0
    (tmp_path / "quant_policy.json").write_text(
        json.dumps(policy, indent=1, sort_keys=True) + "\n")
    bad = _mxprec("--quant", "--contracts-dir", d)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "quant_policy" in bad.stdout


def test_cli_quant_missing_policy_is_a_violation(tmp_path):
    r = _mxprec("--quant", "--contracts-dir", str(tmp_path))
    assert r.returncode == 1
    assert "quant_policy" in r.stdout


# --------------------------------------------------------- self-check

def test_self_check_passes():
    assert quant.self_check() == 0
