"""Statistical tests for the RNG ops (reference
``tests/python/unittest/test_random.py``†: moment and goodness-of-fit
checks per distribution, per-seed determinism, per-context streams).
"""
import numpy as np
import pytest
from scipy import stats

import mxtpu as mx
from mxtpu import nd

N = 20000


def _draw(fn, *args, **kwargs):
    mx.random.seed(42)
    return fn(*args, shape=(N,), **kwargs).asnumpy()


def test_uniform_ks_and_moments():
    s = _draw(nd.random.uniform, 2.0, 5.0)
    assert s.min() >= 2.0 and s.max() < 5.0
    # KS against the exact CDF
    d, p = stats.kstest((s - 2.0) / 3.0, "uniform")
    assert p > 1e-3, (d, p)
    assert abs(s.mean() - 3.5) < 0.05


def test_normal_ks_and_moments():
    s = _draw(nd.random.normal, 1.0, 2.0)
    d, p = stats.kstest((s - 1.0) / 2.0, "norm")
    assert p > 1e-3, (d, p)
    assert abs(s.mean() - 1.0) < 0.06
    assert abs(s.std() - 2.0) < 0.06


def test_gamma_exponential_moments():
    s = _draw(nd.random.gamma, 3.0, 2.0)   # shape k=3, scale θ=2
    assert abs(s.mean() - 6.0) < 0.15      # kθ
    assert abs(s.var() - 12.0) < 1.0       # kθ²
    e = _draw(nd.random.exponential, 0.5)  # scale λ... reference: scale
    assert e.min() >= 0
    assert abs(e.mean() - 0.5) < 0.02


def test_poisson_chisq():
    lam = 4.0
    s = _draw(nd.random.poisson, lam)
    ks = np.arange(0, 12)
    obs = np.array([(s == k).sum() for k in ks], np.float64)
    exp = stats.poisson.pmf(ks, lam) * N
    keep = exp > 5
    chi, p = stats.chisquare(obs[keep], exp[keep] * obs[keep].sum() /
                             exp[keep].sum())
    assert p > 1e-4, (chi, p)


def test_negative_binomial_moments():
    k, p = 5.0, 0.4
    s = _draw(nd.random.negative_binomial, k, p)
    # mean k(1-p)/p, var k(1-p)/p² (reference parameterization:
    # failures before the k-th success)
    assert abs(s.mean() - k * (1 - p) / p) < 0.25, s.mean()
    assert abs(s.var() - k * (1 - p) / p ** 2) < 1.5, s.var()
    g = _draw(nd.random.generalized_negative_binomial, 4.0, 0.25)
    # GNB(mu, alpha): mean mu, var mu + alpha·mu²
    assert abs(g.mean() - 4.0) < 0.2, g.mean()
    assert abs(g.var() - (4.0 + 0.25 * 16.0)) < 1.0, g.var()


def test_randint_uniformity():
    mx.random.seed(0)
    s = nd.random.randint(0, 10, shape=(N,)).asnumpy()
    counts = np.bincount(s.astype(np.int64), minlength=10)
    chi, p = stats.chisquare(counts)
    assert p > 1e-4, (counts, p)
    assert s.min() >= 0 and s.max() <= 9


def test_seed_determinism_and_divergence():
    mx.random.seed(7)
    a = nd.random.normal(0, 1, shape=(64,)).asnumpy()
    mx.random.seed(7)
    b = nd.random.normal(0, 1, shape=(64,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    mx.random.seed(8)
    c = nd.random.normal(0, 1, shape=(64,)).asnumpy()
    assert not np.array_equal(a, c)
    # successive draws differ (stream advances)
    mx.random.seed(7)
    d1 = nd.random.normal(0, 1, shape=(64,)).asnumpy()
    d2 = nd.random.normal(0, 1, shape=(64,)).asnumpy()
    assert not np.array_equal(d1, d2)


def test_multinomial_frequencies():
    mx.random.seed(3)
    probs = nd.array(np.array([0.2, 0.3, 0.5], np.float32))
    s = nd.random.multinomial(probs, shape=(N,)).asnumpy().ravel()
    freq = np.bincount(s.astype(np.int64), minlength=3) / len(s)
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)
