"""Gluon Block/HybridBlock tests.

Modeled on the reference's ``tests/python/unittest/test_gluon.py``†:
layer shapes/values, hybridize≡imperative (fwd and bwd), save/load
round-trips, deferred init. († = canonical upstream path per SURVEY.md.)
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.gluon.block import HybridBlock


class _Dense(HybridBlock):
    def __init__(self, units, in_units=0, **kw):
        super().__init__(**kw)
        self.weight = self.params.get(
            "weight", shape=(units, in_units), allow_deferred_init=True)
        self.bias = self.params.get(
            "bias", shape=(units,), init="zeros")

    def hybrid_forward(self, F, x, weight, bias):
        return F.FullyConnected(x, weight, bias,
                                num_hidden=weight.shape[0])

    def _infer_params(self, x):
        self.weight.shape = (self.weight.shape[0], x.shape[1])


def test_hybridize_takes_cached_path():
    net = _Dense(4, 8)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 8).astype("float32"))
    out_imp = net(x)
    net.hybridize()
    out_hyb = net(x)
    # regression (ADVICE r1): the jit cache must actually be exercised
    assert len(net._cached_entries) == 1
    np.testing.assert_allclose(out_imp.asnumpy(), out_hyb.asnumpy(),
                               rtol=1e-5)
    net(x)
    assert len(net._cached_entries) == 1  # same shape: no recompile
    net(mx.nd.ones((3, 8)))
    assert len(net._cached_entries) == 2  # new shape: new entry


def test_hybridize_gradients_match_imperative():
    net = _Dense(4, 8)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 8).astype("float32"))
    net.hybridize()
    with mx.autograd.record():
        loss = (net(x) * net(x)).sum()
    loss.backward()
    g_hyb = net.weight.grad().asnumpy().copy()
    assert len(net._cached_entries) == 1
    net.hybridize(False)
    with mx.autograd.record():
        loss = (net(x) * net(x)).sum()
    loss.backward()
    np.testing.assert_allclose(g_hyb, net.weight.grad().asnumpy(),
                               rtol=1e-5)


def test_hybridized_dropout_uses_fresh_keys():
    class Drop(HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Dropout(x, p=0.5)

    d = Drop()
    d.hybridize()
    with mx.autograd.record(train_mode=True):
        m1 = d(mx.nd.ones((100,)))
        m2 = d(mx.nd.ones((100,)))
    # regression (ADVICE r1): compiled dropout must not reuse one mask
    assert not np.array_equal(m1.asnumpy(), m2.asnumpy())


def test_deferred_init_through_hybrid_call():
    net = _Dense(3)
    net.initialize()
    net.hybridize()
    out = net(mx.nd.ones((5, 7)))
    assert out.shape == (5, 3)
    assert net.weight.shape == (3, 7)


def test_save_load_parameters_roundtrip(tmp_path):
    net = _Dense(4, 8)
    net.initialize()
    x = mx.nd.ones((2, 8))
    ref = net(x).asnumpy()
    f = str(tmp_path / "dense.params")
    net.save_parameters(f)
    net2 = _Dense(4, 8)
    net2.load_parameters(f)
    np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-6)


def test_train_step_mixed_precision():
    """compute_dtype=bfloat16: bf16 forward/backward, f32 master
    weights and optimizer state; training still converges."""
    import numpy as np
    from mxtpu import nd, parallel
    from mxtpu.gluon import nn, loss as gloss
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1), nn.Flatten(),
            nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9},
        compute_dtype="bfloat16")
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(16, 3, 8, 8).astype(np.float32))
    y = nd.array((rng.rand(16) > 0.5).astype(np.float32))
    losses = [float(step(x, y).asscalar()) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    for p in net.collect_params().values():
        assert p.data().dtype == np.float32, p.name  # master weights
