"""The test harness itself (reference tests exercise
``python/mxnet/test_utils.py``† helpers constantly; these pin the
harness's own behavior)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import test_utils as tu


def test_assert_almost_equal():
    a = np.array([1.0, 2.0, 3.0], np.float32)
    tu.assert_almost_equal(a, a + 1e-7)
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(a, a + 1.0)
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(a, np.zeros((2,), np.float32))


def test_rand_helpers():
    x = tu.rand_ndarray((3, 4))
    assert x.shape == (3, 4)
    s2 = tu.rand_shape_2d()
    assert len(s2) == 2 and all(d >= 1 for d in s2)
    arrs = tu.random_arrays((2, 3), (4,))
    assert arrs[0].shape == (2, 3) and arrs[1].shape == (4,)


def test_check_symbolic_forward_backward():
    sym = mx.sym.var("a") * mx.sym.var("b") + mx.sym.var("a")
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(3, 4).astype(np.float32)
    tu.check_symbolic_forward(sym, {"a": a, "b": b}, [a * b + a])
    og = np.ones((3, 4), np.float32)
    tu.check_symbolic_backward(sym, {"a": a, "b": b}, [og],
                               {"a": b + 1.0, "b": a})


def test_check_numeric_gradient_dense():
    # FullyConnected through the registry — checks the whole
    # bind→forward→backward chain against central differences.
    x = mx.sym.var("x")
    w = mx.sym.var("w")
    bsym = mx.sym.var("bias")
    out = mx.sym.FullyConnected(x, w, bsym, num_hidden=3)
    loc = {"x": np.random.randn(2, 4).astype(np.float64),
           "w": np.random.randn(3, 4).astype(np.float64),
           "bias": np.random.randn(3).astype(np.float64)}
    tu.check_numeric_gradient(out, loc, numeric_eps=1e-4, rtol=1e-2,
                              atol=1e-3)


def test_check_numeric_gradient_nonlinear():
    x = mx.sym.var("x")
    sym = mx.sym.tanh(x)
    loc = {"x": np.random.uniform(-1, 1, (3, 3)).astype(np.float64)}
    tu.check_numeric_gradient(sym, loc, numeric_eps=1e-4, rtol=1e-2,
                              atol=1e-3)


def test_check_consistency_dtypes():
    # Single-backend machine: consistency across dtype variants
    # (f32 baseline vs f16 run) — the harness's cross-run comparison.
    x = mx.sym.var("x")
    w = mx.sym.var("w")
    sym = mx.sym.FullyConnected(x, w, no_bias=True, num_hidden=4)
    params = {"x": np.random.randn(2, 5).astype(np.float32),
              "w": np.random.randn(4, 5).astype(np.float32)}
    tu.check_consistency(
        sym,
        [{"ctx": mx.cpu(), "type_dict": {"x": np.float32, "w": np.float32}},
         {"ctx": mx.cpu(), "type_dict": {"x": np.float16, "w": np.float16}}],
        arg_params=params)


def test_simple_forward():
    sym = mx.sym.relu(mx.sym.var("x"))
    x = np.array([[-1.0, 2.0]], np.float32)
    out = tu.simple_forward(sym, x=x)
    np.testing.assert_allclose(out, [[0.0, 2.0]])


def test_assert_exception():
    tu.assert_exception(lambda: 1 / 0, ZeroDivisionError)
    with pytest.raises(AssertionError):
        tu.assert_exception(lambda: None, ValueError)
