"""mxtpu.serving — dynamic-batching inference layer (ISSUE 4).

The batcher tests are fully deterministic: the policy is pure
(``submit``/``poll``) and driven by an injected clock, so no test here
depends on wall-clock timing except the server end-to-end ones (which
assert outcomes, not latencies) and the slow-marked soak.
"""
import threading

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import symbol as sym
from mxtpu.base import MXNetError
from mxtpu.serving import (DynamicBatcher, InferenceServer, ModelRunner,
                           RequestTimeout, ServerBusy, ServingStats,
                           WorkerLost, batch_ladder)


class FakeClock:
    """Hand-stepped monotonic clock for deterministic batcher tests."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _mul_runner(**kwargs):
    """Per-row-independent graph with one real weight: out = data * w."""
    data = sym.var("data")
    w = sym.var("w")
    graph = data * w
    return ModelRunner(graph, {"w": np.array([1.0, 2.0, 3.0],
                                             np.float32)},
                       {"data": (3,)}, max_batch_size=4, **kwargs)


def _token_runner(**kwargs):
    """Per-token-independent token model: out = data * 3 (padding can
    only pollute rows/positions scatter must not return)."""
    graph = sym.var("data") * 3.0
    return ModelRunner(graph, {}, {"data": (None,)},
                       seq_buckets=[4, 8], max_batch_size=4, **kwargs)


# ---------------------------------------------------------------- buckets

def test_batch_ladder():
    assert batch_ladder(1) == (1,)
    assert batch_ladder(8) == (1, 2, 4, 8)
    assert batch_ladder(6) == (1, 2, 4, 6)   # cap is always a rung
    with pytest.raises(MXNetError):
        batch_ladder(0)


def test_bucket_selection():
    r = _token_runner()
    assert r.bucket_for(3, 2) == (4, 4)
    assert r.bucket_for(1, 5) == (1, 8)
    assert r.bucket_for(4, 8) == (4, 8)
    assert r.seq_bucket_for(3) == 4
    assert set(r.buckets()) == {(b, s) for b in (1, 2, 4)
                                for s in (4, 8)}
    with pytest.raises(MXNetError, match="exceeds max_batch_size"):
        r.bucket_for(5, 2)
    with pytest.raises(MXNetError, match="exceeds largest bucket"):
        r.bucket_for(1, 9)
    with pytest.raises(MXNetError, match="needs seq_len"):
        r.bucket_for(1)
    with pytest.raises(MXNetError, match="pass seq_buckets"):
        ModelRunner(sym.var("data") * 1.0, {}, {"data": (None,)})


# ---------------------------------------------------------------- batcher

def test_batcher_flush_on_full_batch():
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=4, max_queue_delay_us=2000,
                       clock=fc)
    reqs = [b.submit(i) for i in range(4)]
    batch = b.poll()            # full → flushes with zero delay
    assert batch is not None and len(batch) == 4
    assert [r.payload for r in batch.requests] == [0, 1, 2, 3]  # FIFO
    assert all(r.t_dequeue == fc.t for r in reqs)
    assert b.poll() is None     # queue drained


def test_batcher_flush_on_delay_and_batch1_degradation():
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=4, max_queue_delay_us=2000,
                       clock=fc)
    b.submit("lone")
    assert b.poll() is None                 # not full, not overdue
    fc.advance(0.0019)
    assert b.poll() is None                 # still 100us early
    fc.advance(0.0002)
    batch = b.poll()                        # overdue → ships alone
    assert batch is not None and len(batch) == 1
    assert batch.requests[0].payload == "lone"


def test_batcher_groups_never_mix():
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=4, max_queue_delay_us=1000,
                       clock=fc)
    b.submit("a0", group=4)
    b.submit("b0", group=8)
    b.submit("a1", group=4)
    fc.advance(0.002)
    first = b.poll()            # head's group (4), in FIFO order
    assert first.group == 4
    assert [r.payload for r in first.requests] == ["a0", "a1"]
    second = b.poll()           # new head (group 8) is overdue too
    assert second.group == 8
    assert [r.payload for r in second.requests] == ["b0"]


def test_batcher_deadline_expiry_while_queued():
    fc = FakeClock()
    expired = []
    b = DynamicBatcher(max_batch_size=4, max_queue_delay_us=10_000,
                       clock=fc, on_timeout=expired.append)
    doomed = b.submit("x", timeout_s=0.001)
    alive = b.submit("y", timeout_s=10.0)
    fc.advance(0.002)
    assert b.poll() is None     # doomed dropped; alive not overdue yet
    assert doomed.done()
    with pytest.raises(RequestTimeout):
        doomed.result(timeout=0)
    assert expired == [1]
    fc.advance(0.01)
    batch = b.poll()
    assert [r.payload for r in batch.requests] == ["y"]
    assert alive in batch.requests


def test_batcher_late_result_becomes_timeout_not_stale():
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=1, max_queue_delay_us=0, clock=fc)
    req = b.submit("x", timeout_s=0.5)
    batch = b.poll()
    assert len(batch) == 1
    # batch executed, but the result lands after the deadline: the
    # caller must see RequestTimeout, never the stale payload
    req._complete("stale", now=fc.t + 1.0)
    with pytest.raises(RequestTimeout, match="missed its deadline"):
        req.result(timeout=0)
    # one-shot: a later write cannot overwrite the outcome
    assert not req._complete("late again", now=fc.t)

    ok = b.submit("y", timeout_s=0.5)
    assert ok._complete("fresh", now=fc.t + 0.1)
    assert ok.result(timeout=0) == "fresh"
    assert ok.latency_us == pytest.approx(0.1e6)


def test_batcher_bounded_queue_server_busy():
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=2, max_queue_delay_us=1e6,
                       max_queue=3, clock=fc)
    for i in range(3):
        b.submit(i, group=i)    # distinct groups: nothing flushes
    with pytest.raises(ServerBusy, match="queue full"):
        b.submit(99, group=99)
    assert b.depth == 3 and b.peak_depth == 3


def test_batcher_close_fails_queued():
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=4, max_queue_delay_us=1e6,
                       clock=fc)
    req = b.submit("x")
    b.close()
    with pytest.raises(WorkerLost, match="closed"):
        req.result(timeout=0)
    with pytest.raises(WorkerLost, match="closed"):
        b.submit("y")


def test_batcher_close_fails_inflight():
    """ISSUE 7 no-hung-waiters fix: a request already PULLED into a
    batch when the worker dies must fail too — before, only the queue
    was failed and result() hung forever."""
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=2, max_queue_delay_us=0,
                       clock=fc)
    r1 = b.submit("x")
    r2 = b.submit("y")
    batch = b.poll(fc())                  # dispatched, not completed
    assert batch is not None and len(batch) == 2
    b.close()
    for r in (r1, r2):
        assert r.done()
        with pytest.raises(WorkerLost):
            r.result(timeout=0)


def test_batcher_requeue_once_with_original_accounting():
    """A failed batch re-enters the queue exactly once, at the FRONT,
    with its original deadline and t_submit intact — queue_us spans
    submit -> final dequeue."""
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=2, max_queue_delay_us=0,
                       clock=fc)
    old = b.submit("old", timeout_s=10.0)
    fc.advance(0.5)
    batch = b.poll(fc())
    assert batch.requests == [old]
    newer = b.submit("new")
    assert b.requeue(batch.requests) == 1
    assert old.requeues == 1
    assert old.t_dequeue is None          # accounting reset, t_submit
    assert old.deadline == 100.0 + 10.0   # and deadline preserved
    fc.advance(0.5)
    again = b.poll(fc())                  # front of the queue: the
    assert again.requests[0] is old       # requeued one beats `newer`
    old._complete("v", fc())
    assert old.queue_us == pytest.approx(1.0 * 1e6)  # submit->redequeue
    assert newer in again.requests or b.depth == 1


def test_batcher_requeue_second_failure_is_worker_lost():
    fc = FakeClock()
    b = DynamicBatcher(max_batch_size=1, max_queue_delay_us=0,
                       clock=fc)
    r = b.submit("x")
    b.requeue(b.poll(fc()).requests)
    assert b.requeue(b.poll(fc()).requests) == 0   # burned its retry
    assert r.done()
    with pytest.raises(WorkerLost, match="again"):
        r.result(timeout=0)


def test_batcher_requeue_expired_deadline_times_out_not_loops():
    fc = FakeClock()
    timeouts = []
    b = DynamicBatcher(max_batch_size=1, max_queue_delay_us=0,
                       clock=fc, on_timeout=lambda n: timeouts.append(n))
    r = b.submit("x", timeout_s=0.1)
    batch = b.poll(fc())
    fc.advance(0.5)                       # deadline passes mid-flight
    assert b.requeue(batch.requests) == 0
    assert b.depth == 0                   # expired, NOT re-enqueued
    with pytest.raises(RequestTimeout):
        r.result(timeout=0)
    assert timeouts == [1]


def test_batcher_wait_next_blocks_until_submit():
    b = DynamicBatcher(max_batch_size=2, max_queue_delay_us=0)
    got = []
    t = threading.Thread(
        target=lambda: got.append(b.wait_next(timeout=5.0)))
    t.start()
    b.submit("x")
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert got and len(got[0]) == 1
    assert b.wait_next(timeout=0.01) is None   # empty → timeout → None


# ----------------------------------------------------------------- runner

def test_runner_exact_outputs_across_buckets():
    r = _mul_runner()
    w = np.array([1.0, 2.0, 3.0], np.float32)
    rng = np.random.RandomState(0)
    for n in (1, 3, 4):         # buckets (1,None),(4,None),(4,None)
        x = rng.randn(n, 3).astype(np.float32)
        (out,) = r.infer({"data": x})
        assert out.shape == (n, 3)   # sliced back from the bucket
        np.testing.assert_allclose(out, x * w, rtol=1e-6, atol=1e-6)
    assert r.num_compiled() == 2     # (1,) and (4,) — (3→4 shared)


def test_runner_weights_uploaded_once_shared_across_buckets():
    """The MXPredReshape zero-copy contract: one device upload feeds
    every bucket executable — compiling/warming the whole ladder must
    not touch or copy the weight buffers."""
    r = _mul_runner()
    bufs = r.weight_buffers()
    assert len(bufs) == 1
    ptrs = [b.unsafe_buffer_pointer() for b in bufs]
    secs = r.warmup()                # compiles the full ladder
    assert r.num_compiled() == len(r.buckets()) == 3
    assert all(c > 0 for c in secs.values())
    x = np.ones((4, 3), np.float32)
    r.infer({"data": x})
    r.infer({"data": x[:1]})
    after = r.weight_buffers()
    assert all(a is b for a, b in zip(bufs, after))      # same arrays
    assert [b.unsafe_buffer_pointer() for b in after] == ptrs  # same mem


def test_runner_pad_scatter_roundtrip():
    """Mixed-length requests through pad → run → scatter: every request
    gets exactly its own rows, trimmed back to its true length."""
    fc = FakeClock()
    r = _token_runner()
    b = DynamicBatcher(max_batch_size=4, max_queue_delay_us=0, clock=fc)
    lens = [2, 3, 4]
    rows = [np.arange(10 * i, 10 * i + n).astype(np.float32)
            for i, n in enumerate(lens)]
    reqs = [b.submit({"data": row}, group=r.seq_bucket_for(n),
                     seq_len=n)
            for row, n in zip(rows, lens)]
    bucket, _ = r.run_requests(b.poll().requests, now=fc.t)
    assert bucket == (4, 4)
    for req, row, n in zip(reqs, rows, lens):
        (out,) = req.result(timeout=0)
        assert out.shape == (n,)            # padded tail trimmed
        np.testing.assert_allclose(out, row * 3.0, rtol=1e-6)
    # second group: longer sequences land in the (·, 8) bucket
    long_row = np.arange(7).astype(np.float32)
    req = b.submit({"data": long_row}, group=r.seq_bucket_for(7),
                   seq_len=7)
    bucket, _ = r.run_requests(b.poll().requests, now=fc.t)
    assert bucket == (1, 8)
    np.testing.assert_allclose(req.result(timeout=0)[0], long_row * 3.0,
                               rtol=1e-6)
    with pytest.raises(MXNetError, match="exceeds bucket"):
        r._pad_stack([{"data": np.zeros(9, np.float32)}], (1, 8))


def test_runner_export_artifacts_roundtrip(tmp_path):
    """from_export loads gluon export artifacts through the c_predict
    params path and matches the in-process net."""
    from mxtpu import nd
    from mxtpu.gluon import nn
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize(init="xavier")
    x = np.random.RandomState(0).randn(3, 5).astype(np.float32)
    y0 = net(nd.array(x)).asnumpy()
    sym_file, param_file = net.export(str(tmp_path / "m"))
    r = ModelRunner.from_export(sym_file, param_file,
                                input_specs={"data": (5,)},
                                max_batch_size=4)
    (out,) = r.infer({"data": x})
    np.testing.assert_allclose(out, y0, rtol=1e-5, atol=1e-5)


# ----------------------------------------------------------------- server

def test_server_end_to_end_round_robin():
    import jax
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    runners = [_mul_runner(device=devs[0]), _mul_runner(device=devs[1])]
    w = np.array([1.0, 2.0, 3.0], np.float32)
    rng = np.random.RandomState(1)
    rows = [rng.randn(3).astype(np.float32) for _ in range(12)]
    with InferenceServer() as server:
        server.register("mul", runners, max_queue_delay_us=500)
        assert server.models() == {"mul": [1]}
        reqs = [server.submit("mul", {"data": row}, timeout_s=60.0)
                for row in rows]
        for req, row in zip(reqs, rows):
            (out,) = req.result(timeout=60.0)
            np.testing.assert_allclose(out, row * w, rtol=1e-6,
                                       atol=1e-6)
        # completions are recorded by the worker just AFTER futures
        # resolve — give the counters a beat to settle
        import time
        for _ in range(100):
            snap = server.stats("mul")
            if snap["completed"] == 12:
                break
            time.sleep(0.02)
        assert snap["completed"] == 12
        assert snap["timed_out"] == 0 and snap["rejected"] == 0
        assert snap["replicas"] == 2
        d = snap["dispatched_per_replica"]
        assert sum(d.values()) == snap["batches"]
        assert abs(d[0] - d[1]) <= 1        # round-robin stays even
        assert snap["latency_ms"]["p99"] >= snap["latency_ms"]["p50"] > 0
        assert 0 < snap["batch_fill_rate"] <= 1


def test_server_multi_model_versions():
    r_v1 = _mul_runner()
    data = sym.var("data")
    graph = data * sym.var("w")
    r_v2 = ModelRunner(graph, {"w": np.full(3, 10.0, np.float32)},
                       {"data": (3,)}, max_batch_size=4)
    x = np.ones(3, np.float32)
    with InferenceServer() as server:
        server.register("m", r_v1, version=1, max_queue_delay_us=100)
        server.register("m", r_v2, version=2, max_queue_delay_us=100)
        with pytest.raises(MXNetError, match="already registered"):
            server.register("m", r_v1, version=2)
        np.testing.assert_allclose(
            server.infer("m", {"data": x}, version=1)[0],
            [1.0, 2.0, 3.0], rtol=1e-6)
        np.testing.assert_allclose(          # default = latest version
            server.infer("m", {"data": x})[0], [10.0] * 3, rtol=1e-6)
        assert server.models() == {"m": [1, 2]}
        server.unregister("m", version=2)
        np.testing.assert_allclose(          # latest is v1 again
            server.infer("m", {"data": x})[0], [1.0, 2.0, 3.0],
            rtol=1e-6)
        with pytest.raises(MXNetError, match="unknown model"):
            server.infer("nope", {"data": x})


def test_server_request_timeout_and_stats():
    # delay so long the batch never flushes by itself: the request's
    # own deadline must fire (worker wakes on it) → RequestTimeout
    with InferenceServer() as server:
        server.register("m", _mul_runner(),
                        max_queue_delay_us=30_000_000)
        req = server.submit("m", {"data": np.ones(3, np.float32)},
                            timeout_s=0.05)
        with pytest.raises(RequestTimeout):
            req.result(timeout=10.0)
        snap = server.stats("m")
        assert snap["timed_out"] == 1 and snap["completed"] == 0


def test_server_backpressure_records_rejections():
    with InferenceServer() as server:
        server.register("m", _mul_runner(),
                        max_queue_delay_us=30_000_000, max_queue=2)
        x = {"data": np.ones(3, np.float32)}
        server.submit("m", x, timeout_s=30.0)
        server.submit("m", x, timeout_s=30.0)
        with pytest.raises(ServerBusy):
            server.submit("m", x, timeout_s=30.0)
        snap = server.stats("m")
        assert snap["rejected"] == 1
        assert snap["peak_queue_depth"] == 2


def test_server_emits_profiler_spans(tmp_path):
    import json
    from mxtpu import profiler
    profiler.dumps(reset=True)
    profiler.set_state("run")
    try:
        with InferenceServer() as server:
            server.register("traced", _mul_runner(),
                            max_queue_delay_us=100)
            server.infer("traced", {"data": np.ones(3, np.float32)},
                         timeout_s=30.0)
    finally:
        profiler.set_state("stop")
    events = json.loads(profiler.dumps(reset=True))["traceEvents"]
    spans = [e for e in events if e["name"] == "serve/traced:v1"]
    assert spans and spans[0]["cat"] == "serving"
    assert spans[0]["args"]["batch"] == 1
    assert spans[0]["args"]["bucket"] == [1, None]


# ------------------------------------------------------------------ stats

def test_stats_snapshot_and_speedometer_line():
    fc = FakeClock()
    s = ServingStats(name="m:v1", log_every_s=5.0, clock=fc)
    assert s.maybe_log() is None            # throttled at t=+0
    for i in range(100):
        fc.advance(0.01)
        s.record_completion(latency_us=(i + 1) * 1000.0,
                            queue_us=500.0)
    s.record_batch(3, 4)
    s.record_queue_depth(7)
    s.record_queue_depth(2)
    s.record_rejected()
    s.record_timeout(2)
    snap = s.snapshot()
    assert snap["completed"] == 100
    assert snap["latency_ms"]["p50"] == pytest.approx(50.0, abs=2.0)
    assert snap["latency_ms"]["p99"] == pytest.approx(99.0, abs=2.0)
    assert snap["batch_fill_rate"] == 0.75
    assert snap["queue_depth"] == 2 and snap["peak_queue_depth"] == 7
    assert snap["rejected"] == 1 and snap["timed_out"] == 2
    # ~100 completions over ~1s of fake time
    assert snap["requests_per_sec"] == pytest.approx(100.0, rel=0.1)
    fc.advance(5.0)
    line = s.maybe_log()                    # >5s elapsed → emits
    assert line is not None and "req/sec" in line and "m:v1" in line
    assert s.maybe_log() is None            # throttled again


# ------------------------------------------------------------------- soak

@pytest.mark.slow
def test_server_soak_concurrent_closed_loop_clients():
    """Multi-threaded soak: concurrent closed-loop clients with mixed
    sequence lengths; every accepted request must come back correct
    (its OWN rows), with bounded retries on backpressure."""
    import jax
    devs = jax.devices()
    runners = [_token_runner(device=d) for d in devs[:2]]
    n_clients, n_per_client = 6, 25
    errors = []
    done = [0] * n_clients

    with InferenceServer() as server:
        server.register("tok", runners, max_queue_delay_us=1000,
                        warmup=True)

        def client(cid):
            rng = np.random.RandomState(cid)
            try:
                for j in range(n_per_client):
                    n = int(rng.randint(1, 9))
                    row = rng.randn(n).astype(np.float32)
                    for attempt in range(50):
                        try:
                            req = server.submit("tok", {"data": row},
                                                timeout_s=30.0)
                            break
                        except ServerBusy:
                            import time
                            time.sleep(0.002 * (attempt + 1))
                    else:
                        raise AssertionError("starved by backpressure")
                    (out,) = req.result(timeout=60.0)
                    np.testing.assert_allclose(out, row * 3.0,
                                               rtol=1e-5)
                    done[cid] += 1
            except Exception as e:  # noqa: BLE001 — surface in main
                errors.append((cid, e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not any(t.is_alive() for t in threads), "soak deadlocked"
        assert not errors, errors
        assert done == [n_per_client] * n_clients
        import time
        for _ in range(100):
            snap = server.stats("tok")
            if snap["completed"] == n_clients * n_per_client:
                break
            time.sleep(0.02)
        assert snap["completed"] == n_clients * n_per_client
        assert snap["batches"] >= 1
        assert 0 < snap["batch_fill_rate"] <= 1
