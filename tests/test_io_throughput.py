"""Input-pipeline proof (VERDICT r2 item 8): ImageRecordIter
decode+augment throughput at ResNet shapes, and the process-worker
DataLoader for pure-python transforms.

Reference: src/io/iter_image_recordio_2.cc†,
gluon/data/dataloader.py† (+ cpu_shared_storage_manager.h†).
"""
import logging
import time

import numpy as np
import pytest

from mxtpu import recordio as rio
from mxtpu.gluon.data import DataLoader
from mxtpu.gluon.data.dataset import Dataset
from mxtpu.io import ImageRecordIter

log = logging.getLogger(__name__)


def _pack_imagenet_like(prefix, n=96, size=256):
    rng = np.random.RandomState(0)
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        rec.write_idx(i, rio.pack_img(
            rio.IRHeader(0, float(i % 10), i, 0), img, quality=90))
    rec.close()
    return prefix + ".rec", prefix + ".idx"


def test_imagerecorditer_throughput(tmp_path):
    """Decode + random-crop + mirror + normalize at 224^2: measure
    images/sec and record it (the rate the BASELINE.md input-pipeline
    row cites).  The floor only guards against order-of-magnitude
    regressions — CI boxes vary."""
    rec, idx = _pack_imagenet_like(str(tmp_path / "tp"), n=96)
    it = ImageRecordIter(rec, (3, 224, 224), batch_size=32,
                         path_imgidx=idx, shuffle=True, rand_crop=True,
                         rand_mirror=True, mean_r=123.7, mean_g=116.3,
                         mean_b=103.5, std_r=58.4, std_g=57.1,
                         std_b=57.4, preprocess_threads=4)
    # warmup epoch
    for _ in it:
        pass
    n_img = 0
    t0 = time.perf_counter()
    for _ in range(3):
        it.reset()
        for batch in it:
            n_img += batch.data[0].shape[0] - batch.pad
    dt = time.perf_counter() - t0
    rate = n_img / dt
    log.info("ImageRecordIter: %.0f images/sec (decode+augment, "
             "224^2)", rate)
    # measured ~435 img/s on the 1-core CI box; 120 keeps ~3.5x slack
    # while still catching an order-of-magnitude regression
    assert rate > 120, rate


class _SquareDataset(Dataset):
    """Picklable dataset with a pure-python (GIL-bound) transform —
    the case process workers exist for."""

    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        # deliberately python-heavy transform
        x = [(idx + j) ** 2 % 7 for j in range(32)]
        return np.asarray(x, np.float32), np.float32(idx % 3)


def test_dataloader_process_workers_match_serial():
    ds = _SquareDataset(48)
    serial = list(DataLoader(ds, batch_size=16))
    procs = list(DataLoader(ds, batch_size=16, num_workers=2,
                            worker_type="process"))
    assert len(serial) == len(procs) == 3
    for (sx, sy), (px, py) in zip(serial, procs):
        np.testing.assert_array_equal(sx.asnumpy(), px.asnumpy())
        np.testing.assert_array_equal(sy.asnumpy(), py.asnumpy())


def test_dataloader_process_workers_shuffled_epoch():
    ds = _SquareDataset(40)
    dl = DataLoader(ds, batch_size=8, shuffle=True, num_workers=2,
                    worker_type="process")
    ys = np.concatenate([y.asnumpy() for _, y in dl])
    assert len(ys) == 40


def test_dataloader_worker_type_validation():
    ds = _SquareDataset(8)
    with pytest.raises(Exception):
        DataLoader(ds, batch_size=4, worker_type="fiber")
    with pytest.raises(Exception):
        DataLoader(ds, batch_size=4, worker_type="process",
                   batchify_fn=lambda x: x)


def test_imagerecorditer_seeded_reproducible_with_threads(tmp_path):
    """Seeded augmentation draws happen serially on the consumer, so
    identical seeds give identical batches regardless of decode-pool
    scheduling."""
    rec, idx = _pack_imagenet_like(str(tmp_path / "rep"), n=24,
                                   size=256)

    def epoch(threads):
        it = ImageRecordIter(rec, (3, 224, 224), batch_size=8,
                             path_imgidx=idx, shuffle=True,
                             rand_crop=True, rand_mirror=True,
                             preprocess_threads=threads, seed=3)
        out = [b.data[0].asnumpy() for b in it]
        it.close()
        return np.concatenate(out)

    a = epoch(4)
    b = epoch(4)
    c = epoch(1)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)  # pool size cannot matter


def test_dataloader_process_pool_persists_across_epochs():
    ds = _SquareDataset(32)
    dl = DataLoader(ds, batch_size=8, num_workers=2,
                    worker_type="process")
    list(dl)
    pool = dl._proc_pool
    assert pool is not None
    list(dl)
    assert dl._proc_pool is pool  # same workers, not respawned
    dl.close()
    assert dl._proc_pool is None


# ----------------------------------------------------------------------
# raw (pre-decoded) record fast path: batched assembly + uint8 output
# ----------------------------------------------------------------------


def _pack_raw(prefix, n=64, shape=(3, 32, 32)):
    rng = np.random.RandomState(7)
    imgs = (rng.rand(n, *shape) * 255).astype(np.uint8)
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i % 10), i, 0), imgs[i].tobytes()))
    rec.close()
    return prefix + ".rec", prefix + ".idx", imgs


def test_imagerecorditer_raw_uint8_roundtrip(tmp_path):
    """raw_records=True + dtype='uint8' returns the packed pixels
    bit-exactly, labels included, pad rows cycling from the batch
    head."""
    rec, idx, imgs = _pack_raw(str(tmp_path / "raw"), n=10)
    it = ImageRecordIter(rec, (3, 32, 32), batch_size=4,
                         path_imgidx=idx, shuffle=False,
                         raw_records=True, dtype="uint8")
    got, labels, pads = [], [], []
    for b in it:
        got.append(b.data[0].asnumpy())
        labels.append(b.label[0].asnumpy())
        pads.append(b.pad)
    assert [g.dtype for g in got] == [np.uint8] * 3
    assert pads == [0, 0, 2]
    out = np.concatenate(got)
    np.testing.assert_array_equal(out[:10], imgs)
    np.testing.assert_array_equal(out[10:], imgs[8:10])  # pad cycles
    lab = np.concatenate(labels)[:10]
    np.testing.assert_array_equal(lab.ravel(),
                                  np.arange(10, dtype=np.float32) % 10)


def test_imagerecorditer_raw_batched_matches_per_record(tmp_path):
    """The vectorized batch-assembly path must reproduce the
    per-record loop bit-exactly under identical seeds — shuffle,
    mirror, normalization, uint8 and float32 alike."""
    rec, idx, _ = _pack_raw(str(tmp_path / "par"), n=22)

    def epoch(batched, dtype):
        kw = {}
        if dtype == "float32":
            kw = dict(mean_r=123.7, mean_g=116.3, mean_b=103.5,
                      std_r=58.4, std_g=57.1, std_b=57.4)
        it = ImageRecordIter(rec, (3, 32, 32), batch_size=8,
                             path_imgidx=idx, shuffle=True,
                             rand_mirror=True, seed=11,
                             raw_records=True, dtype=dtype, **kw)
        it._raw_batched = batched
        out = [(b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad)
               for b in it]
        it.close()
        return out

    for dtype in ("uint8", "float32"):
        fast = epoch(True, dtype)
        slow = epoch(False, dtype)
        assert len(fast) == len(slow)
        for (fd, fl, fp), (sd, sl, sp) in zip(fast, slow):
            assert fp == sp
            np.testing.assert_array_equal(fd, sd,
                                          err_msg=f"dtype={dtype}")
            np.testing.assert_array_equal(fl, sl)


def test_imagerecorditer_raw_sequential_no_index(tmp_path):
    """Batched assembly also covers the no-index sequential path."""
    rec, idx, imgs = _pack_raw(str(tmp_path / "seq"), n=12)
    it = ImageRecordIter(rec, (3, 32, 32), batch_size=4,
                         raw_records=True, dtype="uint8")
    out = np.concatenate([b.data[0].asnumpy() for b in it])
    np.testing.assert_array_equal(out, imgs)


def _raw_rate(tmp_path, n=256, batch=64, epochs=3):
    rec, idx, _ = _pack_raw(str(tmp_path / "rate"), n=n,
                            shape=(3, 224, 224))
    it = ImageRecordIter(rec, (3, 224, 224), batch_size=batch,
                         path_imgidx=idx, shuffle=True,
                         rand_mirror=True, raw_records=True,
                         dtype="uint8", preprocess_threads=2)
    for _ in it:  # warmup epoch
        pass
    n_img = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        it.reset()
        for batch_ in it:
            n_img += batch_.data[0].shape[0] - batch_.pad
    dt = time.perf_counter() - t0
    it.close()
    return n_img / dt


def test_imagerecorditer_raw_batched_throughput(tmp_path):
    """Floor for the batched raw path at ResNet shapes.  Measured
    ~5,300 img/s on the 1-core CI box (vs ~2,800 per-record, ~170 for
    the r5 per-record path under bench contention); 500 is a 10x
    cushion that still catches a fall back to per-record Python."""
    rate = _raw_rate(tmp_path)
    log.info("raw batched ImageRecordIter: %.0f images/sec (uint8, "
             "224^2)", rate)
    assert rate > 500, rate


@pytest.mark.slow
def test_imagerecorditer_raw_batched_throughput_strict(tmp_path):
    """Strict variant (excluded from tier-1): the vectorized path
    should hold well above the per-record loop's ~2,800 img/s."""
    rate = _raw_rate(tmp_path, n=512, epochs=5)
    log.info("raw batched ImageRecordIter (strict): %.0f images/sec",
             rate)
    assert rate > 2000, rate
