"""Input-pipeline proof (VERDICT r2 item 8): ImageRecordIter
decode+augment throughput at ResNet shapes, and the process-worker
DataLoader for pure-python transforms.

Reference: src/io/iter_image_recordio_2.cc†,
gluon/data/dataloader.py† (+ cpu_shared_storage_manager.h†).
"""
import logging
import time

import numpy as np
import pytest

from mxtpu import recordio as rio
from mxtpu.gluon.data import DataLoader
from mxtpu.gluon.data.dataset import Dataset
from mxtpu.io import ImageRecordIter

log = logging.getLogger(__name__)


def _pack_imagenet_like(prefix, n=96, size=256):
    rng = np.random.RandomState(0)
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        rec.write_idx(i, rio.pack_img(
            rio.IRHeader(0, float(i % 10), i, 0), img, quality=90))
    rec.close()
    return prefix + ".rec", prefix + ".idx"


def test_imagerecorditer_throughput(tmp_path):
    """Decode + random-crop + mirror + normalize at 224^2: measure
    images/sec and record it (the rate the BASELINE.md input-pipeline
    row cites).  The floor only guards against order-of-magnitude
    regressions — CI boxes vary."""
    rec, idx = _pack_imagenet_like(str(tmp_path / "tp"), n=96)
    it = ImageRecordIter(rec, (3, 224, 224), batch_size=32,
                         path_imgidx=idx, shuffle=True, rand_crop=True,
                         rand_mirror=True, mean_r=123.7, mean_g=116.3,
                         mean_b=103.5, std_r=58.4, std_g=57.1,
                         std_b=57.4, preprocess_threads=4)
    # warmup epoch
    for _ in it:
        pass
    n_img = 0
    t0 = time.perf_counter()
    for _ in range(3):
        it.reset()
        for batch in it:
            n_img += batch.data[0].shape[0] - batch.pad
    dt = time.perf_counter() - t0
    rate = n_img / dt
    log.info("ImageRecordIter: %.0f images/sec (decode+augment, "
             "224^2)", rate)
    assert rate > 50, rate


class _SquareDataset(Dataset):
    """Picklable dataset with a pure-python (GIL-bound) transform —
    the case process workers exist for."""

    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        # deliberately python-heavy transform
        x = [(idx + j) ** 2 % 7 for j in range(32)]
        return np.asarray(x, np.float32), np.float32(idx % 3)


def test_dataloader_process_workers_match_serial():
    ds = _SquareDataset(48)
    serial = list(DataLoader(ds, batch_size=16))
    procs = list(DataLoader(ds, batch_size=16, num_workers=2,
                            worker_type="process"))
    assert len(serial) == len(procs) == 3
    for (sx, sy), (px, py) in zip(serial, procs):
        np.testing.assert_array_equal(sx.asnumpy(), px.asnumpy())
        np.testing.assert_array_equal(sy.asnumpy(), py.asnumpy())


def test_dataloader_process_workers_shuffled_epoch():
    ds = _SquareDataset(40)
    dl = DataLoader(ds, batch_size=8, shuffle=True, num_workers=2,
                    worker_type="process")
    ys = np.concatenate([y.asnumpy() for _, y in dl])
    assert len(ys) == 40


def test_dataloader_worker_type_validation():
    ds = _SquareDataset(8)
    with pytest.raises(Exception):
        DataLoader(ds, batch_size=4, worker_type="fiber")
    with pytest.raises(Exception):
        DataLoader(ds, batch_size=4, worker_type="process",
                   batchify_fn=lambda x: x)


def test_imagerecorditer_seeded_reproducible_with_threads(tmp_path):
    """Seeded augmentation draws happen serially on the consumer, so
    identical seeds give identical batches regardless of decode-pool
    scheduling."""
    rec, idx = _pack_imagenet_like(str(tmp_path / "rep"), n=24,
                                   size=256)

    def epoch(threads):
        it = ImageRecordIter(rec, (3, 224, 224), batch_size=8,
                             path_imgidx=idx, shuffle=True,
                             rand_crop=True, rand_mirror=True,
                             preprocess_threads=threads, seed=3)
        out = [b.data[0].asnumpy() for b in it]
        it.close()
        return np.concatenate(out)

    a = epoch(4)
    b = epoch(4)
    c = epoch(1)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)  # pool size cannot matter


def test_dataloader_process_pool_persists_across_epochs():
    ds = _SquareDataset(32)
    dl = DataLoader(ds, batch_size=8, num_workers=2,
                    worker_type="process")
    list(dl)
    pool = dl._proc_pool
    assert pool is not None
    list(dl)
    assert dl._proc_pool is pool  # same workers, not respawned
    dl.close()
    assert dl._proc_pool is None
