"""mxtpu.guards — runtime guard rails (ISSUE 5).

Covers both rails (recompile-churn detector, no-implicit-transfer
scope), the zero-overhead contract bench.py asserts at import, and the
guarded hot paths end to end: a TrainStep and a ModelRunner must run
transfer-clean and churn-free under MXTPU_GUARDS=2 (strict) on the
JAX_PLATFORMS=cpu test mesh — plus the serving dispatch-tally race
regression the lint's lock-discipline rule surfaced.
"""
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxtpu import guards, nd, parallel
from mxtpu import symbol as sym
from mxtpu.gluon import nn
from mxtpu.guards import ChurnDetector, RecompileChurn
from mxtpu.parallel import restore_params, snapshot_params
from mxtpu.serving import ModelRunner
from mxtpu.serving.server import _Endpoint


# ------------------------------------------------------- churn detector

def test_churn_trips_strict_past_limit():
    det = ChurnDetector("t", limit=3, strict=True)
    for i in range(3):
        det.note_compile(("sig", i))
    with pytest.raises(RecompileChurn, match="recompile churn"):
        det.note_compile(("sig", 3))
    assert det.stats()["tripped"] is True


def test_churn_warns_once_in_warn_mode():
    det = ChurnDetector("t", limit=1, strict=False)
    det.note_compile("a")
    with pytest.warns(RuntimeWarning, match="recompile churn"):
        det.note_compile("b")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        det.note_compile("c")   # already tripped: stays silent


def test_churn_silent_across_steady_state_steps():
    """One compile then 100 cache hits — the healthy profile — must
    never fire."""
    det = ChurnDetector("steady", limit=10, strict=True)
    det.note_compile((4, None))
    for _ in range(100):
        det.note_call()
    s = det.stats()
    assert s["compiles"] == 1 and s["calls"] == 100
    assert s["tripped"] is False


def test_churn_fires_on_deliberately_retracing_fn():
    """A jit entry fed a fresh shape every call retraces every call;
    the detector must trip once compiles pass the limit."""
    det = ChurnDetector("retrace", limit=4, strict=True)
    traces = [0]

    def f(x):
        traces[0] += 1          # executes only when jax (re)traces
        return x * 2.0

    jf = jax.jit(f)
    with pytest.raises(RecompileChurn):
        for n in range(1, 16):
            seen = traces[0]
            jf(jnp.zeros((n,), jnp.float32))
            det.note_call()
            if traces[0] > seen:
                det.note_compile(("f32", (n,)))
    assert det.stats()["compiles"] == 5     # limit + the tripping miss


# ----------------------------------------------------- transfer scope

def test_transfer_scope_blocks_implicit_h2d():
    jf = jax.jit(lambda v: v + 1.0)
    host = np.ones((4,), np.float32)
    jf(jax.device_put(host))                 # compile outside the scope
    with guards.no_implicit_transfers(enabled_override=True):
        jf(jax.device_put(host))             # explicit: allowed
        with pytest.raises(Exception, match="isallow"):
            jf(host)                         # implicit H2D: blocked


def test_disabled_scope_is_shared_nullcontext(monkeypatch):
    monkeypatch.delenv("MXTPU_GUARDS", raising=False)
    monkeypatch.delenv("MXNET_GUARDS", raising=False)
    assert guards.enabled() is False
    a = guards.no_implicit_transfers()
    b = guards.no_implicit_transfers()
    assert a is b is guards._NULL            # zero allocation per step


def test_self_check_both_modes(monkeypatch):
    monkeypatch.delenv("MXTPU_GUARDS", raising=False)
    info = guards.self_check()
    assert info["enabled"] is False and info["strict"] is False
    monkeypatch.setenv("MXTPU_GUARDS", "2")
    info = guards.self_check()
    assert info["enabled"] is True and info["strict"] is True


def test_bench_imports_with_self_check_hook():
    """bench.py runs guards.self_check() at import — importing it must
    succeed with guards off (the default) and leave the hook wired."""
    import bench
    assert "guards.self_check()" in open(bench.__file__).read()


# ------------------------------------------------- guarded TrainStep

def _make_net(x):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False), nn.Dense(4, flatten=False))
    net.initialize(init="xavier")
    net(x)
    return net


def _run_steps(monkeypatch, guards_mode, x, y, snap, steps=4):
    if guards_mode is None:
        monkeypatch.delenv("MXTPU_GUARDS", raising=False)
    else:
        monkeypatch.setenv("MXTPU_GUARDS", guards_mode)
    net = _make_net(x)
    restore_params(net, snap)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "sgd",
        {"learning_rate": 0.05})
    losses = [float(step(x, y).asscalar()) for _ in range(steps)]
    return step, losses


@pytest.fixture()
def _data():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))
    snap = snapshot_params(_make_net(x))
    return x, y, snap


def test_train_step_transfer_clean_under_strict_guards(_data,
                                                       monkeypatch):
    """MXTPU_GUARDS=2: every TrainStep dispatch runs inside
    transfer_guard("disallow") — an implicit host transfer anywhere on
    the step path would raise here."""
    x, y, snap = _data
    step, losses = _run_steps(monkeypatch, "2", x, y, snap)
    assert all(np.isfinite(losses))
    s = step._churn.stats()
    assert s["calls"] == 4 and s["compiles"] >= 1
    assert s["tripped"] is False


def test_guards_change_no_training_semantics(_data, monkeypatch):
    """The bench.py contract, end to end: identical params + data give
    bit-identical losses with guards off and strict."""
    x, y, snap = _data
    _, bare = _run_steps(monkeypatch, None, x, y, snap)
    _, strict_ = _run_steps(monkeypatch, "2", x, y, snap)
    assert bare == strict_


# ------------------------------------------------ guarded ModelRunner

def test_model_runner_warmup_and_infer_under_strict_guards(monkeypatch):
    monkeypatch.setenv("MXTPU_GUARDS", "2")
    graph = sym.var("data") * sym.var("w")
    r = ModelRunner(graph, {"w": np.array([1.0, 2.0, 3.0], np.float32)},
                    {"data": (3,)}, max_batch_size=4)
    secs = r.warmup()                      # AOT compiles inside the scope
    assert set(secs) == set(r.buckets())
    out = r.infer({"data": np.ones((2, 3), np.float32)})
    np.testing.assert_allclose(
        out[0], np.tile([1.0, 2.0, 3.0], (2, 1)))
    s = r._churn.stats()
    assert s["compiles"] == len(r.buckets())
    assert s["tripped"] is False           # ladder fits under the limit


# -------------------------------------- serving race regression (lint)

def test_dispatch_counts_is_race_free():
    """Regression for the lock-discipline finding: stats() used to
    read ``_Endpoint.dispatched`` bare while workers increment it in
    ``_next_runner``.  Hammer both sides concurrently; the locked
    snapshot must never tear and the final tally must be exact."""
    runner = ModelRunner(sym.var("data") * 2.0, {}, {"data": (2,)},
                         max_batch_size=2)
    ep = _Endpoint("m", 1, [runner, runner],
                   max_queue_delay_us=1000.0, max_queue=None,
                   log_every_s=60.0)      # workers NOT started
    N, T = 400, 4
    errs = []

    def hammer():
        try:
            for _ in range(N):
                ep._next_runner()
        except Exception as e:              # pragma: no cover
            errs.append(e)

    def snapshot():
        try:
            for _ in range(N):
                c = ep.dispatch_counts()
                assert sum(c.values()) <= N * T
        except Exception as e:              # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(T)] + \
              [threading.Thread(target=snapshot) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errs == []
    counts = ep.dispatch_counts()
    assert sum(counts.values()) == N * T
    assert len(counts) == 2
