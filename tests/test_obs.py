"""mxtpu.obs — unified metrics registry, end-to-end request tracing,
and the fleet flight recorder (ISSUE 8).

Three suites:

* **registry** — typed instruments, label sets, naming enforcement,
  the Prometheus-text / JSON-snapshot round-trip, and the shared
  no-op singletons behind ``MXTPU_OBS=0``;
* **tracing** — profiler state-machine fixes (satellite 2), concurrent
  recorder JSON validity, and THE acceptance scenario: a fleet request
  surviving a scripted worker kill whose full life (submit →
  queue-wait → steal → backoff → re-dispatch → execute) reconstructs
  from a single trace dump via one trace id — deterministic, fake
  clock, no sleeps;
* **flight recorder** — bounded ring semantics, automatic dump on
  worker death, ``MXTPU_OBS_DUMP_ON_ERROR``, and
  ``FleetRouter.postmortem``.
"""
import json
import threading

import numpy as np
import pytest

from mxtpu import obs, profiler
from mxtpu.base import MXNetError
from mxtpu.obs.metrics import (MetricsRegistry, NULL_COUNTER,
                               NULL_GAUGE, NULL_HISTOGRAM,
                               parse_prometheus_text,
                               samples_from_snapshot)
from mxtpu.obs.recorder import NULL_RECORDER, FlightRecorder
from mxtpu.serving import CrashAt, FaultPlan, FleetRouter, FleetWorker
from mxtpu.serving.stats import ServingStats

from tests.test_fleet import (FakeClock, _payload, _router, _worker,
                              _crank)


@pytest.fixture(autouse=True)
def _clean_obs():
    """Each test starts from an empty registry and a stopped
    profiler."""
    obs.reset()
    profiler.set_state("stop")
    profiler.dumps(reset=True)
    yield
    profiler.set_state("stop")
    profiler.dumps(reset=True)
    obs.reset()


# ------------------------------------------------------------- registry

def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("mxtpu_widgets_total", "Widgets.")
    c.inc()
    c.inc(4)
    assert c.value() == 5.0
    with pytest.raises(MXNetError):
        c.inc(-1)                      # counters only go up
    g = r.gauge("mxtpu_depth", "Depth.")
    g.set(7)
    g.inc(2)
    g.dec(4)
    assert g.value() == 5.0
    h = r.histogram("mxtpu_wait_seconds", "Wait.",
                    buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 5.0):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 4
    assert s["sum"] == pytest.approx(5.105)
    assert s["mean"] == pytest.approx(5.105 / 4)
    # get-or-create returns the same family
    assert r.counter("mxtpu_widgets_total") is c
    assert r.names() == ["mxtpu_depth", "mxtpu_wait_seconds",
                         "mxtpu_widgets_total"]


def test_registry_conflicts_and_naming():
    r = MetricsRegistry()
    r.counter("mxtpu_x_total", "x", labels=("a",))
    with pytest.raises(MXNetError):
        r.gauge("mxtpu_x_total")       # type conflict
    with pytest.raises(MXNetError):
        r.counter("mxtpu_x_total", labels=("b",))  # labelname conflict
    with pytest.raises(MXNetError):
        r.counter("widgets_total")     # missing mxtpu_ prefix
    with pytest.raises(MXNetError):
        r.counter("mxtpu_widgets")     # counters end _total
    with pytest.raises(MXNetError):
        r.histogram("mxtpu_wait")      # histograms name their unit
    with pytest.raises(MXNetError):
        r.gauge("mxtpu_Bad-Name")      # snake_case only
    with pytest.raises(MXNetError):
        r.counter("mxtpu_x_total", labels=("a",)).labels(b="?")


def test_labeled_children_are_independent():
    r = MetricsRegistry()
    c = r.counter("mxtpu_req_total", "req", labels=("ep", "code"))
    c.labels(ep="a", code="200").inc(3)
    c.labels(ep="a", code="500").inc()
    c.labels(ep="b", code="200").inc(7)
    assert c.labels(ep="a", code="200").value() == 3.0
    assert c.labels(ep="b", code="200").value() == 7.0
    flat = r.summary()
    assert flat['mxtpu_req_total{code="500",ep="a"}'] == 1.0


def test_prometheus_json_round_trip():
    """Acceptance: the text exposition and the JSON snapshot expose
    the SAME sample values, label escaping included."""
    r = MetricsRegistry()
    c = r.counter("mxtpu_ev_total", "Events.", labels=("kind",))
    c.labels(kind='we"ird\\na\nme').inc(2)
    r.gauge("mxtpu_level", "Level.").set(-3.5)
    h = r.histogram("mxtpu_lat_seconds", "Lat.", labels=("ep",),
                    buckets=(0.001, 0.1, 2.0))
    for v in (0.0005, 0.05, 0.05, 7.0):
        h.labels(ep="x").observe(v)
    text = r.prometheus_text()
    assert "# TYPE mxtpu_ev_total counter" in text
    assert "# TYPE mxtpu_lat_seconds histogram" in text
    assert 'le="+Inf"' in text
    left = parse_prometheus_text(text)
    right = samples_from_snapshot(r.snapshot())
    assert left == right and left            # non-empty, identical
    # histogram buckets are cumulative in BOTH surfaces
    key = ("mxtpu_lat_seconds_bucket",
           (("ep", "x"), ("le", "+Inf")))
    assert left[key] == 4.0
    # snapshot JSON-serializes as-is
    json.dumps(r.snapshot())


def test_disabled_factories_return_shared_singletons():
    assert obs.counter("mxtpu_a_total", enabled_override=False) \
        is NULL_COUNTER
    assert obs.gauge("mxtpu_b", enabled_override=False) is NULL_GAUGE
    assert obs.histogram("mxtpu_c_seconds", enabled_override=False) \
        is NULL_HISTOGRAM
    assert obs.flight("w", enabled_override=False) is NULL_RECORDER
    # the no-op child absorbs the full API
    n = NULL_COUNTER.labels(anything="x")
    assert n is NULL_COUNTER
    n.inc()
    NULL_GAUGE.set(5)
    NULL_HISTOGRAM.observe(1.0)
    assert NULL_COUNTER.value() == 0.0
    assert NULL_RECORDER.dump() == ""
    assert NULL_RECORDER.events() == []
    # and nothing lands in the registry
    assert "mxtpu_a_total" not in obs.registry().names()


def test_obs_off_via_knob(monkeypatch):
    monkeypatch.setenv("MXTPU_OBS", "0")
    assert not obs.enabled()
    assert obs.counter("mxtpu_k_total") is NULL_COUNTER
    s = ServingStats(name="off")
    s.record_completion(1000.0, 100.0)
    s.bump("retries")
    assert "mxtpu_serving_completed_total" not in obs.registry().names()
    # local snapshot still works identically
    assert s.snapshot()["completed"] == 1


def test_self_check_contract():
    info = obs.self_check(probe=True)
    assert info["round_trip_samples"] > 0
    assert info["flight_capacity"] == 256


def test_serving_stats_publish_to_registry():
    fc = FakeClock(10.0)
    s = ServingStats(name="ep1", clock=fc)
    for i in range(4):
        s.record_completion(latency_us=2000.0, queue_us=500.0)
    s.record_batch(3, 4)
    s.record_queue_depth(6)
    s.record_rejected(2)
    s.record_timeout()
    s.bump("retries", 3)
    flat = obs.summary()
    assert flat['mxtpu_serving_completed_total{endpoint="ep1"}'] == 4.0
    assert flat['mxtpu_serving_rejected_total{endpoint="ep1"}'] == 2.0
    assert flat['mxtpu_serving_timeout_total{endpoint="ep1"}'] == 1.0
    assert flat['mxtpu_serving_batches_total{endpoint="ep1"}'] == 1.0
    assert flat['mxtpu_serving_batched_requests_total'
                '{endpoint="ep1"}'] == 3.0
    assert flat['mxtpu_serving_padded_slots_total'
                '{endpoint="ep1"}'] == 1.0
    assert flat['mxtpu_serving_queue_depth{endpoint="ep1"}'] == 6.0
    lat = flat['mxtpu_serving_latency_seconds{endpoint="ep1"}']
    assert lat["count"] == 4 and lat["mean"] == pytest.approx(0.002)
    assert flat['mxtpu_fleet_events_total'
                '{endpoint="ep1",kind="retries"}'] == 3.0


def test_rps_prunes_stale_completions_on_read(monkeypatch):
    """Satellite fix: after an idle gap the rate window must empty —
    the old read path counted completions far outside the window."""
    fc = FakeClock(0.0)
    s = ServingStats(name="idle", rate_window_s=30.0, clock=fc)
    for _ in range(50):
        fc.advance(0.01)
        s.record_completion(1000.0)
    assert s.requests_per_sec() > 0
    fc.advance(120.0)               # idle far past the window
    assert s.requests_per_sec() == 0.0


# ------------------------------------------------------ profiler fixes

def test_set_config_rejects_unknown_keys():
    with pytest.raises(MXNetError, match="filname"):
        profiler.set_config(filname="/tmp/x.json")
    profiler.set_config(aggregate_stats=False)   # known key: fine


def test_stop_clears_pause():
    """run → pause → stop → run must collect again (the stale _PAUSED
    bug left the profiler dead until an unpaired resume())."""
    profiler.set_state("run")
    profiler.pause()
    assert not profiler.is_active()
    profiler.set_state("stop")
    profiler.resume()                # resume after stop: no-op
    assert not profiler.is_active()
    profiler.set_state("run")
    assert profiler.is_active()
    profiler.record_span("x", profiler._now_us(), 1.0)
    assert len(profiler.events()) == 1


def test_pause_resume_round_trip():
    profiler.set_state("run")
    profiler.record_span("a", profiler._now_us(), 1.0)
    profiler.pause()
    profiler.record_span("dropped", profiler._now_us(), 1.0)
    profiler.resume()
    profiler.record_span("b", profiler._now_us(), 1.0)
    names = [e["name"] for e in profiler.events()]
    assert names == ["a", "b"]


def test_concurrent_recorders_yield_valid_json():
    """Satellite 3: hammer record_span from several threads while a
    reader repeatedly dumps; every dump must parse, and every event
    must carry pid/tid and a non-negative dur."""
    profiler.set_state("run")
    stop = threading.Event()
    bad = []

    def writer(tid):
        i = 0
        while not stop.is_set():
            t = profiler._now_us()
            profiler.record_span(f"w{tid}/{i % 7}", t, 5.0,
                                 cat="stress", args={"i": i})
            i += 1

    def reader():
        while not stop.is_set():
            try:
                json.loads(profiler.dumps())
            except Exception as e:  # noqa: BLE001
                bad.append(e)

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(4)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    threading.Event().wait(0.3)
    stop.set()
    for t in threads:
        t.join()
    assert not bad
    events = json.loads(profiler.dumps())["traceEvents"]
    assert len(events) > 100
    for ev in events:
        assert ev["pid"] > 0 and ev["tid"] > 0
        assert ev["dur"] >= 0


def test_dumps_reset_keeps_one_epoch():
    """Events recorded after dumps(reset=True) stay on the SAME ts
    epoch, so spans from before and after a drain remain comparable
    in one timeline."""
    profiler.set_state("run")
    profiler.record_span("early", profiler._now_us(), 1.0)
    first = json.loads(profiler.dumps(reset=True))["traceEvents"]
    profiler.record_span("late", profiler._now_us(), 1.0)
    second = json.loads(profiler.dumps(reset=True))["traceEvents"]
    assert [e["name"] for e in first] == ["early"]
    assert [e["name"] for e in second] == ["late"]
    assert second[0]["ts"] >= first[0]["ts"]


# --------------------------------------------- tracing: the kill test

def _fleet(clk, **kw):
    kw.setdefault("canary", False)
    kw.setdefault("backoff_base_us", 10_000)
    kw.setdefault("backoff_cap_us", 50_000)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("hedge_after_us", 0)
    return _router(clk, **kw)


def test_fleet_kill_reconstructs_from_one_dump():
    """THE acceptance scenario: a request whose first worker dies
    mid-flight is fully reconstructible from a single chrome-trace
    dump — every phase span shares the request's one trace id."""
    clk = FakeClock(100.0)
    profiler.set_state("run")
    router = _fleet(clk)
    w0 = _worker(clk, "w0")
    w1 = _worker(clk, "w1")
    w0.faults = FaultPlan(CrashAt(0))     # dies on its first batch
    router.add_worker(w0)
    router.add_worker(w1)

    req = router.submit(_payload(3.0), timeout_s=30.0)
    assert req.trace_id is not None
    _crank(router, clk, n=12, dt=0.05)
    assert req.done()
    np.testing.assert_allclose(np.asarray(req.result()).ravel(),
                               [3.0, 6.0, 9.0])

    # ONE dump; reconstruct offline from its parsed events
    events = json.loads(profiler.dumps())["traceEvents"]
    timeline = obs.trace_of(req.trace_id, events=events)
    names = [e["name"] for e in timeline]
    for span in (obs.SPAN_SUBMIT, obs.SPAN_QUEUE_WAIT, obs.SPAN_STEAL,
                 obs.SPAN_BACKOFF, obs.SPAN_REDISPATCH,
                 obs.SPAN_EXECUTE, obs.SPAN_PAD_SCATTER, obs.SPAN_RUN):
        assert span in names, f"missing {span} in {names}"
    # every span carries THIS trace id (direct or batch-level)
    for e in timeline:
        args = e["args"]
        assert args.get("trace_id") == req.trace_id or \
            req.trace_id in args.get("trace_ids", ())
    # phase ordering on the fleet clock: submit, the doomed attempt's
    # queue wait on w0, steal+backoff, re-dispatch to w1, execute there
    fleet = [e for e in timeline if e["name"].startswith("fleet/")]
    assert fleet == sorted(fleet, key=lambda e: e["ts"])
    by = {e["name"]: e for e in fleet}
    assert by[obs.SPAN_QUEUE_WAIT]["args"]["worker"] == "w0" or \
        any(e["args"]["worker"] == "w0" for e in fleet
            if e["name"] == obs.SPAN_QUEUE_WAIT)
    assert by[obs.SPAN_STEAL]["args"]["worker"] == "w0"
    assert by[obs.SPAN_REDISPATCH]["args"]["worker"] == "w1"
    assert by[obs.SPAN_EXECUTE]["args"]["worker"] == "w1"
    assert by[obs.SPAN_BACKOFF]["dur"] == pytest.approx(10_000.0)
    # the live-API timeline matches the offline reconstruction
    assert [e["name"] for e in obs.trace_of(req.trace_id)] == names


def test_trace_ids_are_unique_and_absent_when_stopped():
    clk = FakeClock(100.0)
    router = _fleet(clk)
    router.add_worker(_worker(clk, "w0"))
    r1 = router.submit(_payload(1.0))     # profiler stopped
    assert r1.trace_id is None
    profiler.set_state("run")
    r2 = router.submit(_payload(1.0))
    r3 = router.submit(_payload(1.0))
    assert r2.trace_id and r3.trace_id and r2.trace_id != r3.trace_id
    _crank(router, clk)
    assert r1.done() and r2.done() and r3.done()


def test_trace_of_unknown_id_is_empty():
    profiler.set_state("run")
    profiler.record_span("x", profiler._now_us(), 1.0,
                         args={"trace_id": "r-other"})
    assert obs.trace_of("r-nope") == []


def test_obs_off_results_bit_identical(monkeypatch):
    """Zero-overhead contract end to end: the SAME fleet scenario with
    MXTPU_OBS=0 produces bit-identical outputs and fleet counters."""
    def run_once():
        clk = FakeClock(100.0)
        router = _fleet(clk)
        w0 = _worker(clk, "w0")
        w0.faults = FaultPlan(CrashAt(0))
        router.add_worker(w0)
        router.add_worker(_worker(clk, "w1"))
        req = router.submit(_payload(2.5), timeout_s=30.0)
        _crank(router, clk, n=12, dt=0.05)
        snap = router.fleet_stats()
        return np.asarray(req.result()), snap["extras"]

    out_on, extras_on = run_once()
    obs.reset()
    monkeypatch.setenv("MXTPU_OBS", "0")
    out_off, extras_off = run_once()
    assert out_on.tobytes() == out_off.tobytes()
    assert extras_on == extras_off
    assert obs.registry().names() == []   # off: registry untouched


# ------------------------------------------------------ flight recorder

def test_flight_recorder_ring_and_dump(tmp_path):
    fc = FakeClock(5.0)
    rec = FlightRecorder("fleet/w9", capacity=3, clock=fc)
    for k in range(5):
        fc.advance(1.0)
        rec.record("ev", k=k)
    evs = rec.events()
    assert [e["k"] for e in evs] == [2, 3, 4]     # bounded ring
    snap = rec.snapshot()
    assert snap["dropped"] == 2 and snap["capacity"] == 3
    text = rec.dump(reason="test", path=str(tmp_path))
    parsed = json.loads(text)
    assert parsed["reason"] == "test"
    assert [e["k"] for e in parsed["events"]] == [2, 3, 4]
    files = list(tmp_path.glob("flight_*.json"))
    assert len(files) == 1
    assert json.loads(files[0].read_text())["recorder"] == "fleet/w9"
    rec.clear()
    assert rec.events() == []


def test_flight_capacity_knob(monkeypatch):
    monkeypatch.setenv("MXTPU_OBS_FLIGHT_CAPACITY", "2")
    rec = FlightRecorder("small")
    for k in range(4):
        rec.record("e", k=k)
    assert len(rec.events()) == 2


def test_worker_death_dumps_flight_recorder(tmp_path, monkeypatch):
    """Worker dies → its ring holds the health transition, the fault,
    and the death event, and MXTPU_OBS_DUMP_ON_ERROR writes the dump
    as a file."""
    monkeypatch.setenv("MXTPU_OBS_DUMP_ON_ERROR", str(tmp_path))
    clk = FakeClock(100.0)
    router = _fleet(clk)
    w0 = _worker(clk, "w0")
    w0.faults = FaultPlan(CrashAt(0))
    router.add_worker(w0)
    router.add_worker(_worker(clk, "w1"))
    req = router.submit(_payload(1.0), timeout_s=30.0)
    _crank(router, clk, n=12, dt=0.05)
    assert req.done()

    pm = router.postmortem("w0")
    kinds = [e["kind"] for e in pm["flight"]["events"]]
    assert kinds == ["health", "fault", "death"]
    assert pm["health"]["state"] == "dead"
    assert pm["flight"]["events"][1]["fault"] == "crash"
    assert pm["flight"]["events"][2]["reason"].startswith(
        "scripted crash")
    # the automatic on-death dump landed on disk
    dumps = list(tmp_path.glob("flight_fleet_w0*.json"))
    assert dumps, list(tmp_path.iterdir())
    on_disk = json.loads(dumps[0].read_text())
    assert [e["kind"] for e in on_disk["events"]] == kinds


def test_canary_verdicts_and_evictions_reach_recorder():
    from mxtpu.serving.runner import ModelRunner  # noqa: F401
    clk = FakeClock(100.0)
    router = _router(clk, canary=True, canary_interval_s=1.0)
    w0 = _worker(clk, "w0")
    router.add_worker(w0)
    _crank(router, clk, n=5, dt=1.0)      # several canary rounds
    kinds = [e["kind"] for e in w0.recorder.events()]
    assert "canary" in kinds
    ok = [e for e in w0.recorder.events() if e["kind"] == "canary"]
    assert all(e["ok"] for e in ok)


def test_compile_misses_reach_flight_and_registry():
    from mxtpu import guards
    det = guards.ChurnDetector("probe_entry", limit=100)
    det.note_compile("sig0")
    det.note_compile("sig1")
    flat = obs.summary()
    assert flat['mxtpu_compile_cache_miss_total'
                '{entry="probe_entry"}'] == 2.0


def test_dump_all_collects_every_recorder(tmp_path):
    obs.flight("fleet/a").record("x", n=1)
    obs.flight("fleet/b").record("y", n=2)
    dumped = obs.dump_all(reason="test", path=str(tmp_path))
    assert sorted(dumped) == ["fleet/a", "fleet/b"]
    assert len(list(tmp_path.glob("flight_*.json"))) == 2
