"""Fused BatchNorm(+Add)+ReLU — kernel, op, and layer tiers
(VERDICT r4 item 1; reference fused ``BatchNormAddRelu``
``src/operator/nn/batch_norm.cu``†, SURVEY §2.1-N8).

The Pallas path runs in interpreter mode here (MXTPU_FUSED_BN=1 +
MXTPU_PALLAS=interpret); the real-chip perf verdict lives in
BASELINE.md ("Fused-BN verdict") with tools/probe_bn_fusion.py as the
measurement harness.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxtpu.kernels.batch_norm import (_pick_cb, bn_act_reference,
                                      fused_bn_act)


@pytest.fixture
def pallas_interpret(monkeypatch):
    monkeypatch.setenv("MXTPU_PALLAS", "interpret")
    monkeypatch.setenv("MXTPU_FUSED_BN", "1")


def _grad_compare(act, add, shape=(4, 32, 6, 6), dtype=jnp.float32,
                  tol=5e-4):
    rng = np.random.RandomState(0)
    C = shape[1]
    x = jnp.array(rng.randn(*shape), dtype)
    g = jnp.array(rng.rand(C).astype(np.float32) + 0.5)
    b = jnp.array(rng.randn(C).astype(np.float32))
    r = jnp.array(rng.randn(*shape), dtype) if add else None
    argnums = (0, 1, 2) + ((3,) if add else ())

    def f_fused(x, g, b, r):
        y, m, v = fused_bn_act(x, g, b, act=act, residual=r)
        return jnp.sum(jnp.square(y.astype(jnp.float32))), (y, m, v)

    def f_ref(x, g, b, r):
        y, m, v = bn_act_reference(x, g, b, act=act, residual=r)
        return jnp.sum(jnp.square(y.astype(jnp.float32))), (y, m, v)

    (_, (yf, mf, vf)), gf = jax.value_and_grad(
        f_fused, argnums=argnums, has_aux=True)(x, g, b, r)
    (_, (yr, mr, vr)), gr = jax.value_and_grad(
        f_ref, argnums=argnums, has_aux=True)(x, g, b, r)
    np.testing.assert_allclose(np.asarray(yf, np.float32),
                               np.asarray(yr, np.float32), atol=tol)
    np.testing.assert_allclose(mf, mr, atol=tol)
    np.testing.assert_allclose(vf, vr, atol=tol)
    for a, bb in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(bb, np.float32),
                                   atol=tol * 5)


@pytest.mark.parametrize("act,add", [("none", False), ("relu", False),
                                     ("relu", True)])
def test_kernel_parity_interpret(pallas_interpret, act, add):
    _grad_compare(act, add)


def test_kernel_parity_bf16(pallas_interpret):
    _grad_compare("relu", True, dtype=jnp.bfloat16, tol=5e-2)


def test_kernel_infeasible_shape_falls_back(pallas_interpret,
                                            monkeypatch):
    # tiny VMEM cap -> _pick_cb returns None -> composite path; the
    # public fn must stay correct either way
    monkeypatch.setenv("MXTPU_BN_VMEM_CAP_MB", "1")
    assert _pick_cb(256, 64, 3136, 2, 14) is None
    _grad_compare("relu", True)


def test_kernel_parity_3d(pallas_interpret):
    # (N, C, T) sequence layout — axis 1, ndim 3
    _grad_compare("relu", False, shape=(8, 16, 32))


def test_disabled_env_uses_composite(monkeypatch):
    # default (no env): composite path, still correct
    monkeypatch.delenv("MXTPU_FUSED_BN", raising=False)
    _grad_compare("relu", True)


# ---------------------------------------------------------------------
# op tier
# ---------------------------------------------------------------------

def test_ops_match_unfused_composition():
    from mxtpu import autograd, nd
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(4, 8, 5, 5).astype(np.float32))
    res = nd.array(rng.randn(4, 8, 5, 5).astype(np.float32))
    gamma = nd.array(rng.rand(8).astype(np.float32) + 0.5)
    beta = nd.array(rng.randn(8).astype(np.float32))
    mm = nd.zeros((8,))
    mv = nd.ones((8,))

    with autograd.record():
        y1, m1, v1 = nd.BatchNorm(x, gamma, beta, mm, mv,
                                  fix_gamma=False)
        out1 = nd.relu(y1 + res)
    with autograd.record():
        out2, m2, v2 = nd.BatchNormAddRelu(x, res, gamma, beta, mm, mv,
                                           fix_gamma=False)
    np.testing.assert_allclose(out1.asnumpy(), out2.asnumpy(),
                               atol=1e-5)
    np.testing.assert_allclose(m1.asnumpy(), m2.asnumpy(), atol=1e-6)
    np.testing.assert_allclose(v1.asnumpy(), v2.asnumpy(), atol=1e-6)

    out3, _, _ = nd.BatchNormRelu(x, gamma, beta, mm, mv,
                                  fix_gamma=False)
    ref3 = nd.relu(nd.BatchNorm(x, gamma, beta, mm, mv,
                                fix_gamma=False)[0])
    np.testing.assert_allclose(out3.asnumpy(), ref3.asnumpy(),
                               atol=1e-5)


def test_op_inference_mode_uses_running_stats():
    from mxtpu import nd
    rng = np.random.RandomState(2)
    x = nd.array(rng.randn(2, 4, 3, 3).astype(np.float32))
    gamma = nd.array(rng.rand(4).astype(np.float32) + 0.5)
    beta = nd.array(rng.randn(4).astype(np.float32))
    mm = nd.array(rng.randn(4).astype(np.float32) * 0.1)
    mv = nd.array(rng.rand(4).astype(np.float32) + 0.5)
    out, _, _ = nd.BatchNormRelu(x, gamma, beta, mm, mv,
                                 fix_gamma=False,
                                 use_global_stats=True)
    xn = x.asnumpy()
    sc = (gamma.asnumpy() / np.sqrt(mv.asnumpy() + 1e-5))
    ref = (xn - mm.asnumpy().reshape(1, -1, 1, 1)) * \
        sc.reshape(1, -1, 1, 1) + beta.asnumpy().reshape(1, -1, 1, 1)
    np.testing.assert_allclose(out.asnumpy(), np.maximum(ref, 0),
                               atol=1e-5)


# ---------------------------------------------------------------------
# layer + model tier
# ---------------------------------------------------------------------

def test_layer_fused_equals_sequence():
    from mxtpu import autograd, nd
    from mxtpu.gluon import nn
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(4, 6, 7, 7).astype(np.float32))
    res = nd.array(rng.randn(4, 6, 7, 7).astype(np.float32))

    fused = nn.BatchNorm(axis=1, act_type="relu", in_channels=6,
                         prefix="f_")
    plain = nn.BatchNorm(axis=1, in_channels=6, prefix="p_")
    fused.initialize()
    plain.initialize()
    # share parameters/statistics
    plain.gamma.set_data(fused.gamma.data())
    plain.beta.set_data(fused.beta.data())

    with autograd.record(train_mode=True):
        y1 = fused(x, res)
    with autograd.record(train_mode=True):
        y2 = nd.relu(plain(x) + res)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), atol=1e-5)
    # running stats updated identically
    np.testing.assert_allclose(fused.running_mean.data().asnumpy(),
                               plain.running_mean.data().asnumpy(),
                               atol=1e-6)

    # inference mode follows running stats + relu
    y3 = fused(x, res)
    sc = 1.0 / np.sqrt(fused.running_var.data().asnumpy() + 1e-5)
    ref = (x.asnumpy() -
           fused.running_mean.data().asnumpy().reshape(1, -1, 1, 1)) \
        * (fused.gamma.data().asnumpy() * sc).reshape(1, -1, 1, 1) \
        + fused.beta.data().asnumpy().reshape(1, -1, 1, 1) \
        + res.asnumpy()
    np.testing.assert_allclose(y3.asnumpy(), np.maximum(ref, 0),
                               atol=1e-4)


def test_resnet_blocks_train_and_converge():
    from mxtpu import autograd, nd
    from mxtpu.gluon import loss as gloss
    from mxtpu.gluon.model_zoo.vision import resnet18_v1, resnet18_v2
    rng = np.random.RandomState(4)
    for ctor in (resnet18_v1, resnet18_v2):
        net = ctor(classes=10)
        net.initialize(init="xavier")
        x = nd.array(rng.randn(2, 3, 32, 32).astype(np.float32))
        y = nd.array(rng.randint(0, 10, (2,)).astype(np.float32))
        lfn = gloss.SoftmaxCrossEntropyLoss()
        with autograd.record():
            loss = lfn(net(x), y)
        loss.backward()
        lv = float(loss.asnumpy().mean())
        assert np.isfinite(lv)
        # gradients reach the first conv through the fused BN chain
        from mxtpu.gluon import nn as gnn
        first_conv = next(c for c in net.features._children.values()
                          if isinstance(c, gnn.Conv2D))
        g = first_conv.weight.grad()
        assert g is not None and np.isfinite(g.asnumpy()).all() \
            and float(np.abs(g.asnumpy()).max()) > 0
