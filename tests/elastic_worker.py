"""Worker for the preemption/restart recovery test (SURVEY §5.3).

The reference's recovery story is checkpoint + full restart (a dead
ps-lite worker killed the job; ``tools/kill-mxnet.py``† existed to mop
up).  The TPU-native equivalent: preemption-safe checkpoints every
step + coordinator restart of the WHOLE SPMD job — elastically
shrinking mid-collective is impossible by design (documented).

phase=crash : run 3 steps, checkpoint, rank 1 exits 37 (preempted).
phase=resume: load the checkpoint, run 2 more steps.
phase=straight: 5 uninterrupted steps (the oracle trajectory).
Each phase appends per-step losses to <out_dir>/losses.<phase>.<rank>.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    out_dir, phase = sys.argv[1], sys.argv[2]
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))
    rank = jax.process_index()

    import mxtpu
    from mxtpu import nd, parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import mlp

    mxtpu.random.seed(0)
    net = mlp(classes=4, hidden=(16,))
    net.initialize(init="xavier")
    mesh = parallel.make_mesh({"dp": len(jax.devices())},
                              devices=jax.devices())
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh)
    rng = np.random.RandomState(0)  # same data on every rank
    batch = 4 * len(jax.devices())
    X = rng.randn(8, batch, 6).astype(np.float32)
    Y = rng.randint(0, 4, (8, batch)).astype(np.float32)

    ckpt_params = os.path.join(out_dir, "elastic.params")
    ckpt_states = os.path.join(out_dir, "elastic.states")

    def run_steps(lo, hi):
        losses = []
        for t in range(lo, hi):
            losses.append(float(step(nd.array(X[t]),
                                     nd.array(Y[t])).asscalar()))
        return losses

    if phase == "resume":
        # parameter collection must exist before load_states
        net(nd.array(X[0][: batch]))
        net.load_parameters(ckpt_params)
        step.load_states(ckpt_states, x_example=nd.array(X[0]))
        losses = run_steps(3, 5)
    elif phase == "crash":
        losses = run_steps(0, 3)
        if rank == 0:
            net.save_parameters(ckpt_params)
            step.save_states(ckpt_states)
        # all ranks reach the checkpoint barrier before the preemption
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ckpt")
    else:
        losses = run_steps(0, 5)

    with open(os.path.join(out_dir, f"losses.{phase}.{rank}"),
              "w") as f:
        f.write(",".join(f"{v:.8f}" for v in losses))
    if phase == "crash" and rank == 1:
        sys.stdout.flush()
        os._exit(37)  # simulated preemption


if __name__ == "__main__":
    main()
