"""check_consistency(cpu ↔ tpu) on the real chip — SURVEY §4.2 calls
this "the single most important harness to replicate" (the reference's
``tests/python/gpu/test_operator_gpu.py``† reran the CPU suite on GPU
and cross-compared).

Runs only when the session's default backend is a TPU
(``MXTPU_TEST_PLATFORM=tpu``); on the CPU-mesh CI config every test
skips (the cpu↔cpu comparison would be vacuous).
"""
import jax
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.test_utils import check_consistency

pytestmark = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="needs a real accelerator backend (MXTPU_TEST_PLATFORM=tpu)")


def _ctxs(extra_bf16=False):
    ctxs = [{"ctx": mx.cpu(), "type_dict": {}},
            {"ctx": mx.tpu(), "type_dict": {}}]
    if extra_bf16:
        ctxs.append({"ctx": mx.tpu(),
                     "type_dict": {"data": "bfloat16"}})
    return ctxs


def _params(sym, seed=0, **shapes):
    rng = np.random.RandomState(seed)
    arg_shapes, _, _ = sym.infer_shape(**shapes)
    return {n: rng.randn(*s).astype(np.float32) * 0.5
            for n, s in zip(sym.list_arguments(), arg_shapes)}


def test_dense_relu_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc")
    net = mx.sym.Activation(net, act_type="relu")
    check_consistency(net, _ctxs(),
                      arg_params=_params(net, data=(4, 8)))


def test_conv_bn_pool_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="conv")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn")[0]
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    params = _params(net, data=(2, 3, 8, 8))
    aux = {"bn_moving_mean": mx.nd.zeros((8,)),
           "bn_moving_var": mx.nd.ones((8,))}
    check_consistency(net, _ctxs(), arg_params=params,
                      aux_states=aux)


def test_layernorm_softmax_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.LayerNorm(data, name="ln")
    net = mx.sym.softmax(net, axis=-1)
    check_consistency(net, _ctxs(),
                      arg_params=_params(net, data=(4, 32)))


def test_embedding_take_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.Embedding(data, input_dim=20, output_dim=8,
                           name="emb")
    params = _params(net, data=(4, 5))
    params["data"] = np.random.RandomState(1).randint(
        0, 20, (4, 5)).astype(np.float32)
    check_consistency(net, _ctxs(), grad_req="null",
                      arg_params=params)


def test_reductions_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.Group([mx.sym.sum(data, axis=1),
                        mx.sym.max(data, axis=0),
                        mx.sym.norm(data)])
    check_consistency(net, _ctxs(),
                      arg_params=_params(net, data=(6, 7)))


def test_softmax_output_training_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    params = _params(net, data=(6, 10), softmax_label=(6,))
    params["softmax_label"] = np.random.RandomState(2).randint(
        0, 4, (6,)).astype(np.float32)
    check_consistency(net, _ctxs(), arg_params=params)


def test_bf16_variant_consistency():
    """The bf16-on-TPU run agrees with f32 within bf16 tolerance —
    the reference's fp16 check_consistency tier (SURVEY §7
    hard-part 9)."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    net = mx.sym.Activation(net, act_type="tanh")
    check_consistency(net, _ctxs(extra_bf16=True),
                      arg_params=_params(net, data=(4, 16)))
