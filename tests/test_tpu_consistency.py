"""check_consistency(cpu ↔ tpu) on the real chip — SURVEY §4.2 calls
this "the single most important harness to replicate" (the reference's
``tests/python/gpu/test_operator_gpu.py``† reran the CPU suite on GPU
and cross-compared).

Runs only when the session's default backend is a TPU
(``MXTPU_TEST_PLATFORM=tpu``); on the CPU-mesh CI config every test
skips (the cpu↔cpu comparison would be vacuous).
"""
import jax
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.test_utils import check_consistency

pytestmark = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="needs a real accelerator backend (MXTPU_TEST_PLATFORM=tpu)")


def _ctxs(extra_bf16=False):
    ctxs = [{"ctx": mx.cpu(), "type_dict": {}},
            {"ctx": mx.tpu(), "type_dict": {}}]
    if extra_bf16:
        ctxs.append({"ctx": mx.tpu(),
                     "type_dict": {"data": "bfloat16"}})
    return ctxs


def _params(sym, seed=0, **shapes):
    rng = np.random.RandomState(seed)
    arg_shapes, _, _ = sym.infer_shape(**shapes)
    return {n: rng.randn(*s).astype(np.float32) * 0.5
            for n, s in zip(sym.list_arguments(), arg_shapes)}


def test_dense_relu_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc")
    net = mx.sym.Activation(net, act_type="relu")
    check_consistency(net, _ctxs(),
                      arg_params=_params(net, data=(4, 8)))


def test_conv_bn_pool_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="conv")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn")[0]
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    params = _params(net, data=(2, 3, 8, 8))
    aux = {"bn_moving_mean": mx.nd.zeros((8,)),
           "bn_moving_var": mx.nd.ones((8,))}
    check_consistency(net, _ctxs(), arg_params=params,
                      aux_states=aux)


def test_layernorm_softmax_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.LayerNorm(data, name="ln")
    net = mx.sym.softmax(net, axis=-1)
    check_consistency(net, _ctxs(),
                      arg_params=_params(net, data=(4, 32)))


def test_embedding_take_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.Embedding(data, input_dim=20, output_dim=8,
                           name="emb")
    params = _params(net, data=(4, 5))
    params["data"] = np.random.RandomState(1).randint(
        0, 20, (4, 5)).astype(np.float32)
    check_consistency(net, _ctxs(), grad_req="null",
                      arg_params=params)


def test_reductions_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.Group([mx.sym.sum(data, axis=1),
                        mx.sym.max(data, axis=0),
                        mx.sym.norm(data)])
    check_consistency(net, _ctxs(),
                      arg_params=_params(net, data=(6, 7)))


def test_softmax_output_training_consistency():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    params = _params(net, data=(6, 10), softmax_label=(6,))
    params["softmax_label"] = np.random.RandomState(2).randint(
        0, 4, (6,)).astype(np.float32)
    check_consistency(net, _ctxs(), arg_params=params)


def test_bf16_variant_consistency():
    """The bf16-on-TPU run agrees with f32 within bf16 tolerance —
    the reference's fp16 check_consistency tier (SURVEY §7
    hard-part 9)."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    net = mx.sym.Activation(net, act_type="tanh")
    check_consistency(net, _ctxs(extra_bf16=True),
                      arg_params=_params(net, data=(4, 16)))


def test_residual_block_training_consistency():
    """Composite graph the per-op sweep can't cover: a full ResNet
    bottleneck motif (conv-BN-relu x2 + residual add) fwd+bwd — the
    cross-op autodiff interplay of the BN custom-VJP with convs and
    the skip connection, on real hardware."""
    data = mx.sym.Variable("data")
    b1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=6,
                            pad=(1, 1), no_bias=True, name="c1")
    b1 = mx.sym.BatchNorm(b1, fix_gamma=False, name="bn1")[0]
    b1 = mx.sym.Activation(b1, act_type="relu")
    b1 = mx.sym.Convolution(b1, kernel=(3, 3), num_filter=6,
                            pad=(1, 1), no_bias=True, name="c2")
    b1 = mx.sym.BatchNorm(b1, fix_gamma=False, name="bn2")[0]
    sc = mx.sym.Convolution(data, kernel=(1, 1), num_filter=6,
                            no_bias=True, name="sc")
    out = mx.sym.Activation(b1 + sc, act_type="relu")
    out = mx.sym.Pooling(out, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    params = _params(out, data=(2, 3, 9, 7))
    aux = {f"bn{i}_moving_mean": mx.nd.zeros((6,)) for i in (1, 2)}
    aux.update({f"bn{i}_moving_var": mx.nd.ones((6,))
                for i in (1, 2)})
    check_consistency(out, _ctxs(), arg_params=params,
                      aux_states=aux)


def test_lstm_chain_training_consistency():
    """Fused RNN fwd+bwd across time steps on hardware (scan-carried
    state is another cross-op structure the one-op sweep misses)."""
    data = mx.sym.Variable("data")
    par = mx.sym.Variable("rnn_params")
    s0 = mx.sym.Variable("state")
    c0 = mx.sym.Variable("state_cell")
    out = mx.sym.RNN(data, par, s0, c0, state_size=5, num_layers=1,
                     mode="lstm", name="rnn")[0]
    out = mx.sym.sum(out, axis=(0, 2))
    n_par = 4 * 5 * (4 + 5 + 2)
    params = {
        "data": np.random.RandomState(0).randn(6, 3, 4)
        .astype(np.float32) * 0.5,
        "rnn_params": np.random.RandomState(1).randn(n_par)
        .astype(np.float32) * 0.2,
        "state": np.zeros((1, 3, 5), np.float32),
        "state_cell": np.zeros((1, 3, 5), np.float32),
    }
    check_consistency(out, _ctxs(), arg_params=params)


def test_attention_block_training_consistency():
    """Self-attention composite (FC qkv + batched softmax(QK)V + FC)
    fwd+bwd — the transformer motif with its log-softmax/matmul
    autodiff chain on hardware."""
    data = mx.sym.Variable("data")       # (B, T, D)
    qkv = mx.sym.FullyConnected(data, num_hidden=24, flatten=False,
                                no_bias=True, name="qkv")
    q = mx.sym.slice_axis(qkv, axis=2, begin=0, end=8)
    k = mx.sym.slice_axis(qkv, axis=2, begin=8, end=16)
    v = mx.sym.slice_axis(qkv, axis=2, begin=16, end=24)
    s = mx.sym.batch_dot(q, k, transpose_b=True) * (1.0 / np.sqrt(8))
    p = mx.sym.softmax(s, axis=-1)
    o = mx.sym.batch_dot(p, v)
    out = mx.sym.FullyConnected(o, num_hidden=8, flatten=False,
                                name="proj")
    out = mx.sym.LayerNorm(out, axis=-1, name="ln")
    params = _params(out, data=(2, 6, 8))
    check_consistency(out, _ctxs(), arg_params=params)
