"""Distributed tests without a cluster: N local processes through
``tools/launch.py --launcher local`` (the reference's
``tests/nightly/dist_sync_kvstore.py``† mechanism, SURVEY §4.5).

Each process is one simulated host; ``jax.distributed`` forms the
process group over localhost and the kvstore ``dist_sync`` paths are
asserted cross-process in ``tests/dist_worker.py``.
"""
import os
import socket
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("n", [2, 3])
def test_dist_sync_kvstore_local_processes(tmp_path, n):
    env = dict(os.environ)
    # children must form their own CPU-only jax runtime
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    # own process group: on timeout/failure kill the WHOLE tree, not
    # just launch.py — orphaned workers would hold the coordinator
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, os.path.join(_ROOT, "tests",
                                      "dist_worker.py"),
         str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        out, err = proc.communicate()
        pytest.fail(f"distributed run hung: {err[-1500:]}")
    finally:
        try:
            os.killpg(proc.pid, 9)
        except ProcessLookupError:
            pass
    assert proc.returncode == 0, (out[-1500:], err[-1500:])
    for rank in range(n):
        ok = tmp_path / f"ok.{rank}"
        assert ok.exists(), f"rank {rank} never finished"
