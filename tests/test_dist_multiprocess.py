"""Distributed tests without a cluster: N local processes through
``tools/launch.py --launcher local`` (the reference's
``tests/nightly/dist_sync_kvstore.py``† mechanism, SURVEY §4.5).

Each process is one simulated host; ``jax.distributed`` forms the
process group over localhost and the kvstore ``dist_sync`` paths are
asserted cross-process in ``tests/dist_worker.py``.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------
# backend probe: some jaxlib CPU clients cannot execute cross-process
# computations at all ("Multiprocess computations aren't implemented
# on the CPU backend").  That is a backend limitation, not a bug in
# the kvstore/checkpoint paths these tests cover, so we probe ONCE
# per session with a minimal 2-process jitted reduction and skip with
# the backend's own words when it refuses.  Deliberately NOT a
# blanket skip: a jaxlib that can run the computation keeps every
# test live, and any failure other than the capability marker still
# fails loudly.
# ---------------------------------------------------------------------
_MP_UNSUPPORTED_MARKER = "Multiprocess computations aren't implemented"
_MP_PROBE = None  # (ok, reason) after first use


def _mp_probe(tmp_path):
    global _MP_PROBE
    if _MP_PROBE is None:
        rc, out, err = _launch(2, "mp_probe_worker.py", [], tmp_path,
                               timeout=180)
        if rc == 0 and out.count("MP_PROBE_OK") >= 1:
            _MP_PROBE = (True, "")
        elif _MP_UNSUPPORTED_MARKER in out + err:
            _MP_PROBE = (False, _MP_UNSUPPORTED_MARKER
                         + " on this jaxlib")
        else:
            # an unknown probe failure must not mask real breakage
            _MP_PROBE = (True, "")
    return _MP_PROBE


def _require_mp_backend(tmp_path):
    ok, reason = _mp_probe(tmp_path)
    if not ok:
        pytest.skip(f"backend probe: {reason}")


@pytest.mark.parametrize("n", [2, 3])
def test_dist_sync_kvstore_local_processes(tmp_path, n):
    _require_mp_backend(tmp_path)
    env = dict(os.environ)
    # children must form their own CPU-only jax runtime
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    # own process group: on timeout/failure kill the WHOLE tree, not
    # just launch.py — orphaned workers would hold the coordinator
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, os.path.join(_ROOT, "tests",
                                      "dist_worker.py"),
         str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        out, err = proc.communicate()
        pytest.fail(f"distributed run hung: {err[-1500:]}")
    finally:
        try:
            os.killpg(proc.pid, 9)
        except ProcessLookupError:
            pass
    assert proc.returncode == 0, (out[-1500:], err[-1500:])
    for rank in range(n):
        ok = tmp_path / f"ok.{rank}"
        assert ok.exists(), f"rank {rank} never finished"


def _launch(n, worker, extra, tmp_path, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, os.path.join(_ROOT, "tests", worker),
         str(tmp_path)] + extra,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        out, err = proc.communicate()
        pytest.fail(f"distributed run hung: {err[-1500:]}")
    finally:
        try:
            os.killpg(proc.pid, 9)
        except ProcessLookupError:
            pass
    return proc.returncode, out, err


def _losses(tmp_path, phase, rank):
    with open(tmp_path / f"losses.{phase}.{rank}") as f:
        return [float(v) for v in f.read().split(",")]


def test_preemption_restart_recovery(tmp_path):
    """SURVEY §5.3: a preempted multi-process job restarts from the
    per-step checkpoint and continues EXACTLY where it left off —
    the checkpoint+restart recovery story, validated across real
    process groups (elastic mid-collective shrink is impossible in
    SPMD by design, documented)."""
    _require_mp_backend(tmp_path)
    # oracle: 5 uninterrupted steps
    rc, out, err = _launch(2, "elastic_worker.py", ["straight"],
                           tmp_path)
    assert rc == 0, err[-1500:]
    oracle = _losses(tmp_path, "straight", 0)
    assert _losses(tmp_path, "straight", 1) == oracle

    # preempted run: rank 1 dies with code 37 after the step-3 ckpt
    rc, out, err = _launch(2, "elastic_worker.py", ["crash"],
                           tmp_path)
    assert rc != 0  # the launcher surfaces the dead worker
    first = _losses(tmp_path, "crash", 0)
    assert first == oracle[:3]

    # coordinator restart: fresh process group resumes from the ckpt
    rc, out, err = _launch(2, "elastic_worker.py", ["resume"],
                           tmp_path)
    assert rc == 0, err[-1500:]
    resumed = _losses(tmp_path, "resume", 0)
    np.testing.assert_allclose(resumed, oracle[3:], rtol=1e-6,
                               atol=1e-7)
    assert _losses(tmp_path, "resume", 1) == resumed
