"""Compiled Executor path (VERDICT r2 item 5): the bound symbol
interprets under a shape-keyed jax.jit with jax.vjp as the backward
graph — the role of the reference's GraphExecutor
(src/executor/graph_executor.cc†, whose whole point was the fast bound
path).  These tests pin jit ≡ eager for outputs and gradients.

Measured on CPU (3 epochs of a 64-256-128-2 MLP, batch 128):
eager 1.99 s → jit 0.27 s (7.4x).
"""
import numpy as np

import mxtpu as mx
from mxtpu.executor import Executor


def _mlp_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _bind(sym, jit, seed=0):
    rng = np.random.RandomState(seed)
    shapes = {"data": (8, 10), "softmax_label": (8,)}
    arg_shapes, _, _ = sym.infer_shape(**shapes)
    args = {n: mx.nd.array(rng.randn(*s).astype(np.float32))
            for n, s in zip(sym.list_arguments(), arg_shapes)}
    args["softmax_label"] = mx.nd.array(
        rng.randint(0, 4, (8,)).astype(np.float32))
    exe = Executor(sym, args=args, grad_req="write")
    exe._jit = jit
    return exe


def test_jit_matches_eager_forward_backward():
    sym = _mlp_symbol()
    e_jit = _bind(sym, True)
    e_eager = _bind(sym, False)
    out_j = e_jit.forward(is_train=True)[0].asnumpy()
    out_e = e_eager.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out_j, out_e, rtol=1e-5, atol=1e-6)
    e_jit.backward()
    e_eager.backward()
    for n in sym.list_arguments():
        gj = e_jit.grad_dict.get(n)
        ge = e_eager.grad_dict.get(n)
        assert (gj is None) == (ge is None), n
        if gj is not None:
            np.testing.assert_allclose(gj.asnumpy(), ge.asnumpy(),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=n)


def test_jit_out_grads_and_grad_req_add():
    sym = _mlp_symbol()
    e_jit = _bind(sym, True)
    e_eager = _bind(sym, False)
    og = mx.nd.array(np.random.RandomState(1)
                     .randn(8, 4).astype(np.float32))
    for e in (e_jit, e_eager):
        e._grad_req = {n: "add" for n in sym.list_arguments()}
        e.forward(is_train=True)
        e.backward(out_grads=[og])
        e.forward(is_train=True)
        e.backward(out_grads=[og])  # accumulates
    for n in sym.list_arguments():
        gj, ge = e_jit.grad_dict.get(n), e_eager.grad_dict.get(n)
        if gj is not None:
            np.testing.assert_allclose(gj.asnumpy(), ge.asnumpy(),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=n)


def test_jit_cache_reused_across_calls():
    sym = _mlp_symbol()
    exe = _bind(sym, True)
    exe.forward(is_train=False)
    assert len(exe._jit_cache) == 1
    exe.forward(is_train=False)
    assert len(exe._jit_cache) == 1
    exe.forward(is_train=True)
    assert len(exe._jit_cache) == 2


def test_monitor_callback_falls_back_to_eager():
    sym = _mlp_symbol()
    exe = _bind(sym, True)
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward(is_train=False)
    assert seen  # per-output callback ran (eager path)


def test_module_fit_converges_on_jit_executor():
    from mxtpu import io as mio
    rng = np.random.RandomState(0)
    X = rng.randn(512, 16).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    it = mio.NDArrayIter(X, Y, batch_size=64)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1,
                              "rescale_grad": 1.0 / 64},
            initializer=mx.init.Xavier())
    import mxtpu.metric as metric
    it.reset()
    score = dict(mod.score(it, metric.Accuracy()))
    assert score["accuracy"] > 0.9, score


def test_forward_backward_single_program_for_default_cotangent():
    """forward(is_train=True)+backward() runs ONE fwd+bwd program (the
    default-ones cotangent is folded into the forward call)."""
    sym = _mlp_symbol()
    exe = _bind(sym, True)
    exe.forward(is_train=True)
    assert exe._pending_grads is not None
    exe.backward()  # must not need another device program
    assert exe.grad_dict


def test_executor_adaptive_backward_modes():
    """forward(is_train)+backward(explicit cotangents) must produce the
    same grads on every iteration, and after the executor adapts (it
    stops precomputing ones-grads once it sees explicit cotangents /
    no-backward usage — r3 advisor), backward(None) must still work."""
    import numpy as np
    from mxtpu import nd, sym
    x = sym.Variable("x")
    y = sym.sin(x * 2.0)
    a = nd.array(np.linspace(-1, 1, 6).astype(np.float32))
    exe = y.bind(None, {"x": a}, args_grad={"x": nd.zeros_like(a)})
    cot = nd.array(np.full((6,), 0.5, np.float32))
    want = 0.5 * 2.0 * np.cos(2.0 * np.linspace(-1, 1, 6))
    for _ in range(3):  # repeat: mode flips to "explicit" after iter 1
        exe.forward(is_train=True)
        exe.backward(cot)
        np.testing.assert_allclose(exe.grad_dict["x"].asnumpy(), want,
                                   rtol=1e-5)
    # eval-style forwards (never backward) — then a backward(None)
    # arrives anyway and must still be correct
    exe.forward(is_train=True)
    exe.forward(is_train=True)
    exe.backward()
    np.testing.assert_allclose(
        exe.grad_dict["x"].asnumpy(),
        2.0 * np.cos(2.0 * np.linspace(-1, 1, 6)), rtol=1e-5)
