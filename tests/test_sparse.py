"""Sparse NDArray: creation round-trips, compressed views, sparse
ops, lazy row-sparse optimizer updates.

Reference: ``python/mxnet/ndarray/sparse.py``†,
``tests/python/unittest/test_sparse_ndarray.py``† /
``test_sparse_operator.py``†.  The TPU port stores densely (documented
divergence); THESE tests pin the API semantics that must still hold:
compressed views, stype propagation, and lazy-update numerics.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.base import MXNetError
from mxtpu.ndarray import sparse


def test_row_sparse_creation_and_views():
    data = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    rsp = sparse.row_sparse_array((data, [1, 3]), shape=(5, 2))
    assert rsp.stype == "row_sparse"
    dense = np.zeros((5, 2), np.float32)
    dense[[1, 3]] = data
    np.testing.assert_array_equal(rsp.asnumpy(), dense)
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 3])
    np.testing.assert_array_equal(rsp.data.asnumpy(), data)
    # from dense: indices inferred from nonzero rows
    rsp2 = sparse.row_sparse_array(dense)
    np.testing.assert_array_equal(rsp2.indices.asnumpy(), [1, 3])


def test_csr_creation_and_views():
    data = np.array([1.0, 2.0, 3.0], np.float32)
    indices = np.array([1, 0, 2])
    indptr = np.array([0, 1, 3])
    csr = sparse.csr_matrix((data, indices, indptr), shape=(2, 3))
    dense = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
    np.testing.assert_array_equal(csr.asnumpy(), dense)
    np.testing.assert_array_equal(csr.indptr.asnumpy(), indptr)
    np.testing.assert_array_equal(csr.indices.asnumpy(), indices)
    np.testing.assert_array_equal(csr.data.asnumpy(), data)


def test_tostype_round_trip():
    dense = nd.array(np.array([[0, 0], [5, 6], [0, 0]], np.float32))
    rsp = dense.tostype("row_sparse") \
        if hasattr(dense, "tostype") else sparse._cast_storage(
            dense, "row_sparse")
    assert rsp.stype == "row_sparse"
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1])
    back = rsp.tostype("default")
    assert not isinstance(back, sparse.BaseSparseNDArray)
    np.testing.assert_array_equal(back.asnumpy(), dense.asnumpy())


def test_retain():
    rsp = sparse.row_sparse_array(
        (np.ones((3, 2), np.float32), [0, 2, 4]), shape=(5, 2))
    kept = sparse.retain(rsp, nd.array(np.array([2, 4], np.float32)))
    np.testing.assert_array_equal(kept.indices.asnumpy(), [2, 4])
    d = kept.asnumpy()
    assert d[0].sum() == 0 and d[2].sum() == 2 and d[4].sum() == 2


def test_sparse_dot_storage_table():
    rng = np.random.RandomState(0)
    a = np.zeros((4, 3), np.float32)
    a[0, 1] = 2.0
    a[2, 2] = 3.0
    b = rng.randn(4, 5).astype(np.float32)
    csr = sparse.csr_matrix(a)
    # csr · dense → dense
    out = sparse.dot(csr, nd.array(rng.randn(3, 5).astype(np.float32)))
    assert not isinstance(out, sparse.BaseSparseNDArray)
    # csrᵀ · dense → row_sparse (reference storage-type table)
    out_t = sparse.dot(csr, nd.array(b), transpose_a=True)
    assert isinstance(out_t, sparse.RowSparseNDArray)
    np.testing.assert_allclose(out_t.asnumpy(), a.T @ b, rtol=1e-5)
    # only csr columns with stored entries appear as output rows
    np.testing.assert_array_equal(out_t.indices.asnumpy(), [1, 2])


def test_elemwise_add_stype_propagation():
    r1 = sparse.row_sparse_array(
        (np.ones((1, 2), np.float32), [0]), shape=(3, 2))
    r2 = sparse.row_sparse_array(
        (np.ones((1, 2), np.float32), [2]), shape=(3, 2))
    out = sparse.elemwise_add(r1, r2)
    assert isinstance(out, sparse.RowSparseNDArray)
    np.testing.assert_array_equal(out.indices.asnumpy(), [0, 2])
    dense = nd.ones((3, 2))
    out2 = sparse.elemwise_add(r1, dense)
    assert not isinstance(out2, sparse.BaseSparseNDArray)
    out3 = sparse.add_n(r1, r2, r1)
    assert isinstance(out3, sparse.RowSparseNDArray)
    assert out3.asnumpy()[0, 0] == 2.0


def test_lazy_sgd_update_skips_untouched_rows():
    """lazy_update: rows absent from the sparse grad skip BOTH the
    step and weight decay (reference sgd lazy semantics)."""
    from mxtpu import optimizer as opt
    w = nd.array(np.ones((4, 2), np.float32))
    g = sparse.row_sparse_array(
        (np.full((2, 2), 0.5, np.float32), [1, 3]), shape=(4, 2))
    sgd = opt.SGD(learning_rate=0.1, wd=0.1, lazy_update=True)
    state = sgd.create_state(0, w)
    sgd.update(0, w, g, state)
    out = w.asnumpy()
    # untouched rows 0/2: EXACTLY unchanged (no wd either)
    np.testing.assert_array_equal(out[0], [1.0, 1.0])
    np.testing.assert_array_equal(out[2], [1.0, 1.0])
    # touched rows: w - lr*(g + wd*w)
    np.testing.assert_allclose(out[1], 1.0 - 0.1 * (0.5 + 0.1),
                               rtol=1e-6)
    # dense-mode (lazy off): every row decays
    w2 = nd.array(np.ones((4, 2), np.float32))
    sgd2 = opt.SGD(learning_rate=0.1, wd=0.1, lazy_update=False)
    sgd2.update(0, w2, g, sgd2.create_state(0, w2))
    assert not np.allclose(w2.asnumpy()[0], [1.0, 1.0])


def test_lazy_adam_update_state_isolation():
    from mxtpu import optimizer as opt
    w = nd.array(np.ones((3, 2), np.float32))
    g = sparse.row_sparse_array(
        (np.ones((1, 2), np.float32), [1]), shape=(3, 2))
    adam = opt.Adam(learning_rate=0.1, lazy_update=True)
    state = adam.create_state(0, w)
    adam.update(0, w, g, state)
    mean = state[0].asnumpy()
    assert mean[0].sum() == 0 and mean[2].sum() == 0  # untouched
    assert abs(mean[1][0] - 0.1) < 1e-6               # beta1 step
    assert np.array_equal(w.asnumpy()[0], [1.0, 1.0])
    assert not np.array_equal(w.asnumpy()[1], [1.0, 1.0])


def test_sparse_zeros_and_cast_errors():
    z = sparse.zeros("row_sparse", (3, 2))
    assert z.stype == "row_sparse" and z.asnumpy().sum() == 0
    with pytest.raises(MXNetError):
        sparse._cast_storage(nd.zeros((2, 2, 2)), "csr")
    with pytest.raises(MXNetError):
        sparse.zeros("row_sparse", (3, 2)).tostype("blocked")
