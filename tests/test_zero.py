"""ZeRO-1 sharded optimizer states in the compiled train step
(ISSUE 3 tentpole): on a dp mesh the step reduce-scatters gradients
per (shape, dtype) bucket, updates only the local 1/dp state shard,
and all-gathers fresh params — numerically identical to the
replicated all-reduce path (MXTPU_ZERO=0) for every supported
optimizer, with ~dp× less optimizer HBM.

Runs on the virtual 8-device CPU mesh conftest.py forces; the comm
signature is asserted on the compiled HLO itself (reduce-scatter +
all-gather present, no full-gradient all-reduce)."""

import jax
import numpy as np
import pytest

from mxtpu import nd, parallel
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.parallel import (plan_zero_buckets, restore_params,
                            snapshot_params)


def _mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, have {len(devs)}")
    return jax.sharding.Mesh(np.array(devs[:n]), ("dp",))


def _make_net(x):
    net = nn.HybridSequential()
    # three Dense(16) → multi-param buckets for weights and biases,
    # plus singleton buckets from the output layer — exercises both
    # stack-axis and inner-axis sharding in one model
    net.add(nn.Dense(16, flatten=False), nn.Dense(16, flatten=False),
            nn.Dense(16, flatten=False), nn.Dense(4, flatten=False))
    net.initialize(init="xavier")
    net(x)
    return net


def _run(optname, oparams, zero, x, y, snap, monkeypatch, steps=4,
         compute_dtype=None):
    """One training run on the dp8 mesh: ``zero=True`` is the ZeRO-1
    path, ``zero=False`` the replicated all-reduce path via the
    MXTPU_ZERO=0 kill switch (the exact pre-ZeRO program)."""
    monkeypatch.setenv("MXTPU_ZERO", "1" if zero else "0")
    net = _make_net(x)
    restore_params(net, snap)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), optname, dict(oparams),
        mesh=_mesh(), compute_dtype=compute_dtype)
    assert step.zero is zero
    losses = [float(step(x, y).asscalar()) for _ in range(steps)]
    return losses, snapshot_params(net), step


@pytest.fixture()
def _data():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 16).astype(np.float32))
    y = nd.array(rng.randn(8, 4).astype(np.float32))
    snap = snapshot_params(_make_net(x))
    return x, y, snap


# ---------------------------------------------------------------------
# parity: ZeRO-1 vs the replicated path, every supported optimizer
# ---------------------------------------------------------------------
@pytest.mark.parametrize("optname,oparams", [
    ("sgd", {"learning_rate": 0.05}),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 1e-3, "wd": 1e-4}),
    ("rmsprop", {"learning_rate": 1e-3}),
    ("lamb", {"learning_rate": 1e-2, "wd": 1e-2}),
])
def test_zero_parity_all_optimizers(optname, oparams, _data,
                                    monkeypatch):
    x, y, snap = _data
    lz, pz, _ = _run(optname, oparams, True, x, y, snap, monkeypatch)
    lr, pr, _ = _run(optname, oparams, False, x, y, snap, monkeypatch)
    np.testing.assert_allclose(lz, lr, rtol=1e-6, atol=1e-8)
    for a, b in zip(pz, pr):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("optname,oparams", [
    ("adam", {"learning_rate": 1e-3, "wd": 1e-4}),
    ("lamb", {"learning_rate": 1e-2, "wd": 1e-2}),
])
def test_zero_parity_bf16_multi_precision(optname, oparams, _data,
                                          monkeypatch):
    """bf16 compute + f32 master weights (the multi_precision recipe)
    under ZeRO: states stay f32, sharding changes nothing numerically
    beyond bf16 reduction-order noise."""
    x, y, snap = _data
    lz, pz, _ = _run(optname, oparams, True, x, y, snap, monkeypatch,
                     compute_dtype="bfloat16")
    lr, pr, _ = _run(optname, oparams, False, x, y, snap, monkeypatch,
                     compute_dtype="bfloat16")
    np.testing.assert_allclose(lz, lr, rtol=1e-4, atol=1e-5)
    for a, b in zip(pz, pr):
        assert a.dtype == np.float32  # master weights stay f32
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------
# comm-layout smoke (tier-1): the compiled program itself proves the
# mechanism — asserted through mxtpu.analysis (ISSUE 6: one HLO
# parser in the tree) instead of regexing hlo_text
# ---------------------------------------------------------------------
def test_zero_comm_hlo_signature_and_parity(_data, monkeypatch):
    """The acceptance shape of the tentpole, tier-1-safe: a dp8 step
    whose program contains reduce-scatter + all-gather and whose only
    all-reduces are scalar/small (loss, aux) — no full-gradient
    all-reduce — and which matches the replicated path step for step."""
    x, y, snap = _data
    lz, _, zstep = _run("adam", {"learning_rate": 1e-3}, True, x, y,
                        snap, monkeypatch, steps=3)
    lr, _, rstep = _run("adam", {"learning_rate": 1e-3}, False, x, y,
                        snap, monkeypatch, steps=3)
    np.testing.assert_allclose(lz, lr, rtol=1e-6, atol=1e-8)

    coll_z = zstep.program_summary(x, y)["collectives"]
    assert coll_z.get("reduce-scatter", {}).get("count", 0) > 0
    assert coll_z.get("all-gather", {}).get("count", 0) > 0
    # every gradient bucket in this net is > 16 elements; any surviving
    # all-reduce that big would mean a gradient bypassed the scatter
    big = coll_z.get("all-reduce", {}).get("max_elems", 0)
    assert big <= 16, \
        f"full-tensor all-reduce leaked into ZeRO HLO: {big} elems"

    # MXTPU_ZERO=0 restores the exact pre-ZeRO program shape: gradient
    # all-reduce, no scatter/gather collectives
    coll_r = rstep.program_summary(x, y)["collectives"]
    assert "reduce-scatter" not in coll_r
    assert coll_r.get("all-reduce", {}).get("count", 0) > 0


# ---------------------------------------------------------------------
# memory: the dp× saving, measured and planned
# ---------------------------------------------------------------------
def test_zero_opt_state_bytes_sharded(_data, monkeypatch):
    """Per-device optimizer-state bytes under ZeRO must be ≈
    replicated/dp (× ≤1.15 padding allowance) and exactly match the
    plan_zero_buckets geometry."""
    x, y, snap = _data
    _, _, zstep = _run("adam", {"learning_rate": 1e-3}, True, x, y,
                       snap, monkeypatch, steps=1)
    _, _, rstep = _run("adam", {"learning_rate": 1e-3}, False, x, y,
                       snap, monkeypatch, steps=1)
    zsum = zstep.memory_summary(x, y)
    rsum = rstep.memory_summary(x, y)
    z = zsum["zero"]["opt_state_bytes"]
    r = rsum["zero"]["opt_state_bytes"]
    assert z <= r / 8 * 1.15, (z, r)
    # adam: two f32 leaves (m, v) per bucket, each 1/8 of the padded
    # stacked array — the plan_zero_buckets oracle memflow carries
    assert z == zsum["zero"]["planned_shard_bytes"], zsum["zero"]
    assert not [h for h in zsum["hazards"]
                if h["rule"] == "zero-replication"], zsum["hazards"]
    dec = zsum["programs"]["train_step"]
    assert dec["opt_state"] == z
    assert dec["peak_hbm"] >= 0


def test_zero_bucket_axis_geometry():
    """plan_zero_buckets picks the axis that kills padding: a
    BERT-style embedding singleton bucket must shard an inner axis
    pad-free instead of wasting 7/8 of a stack-axis row, and the
    planned footprint for BERT-Large-like sigs stays within the
    ≤ replicated/dp × 1.15 criterion."""
    sigs = ([((30522, 1024), "float32")] * 2        # embeddings
            + [((1024, 1024), "float32")] * 96      # attention proj
            + [((4096, 1024), "float32")] * 24      # FFN in
            + [((1024, 4096), "float32")] * 24      # FFN out
            + [((1024,), "float32")] * 146)         # biases/LN
    buckets = plan_zero_buckets(sigs, 8)
    by_shape = {b["shape"]: b for b in buckets}
    emb = by_shape[(30522, 1024)]
    assert emb["axis"] != 0 and emb["pad"] == 0, emb
    total = sum(b["param_bytes"] for b in buckets)
    per_dev = sum(b["padded_bytes"] // 8 for b in buckets)
    assert per_dev <= total / 8 * 1.15, (per_dev, total)
    # LAMB pins every bucket to the stack axis so per-row trust-ratio
    # norms stay device-local — padding is the price, locality the pin
    for b in plan_zero_buckets(sigs, 8, stack_axis_only=True):
        assert b["axis"] == 0


def test_zero_lamb_buckets_pinned_to_stack_axis(_data, monkeypatch):
    """The built LAMB step must actually use the stack-axis-only plan
    (a non-stack shard would split trust-ratio norms across devices —
    silently wrong, which is why this is pinned by a test)."""
    x, y, snap = _data
    _, _, zstep = _run("lamb", {"learning_rate": 1e-2}, True, x, y,
                       snap, monkeypatch, steps=1)
    assert all(b["axis"] == 0 for b in zstep._zero_buckets)
    # t rides per stacked row: one rank-1 int32 leaf per bucket
    for b, st in zip(zstep._zero_buckets, zstep._opt_state):
        assert st[2].dtype == np.int32
        assert st[2].shape == (b["padded_shape"][0],)


# ---------------------------------------------------------------------
# checkpoints: zero ↔ replicated, both directions
# ---------------------------------------------------------------------
@pytest.mark.parametrize("optname,oparams", [
    ("adam", {"learning_rate": 1e-3, "wd": 1e-4}),
    ("lamb", {"learning_rate": 1e-2, "wd": 1e-2}),
])
def test_zero_checkpoint_interchangeable(optname, oparams, tmp_path,
                                         _data, monkeypatch):
    """save_states always writes the canonical per-parameter layout,
    so a ZeRO checkpoint resumes on a replicated step (and vice versa)
    with identical continued losses."""
    x, y, snap = _data
    fname = str(tmp_path / "opt.states")

    # zero-save → replicated-load (and → fresh-zero-load)
    lz, pz, zstep = _run(optname, oparams, True, x, y, snap,
                         monkeypatch, steps=3)
    zstep.save_states(fname)
    cont_z = [float(zstep(x, y).asscalar()) for _ in range(2)]

    monkeypatch.setenv("MXTPU_ZERO", "0")
    net_r = _make_net(x)
    restore_params(net_r, pz)
    rstep = parallel.build_train_step(
        net_r, lambda p, t: ((p - t) ** 2).mean(), optname,
        dict(oparams), mesh=_mesh())
    assert not rstep.zero
    rstep.load_states(fname, x_example=x)
    cont_r = [float(rstep(x, y).asscalar()) for _ in range(2)]
    np.testing.assert_allclose(cont_z, cont_r, rtol=1e-6, atol=1e-8)

    # replicated-save → zero-load
    rstep.save_states(fname)
    snap_r = snapshot_params(net_r)
    cont_r2 = [float(rstep(x, y).asscalar()) for _ in range(2)]

    monkeypatch.setenv("MXTPU_ZERO", "1")
    net_z = _make_net(x)
    restore_params(net_z, snap_r)
    zstep2 = parallel.build_train_step(
        net_z, lambda p, t: ((p - t) ** 2).mean(), optname,
        dict(oparams), mesh=_mesh())
    assert zstep2.zero
    zstep2.load_states(fname, x_example=x)
    cont_z2 = [float(zstep2(x, y).asscalar()) for _ in range(2)]
    np.testing.assert_allclose(cont_r2, cont_z2, rtol=1e-6, atol=1e-8)


# ---------------------------------------------------------------------
# contract guards
# ---------------------------------------------------------------------
def test_zero_batch_must_divide_dp(_data, monkeypatch):
    x, y, snap = _data
    monkeypatch.setenv("MXTPU_ZERO", "1")
    net = _make_net(x)
    restore_params(net, snap)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "adam",
        {"learning_rate": 1e-3}, mesh=_mesh())
    assert step.zero
    rng = np.random.RandomState(1)
    x6 = nd.array(rng.randn(6, 16).astype(np.float32))
    y6 = nd.array(rng.randn(6, 4).astype(np.float32))
    with pytest.raises(MXNetError, match="divisible"):
        step(x6, y6)


def test_zero_gating(_data, monkeypatch):
    x, _, snap = _data
    net = _make_net(x)
    restore_params(net, snap)
    loss = lambda p, t: ((p - t) ** 2).mean()  # noqa: E731
    # no mesh: auto-off; forcing raises
    monkeypatch.delenv("MXTPU_ZERO", raising=False)
    assert not parallel.build_train_step(net, loss, "adam").zero
    with pytest.raises(MXNetError, match="mesh"):
        parallel.build_train_step(net, loss, "adam", zero=1)
    # dp mesh: auto-on; kill switch wins over the default
    assert parallel.build_train_step(net, loss, "adam",
                                     mesh=_mesh()).zero
    monkeypatch.setenv("MXTPU_ZERO", "0")
    assert not parallel.build_train_step(net, loss, "adam",
                                         mesh=_mesh()).zero
    # tensor-parallel param_spec_fn: ZeRO steps aside
    monkeypatch.delenv("MXTPU_ZERO", raising=False)
    assert not parallel.build_train_step(
        net, loss, "adam", mesh=_mesh(),
        param_spec_fn=lambda p: None).zero


def test_zero_run_steps_scan_parity(_data, monkeypatch):
    """The scanned multi-step path threads the sharded states through
    lax.scan — same trajectory as the replicated scan."""
    x, y, snap = _data

    def scan_run(zero):
        monkeypatch.setenv("MXTPU_ZERO", "1" if zero else "0")
        net = _make_net(x)
        restore_params(net, snap)
        step = parallel.build_train_step(
            net, lambda p, t: ((p - t) ** 2).mean(), "adam",
            {"learning_rate": 3e-3}, mesh=_mesh())
        losses = step.run_steps(x, y, steps=6, reuse_batch=True)
        return np.asarray(losses.asnumpy()), step

    lz, zstep = scan_run(True)
    lr, _ = scan_run(False)
    assert lz.shape == (6,) and lz[-1] < lz[0]
    np.testing.assert_allclose(lz, lr, rtol=1e-6, atol=1e-8)
    mem = zstep.last_memory_analysis()
    if mem is not None:  # backend reports on CPU/TPU AOT programs
        assert mem["hbm_peak"] >= 0
