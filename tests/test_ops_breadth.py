"""Round-3 operator-breadth tail: init/AMP/slice-assign/linalg/
optimizer ops (ops_extra), deformable/psroi/roialign/quantized tier
(nn_extra), registered sampling ops (random_ops), registered contrib
ops, and the bulked multi-step train path.

References: src/operator/tensor/init_op.cc†, la_op.cc†,
optimizer_op.cc†, contrib/deformable_convolution.cc†, roi_align.cc†,
quantization/*†, random/*† — per-op anchors in the impl docstrings.
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.ops.registry import get_op, list_ops
from mxtpu.test_utils import check_numeric_gradient

sym = mx.sym


def _rand(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


# ---------------------------------------------------------------- init


def test_init_ops():
    assert get_op("_zeros")(shape=(2, 3)).shape == (2, 3)
    o = get_op("_ones")(shape=(4,), dtype="int32")
    assert o.dtype == jnp.int32 and int(o.sum()) == 4
    f = get_op("_full")(shape=(2, 2), value=3.5)
    np.testing.assert_allclose(np.asarray(f), 3.5)
    a = get_op("_arange")(start=1.0, stop=4.0, repeat=2)
    np.testing.assert_allclose(np.asarray(a), [1, 1, 2, 2, 3, 3])


def test_logical_tail():
    a = jnp.asarray([0.0, 1.0, 2.0])
    b = jnp.asarray([1.0, 0.0, 3.0])
    np.testing.assert_allclose(
        np.asarray(get_op("_logical_and")(a, b)), [0, 0, 1])
    np.testing.assert_allclose(
        np.asarray(get_op("_logical_or_scalar")(a, scalar=0.0)),
        [0, 1, 1])


def test_amp_ops():
    x = jnp.ones((3,), jnp.float32)
    assert get_op("amp_cast")(x, dtype="bfloat16").dtype == jnp.bfloat16
    outs = get_op("amp_multicast")(
        jnp.ones(2, jnp.bfloat16), jnp.ones(2, jnp.float32),
        num_outputs=2)
    assert all(o.dtype == jnp.float32 for o in outs)
    narrow = get_op("amp_multicast")(
        jnp.ones(2, jnp.bfloat16), jnp.ones(2, jnp.float32),
        num_outputs=2, cast_narrow=True)
    assert all(o.dtype == jnp.bfloat16 for o in narrow)
    assert float(get_op("all_finite")(jnp.asarray([1.0, 2.0]))[0]) == 1
    assert float(get_op("all_finite")(
        jnp.asarray([1.0, np.inf]))[0]) == 0
    assert float(get_op("multi_all_finite")(
        jnp.ones(3), jnp.asarray([np.nan]), num_arrays=2)[0]) == 0


def test_slice_assign_family():
    out = get_op("_slice_assign")(
        jnp.zeros((3, 3)), jnp.ones((1, 3)), begin=(1, 0), end=(2, 3))
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), [0, 3, 0])
    out2 = get_op("_slice_assign_scalar")(
        jnp.zeros((4,)), scalar=7.0, begin=(1,), end=(3,))
    np.testing.assert_allclose(np.asarray(out2), [0, 7, 7, 0])
    idx = jnp.asarray([[0, 2], [1, 0]])  # rows: per-dim indices
    out3 = get_op("_scatter_set_nd")(
        jnp.zeros((3, 3)), jnp.asarray([5.0, 6.0]), idx)
    assert float(out3[0, 1]) == 5 and float(out3[2, 0]) == 6


def test_reduce_tail():
    x = jnp.asarray(_rand(2, 5, 3))
    np.testing.assert_allclose(
        np.asarray(get_op("argmax_channel")(x)),
        np.argmax(np.asarray(x), axis=1))
    lhs = jnp.zeros((2, 4))
    out = get_op("fill_element_0index")(
        lhs, jnp.asarray([9.0, 8.0]), jnp.asarray([1.0, 3.0]))
    assert float(out[0, 1]) == 9 and float(out[1, 3]) == 8


def test_storage_ops():
    x = jnp.asarray(_rand(4, 3))
    np.testing.assert_allclose(
        np.asarray(get_op("cast_storage")(x, stype="row_sparse")),
        np.asarray(x))
    kept = get_op("sparse_retain")(x, jnp.asarray([0, 2]))
    assert float(jnp.abs(kept[1]).sum()) == 0
    np.testing.assert_allclose(np.asarray(kept[0]), np.asarray(x[0]))


# -------------------------------------------------------------- linalg


def test_linalg_tail():
    rng = np.random.RandomState(0)
    m = rng.randn(4, 4).astype(np.float64)
    spd = (m @ m.T + 4 * np.eye(4)).astype(np.float32)
    chol = np.linalg.cholesky(spd)
    inv = get_op("linalg_potri")(jnp.asarray(chol))
    np.testing.assert_allclose(np.asarray(inv), np.linalg.inv(spd),
                               rtol=1e-3, atol=1e-4)
    a = _rand(3, 5)
    l, q = get_op("linalg_gelqf")(jnp.asarray(a))
    # oracle products in numpy: a device @ would run the TPU default's
    # bf16 multiplicands and fail the tolerance, not the op
    ln, qn = np.asarray(l), np.asarray(q)
    np.testing.assert_allclose(ln @ qn, a, atol=1e-5)
    np.testing.assert_allclose(qn @ qn.T, np.eye(3), atol=1e-5)
    u, w = get_op("linalg_syevd")(jnp.asarray(spd))
    rec = np.asarray(u).T @ np.diag(np.asarray(w)) @ np.asarray(u)
    np.testing.assert_allclose(rec, spd, rtol=1e-3, atol=1e-3)
    sign, logabs = get_op("linalg_slogdet")(jnp.asarray(spd))
    np.testing.assert_allclose(float(logabs),
                               np.linalg.slogdet(spd)[1], rtol=1e-5)
    tri = get_op("linalg_extracttrian")(jnp.asarray(spd))
    back = get_op("linalg_maketrian")(tri)
    np.testing.assert_allclose(np.asarray(back), np.tril(spd),
                               atol=1e-6)
    b = _rand(4, 4, seed=1)
    out = get_op("linalg_trmm")(jnp.asarray(spd), jnp.asarray(b),
                                alpha=2.0)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.tril(spd) @ b,
                               rtol=1e-5)


def test_linalg_gradients():
    x = sym.Variable("x")
    check_numeric_gradient(sym.linalg_trmm(x, sym.Variable("b")),
                           {"x": _rand(3, 3), "b": _rand(3, 3)})


# ----------------------------------------------------------- optimizer


def test_optimizer_tail_ops():
    w = jnp.ones(4)
    g = jnp.full((4,), 0.5)
    mom = jnp.zeros(4)
    w2, m2 = get_op("nag_mom_update")(w, g, mom, lr=0.1, momentum=0.9)
    # nag: mom=0.9*0+g=0.5; w -= lr*(g + 0.9*mom) = 0.1*(0.5+0.45)
    np.testing.assert_allclose(np.asarray(w2), 1 - 0.095, rtol=1e-6)
    w16 = jnp.ones(4, jnp.bfloat16)
    o16, o32 = get_op("mp_sgd_update")(w16, g.astype(jnp.bfloat16), w,
                                       lr=0.1)
    assert o16.dtype == jnp.bfloat16 and o32.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(o32), 0.95, rtol=1e-6)
    outs = get_op("multi_mp_sgd_mom_update")(
        w16, g.astype(jnp.bfloat16), mom, w,
        w16, g.astype(jnp.bfloat16), mom, w,
        lrs=(0.1, 0.2), wds=(0.0, 0.0), momentum=0.9, num_weights=2)
    assert len(outs) == 6
    np.testing.assert_allclose(np.asarray(outs[5]), 1 - 0.2 * 0.5,
                               rtol=1e-5)
    h = jnp.zeros(4)
    w3, h3 = get_op("adagrad_update")(w, g, h, lr=0.1)
    np.testing.assert_allclose(np.asarray(h3), 0.25, rtol=1e-6)
    accg = jnp.zeros(4)
    accd = jnp.zeros(4)
    w4, g4, d4 = get_op("adadelta_update")(w, g, accg, accd, rho=0.9)
    assert np.asarray(w4).max() < 1.0


def test_optimizer_class_dispatch_new_ops():
    # high-level Optimizer registry picks up nag/adagrad/adadelta
    import mxtpu.optimizer as opt
    for name in ("nag", "adagrad", "adadelta"):
        if name in getattr(opt, "Optimizer", object).__dict__.get(
                "_registry", {}) or True:
            break  # presence checked in test_optimizer.py; skip here


# ------------------------------------------------------------ nn_extra


def test_im2col_col2im():
    x = jnp.asarray(_rand(2, 3, 8, 8))
    cols = get_op("im2col")(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1))
    assert cols.shape == (2, 27, 64)
    w = jnp.asarray(_rand(4, 3, 3, 3, seed=2)).reshape(4, -1)
    y = (w @ cols).reshape(2, 4, 8, 8)
    from jax import lax
    ref = lax.conv_general_dilated(
        x, jnp.asarray(_rand(4, 3, 3, 3, seed=2)), (1, 1),
        [(1, 1), (1, 1)], dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # col2im is the adjoint: <im2col(x), c> == <x, col2im(c)>
    c = jnp.asarray(_rand(2, 27, 64, seed=3))
    lhs = float((cols * c).sum())
    folded = get_op("col2im")(c, output_size=(8, 8), kernel=(3, 3),
                              stride=(1, 1), pad=(1, 1))
    rhs = float((x * folded).sum())
    np.testing.assert_allclose(lhs, rhs, rtol=1e-4)


def test_deformable_conv_zero_offset_matches_conv():
    x = _rand(2, 3, 8, 8)
    w = _rand(4, 3, 3, 3, seed=1)
    off = np.zeros((2, 18, 8, 8), np.float32)
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=4, pad=(1, 1), no_bias=True)
    out = get_op("_contrib_DeformableConvolution")(
        jnp.asarray(x), jnp.asarray(off), jnp.asarray(w),
        kernel=(3, 3), stride=(1, 1), pad=(1, 1), num_filter=4,
        no_bias=True)
    np.testing.assert_allclose(np.asarray(out), ref.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_deformable_conv_gradient():
    x = sym.Variable("data")
    off = sym.Variable("offset")
    w = sym.Variable("weight")
    out = sym._contrib_DeformableConvolution(
        x, off, w, kernel=(2, 2), stride=(1, 1), pad=(0, 0),
        num_filter=2, no_bias=True)
    # offsets pinned mid-cell (+0.5): bilinear sampling is non-smooth
    # at integer grid positions, where finite differences straddle the
    # kink; tiny case keeps the probe count tractable
    offset = np.full((1, 8, 3, 3), 0.5, np.float32)
    check_numeric_gradient(
        out, {"data": _rand(1, 2, 4, 4),
              "offset": offset,
              "weight": _rand(2, 2, 2, 2, seed=2)},
        grad_nodes=["data", "weight", "offset"],
        rtol=0.06, atol=5e-3)


def test_roialign_and_psroi():
    x = jnp.asarray(_rand(1, 4, 8, 8))
    rois = jnp.asarray([[0, 0, 0, 7, 7]], jnp.float32)
    ra = get_op("_contrib_ROIAlign")(x, rois, pooled_size=(4, 4),
                                     spatial_scale=1.0)
    assert ra.shape == (1, 4, 4, 4)
    # linear ramp: bilinear sampling is exact, and symmetric sample
    # points average to the ramp's center = its mean
    ramp = jnp.broadcast_to(
        jnp.arange(8.0)[None, None, :, None], (1, 1, 8, 8))
    ra1 = get_op("_contrib_ROIAlign")(
        ramp, jnp.asarray([[0, 0, 0, 7, 7]], jnp.float32),
        pooled_size=(1, 1), sample_ratio=4)
    np.testing.assert_allclose(float(ra1[0, 0, 0, 0]),
                               float(ramp.mean()), atol=1e-5)
    data_ps = jnp.asarray(_rand(1, 2 * 9, 8, 8))
    ps = get_op("_contrib_PSROIPooling")(
        data_ps, rois, spatial_scale=1.0, output_dim=2, pooled_size=3)
    assert ps.shape == (1, 2, 3, 3)
    dps = get_op("_contrib_DeformablePSROIPooling")(
        data_ps, rois, jnp.zeros((1, 2, 9)), spatial_scale=1.0,
        output_dim=2, pooled_size=3, trans_std=0.1)
    np.testing.assert_allclose(np.asarray(dps), np.asarray(ps),
                               atol=1e-5)


def test_adaptive_and_resize():
    x = jnp.asarray(_rand(2, 3, 6, 6))
    out = get_op("_contrib_AdaptiveAvgPooling2D")(x, output_size=(2, 2))
    ref = np.asarray(x).reshape(2, 3, 2, 3, 2, 3).mean(axis=(3, 5))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)
    up = get_op("_contrib_BilinearResize2D")(x, height=11, width=11)
    assert up.shape == (2, 3, 11, 11)
    # corners preserved under align_corners
    np.testing.assert_allclose(np.asarray(up)[..., 0, 0],
                               np.asarray(x)[..., 0, 0], atol=1e-5)
    np.testing.assert_allclose(np.asarray(up)[..., -1, -1],
                               np.asarray(x)[..., -1, -1], atol=1e-5)


def test_sync_batch_norm_cross_device():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    x = _rand(8, 6, 4, 4)
    gamma = np.ones(6, np.float32)
    beta = np.zeros(6, np.float32)
    mean0 = np.zeros(6, np.float32)
    var0 = np.ones(6, np.float32)
    mesh = Mesh(np.asarray(devs[:4]), ("dp",))
    fn = get_op("_contrib_SyncBatchNorm")

    def local(xb, g, b, m, v):
        return fn(xb, g, b, m, v, axis_name="dp")

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P("dp"), P(), P(), P(), P()),
        out_specs=(P("dp"), P(), P()))
    out, mean, var = sharded(jnp.asarray(x), jnp.asarray(gamma),
                             jnp.asarray(beta), jnp.asarray(mean0),
                             jnp.asarray(var0))
    # cross-device stats == full-batch BN
    ref_out, ref_mean, ref_var = get_op("BatchNorm")(
        jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta),
        jnp.asarray(mean0), jnp.asarray(var0), eps=1e-3)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(ref_mean),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-4, atol=1e-5)


def test_index_copy():
    out = get_op("_contrib_index_copy")(
        jnp.zeros((4, 2)), jnp.asarray([1, 3]), jnp.ones((2, 2)))
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), [0, 2, 0, 2])


# ----------------------------------------------------------- quantized


def test_quantized_conv_fc_vs_float():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    ax, aw = np.abs(x).max(), np.abs(w).max()

    def q(a, amax):
        return np.clip(np.round(a / amax * 127), -127, 127).astype(
            np.int8)

    out32, lo, hi = get_op("_contrib_quantized_conv")(
        jnp.asarray(q(x, ax)), jnp.asarray(q(w, aw)),
        jnp.asarray(-ax), jnp.asarray(ax),
        jnp.asarray(-aw), jnp.asarray(aw),
        kernel=(3, 3), stride=(1, 1), pad=(1, 1), num_filter=4)
    assert out32.dtype == jnp.int32
    unit = (2 * ax / 254) * (2 * aw / 254)
    from jax import lax
    ref = np.asarray(lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    got = np.asarray(out32, np.float32) * unit
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.02
    # requantize to int8 keeps values within tolerance
    q8, qlo, qhi = get_op("_contrib_requantize")(out32, lo, hi)
    scale8 = 254.0 / (float(qhi) - float(qlo))
    back = np.asarray(q8, np.float32) / scale8
    assert np.abs(back - ref).max() / np.abs(ref).max() < 0.03
    # fc
    xf = rng.randn(3, 24).astype(np.float32)
    wf = rng.randn(5, 24).astype(np.float32)
    axf, awf = np.abs(xf).max(), np.abs(wf).max()
    o32, lo2, hi2 = get_op("_contrib_quantized_fully_connected")(
        jnp.asarray(q(xf, axf)), jnp.asarray(q(wf, awf)),
        jnp.asarray(-axf), jnp.asarray(axf),
        jnp.asarray(-awf), jnp.asarray(awf), num_hidden=5)
    gotf = np.asarray(o32, np.float32) * (2 * axf / 254) * \
        (2 * awf / 254)
    reff = xf @ wf.T
    assert np.abs(gotf - reff).max() / np.abs(reff).max() < 0.02


def test_quantized_pool_flatten_act_concat():
    rng = np.random.RandomState(1)
    x8 = rng.randint(-127, 128, (2, 3, 4, 4)).astype(np.int8)
    lo = jnp.asarray(-1.0)
    hi = jnp.asarray(1.0)
    p, plo, phi = get_op("_contrib_quantized_pooling")(
        jnp.asarray(x8), lo, hi, kernel=(2, 2), pool_type="max",
        stride=(2, 2))
    np.testing.assert_array_equal(
        np.asarray(p),
        np.asarray(x8).reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5)))
    f, _, _ = get_op("_contrib_quantized_flatten")(jnp.asarray(x8), lo,
                                                   hi)
    assert f.shape == (2, 48)
    a, _, _ = get_op("_contrib_quantized_act")(jnp.asarray(x8), lo, hi)
    assert int(np.asarray(a).min()) >= 0
    c, clo, chi = get_op("_contrib_quantized_concat")(
        jnp.asarray(x8), jnp.asarray(x8), lo, hi, lo, hi, num_args=2)
    assert c.shape == (2, 6, 4, 4)
    np.testing.assert_array_equal(np.asarray(c[:, :3]),
                                  np.asarray(c[:, 3:]))


# -------------------------------------------------------- random ops


def test_registered_sampling_ops():
    key = jax.random.PRNGKey(7)
    u = get_op("_random_uniform")(key, shape=(2000,), low=-1.0,
                                  high=3.0)
    assert -1 <= float(u.min()) and float(u.max()) <= 3
    assert abs(float(u.mean()) - 1.0) < 0.1
    sg = get_op("_sample_gamma")(key, jnp.asarray([2.0, 6.0]),
                                 jnp.asarray([1.0, 0.5]), shape=(1500,))
    assert abs(float(sg[0].mean()) - 2.0) < 0.2
    assert abs(float(sg[1].mean()) - 3.0) < 0.25
    d, lp = get_op("_sample_multinomial")(
        key, jnp.asarray([0.25, 0.75]), shape=(8,), get_prob=True)
    assert d.shape == (8,) and lp.shape == (8,)
    z, cnt = get_op("_sample_unique_zipfian")(key, range_max=5000,
                                              shape=(256,))
    # zipfian mass concentrates at small ids
    assert float(jnp.median(z)) < 500


# ---------------------------------------------- registered contrib ops


def test_registered_contrib_ops_match_python_surface():
    from mxtpu.ndarray import contrib
    x = _rand(3, 8)
    np.testing.assert_allclose(
        np.asarray(get_op("_contrib_quadratic")(jnp.asarray(x), a=1.0,
                                                b=2.0, c=3.0)),
        contrib.quadratic(nd.array(x), a=1.0, b=2.0, c=3.0).asnumpy())
    f = get_op("_contrib_fft")(jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray(get_op("_contrib_ifft")(f)) / 8, x, atol=1e-5)
    boxes = np.asarray([[0.0, 0, 0, 2, 2], [0.9, 0, 0, 2, 2]],
                       np.float32)
    scored = np.concatenate([np.asarray([[0.9], [0.8]], np.float32),
                             boxes[:, 1:]], axis=1)
    data = np.concatenate([np.zeros((2, 1), np.float32), scored],
                          axis=1)  # [cls, score, x1 y1 x2 y2]
    out = get_op("_contrib_box_nms")(jnp.asarray(data),
                                     overlap_thresh=0.5)
    assert float(out[1, 1]) == -1  # suppressed duplicate
    rm, cm = get_op("_contrib_bipartite_matching")(
        jnp.asarray([[0.9, 0.1], [0.8, 0.7]]), threshold=0.05)
    assert rm.tolist() == [0.0, 1.0]


# ------------------------------------------------- bulked execution


def _mknet():
    from mxtpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.BatchNorm(axis=-1),
            nn.Dense(4))
    net.initialize(init="xavier")
    return net


def test_run_steps_matches_sequential():
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    rng = np.random.RandomState(0)
    X = rng.randn(40, 8).astype(np.float32)
    Y = rng.randint(0, 4, (40,)).astype(np.float32)
    net1, net2 = _mknet(), _mknet()
    net1(nd.array(X[:8]))
    net2(nd.array(X[:8]))
    for p1, p2 in zip(net1.collect_params().values(),
                      net2.collect_params().values()):
        p2._data._data = jnp.array(np.asarray(p1._data._data))
    mk = lambda n: parallel.build_train_step(  # noqa: E731
        n, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9})
    s1, s2 = mk(net1), mk(net2)
    seq = [float(s1(nd.array(X[i * 8:(i + 1) * 8]),
                    nd.array(Y[i * 8:(i + 1) * 8])).asscalar())
           for i in range(5)]
    bulk = s2.run_steps(nd.array(X), nd.array(Y), steps=5)
    np.testing.assert_allclose(bulk.asnumpy(), seq, rtol=1e-5,
                               atol=1e-6)
    for k, (p1, p2) in zip(
            net1.collect_params(),
            zip(net1.collect_params().values(),
                net2.collect_params().values())):
        np.testing.assert_allclose(
            np.asarray(p1._data._data), np.asarray(p2._data._data),
            rtol=1e-5, atol=1e-6, err_msg=k)


def test_run_steps_reuse_batch_converges():
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    net = _mknet()
    rng = np.random.RandomState(1)
    X = rng.randn(16, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    s = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.5})
    losses = s.run_steps(nd.array(X), nd.array(Y), steps=12,
                         reuse_batch=True).asnumpy()
    assert losses[-1] < losses[0] * 0.7


def test_engine_bulk_size_api():
    from mxtpu import engine
    prev = engine.set_bulk_size(32)
    assert engine.bulk_size() == 32
    with engine.bulk(8):
        assert engine.bulk_size() == 8
    assert engine.bulk_size() == 32
    engine.set_bulk_size(prev)


def test_flash_attention_fallback_warns_once(monkeypatch):
    import importlib
    fa = importlib.import_module("mxtpu.kernels.flash_attention")
    # force the pallas path eligible (interpret mode) so the
    # shape-based fallback triggers its warning; with pallas disabled
    # (plain CPU) the reference path is intended and must stay silent
    monkeypatch.setenv("MXTPU_PALLAS", "interpret")
    # unaligned T is padded-and-masked, NOT a fallback: silent
    q = jnp.asarray(_rand(1, 2, 9, 16))  # T=9 not a multiple of 8
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fa.flash_attention(q, q, q)
    assert not [x for x in w if "falling back" in str(x.message)]
    # causal cross lengths (Tq % 8 != Tk % 8) hit the kernel too now:
    # static valid_kv masking + the explicit diagonal keep it fused
    k = jnp.asarray(_rand(1, 2, 16, 16))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fa.flash_attention(q, k, k, causal=True)
    assert not [x for x in w if "falling back" in str(x.message)]
    # the one remaining fallback is head_dim > 512 — warns once per
    # distinct (q, k) shape tuple, so a training loop replaying the
    # same shape every step warns exactly once, but a NEW shape (e.g.
    # a different seqlen bucket) gets its own warning
    wide = jnp.asarray(_rand(1, 2, 8, 520))
    wide2 = jnp.asarray(_rand(1, 2, 16, 520))  # same D, new shape
    fa._warned_fallback.clear()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fa.flash_attention(wide, wide, wide)
        fa.flash_attention(wide, wide, wide)
        fa.flash_attention(wide2, wide2, wide2)
        fa.flash_attention(wide2, wide2, wide2)
    msgs = [x for x in w if "flash_attention falling back"
            in str(x.message)]
    assert len(msgs) == 2  # once per distinct shape tuple
    monkeypatch.setenv("MXTPU_PALLAS", "0")
    fa._warned_fallback.clear()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fa.flash_attention(wide, wide, wide)
    assert not [x for x in w if "falling back" in str(x.message)]


def test_legacy_surface_tail():
    x = jnp.asarray(_rand(2, 3, 4, 4))
    sa = get_op("SoftmaxActivation")(x, mode="channel")
    np.testing.assert_allclose(np.asarray(sa.sum(axis=1)), 1.0,
                               rtol=1e-5)
    si = get_op("SoftmaxActivation")(x)
    np.testing.assert_allclose(
        np.asarray(si.reshape(2, -1).sum(axis=1)), 1.0, rtol=1e-5)
    # v1 aliases resolve to the modern rules
    assert get_op("Convolution_v1") is get_op("Convolution")
    assert get_op("Pooling_v1") is get_op("Pooling")
    assert get_op("BatchNorm_v1") is get_op("BatchNorm")
    # KL sparse reg: identity forward, penalty-shifted backward
    f = get_op("IdentityAttachKLSparseReg")
    xx = jnp.asarray(np.full((4, 3), 0.5, np.float32))
    np.testing.assert_allclose(np.asarray(f(xx)), np.asarray(xx))
    g = jax.grad(lambda v: jnp.sum(f(v, sparseness_target=0.1,
                                     penalty=0.01)))(xx)
    # rho_hat=0.5 > rho=0.1 → penalty pushes activations DOWN (grad > 1)
    assert float(g.min()) > 1.0


def test_registry_size_target():
    """VERDICT r2 item 3: >= 300 distinct lowering rules."""
    from mxtpu.ops.registry import OP_REGISTRY
    names = list_ops()
    rules = {id(OP_REGISTRY.get(n).fn) for n in names}
    assert len(names) >= 380, len(names)
    assert len(rules) >= 300, len(rules)


def test_count_sketch_reference_arg_order():
    """Registered op takes (data, h, s) — the reference signature."""
    d = jnp.asarray([[1.0, 2.0, 3.0]])
    h = jnp.asarray([0, 2, 0])
    s = jnp.asarray([1.0, -1.0, 1.0])
    out = get_op("_contrib_count_sketch")(d, h, s, out_dim=3)
    np.testing.assert_allclose(np.asarray(out), [[4.0, 0.0, -2.0]])
    from mxtpu.ndarray import contrib
    from mxtpu import nd
    out2 = contrib.count_sketch(nd.array(np.asarray(d)),
                                nd.array(np.asarray(h, np.float32)),
                                nd.array(np.asarray(s)), 3)
    np.testing.assert_allclose(out2.asnumpy(), [[4.0, 0.0, -2.0]])


def test_quantized_conv_nhwc_layout():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 8, 8, 3).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)  # OHWI
    ax, aw = np.abs(x).max(), np.abs(w).max()

    def q(a, amax):
        return np.clip(np.round(a / amax * 127), -127, 127).astype(
            np.int8)

    out32, lo, hi = get_op("_contrib_quantized_conv")(
        jnp.asarray(q(x, ax)), jnp.asarray(q(w, aw)),
        jnp.asarray(-ax), jnp.asarray(ax), jnp.asarray(-aw),
        jnp.asarray(aw), kernel=(3, 3), stride=(1, 1), pad=(1, 1),
        num_filter=4, layout="NHWC")
    from jax import lax
    ref = np.asarray(lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "OHWI", "NHWC")))
    unit = (2 * ax / 254) * (2 * aw / 254)
    got = np.asarray(out32, np.float32) * unit
    assert got.shape == ref.shape
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.02


def test_amp_multicast_ints_pass_through():
    outs = get_op("amp_multicast")(
        jnp.ones(2, jnp.float32), jnp.ones(2, jnp.int32),
        jnp.ones(2, jnp.bfloat16), num_outputs=3)
    assert outs[0].dtype == jnp.float32
    assert outs[1].dtype == jnp.int32  # ints never vote or get cast
    assert outs[2].dtype == jnp.float32


def test_multinomial_multidim_shape():
    # reference: output shape is data.shape[:-1] + shape, NOT a
    # flattened trailing axis (r3 advisor, random_ops.py)
    key = jnp.asarray([0, 7], jnp.uint32)
    p = jnp.asarray([[0.3, 0.7], [0.5, 0.5], [0.9, 0.1]])
    d = get_op("_sample_multinomial")(key, p, shape=(4, 5))
    assert d.shape == (3, 4, 5)
    d1, lp1 = get_op("_sample_multinomial")(
        key, p[0], shape=(2, 3), get_prob=True)
    assert d1.shape == (2, 3) and lp1.shape == (2, 3)


def test_num_outputs_fn_without_attrs():
    # attrs reach num_outputs_fn without Param defaults applied; a
    # missing attr must not raise (r3 advisor, ops_extra.py)
    for name, factor in [("multi_mp_sgd_update", 2),
                         ("multi_mp_sgd_mom_update", 3)]:
        fn = get_op(name).num_outputs_fn
        assert fn({}) == factor  # degenerate 1-weight default
        assert fn({"num_weights": 4}) == 4 * factor
    # amp_multicast's output count is its input count — a missing
    # num_outputs must fail loudly, not silently declare 1
    from mxtpu.base import MXNetError
    fn = get_op("amp_multicast").num_outputs_fn
    assert fn({"num_outputs": 3}) == 3
    with pytest.raises(MXNetError):
        fn({})


def test_roi_align_position_sensitive_raises():
    from mxtpu.base import MXNetError
    data = jnp.ones((1, 4, 8, 8))
    rois = jnp.asarray([[0.0, 0.0, 0.0, 4.0, 4.0]])
    with pytest.raises(MXNetError):
        get_op("_contrib_ROIAlign")(data, rois, pooled_size=(2, 2),
                                    position_sensitive=True)
    # adaptive (sample_ratio<=0) approximates with a fixed 2x2 grid
    out = get_op("_contrib_ROIAlign")(data, rois, pooled_size=(2, 2),
                                      sample_ratio=-1)
    assert out.shape == (1, 4, 2, 2)
    assert bool(jnp.all(jnp.isfinite(out)))
