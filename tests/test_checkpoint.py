"""Checkpoint/resume completeness (VERDICT item 10; reference
``Trainer.save_states``†, ``Updater.get_states``†, SURVEY §5.4
preemption-safe training)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.gluon import nn, loss as gloss


def _net():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize(init="xavier")
    return net


def _data(seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(32, 6).astype(np.float32)
    y = (X.sum(1) > 0).astype(np.float32)
    return nd.array(X), nd.array(y)


def _train_eager(net, trainer, steps, seed0=0):
    L = gloss.SoftmaxCrossEntropyLoss()
    losses = []
    for s in range(steps):
        x, y = _data(seed0 + s)
        with autograd.record():
            l = L(net(x), y)
        l.backward()
        trainer.step(32)
        losses.append(float(l.mean().asnumpy()))
    return losses


def test_trainer_save_load_states_resume(tmp_path):
    """train A 10 steps, checkpoint, train A 5 more; B restores the
    checkpoint and must reproduce A's last 5 steps exactly (adam state
    incl. step counter must round-trip)."""
    mx.random.seed(7)
    np.random.seed(7)
    net_a = _net()
    tr_a = gluon.Trainer(net_a.collect_params(), "adam",
                         {"learning_rate": 0.01})
    _train_eager(net_a, tr_a, 10)
    net_a.save_parameters(str(tmp_path / "net.params"))
    tr_a.save_states(str(tmp_path / "trainer.states"))
    cont_a = _train_eager(net_a, tr_a, 5, seed0=100)

    mx.random.seed(7)
    np.random.seed(7)
    net_b = _net()
    # shapes must materialize before load_parameters
    net_b(nd.array(np.zeros((1, 6), np.float32)))
    net_b.load_parameters(str(tmp_path / "net.params"))
    tr_b = gluon.Trainer(net_b.collect_params(), "adam",
                         {"learning_rate": 0.01})
    # prime the updater indices with one dummy zero-lr step?  No — the
    # reference restores states cold; ours must too
    tr_b.load_states(str(tmp_path / "trainer.states"))
    cont_b = _train_eager(net_b, tr_b, 5, seed0=100)
    np.testing.assert_allclose(cont_a, cont_b, rtol=1e-6, atol=1e-7)
    for (ka, pa), (kb, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-6,
                                   atol=1e-7, err_msg=ka)


def test_trainstep_save_load_states_resume(tmp_path):
    """Same resume contract for the compiled train step."""
    from mxtpu import parallel
    mx.random.seed(3)
    net_a = _net()
    step_a = parallel.build_train_step(
        net_a, gloss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01})
    for s in range(10):
        x, y = _data(s)
        step_a(x, y)
    net_a.save_parameters(str(tmp_path / "net.params"))
    step_a.save_states(str(tmp_path / "step.states"))
    cont_a = [float(step_a(*_data(100 + s)).asscalar())
              for s in range(5)]

    mx.random.seed(3)
    net_b = _net()
    net_b(nd.array(np.zeros((1, 6), np.float32)))
    net_b.load_parameters(str(tmp_path / "net.params"))
    step_b = parallel.build_train_step(
        net_b, gloss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01})
    step_b.load_states(str(tmp_path / "step.states"),
                       x_example=_data(0)[0])
    cont_b = [float(step_b(*_data(100 + s)).asscalar())
              for s in range(5)]
    np.testing.assert_allclose(cont_a, cont_b, rtol=1e-5, atol=1e-6)


def test_updater_states_roundtrip():
    from mxtpu import optimizer as opt_mod
    opt = opt_mod.create("adam", learning_rate=0.1)
    upd = opt_mod.get_updater(opt)
    w = nd.array(np.ones((4,), np.float32))
    g = nd.array(np.full((4,), 0.5, np.float32))
    upd(0, g, w)
    blob = upd.get_states(dump_optimizer=True)
    upd2 = opt_mod.get_updater(opt_mod.create("adam",
                                              learning_rate=0.9))
    upd2.set_states(blob)
    assert upd2.optimizer.learning_rate == 0.1  # optimizer restored
    # state arrays equal
    s1 = upd.states[0]
    s2 = upd2.states[0]
    for a, b in zip(s1, s2):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
