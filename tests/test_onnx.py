"""ONNX interchange: wire-format codec vs a protoc oracle, symbol
round-trips through real .onnx files, metadata, error paths.

Reference: ``python/mxnet/contrib/onnx/``† (mx2onnx/onnx2mx),
``tests/python-pytest/onnx/``†.
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu import symbol as sym
from mxtpu.base import MXNetError
from mxtpu.contrib.onnx import (export_model, get_model_metadata,
                                import_model)
from mxtpu.contrib.onnx import _proto as P
from mxtpu.gluon import nn

# faithful subset of onnx.proto† for the protoc oracle
_ONNX_PROTO = """
syntax = "proto3";
package oracle;
message AttributeProto {
  string name = 1; float f = 2; int64 i = 3; bytes s = 4;
  TensorProto t = 5; repeated float floats = 7; repeated int64 ints = 8;
  repeated bytes strings = 9; int32 type = 20;
}
message ValueInfoProto { string name = 1; TypeProto type = 2; }
message NodeProto {
  repeated string input = 1; repeated string output = 2;
  string name = 3; string op_type = 4;
  repeated AttributeProto attribute = 5;
}
message TensorProto {
  repeated int64 dims = 1; int32 data_type = 2;
  repeated float float_data = 4; repeated int32 int32_data = 5;
  repeated int64 int64_data = 7; string name = 8; bytes raw_data = 9;
  repeated double double_data = 10; repeated uint64 uint64_data = 11;
}
message TensorShapeProto {
  message Dimension { int64 dim_value = 1; string dim_param = 2; }
  repeated Dimension dim = 1;
}
message TypeProto {
  message Tensor { int32 elem_type = 1; TensorShapeProto shape = 2; }
  Tensor tensor_type = 1;
}
message OperatorSetIdProto { string domain = 1; int64 version = 2; }
message GraphProto {
  repeated NodeProto node = 1; string name = 2;
  repeated TensorProto initializer = 5;
  repeated ValueInfoProto input = 11;
  repeated ValueInfoProto output = 12;
}
message ModelProto {
  int64 ir_version = 1; string producer_name = 2;
  string producer_version = 3; GraphProto graph = 7;
  repeated OperatorSetIdProto opset_import = 8;
}
"""


@pytest.fixture(scope="module")
def oracle(tmp_path_factory):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    d = tmp_path_factory.mktemp("onnx_oracle")
    (d / "oracle.proto").write_text(_ONNX_PROTO)
    subprocess.run(["protoc", f"--python_out={d}", "oracle.proto"],
                   cwd=d, check=True)
    sys.path.insert(0, str(d))
    try:
        import oracle_pb2
    finally:
        sys.path.pop(0)
    return oracle_pb2


def _toy_model_bytes():
    g = P.Graph(name="g")
    g.inputs.append(("data", P.FLOAT, (1, 2)))
    g.outputs.append(("out", P.FLOAT, ()))
    g.initializers.append(P.Tensor.from_numpy(
        "w", np.arange(6, dtype=np.float32).reshape(3, 2)))
    g.nodes.append(P.Node(op_type="Gemm", name="fc",
                          inputs=("data", "w"), outputs=("out",),
                          attributes={"alpha": 1.0, "transB": 1,
                                      "perm": (0, 1),
                                      "mode": "test"}))
    return P.Model(graph=g).encode()


def test_codec_against_protoc_oracle(oracle):
    m = oracle.ModelProto()
    m.ParseFromString(_toy_model_bytes())
    assert m.producer_name == "mxtpu"
    assert m.opset_import[0].version == 13
    g = m.graph
    assert [n.name for n in g.node] == ["fc"]
    node = g.node[0]
    assert node.op_type == "Gemm"
    assert list(node.input) == ["data", "w"]
    attrs = {a.name: a for a in node.attribute}
    assert attrs["alpha"].f == 1.0 and attrs["transB"].i == 1
    assert list(attrs["perm"].ints) == [0, 1]
    assert attrs["mode"].s == b"test"
    t = g.initializer[0]
    assert list(t.dims) == [3, 2] and t.data_type == P.FLOAT
    np.testing.assert_array_equal(
        np.frombuffer(t.raw_data, np.float32).reshape(3, 2),
        np.arange(6, dtype=np.float32).reshape(3, 2))
    assert g.input[0].name == "data"
    dims = g.input[0].type.tensor_type.shape.dim
    assert [d.dim_value for d in dims] == [1, 2]

    # reverse direction: oracle-encoded stream decodes with our codec
    blob = m.SerializeToString()
    m2 = P.Model.decode(blob)
    assert m2.graph.nodes[0].op_type == "Gemm"
    assert m2.graph.nodes[0].attributes["perm"] == (0, 1)
    assert m2.graph.initializers[0].to_numpy().shape == (3, 2)
    assert m2.graph.inputs[0] == ("data", P.FLOAT, (1, 2))


def _export_net(net, x, tmp_path, name):
    net.initialize(init="xavier")
    y0 = net(x).asnumpy()
    prefix = str(tmp_path / name)
    sym_file, param_file = net.export(prefix)
    s = sym.load(sym_file)
    params = nd.load(param_file)
    onnx_file = export_model(s, params, input_shape=tuple(x.shape),
                             onnx_file_path=str(tmp_path /
                                                f"{name}.onnx"))
    return y0, onnx_file


def _eval_imported(onnx_file, x):
    s2, args, auxs = import_model(onnx_file)
    bindings = {"data": x}
    bindings.update(args)
    bindings.update(auxs)
    names = set(s2.list_inputs())
    bindings = {k: v for k, v in bindings.items() if k in names}
    return s2.eval(**bindings)[0].asnumpy()


def test_mlp_roundtrip(tmp_path):
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"),
            nn.Dropout(0.5),
            nn.Dense(5))
    x = nd.array(np.random.RandomState(0)
                 .randn(3, 8).astype(np.float32))
    y0, onnx_file = _export_net(net, x, tmp_path, "mlp")
    y1 = _eval_imported(onnx_file, x)
    np.testing.assert_allclose(y0, y1, rtol=1e-5, atol=1e-5)


def test_convnet_roundtrip(tmp_path):
    mx.random.seed(1)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, padding=1, activation="relu"),
            nn.BatchNorm(),
            nn.MaxPool2D(2, strides=2),
            nn.Flatten(),
            nn.Dense(6))
    x = nd.array(np.random.RandomState(1)
                 .randn(2, 3, 8, 8).astype(np.float32))
    y0, onnx_file = _export_net(net, x, tmp_path, "cnn")
    y1 = _eval_imported(onnx_file, x)
    np.testing.assert_allclose(y0, y1, rtol=1e-4, atol=1e-4)


def test_metadata(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    x = nd.zeros((2, 3))
    _, onnx_file = _export_net(net, x, tmp_path, "meta")
    meta = get_model_metadata(onnx_file)
    assert meta["input_tensor_data"][0][0] == "data"
    assert meta["input_tensor_data"][0][1] == (2, 3)
    assert len(meta["output_tensor_data"]) == 1


def test_resnet18_roundtrip(tmp_path):
    """Model-zoo coverage: ResNet-18 (residual adds, BN, global pool)
    round-trips bit-exact through a real .onnx file."""
    mx.random.seed(0)
    from mxtpu.gluon.model_zoo import vision
    net = vision.get_resnet(1, 18, classes=10)
    x = nd.array(np.random.RandomState(0)
                 .randn(1, 3, 32, 32).astype(np.float32))
    y0, onnx_file = _export_net(net, x, tmp_path, "r18")
    y1 = _eval_imported(onnx_file, x)
    np.testing.assert_allclose(y0, y1, rtol=1e-5, atol=1e-5)


def test_unsupported_op_raises(tmp_path):
    data = sym.var("data")
    s = sym.sort(data)  # no ONNX converter registered
    with pytest.raises(MXNetError, match="no converter"):
        export_model(s, {}, input_shape=(2, 2),
                     onnx_file_path=str(tmp_path / "x.onnx"))


def test_symbol_api_bn_fix_gamma_and_bare_transpose(tmp_path):
    """Symbol-API graphs: fix_gamma=True BN (mx ignores stored gamma)
    and axes-less transpose (reverse dims) export/import correctly."""
    rng = np.random.RandomState(5)
    data = sym.var("data")
    bn = sym.BatchNorm(data, name="bn", use_global_stats=True)
    out = sym.transpose(bn[0], name="t")  # no axes: reverse dims
    params = {"bn_gamma": nd.array(rng.rand(3).astype(np.float32) + 2),
              "bn_beta": nd.array(rng.rand(3).astype(np.float32)),
              "bn_moving_mean": nd.array(
                  rng.rand(3).astype(np.float32)),
              "bn_moving_var": nd.array(
                  rng.rand(3).astype(np.float32) + 0.5)}
    x = nd.array(rng.randn(2, 3, 4, 4).astype(np.float32))
    y0 = out.eval(data=x, **params)[0].asnumpy()
    f = export_model(out, params, input_shape=(2, 3, 4, 4),
                     onnx_file_path=str(tmp_path / "bn.onnx"))
    y1 = _eval_imported(f, x)
    assert y1.shape == y0.shape == (4, 4, 3, 2)
    np.testing.assert_allclose(y0, y1, rtol=1e-5, atol=1e-5)


def test_external_tensor_storage_forms(oracle):
    """Tensors from other exporters: f16 bit patterns in int32_data,
    doubles in double_data, floats in float_data — all decode."""
    t = oracle.TensorProto(name="h", dims=[2], data_type=P.FLOAT16)
    t.int32_data.extend([0x3C00, 0xC000])  # bit patterns for 1.0, -2.0
    got = P.Tensor.decode(t.SerializeToString()).to_numpy()
    np.testing.assert_array_equal(got,
                                  np.array([1.0, -2.0], np.float16))

    t = oracle.TensorProto(name="d", dims=[2], data_type=P.DOUBLE)
    t.double_data.extend([1.5, -2.25])
    got = P.Tensor.decode(t.SerializeToString()).to_numpy()
    np.testing.assert_array_equal(got, np.array([1.5, -2.25]))

    t = oracle.TensorProto(name="f", dims=[3], data_type=P.FLOAT)
    t.float_data.extend([0.5, 1.5, 2.5])
    got = P.Tensor.decode(t.SerializeToString()).to_numpy()
    np.testing.assert_array_equal(got,
                                  np.array([0.5, 1.5, 2.5],
                                           np.float32))


def test_import_rejects_unsupported_semantics():
    from mxtpu.contrib.onnx import import_graph
    w = P.Tensor.from_numpy("w", np.ones((4, 3), np.float32))

    def graph_with(node):
        g = P.Graph()
        g.inputs.append(("data", P.FLOAT, (2, 3)))
        g.outputs.append((node.outputs[0], P.FLOAT, ()))
        g.initializers.append(w)
        g.nodes.append(node)
        return g

    with pytest.raises(MXNetError, match="alpha/beta"):
        import_graph(graph_with(P.Node(
            op_type="Gemm", name="g", inputs=("data", "w"),
            outputs=("y",), attributes={"transB": 1, "alpha": 0.5})))
    with pytest.raises(MXNetError, match="auto_pad"):
        import_graph(graph_with(P.Node(
            op_type="MaxPool", name="p", inputs=("data",),
            outputs=("y",),
            attributes={"kernel_shape": (2, 2),
                        "auto_pad": "SAME_UPPER"})))
    with pytest.raises(MXNetError, match="ceil_mode"):
        import_graph(graph_with(P.Node(
            op_type="MaxPool", name="p", inputs=("data",),
            outputs=("y",),
            attributes={"kernel_shape": (2, 2), "ceil_mode": 1})))
