"""mxmem — static memory-footprint analysis + committed HBM ledgers
(ISSUE 20).

Covers: the decomposition/attribution units on synthetic programs and
mem stats; the five hazard rules, each tripped by EXACTLY one seeded
perturbation with the buffer and site named (drop ``donate`` →
donation-missed; ``zero=0`` under a declared-ZeRO record →
zero-replication; grow the slot table past the declared
``kv_cache_spec`` → kv-overcommit; pad past the waste threshold →
padding-waste; shrink a device-class budget → budget-exceeded); the
ONE-memory-analyzer migration (committed hlocheck peak-bytes budgets
stay byte-compatible with the ledgers); the ``python -m tools.mxmem``
CLI exit-code/byte-determinism contract; the ``MXTPU_MEM_AUDIT``
runtime knob; and the committed-ledger acceptance proofs (bert_zero
opt-state ≤ planned shard geometry, generate_decode KV table ==
declared geometry + scratch slot).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mxtpu import analysis, nd, parallel
from mxtpu.analysis import memflow
from mxtpu.base import MXNetError
from mxtpu.gluon import nn

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a synthetic reduce-scatter program for collective-scratch
# attribution: 1024 f32 elems scattered to 128 per shard
RS_SYNTH = """HloModule rssynth

%sum (x: f32[], y: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %z = f32[] add(f32[] %x, f32[] %y)
}

ENTRY %main (p0: f32[1024]) -> f32[128] {
  %p0 = f32[1024]{0} parameter(0)
  ROOT %rs = f32[128]{0} reduce-scatter(f32[1024]{0} %p0), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, to_apply=%sum
}
"""

CLEAN_F32 = """HloModule clean

ENTRY %main (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  ROOT %r = f32[4]{0} add(f32[4]{0} %p0, f32[4]{0} %p0)
}
"""


def _rules(hazards):
    return [h["rule"] for h in hazards]


class _FakeMA:
    argument_size_in_bytes = 1000
    output_size_in_bytes = 100
    temp_size_in_bytes = 500
    alias_size_in_bytes = 40
    generated_code_size_in_bytes = 7


class _FakeCompiled:
    def __init__(self, text=CLEAN_F32, ma=None):
        self._text = text
        self._ma = ma if ma is not None else _FakeMA()

    def as_text(self):
        return self._text

    def memory_analysis(self):
        return self._ma


# ------------------------------------------------ attribution units

def test_mem_stats_hbm_peak_convention():
    """hbm_peak is temp + argument — the repo-wide convention every
    committed peak-bytes budget pins, now owned by memflow alone."""
    mem = memflow.mem_stats(_FakeCompiled())
    assert mem["hbm_peak"] == 1500
    assert mem["alias_size_in_bytes"] == 40
    # parallel._mem_stats is the same analyzer
    assert parallel._mem_stats(_FakeCompiled()) == mem
    # a backend that doesn't report yields None, not a crash
    class _NoMA:
        def memory_analysis(self):
            raise RuntimeError("unimplemented")
    assert memflow.mem_stats(_NoMA()) is None


def test_decompose_categories():
    mem = {"argument_size_in_bytes": 1000, "temp_size_in_bytes": 500,
           "output_size_in_bytes": 100, "alias_size_in_bytes": 40}
    d = memflow.decompose(mem, params_bytes=600, opt_state_bytes=300,
                          kv_table_bytes=0, collective_scratch=64)
    assert d["peak_hbm"] == 1500          # temp + argument, exactly
    assert d["params"] == 600
    assert d["opt_state"] == 300
    assert d["inputs_other"] == 100       # argument remainder
    assert d["activations_temps"] == 500
    assert d["collectives_scratch"] == 64
    assert d["donated_aliased"] == 40
    # over-attribution clamps the remainder at zero instead of going
    # negative (donated args leave the argument count)
    d2 = memflow.decompose(mem, params_bytes=2000)
    assert d2["inputs_other"] == 0
    assert memflow.decompose(None)["peak_hbm"] == 0


def test_collective_scratch_attribution():
    # 128 f32 elems materialized by the reduce-scatter result
    assert memflow.collective_scratch_bytes(RS_SYNTH) == 512
    assert memflow.collective_scratch_bytes(CLEAN_F32) == 0


def test_kv_expected_bytes_geometry():
    # (layers=2, kv=2, lanes=2, heads=2, L=32, head=32) f32 + 1
    # scratch slot: 2*2*3*2*32*32*4
    assert memflow.kv_expected_bytes((2, 2, 2, 2, 32, 32)) == 98304


def test_planned_shard_bytes_oracle():
    sigs = [((16, 16), "float32")] * 4
    planned = memflow.planned_shard_bytes(sigs, 8, 2)
    buckets = parallel.plan_zero_buckets(sigs, 8)
    assert planned == sum(2 * b["padded_bytes"] // 8 for b in buckets)


# --------------------------------------------- seeded perturbations
# each trips EXACTLY one rule, with the buffer and site named

def _donation_record(declared):
    return {"target": "t", "programs": {"step": {
        "mem": {"argument_size_in_bytes": 64,
                "temp_size_in_bytes": 0},
        "donation": {"declared": declared,
                     "donatable": {"0": {"label": "train_vals",
                                         "bytes": 48}}}}}}


def test_seeded_donation_missed():
    led = memflow.build_ledger(_donation_record(declared=[]))
    assert _rules(led["hazards"]) == ["donation-missed"]
    h = led["hazards"][0]
    assert h["op"] == "parameter"
    assert h["site"] == "step:arg0"
    assert "train_vals" in h["detail"]
    assert "donate_argnums" in h["detail"]
    # declaring the donation clears it
    assert memflow.build_ledger(
        _donation_record(declared=[0]))["hazards"] == []


def test_seeded_donation_missed_real_step():
    """Dropping TrainStep's donate_argnums=(0, 2) path surfaces both
    donatable buffers (train_vals + opt_state) under the ONE
    donation-missed rule."""
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False), nn.Dense(4, flatten=False))
    net.initialize(init="xavier")
    net(x)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "sgd",
        {"learning_rate": 0.05}, donate=False)
    step(x, y)
    record = memflow.train_step_record(step, x, y, "nodonate")
    led = memflow.build_ledger(record)
    assert set(_rules(led["hazards"])) == {"donation-missed"}
    sites = sorted(h["site"] for h in led["hazards"])
    assert sites == ["train_step:arg0", "train_step:arg2"]
    # the default (donate=True) is clean
    step_on = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "sgd",
        {"learning_rate": 0.05})
    step_on(x, y)
    led_on = memflow.build_ledger(
        memflow.train_step_record(step_on, x, y, "donate"))
    assert led_on["hazards"] == []


def _mesh(n=8):
    import jax
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, have {len(devs)}")
    return jax.sharding.Mesh(np.array(devs[:n]), ("dp",))


def test_seeded_zero_replication(monkeypatch):
    """zero=0 forced under a record declared to shard: measured
    opt-state bytes exceed the plan_zero_buckets geometry and
    EXACTLY zero-replication fires, naming the opt-state buffer."""
    monkeypatch.setenv("MXTPU_ZERO", "0")
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 16).astype(np.float32))
    y = nd.array(rng.randn(8, 4).astype(np.float32))
    net = nn.HybridSequential()
    net.add(nn.Dense(16, flatten=False), nn.Dense(4, flatten=False))
    net.initialize(init="xavier")
    net(x)
    step = parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "adam",
        {"learning_rate": 1e-3}, mesh=_mesh())
    assert not step.zero
    step(x, y)
    record = memflow.train_step_record(step, x, y, "zero_pert",
                                       zero_expected=True)
    led = memflow.build_ledger(record)
    assert _rules(led["hazards"]) == ["zero-replication"]
    h = led["hazards"][0]
    assert h["op"] == "opt-state"
    assert h["site"] == "zero_pert:opt_state"
    assert "replicated" in h["detail"]
    z = record["zero"]
    assert z["opt_state_bytes"] > z["planned_shard_bytes"]


def test_seeded_kv_overcommit():
    """A slot table grown past the declared kv_cache_spec geometry
    (+1 scratch slot) trips exactly kv-overcommit."""
    spec = (2, 2, 2, 2, 32, 32)
    ok = memflow.kv_expected_bytes(spec)
    record = {"target": "gen", "programs": {},
              "kv": {"spec": list(spec), "itemsize": 4,
                     "table_bytes": ok, "expected_bytes": ok}}
    assert memflow.build_ledger(record)["hazards"] == []
    # two extra lanes past the spec: 5 slots instead of 3
    grown = dict(record, kv=dict(record["kv"],
                                 table_bytes=ok // 3 * 5))
    led = memflow.build_ledger(grown)
    assert _rules(led["hazards"]) == ["kv-overcommit"]
    h = led["hazards"][0]
    assert h["op"] == "kv-table"
    assert h["site"] == "gen:kv_table"
    assert "kv_cache_spec" in h["detail"]


def test_seeded_padding_waste():
    record = {"target": "t", "programs": {},
              "padding": [
                  {"site": "zero_bucket0[(4, 16, 16):float32]",
                   "used_bytes": 1 << 20,
                   "padded_bytes": (1 << 20) + (1 << 19)}]}
    led = memflow.build_ledger(record)
    assert _rules(led["hazards"]) == ["padding-waste"]
    h = led["hazards"][0]
    assert h["op"] == "pad"
    assert "zero_bucket0" in h["site"]
    # under the 25% threshold (or under the absolute floor): clean
    small = {"target": "t", "programs": {},
             "padding": [{"site": "b", "used_bytes": 1 << 20,
                          "padded_bytes": (1 << 20) + (1 << 17)}]}
    assert memflow.build_ledger(small)["hazards"] == []
    tiny = {"target": "t", "programs": {},
            "padding": [{"site": "b", "used_bytes": 64,
                         "padded_bytes": 512}]}
    assert memflow.build_ledger(tiny)["hazards"] == []


def test_seeded_budget_exceeded():
    """Shrinking the target's device-class budget below its peak
    trips exactly budget-exceeded, naming the program."""
    record = {"target": "t", "programs": {"step": {
        "mem": {"argument_size_in_bytes": 1000,
                "temp_size_in_bytes": 500}}}}
    budgets = {"classes": {"nano": {"bytes": 1400}},
               "default_class": "nano", "targets": {}}
    led = memflow.build_ledger(record, budgets)
    assert _rules(led["hazards"]) == ["budget-exceeded"]
    h = led["hazards"][0]
    assert h["op"] == "program"
    assert h["site"] == "step"
    assert "1500" in h["detail"] and "nano" in h["detail"]
    # a roomy class is clean, and headroom is recorded
    budgets["classes"]["nano"]["bytes"] = 1 << 30
    led_ok = memflow.build_ledger(record, budgets)
    assert led_ok["hazards"] == []
    assert led_ok["budget_bytes"] == 1 << 30
    assert 0 < led_ok["headroom_frac"] < 1


# --------------------------------------------- committed acceptance

def _load_ledger(name):
    with open(os.path.join(_ROOT, "contracts", "mem",
                           f"{name}.json")) as f:
        return json.load(f)


def test_committed_bert_zero_proves_shard_geometry():
    """THE ZeRO acceptance proof: the committed ledger's measured
    per-device opt-state bytes are ≤ the plan_zero_buckets geometry
    (equality on this padding-free fixture), at exactly 1/8 of the
    replicated baseline's."""
    z = _load_ledger("bert_zero")["zero"]
    assert z["expected"] and z["sharded"]
    assert z["opt_state_bytes"] <= z["planned_shard_bytes"]
    r = _load_ledger("bert_replicated")["zero"]
    assert not r["expected"]
    assert z["opt_state_bytes"] * 8 == r["opt_state_bytes"]


def test_committed_generate_decode_proves_kv_geometry():
    """THE KV acceptance proof: the committed table bytes equal the
    declared kv_cache_spec geometry + 1 scratch slot, and the decode
    program donates the table."""
    led = _load_ledger("generate_decode")
    kv = led["kv"]
    assert kv["table_bytes"] == kv["expected_bytes"]
    assert kv["table_bytes"] == memflow.kv_expected_bytes(
        kv["spec"], kv["itemsize"])
    decode = led["programs"]["decode_step"]
    don = decode["donation"]
    assert don["declared"], "decode KV table must be donated"
    assert don["donatable"][str(don["declared"][0])]["label"] \
        == "kv_table"


def test_committed_ledgers_hazard_free_and_peak_compatible():
    """Every committed mem ledger is hazard-free, and where the
    hlocheck contract pins a peak-bytes budget for the same program
    the two analyzers agree byte-for-byte (the ONE-analyzer
    migration kept hbm_peak compatible)."""
    mdir = os.path.join(_ROOT, "contracts", "mem")
    names = sorted(fn[:-5] for fn in os.listdir(mdir)
                   if fn.endswith(".json") and fn != "budgets.json")
    assert len(names) >= 9
    checked = 0
    for name in names:
        led = _load_ledger(name)
        assert led["hazards"] == [], (name, led["hazards"])
        cpath = os.path.join(_ROOT, "contracts", f"{name}.json")
        if not os.path.exists(cpath):
            continue
        with open(cpath) as f:
            contract = json.load(f)
        for prog, summ in contract["programs"].items():
            pinned = (summ.get("budgets") or {}).get("peak_bytes")
            if pinned is None or prog not in led["programs"]:
                continue
            dec = led["programs"][prog]["decomposition"]
            assert dec["peak_hbm"] == pinned, (name, prog)
            checked += 1
    assert checked >= 6


def test_budgets_are_declarative():
    with open(os.path.join(_ROOT, "contracts", "mem",
                           "budgets.json")) as f:
        budgets = json.load(f)
    assert budgets["classes"]["hbm16"]["bytes"] == 16 * 1024 ** 3
    assert budgets["default_class"] in budgets["classes"]
    # every committed ledger resolves to a real class with headroom
    cls, limit = memflow.resolve_budget("anything", budgets)
    assert cls and limit


# ------------------------------------------------------ runtime audit

def test_mem_audit_knob(monkeypatch):
    for k in ("MXTPU_MEM_AUDIT", "MXNET_MEM_AUDIT",
              "MXTPU_MEM_BUDGET", "MXNET_MEM_BUDGET",
              "MXTPU_HLO_AUDIT", "MXTPU_PREC_AUDIT"):
        monkeypatch.delenv(k, raising=False)
    fat = _FakeCompiled()  # peak 1500 B
    # off: no parse, no findings
    assert analysis.maybe_audit(fat, label="t") is None
    # warn: peak over a 1-byte budget
    monkeypatch.setenv("MXTPU_MEM_AUDIT", "1")
    monkeypatch.setenv("MXTPU_MEM_BUDGET", "1")
    with pytest.warns(RuntimeWarning, match="memory audit"):
        analysis.maybe_audit(fat, label="t")
    # raise
    monkeypatch.setenv("MXTPU_MEM_AUDIT", "2")
    with pytest.raises(MXNetError, match="MXTPU_MEM_AUDIT=2"):
        analysis.maybe_audit(fat, label="t")
    # a program under budget passes silently even in raise mode
    monkeypatch.setenv("MXTPU_MEM_BUDGET", "1000000")
    assert analysis.maybe_audit(fat, label="t") is not None
    # the stamp records the mode for cache-reaudit decisions
    assert analysis.audit_stamp()["mem_audit"] == 2
    assert analysis.needs_reaudit({"hlo_audit": 0, "prec_audit": 0})


def test_mem_audit_findings_direct():
    from mxtpu import knobs
    assert memflow.mem_audit_findings(None, "x") == []
    assert memflow.mem_audit_findings({}, "x") == []
    # explicit budget override via the knob
    old = os.environ.get("MXTPU_MEM_BUDGET")
    os.environ["MXTPU_MEM_BUDGET"] = "100"
    try:
        out = memflow.mem_audit_findings({"hbm_peak": 1500}, "prog")
        assert len(out) == 1
        assert "1500" in out[0] and "prog" in out[0]
    finally:
        if old is None:
            os.environ.pop("MXTPU_MEM_BUDGET", None)
        else:
            os.environ["MXTPU_MEM_BUDGET"] = old


# ---------------------------------------------------------------- CLI

def _mxmem(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxmem", *args],
        capture_output=True, text=True, cwd=_ROOT, timeout=240)


def test_cli_roundtrip_determinism_and_drift(tmp_path):
    """--update then --check is a fixed point; two --update runs are
    byte-identical; budgets.json is bootstrapped once and never
    overwritten; a corrupted ledger fails with the target named."""
    d = str(tmp_path)
    up1 = _mxmem("--update", "selftest", "--contracts-dir", d)
    assert up1.returncode == 0, up1.stdout + up1.stderr
    path = tmp_path / "mem" / "selftest.json"
    first = path.read_bytes()
    bpath = tmp_path / "mem" / "budgets.json"
    assert bpath.exists()

    # budgets are hand-edited policy: --update must not rewrite them
    budgets = json.loads(bpath.read_text())
    budgets["classes"]["custom"] = {"bytes": 123456, "doc": "mine"}
    bpath.write_text(json.dumps(budgets, indent=1, sort_keys=True)
                     + "\n")
    edited = bpath.read_bytes()

    up2 = _mxmem("--update", "selftest", "--contracts-dir", d)
    assert up2.returncode == 0, up2.stdout + up2.stderr
    assert path.read_bytes() == first  # byte-deterministic
    assert bpath.read_bytes() == edited  # never regenerated

    ok = _mxmem("--check", "selftest", "--contracts-dir", d)
    assert ok.returncode == 0, ok.stdout + ok.stderr

    ledger = json.loads(first)
    ledger["programs"]["eigh_matmul"]["decomposition"]["peak_hbm"] += 8
    path.write_text(json.dumps(ledger, indent=1, sort_keys=True)
                    + "\n")
    bad = _mxmem("--check", "selftest", "--contracts-dir", d)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "selftest" in bad.stdout


def test_cli_usage_errors(tmp_path):
    unk = _mxmem("--check", "no_such_target")
    assert unk.returncode == 2
    assert "unknown target" in unk.stderr

    empty = _mxmem("--check", "--contracts-dir", str(tmp_path))
    assert empty.returncode == 2
    assert "no ledgers" in empty.stderr

    (tmp_path / "mem").mkdir()
    (tmp_path / "mem" / "ghost.json").write_text("{}\n")
    orphan = _mxmem("--check", "--contracts-dir", str(tmp_path))
    assert orphan.returncode == 2
    assert "ghost" in orphan.stderr


@pytest.mark.slow
def test_committed_mem_ledgers_check_clean():
    """THE acceptance check: the committed tree passes a full
    `python -m tools.mxmem --check` (ledgers + README table) with
    exit 0."""
    r = _mxmem("--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 violation(s)" in r.stdout
