"""SSD detector: shapes, anchor/predictor consistency, training step
on a synthetic localization task, end-to-end detect().

Reference: ``example/ssd/``† (training recipe), multibox op tests
(``tests/python/unittest/test_operator.py†`` multibox cases).
"""
import numpy as np

import mxtpu as mx
from mxtpu import nd, autograd
from mxtpu.models.ssd import SSDLoss, toy_ssd


def _synthetic_batch(rng, n=4, size=64):
    """Images with one bright square; label = its box, class 0."""
    x = rng.rand(n, 3, size, size).astype(np.float32) * 0.1
    labels = np.zeros((n, 1, 5), np.float32)
    for i in range(n):
        w = rng.randint(size // 4, size // 2)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - w)
        x[i, :, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [0, x0 / size, y0 / size,
                        (x0 + w) / size, (y0 + w) / size]
    return nd.array(x), nd.array(labels)


def test_ssd_output_shapes():
    mx.random.seed(0)
    net = toy_ssd(num_classes=2)
    net.initialize(init="xavier")
    x = nd.zeros((2, 3, 64, 64))
    anchors, cls_preds, box_preds = net(x)
    A = anchors.shape[1]
    assert anchors.shape == (1, A, 4)
    assert cls_preds.shape == (2, 3, A)  # classes+1 = 3
    assert box_preds.shape == (2, A * 4)
    # anchors within ±margin of the unit square (edge anchors overhang)
    a = anchors.asnumpy()
    assert a.min() > -1.0 and a.max() < 2.0


def test_ssd_train_step_decreases_loss():
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = toy_ssd(num_classes=1)
    net.initialize(init="xavier")
    from mxtpu.gluon import Trainer
    x, labels = _synthetic_batch(rng)
    net(x)
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 5e-3})
    loss_fn = SSDLoss()
    losses = []
    for _ in range(12):
        with autograd.record():
            anchors, cls_preds, box_preds = net(x)
            bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds)
            l = loss_fn(cls_preds, box_preds, ct, bt, bm)
            l = nd.mean(l)
        l.backward()
        trainer.step(batch_size=x.shape[0])
        losses.append(float(l.asscalar()))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.8, losses


def test_ssd_detect_end_to_end():
    mx.random.seed(0)
    net = toy_ssd(num_classes=2)
    net.initialize(init="xavier")
    out = net.detect(nd.zeros((1, 3, 64, 64)))
    o = out.asnumpy()
    assert o.ndim == 3 and o.shape[2] == 6
    # every row is either suppressed (-1) or [cls, score, box] with
    # score in [0,1]
    kept = o[0][o[0, :, 0] >= 0]
    if len(kept):
        assert ((kept[:, 1] >= 0) & (kept[:, 1] <= 1)).all()


def test_ssd_hybridize_matches_imperative():
    mx.random.seed(3)
    net = toy_ssd(num_classes=1)
    net.initialize(init="xavier")
    x = nd.array(np.random.RandomState(1)
                 .randn(2, 3, 64, 64).astype(np.float32))
    a0, c0, b0 = net(x)
    net.hybridize()
    a1, c1, b1 = net(x)
    for e, g in ((a0, a1), (c0, c1), (b0, b1)):
        np.testing.assert_allclose(e.asnumpy(), g.asnumpy(),
                                   rtol=2e-5, atol=2e-5)


def test_ssd_loss_ignores_non_mined_anchors():
    """MultiBoxTarget's ignore label (-1, emitted under negative
    mining) must not train the classifier: ignored anchors contribute
    zero CE and the normalization counts only kept anchors."""
    from mxtpu.models.ssd import SSDLoss
    rng = np.random.RandomState(0)
    N, C, A = 2, 3, 8
    cls_preds = nd.array(rng.randn(N, C + 1, A).astype(np.float32))
    box_preds = nd.array(np.zeros((N, A * 4), np.float32))
    box_target = nd.array(np.zeros((N, A * 4), np.float32))
    box_mask = nd.array(np.zeros((N, A * 4), np.float32))
    ct = np.zeros((N, A), np.float32)
    ct[:, 0] = 2.0          # one positive
    ct[:, 1] = 0.0          # one mined negative
    ct[:, 2:] = -1.0        # ignored
    loss_fn = SSDLoss()
    got = loss_fn(cls_preds, box_preds, nd.array(ct), box_target,
                  box_mask).asnumpy()
    # oracle: mean CE over the two kept anchors only
    logits = cls_preds.asnumpy()
    logp = logits - np.log(np.exp(logits).sum(axis=1, keepdims=True))
    want = []
    for n in range(N):
        kept = [-logp[n, 2, 0], -logp[n, 0, 1]]
        want.append(np.mean(kept))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_ssd_loss_unchanged_without_ignore_labels():
    """No -1 targets (mining off, the default pipeline): the masked
    loss equals the plain anchor mean it replaced."""
    from mxtpu.models.ssd import SSDLoss
    rng = np.random.RandomState(1)
    N, C, A = 2, 2, 6
    cls_preds = nd.array(rng.randn(N, C + 1, A).astype(np.float32))
    zeros = nd.array(np.zeros((N, A * 4), np.float32))
    ct = rng.randint(0, C + 1, (N, A)).astype(np.float32)
    got = SSDLoss()(cls_preds, zeros, nd.array(ct), zeros,
                    zeros).asnumpy()
    logits = cls_preds.asnumpy()
    logp = logits - np.log(np.exp(logits).sum(axis=1, keepdims=True))
    want = [np.mean([-logp[n, int(ct[n, a]), a] for a in range(A)])
            for n in range(N)]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
