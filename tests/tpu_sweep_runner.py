"""Subprocess entry for the registry-wide cpu<->tpu sweep.

Usage: python tests/tpu_sweep_runner.py GROUP_IDX GROUP_SIZE

The whole group runs as ONE jitted program per backend (fwd + grads
for every case, inputs as runtime args so nothing constant-folds) —
one remote compile instead of ~2 per op, which is what makes a
400-name sweep feasible on a tunnel with 5-30 s compiles.  Runs in a
subprocess so an UNIMPLEMENTED lowering poisons only this group's jax
client (axon gotcha, BASELINE.md platform notes).

Prints one JSON line: {"results": [{name, case, status,
max_fwd_err, max_grad_err}...]}.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    group_idx = int(sys.argv[1])
    group_size = int(sys.argv[2])
    import jax
    import jax.numpy as jnp

    from mxtpu.ops.registry import get_op
    from tests.tpu_sweep_lib import build_cases

    cases, _ = build_cases()
    if len(sys.argv) > 3 and sys.argv[3]:
        # explicit absolute case indices: the parent retries a failed
        # group case-by-case to isolate the poisoning op
        picks = [int(x) for x in sys.argv[3].split(",")]
        group = [cases[i] for i in picks]
    else:
        group = cases[group_idx * group_size:
                      (group_idx + 1) * group_size]
    if not group:
        print(json.dumps({"results": []}))
        return

    def case_fwd(name, kw):
        op = get_op(name)

        def f(*aa):
            out = op(*aa, **kw)
            return [l.astype(jnp.float32)
                    if jnp.issubdtype(l.dtype, jnp.floating)
                    else l.astype(jnp.int32)
                    for l in jax.tree_util.tree_leaves(out)
                    if hasattr(l, "dtype")]
        return f

    def float_argnums(args):
        return tuple(i for i, a in enumerate(args)
                     if np.issubdtype(np.asarray(a).dtype,
                                      np.floating))

    # one traced program for the WHOLE group: flat arg list in,
    # flat list of (tagged) outputs out
    flat_args = []
    layout = []  # (name, case, n_args, want_grad, argnums)
    for (name, idx, args, kw) in group:
        argnums = float_argnums(args)
        want_grad = bool(argnums) and get_op(name).differentiable
        layout.append((name, idx, len(args), want_grad, argnums, kw))
        flat_args.extend(np.asarray(a) for a in args)

    def program(*flat):
        pos = 0
        outs = []
        for (name, idx, n_args, want_grad, argnums, kw) in layout:
            aa = flat[pos:pos + n_args]
            pos += n_args
            if (name, idx) in trace_errors:
                outs.append(None)
                outs.append(None)
                continue
            f = case_fwd(name, kw)
            outs.append(f(*aa))
            if want_grad:
                def scalar(*a2):
                    return sum(jnp.sum(l) for l in f(*a2)
                               if jnp.issubdtype(l.dtype,
                                                 jnp.floating))
                outs.append(list(
                    jax.grad(scalar, argnums=argnums)(*aa)))
            else:
                outs.append(None)
        return outs

    def run_backend(device):
        with jax.default_device(device):
            ja = [jnp.asarray(a) for a in flat_args]
            with jax.default_matmul_precision("highest"):
                res = jax.jit(program)(*ja)
            return jax.tree_util.tree_map(np.asarray, res)

    cpu = jax.local_devices(backend="cpu")[0]
    acc = jax.devices()[0]

    # cases whose fwd/grad trace fails must be dropped from the
    # program BEFORE compiling either backend (ONE bad trace would
    # otherwise fail the whole fused group); probe abstractly first
    # (cheap, no execution).  Dropped-fwd cases get their own error
    # entry in the results.
    trace_errors = {}
    for i, (name, idx, n_args, want_grad, argnums, kw) in \
            enumerate(layout):
        start = sum(l[2] for l in layout[:i])
        aa = flat_args[start:start + n_args]
        f = case_fwd(name, kw)
        try:
            jax.eval_shape(f, *aa)
        except Exception as e:
            trace_errors[(name, idx)] = \
                f"trace: {type(e).__name__}: {str(e)[:160]}"
            layout[i] = (name, idx, n_args, False, argnums, kw)
            continue
        if not want_grad:
            continue

        def scalar(*a2):
            return sum(jnp.sum(l) for l in f(*a2)
                       if jnp.issubdtype(l.dtype, jnp.floating))
        try:
            jax.eval_shape(jax.grad(scalar, argnums=argnums), *aa)
        except Exception:
            layout[i] = (name, idx, n_args, False, argnums, kw)

    def try_backend(device):
        try:
            return run_backend(device), None
        except Exception as e:
            return None, f"{type(e).__name__}: {str(e)[:300]}"

    ref, ref_err = try_backend(cpu)
    got, got_err = try_backend(acc)

    results = []
    if ref is None or got is None:
        status = "cpu_error" if ref is None else "tpu_error"
        err = ref_err or got_err
        for (name, idx, *_rest) in layout:
            results.append({"name": name, "case": idx,
                            "status": status, "error": err})
        print(json.dumps({"results": results}))
        return

    def maxerr(a_list, b_list):
        if a_list is None or b_list is None:
            return None
        m = 0.0
        for a, b in zip(a_list, b_list):
            a = np.asarray(a, np.float64)
            b = np.asarray(b, np.float64)
            if a.shape != b.shape:
                return float("inf")
            if a.size:
                m = max(m, float((np.abs(a - b)
                                  / np.maximum(np.abs(a), 1.0)).max()))
        return m

    for i, (name, idx, n_args, want_grad, argnums, kw) in \
            enumerate(layout):
        if (name, idx) in trace_errors:
            results.append({"name": name, "case": idx,
                            "status": "trace_error",
                            "error": trace_errors[(name, idx)]})
            continue
        fwd_err = maxerr(ref[2 * i], got[2 * i])
        grad_err = maxerr(ref[2 * i + 1], got[2 * i + 1]) \
            if want_grad else None
        results.append({"name": name, "case": idx, "status": "ok",
                        "max_fwd_err": fwd_err,
                        "max_grad_err": grad_err})
    print(json.dumps({"results": results}))


if __name__ == "__main__":
    main()
