"""engine / operator(CustomOp) / rtc / contrib / util compat modules
(reference ``test_operator.py::test_custom_op``†, ``test_engine.py``†)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, nd


def test_engine_controls():
    from mxtpu import engine
    prev = engine.set_bulk_size(4)
    assert engine.set_bulk_size(prev) == 4
    with engine.bulk(8):
        pass
    assert not engine.sync_enabled()
    engine.set_sync_mode(True)
    try:
        # ops still work (each now blocks until materialized)
        out = nd.relu(nd.array(np.array([-1.0, 2.0], np.float32)))
        np.testing.assert_allclose(out.asnumpy(), [0.0, 2.0])
    finally:
        engine.set_sync_mode(False)


def test_custom_op_forward_backward():
    """The reference's 'quadratic' custom-op tutorial, through the
    CustomOp/CustomOpProp surface."""
    import mxtpu.operator as op_mod

    class Quadratic(op_mod.CustomOp):
        def __init__(self, a):
            self.a = a

        def forward(self, is_train, req, in_data, out_data, aux):
            x = in_data[0]
            self.assign(out_data[0], req[0], x * x * self.a)

        def backward(self, req, out_grad, in_data, out_data, in_grad,
                     aux):
            x = in_data[0]
            self.assign(in_grad[0], req[0],
                        out_grad[0] * x * (2.0 * self.a))

    @op_mod.register("quadratic_test")
    class QuadraticProp(op_mod.CustomOpProp):
        def __init__(self, a="1.0"):
            super().__init__(need_top_grad=True)
            self.a = float(a)

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return Quadratic(self.a)

    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    out = op_mod.Custom(x, op_type="quadratic_test", a="2.0")
    np.testing.assert_allclose(out.asnumpy(), [2.0, 8.0, 18.0])

    x.attach_grad()
    with autograd.record():
        y = op_mod.Custom(x, op_type="quadratic_test", a="2.0")
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0, 8.0, 12.0])


def test_rtc_pallas_kernel():
    import jax
    import jax.numpy as jnp
    import os
    os.environ.setdefault("MXTPU_PALLAS", "interpret")
    from mxtpu import rtc
    with pytest.raises(mx.MXNetError):
        rtc.CudaModule("__global__ void k() {}")

    from jax.experimental import pallas as pl

    def double_kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    k = rtc.PallasKernel(
        double_kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        interpret=True)
    x = nd.array(np.random.randn(8, 128).astype(np.float32))
    out = k(x)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy() * 2.0,
                               rtol=1e-6)


def test_contrib_quantization():
    from mxtpu.contrib import quantization as q
    from mxtpu.io import NDArrayIter
    X = np.random.uniform(-2, 3, (20, 4)).astype(np.float32)
    it = NDArrayIter(X, np.zeros(20), batch_size=5)
    ranges = q.calib_minmax(it, num_batches=4)
    assert "data" in ranges
    lo, hi = ranges["data"]
    assert lo <= X.min() + 1e-5 and hi >= X.max() - 1e-5

    params = {"w": nd.array(np.random.randn(3, 3).astype(np.float32))}
    qp, r = q.quantize_params(params)
    assert qp["w"].asnumpy().dtype == np.int8


def test_gluon_contrib_layers():
    from mxtpu.gluon.contrib import nn as cnn
    from mxtpu.gluon import nn
    net = cnn.HybridConcurrent(axis=-1)
    net.add(nn.Dense(3, flatten=False), nn.Dense(5, flatten=False),
            cnn.Identity())
    net.initialize(init="xavier")
    x = nd.array(np.random.randn(2, 4).astype(np.float32))
    out = net(x)
    assert out.shape == (2, 3 + 5 + 4)


def test_util_helpers(tmp_path):
    from mxtpu import utils
    d = str(tmp_path / "a" / "b")
    utils.makedirs(d)
    utils.makedirs(d)  # idempotent
    import os
    assert os.path.isdir(d)


def test_bucket_sentence_iter_with_bucketing_module():
    """Legacy mx.rnn.BucketSentenceIter drives BucketingModule
    (reference test_bucketing†)."""
    from mxtpu.rnn import BucketSentenceIter
    rng = np.random.RandomState(0)
    sentences = [list(rng.randint(1, 20, rng.randint(3, 12)))
                 for _ in range(200)]
    it = BucketSentenceIter(sentences, batch_size=16, buckets=[6, 12])
    assert it.default_bucket_key == 12
    seen_keys = set()
    for batch in it:
        seen_keys.add(batch.bucket_key)
        assert batch.data[0].shape == (16, batch.bucket_key)
    assert seen_keys <= {6, 12} and len(seen_keys) >= 1

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=8,
                               name="embed")
        pooled = mx.sym.mean(emb, axis=1)
        fc = mx.sym.FullyConnected(pooled, num_hidden=20, name="fc")
        out = mx.sym.SoftmaxOutput(fc, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key)
    it.reset()
    first = next(it)
    mod.bind(data_shapes=first.provide_data,
             label_shapes=first.provide_label)
    mod.init_params(initializer="xavier")
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    it.reset()
    for i, batch in enumerate(it):
        # labels here are sequences; use first-token label for this
        # classification-shaped smoke test
        batch.label = [batch.label[0][:, 0]]
        batch.provide_label = [type(batch.provide_data[0])(
            "softmax_label", (16,), np.float32)]
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        if i >= 5:
            break


def test_sequential_module_trains():
    """SequentialModule chains two Modules; outputs of the feature
    module feed the classifier (reference sequential_module.py†)."""
    import mxtpu as mx
    from mxtpu.io import NDArrayIter
    rng = np.random.RandomState(0)
    X = rng.randn(256, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    it = NDArrayIter(X, Y, batch_size=32)

    feat_sym = mx.sym.Activation(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                              name="feat_fc"), act_type="relu")
    cls_in = mx.sym.Variable("feat")
    cls_sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(cls_in, num_hidden=2, name="cls_fc"),
        name="softmax")

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(feat_sym, data_names=["data"],
                          label_names=[]))
    seq.add(mx.mod.Module(cls_sym, data_names=["feat"],
                          label_names=["softmax_label"]),
            take_labels=True)
    seq.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    seq.init_params(initializer=mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "rescale_grad": 1.0 / 32})
    metric = mx.metric.Accuracy()
    for _ in range(12):
        it.reset()
        for batch in it:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
    it.reset()
    metric.reset()
    for batch in it:
        seq.forward(batch, is_train=False)
        seq.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.9, metric.get()


def test_python_loss_module_chain():
    """PythonLossModule closes a SequentialModule with a hand-written
    gradient (reference python_module.py†)."""
    import mxtpu as mx
    from mxtpu.io import NDArrayIter
    rng = np.random.RandomState(1)
    X = rng.randn(128, 4).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    it = NDArrayIter(X, Y, batch_size=32)

    body = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                 num_hidden=2, name="fc")

    def softmax_grad(scores, labels):
        s = scores.asnumpy()
        e = np.exp(s - s.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        lab = labels.asnumpy().astype(int)
        p[np.arange(len(lab)), lab] -= 1.0
        return p / len(lab)

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(body, data_names=["data"], label_names=[]))
    seq.add(mx.mod.PythonLossModule(grad_func=softmax_grad),
            take_labels=True)
    seq.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    seq.init_params(initializer=mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    for _ in range(8):
        it.reset()
        for batch in it:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
    metric = mx.metric.Accuracy()
    it.reset()
    for batch in it:
        seq.forward(batch, is_train=False)
        seq.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.85, metric.get()
