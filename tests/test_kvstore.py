"""KVStore facade: init/push/pull semantics, server-side optimizer,
gradient compression (2-bit/1-bit with error feedback).

Reference: ``python/mxnet/kvstore.py``† tests
(``tests/python/unittest/test_kvstore.py``†) and
``GradientCompression``† (``src/kvstore/gradient_compression.cc``†).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu import kvstore as kv_mod
from mxtpu.base import MXNetError


def test_init_push_pull():
    kv = kv_mod.create("local")
    kv.init(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_array_equal(out.asnumpy(), np.ones((2, 3)))
    kv.push(3, nd.ones((2, 3)) * 4)
    kv.pull(3, out=out)
    np.testing.assert_array_equal(out.asnumpy(), 4 * np.ones((2, 3)))


def test_push_aggregates_parts():
    kv = kv_mod.create("device")
    kv.init("w", nd.zeros((4,)))
    parts = [nd.ones((4,)) * v for v in (1.0, 2.0, 3.0)]
    kv.push("w", parts)
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 6 * np.ones(4))


def test_server_side_optimizer():
    kv = kv_mod.create("local")
    kv.init(0, nd.ones((3,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push(0, nd.ones((3,)))  # grad = 1 → w -= 0.1
    out = nd.zeros((3,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.9 * np.ones(3),
                               rtol=1e-6)


# ----------------------------------------------------------------------
# gradient compression
# ----------------------------------------------------------------------
def test_2bit_quantization_values():
    kv = kv_mod.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("g", nd.zeros((5,)))
    g = nd.array(np.array([0.9, -0.7, 0.3, -0.2, 0.0], np.float32))
    kv.push("g", g)
    out = nd.zeros((5,))
    kv.pull("g", out=out)
    # quantized to {-t, 0, +t}
    np.testing.assert_allclose(out.asnumpy(),
                               [0.5, -0.5, 0.0, 0.0, 0.0])


def test_2bit_error_feedback_accumulates():
    """Sub-threshold gradients accumulate in the residual and flush
    once they cross the threshold — the defining EF-compression
    property."""
    kv = kv_mod.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("g", nd.zeros((1,)))
    out = nd.zeros((1,))
    sent = []
    for _ in range(5):
        kv.push("g", nd.array(np.array([0.2], np.float32)))
        kv.pull("g", out=out)
        sent.append(float(out.asnumpy()[0]))
    # 0.2 accumulates: pushes emit 0 until residual+g >= 0.5
    assert sent[0] == 0.0 and sent[1] == 0.0
    assert sent[2] == 0.5  # 0.6 accumulated → emit 0.5, keep 0.1
    total = sum(sent)
    assert abs(total - 1.0) <= 0.5  # compressed stream tracks the true
    # cumulative gradient (5 * 0.2) to within one threshold step


def test_2bit_per_slot_residuals():
    """Each device slot keeps its own residual (reference: per-worker
    residual_)."""
    kv = kv_mod.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("g", nd.zeros((1,)))
    out = nd.zeros((1,))
    # asymmetric parts so shared-residual or residual-free
    # implementations give a DIFFERENT answer from per-slot residuals
    kv.push("g", [nd.array(np.array([0.3], np.float32)),
                  nd.array(np.array([-0.4], np.float32))])
    kv.pull("g", out=out)
    assert float(out.asnumpy()[0]) == 0.0  # both below threshold
    # per-slot residuals: slot0 0.3+0.3=0.6→+0.5, slot1 -0.4-0.4=-0.8→-0.5
    # → sum 0.  (A single shared residual would see 0.3-0.4+0.3-0.4 and
    # emit -0.5; no residual at all emits 0 on both slots.)
    kv.push("g", [nd.array(np.array([0.3], np.float32)),
                  nd.array(np.array([-0.4], np.float32))])
    kv.pull("g", out=out)
    assert float(out.asnumpy()[0]) == 0.0
    # third push flushes slot1's residual (-0.3-0.4=-0.7→-0.5) while
    # slot0 (0.1+0.3=0.4) stays silent → nonzero total only with
    # per-slot bookkeeping
    kv.push("g", [nd.array(np.array([0.3], np.float32)),
                  nd.array(np.array([-0.4], np.float32))])
    kv.pull("g", out=out)
    assert float(out.asnumpy()[0]) == -0.5


def test_1bit_sign_compression():
    kv = kv_mod.create("device")
    kv.set_gradient_compression({"type": "1bit", "threshold": 0.1})
    kv.init("g", nd.zeros((3,)))
    kv.push("g", nd.array(np.array([0.9, -0.7, 0.01], np.float32)))
    out = nd.zeros((3,))
    kv.pull("g", out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.1, -0.1, 0.1])


def test_compression_rejects_bad_params():
    kv = kv_mod.create("device")
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"type": "4bit"})
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"type": "2bit", "threshold": -1})
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"threshold": 0.5})  # no type
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"Type": "2bit"})  # typo'd key
    # explicit empty/None = disable (old no-op behaviour preserved)
    kv.set_gradient_compression({"type": "2bit"})
    kv.set_gradient_compression(None)
    assert kv._compression == {}


def test_compression_slot_and_shape_guards():
    kv = kv_mod.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("g", nd.zeros((2,)))
    kv.push("g", [nd.ones((2,)), nd.ones((2,))])
    with pytest.raises(MXNetError):  # part count changed
        kv.push("g", nd.ones((2,)))
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.push("g", nd.ones((2,)))  # reset → new slot layout accepted
    with pytest.raises(MXNetError):  # shape changed for a live residual
        kv.push("g", nd.ones((3,)))


def test_trainer_compression_without_store_raises():
    from mxtpu.gluon import Trainer, nn
    net = nn.Dense(1)
    net.initialize(init="zeros")
    net(nd.zeros((2, 3)))
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1}, kvstore=None,
                      compression_params={"type": "2bit"})
    with pytest.raises(MXNetError):
        trainer._init_kvstore()


def test_trainer_with_compression_trains():
    """End-to-end: Trainer(compression_params=...) still converges on a
    least-squares problem (EF compression is lossy but unbiased-ish)."""
    from mxtpu import autograd
    from mxtpu.gluon import Trainer, nn
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.Dense(1)
    net.initialize(init="zeros")
    x = nd.array(np.random.randn(64, 4).astype(np.float32))
    w_true = np.array([[1.0, -2.0, 0.5, 3.0]], np.float32)
    y = nd.array(np.asarray(x.asnumpy() @ w_true.T))
    net(x)  # shape inference
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1},
                      compression_params={"type": "2bit",
                                          "threshold": 0.25})
    assert trainer._compression_params["type"] == "2bit"
    losses = []
    for _ in range(150):
        with autograd.record():
            out = net(x)
            loss = nd.mean((out - y) ** 2)
        loss.backward()
        # batch_size=1: grads of a mean loss are already averaged;
        # EF-compressed steps are ±threshold-sized, so don't shrink
        # them further
        trainer.step(batch_size=1)
        losses.append(float(loss.asscalar()))
    # EF-SGD converges to a floor ~lr*threshold around the optimum —
    # check substantial descent, not exact convergence
    assert min(losses) < losses[0] * 0.2, (losses[0], min(losses))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
