"""Detection / CTC / quantization ops (reference
``test_operator.py::test_roipooling/test_ctc_loss``†,
``tests/python/unittest/test_contrib_*``†)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, nd


def test_roi_pooling_values():
    # 1x1x4x4 ramp; roi covering the whole image, 2x2 pool
    data = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(out.asnumpy()[0, 0],
                               [[5, 7], [13, 15]])


def test_roi_pooling_batch_and_scale():
    rng = np.random.RandomState(0)
    data = rng.randn(2, 3, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 7], [1, 2, 2, 5, 5]], np.float32)
    out = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(3, 3))
    assert out.shape == (2, 3, 3, 3)
    # roi 0 covers image 0 entirely: global-ish max per bin >= mean
    assert np.isfinite(out.asnumpy()).all()
    # spatial_scale halves coordinates
    out2 = nd.ROIPooling(nd.array(data),
                         nd.array(np.array([[0, 0, 0, 14, 14]],
                                           np.float32)),
                         pooled_size=(2, 2), spatial_scale=0.5)
    assert out2.shape == (1, 3, 2, 2)


def test_multibox_prior():
    x = nd.zeros((1, 3, 4, 6))
    anchors = nd.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1, 2))
    # K = S + R - 1 = 3 anchors per position
    assert anchors.shape == (1, 4 * 6 * 3, 4)
    a = anchors.asnumpy()[0]
    # first anchor of first cell: centered at (offset/W, offset/H)
    cx, cy = (0.5 / 6), (0.5 / 4)
    np.testing.assert_allclose(a[0], [cx - 0.25, cy - 0.25,
                                      cx + 0.25, cy + 0.25], atol=1e-6)
    # width/height of ratio-2 anchor: w = s*sqrt(2), h = s/sqrt(2)
    w = a[2, 2] - a[2, 0]
    h = a[2, 3] - a[2, 1]
    np.testing.assert_allclose(w / h, 2.0, rtol=1e-5)


def test_multibox_target_and_detection():
    anchors = nd.array(np.array(
        [[[0.0, 0.0, 0.4, 0.4],
          [0.5, 0.5, 1.0, 1.0],
          [0.0, 0.6, 0.3, 1.0]]], np.float32))
    # one gt box (class 1) overlapping anchor 1
    labels = nd.array(np.array(
        [[[1.0, 0.55, 0.55, 0.95, 0.95],
          [-1.0, 0, 0, 0, 0]]], np.float32))
    cls_preds = nd.zeros((1, 3, 3))  # (N, C, A)
    bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds)
    assert bt.shape == (1, 12) and bm.shape == (1, 12)
    ct_np = ct.asnumpy()[0]
    assert ct_np[1] == 2.0  # gt class 1 → target 2 (bg=0 shift)
    assert ct_np[0] == 0.0 and ct_np[2] == 0.0
    mask = bm.asnumpy()[0].reshape(3, 4)
    assert mask[1].sum() == 4 and mask[0].sum() == 0

    # detection: probabilities put class 1 on anchor 1
    cls_prob = np.zeros((1, 3, 3), np.float32)
    cls_prob[0, 0] = [0.9, 0.1, 0.9]   # background
    cls_prob[0, 1] = [0.05, 0.8, 0.05]
    cls_prob[0, 2] = [0.05, 0.1, 0.05]
    loc = np.zeros((1, 12), np.float32)
    out = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc),
                               anchors)
    o = out.asnumpy()[0]
    kept = o[o[:, 0] >= 0]
    assert len(kept) >= 1
    best = kept[np.argmax(kept[:, 1])]
    assert best[0] == 0.0  # class id 0 (= original class 1 - bg)
    np.testing.assert_allclose(best[2:], [0.5, 0.5, 1.0, 1.0],
                               atol=1e-5)


def _np_ctc_ref(logits, labels, blank=0):
    """Brute-force CTC by enumerating alignments (tiny T only)."""
    from itertools import product
    T, C = logits.shape
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)

    def collapse(path):
        out = []
        prev = None
        for s in path:
            if s != prev and s != blank:
                out.append(s)
            prev = s
        return tuple(out)

    target = tuple(labels)
    total = 0.0
    for path in product(range(C), repeat=T):
        if collapse(path) == target:
            prob = 1.0
            for t, s in enumerate(path):
                prob *= p[t, s]
            total += prob
    return -np.log(total)


def test_ctc_loss_matches_bruteforce():
    rng = np.random.RandomState(0)
    T, N, C = 4, 2, 4
    logits = rng.randn(T, N, C).astype(np.float64)
    # blank_label='first': labels are 1-based, 0 = padding
    labels = np.array([[1, 2], [3, 0]], np.float64)
    loss = nd.ctc_loss(nd.array(logits.astype(np.float32)),
                       nd.array(labels.astype(np.float32)))
    ref0 = _np_ctc_ref(logits[:, 0], [1, 2], blank=0)
    ref1 = _np_ctc_ref(logits[:, 1], [3], blank=0)
    np.testing.assert_allclose(loss.asnumpy(), [ref0, ref1], rtol=1e-4)


def test_ctc_loss_differentiable():
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(5, 2, 4).astype(np.float32))
    x.attach_grad()
    labels = nd.array(np.array([[1, 2], [2, 0]], np.float32))
    with autograd.record():
        loss = nd.ctc_loss(x, labels)
        total = loss.sum()
    total.backward()
    g = x.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(2)
    x = rng.uniform(-3, 5, (4, 5)).astype(np.float32)
    lo = nd.array(np.array([-3.0], np.float32))
    hi = nd.array(np.array([5.0], np.float32))
    q, qlo, qhi = nd.quantize(nd.array(x), lo, hi, out_type="uint8")
    assert q.asnumpy().dtype == np.uint8
    back = nd.dequantize(q, qlo, qhi)
    np.testing.assert_allclose(back.asnumpy(), x, atol=(8.0 / 255) + 1e-6)

    q2, l2, h2 = nd.quantize_v2(nd.array(x), out_type="int8")
    assert q2.asnumpy().dtype == np.int8
    back2 = nd.dequantize(q2, l2, h2)
    np.testing.assert_allclose(back2.asnumpy(), x, atol=(8.0 / 254) + 1e-6)


def test_detection_ops_symbolic():
    """The new ops compose symbolically too."""
    data = mx.sym.var("data")
    rois = mx.sym.var("rois")
    out = mx.sym.ROIPooling(data, rois, pooled_size=(2, 2))
    res = out.eval(data=nd.array(np.arange(16, dtype=np.float32)
                                 .reshape(1, 1, 4, 4)),
                   rois=nd.array(np.array([[0, 0, 0, 3, 3]],
                                          np.float32)))
    assert res[0].shape == (1, 1, 2, 2)


def test_image_module(tmp_path):
    """mx.image helpers (reference test_image.py†)."""
    import cv2
    from mxtpu import image as img_mod
    rng = np.random.RandomState(0)
    raw = (rng.rand(20, 30, 3) * 255).astype(np.uint8)
    path = str(tmp_path / "x.png")
    cv2.imwrite(path, raw[:, :, ::-1])  # imwrite takes BGR
    img = img_mod.imread(path)
    np.testing.assert_array_equal(img.asnumpy(), raw)

    small = img_mod.imresize(img, 15, 10)
    assert small.shape == (10, 15, 3)
    rs = img_mod.resize_short(img, 10)
    assert min(rs.shape[:2]) == 10
    crop, rect = img_mod.center_crop(img, (12, 8))
    assert crop.shape == (8, 12, 3)
    crop2, _ = img_mod.random_crop(img, (12, 8))
    assert crop2.shape == (8, 12, 3)
    norm = img_mod.color_normalize(img, mean=[100, 100, 100],
                                   std=[50, 50, 50])
    assert norm.asnumpy().dtype == np.float32

    augs = img_mod.CreateAugmenter((3, 8, 8), rand_mirror=True,
                                   mean=True, std=True)
    out = img
    for a in augs:
        out = a(out)
    assert out.shape == (8, 8, 3)
    assert out.asnumpy().dtype == np.float32


def test_ctc_loss_with_lengths():
    """data_lengths/label_lengths inputs (review regression)."""
    rng = np.random.RandomState(3)
    T, N, C = 6, 2, 4
    logits = rng.randn(T, N, C).astype(np.float64)
    # row 0 uses only 4 timesteps and 2 labels
    loss = nd.ctc_loss(nd.array(logits.astype(np.float32)),
                       nd.array(np.array([[1, 2, 3], [3, 1, 0]],
                                         np.float32)),
                       nd.array(np.array([4, 6], np.float32)),
                       nd.array(np.array([2, 2], np.float32)),
                       use_data_lengths=True, use_label_lengths=True)
    ref0 = _np_ctc_ref(logits[:4, 0], [1, 2], blank=0)
    ref1 = _np_ctc_ref(logits[:, 1], [3, 1], blank=0)
    np.testing.assert_allclose(loss.asnumpy(), [ref0, ref1], rtol=1e-4)


def test_ctc_loss_empty_label():
    """Empty transcript: loss = -log P(all blanks), no double count
    (review regression)."""
    rng = np.random.RandomState(4)
    T, C = 3, 3
    logits = rng.randn(T, 1, C).astype(np.float64)
    loss = nd.ctc_loss(nd.array(logits.astype(np.float32)),
                       nd.array(np.zeros((1, 2), np.float32)))
    ref = _np_ctc_ref(logits[:, 0], [], blank=0)
    np.testing.assert_allclose(loss.asnumpy(), [ref], rtol=1e-4)


def test_multibox_prior_nonunit_first_ratio():
    """sizes expand at ratios[0], not hardcoded square (review
    regression)."""
    x = nd.zeros((1, 3, 2, 2))
    a = nd.MultiBoxPrior(x, sizes=(0.5,), ratios=(2.0,)).asnumpy()[0]
    w = a[0, 2] - a[0, 0]
    h = a[0, 3] - a[0, 1]
    np.testing.assert_allclose(w / h, 2.0, rtol=1e-5)
    np.testing.assert_allclose(w * h, 0.25, rtol=1e-5)


def test_multibox_target_negative_mining():
    anchors = nd.array(np.array(
        [[[0.0, 0.0, 0.4, 0.4],
          [0.5, 0.5, 1.0, 1.0],
          [0.0, 0.6, 0.3, 1.0],
          [0.6, 0.0, 1.0, 0.3]]], np.float32))
    labels = nd.array(np.array(
        [[[1.0, 0.55, 0.55, 0.95, 0.95]]], np.float32))
    # anchor 2 has high fg confidence → hard negative kept; anchor 0/3
    # low → ignored (ratio 1:1 with a single positive)
    cls_preds = np.zeros((1, 3, 4), np.float32)
    cls_preds[0, 1, 2] = 5.0
    bt, bm, ct = nd.MultiBoxTarget(anchors, labels,
                                   nd.array(cls_preds),
                                   negative_mining_ratio=1.0)
    c = ct.asnumpy()[0]
    assert c[1] == 2.0          # positive
    assert c[2] == 0.0          # hard negative kept as background
    assert c[0] == -1.0 and c[3] == -1.0  # easy negatives ignored


def test_multibox_detection_topk():
    """nms_topk discards boxes beyond top-k (review regression)."""
    A = 6
    anchors = np.zeros((1, A, 4), np.float32)
    for i in range(A):  # disjoint boxes: nothing suppressed by IoU
        anchors[0, i] = [i * 0.15, 0.0, i * 0.15 + 0.1, 0.1]
    cls_prob = np.zeros((1, 2, A), np.float32)
    cls_prob[0, 1] = np.linspace(0.9, 0.4, A)
    loc = np.zeros((1, A * 4), np.float32)
    out = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc),
                               nd.array(anchors), nms_topk=2)
    o = out.asnumpy()[0]
    assert (o[:, 0] >= 0).sum() == 2
