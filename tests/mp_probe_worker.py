"""Backend-capability probe for the multi-process tests: can THIS
jaxlib's CPU client execute a computation over a cross-process global
array?  Some jaxlib builds refuse with "Multiprocess computations
aren't implemented on the CPU backend" — a backend limitation, not a
bug in the code paths under test.  The probe runs ONE jitted
reduction over a global array spanning both processes and prints
MP_PROBE_OK on success; the pytest parent turns a refusal into a
skip-with-reason instead of a failure."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.array(jax.devices())  # GLOBAL devices, all processes
    mesh = Mesh(devices, ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    n = len(devices)
    host = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
    idx_map = sharding.addressable_devices_indices_map(host.shape)
    shards = [jax.device_put(host[idx], d)
              for d, idx in idx_map.items()]
    garr = jax.make_array_from_single_device_arrays(
        host.shape, sharding, shards)
    # the probe moment: a multiprocess computation.  Unsupported CPU
    # clients raise XlaRuntimeError INVALID_ARGUMENT here.
    total = jax.jit(lambda a: a.sum())(garr)
    expect = float(host.sum())
    got = float(total)
    assert abs(got - expect) < 1e-5, (got, expect)
    print("MP_PROBE_OK", jax.process_index(), got, flush=True)


if __name__ == "__main__":
    main()
