"""Faster R-CNN family: Proposal op semantics, model shapes, RPN
training, end-to-end detect().

Reference: ``src/operator/contrib/proposal.cc``† and
``example/rcnn/``†.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, autograd
from mxtpu.base import MXNetError
from mxtpu.models.rcnn import faster_rcnn_small, rpn_anchors


# ----------------------------------------------------------------------
# Proposal op
# ----------------------------------------------------------------------
def test_proposal_shapes_and_ordering():
    np.random.seed(0)
    N, A, H, W = 2, 3, 4, 4
    cls = np.random.rand(N, 2 * A, H, W).astype(np.float32)
    bbox = (np.random.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
    info = np.array([[64, 64, 1.0]] * N, np.float32)
    post = 8
    rois = nd.Proposal(nd.array(cls), nd.array(bbox), nd.array(info),
                       scales=(8.0,), ratios=(0.5, 1.0, 2.0),
                       feature_stride=16, rpn_pre_nms_top_n=24,
                       rpn_post_nms_top_n=post, threshold=0.7,
                       rpn_min_size=4)
    r = rois.asnumpy()
    assert r.shape == (N * post, 5)
    # batch indices laid out block-wise
    np.testing.assert_array_equal(r[:post, 0], np.zeros(post))
    np.testing.assert_array_equal(r[post:, 0], np.ones(post))
    # boxes clipped to the image
    assert r[:, 1:].min() >= 0.0 and r[:, 1:].max() <= 63.0


def test_proposal_picks_highest_objectness():
    """The proposal with the clearly highest fg score must survive as
    roi #1, with its regressed (delta=0 → anchor) box."""
    N, A, H, W = 1, 1, 4, 4
    cls = np.zeros((N, 2, H, W), np.float32)
    cls[0, 1, 2, 1] = 5.0  # strong fg at cell (2,1)
    bbox = np.zeros((N, 4, H, W), np.float32)
    info = np.array([[64, 64, 1.0]], np.float32)
    rois, scores = nd.Proposal(
        nd.array(cls), nd.array(bbox), nd.array(info),
        scales=(2.0,), ratios=(1.0,), feature_stride=16,
        rpn_pre_nms_top_n=16, rpn_post_nms_top_n=4, threshold=0.5,
        rpn_min_size=4, output_score=True)
    r = rois.asnumpy()
    s = scores.asnumpy()
    assert s[0, 0] == s.max()
    # anchor at cell (h=2, w=1): center ≈ (16*1+7.5, 16*2+7.5)
    cx = (r[0, 1] + r[0, 3]) / 2
    cy = (r[0, 2] + r[0, 4]) / 2
    assert abs(cx - 23.5) < 1.0 and abs(cy - 39.5) < 1.0


def test_proposal_nms_suppresses_duplicates():
    """Two near-identical high-score anchors → only one survives."""
    N, A, H, W = 1, 2, 2, 2
    cls = np.zeros((N, 2 * A, H, W), np.float32)
    cls[0, A + 0, 0, 0] = 4.0   # anchor 0 at (0,0)
    cls[0, A + 1, 0, 0] = 3.9   # anchor 1 at (0,0) — same center
    bbox = np.zeros((N, 4 * A, H, W), np.float32)
    info = np.array([[64, 64, 1.0]], np.float32)
    rois, scores = nd.Proposal(
        nd.array(cls), nd.array(bbox), nd.array(info),
        scales=(2.0, 2.2), ratios=(1.0,), feature_stride=16,
        rpn_pre_nms_top_n=8, rpn_post_nms_top_n=8, threshold=0.5,
        rpn_min_size=4, output_score=True)
    s = scores.asnumpy().ravel()
    # the two duplicates collapse to one strong survivor
    assert (s > 0.9).sum() == 1


def test_proposal_validates_anchor_count():
    with pytest.raises(MXNetError):
        nd.Proposal(nd.zeros((1, 6, 4, 4)), nd.zeros((1, 12, 4, 4)),
                    nd.array(np.array([[64, 64, 1.0]], np.float32)),
                    scales=(8.0,), ratios=(1.0,))


# ----------------------------------------------------------------------
# model
# ----------------------------------------------------------------------
def test_faster_rcnn_forward_shapes():
    mx.random.seed(0)
    net = faster_rcnn_small(num_classes=2)
    net.initialize(init="xavier")
    x = nd.array(np.random.RandomState(0)
                 .randn(2, 3, 64, 64).astype(np.float32))
    info = nd.array(np.array([[64, 64, 1.0]] * 2, np.float32))
    rois, cls_scores, deltas, rpn_raw, rpn_reg = net(x, info)
    R = net._post_nms
    assert rois.shape == (2 * R, 5)
    assert cls_scores.shape == (2 * R, 3)
    assert deltas.shape == (2 * R, 12)
    assert rpn_raw.shape[1] == 2 * net._A
    assert rpn_reg.shape[1] == 4 * net._A


def test_rpn_training_improves_objectness():
    """Train the RPN alone on a fixed synthetic scene: objectness CE
    against MultiBoxTarget assignment on the generated anchors."""
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = faster_rcnn_small(num_classes=1)
    net.initialize(init="xavier")
    from mxtpu.gluon import Trainer
    size = 64
    x = rng.rand(2, 3, size, size).astype(np.float32) * 0.1
    labels = np.zeros((2, 1, 5), np.float32)
    for i in range(2):
        w = 24
        x0 = 8 + 16 * i
        x[i, :, x0:x0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [0, x0 / size, x0 / size,
                        (x0 + w) / size, (x0 + w) / size]
    x = nd.array(x)
    labels = nd.array(labels)
    info = nd.array(np.array([[size, size, 1.0]] * 2, np.float32))
    net(x, info)  # deferred init
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 3e-3})
    fh = fw = size // net._stride
    anchors = rpn_anchors(fh, fw, net._stride, net._scales,
                          net._ratios, size)
    A = net._A
    losses = []
    for _ in range(12):
        with autograd.record():
            _, _, _, rpn_raw, rpn_reg = net(x, info)
            # (N,2A,H,W) → logits (N, 2, M): bg/fg halves
            bg = nd.transpose(
                nd.slice_axis(rpn_raw, axis=1, begin=0, end=A),
                axes=(0, 2, 3, 1)).reshape((2, -1))
            fg = nd.transpose(
                nd.slice_axis(rpn_raw, axis=1, begin=A, end=2 * A),
                axes=(0, 2, 3, 1)).reshape((2, -1))
            logits = nd.stack(bg, fg, axis=1)     # (N, 2, M)
            cls_preds = logits  # MultiBoxTarget wants (N, C, Anum)
            bt, bm, ct = nd.MultiBoxTarget(
                anchors, labels, cls_preds, overlap_threshold=0.3,
                negative_mining_ratio=3.0)
            logp = nd.log_softmax(logits, axis=1)
            ce = -nd.pick(logp, ct, axis=1)
            loss = nd.mean(ce)
        loss.backward()
        trainer.step(batch_size=2)
        losses.append(float(loss.asscalar()))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.7, losses


def test_proposal_symbolic_output_score():
    """num_outputs tracks output_score through the symbol graph."""
    from mxtpu import symbol as sym
    cls = sym.var("cls")
    bbox = sym.var("bbox")
    info = sym.var("info")
    two = sym.Proposal(cls, bbox, info, scales=(8.0,), ratios=(1.0,),
                       rpn_post_nms_top_n=4, output_score=True)
    assert len(two) == 2
    one = sym.Proposal(cls, bbox, info, scales=(8.0,), ratios=(1.0,),
                       rpn_post_nms_top_n=4)
    assert len(one) == 1
    np.random.seed(3)
    c = nd.array(np.random.rand(1, 2, 4, 4).astype(np.float32))
    b = nd.array(np.zeros((1, 4, 4, 4), np.float32))
    i = nd.array(np.array([[64, 64, 1.0]], np.float32))
    rois, scores = two.eval(cls=c, bbox=b, info=i)
    assert rois.shape == (4, 5) and scores.shape == (4, 1)


def test_box_nms_id_index_class_separation():
    """force_suppress=False + id_index: overlapping boxes of DIFFERENT
    classes both survive; same class suppresses."""
    rows = np.array([
        [0, 0.9, 0, 0, 10, 10],
        [1, 0.8, 0, 0, 10, 10],   # same box, other class → survives
        [0, 0.7, 1, 1, 10, 10],   # same class, overlaps → suppressed
    ], np.float32)
    out = nd.contrib.box_nms(nd.array(rows), overlap_thresh=0.5,
                             valid_thresh=0.0, coord_start=2,
                             score_index=1, id_index=0,
                             force_suppress=False).asnumpy()
    assert out[0, 0] == 0 and out[1, 0] == 1
    assert np.all(out[2] == -1)
    out2 = nd.contrib.box_nms(nd.array(rows), overlap_thresh=0.5,
                              valid_thresh=0.0, coord_start=2,
                              score_index=1, id_index=0,
                              force_suppress=True).asnumpy()
    assert np.all(out2[1] == -1) and np.all(out2[2] == -1)


def test_detect_end_to_end():
    mx.random.seed(1)
    net = faster_rcnn_small(num_classes=2)
    net.initialize(init="xavier")
    x = nd.array(np.random.RandomState(2)
                 .randn(1, 3, 64, 64).astype(np.float32))
    info = nd.array(np.array([[64, 64, 1.0]], np.float32))
    out = net.detect(x, info, score_threshold=0.01)
    assert out.shape == (1, net._post_nms * 2, 6)
    kept = out[0][out[0, :, 0] >= 0]
    if len(kept):
        assert ((kept[:, 1] >= 0) & (kept[:, 1] <= 1)).all()
        assert kept[:, 2:].min() >= 0 and kept[:, 2:].max() <= 63
