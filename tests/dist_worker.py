"""Worker program for the local multi-process distributed test
(reference ``tests/nightly/dist_sync_kvstore.py``† run via
``tools/launch.py --launcher local``).  Each process = one simulated
host; asserts cross-process kvstore semantics and writes an OK file
the pytest parent checks."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    out_dir = sys.argv[1]
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))

    from mxtpu import kvstore as kv_mod
    from mxtpu import nd

    kv = kv_mod.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    assert n == int(os.environ["JAX_NUM_PROCESSES"]), (rank, n)

    # 1. push/pull reduces across processes: each worker pushes
    #    (rank+1) * ones → pulled value = sum_{r} (r+1)
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.ones((4,)) * (rank + 1))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    expect = sum(r + 1 for r in range(n))
    np.testing.assert_allclose(out.asnumpy(), expect * np.ones(4),
                               rtol=1e-6)

    # 2. barrier: all ranks reach it and proceed
    kv.barrier()

    # 3. server-side optimizer: push grads from every worker; the
    #    stored weight steps by lr * sum(grads)
    from mxtpu import optimizer as opt
    kv2 = kv_mod.create("dist_sync")
    kv2.init(3, nd.ones((2,)))
    kv2.set_optimizer(opt.SGD(learning_rate=0.5))
    kv2.push(3, nd.ones((2,)))
    got = nd.zeros((2,))
    kv2.pull(3, out=got)
    np.testing.assert_allclose(got.asnumpy(),
                               (1.0 - 0.5 * n) * np.ones(2),
                               rtol=1e-6)
    kv2.barrier()

    # 4. full SPMD training step over the GLOBAL mesh: batch sharded
    #    dp across process boundaries; XLA's gradient all-reduce rides
    #    the cross-process transport (gloo here, ICI/DCN on real
    #    slices).  Every rank must see the identical loss.
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import mlp
    import mxtpu

    mxtpu.random.seed(0)
    net = mlp(classes=4, hidden=(16,))
    net.initialize(init="xavier")
    mesh = parallel.make_mesh({"dp": len(jax.devices())},
                              devices=jax.devices())
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh)
    rng = np.random.RandomState(0)  # same data on every rank
    batch = 4 * len(jax.devices())  # divisible by the dp axis
    x = nd.array(rng.randn(batch, 6).astype(np.float32))
    y = nd.array(rng.randint(0, 4, (batch,)).astype(np.float32))
    losses = [float(step(x, y).asscalar()) for _ in range(5)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # loss agreement across ranks = the all-reduce really synchronized
    from jax.experimental import multihost_utils
    all_last = multihost_utils.process_allgather(
        jax.numpy.asarray(losses[-1]))
    assert np.allclose(np.asarray(all_last), losses[-1], rtol=1e-6), \
        all_last
    # NUMERICAL PARITY vs a single-device run of the same batch + init
    # (VERDICT r3 weak-5: rank-identical losses alone would also pass
    # with a consistently-wrong all-reduce).  Re-seeding reproduces the
    # init; mesh=None runs purely locally, no collectives involved.
    mxtpu.random.seed(0)
    net_ref = mlp(classes=4, hidden=(16,))
    net_ref.initialize(init="xavier")
    step_ref = parallel.build_train_step(
        net_ref, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1})
    ref_losses = [float(step_ref(x, y).asscalar()) for _ in range(5)]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5,
                               atol=2e-5)

    # 5. ring attention (sequence parallelism) ACROSS PROCESSES: the
    #    ppermute ring rides the cross-process transport; result must
    #    match the local single-device reference (VERDICT r2 item 7 —
    #    the dryrun only proves single-process virtual devices)
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from mxtpu.kernels.flash_attention import attention_reference
    from mxtpu.parallel.ring_attention import ring_attention
    n_dev = len(jax.devices())
    sp_mesh = parallel.make_mesh({"sp": n_dev}, devices=jax.devices())
    B, H, T, D = 1, 2, 8 * n_dev, 8
    rng2 = np.random.RandomState(7)  # same tensors on every rank
    q = rng2.randn(B, H, T, D).astype(np.float32) * 0.4
    k = rng2.randn(B, H, T, D).astype(np.float32) * 0.4
    v = rng2.randn(B, H, T, D).astype(np.float32)
    ring = ring_attention(q, k, v, sp_mesh, causal=True)
    ring_full = np.asarray(multihost_utils.process_allgather(
        ring, tiled=True)).reshape(B, H, T, D)
    ref = np.asarray(attention_reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
    np.testing.assert_allclose(ring_full, ref, rtol=1e-4, atol=1e-4)

    # 6. GPipe pipeline ACROSS PROCESSES: stage-to-stage ppermute over
    #    the process boundary; parity with sequential layer application
    from mxtpu.parallel.pipeline import (spmd_pipeline,
                                         stack_stage_params)
    pp_mesh = parallel.make_mesh({"pp": n_dev}, devices=jax.devices())
    L, C, Bp = 2 * n_dev, 8, 4
    ws = [rng2.randn(C, C).astype(np.float32) * 0.3 for _ in range(L)]
    bs = [rng2.randn(C).astype(np.float32) * 0.1 for _ in range(L)]

    def stage_fn(params_loc, h):
        def layer(carry, lp):
            w, b = lp
            return carry + jnp.tanh(carry @ w + b), None
        h, _ = jax.lax.scan(layer, h, tuple(params_loc))
        return h

    xp = rng2.randn(Bp, C).astype(np.float32)
    got = spmd_pipeline(
        stage_fn,
        stack_stage_params([[jnp.asarray(w), jnp.asarray(b)]
                            for w, b in zip(ws, bs)]),
        xp, mesh=pp_mesh, axis="pp", n_microbatches=2)
    got_full = np.asarray(multihost_utils.process_allgather(
        got, tiled=True)).reshape(Bp, C)
    want = xp
    for w, b in zip(ws, bs):
        want = want + np.tanh(want @ w + b)
    np.testing.assert_allclose(got_full, want, rtol=1e-4, atol=1e-4)

    with open(os.path.join(out_dir, f"ok.{rank}"), "w") as f:
        f.write(f"rank {rank}/{n} passed; spmd losses {losses}; "
                f"ring sp{n_dev} ok; pipeline pp{n_dev} ok\n")


if __name__ == "__main__":
    main()
