"""Worker program for the local multi-process distributed test
(reference ``tests/nightly/dist_sync_kvstore.py``† run via
``tools/launch.py --launcher local``).  Each process = one simulated
host; asserts cross-process kvstore semantics and writes an OK file
the pytest parent checks."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    out_dir = sys.argv[1]
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))

    from mxtpu import kvstore as kv_mod
    from mxtpu import nd

    kv = kv_mod.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    assert n == int(os.environ["JAX_NUM_PROCESSES"]), (rank, n)

    # 1. push/pull reduces across processes: each worker pushes
    #    (rank+1) * ones → pulled value = sum_{r} (r+1)
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.ones((4,)) * (rank + 1))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    expect = sum(r + 1 for r in range(n))
    np.testing.assert_allclose(out.asnumpy(), expect * np.ones(4),
                               rtol=1e-6)

    # 2. barrier: all ranks reach it and proceed
    kv.barrier()

    # 3. server-side optimizer: push grads from every worker; the
    #    stored weight steps by lr * sum(grads)
    from mxtpu import optimizer as opt
    kv2 = kv_mod.create("dist_sync")
    kv2.init(3, nd.ones((2,)))
    kv2.set_optimizer(opt.SGD(learning_rate=0.5))
    kv2.push(3, nd.ones((2,)))
    got = nd.zeros((2,))
    kv2.pull(3, out=got)
    np.testing.assert_allclose(got.asnumpy(),
                               (1.0 - 0.5 * n) * np.ones(2),
                               rtol=1e-6)
    kv2.barrier()

    with open(os.path.join(out_dir, f"ok.{rank}"), "w") as f:
        f.write(f"rank {rank}/{n} passed\n")


if __name__ == "__main__":
    main()
