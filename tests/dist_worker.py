"""Worker program for the local multi-process distributed test
(reference ``tests/nightly/dist_sync_kvstore.py``† run via
``tools/launch.py --launcher local``).  Each process = one simulated
host; asserts cross-process kvstore semantics and writes an OK file
the pytest parent checks."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    out_dir = sys.argv[1]
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))

    from mxtpu import kvstore as kv_mod
    from mxtpu import nd

    kv = kv_mod.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    assert n == int(os.environ["JAX_NUM_PROCESSES"]), (rank, n)

    # 1. push/pull reduces across processes: each worker pushes
    #    (rank+1) * ones → pulled value = sum_{r} (r+1)
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.ones((4,)) * (rank + 1))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    expect = sum(r + 1 for r in range(n))
    np.testing.assert_allclose(out.asnumpy(), expect * np.ones(4),
                               rtol=1e-6)

    # 2. barrier: all ranks reach it and proceed
    kv.barrier()

    # 3. server-side optimizer: push grads from every worker; the
    #    stored weight steps by lr * sum(grads)
    from mxtpu import optimizer as opt
    kv2 = kv_mod.create("dist_sync")
    kv2.init(3, nd.ones((2,)))
    kv2.set_optimizer(opt.SGD(learning_rate=0.5))
    kv2.push(3, nd.ones((2,)))
    got = nd.zeros((2,))
    kv2.pull(3, out=got)
    np.testing.assert_allclose(got.asnumpy(),
                               (1.0 - 0.5 * n) * np.ones(2),
                               rtol=1e-6)
    kv2.barrier()

    # 4. full SPMD training step over the GLOBAL mesh: batch sharded
    #    dp across process boundaries; XLA's gradient all-reduce rides
    #    the cross-process transport (gloo here, ICI/DCN on real
    #    slices).  Every rank must see the identical loss.
    from mxtpu import parallel
    from mxtpu.gluon import loss as gloss
    from mxtpu.models import mlp
    import mxtpu

    mxtpu.random.seed(0)
    net = mlp(classes=4, hidden=(16,))
    net.initialize(init="xavier")
    mesh = parallel.make_mesh({"dp": len(jax.devices())},
                              devices=jax.devices())
    step = parallel.build_train_step(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh)
    rng = np.random.RandomState(0)  # same data on every rank
    batch = 4 * len(jax.devices())  # divisible by the dp axis
    x = nd.array(rng.randn(batch, 6).astype(np.float32))
    y = nd.array(rng.randint(0, 4, (batch,)).astype(np.float32))
    losses = [float(step(x, y).asscalar()) for _ in range(5)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # loss agreement across ranks = the all-reduce really synchronized
    from jax.experimental import multihost_utils
    all_last = multihost_utils.process_allgather(
        jax.numpy.asarray(losses[-1]))
    assert np.allclose(np.asarray(all_last), losses[-1], rtol=1e-6), \
        all_last

    with open(os.path.join(out_dir, f"ok.{rank}"), "w") as f:
        f.write(f"rank {rank}/{n} passed; spmd losses {losses}\n")


if __name__ == "__main__":
    main()
