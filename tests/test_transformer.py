"""Transformer/BERT family + ring attention (north-star workloads 3/4;
sequence parallelism per SURVEY §2.4/§5.7)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxtpu import autograd, gluon, nd
from mxtpu.gluon import loss as gloss
from mxtpu.models.transformer import (BERTModel, MultiHeadAttention,
                                      TransformerEncoder, bert_base,
                                      transformer_encoder)


def test_multi_head_attention_shapes():
    attn = MultiHeadAttention(32, 4)
    attn.initialize(init="xavier")
    x = nd.array(np.random.randn(2, 10, 32).astype(np.float32))
    out = attn(x)
    assert out.shape == (2, 10, 32)


def test_mha_causal_masks_future():
    attn = MultiHeadAttention(16, 2, causal=True)
    attn.initialize(init="xavier")
    x = np.random.randn(1, 8, 16).astype(np.float32)
    out1 = attn(nd.array(x)).asnumpy()
    x2 = x.copy()
    x2[:, -1] += 10.0  # perturb the last position
    out2 = attn(nd.array(x2)).asnumpy()
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-5,
                               atol=1e-5)


def test_transformer_encoder_hybridize():
    enc = transformer_encoder(num_layers=2, units=32, hidden_size=64,
                              num_heads=4, dropout=0.0)
    enc.initialize(init="xavier")
    x = nd.array(np.random.randn(2, 12, 32).astype(np.float32))
    eager = enc(x).asnumpy()
    enc.hybridize()
    hybrid = enc(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-5)


def test_bert_trains():
    """Tiny BERT learns an identity-token MLM-style task."""
    V = 16
    net = BERTModel(vocab_size=V, units=32, hidden_size=64,
                    num_layers=2, num_heads=4, max_length=16,
                    dropout=0.0)
    net.initialize(init="xavier")
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    L = gloss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    losses = []
    for step in range(60):
        toks = rng.randint(0, V, (8, 12)).astype(np.float32)
        x = nd.array(toks)
        with autograd.record():
            out = net(x)
            l = L(out.reshape((-1, V)), x.reshape((-1,)))
        l.backward()
        tr.step(8)
        losses.append(float(l.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_bert_compiled_train_step_mesh():
    """BERT through the fused SPMD train step on the 8-device mesh
    (dp=4, mp=2) with bf16 compute — the north-star workload shape."""
    from mxtpu import parallel
    from mxtpu.parallel import P

    net = BERTModel(vocab_size=32, units=32, hidden_size=64,
                    num_layers=2, num_heads=4, max_length=16,
                    dropout=0.1)
    net.initialize(init="xavier")
    mesh = parallel.make_mesh({"dp": 4, "mp": 2})

    def spec_fn(p):
        if p.name.endswith("weight") and "dense" in p.name and \
                p.shape and len(p.shape) == 2 and p.shape[0] % 2 == 0:
            return P("mp", None)
        return P()

    step = parallel.build_train_step(
        net, lambda pred, y: gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, 32)), y.reshape((-1,))),
        "adam", {"learning_rate": 1e-3}, mesh=mesh,
        param_spec_fn=spec_fn, compute_dtype="bfloat16")
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 32, (8, 12)).astype(np.float32)
    x = nd.array(toks)
    losses = [float(step(x, x).asscalar()) for _ in range(10)]
    assert losses[-1] < losses[0], losses


# ----------------------------------------------------------------------
# ring attention (sequence parallelism)
# ----------------------------------------------------------------------

def test_ring_attention_parity():
    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.ring_attention import ring_attention
    from mxtpu.kernels.flash_attention import attention_reference
    mesh = make_mesh({"sp": 8})
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 64, 16
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.4
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.4
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    for causal in (False, True):
        got = np.asarray(ring_attention(q, k, v, mesh, causal=causal))
        ref = np.asarray(attention_reference(q, k, v, causal=causal))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5,
                                   err_msg=f"causal={causal}")


def test_ring_attention_grad():
    """Ring attention differentiates (training path) and matches the
    reference gradients."""
    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.ring_attention import ring_attention
    from mxtpu.kernels.flash_attention import attention_reference
    mesh = make_mesh({"sp": 4})
    rng = np.random.RandomState(1)
    B, H, T, D = 1, 2, 32, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.4
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.4
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    do = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    def f(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) * do)

    def f_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) * do)

    gp = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(gp, gr, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_ring_attention_jit_sharded():
    """Under jit with sharded inputs the ring executes across all 8
    devices (the long-context execution mode)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.ring_attention import ring_attention
    from mxtpu.kernels.flash_attention import attention_reference
    mesh = make_mesh({"sp": 8})
    rng = np.random.RandomState(2)
    B, H, T, D = 1, 2, 128, 16
    sh = NamedSharding(mesh, P(None, None, "sp", None))
    q = jax.device_put(
        jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.4, sh)
    k = jax.device_put(
        jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.4, sh)
    v = jax.device_put(
        jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)), sh)
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh,
                                                causal=True))
    out = fn(q, k, v)
    assert out.sharding.is_equivalent_to(sh, 4)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_mixed_precision_preserves_token_ids():
    """cast_batch=False: large token ids reach Embedding exactly
    (review regression — bf16 rounds ids > 256)."""
    from mxtpu import parallel
    from mxtpu.gluon import nn
    V = 4096
    net = nn.HybridSequential()
    net.add(nn.Embedding(V, 8), nn.Flatten(), nn.Dense(2))
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, lambda p, y: gloss.L2Loss()(p, y), "sgd",
        {"learning_rate": 0.0},  # lr 0: pure forward check
        compute_dtype="bfloat16", cast_batch=False)
    y = nd.array(np.zeros((1, 2), np.float32))
    # 4095 and 4094 both round to 4096 in bf16 — with cast_batch=False
    # they must fetch DIFFERENT embedding rows (different losses)
    l1 = float(step(nd.array(np.array([[4095, 1, 2, 3]], np.float32)),
                    y).asscalar())
    l2 = float(step(nd.array(np.array([[4094, 1, 2, 3]], np.float32)),
                    y).asscalar())
    assert abs(l1 - l2) > 1e-9, (l1, l2)


def test_remat_matches_no_remat():
    """set_remat: identical results, gradients intact (memory-only
    change)."""
    from mxtpu import parallel
    from mxtpu.models.transformer import BERTModel
    import mxtpu as mx

    def build(remat):
        mx.random.seed(11)
        np.random.seed(11)
        net = BERTModel(vocab_size=32, units=32, hidden_size=64,
                        num_layers=2, num_heads=4, max_length=16,
                        dropout=0.0, remat=remat)
        net.initialize(init="xavier")
        return net

    rng = np.random.RandomState(0)
    toks = rng.randint(0, 32, (4, 8)).astype(np.float32)

    losses = {}
    for remat in (False, True):
        net = build(remat)
        step = parallel.build_train_step(
            net, lambda p, y: gloss.SoftmaxCrossEntropyLoss()(
                p.reshape((-1, 32)), y.reshape((-1,))),
            "sgd", {"learning_rate": 0.1})
        x = nd.array(toks)
        losses[remat] = [float(step(x, x).asscalar()) for _ in range(4)]
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5,
                               atol=1e-6)


def test_remat_rejects_batchnorm_aux():
    """Blocks emitting BN aux updates inside a remat region fail
    loudly, not silently."""
    from mxtpu import parallel
    from mxtpu.gluon import nn
    import pytest as _pytest

    net = nn.HybridSequential()
    inner = nn.HybridSequential()
    inner.add(nn.Dense(4, flatten=False), nn.BatchNorm(axis=-1))
    inner.set_remat(True)
    net.add(inner)
    net.initialize(init="xavier")
    step = parallel.build_train_step(
        net, lambda p, y: gloss.L2Loss()(p, y), "sgd",
        {"learning_rate": 0.1})
    x = nd.array(np.random.randn(4, 3).astype(np.float32))
    y = nd.array(np.zeros((4, 4), np.float32))
    with _pytest.raises(Exception):
        step(x, y)


def test_remat_on_root_block():
    """set_remat on the net passed to build_train_step engages (review
    regression: used to be a silent no-op)."""
    from mxtpu import parallel
    from mxtpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize(init="xavier")
    net.set_remat(True)
    step = parallel.build_train_step(
        net, lambda p, y: gloss.L2Loss()(p, y), "sgd",
        {"learning_rate": 0.1})
    x = nd.array(np.random.randn(4, 3).astype(np.float32))
    y = nd.array(np.zeros((4, 2), np.float32))
    losses = [float(step(x, y).asscalar()) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_set_remat_invalidates_hybridize_cache():
    """Toggling remat after a hybridized call must not reuse the stale
    executable (review regression)."""
    from mxtpu.gluon import nn
    from mxtpu.gluon.block import HybridBlock
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize(init="xavier")
    net.hybridize()
    x = nd.array(np.random.randn(2, 3).astype(np.float32))
    out1 = net(x).asnumpy()
    n_entries = len(net._cached_entries)
    assert n_entries == 1
    net[0].set_remat(True)  # child toggle must invalidate parent cache
    out2 = net(x).asnumpy()
    assert len(net._cached_entries) == 2  # new generation, new entry
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


# ----------------------------------------------------------------------
# encoder-decoder TransformerModel (the translation config bench.py's
# transformer rows instantiate — previously zero direct coverage)
# ----------------------------------------------------------------------


def test_transformer_model_smoke_train():
    """Tiny encoder-decoder learns a copy task: loss drops and the
    decoder path (cross-attention + causal self-attention at a
    non-multiple-of-8 T) runs end to end."""
    from mxtpu.models.transformer import TransformerModel
    V = 16
    net = TransformerModel(vocab_size=V, units=32, hidden_size=64,
                           num_layers=2, num_heads=4, max_length=16,
                           dropout=0.0)
    net.initialize(init="xavier")
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    L = gloss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    losses = []
    for step in range(30):
        toks = rng.randint(0, V, (8, 12)).astype(np.float32)
        src, tgt = nd.array(toks), nd.array(toks)
        with autograd.record():
            out = net(src, tgt)
            l = L(out.reshape((-1, V)), tgt.reshape((-1,)))
        l.backward()
        tr.step(8)
        losses.append(float(l.mean().asnumpy()))
    # copy task reaches ~0.02x the initial loss by step 30; 0.5x leaves
    # a wide determinism margin while keeping the eager path cheap
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_transformer_big_config():
    """transformer_big pins the WMT big config (north-star workload 4):
    6+6 layers, 1024 units, 16 heads, 4096 FFN, shared embedding."""
    from mxtpu.models.transformer import (TransformerModel,
                                          transformer_big)
    net = transformer_big(vocab_size=512, max_length=32)
    assert isinstance(net, TransformerModel)
    assert len(net.encoder.layers._children) == 6
    assert len(net.decoder.layers._children) == 6
    enc0 = list(net.encoder.layers._children.values())[0]
    assert enc0.attn._heads == 16 and enc0.attn._units == 1024
    assert enc0.ffn.ffn1._units == 4096  # FFN up-projection width
    assert net.pos_embed.shape == (32, 1024)


@pytest.mark.slow
def test_transformer_big_smoke_forward():
    """transformer_big (full width, small vocab) runs a forward pass
    and produces finite logits of the right shape."""
    from mxtpu.models.transformer import transformer_big
    net = transformer_big(vocab_size=64, max_length=16)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 64, (2, 8)).astype(np.float32)
    out = net(nd.array(toks), nd.array(toks))
    assert out.shape == (2, 8, 64)
    assert np.isfinite(out.asnumpy()).all()
